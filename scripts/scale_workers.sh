#!/usr/bin/env bash
# Elastic scaling of local workers — analogue of the reference's
# scripts/scale_workers.sh, minus its biggest flaw: the reference KILLS AND
# RESTARTS the parameter server with a new TOTAL_WORKERS on every scale
# event (losing all in-memory parameters; reference
# scripts/scale_workers.sh:137-144).  Here the PS runs with --elastic and
# its barrier width follows the coordinator registry, so scaling is purely
# starting/stopping workers.
#
#   scale_workers.sh up N    start workers so that N are running
#   scale_workers.sh down N  stop workers so that N remain
#
# Env: COORDINATOR_ADDR, ITERATIONS, MODEL, BATCH, PID_DIR, LOG_DIR
set -euo pipefail
cd "$(dirname "$0")/.."
ACTION="${1:?usage: scale_workers.sh up|down N}"
TARGET="${2:?usage: scale_workers.sh up|down N}"
PID_DIR="${PID_DIR:-./run}"
LOG_DIR="${LOG_DIR:-.}"
COORDINATOR_ADDR="${COORDINATOR_ADDR:-127.0.0.1:50052}"
ITERATIONS="${ITERATIONS:-1000000}"
MODEL="${MODEL:-mnist_mlp}"
BATCH="${BATCH:-32}"
mkdir -p "$PID_DIR"

running_ids() {
  for f in "$PID_DIR"/worker_*.pid; do
    [ -e "$f" ] || continue
    pid=$(cat "$f")
    if kill -0 "$pid" 2>/dev/null; then
      basename "$f" | sed 's/worker_\([0-9]*\)\.pid/\1/'
    else
      rm -f "$f"
    fi
  done
}

CURRENT=($(running_ids))
COUNT=${#CURRENT[@]}
echo "currently running: $COUNT worker(s) [${CURRENT[*]:-}]"

case "$ACTION" in
  up)
    NEXT_ID=0
    for (( ; COUNT < TARGET; COUNT++ )); do
      while printf '%s\n' "${CURRENT[@]:-}" | grep -qx "$NEXT_ID"; do
        NEXT_ID=$((NEXT_ID + 1))
      done
      WORKER_ID="$NEXT_ID" ITERATIONS="$ITERATIONS" MODEL="$MODEL" \
        BATCH="$BATCH" COORDINATOR_ADDR="$COORDINATOR_ADDR" \
        PID_DIR="$PID_DIR" LOG_FILE="$LOG_DIR/worker_${NEXT_ID}.log" \
        bash scripts/start_worker.sh
      CURRENT+=("$NEXT_ID")
    done
    ;;
  down)
    # stop the highest-numbered workers first; the coordinator reaper evicts
    # them after the 30 s heartbeat timeout and the elastic barrier shrinks
    mapfile -t SORTED < <(printf '%s\n' "${CURRENT[@]}" | sort -n -r)
    for id in "${SORTED[@]}"; do
      [ "$COUNT" -le "$TARGET" ] && break
      pid=$(cat "$PID_DIR/worker_${id}.pid")
      echo "stopping worker $id (pid $pid)"
      kill "$pid" 2>/dev/null || true
      rm -f "$PID_DIR/worker_${id}.pid"
      COUNT=$((COUNT - 1))
    done
    ;;
  *)
    echo "unknown action $ACTION"; exit 1;;
esac
echo "now targeting $TARGET worker(s)"
