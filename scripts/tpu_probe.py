"""Single source of truth for the TPU health predicate.

bench.py's preflight and scripts/tpu_watchdog.sh / tpu_recovery.sh all run
this file in a subprocess (the wedged-tunnel failure mode is a hard HANG at
backend init, so the caller must wrap it in a timeout).  Exit 0 = a real
TPU-like device answered a tiny op; nonzero/hang = treat the device as down.

Keep the predicate here only — duplicating it risks bench.py and the
watchdog disagreeing about device health.
"""
import jax

d = jax.devices()[0]
assert (d.platform in ("tpu", "axon")
        or d.device_kind.upper().startswith("TPU")), d.platform
import jax.numpy as jnp

print(float(jnp.ones((8, 8)).sum()))
