#!/usr/bin/env bash
# Wait out a TPU-tunnel outage and bank the measurement sweep the moment
# the device returns.  The tunnel's failure mode is a hard multi-hour hang
# (jax.devices() never returns), so the loop is: cheap 60 s probe ->
# down? sleep and re-probe -> up? run scripts/tpu_recovery.sh (resumable;
# rc=2 means the tunnel died mid-sweep -> back to probing).
#
#   RESULTS=/tmp/tpu_recovery.jsonl LOG=... DEADLINE_S=36000 \
#     bash scripts/tpu_watchdog.sh
set -u
cd "$(dirname "$0")/.."

RESULTS="${RESULTS:-/tmp/tpu_recovery.jsonl}"
LOG="${LOG:-/tmp/tpu_recovery.log}"
PROBE_SPACING_S="${PROBE_SPACING_S:-240}"
DEADLINE_S="${DEADLINE_S:-36000}"
# Which resumable sweep to bank (same run/skip/abort contract).  The
# default is the full chain — it is the only entry point that runs the
# SWEEP_RETRY_DEFERRED pass, so tags deferred for repeated live-device
# failures get the leftover budget instead of ending the round banked as
# bench_error.  Point SWEEP at a single sweep script only for targeted
# captures.
SWEEP="${SWEEP:-scripts/tpu_recovery_chain.sh}"
START=$(date +%s)

# Shared predicate + wrapper (scripts/tpu_probe.sh) so watchdog, recovery,
# and bench.py cannot disagree about what a healthy device is.  PROBE_CMD
# is the same test seam scripts/tpu_sweep_lib.sh exposes
# (tests/test_tpu_sweep.py drives the full watchdog loop with it);
# EXPORTED so the child sweep inherits exactly this watchdog's resolved
# predicate — one health definition per watchdog<->recovery pair at
# runtime, whatever either file's fallback default says.
export PROBE_CMD="${PROBE_CMD:-bash scripts/tpu_probe.sh}"
probe() {
  $PROBE_CMD
}

while :; do
  now=$(date +%s)
  if [ $((now - START)) -ge "$DEADLINE_S" ]; then
    echo "watchdog: deadline reached ($DEADLINE_S s); giving up" | tee -a "$LOG"
    exit 1
  fi
  if probe; then
    echo "watchdog: TPU up ($(date -u +%H:%M:%S)); running sweep" | tee -a "$LOG"
    RESULTS="$RESULTS" LOG="$LOG" bash "$SWEEP"
    rc=$?
    if [ "$rc" -eq 0 ]; then
      echo "watchdog: sweep complete" | tee -a "$LOG"
      exit 0
    fi
    echo "watchdog: sweep aborted (rc=$rc); back to probing" | tee -a "$LOG"
  else
    echo "watchdog: TPU down ($(date -u +%H:%M:%S)); retry in ${PROBE_SPACING_S}s" >> "$LOG"
  fi
  sleep "$PROBE_SPACING_S"
done
