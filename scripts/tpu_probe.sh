#!/usr/bin/env bash
# Shared health-probe wrapper: single place for the timeout + env handling
# around scripts/tpu_probe.py (the predicate itself).  Exit 0 = device up.
# PROBE_TIMEOUT_S defaults to 90 s to match bench.py's
# PSDT_BENCH_PREFLIGHT_TIMEOUT default — the two must agree or the watchdog
# and bench.py can disagree about whether a slow-init tunnel is healthy.
timeout "${PROBE_TIMEOUT_S:-90}" env -u PSDT_PLATFORM \
  python "$(dirname "$0")/tpu_probe.py" >/dev/null 2>&1
