#!/usr/bin/env bash
# Local multi-process integration smoke test — the analogue of the
# reference's only test (scripts/test_local.sh): coordinator + PS
# (TOTAL_WORKERS=2) + 2 workers x N iterations, all on localhost, real gRPC.
# Unlike the reference (whose pass/fail was human log inspection), this
# script asserts worker exit codes and grep-checks the learning signal.
set -euo pipefail
cd "$(dirname "$0")/.."
# PSDT_PLATFORM pins the JAX backend in-process (reliable even where a
# sitecustomize PJRT plugin overrides the JAX_PLATFORMS env var).
export PSDT_PLATFORM="${PSDT_PLATFORM:-cpu}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export PYTHONUNBUFFERED=1

PORT_BASE="${PORT_BASE:-15050}"
PS_PORT=$((PORT_BASE + 1))
COORD_PORT=$((PORT_BASE + 2))
ITERATIONS="${ITERATIONS:-4}"
WORKDIR="$(mktemp -d)"
trap 'kill $(jobs -p) 2>/dev/null || true; rm -rf "$WORKDIR"' EXIT

echo "== starting parameter server (port $PS_PORT) =="
python -m parameter_server_distributed_tpu.cli.ps_main \
  "127.0.0.1:${PS_PORT}" 2 2 --lr=0.05 --ckpt-dir="$WORKDIR" \
  >"$WORKDIR/ps.log" 2>&1 &
PS_PID=$!

echo "== starting coordinator (port $COORD_PORT) =="
python -m parameter_server_distributed_tpu.cli.coordinator_main \
  "127.0.0.1:${COORD_PORT}" "127.0.0.1:${PS_PORT}" \
  >"$WORKDIR/coordinator.log" 2>&1 &
COORD_PID=$!

for i in $(seq 1 50); do
  grep -q "listening" "$WORKDIR/ps.log" 2>/dev/null && \
  grep -q "listening" "$WORKDIR/coordinator.log" 2>/dev/null && break
  sleep 0.2
done

echo "== starting 2 workers x ${ITERATIONS} iterations =="
python -m parameter_server_distributed_tpu.cli.worker_main \
  "127.0.0.1:${COORD_PORT}" 0 "$ITERATIONS" 127.0.0.1 15060 "" --batch=16 \
  >"$WORKDIR/worker_0.log" 2>&1 &
W0=$!
python -m parameter_server_distributed_tpu.cli.worker_main \
  "127.0.0.1:${COORD_PORT}" 1 "$ITERATIONS" 127.0.0.1 15061 "" --batch=16 \
  >"$WORKDIR/worker_1.log" 2>&1 &
W1=$!

FAIL=0
wait $W0 || { echo "worker 0 FAILED"; FAIL=1; }
wait $W1 || { echo "worker 1 FAILED"; FAIL=1; }

echo "== logs =="
for f in ps coordinator worker_0 worker_1; do
  echo "--- $f ---"; tail -5 "$WORKDIR/$f.log"
done

if [ "$FAIL" -ne 0 ]; then echo "SMOKE TEST FAILED"; exit 1; fi
N0=$(grep -c "completed iteration" "$WORKDIR/worker_0.log")
N1=$(grep -c "completed iteration" "$WORKDIR/worker_1.log")
if [ "$N0" -ne "$ITERATIONS" ] || [ "$N1" -ne "$ITERATIONS" ]; then
  echo "SMOKE TEST FAILED: expected $ITERATIONS iterations, got $N0/$N1"
  exit 1
fi
kill "$PS_PID" "$COORD_PID" 2>/dev/null || true
echo "SMOKE TEST PASSED (${ITERATIONS} iterations x 2 workers)"
