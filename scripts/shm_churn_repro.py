#!/usr/bin/env python
"""Flight-recorder-instrumented repro drive for the PR-7 shm flake.

The flake: under post-failover same-host churn (workers and the primary
PS being ``kill -9``-ed while the shm fused data plane is active), the
BACKUP PS rarely (~1/6 observed) died with SIGSEGV; ``PSDT_SHM=0`` was
stable on the same drive.  Suspected cause: a double segment reap — the
serve-thread exit reap racing the shutdown/negotiation-failure unlink,
the second unmap pulling the mapping out from under a native ring copy.

This script is the scripted kill-9 churn drive, with every process
running under ``PSDT_FLIGHT_DIR`` so a crash leaves decodable rings —
including the dead process's own.  It:

1. launches coordinator + primary PS (sync-replicating) + backup PS +
   2 workers as real processes with ``PSDT_SHM=1``;
2. churns: repeatedly ``kill -9``-s a worker mid-run and restarts it,
   and once mid-drive kills the PRIMARY so the backup is promoted and
   the churn continues against the promoted replica — the post-failover
   same-host pattern the flake needed;
3. watches the backup: if it dies, the flake reproduced — the script
   runs ``pst-trace`` over the flight directory and prints the decoded
   evidence (the dead backup's ring ends with the double ``shm.reap``
   and the open native copy; see docs/observability.md).

Usage:
    python scripts/shm_churn_repro.py [--rounds=N] [--dir=FLIGHT_DIR]
                                      [--no-shm]

Exit status: 0 = drive completed with the backup alive (post-fix
expectation; the ``shm.reap.dup`` events in the rings show the latch
absorbing the double-reap attempts), 3 = backup died (pre-fix flake
reproduced; evidence printed).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = "parameter_server_distributed_tpu"


def _spawn(args: list[str], env: dict, log_path: str) -> subprocess.Popen:
    log_fh = open(log_path, "ab")
    return subprocess.Popen([sys.executable, "-m", *args], env=env,
                            cwd=REPO, stdout=log_fh, stderr=log_fh)


def main(argv: list[str] | None = None) -> int:
    sys.path.insert(0, REPO)
    from parameter_server_distributed_tpu.config import parse_argv

    _, flags = parse_argv(sys.argv[1:] if argv is None else argv)
    rounds = int(flags.get("rounds", 6))
    flight_dir = flags.get("dir") or tempfile.mkdtemp(prefix="psdt-flight-")
    use_shm = "no-shm" not in flags

    base = 21300 + (os.getpid() % 500) * 10
    coord_addr = f"127.0.0.1:{base}"
    primary_addr = f"127.0.0.1:{base + 1}"
    backup_addr = f"127.0.0.1:{base + 2}"

    env = dict(os.environ)
    env.update({
        "PSDT_FLIGHT_DIR": flight_dir,
        "PSDT_SHM": "1" if use_shm else "0",
        "JAX_PLATFORMS": "cpu",
        "PSDT_PLATFORM": "cpu",
    })
    logs = os.path.join(flight_dir, "logs")
    os.makedirs(logs, exist_ok=True)
    print(f"churn drive: flight dir {flight_dir} (shm "
          f"{'on' if use_shm else 'off'}), {rounds} kill rounds")

    procs: dict[str, subprocess.Popen] = {}

    def start_worker(wid: int) -> None:
        procs[f"worker{wid}"] = _spawn(
            [f"{PKG}.cli.worker_main", coord_addr, str(wid), "500",
             "127.0.0.1", str(base + 5 + wid), "", "--model=mnist_mlp",
             "--batch=16"],
            env, os.path.join(logs, f"worker{wid}.log"))

    try:
        procs["backup"] = _spawn(
            [f"{PKG}.cli.ps_main", backup_addr, "2", "1000000",
             f"--ckpt-dir={os.path.join(flight_dir, 'ck-b')}"],
            env, os.path.join(logs, "backup.log"))
        procs["primary"] = _spawn(
            [f"{PKG}.cli.ps_main", primary_addr, "2", "1000000",
             f"--backup={backup_addr}", "--replication=sync",
             f"--ckpt-dir={os.path.join(flight_dir, 'ck-p')}"],
            env, os.path.join(logs, "primary.log"))
        procs["coordinator"] = _spawn(
            [f"{PKG}.cli.coordinator_main", coord_addr, primary_addr,
             f"--ps-backups={backup_addr}"],
            env, os.path.join(logs, "coordinator.log"))
        time.sleep(3.0)
        start_worker(0)
        start_worker(1)
        time.sleep(5.0)  # let fused+shm rounds establish

        killed_primary = False
        for r in range(rounds):
            victim = f"worker{r % 2}"
            proc = procs.get(victim)
            if proc is not None and proc.poll() is None:
                print(f"round {r}: kill -9 {victim}")
                proc.send_signal(signal.SIGKILL)
                proc.wait()
            time.sleep(1.0)
            start_worker(r % 2)
            if not killed_primary and r >= rounds // 2:
                # mid-drive failover: kill the primary; the workers
                # report it and the backup is promoted — churn continues
                # against the promoted replica (the flake's habitat)
                print(f"round {r}: kill -9 PRIMARY (forcing promotion)")
                procs["primary"].send_signal(signal.SIGKILL)
                procs["primary"].wait()
                killed_primary = True
            time.sleep(2.0)
            backup = procs["backup"]
            if backup.poll() is not None:
                rc = backup.returncode
                print(f"BACKUP DIED (rc={rc}, signal "
                      f"{-rc if rc and rc < 0 else 'n/a'}) — flake "
                      f"reproduced")
                status = 3
                break
        else:
            print("drive complete: backup alive across churn + failover")
            status = 0
    finally:
        # kill everything BEFORE decoding: the postmortem's liveness
        # probe would otherwise (correctly) list the survivors as
        # "still running" instead of closing out the drive's story
        for proc in procs.values():
            if proc.poll() is None:
                proc.send_signal(signal.SIGKILL)
        for proc in procs.values():
            try:
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        print(f"flight rings preserved under {flight_dir}")
    print("decoding flight evidence:")
    _postmortem(flight_dir)
    return status


def _postmortem(flight_dir: str) -> None:
    from parameter_server_distributed_tpu.cli.trace_main import main as trace
    trace([flight_dir])


if __name__ == "__main__":
    sys.exit(main())
