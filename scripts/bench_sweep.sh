#!/usr/bin/env bash
# LM benchmark sweep: attention kernel x remat x layer layout x context
# length, one bench.py run per config, serial (single chip).  Each run
# appends its JSON line to the results file with the config as a prefix
# key; stderr goes to the log.  Skips nothing on failure — a bench_error
# line records what failed.
#
#   RESULTS=/tmp/lm_sweep.jsonl LOG=/tmp/lm_sweep.log scripts/bench_sweep.sh
#
# Env passthrough: PSDT_BENCH_TPU_TIMEOUT (default 560 here: first
# compiles of the unrolled 24-layer flagship run ~2 min on the tunneled
# backend), PSDT_BENCH_STEPS.
set -u
cd "$(dirname "$0")/.."

RESULTS="${RESULTS:-/tmp/lm_sweep.jsonl}"
LOG="${LOG:-/tmp/lm_sweep.log}"
export PSDT_BENCH_MODEL="${PSDT_BENCH_MODEL:-lm_350m}"
export PSDT_BENCH_TPU_TIMEOUT="${PSDT_BENCH_TPU_TIMEOUT:-560}"
export PSDT_BENCH_TPU_ATTEMPTS=1
export PSDT_BENCH_CPU_TIMEOUT=1   # TPU sweep: a CPU fallback number is noise
# fail fast per run: one probe, no retry window (bench.py defaults to a
# 12.5-min spaced window meant for the single driver run, which would turn
# a dead-tunnel 7-config sweep into ~1.5 h of waiting)
export PSDT_BENCH_PREFLIGHT_RETRIES=1

run() {  # run <tag> [VAR=VALUE...]
  local tag="$1"; shift
  echo "=== $tag ($(date -u +%H:%M:%S)) ===" | tee -a "$LOG"
  local line
  line=$(env "$@" python bench.py 2>>"$LOG")
  [ -n "$line" ] || line='{"metric": "bench_error", "value": 0.0, "unit": "error", "vs_baseline": 0.0, "note": "bench.py emitted no output"}'
  echo "{\"config\": \"$tag\", \"result\": $line}" | tee -a "$RESULTS"
}

# seq 1024 (flagship default): layout/remat matrix on dense attention
run dense_remat_b32        PSDT_BENCH_BATCH=32
run dense_noremat_b32      PSDT_BENCH_BATCH=32 PSDT_BENCH_REMAT=0
run dense_scan_remat_b32   PSDT_BENCH_BATCH=32 PSDT_BENCH_SCAN=1
run dense_scan_noremat_b32 PSDT_BENCH_BATCH=32 PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT=0
# batch scaling at remat (compute-vs-HBM bound diagnosis) + the
# remat-credited hardware-utilization view of the same config
run dense_remat_b64        PSDT_BENCH_BATCH=64
run dense_remat_b32_credit PSDT_BENCH_BATCH=32 PSDT_BENCH_REMAT_CREDIT=1
# flash at seq 1024 (expected slower than dense here; recorded for the
# crossover curve)
run flash_remat_b32        PSDT_BENCH_BATCH=32 PSDT_BENCH_ATTENTION=flash
# long context: flash + remat is the memory-viable config; the crossover
# curve needs both kernels at 4096 and 8192
run flash_seq4096_b8       PSDT_BENCH_BATCH=8 PSDT_BENCH_SEQ=4096 PSDT_BENCH_ATTENTION=flash
run dense_seq4096_b8       PSDT_BENCH_BATCH=8 PSDT_BENCH_SEQ=4096
run flash_seq8192_b4       PSDT_BENCH_BATCH=4 PSDT_BENCH_SEQ=8192 PSDT_BENCH_ATTENTION=flash
run xlaflash_seq4096_b8    PSDT_BENCH_BATCH=8 PSDT_BENCH_SEQ=4096 PSDT_BENCH_ATTENTION=xla_flash
run xlaflash_seq8192_b4    PSDT_BENCH_BATCH=4 PSDT_BENCH_SEQ=8192 PSDT_BENCH_ATTENTION=xla_flash
run attn_ab_seq8192        PSDT_BENCH_MODE=attention PSDT_BENCH_SEQ=8192
run dense_seq8192_b4       PSDT_BENCH_BATCH=4 PSDT_BENCH_SEQ=8192
# GQA flagship (kv_heads=4): unexpanded-K/V flash fold vs dense at long
# context — the KV-cache/ICI-frugal long-context config
run gqa_flash_seq4096_b8   PSDT_BENCH_MODEL=lm_350m_gqa PSDT_BENCH_BATCH=8 PSDT_BENCH_SEQ=4096 PSDT_BENCH_ATTENTION=flash
run gqa_dense_seq4096_b8   PSDT_BENCH_MODEL=lm_350m_gqa PSDT_BENCH_BATCH=8 PSDT_BENCH_SEQ=4096
# speculative decode serving row: perfect-draft upper bound + realistic
run spec_perfect_draft     PSDT_BENCH_MODE=generate PSDT_BENCH_MODEL=small_lm PSDT_BENCH_DRAFT=self PSDT_BENCH_BATCH=8 PSDT_BENCH_STEPS=64
run spec_tiny_draft        PSDT_BENCH_MODE=generate PSDT_BENCH_MODEL=small_lm PSDT_BENCH_DRAFT=tiny_lm PSDT_BENCH_BATCH=8 PSDT_BENCH_STEPS=64

echo "sweep done -> $RESULTS" | tee -a "$LOG"
