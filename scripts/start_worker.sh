#!/usr/bin/env bash
# Env-var driven worker launcher (analogue of the reference's
# scripts/start_worker.sh; the reference also exports CUDA/NCCL
# LD_LIBRARY_PATH — not needed on TPU/JAX).
#   COORDINATOR_ADDR (default 127.0.0.1:50052)  WORKER_ID (default 0)
#   ITERATIONS (default 10)  WORKER_PORT (default 50060+WORKER_ID)
#   CHECKPOINT_PATH (optional restore-at-start)
#   MODEL (default mnist_mlp)  BATCH (default 32)  EXTRA_FLAGS
#   LOG_FILE (default ./worker_${WORKER_ID}.log)  PID_DIR (default ./run)
set -euo pipefail
COORDINATOR_ADDR="${COORDINATOR_ADDR:-127.0.0.1:50052}"
WORKER_ID="${WORKER_ID:-0}"
ITERATIONS="${ITERATIONS:-10}"
WORKER_PORT="${WORKER_PORT:-$((50060 + WORKER_ID))}"
CHECKPOINT_PATH="${CHECKPOINT_PATH:-}"
MODEL="${MODEL:-mnist_mlp}"
BATCH="${BATCH:-32}"
EXTRA_FLAGS="${EXTRA_FLAGS:-}"
LOG_FILE="${LOG_FILE:-./worker_${WORKER_ID}.log}"
PID_DIR="${PID_DIR:-./run}"
mkdir -p "$PID_DIR"
# shellcheck disable=SC2086
nohup python -m parameter_server_distributed_tpu.cli.worker_main \
  "${COORDINATOR_ADDR}" "${WORKER_ID}" "${ITERATIONS}" "127.0.0.1" \
  "${WORKER_PORT}" "${CHECKPOINT_PATH}" \
  --model="${MODEL}" --batch="${BATCH}" ${EXTRA_FLAGS} >"$LOG_FILE" 2>&1 &
echo $! > "${PID_DIR}/worker_${WORKER_ID}.pid"
echo "worker ${WORKER_ID} started (pid $(cat "${PID_DIR}/worker_${WORKER_ID}.pid"))"
