#!/usr/bin/env bash
# Follow-up TPU capture: the selective-remat (remat_policy=dots) MFU rows.
# dots saves the projection/MLP dot outputs and recomputes only the
# attention einsums in the backward pass — executed work drops from
# ~(8P+16A) to ~(6P+16A) per token (~0.78x), so the measured-MFU ceiling
# rises ~1.28x over full remat IF the saved f32 dot outputs fit HBM.
# Memory is the open question at batch 32 (~29 GB of saved dots vs 16 GB
# HBM on v5e), hence the batch ladder: an OOM fails fast at compile
# (~40 s) and the next batch down answers.
#
# Same resumable contract as scripts/tpu_recovery.sh: tags with a real
# TPU number are skipped on re-run, bench_error rows retried, tunnel-down
# signatures abort rc=2 for scripts/tpu_watchdog.sh to wait out.
set -u
cd "$(dirname "$0")/.."
. scripts/tpu_sweep_lib.sh

# hd128 first: full-remat already measured highest (38.7% vs 31.5% for
# head_dim 64), so hd128 x dots is the best shot at the >=45% target
run lm350_hd128_scan_dots_b32   PSDT_BENCH_MODEL=lm_350m_hd128 PSDT_BENCH_BATCH=32 PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT_POLICY=dots
run lm350_hd128_scan_dots_b16   PSDT_BENCH_MODEL=lm_350m_hd128 PSDT_BENCH_BATCH=16 PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT_POLICY=dots
run lm350_hd128_scan_dots_b8    PSDT_BENCH_MODEL=lm_350m_hd128 PSDT_BENCH_BATCH=8  PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT_POLICY=dots
# head_dim-64 flagship on the same ladder
run lm350_scan_dots_b32         PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=32 PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT_POLICY=dots
run lm350_scan_dots_b16         PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=16 PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT_POLICY=dots
# LLaMA-architecture sibling (SwiGLU/GQA): transfers to converted ckpts
run llama350_scan_dots_b32      PSDT_BENCH_MODEL=llama_350m PSDT_BENCH_BATCH=32 PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT_POLICY=dots
run llama350_scan_dots_b16      PSDT_BENCH_MODEL=llama_350m PSDT_BENCH_BATCH=16 PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT_POLICY=dots
# credited view of the winner shape, for the hardware-utilization column
run lm350_hd128_scan_dots_b32_credit PSDT_BENCH_MODEL=lm_350m_hd128 PSDT_BENCH_BATCH=32 PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT_POLICY=dots PSDT_BENCH_REMAT_CREDIT=1

echo "dots sweep done -> $RESULTS" | tee -a "$LOG"
