#!/usr/bin/env bash
# Follow-up TPU capture: the selective-remat (remat_policy=dots) MFU rows.
# dots saves the projection/MLP dot outputs and recomputes only the
# attention einsums in the backward pass — executed work drops from
# ~(8P+16A) to ~(6P+16A) per token (~0.78x), so the measured-MFU ceiling
# rises ~1.28x over full remat IF the saved f32 dot outputs fit HBM.
# Memory is the open question at batch 32 (~29 GB of saved dots vs 16 GB
# HBM on v5e), hence the batch ladder: an OOM fails fast at compile
# (~40 s) and the next batch down answers.
#
# Same resumable contract as scripts/tpu_recovery.sh: tags with a real
# TPU number are skipped on re-run, bench_error rows retried, tunnel-down
# signatures abort rc=2 for scripts/tpu_watchdog.sh to wait out.
set -u
cd "$(dirname "$0")/.."

RESULTS="${RESULTS:-/tmp/tpu_recovery.jsonl}"
LOG="${LOG:-/tmp/tpu_recovery.log}"
export PSDT_BENCH_TPU_ATTEMPTS=1
export PSDT_BENCH_CPU_TIMEOUT=1
export PSDT_BENCH_PREFLIGHT_RETRIES=1
export PSDT_BENCH_TPU_TIMEOUT="${PSDT_BENCH_TPU_TIMEOUT:-560}"

device_up() {
  bash scripts/tpu_probe.sh
}

run() {  # run <tag> [VAR=VALUE...]
  local tag="$1"; shift
  if grep -q "\"config\": \"$tag\"" "$RESULTS" 2>/dev/null \
     && ! grep "\"config\": \"$tag\"" "$RESULTS" \
          | grep -qE "bench_error|_cpu_fallback"; then
    echo "=== $tag: already captured, skipping ===" | tee -a "$LOG"
    return 0
  fi
  echo "=== $tag ($(date -u +%H:%M:%S)) ===" | tee -a "$LOG"
  local line
  line=$(env "$@" python bench.py 2>>"$LOG")
  [ -n "$line" ] || line='{"metric": "bench_error", "value": 0.0, "unit": "error", "vs_baseline": 0.0, "note": "bench.py emitted no output"}'
  if grep -q "\"config\": \"$tag\"" "$RESULTS" 2>/dev/null; then
    grep -v "\"config\": \"$tag\"" "$RESULTS" > "$RESULTS.tmp"
    mv "$RESULTS.tmp" "$RESULTS"
  fi
  echo "{\"config\": \"$tag\", \"result\": $line}" | tee -a "$RESULTS"
  case "$line" in
    *"preflight hung"*)
      echo "tunnel-down signature on $tag; aborting sweep (rc=2)" \
        | tee -a "$LOG"
      exit 2 ;;
    *"tpu attempt timed out"*)
      if device_up; then
        echo "$tag timed out on a live device (config too slow for its" \
             "budget); continuing" | tee -a "$LOG"
      else
        echo "tunnel died during $tag; aborting sweep (rc=2)" | tee -a "$LOG"
        exit 2
      fi ;;
  esac
}

# hd128 first: full-remat already measured highest (38.7% vs 31.5% for
# head_dim 64), so hd128 x dots is the best shot at the >=45% target
run lm350_hd128_scan_dots_b32   PSDT_BENCH_MODEL=lm_350m_hd128 PSDT_BENCH_BATCH=32 PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT_POLICY=dots
run lm350_hd128_scan_dots_b16   PSDT_BENCH_MODEL=lm_350m_hd128 PSDT_BENCH_BATCH=16 PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT_POLICY=dots
run lm350_hd128_scan_dots_b8    PSDT_BENCH_MODEL=lm_350m_hd128 PSDT_BENCH_BATCH=8  PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT_POLICY=dots
# head_dim-64 flagship on the same ladder
run lm350_scan_dots_b32         PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=32 PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT_POLICY=dots
run lm350_scan_dots_b16         PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=16 PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT_POLICY=dots
# LLaMA-architecture sibling (SwiGLU/GQA): transfers to converted ckpts
run llama350_scan_dots_b32      PSDT_BENCH_MODEL=llama_350m PSDT_BENCH_BATCH=32 PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT_POLICY=dots
run llama350_scan_dots_b16      PSDT_BENCH_MODEL=llama_350m PSDT_BENCH_BATCH=16 PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT_POLICY=dots
# credited view of the winner shape, for the hardware-utilization column
run lm350_hd128_scan_dots_b32_credit PSDT_BENCH_MODEL=lm_350m_hd128 PSDT_BENCH_BATCH=32 PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT_POLICY=dots PSDT_BENCH_REMAT_CREDIT=1

echo "dots sweep done -> $RESULTS" | tee -a "$LOG"
