#!/usr/bin/env bash
# Priority-ordered TPU capture: the remat_policy=dots ladder (the >=45%
# MFU chase) first, then the remaining main-sweep configs (long-context
# A/B, decode/serve, 1B/resnet rows), then a final SWEEP_RETRY_DEFERRED
# pass that gives configs deferred for repeated live-device failures the
# leftover window budget.  All passes are resumable and share the tag
# contract (scripts/tpu_sweep_lib.sh), so a tunnel death anywhere
# propagates rc=2 to scripts/tpu_watchdog.sh, which waits out the outage
# and re-invokes this chain — already-banked tags are skipped.
set -u
cd "$(dirname "$0")/.."
bash scripts/tpu_recovery_dots.sh || exit $?
bash scripts/tpu_recovery.sh || exit $?
SWEEP_RETRY_DEFERRED=1 bash scripts/tpu_recovery_dots.sh || exit $?
SWEEP_RETRY_DEFERRED=1 bash scripts/tpu_recovery.sh
