#!/usr/bin/env bash
# Static-analysis gate: run pst-analyze over the package and fail on any
# non-baselined violation.  Wire this next to the tier-1 test run in CI.
#
#   scripts/analyze.sh            # human-readable report
#   scripts/analyze.sh --json     # machine-readable (dashboards, CI annot.)
#
# Extra args pass straight through to pst-analyze (e.g. --no-wire,
# --baseline=..., --write-wire-manifest).  See docs/analysis.md.
set -euo pipefail

cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -m parameter_server_distributed_tpu.cli.analyze_main "$@"
