#!/usr/bin/env bash
# Env-var driven PS launcher (analogue of the reference's
# scripts/start_parameter_server.sh).
#   PS_PORT (default 50051)  TOTAL_WORKERS (default 2)
#   CHECKPOINT_INTERVAL (default 10)  CHECKPOINT_DIR (default .)
#   EXTRA_FLAGS (e.g. "--lr=0.1 --optimizer=adam --staleness=4 --elastic
#   --coordinator=127.0.0.1:50052")
#   LOG_FILE (default ./parameter_server.log)  PID_DIR (default ./run)
set -euo pipefail
PS_PORT="${PS_PORT:-50051}"
TOTAL_WORKERS="${TOTAL_WORKERS:-2}"
CHECKPOINT_INTERVAL="${CHECKPOINT_INTERVAL:-10}"
CHECKPOINT_DIR="${CHECKPOINT_DIR:-.}"
EXTRA_FLAGS="${EXTRA_FLAGS:-}"
LOG_FILE="${LOG_FILE:-./parameter_server.log}"
# default the PS to the host backend (control plane + host optimizers);
# override PSDT_PLATFORM when using a device-resident optimizer
# (--optimizer=device_*/pallas_* in EXTRA_FLAGS)
export PSDT_PLATFORM="${PSDT_PLATFORM:-cpu}"
PID_DIR="${PID_DIR:-./run}"
mkdir -p "$PID_DIR"
# shellcheck disable=SC2086
nohup python -m parameter_server_distributed_tpu.cli.ps_main \
  "0.0.0.0:${PS_PORT}" "${TOTAL_WORKERS}" "${CHECKPOINT_INTERVAL}" \
  --ckpt-dir="${CHECKPOINT_DIR}" ${EXTRA_FLAGS} >"$LOG_FILE" 2>&1 &
echo $! > "${PID_DIR}/parameter_server.pid"
echo "parameter server started (pid $(cat "${PID_DIR}/parameter_server.pid"), port ${PS_PORT}, workers ${TOTAL_WORKERS})"
