#!/usr/bin/env bash
# Env-var driven coordinator launcher (analogue of the reference's
# scripts/start_coordinator.sh: nohup daemonization + PID file).
#   COORDINATOR_PORT (default 50052)  PS_ADDR (default 127.0.0.1:50051)
#   LOG_FILE (default ./coordinator.log)  PID_DIR (default ./run)
set -euo pipefail
COORDINATOR_PORT="${COORDINATOR_PORT:-50052}"
PS_ADDR="${PS_ADDR:-127.0.0.1:50051}"
LOG_FILE="${LOG_FILE:-./coordinator.log}"
PID_DIR="${PID_DIR:-./run}"
# the control plane is device-free: pin to the host backend so a TPU
# plugin's JAX_PLATFORMS override can't make the coordinator grab a chip
export PSDT_PLATFORM="${PSDT_PLATFORM:-cpu}"
mkdir -p "$PID_DIR"
nohup python -m parameter_server_distributed_tpu.cli.coordinator_main \
  "0.0.0.0:${COORDINATOR_PORT}" "${PS_ADDR}" >"$LOG_FILE" 2>&1 &
echo $! > "${PID_DIR}/coordinator.pid"
echo "coordinator started (pid $(cat "${PID_DIR}/coordinator.pid"), port ${COORDINATOR_PORT})"
