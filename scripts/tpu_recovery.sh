#!/usr/bin/env bash
# One-shot TPU measurement capture, highest-value first — run the moment
# the tunneled TPU recovers (it has a history of multi-hour outages, so a
# short window must bank the most important numbers first):
#
#   1. headline MFU (the BASELINE north-star + driver default)
#   2. lm_350m flagship rows: dense/remat matrix, remat-credited view
#   3. long-context flash-vs-dense crossover incl. the GQA flagship
#   4. speculative-decode serving rows
#
# Each line appends to $RESULTS as it lands, so a mid-run outage keeps
# everything captured so far.  RESULTS=/tmp/tpu_recovery.jsonl LOG=...
set -u
cd "$(dirname "$0")/.."

RESULTS="${RESULTS:-/tmp/tpu_recovery.jsonl}"
LOG="${LOG:-/tmp/tpu_recovery.log}"
export PSDT_BENCH_TPU_ATTEMPTS=1
export PSDT_BENCH_CPU_TIMEOUT=1        # a CPU fallback number is noise here
export PSDT_BENCH_PREFLIGHT_RETRIES=1  # fail fast per config
export PSDT_BENCH_TPU_TIMEOUT="${PSDT_BENCH_TPU_TIMEOUT:-560}"

run() {  # run <tag> [VAR=VALUE...]
  local tag="$1"; shift
  echo "=== $tag ($(date -u +%H:%M:%S)) ===" | tee -a "$LOG"
  local line
  line=$(env "$@" python bench.py 2>>"$LOG")
  [ -n "$line" ] || line='{"metric": "bench_error", "value": 0.0, "unit": "error", "vs_baseline": 0.0, "note": "bench.py emitted no output"}'
  echo "{\"config\": \"$tag\", \"result\": $line}" | tee -a "$RESULTS"
}

# -- 1. headline (driver default config)
run headline_mlp_mfu
# -- 2. flagship LM rows
run lm350_dense_remat_b32        PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=32
run lm350_dense_remat_b32_credit PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=32 PSDT_BENCH_REMAT_CREDIT=1
run lm350_dense_noremat_b32      PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=32 PSDT_BENCH_REMAT=0
run lm350_dense_remat_b64        PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=64
run lm350_hd128_dense_b32        PSDT_BENCH_MODEL=lm_350m_hd128 PSDT_BENCH_BATCH=32
run lm350_xlaflash_b32           PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=32 PSDT_BENCH_ATTENTION=xla_flash
# -- 3. long-context crossover
run attn_ab_seq4096              PSDT_BENCH_MODE=attention PSDT_BENCH_SEQ=4096
run attn_ab_seq8192              PSDT_BENCH_MODE=attention PSDT_BENCH_SEQ=8192
run attn_ab_seq8192_hd128        PSDT_BENCH_MODE=attention PSDT_BENCH_SEQ=8192 PSDT_BENCH_HEADS=8 PSDT_BENCH_HEAD_DIM=128
run lm350_flash_seq4096_b8       PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=8 PSDT_BENCH_SEQ=4096 PSDT_BENCH_ATTENTION=flash
run lm350_dense_seq4096_b8       PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=8 PSDT_BENCH_SEQ=4096
run lm350_hd128_seq4096_b8       PSDT_BENCH_MODEL=lm_350m_hd128 PSDT_BENCH_BATCH=8 PSDT_BENCH_SEQ=4096 PSDT_BENCH_ATTENTION=flash
run gqa_flash_seq4096_b8         PSDT_BENCH_MODEL=lm_350m_gqa PSDT_BENCH_BATCH=8 PSDT_BENCH_SEQ=4096 PSDT_BENCH_ATTENTION=flash
run lm350_flash_seq8192_b4       PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=4 PSDT_BENCH_SEQ=8192 PSDT_BENCH_ATTENTION=flash
run lm350_dense_seq8192_b4       PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=4 PSDT_BENCH_SEQ=8192
# -- 4. decode/serving
run decode_small_lm              PSDT_BENCH_MODE=generate PSDT_BENCH_MODEL=small_lm PSDT_BENCH_BATCH=8 PSDT_BENCH_STEPS=64
run spec_perfect_draft           PSDT_BENCH_MODE=generate PSDT_BENCH_MODEL=small_lm PSDT_BENCH_DRAFT=self PSDT_BENCH_BATCH=8 PSDT_BENCH_STEPS=64
run spec_tiny_draft              PSDT_BENCH_MODE=generate PSDT_BENCH_MODEL=small_lm PSDT_BENCH_DRAFT=tiny_lm PSDT_BENCH_BATCH=8 PSDT_BENCH_STEPS=64
run spec_trained_draft_k2        PSDT_BENCH_MODE=generate PSDT_BENCH_MODEL=small_lm PSDT_BENCH_DRAFT=tiny_lm PSDT_BENCH_TRAIN_STEPS=200 PSDT_BENCH_DRAFT_LEN=2 PSDT_BENCH_BATCH=8 PSDT_BENCH_STEPS=64
# -- 5. remaining sweep matrix (scan layout variants)
run lm350_scan_remat_b32         PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=32 PSDT_BENCH_SCAN=1
run lm350_flash_remat_b32        PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=32 PSDT_BENCH_ATTENTION=flash

echo "recovery sweep done -> $RESULTS" | tee -a "$LOG"
