#!/usr/bin/env bash
# One-shot TPU measurement capture, highest-value first — run the moment
# the tunneled TPU recovers (it has a history of multi-hour outages, so a
# short window must bank the most important numbers first):
#
#   1. headline MFU (the BASELINE north-star + driver default)
#   2. lm_350m flagship rows, scan layout (compiles ~7x smaller HLO — a
#      short window banks flagship numbers before anything slow)
#   3. long-context flash-vs-dense crossover incl. the GQA flagship
#   4. speculative-decode / serving rows (cheap, decode-sized compiles)
#   5. model-family rows (MoE, ViT, 1B MLP, resnets)
#   6. LONG-BUDGET tail: unrolled-layout LM rows and xla-cost-analysis
#      rows (multi-minute compiles, 900 s budgets) — deliberately last so
#      they can never starve a short window (round-4 lost 8 configs to
#      exactly that)
#
# RESUMABLE: each line appends to $RESULTS as it lands, a tag that already
# has a non-error result is skipped on re-run, and a tunnel-down signature
# (preflight hang / attempt timeout) aborts with rc=2 so a caller
# (scripts/tpu_watchdog.sh) can wait for recovery and re-invoke — a mid-run
# outage keeps everything captured so far and loses nothing else.
# Live-device timeouts get one adaptive doubled-budget retry (warm compile
# cache), transport 5xxs one paused retry, and repeat offenders are
# deferred to the chain's SWEEP_RETRY_DEFERRED pass — scripts/tpu_sweep_lib.sh.
set -u
cd "$(dirname "$0")/.."
. scripts/tpu_sweep_lib.sh

# -- 1. headline (driver default config)
run headline_mlp_mfu
# -- 2. flagship LM rows, scan layout
run lm350_scan_remat_b32         PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=32 PSDT_BENCH_SCAN=1
run lm350_scan_noremat_b32       PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=32 PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT=0
run lm350_scan_remat_b64         PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=64 PSDT_BENCH_SCAN=1
run lm350_scan_remat_b32_credit  PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=32 PSDT_BENCH_SCAN=1 PSDT_BENCH_REMAT_CREDIT=1
run lm350_hd128_scan_b32         PSDT_BENCH_MODEL=lm_350m_hd128 PSDT_BENCH_BATCH=32 PSDT_BENCH_SCAN=1
run llama350_scan_b32            PSDT_BENCH_MODEL=llama_350m PSDT_BENCH_BATCH=32 PSDT_BENCH_SCAN=1
run lm350_xlaflash_scan_b32      PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=32 PSDT_BENCH_SCAN=1 PSDT_BENCH_ATTENTION=xla_flash
# -- 3. long-context crossover
run attn_ab_seq4096              PSDT_BENCH_MODE=attention PSDT_BENCH_SEQ=4096
run attn_ab_seq8192              PSDT_BENCH_MODE=attention PSDT_BENCH_SEQ=8192
run attn_ab_seq8192_hd128        PSDT_BENCH_MODE=attention PSDT_BENCH_SEQ=8192 PSDT_BENCH_HEADS=8 PSDT_BENCH_HEAD_DIM=128
run lm350_flash_seq4096_b8       PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=8 PSDT_BENCH_SEQ=4096 PSDT_BENCH_SCAN=1 PSDT_BENCH_ATTENTION=flash
run lm350_dense_seq4096_b8       PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=8 PSDT_BENCH_SEQ=4096 PSDT_BENCH_SCAN=1
run lm350_hd128_seq4096_b8       PSDT_BENCH_MODEL=lm_350m_hd128 PSDT_BENCH_BATCH=8 PSDT_BENCH_SEQ=4096 PSDT_BENCH_SCAN=1 PSDT_BENCH_ATTENTION=flash
run gqa_flash_seq4096_b8         PSDT_BENCH_MODEL=lm_350m_gqa PSDT_BENCH_BATCH=8 PSDT_BENCH_SEQ=4096 PSDT_BENCH_SCAN=1 PSDT_BENCH_ATTENTION=flash
run lm350_flash_seq8192_b4       PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=4 PSDT_BENCH_SEQ=8192 PSDT_BENCH_SCAN=1 PSDT_BENCH_ATTENTION=flash
run lm350_dense_seq8192_b4       PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=4 PSDT_BENCH_SEQ=8192 PSDT_BENCH_SCAN=1
# flash kernel tile tuning (PSDT_FLASH_BLOCK_Q/K): larger K blocks raise
# arithmetic intensity per HBM fetch at O(bq*bk) VMEM cost
run flash_seq4096_bk256          PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=8 PSDT_BENCH_SEQ=4096 PSDT_BENCH_SCAN=1 PSDT_BENCH_ATTENTION=flash PSDT_FLASH_BLOCK_K=256
run flash_seq4096_bq256_bk256    PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=8 PSDT_BENCH_SEQ=4096 PSDT_BENCH_SCAN=1 PSDT_BENCH_ATTENTION=flash PSDT_FLASH_BLOCK_Q=256 PSDT_FLASH_BLOCK_K=256
# -- 4. decode/serving
run decode_small_lm              PSDT_BENCH_MODE=generate PSDT_BENCH_MODEL=small_lm PSDT_BENCH_BATCH=8 PSDT_BENCH_STEPS=64
run decode_small_lm_int8         PSDT_BENCH_MODE=generate PSDT_BENCH_MODEL=small_lm PSDT_BENCH_BATCH=8 PSDT_BENCH_STEPS=64 PSDT_BENCH_QUANT=int8
run decode_small_lm_int8_full    PSDT_BENCH_MODE=generate PSDT_BENCH_MODEL=small_lm PSDT_BENCH_BATCH=8 PSDT_BENCH_STEPS=64 PSDT_BENCH_QUANT=int8 PSDT_BENCH_KV_CACHE=int8
run spec_perfect_draft           PSDT_BENCH_MODE=generate PSDT_BENCH_MODEL=small_lm PSDT_BENCH_DRAFT=self PSDT_BENCH_BATCH=8 PSDT_BENCH_STEPS=64
run spec_tiny_draft              PSDT_BENCH_MODE=generate PSDT_BENCH_MODEL=small_lm PSDT_BENCH_DRAFT=tiny_lm PSDT_BENCH_BATCH=8 PSDT_BENCH_STEPS=64
run spec_trained_draft_k2        PSDT_BENCH_MODE=generate PSDT_BENCH_MODEL=small_lm PSDT_BENCH_DRAFT=tiny_lm PSDT_BENCH_TRAIN_STEPS=200 PSDT_BENCH_DRAFT_LEN=2 PSDT_BENCH_BATCH=8 PSDT_BENCH_STEPS=64
# adaptive depth (cap 4): the config that LOST at fixed k=4 (0.76x, r04)
# must never lose now — the controller shortens k when accept is low
run spec_trained_draft_k4        PSDT_BENCH_MODE=generate PSDT_BENCH_MODEL=small_lm PSDT_BENCH_DRAFT=tiny_lm PSDT_BENCH_TRAIN_STEPS=200 PSDT_BENCH_DRAFT_LEN=4 PSDT_BENCH_BATCH=8 PSDT_BENCH_STEPS=64
run serve_small_lm               PSDT_BENCH_MODE=serve PSDT_BENCH_MODEL=small_lm PSDT_BENCH_BATCH=8 PSDT_BENCH_STEPS=64
# fused multi-round serving (step_many): amortizes the per-round
# host<->device dispatch — the tunneled-device regime's biggest lever
run serve_small_lm_fused8        PSDT_BENCH_MODE=serve PSDT_BENCH_MODEL=small_lm PSDT_BENCH_BATCH=8 PSDT_BENCH_STEPS=64 PSDT_BENCH_SERVE_FUSED=8
run serve_small_lm_int8_full     PSDT_BENCH_MODE=serve PSDT_BENCH_MODEL=small_lm PSDT_BENCH_BATCH=8 PSDT_BENCH_STEPS=64 PSDT_BENCH_QUANT=int8 PSDT_BENCH_KV_CACHE=int8
# trained tiny_lm draft (self-draft costs as much as the target and can
# only lose; a cheap trained draft is the regime speculation serves)
run serve_small_lm_spec          PSDT_BENCH_MODE=serve PSDT_BENCH_MODEL=small_lm PSDT_BENCH_BATCH=8 PSDT_BENCH_STEPS=64 PSDT_BENCH_DRAFT=tiny_lm PSDT_BENCH_TRAIN_STEPS=200 PSDT_BENCH_DRAFT_LEN=4
# -- 5. model-family rows (flagship-scale sparse MoE reports MFU with
#    ACTIVE-expert FLOPs — top_k of E experts per token, noted in the
#    metric; the xlaflops rows in section 6 are the hardware-executed
#    view; ViT gets its first perf row)
run moe350_b16                   PSDT_BENCH_MODEL=moe_350m PSDT_BENCH_BATCH=16
run vit_s16_b64                  PSDT_BENCH_MODEL=vit_s16_imagenet PSDT_BENCH_BATCH=64
run mlp1b_sgd_b1024              PSDT_BENCH_MODEL=mlp_1b PSDT_BENCH_BATCH=1024
run mnist_mlp_b256               PSDT_BENCH_MODEL=mnist_mlp PSDT_BENCH_BATCH=256
run resnet18_b256                PSDT_BENCH_MODEL=resnet18_cifar PSDT_BENCH_BATCH=256
# -- 6. LONG-BUDGET tail (multi-minute unrolled/conv compiles; 900 s
#    budgets; adaptive retry doubles to 1800 s on a live device)
run lm350_dense_remat_b32        PSDT_BENCH_TPU_TIMEOUT=900 PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=32
run lm350_dense_noremat_b32      PSDT_BENCH_TPU_TIMEOUT=900 PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=32 PSDT_BENCH_REMAT=0
run resnet50_b128                PSDT_BENCH_TPU_TIMEOUT=900 PSDT_BENCH_MODEL=resnet50_imagenet PSDT_BENCH_BATCH=128
# XLA cost-analysis MFU (hardware-executed FLOPs, any model): conv nets
# get their first MFU rows, and the LM row cross-checks the analytic
# remat-credited accounting against XLA's own count
run resnet50_b128_xlaflops       PSDT_BENCH_TPU_TIMEOUT=900 PSDT_BENCH_MODEL=resnet50_imagenet PSDT_BENCH_BATCH=128 PSDT_BENCH_FLOPS=xla
run vit_s16_b64_xlaflops         PSDT_BENCH_TPU_TIMEOUT=900 PSDT_BENCH_MODEL=vit_s16_imagenet PSDT_BENCH_BATCH=64 PSDT_BENCH_FLOPS=xla
run lm350_scan_b32_xlaflops      PSDT_BENCH_TPU_TIMEOUT=900 PSDT_BENCH_MODEL=lm_350m PSDT_BENCH_BATCH=32 PSDT_BENCH_SCAN=1 PSDT_BENCH_FLOPS=xla
# hardware-executed FLOPs for the sparse MoE flagship: cross-checks the
# analytic ACTIVE-expert MFU accounting against XLA's own count
run moe350_b16_xlaflops          PSDT_BENCH_TPU_TIMEOUT=900 PSDT_BENCH_MODEL=moe_350m PSDT_BENCH_BATCH=16 PSDT_BENCH_FLOPS=xla

echo "recovery sweep done -> $RESULTS" | tee -a "$LOG"
