# Shared scaffolding for the resumable TPU capture sweeps — sourced by
# scripts/tpu_recovery.sh and scripts/tpu_recovery_dots.sh so the
# run/skip/abort contract cannot diverge between them:
#   * a tag with a real TPU number in $RESULTS is skipped on re-run;
#     bench_error and *_cpu_fallback rows are retried
#   * a tunnel-down signature (preflight hang, or a timeout on a dead
#     device) aborts with rc=2 so scripts/tpu_watchdog.sh can wait out
#     the outage and re-invoke
#   * each banked line replaces any stale row for its tag
# Callers must set (or accept the defaults for) RESULTS and LOG, then
# call `run <tag> [VAR=VALUE...]` per config.

RESULTS="${RESULTS:-/tmp/tpu_recovery.jsonl}"
LOG="${LOG:-/tmp/tpu_recovery.log}"
export PSDT_BENCH_TPU_ATTEMPTS=1
export PSDT_BENCH_CPU_TIMEOUT=1        # a CPU fallback number is noise here
export PSDT_BENCH_PREFLIGHT_RETRIES=1  # fail fast per config
export PSDT_BENCH_TPU_TIMEOUT="${PSDT_BENCH_TPU_TIMEOUT:-560}"

device_up() {  # same predicate + timeout bench.py's preflight uses
  bash scripts/tpu_probe.sh
}

run() {  # run <tag> [VAR=VALUE...]
  local tag="$1"; shift
  # A tag counts as captured only with a real TPU number — bench_error and
  # *_cpu_fallback rows are both retried on resume.
  if grep -q "\"config\": \"$tag\"" "$RESULTS" 2>/dev/null \
     && ! grep "\"config\": \"$tag\"" "$RESULTS" \
          | grep -qE "bench_error|_cpu_fallback"; then
    echo "=== $tag: already captured, skipping ===" | tee -a "$LOG"
    return 0
  fi
  echo "=== $tag ($(date -u +%H:%M:%S)) ===" | tee -a "$LOG"
  local line
  line=$(env "$@" python bench.py 2>>"$LOG")
  [ -n "$line" ] || line='{"metric": "bench_error", "value": 0.0, "unit": "error", "vs_baseline": 0.0, "note": "bench.py emitted no output"}'
  # Drop a stale row for this tag before appending the retry (grep -v exits
  # 1 on empty output, so don't chain the mv on it).
  if grep -q "\"config\": \"$tag\"" "$RESULTS" 2>/dev/null; then
    grep -v "\"config\": \"$tag\"" "$RESULTS" > "$RESULTS.tmp"
    mv "$RESULTS.tmp" "$RESULTS"
  fi
  echo "{\"config\": \"$tag\", \"result\": $line}" | tee -a "$RESULTS"
  case "$line" in
    *"preflight hung"*)
      # The preflight is itself a probe — a hang means the tunnel is gone.
      echo "tunnel-down signature on $tag; aborting sweep (rc=2)" \
        | tee -a "$LOG"
      exit 2 ;;
    *"tpu attempt timed out"*)
      # Ambiguous: a mid-run tunnel death and a config that genuinely needs
      # more compile/run budget produce the same timeout.  Re-probe to
      # disambiguate, else a deterministically-slow config would livelock
      # the watchdog<->recovery pair and starve every config after it.
      if device_up; then
        echo "$tag timed out on a live device (config too slow for its" \
             "budget); continuing" | tee -a "$LOG"
      else
        echo "tunnel died during $tag; aborting sweep (rc=2)" | tee -a "$LOG"
        exit 2
      fi ;;
  esac
}
