# Shared scaffolding for the resumable TPU capture sweeps — sourced by
# scripts/tpu_recovery.sh and scripts/tpu_recovery_dots.sh so the
# run/skip/abort contract cannot diverge between them:
#   * a tag with a real TPU number in $RESULTS is skipped on re-run;
#     bench_error and *_cpu_fallback rows are retried
#   * a tunnel-down signature (preflight hang, or a timeout on a dead
#     device) aborts with rc=2 so scripts/tpu_watchdog.sh can wait out
#     the outage and re-invoke
#   * each banked line replaces any stale row for its tag
#   * a timeout on a LIVE device retries once with a doubled budget —
#     the persistent compile cache (PSDT_COMPILE_CACHE) makes the retry
#     resume from the already-compiled modules, so "compile + step didn't
#     fit one budget" no longer forfeits the config (round-4 lost
#     llama350_scan_b32 this way)
#   * a transport-layer 5xx from the tunnel's remote-compile helper
#     retries once after a short pause (round-4 lost
#     lm350_scan_noremat_b32 to a single unretried HTTP 500)
#   * a tag that keeps failing on a live device is DEFERRED after
#     MAX_TAG_FAILS failures so it cannot starve the configs behind it
#     during a short window; scripts/tpu_recovery_chain.sh re-runs with
#     SWEEP_RETRY_DEFERRED=1 at the end to give deferred tags the
#     leftover budget
# Callers must set (or accept the defaults for) RESULTS and LOG, then
# call `run <tag> [VAR=VALUE...]` per config.

RESULTS="${RESULTS:-/tmp/tpu_recovery.jsonl}"
LOG="${LOG:-/tmp/tpu_recovery.log}"
FAILS="${FAILS:-$RESULTS.fails}"          # "tag count" per line, last wins
MAX_TAG_FAILS="${MAX_TAG_FAILS:-2}"       # live-device failures before deferral
SWEEP_RETRY_DEFERRED="${SWEEP_RETRY_DEFERRED:-0}"
export PSDT_BENCH_TPU_ATTEMPTS=1
export PSDT_BENCH_CPU_TIMEOUT=1        # a CPU fallback number is noise here
export PSDT_BENCH_PREFLIGHT_RETRIES=1  # fail fast per config
export PSDT_BENCH_TPU_TIMEOUT="${PSDT_BENCH_TPU_TIMEOUT:-560}"
# Persistent XLA compile cache shared across configs, retries, and tunnel
# windows (bench.py wires it into jax_compilation_cache_dir).  Lives in
# the repo (gitignored), not /tmp, so it survives whatever cleans /tmp
# between rounds.
export PSDT_COMPILE_CACHE="${PSDT_COMPILE_CACHE:-$PWD/.jax_cache}"
# Overridable for the no-hardware kill-switch test
# (tests/test_tpu_sweep.py): BENCH simulates bench.py, PROBE_CMD the
# device-health predicate.
BENCH="${BENCH:-python bench.py}"
PROBE_CMD="${PROBE_CMD:-bash scripts/tpu_probe.sh}"

device_up() {  # same predicate + timeout bench.py's preflight uses
  $PROBE_CMD
}

_fails_of() {
  grep "^$1 " "$FAILS" 2>/dev/null | tail -1 | awk '{print $2}'
}

_set_fails() {  # _set_fails <tag> <count>
  echo "$1 $2" >> "$FAILS"
}

_bank() {  # _bank <tag> <json-line> — replace any stale row, append
  local tag="$1" line="$2"
  if grep -q "\"config\": \"$tag\"" "$RESULTS" 2>/dev/null; then
    grep -v "\"config\": \"$tag\"" "$RESULTS" > "$RESULTS.tmp"
    mv "$RESULTS.tmp" "$RESULTS"
  fi
  echo "{\"config\": \"$tag\", \"result\": $line}" | tee -a "$RESULTS"
}

_invoke() {  # _invoke [VAR=VALUE...] — one bench run, stdout = JSON line
  local line
  line=$(env "$@" $BENCH 2>>"$LOG")
  [ -n "$line" ] || line='{"metric": "bench_error", "value": 0.0, "unit": "error", "vs_baseline": 0.0, "note": "bench emitted no output"}'
  echo "$line"
}

run() {  # run <tag> [VAR=VALUE...]
  local tag="$1"; shift
  # A tag counts as captured only with a real TPU number — bench_error and
  # *_cpu_fallback rows are both retried on resume.
  if grep -q "\"config\": \"$tag\"" "$RESULTS" 2>/dev/null \
     && ! grep "\"config\": \"$tag\"" "$RESULTS" \
          | grep -qE "bench_error|_cpu_fallback"; then
    echo "=== $tag: already captured, skipping ===" | tee -a "$LOG"
    return 0
  fi
  local fails
  fails=$(_fails_of "$tag"); fails="${fails:-0}"
  if [ "$fails" -ge "$MAX_TAG_FAILS" ] \
     && [ "$SWEEP_RETRY_DEFERRED" != "1" ]; then
    echo "=== $tag: deferred ($fails live-device failures) — unbanked" \
         "configs go first; retried by the chain's deferred pass ===" \
      | tee -a "$LOG"
    return 0
  fi
  echo "=== $tag ($(date -u +%H:%M:%S)) ===" | tee -a "$LOG"
  # Each device_up probe blocks up to PROBE_TIMEOUT_S (90 s) on a hung
  # tunnel, so a gate probe that already said "down" is cached and the
  # disposition below aborts without re-probing; a gate probe that said
  # "up" and then ran a multi-minute retry is stale, so the disposition
  # probes fresh in that path.
  local line gate_said_down=0
  line=$(_invoke "$@")
  # -- transport-layer 5xx from the remote-compile helper: transient on a
  #    live device; one retry after a pause (r04: a single HTTP 500 cost
  #    lm350_scan_noremat_b32 its only window of the round).  The gate is
  #    the actual error signature — a bench_error row whose note carries
  #    an HTTP 5xx — NOT "remote_compile" anywhere in the output, which
  #    also matched SUCCESSFUL rows that merely mention remote compilation
  #    (e.g. a compile-cache note) and double-ran them.
  case "$line" in
    *'"metric": "bench_error"'*"HTTP 5"*)
      if device_up; then
        echo "$tag: transport 5xx on a live device; retrying once in" \
             "${RETRY_5XX_PAUSE_S:-20}s" | tee -a "$LOG"
        sleep "${RETRY_5XX_PAUSE_S:-20}"
        line=$(_invoke "$@")
      else
        gate_said_down=1
      fi ;;
  esac
  # -- timeout on a live device: the budget was compile-dominated; retry
  #    once with double the budget.  The persistent compile cache means
  #    the retry reuses every module the first attempt finished compiling,
  #    so the second attempt is mostly steady-state.
  case "$line" in
    *"tpu attempt timed out"*)
      if [ "$gate_said_down" = 0 ] && device_up; then
        local budget retry_budget
        budget=$PSDT_BENCH_TPU_TIMEOUT
        for kv in "$@"; do
          case "$kv" in PSDT_BENCH_TPU_TIMEOUT=*) budget="${kv#*=}" ;; esac
        done
        retry_budget=$((budget * 2))
        echo "$tag: timed out at ${budget}s on a live device; adaptive" \
             "retry with ${retry_budget}s (compile cache warm)" \
          | tee -a "$LOG"
        line=$(_invoke "$@" PSDT_BENCH_TPU_TIMEOUT="$retry_budget")
        gate_said_down=0  # probe verdict is now stale; re-probe below
      else
        gate_said_down=1
      fi ;;
  esac
  _bank "$tag" "$line"
  case "$line" in
    *"preflight hung"*)
      # The preflight is itself a probe — a hang means the tunnel is gone.
      echo "tunnel-down signature on $tag; aborting sweep (rc=2)" \
        | tee -a "$LOG"
      exit 2 ;;
    *"tpu attempt timed out"*)
      # Still timing out after the doubled budget.  Disambiguate a dead
      # tunnel from a genuinely-slow config, else a deterministically-slow
      # config would livelock the watchdog<->recovery pair and starve
      # every config after it.
      if [ "$gate_said_down" = 0 ] && device_up; then
        _set_fails "$tag" $((fails + 1))
        echo "$tag exceeded its doubled budget on a live device" \
             "(failure $((fails + 1))/$MAX_TAG_FAILS before deferral);" \
             "continuing" | tee -a "$LOG"
      else
        echo "tunnel died during $tag; aborting sweep (rc=2)" | tee -a "$LOG"
        exit 2
      fi ;;
    *bench_error*)
      if [ "$gate_said_down" = 0 ] && device_up; then
        _set_fails "$tag" $((fails + 1))
        echo "$tag errored on a live device" \
             "(failure $((fails + 1))/$MAX_TAG_FAILS before deferral)" \
          | tee -a "$LOG"
      else
        echo "tunnel died during $tag; aborting sweep (rc=2)" | tee -a "$LOG"
        exit 2
      fi ;;
    *)
      [ "$fails" -gt 0 ] && _set_fails "$tag" 0 ;;
  esac
  return 0
}
