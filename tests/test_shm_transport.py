"""Shared-memory same-host transport tests (rpc/shm_transport.py, ISSUE 6).

Covers the ring protocol itself (framing, wrap, oversized frames,
teardown), the negotiation/downgrade matrix (same host accept, host
mismatch, PSDT_SHM=0, /dev/shm unavailable, reference server
UNIMPLEMENTED, mid-flight failure), and the fused data plane riding the
rings end to end — byte-tracked against the TCP path and hammered under
PSDT_LOCK_CHECK=1.
"""

import socket
import threading
import time

import numpy as np
import pytest

from parameter_server_distributed_tpu.config import ParameterServerConfig
from parameter_server_distributed_tpu.obs import stats as obs_stats
from parameter_server_distributed_tpu.rpc import messages as m
from parameter_server_distributed_tpu.rpc import shm_transport as st
from parameter_server_distributed_tpu.rpc.data_plane import PSClient
from parameter_server_distributed_tpu.server.ps_service import ParameterServer


def _ring_pair(capacity=1 << 20, doorbell=True):
    seg = st._create_segment(f"psdt-test-{time.monotonic_ns()}",
                             64 + capacity)
    if doorbell:
        a, b = socket.socketpair(socket.AF_UNIX, socket.SOCK_STREAM)
        prod = st.ShmRing(seg, capacity, st._Doorbell(a))
        cons = st.ShmRing(seg, capacity, st._Doorbell(b))
    else:
        prod = st.ShmRing(seg, capacity)
        cons = st.ShmRing(seg, capacity)
    return seg, prod, cons


def _cleanup(seg):
    try:
        seg.close()
        seg.unlink()
    except (OSError, BufferError):
        pass


# ---------------------------------------------------------------- ring unit

@pytest.mark.parametrize("doorbell", [True, False],
                         ids=["doorbell", "polling"])
def test_ring_frame_roundtrip_and_wrap(doorbell):
    """Frames round-trip exactly, including across the wrap boundary
    (with and without the doorbell socket — the polling fallback must
    stay correct)."""
    seg, prod, cons = _ring_pair(capacity=8192, doorbell=doorbell)
    try:
        rng = np.random.default_rng(0)
        payloads = [rng.bytes(n) for n in (1, 100, 3000, 5000, 0, 7777)]
        got = []

        def consume():
            for _ in payloads:
                got.append(cons.read_frame(time.monotonic() + 20))

        th = threading.Thread(target=consume, daemon=True, name="t-cons")
        th.start()
        for p in payloads:
            prod.write_frame(p, time.monotonic() + 20)
        th.join(timeout=20)
        assert not th.is_alive()
        assert got == payloads
    finally:
        _cleanup(seg)


def test_ring_frame_larger_than_capacity_streams_through():
    """A frame bigger than the whole ring streams through in blocks —
    the oversized-chunk case (single tensor above the chunk budget)."""
    seg, prod, cons = _ring_pair(capacity=64 << 10)
    try:
        big = np.random.default_rng(1).bytes(1 << 20)
        out = []
        th = threading.Thread(
            target=lambda: out.append(cons.read_frame(
                time.monotonic() + 30)),
            daemon=True, name="t-cons")
        th.start()
        prod.write_frame(big, time.monotonic() + 30)
        th.join(timeout=30)
        assert out and out[0] == big
    finally:
        _cleanup(seg)


def test_ring_empty_data_frame_distinct_from_end_marker():
    """A zero-length DATA frame (a fully-default GradientUpdate encodes
    to b'' under proto3 elision) must round-trip as b'', distinct from
    the end-of-stream marker (None)."""
    seg, prod, cons = _ring_pair()
    try:
        got = []

        def consume():
            while True:
                frame = cons.read_frame(time.monotonic() + 10)
                got.append(frame)
                if frame is None:
                    return

        th = threading.Thread(target=consume, daemon=True, name="t-cons")
        th.start()
        prod.write_frame(b"", time.monotonic() + 10)
        prod.write_frame(b"x", time.monotonic() + 10)
        prod.write_end(time.monotonic() + 10)
        th.join(timeout=10)
        assert got == [b"", b"x", None]
    finally:
        _cleanup(seg)


def test_ring_close_unblocks_waiters_and_timeout_raises():
    seg, prod, cons = _ring_pair()
    try:
        with pytest.raises(st.ShmTransportError, match="timeout"):
            cons.read_frame(time.monotonic() + 0.2)
        errs = []

        def blocked_read():
            try:
                cons.read_frame(time.monotonic() + 30)
            except st.ShmTransportError as exc:
                errs.append(exc)

        th = threading.Thread(target=blocked_read, daemon=True,
                              name="t-cons")
        th.start()
        time.sleep(0.05)
        prod.close()
        th.join(timeout=5)
        assert not th.is_alive() and errs
    finally:
        _cleanup(seg)


# ------------------------------------------------------------- negotiation

@pytest.fixture
def ps(tmp_path):
    server = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=1,
        checkpoint_dir=str(tmp_path), learning_rate=0.5,
        autosave_period_s=3600.0))
    port = server.start()
    yield server, port
    server.stop()


def _seed(client, n=16):
    w0 = np.arange(n, dtype=np.float32)
    push = client.push_gradients(m.GradientUpdate(
        worker_id=0, iteration=0,
        gradients=[m.Tensor.from_array("w", w0)]))
    assert push.success, push.message
    return w0


def test_same_host_negotiation_and_fused_rounds(ps):
    """Acceptance: same-host fused rounds negotiate the rings, move the
    payload through shared memory (rpc.shm.bytes grows), and produce
    results identical to the TCP path."""
    _, port = ps
    before = obs_stats.REGISTRY.snapshot()["counters"].get(
        "rpc.shm.bytes", 0)
    with PSClient(f"127.0.0.1:{port}") as client:
        w0 = _seed(client)
        grads = [m.Tensor.from_array("w", np.full(16, 0.1, np.float32))]
        for it in (1, 2, 3):
            push, params = client.push_pull(0, it, grads)
            assert push.success and params is not None and params.ready
            np.testing.assert_allclose(
                params.parameters[0].to_array(), w0 - 0.05 * it,
                rtol=1e-6)
        assert client.shm_active
        assert client._fused_ok is True
    after = obs_stats.REGISTRY.snapshot()["counters"].get(
        "rpc.shm.bytes", 0)
    assert after > before


def test_shm_and_tcp_rounds_bit_identical(tmp_path):
    """The transport must be invisible: the same push sequence over shm
    and over TCP (PSDT_SHM=0) yields bit-identical served parameters."""
    import os

    results = {}
    for shm_on in (True, False):
        os.environ["PSDT_SHM"] = "1" if shm_on else "0"
        try:
            server = ParameterServer(ParameterServerConfig(
                bind_address="127.0.0.1", port=0, total_workers=1,
                checkpoint_dir=str(tmp_path / f"shm{shm_on}"),
                learning_rate=0.5, autosave_period_s=3600.0))
            port = server.start()
            try:
                with PSClient(f"127.0.0.1:{port}") as client:
                    _seed(client, 64)
                    grads = [m.Tensor.from_array(
                        "w", np.linspace(-1, 1, 64, dtype=np.float32))]
                    push, params = client.push_pull(0, 1, grads)
                    assert push.success and params is not None
                    assert client.shm_active is shm_on
                    results[shm_on] = params.parameters[0].to_array()
            finally:
                server.stop()
        finally:
            os.environ.pop("PSDT_SHM", None)
    assert results[True].tobytes() == results[False].tobytes()


def test_all_default_empty_push_round_over_shm(ps):
    """The sharded-topology empty barrier contribution at worker 0 /
    iteration 0 encodes to b'' — it must complete a fused round over the
    rings (the END sentinel is out-of-band), not hang or desync."""
    _, port = ps
    with PSClient(f"127.0.0.1:{port}") as client:
        w0 = _seed(client)
        # establish the shm connection with a normal round first
        push, params = client.push_pull(
            0, 1, [m.Tensor.from_array("w", np.full(16, 0.1, np.float32))])
        assert push.success and client.shm_active
        # all-default chunk: worker 0, iteration 0, no tensors -> b''
        push, params = client.push_pull(0, 0, [], timeout=20.0)
        assert push is not None  # stale rejection is fine; hanging is not
        assert client.shm_active  # connection survived the round
        # and the connection still serves normal rounds afterwards
        push, params = client.push_pull(
            0, 2, [m.Tensor.from_array("w", np.full(16, 0.1, np.float32))])
        assert push.success and params is not None
        np.testing.assert_allclose(params.parameters[0].to_array(),
                                   w0 - 0.10, rtol=1e-6)


def test_client_disconnect_reaps_server_segments(ps):
    """Closing the client frees the server-side segments promptly (no
    /dev/shm accretion under elastic worker churn)."""
    server, port = ps
    client = PSClient(f"127.0.0.1:{port}")
    _seed(client)
    push, _ = client.push_pull(
        0, 1, [m.Tensor.from_array("w", np.full(16, 0.1, np.float32))])
    assert push.success and client.shm_active
    assert len(server.service.shm_server._conns) == 1
    client.close()
    deadline = time.monotonic() + 10
    while (server.service.shm_server._conns
           and time.monotonic() < deadline):
        time.sleep(0.05)
    assert server.service.shm_server._conns == []


def test_host_mismatch_refused_and_downgrades(ps, monkeypatch):
    """A client reporting a different host/boot-id is refused; the fused
    round rides TCP and the downgrade is permanent (one fallback count,
    no re-negotiation)."""
    _, port = ps
    monkeypatch.setattr(st, "host_id", lambda: "elsewhere/deadbeef")
    with PSClient(f"127.0.0.1:{port}") as client:
        w0 = _seed(client)
        push, params = client.push_pull(
            0, 1, [m.Tensor.from_array("w", np.full(16, 0.1, np.float32))])
        assert push.success and params is not None
        assert not client.shm_active and client._shm_ok is False
        np.testing.assert_allclose(params.parameters[0].to_array(),
                                   w0 - 0.05, rtol=1e-6)


def test_psdt_shm_0_disables_both_ends(ps, monkeypatch):
    _, port = ps
    monkeypatch.setenv("PSDT_SHM", "0")
    with PSClient(f"127.0.0.1:{port}") as client:
        _seed(client)
        push, params = client.push_pull(
            0, 1, [m.Tensor.from_array("w", np.full(16, 0.1, np.float32))])
        assert push.success and params is not None
        # client-side gate: negotiation never even attempted
        assert client._shm_ok is None and not client.shm_active


def test_dev_shm_unavailable_refused_and_downgrades(ps, monkeypatch):
    """Segment creation failing server-side (no /dev/shm, exhausted)
    refuses the negotiation; the client downgrades permanently with zero
    failed steps."""
    _, port = ps

    def boom(name, size):
        raise OSError("No space left on device")

    monkeypatch.setattr(st, "_create_segment", boom)
    with PSClient(f"127.0.0.1:{port}") as client:
        w0 = _seed(client)
        push, params = client.push_pull(
            0, 1, [m.Tensor.from_array("w", np.full(16, 0.1, np.float32))])
        assert push.success and params is not None
        assert client._shm_ok is False
        np.testing.assert_allclose(params.parameters[0].to_array(),
                                   w0 - 0.05, rtol=1e-6)


def test_reference_server_unimplemented_downgrades(tmp_path):
    """A reference-shaped PS (5 unary RPCs, no NegotiateShm) answers
    UNIMPLEMENTED: permanent TCP downgrade, push still lands."""
    from parameter_server_distributed_tpu.checkpoint.manager import (
        CheckpointManager)
    from parameter_server_distributed_tpu.core.ps_core import (
        ParameterServerCore)
    from parameter_server_distributed_tpu.rpc.service import (bind_service,
                                                              make_server)
    from parameter_server_distributed_tpu.server.ps_service import (
        ParameterServerService)

    core = ParameterServerCore(total_workers=1)
    core.initialize_parameters({"w": np.ones(4, np.float32)})
    service = ParameterServerService(
        core, CheckpointManager(core, directory=str(tmp_path),
                                checkpoint_interval=100,
                                check_period_s=600.0))
    server = make_server()
    bind_service(server, m.PARAMETER_SERVER_SERVICE,
                 m.PARAMETER_SERVER_METHODS, service)  # unary only
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        with PSClient(f"127.0.0.1:{port}") as client:
            push, params = client.push_pull(
                0, 1, [m.Tensor.from_array("w", np.full(4, 0.5,
                                                        np.float32))])
            assert push.success
            assert params is None  # unary fallback: caller polls + pulls
            assert client._shm_ok is False
    finally:
        server.stop(0)
        service.shm_server.close()


def test_midflight_shm_failure_downgrades_and_replays(ps):
    """Killing the rings under a live connection: the NEXT fused round
    catches the transport error, downgrades permanently, counts a
    fallback, and replays over TCP — zero failed steps."""
    server, port = ps
    before = obs_stats.REGISTRY.snapshot()["counters"].get(
        "rpc.shm.fallback", 0)
    with PSClient(f"127.0.0.1:{port}") as client:
        w0 = _seed(client)
        grads = [m.Tensor.from_array("w", np.full(16, 0.1, np.float32))]
        push, params = client.push_pull(0, 1, grads)
        assert push.success and client.shm_active
        # sabotage: server tears down every shm connection
        server.service.shm_server.close()
        push, params = client.push_pull(0, 2, grads)
        assert push.success and params is not None
        assert client._shm_ok is False and not client.shm_active
        np.testing.assert_allclose(params.parameters[0].to_array(),
                                   w0 - 0.10, rtol=1e-6)
    after = obs_stats.REGISTRY.snapshot()["counters"].get(
        "rpc.shm.fallback", 0)
    assert after == before + 1


@pytest.mark.lockcheck
def test_concurrent_fused_rounds_over_shm_lockcheck(tmp_path):
    """Two same-host workers close a 2-wide barrier over their own shm
    connections while a third thread hammers unary pulls — under
    PSDT_LOCK_CHECK=1, so any lock-order violation in the new
    ring/registry locks raises instead of deadlocking."""
    server = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=2,
        checkpoint_dir=str(tmp_path), learning_rate=0.5,
        autosave_period_s=3600.0))
    port = server.start()
    try:
        server.core.initialize_parameters(
            {"w": np.zeros(1024, np.float32)})
        clients = [PSClient(f"127.0.0.1:{port}") for _ in range(2)]
        errors: list = []

        def run_worker(wid: int):
            try:
                grads = [m.Tensor.from_array(
                    "w", np.full(1024, float(wid + 1), np.float32))]
                for it in range(1, 6):
                    push, params = clients[wid].push_pull(wid, it, grads)
                    assert push.success, push.message
                    assert params is not None and params.ready
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                errors.append(exc)

        threads = [threading.Thread(target=run_worker, args=(wid,),
                                    daemon=True, name=f"t-worker-{wid}")
                   for wid in range(2)]
        for th in threads:
            th.start()
        with PSClient(f"127.0.0.1:{port}") as puller:
            for _ in range(10):
                puller.pull_parameters(m.PullRequest(worker_id=9))
        for th in threads:
            th.join(timeout=60)
            assert not th.is_alive()
        if errors:
            raise errors[0]
        assert all(c.shm_active for c in clients)
        # 5 barriers x mean(1, 2) * lr 0.5 applied from zeros
        np.testing.assert_allclose(
            server.core.get_parameters()["w"],
            np.full(1024, -0.75 * 5, np.float32), rtol=1e-5)
        for c in clients:
            c.close()
    finally:
        server.stop()
