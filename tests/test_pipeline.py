"""Pipeline parallelism: pipelined result == sequential stage application."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_distributed_tpu.config import MeshConfig
from parameter_server_distributed_tpu.parallel.mesh import build_mesh
from parameter_server_distributed_tpu.parallel.pipeline import (
    pipeline_apply, stack_stage_params)


def stage_fn(params, h):
    return jax.nn.tanh(h @ params["w"] + params["b"])


def make_stages(rng, n_stages, d):
    return [{"w": rng.standard_normal((d, d)).astype(np.float32) * 0.5,
             "b": rng.standard_normal(d).astype(np.float32) * 0.1}
            for _ in range(n_stages)]


def sequential(stages, x):
    h = x
    for p in stages:
        h = stage_fn(p, h)
    return h


@pytest.mark.parametrize("n_pipe,microbatches", [(2, 4), (4, 4), (4, 8)])
def test_pipeline_matches_sequential(rng, n_pipe, microbatches):
    mesh = build_mesh(MeshConfig(pipeline=n_pipe, data=8 // n_pipe))
    d = 16
    stages = make_stages(rng, n_pipe, d)
    x = rng.standard_normal((32, d)).astype(np.float32)
    expect = np.asarray(sequential(stages, jnp.asarray(x)))
    stacked = stack_stage_params([{k: jnp.asarray(v) for k, v in s.items()}
                                  for s in stages], mesh)
    got = np.asarray(pipeline_apply(stage_fn, stacked, x, mesh, microbatches))
    np.testing.assert_allclose(got, expect, rtol=2e-5, atol=2e-5)


def test_pipeline_gradients_match_sequential(rng):
    mesh = build_mesh(MeshConfig(pipeline=4, data=2))
    d = 8
    stages = make_stages(rng, 4, d)
    x = rng.standard_normal((16, d)).astype(np.float32)
    stacked = stack_stage_params([{k: jnp.asarray(v) for k, v in s.items()}
                                  for s in stages], mesh)

    def loss_pipe(params):
        return jnp.sum(pipeline_apply(stage_fn, params, x, mesh, 4) ** 2)

    def loss_seq(stage_list):
        return jnp.sum(sequential(stage_list, jnp.asarray(x)) ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stages)
    for i in range(4):
        np.testing.assert_allclose(np.asarray(g_pipe["w"][i]),
                                   np.asarray(g_seq[i]["w"]),
                                   rtol=5e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(g_pipe["b"][i]),
                                   np.asarray(g_seq[i]["b"]),
                                   rtol=5e-4, atol=1e-5)


def test_pipeline_single_stage_passthrough(rng):
    mesh = build_mesh(MeshConfig(data=8))
    d = 8
    stages = make_stages(rng, 1, d)
    x = rng.standard_normal((8, d)).astype(np.float32)
    stacked = stack_stage_params([{k: jnp.asarray(v) for k, v in stages[0].items()}],
                                 mesh)
    got = np.asarray(pipeline_apply(stage_fn, stacked, x, mesh, 4))
    expect = np.asarray(sequential(stages, jnp.asarray(x)))
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_pipeline_rejects_indivisible_microbatches(rng):
    mesh = build_mesh(MeshConfig(pipeline=2, data=4))
    stages = make_stages(rng, 2, 8)
    stacked = stack_stage_params([{k: jnp.asarray(v) for k, v in s.items()}
                                  for s in stages], mesh)
    x = np.zeros((8, 8), np.float32)
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_apply(stage_fn, stacked, x, mesh, 3)
