"""Pipeline parallelism: pipelined result == sequential stage application."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_distributed_tpu.config import MeshConfig
from parameter_server_distributed_tpu.parallel.mesh import build_mesh
from parameter_server_distributed_tpu.parallel.pipeline import (
    pipeline_apply, stack_stage_params)


def stage_fn(params, h):
    return jax.nn.tanh(h @ params["w"] + params["b"])


def make_stages(rng, n_stages, d):
    return [{"w": rng.standard_normal((d, d)).astype(np.float32) * 0.5,
             "b": rng.standard_normal(d).astype(np.float32) * 0.1}
            for _ in range(n_stages)]


def sequential(stages, x):
    h = x
    for p in stages:
        h = stage_fn(p, h)
    return h


@pytest.mark.parametrize("n_pipe,microbatches", [(2, 4), (4, 4), (4, 8)])
def test_pipeline_matches_sequential(rng, n_pipe, microbatches):
    mesh = build_mesh(MeshConfig(pipeline=n_pipe, data=8 // n_pipe))
    d = 16
    stages = make_stages(rng, n_pipe, d)
    x = rng.standard_normal((32, d)).astype(np.float32)
    expect = np.asarray(sequential(stages, jnp.asarray(x)))
    stacked = stack_stage_params([{k: jnp.asarray(v) for k, v in s.items()}
                                  for s in stages], mesh)
    got = np.asarray(pipeline_apply(stage_fn, stacked, x, mesh, microbatches))
    np.testing.assert_allclose(got, expect, rtol=2e-5, atol=2e-5)


def test_pipeline_gradients_match_sequential(rng):
    mesh = build_mesh(MeshConfig(pipeline=4, data=2))
    d = 8
    stages = make_stages(rng, 4, d)
    x = rng.standard_normal((16, d)).astype(np.float32)
    stacked = stack_stage_params([{k: jnp.asarray(v) for k, v in s.items()}
                                  for s in stages], mesh)

    def loss_pipe(params):
        return jnp.sum(pipeline_apply(stage_fn, params, x, mesh, 4) ** 2)

    def loss_seq(stage_list):
        return jnp.sum(sequential(stage_list, jnp.asarray(x)) ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stages)
    for i in range(4):
        np.testing.assert_allclose(np.asarray(g_pipe["w"][i]),
                                   np.asarray(g_seq[i]["w"]),
                                   rtol=5e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(g_pipe["b"][i]),
                                   np.asarray(g_seq[i]["b"]),
                                   rtol=5e-4, atol=1e-5)


def test_pipeline_single_stage_passthrough(rng):
    mesh = build_mesh(MeshConfig(data=8))
    d = 8
    stages = make_stages(rng, 1, d)
    x = rng.standard_normal((8, d)).astype(np.float32)
    stacked = stack_stage_params([{k: jnp.asarray(v) for k, v in stages[0].items()}],
                                 mesh)
    got = np.asarray(pipeline_apply(stage_fn, stacked, x, mesh, 4))
    expect = np.asarray(sequential(stages, jnp.asarray(x)))
    np.testing.assert_allclose(got, expect, rtol=1e-5)


def test_pipeline_rejects_indivisible_microbatches(rng):
    mesh = build_mesh(MeshConfig(pipeline=2, data=4))
    stages = make_stages(rng, 2, 8)
    stacked = stack_stage_params([{k: jnp.asarray(v) for k, v in s.items()}
                                  for s in stages], mesh)
    x = np.zeros((8, 8), np.float32)
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_apply(stage_fn, stacked, x, mesh, 3)


# ---------------------------------------------------------------------------
# PipelinedTransformerLM: the full-model training mode (embed -> pipelined
# blocks -> head), gradients exact vs the non-pipelined Transformer
# ---------------------------------------------------------------------------

def _lm_fixtures(rng, n_layers=4, pipe=2, seq=16, batch=8):
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    mesh = build_mesh(MeshConfig(pipeline=pipe, data=8 // pipe))
    config = TransformerConfig(vocab=64, d_model=32, n_heads=4,
                               n_layers=n_layers, d_ff=64, max_seq=seq,
                               dtype=jnp.float32)
    plain = Transformer(config)
    piped = PipelinedTransformerLM(plain, mesh, num_microbatches=2)
    tokens = rng.integers(0, 64, (batch, seq)).astype(np.int32)
    return plain, piped, mesh, tokens


def _restack_grads(piped, flat_grads):
    """Flat per-layer grads -> the pipelined blocks/ layout, for
    comparison — the model's own checkpoint-restack transform."""
    return {name: np.asarray(value) for name, value in
            piped.restack_params(flat_grads).items()}


def test_pipelined_lm_loss_matches_plain(rng):
    plain, piped, mesh, tokens = _lm_fixtures(rng)
    piped_params = piped.init_params(0)
    plain_params = plain.init_params(0)
    loss_plain = float(jax.jit(plain.loss)(plain_params, tokens))
    loss_piped = float(jax.jit(piped.loss)(piped_params, tokens))
    np.testing.assert_allclose(loss_piped, loss_plain, rtol=1e-5)


def test_pipelined_lm_gradients_match_plain(rng):
    """jax.grad through the GPipe schedule == grad of the sequential model,
    for every parameter (the VERDICT item 6 'verify gradients equal the
    non-pipelined run' contract)."""
    plain, piped, mesh, tokens = _lm_fixtures(rng)
    plain_params = plain.init_params(0)
    piped_params = piped.init_params(0)
    g_plain = jax.jit(jax.grad(plain.loss))(plain_params, tokens)
    g_piped = jax.jit(jax.grad(piped.loss))(piped_params, tokens)
    expected = _restack_grads(piped, {k: np.asarray(v)
                                      for k, v in g_plain.items()})
    assert set(expected) == set(g_piped)
    for name in sorted(expected):
        np.testing.assert_allclose(
            np.asarray(g_piped[name]), expected[name], rtol=2e-4, atol=1e-5,
            err_msg=f"gradient mismatch for {name}")


def test_pipelined_lm_trains_in_sharded_trainer(rng):
    """ShardedTrainer + pipeline_rule: one step updates the pipe-sharded
    state and matches the equivalent non-pipelined step."""
    from parameter_server_distributed_tpu.parallel.pipeline import (
        pipeline_rule)
    from parameter_server_distributed_tpu.parallel.train_step import (
        ShardedTrainer, make_optimizer)
    from parameter_server_distributed_tpu.models.transformer import (
        transformer_rule)

    plain, piped, mesh, tokens = _lm_fixtures(rng)
    trainer = ShardedTrainer(piped.loss, mesh, pipeline_rule(mesh),
                             make_optimizer("sgd", 0.1))
    state = trainer.init_state(piped.init_params(0))
    # block params actually live sharded over pipe
    spec = state.params["blocks/attn/wq"].sharding.spec
    assert spec[0] == "pipe"
    state, metrics = trainer.step(state, tokens)
    assert np.isfinite(float(metrics["loss"]))

    # reference: the plain model on a data-only mesh, same sgd step
    dmesh = build_mesh(MeshConfig(data=8))
    ref = ShardedTrainer(plain.loss, dmesh, transformer_rule(dmesh),
                         make_optimizer("sgd", 0.1))
    ref_state = ref.init_state(plain.init_params(0))
    ref_state, ref_metrics = ref.step(ref_state, tokens)
    np.testing.assert_allclose(float(metrics["loss"]),
                               float(ref_metrics["loss"]), rtol=1e-5)
    got = np.asarray(state.params["blocks/mlp/w1"])[0, 0]
    want = np.asarray(ref_state.params["layer0/mlp/w1"])
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-5)


def test_run_training_pipeline_mode(rng):
    """train_main --mesh=pipe:2,data:4 trains the LM end to end."""
    from parameter_server_distributed_tpu.parallel.train_loop import (
        TrainLoopConfig, run_training)

    config = TrainLoopConfig(
        model="small_lm", batch_size=8, steps=6, optimizer="sgd",
        learning_rate=0.5, mesh=MeshConfig(pipeline=2, data=4),
        microbatches=2, log_every=2)
    summary = run_training(config)
    assert summary["steps"] == 6
    assert np.isfinite(summary["final_loss"])


def test_pipeline_rejects_bad_configs(rng):
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    mesh = build_mesh(MeshConfig(pipeline=2, data=4))
    with pytest.raises(ValueError, match="divide"):
        PipelinedTransformerLM(
            Transformer(TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                          n_layers=3, d_ff=64,
                                          dtype=jnp.float32)), mesh)
    with pytest.raises(ValueError, match="Transformer"):
        PipelinedTransformerLM(object(), mesh)


def test_pipelined_lm_remat_gradients_match(rng):
    """config.remat flows into the pipeline stages (jax.checkpoint per
    block) without changing loss or gradients."""
    import dataclasses

    from parameter_server_distributed_tpu.models.transformer import (
        Transformer)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    plain, piped, mesh, tokens = _lm_fixtures(rng)
    remat_model = Transformer(dataclasses.replace(plain.config, remat=True))
    piped_remat = PipelinedTransformerLM(remat_model, mesh,
                                         num_microbatches=2)
    params = piped.init_params(0)
    g_a = jax.jit(jax.grad(piped.loss))(params, tokens)
    g_b = jax.jit(jax.grad(piped_remat.loss))(params, tokens)
    for name in g_a:
        np.testing.assert_allclose(np.asarray(g_b[name]),
                                   np.asarray(g_a[name]), rtol=1e-5,
                                   atol=1e-7, err_msg=name)


def test_pipelined_lm_chunked_loss_matches(rng):
    """config.loss_chunk flows through the pipelined loss: same loss and
    gradients as the unchunked pipelined run."""
    import dataclasses

    from parameter_server_distributed_tpu.models.transformer import (
        Transformer)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    plain, piped, mesh, tokens = _lm_fixtures(rng)
    chunked_model = Transformer(dataclasses.replace(plain.config,
                                                    loss_chunk=4))
    piped_chunked = PipelinedTransformerLM(chunked_model, mesh,
                                           num_microbatches=2)
    params = piped.init_params(0)
    la = float(jax.jit(piped.loss)(params, tokens))
    lb = float(jax.jit(piped_chunked.loss)(params, tokens))
    np.testing.assert_allclose(lb, la, rtol=1e-6)
    g_a = jax.jit(jax.grad(piped.loss))(params, tokens)
    g_b = jax.jit(jax.grad(piped_chunked.loss))(params, tokens)
    for name in g_a:
        np.testing.assert_allclose(np.asarray(g_b[name]),
                                   np.asarray(g_a[name]), rtol=2e-5,
                                   atol=1e-7, err_msg=name)


# ---------------------------------------------------------------------------
# 1F1B schedule: hand-written interleaved fwd/bwd must be grad-exact vs the
# non-pipelined model (same contract the GPipe tests prove for autodiff)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("pipe,microbatches", [(2, 4), (4, 4), (4, 8)])
def test_pipelined_lm_1f1b_matches_plain(rng, pipe, microbatches):
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    plain, _, mesh, tokens = _lm_fixtures(rng, pipe=pipe,
                                          batch=microbatches * (8 // pipe))
    piped = PipelinedTransformerLM(plain, mesh,
                                   num_microbatches=microbatches,
                                   schedule="1f1b")
    l_plain, g_plain = jax.jit(jax.value_and_grad(plain.loss))(
        plain.init_params(0), tokens)
    l_piped, g_piped = jax.jit(piped.value_and_grad)(piped.init_params(0),
                                                     tokens)
    np.testing.assert_allclose(float(l_piped), float(l_plain), rtol=1e-5)
    expected = _restack_grads(piped, {k: np.asarray(v)
                                      for k, v in g_plain.items()})
    assert set(expected) == set(g_piped)
    for name in sorted(expected):
        np.testing.assert_allclose(
            np.asarray(g_piped[name]), expected[name], rtol=2e-4, atol=1e-5,
            err_msg=f"1f1b gradient mismatch for {name}")


def test_pipelined_lm_1f1b_remat_and_chunked(rng):
    """config.remat (per-block checkpoint inside the stage vjp) and
    loss_chunk both compose with the 1F1B schedule unchanged."""
    import dataclasses

    from parameter_server_distributed_tpu.models.transformer import (
        Transformer)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    plain, _, mesh, tokens = _lm_fixtures(rng)
    base = PipelinedTransformerLM(plain, mesh, num_microbatches=2,
                                  schedule="1f1b")
    params = base.init_params(0)
    l_a, g_a = jax.jit(base.value_and_grad)(params, tokens)
    for override in (dict(remat=True), dict(loss_chunk=4)):
        variant_model = Transformer(dataclasses.replace(plain.config,
                                                        **override))
        variant = PipelinedTransformerLM(variant_model, mesh,
                                         num_microbatches=2,
                                         schedule="1f1b")
        l_b, g_b = jax.jit(variant.value_and_grad)(params, tokens)
        np.testing.assert_allclose(float(l_b), float(l_a), rtol=1e-5)
        for name in g_a:
            np.testing.assert_allclose(
                np.asarray(g_b[name]), np.asarray(g_a[name]), rtol=2e-5,
                atol=1e-6, err_msg=f"{override}: {name}")


def test_pipelined_lm_1f1b_trains_in_sharded_trainer(rng):
    """ShardedTrainer with the 1F1B grad_fn: one sgd step equals the
    GPipe-scheduled step (same grads -> same update)."""
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM, pipeline_rule)
    from parameter_server_distributed_tpu.parallel.train_step import (
        ShardedTrainer, make_optimizer)

    plain, piped_gpipe, mesh, tokens = _lm_fixtures(rng)
    piped_1f1b = PipelinedTransformerLM(plain, mesh, num_microbatches=2,
                                        schedule="1f1b")
    kw = dict(mesh=mesh, rule=pipeline_rule(mesh),
              optimizer=make_optimizer("sgd", 0.1))
    t_a = ShardedTrainer(piped_gpipe.loss, kw["mesh"], kw["rule"],
                         kw["optimizer"])
    t_b = ShardedTrainer(piped_1f1b.loss, kw["mesh"], kw["rule"],
                         kw["optimizer"], grad_fn=piped_1f1b.value_and_grad)
    s_a = t_a.init_state(piped_gpipe.init_params(0))
    s_b = t_b.init_state(piped_1f1b.init_params(0))
    s_a, m_a = t_a.step(s_a, tokens)
    s_b, m_b = t_b.step(s_b, tokens)
    np.testing.assert_allclose(float(m_b["loss"]), float(m_a["loss"]),
                               rtol=1e-5)
    np.testing.assert_allclose(float(m_b["grad_norm"]),
                               float(m_a["grad_norm"]), rtol=2e-4)
    for name in s_a.params:
        np.testing.assert_allclose(np.asarray(s_b.params[name]),
                                   np.asarray(s_a.params[name]), rtol=2e-4,
                                   atol=1e-6, err_msg=name)


def test_pipeline_flash_attention_stage(rng):
    """--attention=flash inside pipeline stages: the per-device pallas
    kernel (interpret mode on CPU) gives the same loss as dense stages
    when seq is block-divisible."""
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    mesh = build_mesh(MeshConfig(pipeline=2, data=4))
    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                               d_ff=64, max_seq=128, dtype=jnp.float32)
    tokens = rng.integers(0, 64, (8, 128)).astype(np.int32)
    dense = PipelinedTransformerLM(Transformer(config), mesh,
                                   num_microbatches=2, attention="dense")
    flash = PipelinedTransformerLM(Transformer(config), mesh,
                                   num_microbatches=2, attention="flash")
    params = dense.init_params(0)
    l_dense = float(jax.jit(dense.loss)(params, tokens))
    l_flash = float(jax.jit(flash.loss)(params, tokens))
    np.testing.assert_allclose(l_flash, l_dense, rtol=1e-4)


def test_pipeline_rejects_bad_schedule_and_attention(rng):
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    mesh = build_mesh(MeshConfig(pipeline=2, data=4))
    model = Transformer(TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                          n_layers=2, d_ff=64,
                                          dtype=jnp.float32))
    with pytest.raises(ValueError, match="schedule"):
        PipelinedTransformerLM(model, mesh, schedule="pipedream")
    with pytest.raises(ValueError, match="attention"):
        PipelinedTransformerLM(model, mesh, attention="ring")


def test_run_training_pipeline_1f1b_mode(rng):
    """train_main --mesh=pipe:2,data:4 --pipeline-schedule=1f1b trains."""
    from parameter_server_distributed_tpu.parallel.train_loop import (
        TrainLoopConfig, run_training)

    config = TrainLoopConfig(
        model="small_lm", batch_size=8, steps=4, optimizer="sgd",
        learning_rate=0.5, mesh=MeshConfig(pipeline=2, data=4),
        microbatches=2, pipeline_schedule="1f1b", log_every=2)
    summary = run_training(config)
    assert summary["steps"] == 4
    assert np.isfinite(summary["final_loss"])


# ---------------------------------------------------------------------------
# Interleaved 1F1B (virtual stages): Megatron round-robin chunk schedule
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("pipe,virtual,microbatches", [(2, 2, 4), (4, 2, 4),
                                                       (2, 4, 6), (4, 2, 6)])
def test_pipelined_lm_interleaved_matches_plain(rng, pipe, virtual,
                                                microbatches):
    """virtual_stages > 1: loss and every gradient equal the non-pipelined
    model — covers ragged microbatch groups (M % P != 0) too."""
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    mesh = build_mesh(MeshConfig(pipeline=pipe, data=8 // pipe))
    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=8,
                               d_ff=64, max_seq=16, dtype=jnp.float32)
    plain = Transformer(config)
    piped = PipelinedTransformerLM(plain, mesh,
                                   num_microbatches=microbatches,
                                   schedule="1f1b",
                                   virtual_stages=virtual)
    tokens = rng.integers(
        0, 64, (microbatches * (8 // pipe), 16)).astype(np.int32)
    l_plain, g_plain = jax.jit(jax.value_and_grad(plain.loss))(
        plain.init_params(0), tokens)
    params = piped.init_params(0)
    l_eval = float(jax.jit(piped.loss)(params, tokens))  # V-pass GPipe fwd
    np.testing.assert_allclose(l_eval, float(l_plain), rtol=1e-5)
    l_piped, g_piped = jax.jit(piped.value_and_grad)(params, tokens)
    np.testing.assert_allclose(float(l_piped), float(l_plain), rtol=1e-5)

    lc = piped.layers_per_stage
    for layer in range(config.n_layers):
        stage, j = divmod(layer, lc)
        c, r = divmod(stage, pipe)
        for suffix in ("mlp/w1", "attn/wq", "ln1/scale"):
            np.testing.assert_allclose(
                np.asarray(g_piped[f"blocks/{suffix}"])[r, c, j],
                np.asarray(g_plain[f"layer{layer}/{suffix}"]),
                rtol=2e-4, atol=1e-5,
                err_msg=f"layer {layer} (stage {stage} -> rank {r} "
                        f"chunk {c} slot {j}) {suffix}")
    for name in ("embed/tok", "lm_head/w", "final_ln/scale"):
        np.testing.assert_allclose(np.asarray(g_piped[name]),
                                   np.asarray(g_plain[name]), rtol=2e-4,
                                   atol=1e-5, err_msg=name)


def test_interleaved_rejects_bad_configs(rng):
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    mesh = build_mesh(MeshConfig(pipeline=2, data=4))
    model = Transformer(TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                          n_layers=8, d_ff=64,
                                          dtype=jnp.float32))
    with pytest.raises(ValueError, match="1f1b"):
        PipelinedTransformerLM(model, mesh, virtual_stages=2)  # gpipe
    with pytest.raises(ValueError, match="divide"):
        PipelinedTransformerLM(model, mesh, schedule="1f1b",
                               virtual_stages=3)  # 8 % (2*3) != 0


def test_run_training_interleaved_mode(rng):
    """--mesh=pipe:2,data:4 --pipeline-schedule=1f1b --virtual-stages=2."""
    import dataclasses

    from parameter_server_distributed_tpu.parallel.train_loop import (
        TrainLoopConfig, run_training)

    config = TrainLoopConfig(
        model="small_lm4", batch_size=8, steps=3, optimizer="sgd",
        learning_rate=0.5, mesh=MeshConfig(pipeline=2, data=4),
        microbatches=2, pipeline_schedule="1f1b", virtual_stages=2,
        log_every=2)
    summary = run_training(config)
    assert summary["steps"] == 3
    assert np.isfinite(summary["final_loss"])


@pytest.mark.parametrize("virtual", [1, 2])
def test_flat_params_roundtrip(rng, virtual):
    """flat_params inverts init_params' restack in both layouts, so a
    pipeline-trained checkpoint loads into the plain Transformer."""
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    mesh = build_mesh(MeshConfig(pipeline=2, data=4))
    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=4,
                               d_ff=64, max_seq=16, dtype=jnp.float32)
    plain = Transformer(config)
    piped = PipelinedTransformerLM(plain, mesh, num_microbatches=2,
                                   schedule="1f1b", virtual_stages=virtual)
    flat = plain.init_params(0)
    back = piped.flat_params(piped.init_params(0))
    assert set(back) == set(flat)
    for name in flat:
        np.testing.assert_array_equal(np.asarray(back[name]),
                                      np.asarray(flat[name]), err_msg=name)
    # and the plain model actually runs on the round-tripped store
    tokens = rng.integers(0, 64, (4, 16)).astype(np.int32)
    l_a = float(jax.jit(plain.loss)(flat, tokens))
    l_b = float(jax.jit(plain.loss)(back, tokens))
    np.testing.assert_allclose(l_b, l_a, rtol=1e-6)


def test_pipeline_trained_checkpoint_serves_plain_generation(rng, tmp_path,
                                                             capsys):
    """End to end: train under the interleaved-1F1B pipeline, flatten the
    store with flat_params, write the reference-format host checkpoint,
    and decode from it with the plain pst-generate CLI — the
    train-pipelined / serve-unwrapped round trip."""
    from parameter_server_distributed_tpu.checkpoint import codec
    from parameter_server_distributed_tpu.cli import generate_main
    from parameter_server_distributed_tpu.models.registry import (
        get_model_and_batches)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM, pipeline_rule)
    from parameter_server_distributed_tpu.parallel.train_step import (
        ShardedTrainer, make_optimizer)

    mesh = build_mesh(MeshConfig(pipeline=2, data=4))
    model, batches = get_model_and_batches("small_lm4", 8, seed=0)
    piped = PipelinedTransformerLM(model, mesh, num_microbatches=2,
                                   schedule="1f1b", virtual_stages=2)
    trainer = ShardedTrainer(piped.loss, mesh, pipeline_rule(mesh),
                             make_optimizer("sgd", 0.1),
                             grad_fn=piped.value_and_grad)
    state = trainer.init_state(piped.init_params(0))
    for _ in range(2):
        state, metrics = trainer.step(state, next(batches))
    assert np.isfinite(float(metrics["loss"]))

    flat = piped.flat_params({k: np.asarray(v)
                              for k, v in state.params.items()})
    path = str(tmp_path / "piped.ckpt")
    codec.save(path, epoch=1, iteration=2, params=flat)

    rc = generate_main.main([
        "--model=small_lm4", f"--ckpt={path}", "--tokens=1,2,3",
        "--max-new=4"])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.strip()  # decoded token ids printed


# ----------------------------------------------------------- pipeline x MoE

def test_pipelined_moe_matches_per_microbatch_reference(rng):
    """pipe x MoE (moe_every=1, gpipe): the pipelined loss must equal the
    mean over microbatches of the plain MoE model's loss on each
    microbatch — expert capacity (and therefore token dropping) is a
    per-microbatch statistic under pipelining, exactly as it is under any
    microbatched MoE schedule."""
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    mesh = build_mesh(MeshConfig(pipeline=2, data=4))
    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=4,
                               d_ff=64, max_seq=16, dtype=jnp.float32,
                               moe_every=1, moe_experts=4)
    plain = Transformer(config)
    piped = PipelinedTransformerLM(plain, mesh, num_microbatches=2,
                                   schedule="gpipe")
    tokens = rng.integers(0, 64, (8, 16)).astype(np.int32)
    piped_params = piped.init_params(0)
    plain_params = plain.init_params(0)

    loss_piped = float(jax.jit(piped.loss)(piped_params, tokens))
    # reference: the plain model on each (data shard, microbatch) piece —
    # data rank d holds rows [2d, 2d+2), microbatch m is its m-th row
    pieces = [tokens[row:row + 1] for row in range(tokens.shape[0])]
    loss_ref = float(np.mean([jax.jit(plain.loss)(plain_params, piece)
                              for piece in pieces]))
    np.testing.assert_allclose(loss_piped, loss_ref, rtol=1e-5)


def test_pipelined_moe_gradients_flow_to_experts(rng):
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    mesh = build_mesh(MeshConfig(pipeline=2, expert=4))
    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                               d_ff=64, max_seq=16, dtype=jnp.float32,
                               moe_every=1, moe_experts=4)
    piped = PipelinedTransformerLM(Transformer(config), mesh,
                                   num_microbatches=2, schedule="gpipe")
    tokens = rng.integers(0, 64, (8, 16)).astype(np.int32)
    params = piped.init_params(0)
    grads = jax.grad(piped.loss)(params, tokens)
    assert "blocks/moe/w1" in grads
    for name in ("blocks/moe/w1", "blocks/moe/w2", "blocks/moe/router/w"):
        assert float(np.abs(np.asarray(grads[name])).max()) > 0, name


def test_pipeline_moe_rejections(rng):
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    mesh = build_mesh(MeshConfig(pipeline=2, data=4))
    interleaved = Transformer(TransformerConfig(
        vocab=64, d_model=32, n_heads=4, n_layers=4, d_ff=64, max_seq=16,
        moe_every=2, moe_experts=4))
    with pytest.raises(ValueError, match="homogeneous"):
        PipelinedTransformerLM(interleaved, mesh)
    # 1F1B x MoE composes since round 5 (aux threads through the
    # backward wave) — construction must NOT raise
    all_moe = Transformer(TransformerConfig(
        vocab=64, d_model=32, n_heads=4, n_layers=4, d_ff=64, max_seq=16,
        moe_every=1, moe_experts=4))
    piped = PipelinedTransformerLM(all_moe, mesh, schedule="1f1b")
    assert piped.schedule == "1f1b"


def test_pipelined_moe_expert_sharded_matches_replicated(rng):
    """pipe x EXPERT 2-D sharding: every block's expert weights split over
    the mesh's expert axis (each rank computes its local experts' partial
    output, psum over 'expert' combines).  A pure factorization — must be
    numerically identical to the expert-replicated pipeline and therefore
    to the per-microbatch plain reference."""
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=4,
                               d_ff=64, max_seq=16, dtype=jnp.float32,
                               moe_every=1, moe_experts=4)
    plain = Transformer(config)
    tokens = rng.integers(0, 64, (8, 16)).astype(np.int32)
    plain_params = plain.init_params(0)

    mesh_ep = build_mesh(MeshConfig(pipeline=2, expert=2, data=2))
    piped_ep = PipelinedTransformerLM(plain, mesh_ep, num_microbatches=2,
                                      schedule="gpipe")
    loss_ep = float(jax.jit(piped_ep.loss)(piped_ep.init_params(0), tokens))

    # comparison mesh replaces 'expert' with the (pipeline-unused)
    # 'tensor' axis so the data split — and therefore the per-microbatch
    # expert capacity — is IDENTICAL; only the expert factorization differs
    mesh_rep = build_mesh(MeshConfig(pipeline=2, tensor=2, data=2))
    piped_rep = PipelinedTransformerLM(plain, mesh_rep, num_microbatches=2,
                                       schedule="gpipe")
    loss_rep = float(jax.jit(piped_rep.loss)(piped_rep.init_params(0),
                                             tokens))
    np.testing.assert_allclose(loss_ep, loss_rep, rtol=1e-5)

    # gradients flow to the sharded expert weights
    grads = jax.grad(piped_ep.loss)(piped_ep.init_params(0), tokens)
    for name in ("blocks/moe/w1", "blocks/moe/w2", "blocks/moe/router/w"):
        assert float(np.abs(np.asarray(grads[name])).max()) > 0, name


def test_pipelined_moe_1f1b_matches_gpipe(rng):
    """1F1B x MoE: the hand-written schedule threads the aux-loss
    accumulator (each valid unit's aux read off the backward vjp's primal,
    cotangent seeded with moe_aux_coef), so loss AND gradients must match
    GPipe-by-autodiff on the same microbatch split — the two schedules
    are different orderings of identical math."""
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    mesh = build_mesh(MeshConfig(pipeline=2, data=4))
    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=4,
                               d_ff=64, max_seq=16, dtype=jnp.float32,
                               moe_every=1, moe_experts=4)
    plain = Transformer(config)
    gp = PipelinedTransformerLM(plain, mesh, num_microbatches=2,
                                schedule="gpipe")
    fb = PipelinedTransformerLM(plain, mesh, num_microbatches=2,
                                schedule="1f1b")
    tokens = rng.integers(0, 64, (8, 16)).astype(np.int32)
    params = gp.init_params(0)
    loss_g, grads_g = jax.jit(gp.value_and_grad)(params, tokens)
    loss_f, grads_f = jax.jit(fb.value_and_grad)(params, tokens)
    np.testing.assert_allclose(float(loss_f), float(loss_g), rtol=1e-5)
    assert set(grads_f) == set(grads_g)
    for name in grads_g:
        np.testing.assert_allclose(np.asarray(grads_f[name]),
                                   np.asarray(grads_g[name]),
                                   rtol=5e-4, atol=1e-6, err_msg=name)
    # router/expert gradients actually flow under 1F1B
    for name in ("blocks/moe/w1", "blocks/moe/w2", "blocks/moe/router/w"):
        assert float(np.abs(np.asarray(grads_f[name])).max()) > 0, name


def test_pipelined_moe_1f1b_expert_axis_rejected(rng):
    """1F1B x MoE x expert sharding is explicitly out of scope: the manual
    schedule seeds jax.vjp cotangents mid-shard_map, which breaks the
    unreduced-cotangent convention the expert psum transpose relies on
    (measured: expert grads come out exactly ep x too large).  GPipe owns
    expert parallelism — and its grads are verified correct below."""
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=4,
                               d_ff=64, max_seq=16, dtype=jnp.float32,
                               moe_every=1, moe_experts=4)
    plain = Transformer(config)
    tokens = rng.integers(0, 64, (8, 16)).astype(np.int32)
    mesh_ep = build_mesh(MeshConfig(pipeline=2, expert=2, data=2))
    fb_ep = PipelinedTransformerLM(plain, mesh_ep, num_microbatches=2,
                                   schedule="1f1b")
    with pytest.raises(ValueError, match="gpipe"):
        fb_ep.value_and_grad(fb_ep.init_params(0), tokens)


def test_pipelined_moe_expert_sharded_grads_match_replicated(rng):
    """GPipe x MoE x expert sharding, GRADIENT equality (the existing
    sharded-vs-replicated test checks the loss and grad flow only):
    differentiating the whole shard_map pairs the expert-psum transposes
    correctly, so every gradient must match the expert-replicated run."""
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=4,
                               d_ff=64, max_seq=16, dtype=jnp.float32,
                               moe_every=1, moe_experts=4)
    plain = Transformer(config)
    tokens = rng.integers(0, 64, (8, 16)).astype(np.int32)

    mesh_ep = build_mesh(MeshConfig(pipeline=2, expert=2, data=2))
    gp_ep = PipelinedTransformerLM(plain, mesh_ep, num_microbatches=2,
                                   schedule="gpipe")
    g_ep = jax.jit(jax.grad(gp_ep.loss))(gp_ep.init_params(0), tokens)

    mesh_rep = build_mesh(MeshConfig(pipeline=2, tensor=2, data=2))
    gp_rep = PipelinedTransformerLM(plain, mesh_rep, num_microbatches=2,
                                    schedule="gpipe")
    g_rep = jax.jit(jax.grad(gp_rep.loss))(gp_rep.init_params(0), tokens)
    for name in ("blocks/moe/w1", "blocks/moe/w2", "blocks/moe/router/w",
                 "blocks/attn/wq", "embed/tok"):
        np.testing.assert_allclose(np.asarray(g_ep[name]),
                                   np.asarray(g_rep[name]),
                                   rtol=5e-4, atol=1e-6, err_msg=name)


def test_pipelined_moe_1f1b_interleaved_matches_plain_1f1b(rng):
    """1F1B x MoE x virtual stages: interleaving re-chunks the SAME layer
    sequence over the same microbatch split, so V=2 must reproduce V=1
    exactly."""
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    mesh = build_mesh(MeshConfig(pipeline=2, data=4))
    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=4,
                               d_ff=64, max_seq=16, dtype=jnp.float32,
                               moe_every=1, moe_experts=4)
    plain = Transformer(config)
    v1 = PipelinedTransformerLM(plain, mesh, num_microbatches=2,
                                schedule="1f1b")
    v2 = PipelinedTransformerLM(plain, mesh, num_microbatches=2,
                                schedule="1f1b", virtual_stages=2)
    tokens = rng.integers(0, 64, (8, 16)).astype(np.int32)
    loss1, grads1 = jax.jit(v1.value_and_grad)(v1.init_params(0), tokens)
    loss2, grads2 = jax.jit(v2.value_and_grad)(v2.init_params(0), tokens)
    np.testing.assert_allclose(float(loss2), float(loss1), rtol=1e-5)
    # layouts differ ([P,Lc] vs [P,V,Lc']) — compare through flat_params
    flat1 = v1.flat_params(grads1)
    flat2 = v2.flat_params(grads2)
    for name in flat1:
        np.testing.assert_allclose(np.asarray(flat2[name]),
                                   np.asarray(flat1[name]),
                                   rtol=5e-4, atol=1e-6, err_msg=name)


def test_pipelined_gpt2_arch_matches_plain(rng):
    """Converted GPT-2-family configs (learned positions + layernorm +
    biases) pipeline under GPipe: the model's own embed adds the
    positional table, the stage helpers carry biases/LN, and loss AND
    gradients (positional table and biases included) match the plain
    model.  The hand-written 1F1B schedule keeps its native-arch guard."""
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    mesh = build_mesh(MeshConfig(pipeline=2, data=4))
    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=4,
                               d_ff=64, max_seq=16, dtype=jnp.float32,
                               pos_emb="learned", norm="layernorm",
                               bias=True, mlp_act="gelu")
    plain = Transformer(config)
    piped = PipelinedTransformerLM(plain, mesh, num_microbatches=2,
                                   schedule="gpipe")
    tokens = rng.integers(0, 64, (8, 16)).astype(np.int32)
    plain_params = plain.init_params(0)
    piped_params = piped.init_params(0)
    loss_plain = float(jax.jit(plain.loss)(plain_params, tokens))
    loss_piped = float(jax.jit(piped.loss)(piped_params, tokens))
    np.testing.assert_allclose(loss_piped, loss_plain, rtol=1e-5)

    g_plain = jax.jit(jax.grad(plain.loss))(plain_params, tokens)
    g_piped = jax.jit(jax.grad(piped.loss))(piped_params, tokens)
    expected = _restack_grads(piped, {k: np.asarray(v)
                                      for k, v in g_plain.items()})
    assert set(expected) == set(g_piped)
    # the params a raw token-embed pipeline would silently drop
    for name in ("embed/pos", "layer0/attn/bq", "final_ln/bias"):
        assert name in g_plain
    for name in sorted(expected):
        np.testing.assert_allclose(
            np.asarray(g_piped[name]), expected[name], rtol=3e-4,
            atol=1e-5, err_msg=name)

    # 1F1B covers GPT-2-family configs too since round 5: the schedule
    # injects via the model's embed (positional table included) and
    # scatters the positional-table gradient at the embed tick —
    # loss AND grads must match GPipe-by-autodiff exactly
    fb = PipelinedTransformerLM(plain, mesh, num_microbatches=2,
                                schedule="1f1b")
    loss_fb, g_fb = jax.jit(fb.value_and_grad)(piped_params, tokens)
    np.testing.assert_allclose(float(loss_fb), loss_piped, rtol=1e-5)
    for name in sorted(expected):
        np.testing.assert_allclose(
            np.asarray(g_fb[name]), expected[name], rtol=3e-4,
            atol=1e-5, err_msg=f"1f1b {name}")
    # the learned-position overflow guard survives the pipelining (the
    # plain model raises; embed's mode='clip' must not silently engage)
    with pytest.raises(ValueError, match="exceeds the"):
        piped.loss(piped_params,
                   rng.integers(0, 64, (8, 32)).astype(np.int32))
    with pytest.raises(ValueError, match="exceeds the"):
        fb.value_and_grad(piped_params,
                          rng.integers(0, 64, (8, 32)).astype(np.int32))
