"""Unit tests for the PS aggregation state machine.

Covers the behaviors SURVEY.md §4 calls out as untested in the reference:
barrier counting, mean-over-contributors, late-push idempotence, bootstrap
from gradients, serve-latest semantics, iteration GC, elastic barrier width,
and bounded-staleness async mode.
"""

import numpy as np
import pytest

from parameter_server_distributed_tpu.core.optimizer import SGD, Adam, Momentum, make_optimizer
from parameter_server_distributed_tpu.core.ps_core import ParameterServerCore


def store(**kw):
    return {k: np.asarray(v, np.float32) for k, v in kw.items()}


def test_barrier_counts_and_aggregates_at_width():
    ps = ParameterServerCore(total_workers=3)
    ps.initialize_parameters(store(w=[10.0, 10.0]))
    r1 = ps.receive_gradients(0, 1, store(w=[1.0, 2.0]))
    assert r1.success and not r1.aggregation_complete
    assert r1.workers_received == 1 and r1.total_workers == 3
    r2 = ps.receive_gradients(1, 1, store(w=[3.0, 4.0]))
    assert not r2.aggregation_complete and r2.workers_received == 2
    r3 = ps.receive_gradients(2, 1, store(w=[5.0, 6.0]))
    assert r3.aggregation_complete and r3.workers_received == 3
    # mean = [3, 4]; param -= mean (lr=1.0)
    np.testing.assert_allclose(ps.get_parameters()["w"], [7.0, 6.0])


def test_mean_over_actual_contributors_not_configured_total():
    # If the barrier fires with duplicates-free count == width, mean divides
    # by contributors (reference divides by gradient count, cpp:59-63)
    ps = ParameterServerCore(total_workers=2)
    ps.initialize_parameters(store(w=[0.0]))
    ps.receive_gradients(0, 1, store(w=[2.0]))
    ps.receive_gradients(1, 1, store(w=[4.0]))
    np.testing.assert_allclose(ps.get_parameters()["w"], [-3.0])


def test_same_worker_push_never_double_counts():
    """Duplicate pre-barrier pushes from one worker count ONCE, whatever
    the aggregation mode.  The documented per-mode policy (docs/training.md)
    differs in which payload wins: streaming folds on arrival and is
    first-push-wins (an RPC retry replays an identical payload, so the
    distinction only shows for a worker that *recomputes* mid-iteration);
    the buffered escape hatch keeps the original last-push-wins."""
    ps = ParameterServerCore(total_workers=2, aggregation="streaming")
    ps.initialize_parameters(store(w=[0.0]))
    ps.receive_gradients(0, 1, store(w=[2.0]))
    r = ps.receive_gradients(0, 1, store(w=[100.0]))  # ignored, still 1 worker
    assert not r.aggregation_complete and r.workers_received == 1
    assert "duplicate" in r.message
    ps.receive_gradients(1, 1, store(w=[4.0]))
    np.testing.assert_allclose(ps.get_parameters()["w"], [-3.0])

    ps = ParameterServerCore(total_workers=2, aggregation="buffered")
    ps.initialize_parameters(store(w=[0.0]))
    ps.receive_gradients(0, 1, store(w=[100.0]))
    r = ps.receive_gradients(0, 1, store(w=[2.0]))  # overwrite, still 1 worker
    assert not r.aggregation_complete and r.workers_received == 1
    ps.receive_gradients(1, 1, store(w=[4.0]))
    np.testing.assert_allclose(ps.get_parameters()["w"], [-3.0])


def test_late_push_succeeds_without_contributing():
    ps = ParameterServerCore(total_workers=2)
    ps.initialize_parameters(store(w=[0.0]))
    ps.receive_gradients(0, 1, store(w=[2.0]))
    ps.receive_gradients(1, 1, store(w=[4.0]))
    before = ps.get_parameters()["w"].copy()
    late = ps.receive_gradients(2, 1, store(w=[999.0]))
    assert late.success and late.aggregation_complete
    np.testing.assert_array_equal(ps.get_parameters()["w"], before)


def test_bootstrap_params_from_first_aggregation():
    # reference: src/parameter_server.cpp:78-81
    ps = ParameterServerCore(total_workers=2)
    ps.receive_gradients(0, 0, store(w=[2.0, 4.0]))
    ps.receive_gradients(1, 0, store(w=[4.0, 8.0]))
    np.testing.assert_allclose(ps.get_parameters()["w"], [3.0, 6.0])


def test_serve_parameters_ignores_iteration_returns_latest():
    # reference: src/parameter_server.cpp:93-97
    ps = ParameterServerCore(total_workers=1)
    ps.initialize_parameters(store(w=[1.0]))
    ps.receive_gradients(0, 5, store(w=[1.0]))
    it, params, ready = ps.serve_parameters(iteration=12345)
    assert ready and it == 5
    np.testing.assert_allclose(params["w"], [0.0])


def test_current_iteration_monotone_max():
    ps = ParameterServerCore(total_workers=2)
    ps.receive_gradients(0, 7, store(w=[1.0]))
    assert ps.current_iteration == 7
    ps.receive_gradients(0, 3, store(w=[1.0]))
    assert ps.current_iteration == 7


def test_check_sync_status_lifecycle():
    ps = ParameterServerCore(total_workers=2)
    it, ready, recv, total = ps.check_sync_status(9)
    assert (ready, recv, total) == (False, 0, 2)
    ps.receive_gradients(0, 9, store(w=[1.0]))
    _, ready, recv, _ = ps.check_sync_status(9)
    assert (ready, recv) == (False, 1)
    ps.receive_gradients(1, 9, store(w=[1.0]))
    _, ready, recv, _ = ps.check_sync_status(9)
    assert (ready, recv) == (True, 2)


def test_iteration_state_gc_bounds_memory():
    # the reference never GCs iteration_states_ (unbounded growth)
    ps = ParameterServerCore(total_workers=1, gc_iterations=8)
    for it in range(100):
        ps.receive_gradients(0, it, store(w=[0.0]))
    assert ps.tracked_iterations <= 8


def test_elastic_barrier_width_tracks_live_workers():
    live = {"n": 3}
    ps = ParameterServerCore(total_workers=5, live_workers_fn=lambda: live["n"])
    ps.initialize_parameters(store(w=[0.0]))
    ps.receive_gradients(0, 1, store(w=[3.0]))
    ps.receive_gradients(1, 1, store(w=[3.0]))
    r = ps.receive_gradients(2, 1, store(w=[3.0]))
    assert r.aggregation_complete  # barrier = 3 live, not 5 configured
    live["n"] = 1
    r2 = ps.receive_gradients(0, 2, store(w=[1.0]))
    assert r2.aggregation_complete  # barrier shrank without restart


def test_multiple_tensors_and_shapes():
    ps = ParameterServerCore(total_workers=2)
    ps.initialize_parameters(store(w=np.ones((2, 2)), b=np.zeros(3)))
    ps.receive_gradients(0, 1, store(w=np.full((2, 2), 2.0), b=[1.0, 1.0, 1.0]))
    ps.receive_gradients(1, 1, store(w=np.full((2, 2), 4.0), b=[3.0, 3.0, 3.0]))
    p = ps.get_parameters()
    np.testing.assert_allclose(p["w"], np.full((2, 2), -2.0))
    np.testing.assert_allclose(p["b"], [-2.0, -2.0, -2.0])


def test_snapshot_restore_roundtrip():
    ps = ParameterServerCore(total_workers=1)
    ps.initialize_parameters(store(w=[1.0, 2.0]))
    ps.receive_gradients(0, 4, store(w=[0.5, 0.5]))
    ps.epoch = 2
    epoch, it, params = ps.snapshot()
    ps2 = ParameterServerCore(total_workers=1)
    ps2.restore(epoch, it, params)
    assert ps2.epoch == 2 and ps2.current_iteration == 4
    np.testing.assert_allclose(ps2.get_parameters()["w"], [0.5, 1.5])


def test_elastic_shrink_releases_buffered_iteration_via_poll():
    # Barrier=3; two workers push, then the third dies and the barrier
    # shrinks to 2.  The next sync-status poll must fire the aggregation
    # rather than strand the survivors.
    live = {"n": 3}
    ps = ParameterServerCore(total_workers=3, live_workers_fn=lambda: live["n"])
    ps.initialize_parameters(store(w=[0.0]))
    ps.receive_gradients(0, 1, store(w=[2.0]))
    ps.receive_gradients(1, 1, store(w=[4.0]))
    _, ready, _, _ = ps.check_sync_status(1)
    assert not ready
    live["n"] = 2  # worker 2 evicted
    _, ready, recv, total = ps.check_sync_status(1)
    assert ready and recv == 2 and total == 2
    np.testing.assert_allclose(ps.get_parameters()["w"], [-3.0])


def test_straggler_push_after_gc_is_noop():
    # A push for a long-GC'd aggregated iteration must not re-apply a stale
    # gradient through a freshly-created state.
    ps = ParameterServerCore(total_workers=1, gc_iterations=4)
    ps.initialize_parameters(store(w=[0.0]))
    for it in range(20):
        ps.receive_gradients(0, it, store(w=[0.0]))
    before = ps.get_parameters()["w"].copy()
    r = ps.receive_gradients(1, 2, store(w=[1000.0]))  # iteration 2 was GC'd
    assert r.success and r.aggregation_complete
    np.testing.assert_array_equal(ps.get_parameters()["w"], before)
    # and its sync status reads ready, not stuck-forever
    _, ready, _, _ = ps.check_sync_status(2)
    assert ready


def test_optimizer_state_survives_snapshot_restore():
    opt = Adam(0.1)
    ps = ParameterServerCore(total_workers=1, optimizer=opt)
    ps.initialize_parameters(store(w=[1.0]))
    ps.receive_gradients(0, 1, store(w=[0.5]))
    epoch, it, params = ps.snapshot()
    opt_state = ps.optimizer_state()
    assert opt_state["step"] == 1

    opt2 = Adam(0.1)
    ps2 = ParameterServerCore(total_workers=1, optimizer=opt2)
    ps2.restore(epoch, it, params, optimizer_state=opt_state)
    ps.receive_gradients(0, 2, store(w=[0.5]))
    ps2.receive_gradients(0, 2, store(w=[0.5]))
    np.testing.assert_allclose(ps2.get_parameters()["w"],
                               ps.get_parameters()["w"])


# ---------------------------------------------------------------- async mode

def test_async_applies_on_arrival():
    ps = ParameterServerCore(total_workers=4, staleness_bound=2,
                             optimizer=SGD(0.5))
    ps.initialize_parameters(store(w=[10.0]))
    r = ps.receive_gradients(0, 0, store(w=[2.0]))
    assert r.success and r.aggregation_complete
    np.testing.assert_allclose(ps.get_parameters()["w"], [9.0])
    # current_iteration stays the monotone max of worker iterations seen;
    # the applied-update count is the PS version
    assert ps.current_iteration == 0 and ps.applied_updates == 1


def test_async_rejects_stale_push():
    ps = ParameterServerCore(total_workers=2, staleness_bound=1)
    ps.initialize_parameters(store(w=[0.0]))
    for i in range(5):
        ps.receive_gradients(0, i, store(w=[0.0]))
    stale = ps.receive_gradients(1, 0, store(w=[100.0]))
    assert not stale.success and "stale" in stale.message
    fresh_it = ps.current_iteration
    ok = ps.receive_gradients(1, fresh_it, store(w=[1.0]))
    assert ok.success


def test_async_bootstrap_race_does_not_zero_params():
    # Two workers race identical init pushes at an empty async PS; the
    # second must be dropped, not applied as a gradient (params - lr*init
    # would be exactly zero at lr=1.0).
    ps = ParameterServerCore(total_workers=2, staleness_bound=2)
    init = store(w=[3.0, -1.0])
    r1 = ps.receive_gradients(0, 0, init)
    r2 = ps.receive_gradients(1, 0, init)
    assert r1.success and r2.success
    np.testing.assert_allclose(ps.get_parameters()["w"], [3.0, -1.0])
    # real gradients after bootstrap still apply
    ps.receive_gradients(0, 1, store(w=[1.0, 1.0]))
    np.testing.assert_allclose(ps.get_parameters()["w"], [2.0, -2.0])


def test_async_sync_status_always_ready():
    ps = ParameterServerCore(total_workers=2, staleness_bound=3)
    _, ready, _, _ = ps.check_sync_status(0)
    assert ready


# ---------------------------------------------------------------- optimizers

def test_sgd_momentum_adam_steps():
    p = store(w=[1.0])
    g = store(w=[1.0])
    sgd = SGD(0.1)
    np.testing.assert_allclose(sgd.apply(p, g)["w"], [0.9])
    mom = Momentum(0.1, momentum=0.5)
    p1 = mom.apply(p, g)
    p2 = mom.apply(p1, g)  # velocity = 1, then 1.5
    np.testing.assert_allclose(p2["w"], [0.9 - 0.15], rtol=1e-6)
    adam = Adam(0.1)
    pa = adam.apply(p, g)
    assert pa["w"][0] < 1.0
    # state round-trips
    st = adam.state_dict()
    adam2 = Adam(0.1)
    adam2.load_state_dict(st)
    np.testing.assert_allclose(adam2.apply(pa, g)["w"], adam.apply(pa, g)["w"])


def test_make_optimizer_factory():
    from parameter_server_distributed_tpu.core.optimizer import Lion

    assert isinstance(make_optimizer("sgd", 1.0), SGD)
    assert isinstance(make_optimizer("momentum", 1.0), Momentum)
    assert isinstance(make_optimizer("adam", 1e-3), Adam)
    assert isinstance(make_optimizer("lion", 1e-4), Lion)
    with pytest.raises(ValueError):
        make_optimizer("adagrad", 1.0)


def test_host_lion_sign_update_one_slot():
    """Host Lion: sign-of-interpolated-momentum update (bounded step
    magnitude lr*(1+wd*|p|)), ONE slot, matrices-only decay, state
    round-trips through the checkpoint dict."""
    import numpy as np

    from parameter_server_distributed_tpu.core.optimizer import Lion

    opt = Lion(0.1, weight_decay=0.0)
    params = {"w": np.zeros((2, 2), np.float32),
              "ln/scale": np.ones((2,), np.float32)}
    grads = {"w": np.asarray([[3.0, -2.0], [0.5, -9.0]], np.float32),
             "ln/scale": np.zeros((2,), np.float32)}
    out = opt.apply(params, grads)
    # first step: update = sign((1-b1) g) = sign(g); lr 0.1
    np.testing.assert_allclose(
        out["w"], [[-0.1, 0.1], [-0.1, 0.1]], atol=1e-7)
    assert set(opt.state_dict()["m"]) == {"w", "ln/scale"}  # one slot
    # decay masked off 1D params
    opt_wd = Lion(0.1, weight_decay=0.5)
    out2 = opt_wd.apply({"ln/scale": np.ones((2,), np.float32)},
                        {"ln/scale": np.zeros((2,), np.float32)})
    np.testing.assert_array_equal(out2["ln/scale"], 1.0)
    # checkpoint round-trip
    fresh = Lion(0.1)
    fresh.load_state_dict(opt.state_dict())
    np.testing.assert_array_equal(fresh.m["w"], opt.m["w"])


def test_host_adamw_decays_matrices_only():
    """Host AdamW: decoupled decay shrinks matrices, never 1D params —
    matching the device-side optax mask."""
    import numpy as np

    from parameter_server_distributed_tpu.core.optimizer import make_optimizer

    opt = make_optimizer("adamw", 0.1)
    params = {"w": np.ones((4, 4), np.float32),
              "ln/scale": np.ones((4,), np.float32)}
    zero = {k: np.zeros_like(v) for k, v in params.items()}
    out = opt.apply(params, zero)
    np.testing.assert_array_equal(out["ln/scale"], params["ln/scale"])
    assert out["w"].max() < 1.0


# ------------------------------------------------- async non-blocking serve

class _LazyArray(np.ndarray):
    """numpy array with a jax-like async-materialization surface: is_ready
    flips when block_until_ready() is called (or the test flips it)."""

    def __new__(cls, values):
        obj = np.asarray(values, np.float32).view(cls)
        obj._ready = False
        return obj

    def is_ready(self):
        return self._ready

    def block_until_ready(self):
        self._ready = True
        return self


class _LazyOptimizer(SGD):
    """SGD whose outputs pretend to be in-flight device computations."""

    def apply(self, params, grads):
        out = super().apply(params, grads)
        return {k: _LazyArray(v) for k, v in out.items()}


def test_async_serve_does_not_block_on_in_flight_apply():
    """Bounded-staleness reads never wait on device compute: while the
    newest store is an unmaterialized promise, the previous materialized
    version is served; once the apply lands, the new store is promoted."""
    ps = ParameterServerCore(total_workers=1, staleness_bound=10,
                             optimizer=_LazyOptimizer(0.5))
    ps.initialize_parameters(store(w=[10.0]))
    r = ps.receive_gradients(0, 0, store(w=[2.0]))
    assert r.success
    # apply in flight: serve returns the PREVIOUS (materialized) params
    _, served, ready = ps.serve_parameters()
    assert ready
    np.testing.assert_allclose(served["w"], [10.0])
    # apply lands -> next serve promotes the new store
    with ps._params_lock:
        for v in ps._params.values():
            v.block_until_ready()
    _, served2, _ = ps.serve_parameters()
    np.testing.assert_allclose(served2["w"], [9.0])


def test_async_depth_bound_fences_previous_apply():
    """A second push while the previous apply is still in flight fences on
    it first (depth-1 pipeline), so the XLA queue cannot grow without
    bound; values stay exact."""
    ps = ParameterServerCore(total_workers=1, staleness_bound=10,
                             optimizer=_LazyOptimizer(0.5))
    ps.initialize_parameters(store(w=[10.0]))
    ps.receive_gradients(0, 0, store(w=[2.0]))   # -> 9.0, in flight
    ps.receive_gradients(0, 1, store(w=[2.0]))   # fences 9.0, -> 8.0
    _, served, _ = ps.serve_parameters()
    np.testing.assert_allclose(served["w"], [9.0])  # 8.0 still in flight
    with ps._params_lock:
        for v in ps._params.values():
            v.block_until_ready()
    _, served2, _ = ps.serve_parameters()
    np.testing.assert_allclose(served2["w"], [8.0])


def test_sync_serve_unaffected_by_nonblocking_path():
    """Sync (barrier) mode always serves _params itself — clients polled
    the barrier and must observe post-aggregation values."""
    ps = ParameterServerCore(total_workers=1, optimizer=SGD(1.0))
    ps.initialize_parameters(store(w=[4.0]))
    ps.receive_gradients(0, 1, store(w=[1.0]))
    _, served, _ = ps.serve_parameters()
    np.testing.assert_allclose(served["w"], [3.0])


def test_async_concurrent_push_pull_serves_consistent_snapshots():
    """Race discipline for the non-blocking serve path: concurrent async
    pushes (device-style lazy applies) and serves must never hand out a
    TORN store — every served snapshot's tensors must all come from the
    same applied generation.  Generation g's store is {w: g, b: g}, so
    consistency is checkable per pull."""
    import threading
    import time as _time

    ps = ParameterServerCore(total_workers=1, staleness_bound=10**9,
                             optimizer=_LazyOptimizer(1.0))
    ps.initialize_parameters(store(w=[0.0, 0.0], b=[0.0]))
    stop = threading.Event()
    errors: list = []

    def guarded(fn):
        # a crashed thread must FAIL the test, not die silently and let
        # the invariant check pass vacuously
        def run():
            try:
                fn()
            except Exception as exc:  # noqa: BLE001
                errors.append(f"thread crashed: {exc!r}")
                stop.set()
        return run

    def pusher():
        it = 0
        while not stop.is_set():
            it += 1
            # grad -1 at lr 1.0: params increase by exactly 1 per apply
            ps.receive_gradients(0, it, store(w=[-1.0, -1.0], b=[-1.0]))
            # materialize promptly so serves can promote
            with ps._params_lock:
                for v in ps._params.values():
                    if hasattr(v, "block_until_ready"):
                        v.block_until_ready()
            if ps.applied_updates >= 200:   # progress-bound, not
                stop.set()                  # wall-clock-bound

    def puller():
        while not stop.is_set():
            _, served, ready = ps.serve_parameters()
            if not ready:
                errors.append("not ready")
                continue
            gens = {float(np.asarray(v).reshape(-1)[0])
                    for v in served.values()}
            if len(gens) != 1:
                errors.append(f"torn snapshot: generations {gens}")

    # daemon: if a lock-order regression ever deadlocks the pusher, the
    # join timeout must FAIL the test — not hang interpreter exit
    threads = [threading.Thread(target=guarded(pusher), daemon=True)] + [
        threading.Thread(target=guarded(puller), daemon=True)
        for _ in range(3)]
    deadline = _time.monotonic() + 30.0
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=max(0.1, deadline - _time.monotonic()))
    stop.set()
    assert not errors, errors[:5]
    assert ps.applied_updates >= 200  # the pusher made real progress


# ------------------------------------------------- fused barrier wait (CV)
def test_wait_for_aggregation_wakes_on_barrier_close():
    """A waiter parked on an incomplete iteration is released by the push
    that closes the barrier — the serve-when-complete primitive of the
    fused data plane (no polling)."""
    import threading
    import time

    ps = ParameterServerCore(total_workers=2)
    ps.initialize_parameters(store(w=[10.0]))
    ps.receive_gradients(0, 1, store(w=[2.0]))
    out = {}

    def wait():
        out["result"] = ps.wait_for_aggregation(1, timeout=30.0)

    t = threading.Thread(target=wait)
    t.start()
    time.sleep(0.05)          # waiter parks before the closing push
    t0 = time.perf_counter()
    ps.receive_gradients(1, 1, store(w=[4.0]))
    t.join(timeout=5.0)
    woke_in = time.perf_counter() - t0
    assert not t.is_alive()
    ready, received, total = out["result"]
    assert ready and received == 2 and total == 2
    # woken by notify, not by a poll cadence: well under the 250 ms
    # heartbeat re-check, let alone the reference's 50 ms poll loop
    assert woke_in < 0.2
    np.testing.assert_allclose(ps.get_parameters()["w"], [7.0])


def test_wait_for_aggregation_already_complete_and_gcd():
    ps = ParameterServerCore(total_workers=1, gc_iterations=2)
    ps.initialize_parameters(store(w=[1.0]))
    for it in range(1, 6):
        ps.receive_gradients(0, it, store(w=[0.0]))
    # a long-GC'd iteration still reads as complete via the watermark
    ready, received, total = ps.wait_for_aggregation(1, timeout=0.0)
    assert ready and received == total == 1
    ready, _, _ = ps.wait_for_aggregation(5, timeout=0.0)
    assert ready


def test_wait_for_aggregation_times_out_with_progress():
    ps = ParameterServerCore(total_workers=3)
    ps.initialize_parameters(store(w=[1.0]))
    ps.receive_gradients(0, 1, store(w=[0.5]))
    ready, received, total = ps.wait_for_aggregation(1, timeout=0.05)
    assert not ready and received == 1 and total == 3


def test_wait_for_aggregation_async_mode_immediate():
    ps = ParameterServerCore(total_workers=4, staleness_bound=3)
    ready, _, _ = ps.wait_for_aggregation(7, timeout=0.0)
    assert ready


def test_wait_for_aggregation_releases_on_elastic_shrink():
    """A fully-buffered iteration must fire from INSIDE the wait when the
    elastic barrier width shrinks (worker evicted mid-iteration) — the CV
    wait re-evaluates the width on its heartbeat, like the polled path."""
    import threading
    import time

    width = {"n": 2}
    ps = ParameterServerCore(total_workers=2,
                             live_workers_fn=lambda: width["n"])
    ps.initialize_parameters(store(w=[10.0]))
    ps.receive_gradients(0, 1, store(w=[2.0]))
    out = {}

    def wait():
        out["result"] = ps.wait_for_aggregation(1, timeout=10.0)

    t = threading.Thread(target=wait)
    t.start()
    time.sleep(0.05)
    width["n"] = 1            # eviction: the lone contributor satisfies it
    t.join(timeout=5.0)
    assert not t.is_alive()
    ready, received, total = out["result"]
    assert ready and received == 1 and total == 1
    np.testing.assert_allclose(ps.get_parameters()["w"], [8.0])
