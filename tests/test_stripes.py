"""Stripe-parallel PS hot path (ISSUE 5).

Covers: the stripe partition + shared executor primitives, bit-for-bit
striped==serial equivalence across optimizers / stripe counts / chunked
pushes, the in-flight-fold drain at barrier close, checkpoint round-trip
of striped optimizer state, lock-checked concurrent push/close/restore
races, the in-place optimizer peak-allocation regression, the
error-feedback gate + convergence property, the striped serve-cache
encode's byte identity, and the stripe observability metrics.
"""

from __future__ import annotations

import threading
import time
import tracemalloc

import numpy as np
import pytest

from parameter_server_distributed_tpu import native
from parameter_server_distributed_tpu.core import stripes as st
from parameter_server_distributed_tpu.core.optimizer import (
    SGD, Adam, AdamW, Lion, Momentum, make_optimizer)
from parameter_server_distributed_tpu.core.ps_core import ParameterServerCore
from parameter_server_distributed_tpu.core.tensor import to_wire
from parameter_server_distributed_tpu.obs import stats as obs_stats
from parameter_server_distributed_tpu.rpc import messages as m
from parameter_server_distributed_tpu.rpc.data_plane import (
    encode_parameter_record_groups, split_tensors)


@pytest.fixture
def numpy_only():
    """Pin the numpy paths: the bit-for-bit contracts are defined on the
    numpy semantics (the native kernels associate sums differently)."""
    native.set_enabled(False)
    yield
    native.set_enabled(True)


def _grads(rng, shapes):
    return {name: rng.standard_normal(shape).astype(np.float32)
            for name, shape in shapes.items()}


SHAPES = {f"layer{i}/w": (23, 7) for i in range(6)}
SHAPES.update({"bias": (11,), "scale": ()})


# ------------------------------------------------------------- primitives

def test_stripe_of_is_stable_and_total():
    # crc32-based: stable across processes (hash() is salted) and total
    # over any stripe count
    assert st.stripe_of("layer0/w", 1) == 0
    for s in (2, 3, 8):
        for name in SHAPES:
            assert 0 <= st.stripe_of(name, s) < s
            assert st.stripe_of(name, s) == st.stripe_of(name, s)


def test_partition_names_covers_everything_in_order():
    names = list(SHAPES)
    groups = st.partition_names(names, 3)
    flat = [n for g in groups for n in g]
    assert sorted(flat) == sorted(names)
    for group in groups:
        # input order preserved within a stripe
        assert group == [n for n in names if n in set(group)]
        owners = {st.stripe_of(n, 3) for n in group}
        assert len(owners) == 1


def test_stripe_count_env_and_override(monkeypatch):
    monkeypatch.setenv(st.ENV_STRIPES, "5")
    assert st.stripe_count() == 5
    assert st.stripe_count(3) == 3  # explicit override beats env
    monkeypatch.delenv(st.ENV_STRIPES)
    assert st.stripe_count() >= 1
    with pytest.raises(ValueError):
        st.stripe_count(0)


def test_run_striped_orders_results_and_propagates_errors():
    assert st.run_striped([]) == []
    assert st.run_striped([lambda: 7]) == [7]
    results = st.run_striped([(lambda i=i: i * i) for i in range(8)])
    assert results == [i * i for i in range(8)]

    finished = []

    def ok(i):
        time.sleep(0.01)
        finished.append(i)
        return i

    def boom():
        raise RuntimeError("stripe failed")

    with pytest.raises(RuntimeError, match="stripe failed"):
        # the error propagates only after every sibling finished — the
        # quiescence guarantee ps_core's put-back paths rely on
        st.run_striped([boom] + [(lambda i=i: ok(i)) for i in range(4)])
    assert sorted(finished) == [0, 1, 2, 3]


# ------------------------------------------------------------ equivalence

@pytest.mark.parametrize("make_opt", [
    lambda: SGD(1.0), lambda: Momentum(0.1, momentum=0.9),
    lambda: Adam(0.01), lambda: AdamW(0.01), lambda: Lion(0.01)])
@pytest.mark.parametrize("n_stripes", [2, 3, 8])
def test_striped_matches_serial_bit_for_bit(numpy_only, n_stripes,
                                            make_opt):
    """PSDT_STRIPES=1 is the exact pre-stripe serial path; S>1 must land
    bit-identical parameters — stripes never split a tensor's reduction
    and the per-tensor ufunc sequences are unchanged."""
    rng = np.random.default_rng(7)
    init = _grads(rng, SHAPES)
    cores = {s: ParameterServerCore(total_workers=3, optimizer=make_opt(),
                                    stripes=s)
             for s in (1, n_stripes)}
    for core in cores.values():
        core.initialize_parameters(init)
    for it in range(1, 4):
        pushes = [_grads(rng, SHAPES) for _ in range(3)]
        for core in cores.values():
            for wid, grads in enumerate(pushes):
                r = core.receive_gradients(wid, it, grads)
            assert r.aggregation_complete, r.message
    serial = cores[1].get_parameters()
    striped = cores[n_stripes].get_parameters()
    for name in SHAPES:
        np.testing.assert_array_equal(serial[name], striped[name])


def test_striped_chunked_fold_equals_whole_push(numpy_only):
    """A chunk-streamed push through begin_push folds stripe-parallel and
    must land exactly what one whole-store push lands."""
    rng = np.random.default_rng(3)
    init = _grads(rng, SHAPES)
    grads = [_grads(rng, SHAPES) for _ in range(2)]
    whole = ParameterServerCore(total_workers=2, stripes=1)
    chunked = ParameterServerCore(total_workers=2, stripes=4)
    for core in (whole, chunked):
        core.initialize_parameters(init)
    for wid in range(2):
        whole.receive_gradients(wid, 1, grads[wid])
        sink = chunked.begin_push(wid, 1)
        items = list(grads[wid].items())
        for lo in range(0, len(items), 3):
            sink.fold(dict(items[lo:lo + 3]))
        r = sink.commit()
    assert r.aggregation_complete
    a, b = whole.get_parameters(), chunked.get_parameters()
    for name in SHAPES:
        np.testing.assert_array_equal(a[name], b[name])


def test_striped_retry_replay_folds_each_tensor_once(numpy_only):
    """The reservation set must dedup a replayed chunk exactly like the
    serial folded set: retries converge to one contribution."""
    core = ParameterServerCore(total_workers=2, stripes=4)
    core.initialize_parameters({"w": np.zeros(4, np.float32)})
    payload = {"w": np.full(4, 6.0, np.float32)}
    sink = core.begin_push(0, 1)
    sink.fold(payload)
    sink.fold(payload)  # replayed chunk (RPC retry): must not double-add
    sink.commit()
    core.receive_gradients(1, 1, {"w": np.full(4, 2.0, np.float32)})
    # mean of {6, 2} = 4; lr 1.0 SGD from 0 => -4
    np.testing.assert_array_equal(core.get_parameters()["w"],
                                  np.full(4, -4.0, np.float32))


class _GatedArray:
    """Array-like whose materialization parks on an event — pins a
    striped fold inside its numpy conversion, outside _state_lock."""

    def __init__(self, value: np.ndarray, gate: threading.Event,
                 entered: threading.Event):
        self._value = value
        self._gate = gate
        self._entered = entered

    def __array__(self, dtype=None, copy=None):
        self._entered.set()
        assert self._gate.wait(10.0), "test gate never released"
        return np.asarray(self._value, dtype or np.float32)


def test_close_drains_inflight_striped_folds(numpy_only):
    """A fold whose numpy add is still running when the barrier fills
    must be drained into the aggregate before the close scales it — the
    mid-stream worker's values stay in their per-name means (the
    documented fold-on-arrival semantics), never torn or dropped."""
    core = ParameterServerCore(total_workers=2, stripes=2)
    core.initialize_parameters({"w": np.zeros(3, np.float32)})
    gate, entered = threading.Event(), threading.Event()
    slow = _GatedArray(np.full(3, 9.0, np.float32), gate, entered)

    def slow_fold():
        sink = core.begin_push(0, 1)
        sink.fold({"w": slow})  # blocks in __array__ inside the stripe

    folder = threading.Thread(target=slow_fold, name="test-slow-fold",
                              daemon=True)
    folder.start()
    assert entered.wait(5.0)

    done = threading.Event()

    def closing_pushes():
        core.receive_gradients(1, 1, {"w": np.full(3, 3.0, np.float32)})
        core.receive_gradients(2, 1, {"w": np.full(3, 6.0, np.float32)})
        done.set()

    closer = threading.Thread(target=closing_pushes, name="test-closer",
                              daemon=True)
    closer.start()
    time.sleep(0.3)
    # the barrier is full (workers 1+2) but the close must still be
    # draining worker 0's in-flight fold
    assert not done.is_set()
    gate.set()
    folder.join(5.0)
    closer.join(5.0)
    assert done.is_set()
    # all three folds are in the mean: (9 + 3 + 6) / 3 = 6, SGD lr 1.0
    np.testing.assert_array_equal(core.get_parameters()["w"],
                                  np.full(3, -6.0, np.float32))


class _GatedSGD(SGD):
    """SGD whose striped shards park on an event — pins the striped
    apply's compute window open for race tests."""

    def __init__(self, gate: threading.Event, entered: threading.Event):
        super().__init__(1.0)
        self._gate = gate
        self._entered = entered

    def apply_shard(self, params, grads):
        self._entered.set()
        assert self._gate.wait(10.0), "test gate never released"
        return super().apply_shard(params, grads)


def test_initialize_during_striped_apply_wins(numpy_only):
    """An initialize_parameters() landing while the striped apply is
    computing must not be clobbered by the swap — the serial path's
    outcome for that interleaving (apply under the lock, then the
    initialize overwrites) is 'the initialize wins'."""
    gate, entered = threading.Event(), threading.Event()
    core = ParameterServerCore(total_workers=1, stripes=2,
                               optimizer=_GatedSGD(gate, entered))
    core.initialize_parameters({"w": np.zeros(4, np.float32),
                                "b": np.zeros(2, np.float32)})

    pusher = threading.Thread(
        target=core.receive_gradients, name="test-apply-pusher",
        args=(0, 1, {"w": np.full(4, 5.0, np.float32),
                     "b": np.full(2, 5.0, np.float32)}), daemon=True)
    pusher.start()
    assert entered.wait(5.0)
    fresh = {"w": np.full(4, 42.0, np.float32),
             "b": np.full(2, 42.0, np.float32)}
    core.initialize_parameters(fresh)
    gate.set()
    pusher.join(10.0)
    assert not pusher.is_alive()
    params = core.get_parameters()
    np.testing.assert_array_equal(params["w"], fresh["w"])
    np.testing.assert_array_equal(params["b"], fresh["b"])


# ------------------------------------------------------------- checkpoint

def test_checkpoint_roundtrip_of_striped_optimizer_state(tmp_path,
                                                         numpy_only):
    """Optimizer state written by stripe-parallel applies must survive a
    CheckpointManager save/load into ANY stripe count (the slices are
    keyed by tensor name, not by stripe id) and continue bit-identically."""
    from parameter_server_distributed_tpu.checkpoint.manager import (
        CheckpointManager)

    rng = np.random.default_rng(11)
    init = _grads(rng, SHAPES)
    steps = [_grads(rng, SHAPES) for _ in range(4)]

    core = ParameterServerCore(total_workers=1, optimizer=Adam(0.05),
                               stripes=3)
    core.initialize_parameters(init)
    for it, grads in enumerate(steps[:2], start=1):
        core.receive_gradients(0, it, grads)
    mgr = CheckpointManager(core, directory=str(tmp_path),
                            checkpoint_interval=10**9)
    path = mgr.save(epoch=1)

    finals = {}
    for restore_stripes in (1, 2, 3):
        restored = ParameterServerCore(total_workers=1,
                                       optimizer=Adam(0.05),
                                       stripes=restore_stripes)
        CheckpointManager(restored, directory=str(tmp_path),
                          checkpoint_interval=10**9).load(path)
        for it, grads in enumerate(steps[2:], start=3):
            restored.receive_gradients(0, it, grads)
        finals[restore_stripes] = restored.get_parameters()
    for it, grads in enumerate(steps[2:], start=3):
        core.receive_gradients(0, it, grads)
    live = core.get_parameters()
    for s, params in finals.items():
        for name in SHAPES:
            np.testing.assert_array_equal(live[name], params[name])


# --------------------------------------------------------------- lockcheck

@pytest.mark.lockcheck
def test_concurrent_striped_push_close_restore_races(numpy_only):
    """Pushers, chunk folders, sync pollers, and a restorer hammering a
    striped core under PSDT_LOCK_CHECK=1: every stripe/pool/core lock is
    an order-asserting CheckedLock, so an ordering bug raises instead of
    deadlocking.  The store must stay structurally intact throughout."""
    rng = np.random.default_rng(5)
    init = _grads(rng, SHAPES)
    core = ParameterServerCore(total_workers=3, optimizer=Adam(0.01),
                               stripes=3)
    core.initialize_parameters(init)
    errors: list[BaseException] = []
    stop = threading.Event()

    def pusher(wid: int):
        try:
            it = 1
            while not stop.is_set():
                sink = core.begin_push(wid, it)
                items = list(_grads(rng, SHAPES).items())
                sink.fold(dict(items[:4]))
                sink.fold(dict(items[4:]))
                sink.commit()
                core.check_sync_status(it)
                it += 1
        except BaseException as exc:  # noqa: BLE001 — surfaced below
            errors.append(exc)

    def restorer():
        try:
            while not stop.is_set():
                time.sleep(0.02)
                epoch, it, params = core.snapshot()
                state = core.optimizer_state()
                core.restore(epoch, it, params, optimizer_state=state)
        except BaseException as exc:  # noqa: BLE001 — surfaced below
            errors.append(exc)

    threads = [threading.Thread(target=pusher, args=(wid,),
                                name=f"test-pusher-{wid}", daemon=True)
               for wid in range(3)]
    threads.append(threading.Thread(target=restorer, name="test-restorer",
                                    daemon=True))
    for t in threads:
        t.start()
    time.sleep(1.0)
    stop.set()
    for t in threads:
        t.join(10.0)
        assert not t.is_alive()
    assert not errors, errors
    params = core.get_parameters()
    assert set(params) == set(SHAPES)
    for name, value in params.items():
        assert np.all(np.isfinite(value)), name


# ------------------------------------------------- in-place optimizer path

@pytest.mark.parametrize("make_opt", [lambda: Adam(0.01),
                                      lambda: Momentum(0.1)])
def test_optimizer_numpy_path_peak_allocation(numpy_only, make_opt):
    """The in-place numpy paths must allocate ~(output + scratch) per
    tensor, not one temporary per sub-op: a steady-state apply over a
    4 MB tensor stays under 2.5 tensor-sizes of peak traced allocation
    (the old expression-per-line Adam peaked well past 4x)."""
    n = 1_000_000
    params = {"w": np.zeros(n, np.float32)}
    grads = {"w": np.full(n, 0.5, np.float32)}
    opt = make_opt()
    params = opt.apply(params, grads)  # warm: slots + scratch allocate
    tracemalloc.start()
    params = opt.apply(params, grads)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert peak <= 2.5 * 4 * n, f"peak {peak / 4 / n:.2f}x tensor size"


def test_inplace_adam_is_bitwise_the_pre_inplace_formula(numpy_only):
    """The in-place rewrite must preserve the ORIGINAL expression's
    evaluation order exactly — `p - lr * (m/bc1) / denom` associates as
    ((lr * (m/bc1)) / denom), and reordering it costs 1-ulp drift that
    breaks PSDT_STRIPES=1 bit-compatibility with pre-stripe checkpoints."""
    rng = np.random.default_rng(2)
    p0 = rng.standard_normal(257).astype(np.float32)
    opt = Adam(0.01, b1=0.9, b2=0.999, eps=1e-8)
    params = {"w": p0.copy()}
    m_ref = np.zeros_like(p0)
    v_ref = np.zeros_like(p0)
    p_ref = p0.copy()
    for step in range(1, 4):
        g = rng.standard_normal(257).astype(np.float32)
        params = opt.apply(params, {"w": g})
        b1, b2 = np.float32(0.9), np.float32(0.999)
        m_ref = b1 * m_ref + (1 - b1) * g
        v_ref = b2 * v_ref + (1 - b2) * (g * g)
        bc1 = 1.0 - 0.9 ** step
        bc2 = 1.0 - 0.999 ** step
        # verbatim pre-in-place expression, original precedence
        p_ref = p_ref - np.float32(0.01) * (m_ref / bc1) / (
            np.sqrt(v_ref / bc2) + 1e-8)
    np.testing.assert_array_equal(params["w"], p_ref)


def test_striping_declarations():
    """Host optimizers are name-sliceable; device-resident jit programs
    are not and must fall back to the serial whole-store apply."""
    for name in ("sgd", "momentum", "adam", "adamw", "lion"):
        assert make_optimizer(name, 0.1).supports_striping, name
    from parameter_server_distributed_tpu.async_sgd.device_optimizer import (
        DeviceOptimizer, PallasOptimizer)
    assert DeviceOptimizer.supports_striping is False
    assert PallasOptimizer.supports_striping is False


def test_pallas_optimizer_on_striped_sync_path():
    """optimizer=pallas_* on the synchronous barrier path: the striped
    close must fall back to the (device-resident) whole-store apply and
    land the correct SGD result even with stripes configured."""
    core = ParameterServerCore(total_workers=2, stripes=2,
                               optimizer=make_optimizer("pallas_sgd", 1.0))
    init = {"w": np.arange(8, dtype=np.float32),
            "b": np.ones(3, np.float32)}
    core.initialize_parameters(init)
    core.receive_gradients(0, 1, {"w": np.full(8, 2.0, np.float32),
                                  "b": np.full(3, 4.0, np.float32)})
    r = core.receive_gradients(1, 1, {"w": np.full(8, 4.0, np.float32),
                                      "b": np.full(3, 2.0, np.float32)})
    assert r.aggregation_complete, r.message
    params = core.get_parameters()
    np.testing.assert_allclose(np.asarray(params["w"]),
                               np.arange(8, dtype=np.float32) - 3.0)
    np.testing.assert_allclose(np.asarray(params["b"]),
                               np.ones(3, np.float32) - 3.0)


# ---------------------------------------------------------- error feedback

def _make_worker(wire_dtype: str, topk_density: float = 0.25):
    from parameter_server_distributed_tpu.config import WorkerConfig
    from parameter_server_distributed_tpu.worker.worker import Worker

    worker = Worker(WorkerConfig(wire_dtype=wire_dtype,
                                 topk_density=topk_density),
                    trainer=None, batches=iter(()), start_heartbeat=False)
    worker._peer_packed_ok = True  # packed support proven, for the test
    return worker


@pytest.mark.parametrize("wire", ["int8", "topk"])
def test_lossy_with_error_feedback_tracks_f32_closer(numpy_only, wire):
    """The convergence property the residual exists for: over a run of
    lossy pushes, carrying the quantization error forward keeps the
    parameter trajectory strictly closer to the exact-f32 trajectory than
    dropping it (PSDT_ERROR_FEEDBACK=0)."""
    rng = np.random.default_rng(13)
    shapes = {"w": (64, 16), "b": (32,)}
    init = _grads(rng, shapes)
    steps = [_grads(rng, shapes) for _ in range(20)]
    wire_id = m.WIRE_DTYPE_NAMES[wire]

    worker = _make_worker(wire)

    def run(mode: str) -> dict:
        core = ParameterServerCore(total_workers=1, optimizer=SGD(0.05))
        core.initialize_parameters(init)
        worker._ef_residual = {}
        for it, grads in enumerate(steps, start=1):
            if mode == "f32":
                seen = grads
            elif mode == "ef":
                tensors, residual = worker._compress_with_feedback(
                    grads, wire_id)
                worker._ef_residual = residual
                seen = {t.name: t.to_array() for t in tensors}
            else:  # lossy, no feedback
                tensors = to_wire(grads, wire_id, topk_density=0.25)
                seen = {t.name: t.to_array() for t in tensors}
            core.receive_gradients(0, it, seen)
        return core.get_parameters()

    exact = run("f32")
    with_ef = run("ef")
    without = run("lossy")

    def dist(a):
        return sum(float(np.linalg.norm(a[k] - exact[k])) for k in shapes)

    assert dist(with_ef) < dist(without), (
        f"{wire}: EF {dist(with_ef):.4f} !< no-EF {dist(without):.4f}")


def test_error_feedback_env_gate(monkeypatch):
    """PSDT_ERROR_FEEDBACK=0 disables the residual carry on both push
    paths (the A/B knob); the default carries it."""
    worker = _make_worker("int8")
    grads = {"w": np.linspace(-1, 1, 64, dtype=np.float32)}

    tensors_fn, box = worker._wire_tensors(grads)
    list(tensors_fn())
    assert box is not None and "w" in box  # default: residual carried

    monkeypatch.setenv("PSDT_ERROR_FEEDBACK", "0")
    tensors_fn, box = worker._wire_tensors(grads)
    tensors = list(tensors_fn())
    assert box is None
    # and the payload is the PLAIN compression of g (no residual added)
    plain = to_wire(grads, m.WIRE_INT8)
    np.testing.assert_array_equal(tensors[0].to_array(),
                                  plain[0].to_array())


# -------------------------------------------------------- encode + metrics

def test_striped_encode_is_byte_identical(monkeypatch):
    rng = np.random.default_rng(17)
    store = {f"t{i}": rng.standard_normal((256, 33)).astype(np.float32)
             for i in range(7)}
    budget = 64 << 10  # several tensors per group, several groups

    def bodies(stripes: str) -> list[bytes]:
        monkeypatch.setenv(st.ENV_STRIPES, stripes)
        tensors = to_wire(store, wire_dtype=m.WIRE_BF16)
        return encode_parameter_record_groups(
            list(split_tensors(tensors, budget)))

    serial = bodies("1")
    striped = bodies("4")
    assert len(serial) > 1
    assert serial == striped


def test_striped_apply_metrics_and_rollup(numpy_only):
    """The striped close must publish ps.apply.stripe_ms observations and
    the ps.apply.parallelism gauge, and the pst-status rollup must carry
    them."""
    from parameter_server_distributed_tpu.obs.export import (
        render_rollup, worker_rollup)

    rng = np.random.default_rng(23)
    init = _grads(rng, SHAPES)
    core = ParameterServerCore(total_workers=1, optimizer=Adam(0.01),
                               stripes=2)
    core.initialize_parameters(init)
    before = obs_stats.REGISTRY.snapshot()["histograms"].get(
        "ps.apply.stripe_ms", {"count": 0})["count"]
    core.receive_gradients(0, 1, _grads(rng, SHAPES))
    snap = obs_stats.REGISTRY.snapshot()
    after = snap["histograms"]["ps.apply.stripe_ms"]["count"]
    assert after >= before + 2  # one observation per stripe
    assert snap["gauges"]["ps.apply.parallelism"] > 0
    rollup = worker_rollup(snap)
    assert "apply_stripe_ms" in rollup["ps"]
    assert rollup["ps"]["apply_parallelism"] > 0
    text = render_rollup({"per_worker": {0: rollup}, "cluster": {}})
    assert "apply stripes" in text
