"""Pure units for models/prefix_tree.py (ISSUE 20) — the radix index
behind the serving prefix cache and the fleet's prefix-aware routing.
Deliberately jax-free: rows are plain RowRef payloads, so these tests
pin the tree's invariants (split inheritance, refcounted byte
accounting, LRU + path compression, fingerprint chaining) without
touching the model stack."""

import os

import pytest

from parameter_server_distributed_tpu.models.prefix_tree import (
    PrefixTree, RowRef, block_hashes, fp_block, overlap_blocks, pack_fp,
    unpack_fp)


def ref(nbytes=100):
    return RowRef(row=object(), nbytes=nbytes)


def test_lookup_matches_partially_into_edge():
    t = PrefixTree(10**9)
    t.insert((1, 2, 3, 4, 5), last="L", handle=ref())
    node, matched, partial = t.lookup((1, 2, 3, 9))
    assert matched == 3 and partial
    # the partially-entered child's handle covers the matched prefix
    assert node.handle is not None and node.depth == 5
    node, matched, partial = t.lookup((7, 7))
    assert matched == 0 and not partial and node is t.root


def test_split_inherits_handle_and_counts_bytes_once():
    t = PrefixTree(10**9)
    r1 = ref(100)
    t.insert((1, 2, 3, 4, 5), last="a", handle=r1)
    assert t.bytes == 100 and t.nodes == 1
    r2 = ref(150)
    t.insert((1, 2, 3, 9, 9), last="b", handle=r2)
    # split at depth 3: interior node SHARES r1 (no copy, no recharge)
    assert t.splits == 1 and t.nodes == 3
    assert t.bytes == 250  # 100 once (refs=2) + 150
    assert r1.refs == 2 and r2.refs == 1
    mid, matched, partial = t.lookup((1, 2, 3))
    assert matched == 3 and not partial
    assert mid.handle is r1 and mid.last is None  # interior, no logits


def test_readmission_fills_last_and_draft_handle():
    t = PrefixTree(10**9)
    t.insert((1, 2, 3, 4), last="a", handle=ref())
    t.insert((1, 2), last="b", handle=ref(30))  # splits; mid gets last
    mid, matched, partial = t.lookup((1, 2))
    assert not partial and mid.last == "b"
    # the mid node inherited the descendant's handle, so the offered
    # 30-byte handle is NOT taken (and not charged)
    assert t.bytes == 100
    d = ref(40)
    t.insert((1, 2), last="b2", handle=ref(5), dhandle=d)
    assert mid.dhandle is d and t.bytes == 140  # draft row attaches


def test_eviction_is_min_tick_leaf_with_path_compression():
    t = PrefixTree(10**9)
    t.insert((1, 2, 3, 4), last="a", handle=ref())
    t.insert((1, 2, 8, 8), last="b", handle=ref())  # split at (1,2)
    t.insert((5, 5), last="c", handle=ref())
    hit, _, _ = t.lookup((1, 2, 3, 4))
    t.touch(hit)                       # a (and its path) is hot
    hit, _, _ = t.lookup((5, 5))
    t.touch(hit)                       # c is hot; b is the LRU victim
    t.budget_bytes = t.bytes - 1       # force one eviction round
    assert t.evict_over_budget() == 1
    node, matched, _ = t.lookup((1, 2, 8, 8))
    assert matched == 2                # b is gone
    # the split-created (1,2) interior had one child left and no last:
    # path compression merged it away
    node, matched, partial = t.lookup((1, 2, 3, 4))
    assert matched == 4 and not partial and node.last == "a"
    assert node.parent is t.root and node.edge == (1, 2, 3, 4)


def test_ancestor_touch_protects_shared_prefix():
    t = PrefixTree(10**9)
    t.insert((1, 2), last="shared", handle=ref())
    t.insert((9, 9), last="cold", handle=ref())
    deep = t.insert((1, 2, 3, 4), last="deep", handle=ref())
    t.touch(deep)  # touching the descendant refreshes the ancestors
    shared, _, _ = t.lookup((1, 2))
    cold, _, _ = t.lookup((9, 9))
    assert shared.tick > cold.tick
    t.budget_bytes = t.bytes - 1
    t.evict_over_budget()
    _, matched, _ = t.lookup((9, 9))
    assert matched == 0                 # the cold entry was the victim
    node, matched, _ = t.lookup((1, 2))
    assert matched == 2 and node.last == "shared"


def test_evict_over_budget_enforces_byte_bound():
    t = PrefixTree(250)
    for i in range(5):
        t.insert((i, i + 1, i + 2), last=i, handle=ref(100))
    assert t.evict_over_budget() == 3
    assert t.bytes <= 250 and t.nodes == 2 and t.evictions == 3
    # the two survivors are the two most recently admitted
    assert {n.last for n in t._walk()} == {3, 4}


def test_refcounts_drop_bytes_only_at_zero():
    t = PrefixTree(10**9)
    r = ref(100)
    t.insert((1, 2, 3, 4), last="a", handle=r)
    t.insert((1, 2, 7, 7), last="b", handle=ref(60))  # mid shares r
    assert r.refs == 2 and t.bytes == 160
    t.insert((1, 2), last="mid", handle=ref(5))  # complete-prompt mid
    # mid already inherited r, so the 5-byte handle is declined
    assert t.bytes == 160
    # evict the deep leaf: r drops to one ref (the mid node), its 100
    # bytes stay charged — and mid survives (last set, no compression)
    leaf, _, _ = t.lookup((1, 2, 3, 4))
    t._remove_leaf(leaf)
    assert r.refs == 1 and t.bytes == 160
    node, matched, partial = t.lookup((1, 2))
    assert matched == 2 and not partial and node.last == "mid"


def test_compression_sheds_inherited_handle():
    t = PrefixTree(10**9)
    r = ref(100)
    t.insert((1, 2, 3, 4), last="a", handle=r)
    t.insert((1, 2, 7, 7), last="b", handle=ref(60))
    # removing the leaf that brought r leaves the split node with one
    # child and no complete-prompt payload: it merges away and releases
    # its inherited reference — r hits zero refs and is uncharged
    leaf, _, _ = t.lookup((1, 2, 3, 4))
    t._remove_leaf(leaf)
    assert r.refs == 0 and t.bytes == 60
    node, matched, partial = t.lookup((1, 2, 7, 7))
    assert matched == 4 and not partial and node.edge == (1, 2, 7, 7)


def test_clear_resets_everything():
    t = PrefixTree(10**9)
    t.insert((1, 2, 3), last="a", handle=ref())
    assert t.fingerprint == b"" or t.nodes  # fp may be empty (short path)
    t.insert(tuple(range(40)), last="b", handle=ref())
    assert t.fingerprint != b""
    t.clear()
    assert t.nodes == 0 and t.bytes == 0 and t.fingerprint == b""
    assert not t.root.children


def test_fingerprint_matches_router_block_hashes(monkeypatch):
    monkeypatch.setenv("PSDT_PREFIX_FP_BLOCK", "4")
    t = PrefixTree(10**9)
    prompt = tuple(range(10))
    t.insert(prompt, last="a", handle=ref())
    fp = unpack_fp(t.fingerprint)
    hashes = block_hashes(prompt)
    assert len(hashes) == 2            # boundaries at 4 and 8 of 10
    assert overlap_blocks(hashes, fp) == 2
    # a prompt diverging inside the first block shares nothing
    other = (99,) + prompt[1:]
    assert overlap_blocks(block_hashes(other), fp) == 0
    # consecutive-from-start: a hole ends the reusable prefix
    assert overlap_blocks([hashes[0], 0xDEAD, hashes[1]], fp) == 1


def test_fingerprint_cap_keeps_shallow_blocks(monkeypatch):
    monkeypatch.setenv("PSDT_PREFIX_FP_BLOCK", "2")
    monkeypatch.setenv("PSDT_PREFIX_FP_MAX", "3")
    t = PrefixTree(10**9)
    t.insert(tuple(range(20)), last="a", handle=ref())
    fp = unpack_fp(t.fingerprint)
    assert len(fp) == 3
    # the SHALLOW boundaries survive the cap (BFS): blocks 1..3, not the
    # deep tail — exactly the shared-system-prompt blocks routing needs
    assert overlap_blocks(block_hashes(tuple(range(20))), fp) == 3


def test_pack_unpack_roundtrip_and_truncation():
    hashes = [0, 1, 0xFFFFFFFF, 12345]
    blob = pack_fp(hashes)
    assert len(blob) == 16
    assert unpack_fp(blob) == frozenset(hashes)
    # a truncated tail from a foreign writer is ignored, not misparsed
    assert unpack_fp(blob[:-2]) == frozenset(hashes[:3])
    assert unpack_fp(b"") == frozenset()


def test_fp_block_env_default():
    assert "PSDT_PREFIX_FP_BLOCK" not in os.environ or True
    assert fp_block() >= 1
