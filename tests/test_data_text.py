"""Raw-text -> token-shard pipeline (data/text.py)."""

import numpy as np
import pytest

from parameter_server_distributed_tpu.data.text import (ByteTokenizer,
                                                        encode_file,
                                                        text_stream)


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    text = "hello, TPU world! éè€"
    ids = tok.encode(text)
    assert all(0 <= i < 256 for i in ids)
    assert tok.decode(ids) == text
    assert tok.vocab_size == 258


def test_encode_file_and_stream(tmp_path):
    text = "the quick brown fox jumps over the lazy dog\n" * 50
    src = tmp_path / "corpus.txt"
    src.write_text(text)
    shard = tmp_path / "corpus.bin"
    n = encode_file(str(src), str(shard), chunk_bytes=64)
    assert n == len(text.encode()) + 2  # BOS + EOS
    raw = np.fromfile(shard, dtype="<u2")
    assert raw[0] == ByteTokenizer.BOS and raw[-1] == ByteTokenizer.EOS
    assert bytes(raw[1:-1].astype(np.uint8)).decode() == text

    batches = text_stream(str(src), batch_size=4, seq_len=16, seed=0)
    batch = next(batches)
    assert batch.shape == (4, 16) and batch.dtype == np.int32
    assert batch.max() < 258


def test_text_stream_caches_shard(tmp_path):
    src = tmp_path / "c.txt"
    src.write_text("abcdefgh" * 100)
    it1 = text_stream(str(src), 2, 8)
    next(it1)
    shards = [p for p in tmp_path.iterdir() if p.suffix == ".bin"]
    assert len(shards) == 1
    mtime = shards[0].stat().st_mtime_ns
    it2 = text_stream(str(src), 2, 8)  # reuses the cached shard
    next(it2)
    assert shards[0].stat().st_mtime_ns == mtime


def test_registry_trains_lm_from_txt(tmp_path):
    from parameter_server_distributed_tpu.models.registry import (
        get_model_and_batches)

    src = tmp_path / "c.txt"
    src.write_text("to be or not to be, that is the question. " * 40)
    model, batches = get_model_and_batches("small_lm", 4,
                                           data_path=str(src))
    batch = next(batches)
    assert batch.shape == (4, model.config.max_seq)
    # byte ids fit the small_lm vocab (1024 >= 258)
    assert 0 <= batch.min() and batch.max() < 258


def test_registry_rejects_txt_for_tiny_vocab(tmp_path, monkeypatch):
    """The registry's .txt path errors for models whose vocab cannot
    cover the byte tokenizer's 258 ids."""
    import jax.numpy as jnp

    import parameter_server_distributed_tpu.models.registry as reg
    from parameter_server_distributed_tpu.models.transformer import small_lm

    monkeypatch.setitem(
        reg.REGISTRY, "tiny_vocab_lm",
        (lambda: small_lm(vocab=96, seq=16, dtype=jnp.float32),
         reg._lm_batches, "tokens"))
    src = tmp_path / "c.txt"
    src.write_text("hello")
    with pytest.raises(ValueError, match="byte tokenizer"):
        reg.get_model_and_batches("tiny_vocab_lm", 2, data_path=str(src))


def test_encode_chunks_match_whole_text(tmp_path):
    """Whitespace-cut chunking must produce identical shards regardless of
    chunk size (the subword-tokenizer safety contract)."""
    text = ("supercalifragilistic words of many different lengths "
            "spread across lines\nand paragraphs " * 30)
    src = tmp_path / "c.txt"
    src.write_text(text)
    encode_file(str(src), str(tmp_path / "whole.bin"),
                chunk_bytes=1 << 24)
    encode_file(str(src), str(tmp_path / "tiny.bin"), chunk_bytes=17)
    whole = np.fromfile(tmp_path / "whole.bin", dtype="<u2")
    tiny = np.fromfile(tmp_path / "tiny.bin", dtype="<u2")
    np.testing.assert_array_equal(whole, tiny)


def test_failed_encode_leaves_no_shard(tmp_path):
    """A tokenizer error mid-encode must not leave a partial shard that a
    later call would treat as a valid cache."""
    class BrokenTokenizer(ByteTokenizer):
        def encode(self, text):
            return [999999]  # out of vocab -> ValueError mid-stream

    src = tmp_path / "c.txt"
    src.write_text("some text")
    shard = tmp_path / "c.bin"
    with pytest.raises(ValueError, match="vocab_size"):
        encode_file(str(src), str(shard), BrokenTokenizer())
    assert not shard.exists()
    assert not list(tmp_path.glob("*.tmp.*"))
