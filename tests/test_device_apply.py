"""Accelerator-resident sharded apply (ISSUE 11, core/device_apply.py +
async_sgd ShardedDeviceOptimizer): the f32 bit-exactness oracle against
the numpy path across optimizers x stripe counts x fold residences,
dequantize-on-device byte-compat with the codec oracle (and the native
C++ kernels when buildable), checkpoint round-trips of device slot state
across restore stripe counts and across the host/device optimizer
families, the make_optimizer downgrade matrix, the device_fold gate, and
a lockcheck-marked concurrent push/close/serve hammer."""

import os
import threading

import numpy as np
import pytest

from parameter_server_distributed_tpu import native
from parameter_server_distributed_tpu.async_sgd.device_optimizer import (
    ShardedDeviceOptimizer)
from parameter_server_distributed_tpu.checkpoint.manager import (
    CheckpointManager)
from parameter_server_distributed_tpu.core import device_apply
from parameter_server_distributed_tpu.core.optimizer import (
    SGD, Adam, AdamW, Lion, Momentum, make_optimizer)
from parameter_server_distributed_tpu.core.ps_core import ParameterServerCore
from parameter_server_distributed_tpu.obs import stats as obs_stats
from parameter_server_distributed_tpu.rpc import codec as codec_mod
from parameter_server_distributed_tpu.rpc import messages as m
from parameter_server_distributed_tpu.rpc.data_plane import decode_gradients
from parameter_server_distributed_tpu.core.tensor import to_wire


def _jnp():
    import jax.numpy as jnp

    return jnp


@pytest.fixture
def numpy_oracle():
    """Pin the pure-numpy host path (the bit-exactness oracle): the
    native fused adam differs from numpy in the v-slot rounding, so the
    oracle comparisons must not ride the C++ kernels."""
    native.set_enabled(False)
    try:
        yield
    finally:
        native.set_enabled(
            os.environ.get("PSDT_NATIVE", "1").lower()
            not in ("0", "false"))


def _shapes():
    # odd sizes + a matrix (exercises the adamw/lion decay mask lanes)
    return {"emb/w": (129, 33), "l0/w": (64, 65), "l0/b": (65,),
            "head/w": (33, 17), "odd": (513,)}


def _stores_equal(a, b) -> bool:
    if set(a) != set(b):
        return False
    return all(np.asarray(a[k], np.float32).tobytes()
               == np.asarray(b[k], np.float32).tobytes() for k in a)


# --------------------------------------------------------------- oracle
@pytest.mark.parametrize("rule", ShardedDeviceOptimizer.RULES)
def test_optimizer_oracle_bit_identical(rule, numpy_oracle, rng):
    """Raw apply_shard: device == numpy bit for bit over several steps,
    including pass-through names (a shard with no gradient for them)."""
    shapes = _shapes()
    host = make_optimizer(rule, 0.01)
    dev = ShardedDeviceOptimizer(rule, 0.01)
    params_h = {k: rng.standard_normal(s).astype(np.float32)
                for k, s in shapes.items()}
    params_d = {k: v.copy() for k, v in params_h.items()}
    for step in range(5):
        grads = {k: rng.standard_normal(s).astype(np.float32)
                 for k, s in shapes.items()}
        if step == 2:  # partial shard: 'odd' passes through untouched
            grads.pop("odd")
        host.tick()
        dev.tick()
        params_h = host.apply_shard(
            params_h, {k: g.copy() for k, g in grads.items()})
        params_d = dev.apply_shard(
            params_d, {k: g.copy() for k, g in grads.items()})
        assert _stores_equal(params_h, params_d), (rule, step)


@pytest.mark.parametrize("stripes", [1, 2, 4])
@pytest.mark.parametrize("rule", ["sgd", "momentum", "adam"])
@pytest.mark.parametrize("device_grads", [False, True])
def test_core_close_oracle_across_stripes(rule, stripes, device_grads,
                                          numpy_oracle, each_arena, rng):
    """Full barrier closes through ParameterServerCore: the device
    optimizer's store is byte-identical to the numpy optimizer's at
    every stripe count, with folds arriving as numpy arrays AND as
    device buffers (the decode-on-device residence) — and across
    PSDT_ARENA=0/1 (the flat mega-array layout must reproduce the same
    bytes; ISSUE 15)."""
    jnp = _jnp()
    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    grads_by_iter = [
        {k: rng.standard_normal(s).astype(np.float32)
         for k, s in shapes.items()} for _ in range(3)]

    def run(optimizer, device: bool):
        core = ParameterServerCore(total_workers=2, stripes=stripes,
                                   optimizer=optimizer)
        core.initialize_parameters(params)
        for it, grads in enumerate(grads_by_iter, start=1):
            for wid in range(2):
                payload = ({k: jnp.asarray(g) for k, g in grads.items()}
                           if device else
                           {k: g.copy() for k, g in grads.items()})
                r = core.receive_gradients(wid, it, payload)
            assert r.aggregation_complete, r.message
        store = core.get_parameters()
        return {k: np.asarray(v, np.float32) for k, v in store.items()}

    host_store = run(make_optimizer(rule, 0.02), device=False)
    dev_store = run(ShardedDeviceOptimizer(rule, 0.02),
                    device=device_grads)
    assert _stores_equal(host_store, dev_store)


# -------------------------------------------------------------- dequant
@pytest.mark.parametrize("wire", ["raw", "bf16", "int8", "topk"])
def test_device_unpack_matches_codec_oracle(wire, each_codec, rng):
    """device_unpack == Codec.unpack byte for byte, for every packed
    wire dtype, against both codec backends (the ``native`` leg proves
    byte-compat with psdt_native.cpp::psdt_dequant_int8 and friends)."""
    wire_dtype = codec_mod.WIRE_DTYPE_NAMES[wire]
    flat = rng.standard_normal(1023).astype(np.float32)
    size = flat.size
    k = codec_mod.topk_k(size, m.TOPK_DEFAULT_DENSITY)
    raw = bytearray(codec_mod.payload_nbytes(wire_dtype, size, k))
    codec_mod.active_codec().pack_into(wire_dtype, flat, raw, k=k)
    oracle = codec_mod.PythonCodec().unpack(wire_dtype, bytes(raw), size)
    got = np.asarray(device_apply.device_unpack(wire_dtype, bytes(raw),
                                                size))
    assert got.dtype == np.float32
    assert got.tobytes() == np.asarray(oracle, np.float32).tobytes()


@pytest.mark.parametrize("wire", ["raw", "bf16", "int8", "topk"])
def test_decode_gradients_device_matches_host(wire, rng):
    """rpc/data_plane.decode_gradients(device=True) lands jax buffers
    bit-identical to the host decode, for every packed wire dtype."""
    store = {"a": rng.standard_normal((31, 7)).astype(np.float32),
             "b": rng.standard_normal(257).astype(np.float32)}
    wire_dtype = codec_mod.WIRE_DTYPE_NAMES[wire]
    host = decode_gradients(to_wire(store, wire_dtype), device=False)
    dev = decode_gradients(to_wire(store, wire_dtype), device=True)
    for name in host:
        assert device_apply.is_device_array(dev[name])
        assert (np.asarray(dev[name], np.float32).tobytes()
                == np.asarray(host[name], np.float32).tobytes())
        assert dev[name].shape == host[name].shape


# ----------------------------------------------------------- checkpoint
@pytest.mark.parametrize("save_stripes,restore_stripes", [(1, 4), (2, 1),
                                                          (4, 2)])
def test_checkpoint_roundtrip_across_stripe_counts(save_stripes,
                                                   restore_stripes,
                                                   tmp_path, numpy_oracle,
                                                   rng):
    """Device slot state round-trips through the existing .ckpt layout
    bit-identically, across restore stripe counts AND across optimizer
    families (device state restores into the host adam and vice versa —
    the state_dict layouts are shared by construction)."""
    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    grads_by_iter = [
        {k: rng.standard_normal(s).astype(np.float32)
         for k, s in shapes.items()} for _ in range(4)]

    def closes(core, iters):
        for it in iters:
            for wid in range(2):
                r = core.receive_gradients(
                    wid, it, {k: g.copy()
                              for k, g in grads_by_iter[it - 1].items()})
            assert r.aggregation_complete

    core_a = ParameterServerCore(total_workers=2, stripes=save_stripes,
                                 optimizer=ShardedDeviceOptimizer(
                                     "adam", 0.02))
    core_a.initialize_parameters(params)
    closes(core_a, [1, 2])
    path = CheckpointManager(core_a, directory=str(tmp_path)).save(epoch=7)

    for opt in (ShardedDeviceOptimizer("adam", 0.02),
                make_optimizer("adam", 0.02)):
        core_b = ParameterServerCore(total_workers=2,
                                     stripes=restore_stripes,
                                     optimizer=opt)
        epoch, iteration = CheckpointManager(
            core_b, directory=str(tmp_path)).load(path)
        assert (epoch, iteration) == (7, 2)
        assert _stores_equal(core_b.get_parameters(),
                             core_a.get_parameters())
        # continue training on both: restored state must evolve
        # identically (slots round-tripped bit-exactly)
        closes(core_b, [3, 4])
        ref = ParameterServerCore(total_workers=2, stripes=save_stripes,
                                  optimizer=ShardedDeviceOptimizer(
                                      "adam", 0.02))
        ref.restore(7, 2, core_a.get_parameters(),
                    optimizer_state=core_a.optimizer_state())
        closes(ref, [3, 4])
        assert _stores_equal(core_b.get_parameters(),
                             ref.get_parameters())


def test_codec_dumps_device_store_bytes(tmp_path, rng):
    """checkpoint/codec.dumps of a device-resident store produces the
    exact bytes of the numpy store it mirrors (the async D2H prefetch is
    an overlap optimization, not a format change)."""
    from parameter_server_distributed_tpu.checkpoint import codec

    jnp = _jnp()
    store = {"w": rng.standard_normal((17, 5)).astype(np.float32),
             "b": rng.standard_normal(63).astype(np.float32)}
    dev_store = {k: jnp.asarray(v) for k, v in store.items()}
    assert (codec.dumps(3, 9, dev_store) == codec.dumps(3, 9, store))


# -------------------------------------------------- selection/downgrade
def test_make_optimizer_device_apply_resolves_sharded(monkeypatch):
    monkeypatch.setenv(device_apply.ENV_DEVICE_APPLY, "1")
    opt = make_optimizer("device_adam", 0.01)
    assert isinstance(opt, ShardedDeviceOptimizer)
    assert opt.rule == "adam"
    assert opt.supports_striping and opt.device_resident
    # flag off: the pre-existing whole-store optax family, unchanged
    monkeypatch.delenv(device_apply.ENV_DEVICE_APPLY)
    opt = make_optimizer("device_adam", 0.01)
    assert not isinstance(opt, ShardedDeviceOptimizer)
    assert not getattr(opt, "supports_striping", False)


def test_make_optimizer_sharded_names(monkeypatch):
    for rule, host_cls in (("sgd", SGD), ("momentum", Momentum),
                           ("adam", Adam), ("adamw", AdamW),
                           ("lion", Lion)):
        opt = make_optimizer(f"sharded_{rule}", 0.01)
        assert isinstance(opt, ShardedDeviceOptimizer), rule
        assert opt.rule == rule


def test_make_optimizer_degrades_to_matching_host(monkeypatch):
    """No accelerator => the MATCHING host optimizer (same rule) with a
    logged ps.apply.device_fallback counter, never a boot failure."""
    monkeypatch.setattr(device_apply, "_available", False)
    before = obs_stats.REGISTRY.snapshot().get("counters", {}).get(
        "ps.apply.device_fallback", 0)
    for name, host_cls in (("device_sgd", SGD), ("sharded_momentum",
                                                 Momentum),
                           ("device_adam", Adam), ("device_adamw", AdamW),
                           ("pallas_adamw_bf16", AdamW),
                           ("sharded_lion", Lion)):
        opt = make_optimizer(name, 0.01)
        assert type(opt) is host_cls, name
    after = obs_stats.REGISTRY.snapshot()["counters"][
        "ps.apply.device_fallback"]
    assert after >= before + 6
    # an unknown RULE still raises — a typo must never silently train
    # with a different update rule
    with pytest.raises(ValueError):
        make_optimizer("device_bogus", 0.01)
    monkeypatch.setattr(device_apply, "_available", True)
    with pytest.raises(ValueError):
        make_optimizer("sharded_adamw_bf16", 0.01)  # not a sharded rule


def test_make_optimizer_degrades_on_constructor_error(monkeypatch):
    monkeypatch.setattr(device_apply, "_available", True)

    def boom(*a, **kw):
        raise RuntimeError("backend init failed")

    import parameter_server_distributed_tpu.core.optimizer as opt_mod
    monkeypatch.setattr(opt_mod, "_make_accelerator_optimizer", boom)
    opt = make_optimizer("device_adam", 0.01)
    assert type(opt) is Adam


def test_make_optimizer_pallas_unimplemented_rule_raises(monkeypatch):
    """A pallas_<rule> the pallas family does not implement must RAISE
    on a healthy jax host (the pre-existing behavior), not degrade —
    degrading is only for accelerator UNAVAILABILITY."""
    monkeypatch.setattr(device_apply, "_available", True)
    with pytest.raises(ValueError):
        make_optimizer("pallas_adamw", 0.01)


def test_fold_add_rejects_wrong_shapes(rng):
    """fold_add reproduces np.add(acc, g, out=acc)'s shape contract:
    g may broadcast UP to the accumulator, but anything that would grow
    or change the result shape raises BEFORE the donation — jax's add
    would otherwise silently broadcast both ways."""
    jnp = _jnp()
    acc = device_apply.owned_copy(jnp.ones((2, 3), jnp.float32))
    with pytest.raises(ValueError):
        device_apply.fold_add(acc, jnp.ones((3, 1), jnp.float32))
    acc = device_apply.owned_copy(jnp.ones((3,), jnp.float32))
    with pytest.raises(ValueError):
        device_apply.fold_add(acc, jnp.ones((2, 3), jnp.float32))
    # broadcast-up matches numpy: acc (2,3) += g (3,)
    acc = device_apply.owned_copy(jnp.ones((2, 3), jnp.float32))
    out = device_apply.fold_add(acc, jnp.full((3,), 2.0, jnp.float32))
    ref = np.ones((2, 3), np.float32)
    np.add(ref, np.full((3,), 2.0, np.float32), out=ref)
    assert np.asarray(out).tobytes() == ref.tobytes()


@pytest.mark.parametrize("rule", ["momentum", "adam"])
def test_shape_change_raises_without_bricking_slots(rule, rng):
    """A per-name shape change (config skew / bad reshard) raises with
    the slot tables UNTOUCHED — the batched kernels donate slot buffers,
    so an unvalidated mismatch surfacing mid-chain would leave the
    optimizer holding deleted arrays and brick every later step."""
    opt = ShardedDeviceOptimizer(rule, 0.01)
    params = {"w": rng.standard_normal((4, 5)).astype(np.float32)}
    opt.tick()
    params = opt.apply_shard(
        params, {"w": rng.standard_normal((4, 5)).astype(np.float32)})
    opt.tick()
    with pytest.raises(ValueError):
        opt.apply_shard(
            params, {"w": rng.standard_normal((5,)).astype(np.float32)})
    # slots still alive: the original-shape step retries cleanly
    params = opt.apply_shard(
        params, {"w": rng.standard_normal((4, 5)).astype(np.float32)})
    assert np.asarray(params["w"]).shape == (4, 5)


# ----------------------------------------------------------- fold gate
def test_device_fold_gating(monkeypatch):
    core = ParameterServerCore(total_workers=1,
                               optimizer=ShardedDeviceOptimizer("sgd",
                                                                0.01))
    assert not core.device_fold  # env off => zero behavior change
    monkeypatch.setenv(device_apply.ENV_DEVICE_APPLY, "1")
    monkeypatch.setattr(device_apply, "_available", True)
    assert core.device_fold
    host = ParameterServerCore(total_workers=1,
                               optimizer=make_optimizer("sgd", 0.01))
    assert not host.device_fold  # host optimizer, no relay => host folds
    buffered = ParameterServerCore(total_workers=1,
                                   aggregation="buffered",
                                   optimizer=ShardedDeviceOptimizer(
                                       "sgd", 0.01))
    assert not buffered.device_fold  # buffered escape hatch stays host


def test_stripe_dispatch_policy(monkeypatch):
    small = {f"t{i}": np.zeros(1024, np.float32) for i in range(4)}
    assert device_apply.stripe_dispatch(small)
    big = {"t": np.zeros(8 << 20, np.float32)}  # 32MB mean
    assert not device_apply.stripe_dispatch(big)
    monkeypatch.setenv(device_apply.ENV_STRIPE_DISPATCH_MAX,
                       str(1 << 30))
    assert device_apply.stripe_dispatch(big)
    assert not device_apply.stripe_dispatch({})


def test_device_close_records_obs(numpy_oracle, rng):
    """A device-resident barrier close bumps ps.apply.device and the
    rollup renders the 'device apply' line."""
    from parameter_server_distributed_tpu.obs.export import (
        render_rollup, worker_rollup)

    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    core = ParameterServerCore(total_workers=1,
                               optimizer=ShardedDeviceOptimizer("sgd",
                                                                0.01))
    core.initialize_parameters(params)
    before = obs_stats.REGISTRY.snapshot().get("counters", {}).get(
        "ps.apply.device", 0)
    r = core.receive_gradients(0, 1, {
        k: rng.standard_normal(s).astype(np.float32)
        for k, s in shapes.items()})
    assert r.aggregation_complete
    device_apply.block_on_store(core.get_parameters())
    snap = obs_stats.REGISTRY.snapshot()
    assert snap["counters"]["ps.apply.device"] >= before + 1
    rolled = worker_rollup(snap)
    assert rolled["ps"]["device_apply"]["applies"] >= 1
    text = render_rollup({"cluster": {}, "per_worker": {0: rolled}})
    assert "device apply" in text


# ------------------------------------------------------- leaf relay
def test_leaf_relay_gets_host_sums_from_device_folds(monkeypatch, rng):
    """The PR-9 intra-host tier leftover: a leaf-aggregator core with
    device folds enabled accumulates member pushes as device reductions,
    and its barrier relay receives MATERIALIZED host numpy sums (the EF
    residual math and the native quantize kernels are numpy) that are
    bit-identical to a numpy-folded leaf's."""
    jnp = _jnp()
    monkeypatch.setenv(device_apply.ENV_DEVICE_APPLY, "1")
    monkeypatch.setattr(device_apply, "_available", True)
    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    grads = [{k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()} for _ in range(2)]
    seen: dict = {}

    def relay(iteration, sums, counts):
        seen["types"] = {k: type(v).__name__ for k, v in sums.items()}
        seen["sums"] = {k: np.asarray(v).copy() for k, v in sums.items()}
        seen["counts"] = dict(counts)
        return dict(params)  # "fresh params from upstream"

    core = ParameterServerCore(total_workers=2,
                               optimizer=make_optimizer("sgd", 0.01))
    core.set_barrier_relay(relay)
    assert core.device_fold  # relay + env on => device member folds
    core.initialize_parameters(params)
    for wid in range(2):
        r = core.receive_gradients(
            wid, 1, {k: jnp.asarray(g) for k, g in grads[wid].items()})
    assert r.aggregation_complete
    assert all(t == "ndarray" for t in seen["types"].values()), (
        seen["types"])
    assert all(c == 2 for c in seen["counts"].values())
    for k in shapes:  # device adds == numpy adds, bit for bit
        expect = (np.array(grads[0][k], np.float32)
                  + grads[1][k].astype(np.float32))
        assert seen["sums"][k].tobytes() == expect.tobytes()


def test_relay_raise_puts_back_writeable_host_sums(monkeypatch, rng):
    """A relay raise must put back WRITEABLE host sums: np.asarray of a
    jax CPU array is a read-only view, and a read-only accumulator would
    crash every replayed member fold (np.add out=acc), wedging the
    barrier permanently."""
    jnp = _jnp()
    monkeypatch.setenv(device_apply.ENV_DEVICE_APPLY, "1")
    monkeypatch.setattr(device_apply, "_available", True)
    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    grads = {k: rng.standard_normal(s).astype(np.float32)
             for k, s in shapes.items()}
    calls = {"n": 0}

    def flaky_relay(iteration, sums, counts):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient upstream failure")
        return dict(params)

    core = ParameterServerCore(total_workers=2,
                               optimizer=make_optimizer("sgd", 0.01))
    core.set_barrier_relay(flaky_relay)
    core.initialize_parameters(params)
    for wid in range(2):
        try:
            core.receive_gradients(
                wid, 1, {k: jnp.asarray(g) for k, g in grads.items()})
        except RuntimeError:
            pass
    state = core._iteration_states[1]
    for name, acc in state.accum.items():
        assert isinstance(acc, np.ndarray) and acc.flags.writeable, name
    _, complete, _, _ = core.check_sync_status(1)  # retry closes cleanly
    assert complete and calls["n"] == 2


def test_make_optimizer_degrades_when_device_family_unimportable(
        monkeypatch):
    """PSDT_DEVICE_APPLY=1 on a host where the device-optimizer module
    cannot import (no jax/optax) must degrade to the host optimizer at
    PS boot, not crash — the import happens inside the try."""
    import sys

    monkeypatch.setenv(device_apply.ENV_DEVICE_APPLY, "1")
    monkeypatch.setattr(device_apply, "_available", True)
    monkeypatch.setitem(
        sys.modules,
        "parameter_server_distributed_tpu.async_sgd.device_optimizer",
        None)  # import of the module now raises ImportError
    opt = make_optimizer("device_adam", 0.01)
    assert type(opt) is Adam


# ------------------------------------------------------------ put-back
def test_failed_device_apply_leaves_barrier_retryable(numpy_oracle, rng):
    """The put-back contract on the device path: an apply raise puts the
    accumulator back and the next push retries the close successfully
    (sums are never donated into the apply, so the retry reads live
    buffers)."""
    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}

    class Flaky(ShardedDeviceOptimizer):
        fail = True

        def apply_shard(self, p, g):
            if Flaky.fail:
                Flaky.fail = False
                raise RuntimeError("injected apply failure")
            return super().apply_shard(p, g)

    # stripes=1: the injected raise precedes ANY mutation (a striped
    # apply commits per-stripe slot updates before the close raises —
    # the pre-existing partial-failure semantic shared with the host
    # optimizers — which would make retry-vs-clean comparison moot)
    core = ParameterServerCore(total_workers=1, stripes=1,
                               optimizer=Flaky("momentum", 0.02))
    core.initialize_parameters(params)
    grads = {k: rng.standard_normal(s).astype(np.float32)
             for k, s in shapes.items()}
    with pytest.raises(RuntimeError):
        core.receive_gradients(0, 1, {k: g.copy()
                                      for k, g in grads.items()})
    # the sync poll re-fires the close off the put-back accumulator
    # (the duplicate push dedups — first push wins)
    _, complete, _, _ = core.check_sync_status(1)
    assert complete
    # reference without the failure: momentum's tick is a no-op, and
    # the raise fired before any slot mutation, so the retried close
    # must be bit-identical to a clean run
    ref = ParameterServerCore(total_workers=1, stripes=1,
                              optimizer=ShardedDeviceOptimizer(
                                  "momentum", 0.02))
    ref.initialize_parameters(params)
    ref.receive_gradients(0, 1, {k: g.copy() for k, g in grads.items()})
    assert _stores_equal(core.get_parameters(), ref.get_parameters())


# --------------------------------------------------------------- hammer
@pytest.mark.lockcheck
def test_concurrent_push_close_serve_hammer(numpy_oracle, rng):
    """Concurrent pushes (device buffers), barrier closes, checkpoint
    snapshots, and serves against the device path, under the runtime
    lock-order checker; final store must equal the single-threaded
    oracle."""
    jnp = _jnp()
    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    grads_by_iter = [
        {k: rng.standard_normal(s).astype(np.float32)
         for k, s in shapes.items()} for _ in range(5)]
    n_workers = 3
    core = ParameterServerCore(total_workers=n_workers, stripes=2,
                               optimizer=ShardedDeviceOptimizer("adam",
                                                                0.02))
    core.initialize_parameters(params)
    stop = threading.Event()
    errors: list = []

    def server_noise():
        while not stop.is_set():
            try:
                core.serve_parameters()
                core.get_parameters()
                core.optimizer_state()
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)
                return

    noise = threading.Thread(target=server_noise)
    noise.start()
    gate = threading.Barrier(n_workers)

    def worker(wid: int):
        try:
            for it, grads in enumerate(grads_by_iter, start=1):
                gate.wait(timeout=30)
                core.receive_gradients(
                    wid, it, {k: jnp.asarray(g)
                              for k, g in grads.items()})
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    stop.set()
    noise.join(timeout=10)
    assert not errors, errors

    ref = ParameterServerCore(total_workers=n_workers,
                              optimizer=ShardedDeviceOptimizer("adam",
                                                               0.02))
    ref.initialize_parameters(params)
    for it, grads in enumerate(grads_by_iter, start=1):
        for wid in range(n_workers):
            ref.receive_gradients(wid, it, {k: g.copy()
                                            for k, g in grads.items()})
    assert _stores_equal(core.get_parameters(), ref.get_parameters())
