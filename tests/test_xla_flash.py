"""XLA-native blockwise flash attention (ops/xla_flash.py): exact parity
with the dense einsum reference, values AND gradients, MHA and GQA."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_distributed_tpu.models.transformer import (
    causal_attention, select_attention)
from parameter_server_distributed_tpu.ops.xla_flash import (
    auto_block, make_xla_flash_attention, xla_flash_attention)


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.mark.parametrize("heads,kv_heads", [(4, 4), (8, 2), (4, 1)])
def test_values_match_dense(rng, heads, kv_heads):
    q = jnp.asarray(rng.standard_normal((2, 64, heads, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 64, kv_heads, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 64, kv_heads, 16)), jnp.float32)
    ref = causal_attention(q, k, v)
    got = xla_flash_attention(q, k, v, block_k=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_gradients_match_dense(rng):
    q = jnp.asarray(rng.standard_normal((1, 32, 8, 8)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 32, 2, 8)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 32, 2, 8)), jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_ref = jax.grad(loss(causal_attention), argnums=(0, 1, 2))(q, k, v)
    g_fla = jax.grad(loss(lambda q, k, v: xla_flash_attention(
        q, k, v, block_k=8)), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fla):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=3e-4, atol=3e-4)


def test_block_must_divide_seq(rng):
    q = jnp.zeros((1, 48, 2, 8), jnp.float32)
    with pytest.raises(ValueError, match="divide"):
        xla_flash_attention(q, q[:, :, :2], q[:, :, :2], block_k=32)
    assert auto_block(48, 32) == 24  # largest divisor <= 32
    assert auto_block(8192, 512) == 512


def test_select_attention_wires_xla_flash(rng):
    attend = select_attention("xla_flash", None)
    q = jnp.asarray(rng.standard_normal((1, 48, 4, 8)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, 48, 4, 8)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, 48, 4, 8)), jnp.float32)
    # 48 is not 512-divisible: auto_block picks a divisor, still exact
    np.testing.assert_allclose(np.asarray(attend(q, k, v)),
                               np.asarray(causal_attention(q, k, v)),
                               rtol=2e-5, atol=2e-5)


def test_model_loss_identical_under_xla_flash(rng):
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)

    config = TransformerConfig(vocab=64, d_model=32, n_heads=4,
                               n_kv_heads=2, n_layers=2, d_ff=64,
                               max_seq=32, dtype=jnp.float32)
    tokens = rng.integers(0, 64, (2, 32)).astype(np.int32)
    dense_model = Transformer(config)
    flash_model = Transformer(config,
                              attention_fn=select_attention("xla_flash",
                                                            None))
    params = dense_model.init_params(0)
    np.testing.assert_allclose(
        float(jax.jit(flash_model.loss)(params, tokens)),
        float(jax.jit(dense_model.loss)(params, tokens)), rtol=1e-5)
