"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip behavior (DP/TP/SP shardings, collectives) is tested on host CPU
with XLA's forced device count, mirroring how the reference exercised its
multi-node protocol with multi-process-on-localhost
(reference: scripts/test_local.sh).  Must run before any jax import.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Force CPU via jax.config: the session may register a TPU PJRT plugin at
# interpreter startup (sitecustomize) that overrides the JAX_PLATFORMS env
# var, so the env-var route is not reliable here.  config.update after
# import wins as long as no backend has been initialized yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
