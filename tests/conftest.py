"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip behavior (DP/TP/SP shardings, collectives) is tested on host CPU
with XLA's forced device count, mirroring how the reference exercised its
multi-node protocol with multi-process-on-localhost
(reference: scripts/test_local.sh).  Must run before any jax import.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Force CPU via jax.config: the session may register a TPU PJRT plugin at
# interpreter startup (sitecustomize) that overrides the JAX_PLATFORMS env
# var, so the env-var route is not reliable here.  config.update after
# import wins as long as no backend has been initialized yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(params=["python", "native"])
def each_codec(request):
    """Parametrize a data-plane test across the wire codec backends
    (PSDT_NATIVE=0/1 — rpc/codec.py): the ``python`` leg pins the
    pure-numpy oracle so the fallback path can never rot, the ``native``
    leg exercises the C++ kernels (skipped cleanly when no compiler can
    build them).  Yields the active backend name."""
    from parameter_server_distributed_tpu import native

    if request.param == "native":
        native.set_enabled(True)
        if native.lib() is None:
            pytest.skip("native lib unavailable (no g++)")
    else:
        native.set_enabled(False)
    try:
        yield request.param
    finally:
        # restore the process default (PSDT_NATIVE env, read at import)
        native.set_enabled(
            os.environ.get("PSDT_NATIVE", "1").lower()
            not in ("0", "false"))


@pytest.fixture(params=["0", "1"])
def each_arena(request, monkeypatch):
    """Parametrize a device-apply test across the flat-arena layout
    (PSDT_ARENA=0/1 — core/arena.py, ISSUE 15): the ``0`` leg pins the
    PR 11 per-tensor device path, the ``1`` leg runs the same closes
    through the per-stripe mega-array layout (skipped cleanly when no
    jax backend owns a device).  Yields the flag value; cores read it
    at construction, so construct the core inside the test body."""
    if request.param == "1":
        from parameter_server_distributed_tpu.core import device_apply

        if not device_apply.available():
            pytest.skip("no jax backend/device for the arena leg")
    monkeypatch.setenv("PSDT_ARENA", request.param)
    yield request.param


@pytest.fixture(autouse=True)
def _lockcheck_env(request, monkeypatch):
    """Opt-in runtime lock-discipline checking: tests marked
    ``@pytest.mark.lockcheck`` run with PSDT_LOCK_CHECK=1, so the known
    locks (core/ps_core.py, checkpoint/manager.py, server/ps_service.py,
    obs/export.py) are constructed as order-asserting proxies and any
    lock-order violation raises LockOrderError instead of deadlocking
    (analysis/lock_order.py, docs/analysis.md).  The env var is read at
    lock construction, which happens inside the test body — after this
    fixture has set it."""
    if request.node.get_closest_marker("lockcheck"):
        monkeypatch.setenv("PSDT_LOCK_CHECK", "1")
