"""Metrics utilities + status CLI + distributed helpers."""

import json
import os

import numpy as np
import pytest

from parameter_server_distributed_tpu.utils.metrics import (MetricsLogger,
                                                            StepTimer,
                                                            samples_per_sec)


def test_step_timer_percentiles():
    timer = StepTimer()
    for d in [0.01, 0.02, 0.03, 0.04, 0.10]:
        timer.record(d)
    s = timer.summary()
    assert s["count"] == 5
    assert s["p50_s"] == 0.03
    assert s["p95_s"] == 0.10
    assert abs(s["mean_s"] - 0.04) < 1e-9


def test_step_timer_context_manager():
    timer = StepTimer()
    with timer:
        pass
    assert timer.count == 1 and timer.summary()["last_s"] >= 0


def test_metrics_logger_jsonl(tmp_path):
    path = str(tmp_path / "metrics" / "train.jsonl")
    logger = MetricsLogger(path)
    logger.log(step=1, loss=2.5)
    logger.log(step=2, loss=2.1, samples_per_sec=100.0)
    assert logger.latest("loss") == 2.1
    assert logger.latest("samples_per_sec") == 100.0
    lines = [json.loads(l) for l in open(path)]
    assert [l["step"] for l in lines] == [1, 2]
    assert all("t" in l for l in lines)


def test_samples_per_sec():
    assert samples_per_sec(128, 0.5) == 256.0
    assert samples_per_sec(128, 0.5, num_chips=4) == 64.0


def test_status_cli_against_live_cluster(capsys):
    from parameter_server_distributed_tpu.cli.status_main import main
    from parameter_server_distributed_tpu.config import (CoordinatorConfig,
                                                         ParameterServerConfig)
    from parameter_server_distributed_tpu.server.coordinator_service import Coordinator
    from parameter_server_distributed_tpu.server.ps_service import ParameterServer

    ps = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=2,
        autosave_period_s=600.0))
    ps_port = ps.start()
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0, ps_address="127.0.0.1",
        ps_port=ps_port, reap_period_s=600.0))
    coord_port = coordinator.start()
    coordinator.core.register_worker(3, "10.0.0.9", 50063, "hostX")
    try:
        assert main([f"127.0.0.1:{coord_port}", "--iteration=5"]) == 0
        out = capsys.readouterr().out
        assert "registered workers: 1" in out
        assert "worker 3: 10.0.0.9:50063 (hostX)" in out
        assert "ready=False received=0/2" in out
    finally:
        coordinator.stop()
        ps.stop()


def test_hybrid_mesh_config_single_host():
    from parameter_server_distributed_tpu.parallel.distributed import (
        hybrid_mesh_config, initialize_multihost)
    assert initialize_multihost() is False  # single-process no-op
    config = hybrid_mesh_config(tensor=2)
    assert config.num_devices == 8 and config.tensor == 2
    with pytest.raises(ValueError):
        hybrid_mesh_config(tensor=3)


def test_status_main_shows_shards(tmp_path, capsys):
    """pst-status lists shard addresses and per-shard sync state when the
    coordinator reports a sharded store."""
    from parameter_server_distributed_tpu.cli.status_main import main
    from parameter_server_distributed_tpu.config import (CoordinatorConfig,
                                                         ParameterServerConfig)
    from parameter_server_distributed_tpu.server.coordinator_service import (
        Coordinator)
    from parameter_server_distributed_tpu.server.ps_service import (
        ParameterServer)

    shards = []
    ports = []
    for i in range(2):
        ps = ParameterServer(ParameterServerConfig(
            bind_address="127.0.0.1", port=0, total_workers=1,
            checkpoint_dir=str(tmp_path / f"s{i}"), autosave_period_s=600.0))
        shards.append(ps)
        ports.append(ps.start())
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0, ps_address="127.0.0.1",
        ps_port=ports[0], ps_shards=(f"127.0.0.1:{ports[1]}",),
        reap_period_s=600.0))
    coord_port = coordinator.start()
    try:
        rc = main([f"127.0.0.1:{coord_port}"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "ps shards: 2" in out
        assert f"shard 1: 127.0.0.1:{ports[1]}" in out
        assert out.count("sync status") == 2  # one per shard
    finally:
        coordinator.stop()
        for ps in shards:
            ps.stop()
