"""Unit tests for the coordinator membership registry."""

from parameter_server_distributed_tpu.core.coordinator_core import CoordinatorCore
from parameter_server_distributed_tpu.rpc.messages import WorkerStatus


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def make():
    clock = FakeClock()
    return CoordinatorCore("10.0.0.2", 50051, time_fn=clock), clock


def test_register_upsert_and_count():
    c, _ = make()
    assert c.register_worker(0, "127.0.0.1", 50060, "h0") == 1
    assert c.register_worker(1, "127.0.0.1", 50061, "h1") == 2
    # re-register same id is an upsert, not a duplicate
    assert c.register_worker(0, "127.0.0.1", 50070, "h0b") == 2
    entries = {e.worker_id: e for e in c.list_workers()}
    assert entries[0].port == 50070 and entries[0].hostname == "h0b"


def test_heartbeat_updates_status_and_unknown_worker_fails():
    c, clock = make()
    c.register_worker(3, "a", 1, "h")
    assert c.update_heartbeat(3, WorkerStatus.TRAINING)
    assert c.list_workers()[0].status == WorkerStatus.TRAINING
    assert not c.update_heartbeat(99, WorkerStatus.IDLE)


def test_stale_eviction():
    c, clock = make()
    c.register_worker(0, "a", 1, "h0")
    c.register_worker(1, "a", 2, "h1")
    clock.t += 20
    c.update_heartbeat(1, WorkerStatus.TRAINING)  # keep worker 1 fresh
    clock.t += 15  # worker 0 now 35s stale, worker 1 15s
    evicted = c.remove_stale_workers(timeout_s=30)
    assert evicted == [0]
    assert c.live_worker_count() == 1


def test_ps_address_static_echo():
    c, _ = make()
    assert c.get_parameter_server_address() == ("10.0.0.2", 50051)


def test_live_count_feeds_elastic_barrier():
    from parameter_server_distributed_tpu.core.ps_core import ParameterServerCore
    import numpy as np
    c, clock = make()
    c.register_worker(0, "a", 1, "h0")
    c.register_worker(1, "a", 2, "h1")
    ps = ParameterServerCore(total_workers=99, live_workers_fn=c.live_worker_count)
    ps.initialize_parameters({"w": np.zeros(1, np.float32)})
    ps.receive_gradients(0, 1, {"w": np.ones(1, np.float32)})
    r = ps.receive_gradients(1, 1, {"w": np.ones(1, np.float32)})
    assert r.aggregation_complete and r.total_workers == 2
