"""utils/netsim.ThrottledRelay: injected latency/bandwidth are real and
gRPC traffic relays transparently (the substrate for the wire-encoding
network A/B — bench.py PSDT_BENCH_NET)."""
from __future__ import annotations

import socket
import threading
import time

import numpy as np
import pytest

from parameter_server_distributed_tpu.utils.netsim import ThrottledRelay


def _echo_server():
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)

    def loop():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            def pump(c=conn):
                while True:
                    try:
                        data = c.recv(65536)
                    except OSError:
                        return
                    if not data:
                        return
                    c.sendall(data)
            threading.Thread(target=pump, daemon=True).start()

    threading.Thread(target=loop, daemon=True).start()
    return srv, srv.getsockname()[1]


def test_relay_injects_round_trip_latency():
    srv, port = _echo_server()
    relay = ThrottledRelay(port, delay_ms=30.0)   # one-way 30 -> RTT ~60
    try:
        rport = relay.start()
        with socket.create_connection(("127.0.0.1", rport)) as conn:
            # warm the path, then measure echo RTTs
            conn.sendall(b"x")
            conn.recv(16)
            rtts = []
            for _ in range(3):
                t0 = time.perf_counter()
                conn.sendall(b"ping")
                assert conn.recv(16) == b"ping"
                rtts.append(time.perf_counter() - t0)
        rtt = min(rtts)
        assert rtt >= 0.055, f"RTT {rtt * 1e3:.1f}ms < injected 60ms"
        assert rtt < 0.5, f"RTT {rtt * 1e3:.1f}ms implausibly high"
    finally:
        relay.stop()
        srv.close()


def test_relay_caps_bandwidth_without_serializing_on_latency():
    """8 Mbit/s cap: 1 MB must take ~1 s; the 20 ms one-way delay must
    NOT multiply per chunk (a pipelined link adds latency once)."""
    srv, port = _echo_server()
    relay = ThrottledRelay(port, delay_ms=20.0, mbps=8.0)
    try:
        rport = relay.start()
        payload = np.random.default_rng(0).bytes(1_000_000)
        got = bytearray()
        with socket.create_connection(("127.0.0.1", rport)) as conn:
            t0 = time.perf_counter()

            def sender():
                conn.sendall(payload)

            th = threading.Thread(target=sender, daemon=True)
            th.start()
            while len(got) < len(payload):
                chunk = conn.recv(65536)
                assert chunk, "connection dropped mid-transfer"
                got.extend(chunk)
            dt = time.perf_counter() - t0
        assert bytes(got) == payload
        # 1 MB at 8 Mbit/s = 1.0 s per direction; the two directions
        # PIPELINE through the echo (like a real full-duplex link), so
        # total ~1 s — and if the 20 ms delay serialized per 64KB chunk
        # the 2 x 16 chunks would add >= 0.64 s on top
        assert dt >= 0.95, f"transfer {dt:.2f}s beat the 8 Mbit/s cap"
        assert dt < 1.8, f"transfer {dt:.2f}s: delay appears serialized"
    finally:
        relay.stop()
        srv.close()


@pytest.mark.slow
def test_pushpull_through_relay_roundtrips():
    """The PS gRPC data plane works unchanged through the relay — the
    exact path bench.py's PSDT_BENCH_NET mode exercises."""
    from parameter_server_distributed_tpu.config import (
        ParameterServerConfig)
    from parameter_server_distributed_tpu.core.tensor import to_wire
    from parameter_server_distributed_tpu.rpc import messages as m
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient
    from parameter_server_distributed_tpu.server.ps_service import (
        ParameterServer)

    ps = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=1,
        autosave_period_s=3600.0, checkpoint_dir="/tmp"))
    port = ps.start()
    relay = ThrottledRelay(port, delay_ms=5.0, mbps=200.0)
    try:
        rport = relay.start()
        rng = np.random.default_rng(0)
        params = {"w": rng.standard_normal((256, 64)).astype(np.float32)}
        ps.core.initialize_parameters(params)
        client = PSClient(f"127.0.0.1:{rport}")
        grads = to_wire({"w": np.ones((256, 64), np.float32)},
                        m.WIRE_BF16)
        t0 = time.perf_counter()
        client.push_gradients(m.GradientUpdate(worker_id=0, iteration=1,
                                               gradients=grads))
        resp = client.pull_parameters(m.PullRequest(
            worker_id=0, iteration=1, wire_dtype=m.WIRE_BF16))
        dt = time.perf_counter() - t0
        assert resp.parameters
        # two RPCs x RTT 10ms minimum through the relay
        assert dt >= 0.02
        client.close()
    finally:
        relay.stop()
        ps.stop()
