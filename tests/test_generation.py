"""KV-cached generation tests: the cached decode path must reproduce the
full-sequence forward exactly (the strongest possible cache-correctness
check), plus sampling behavior."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_distributed_tpu.models.generation import (
    generate, init_cache, prefill, sample_token, sample_token_rowwise)
from parameter_server_distributed_tpu.models.transformer import (
    Transformer, TransformerConfig)


def tiny_model():
    return Transformer(TransformerConfig(
        vocab=96, d_model=48, n_heads=4, n_layers=2, d_ff=96,
        max_seq=64, dtype=jnp.float32))


def greedy_by_full_forward(model, params, prompt, n):
    """Reference: re-run the whole sequence through apply() per token."""
    toks = prompt
    out = []
    for _ in range(n):
        logits = model.apply(params, toks)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out.append(nxt)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


def test_cached_greedy_matches_full_forward(rng):
    model = tiny_model()
    params = model.init_params(0)
    prompt = jnp.asarray(rng.integers(0, 96, (2, 8)), jnp.int32)
    expected = greedy_by_full_forward(model, params, prompt, 6)
    got = generate(model, params, prompt, 6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_prefill_logits_match_apply(rng):
    model = tiny_model()
    params = model.init_params(1)
    prompt = jnp.asarray(rng.integers(0, 96, (3, 10)), jnp.int32)
    full = model.apply(params, prompt)[:, -1]
    last, cache = prefill(model, params, prompt, max_len=16)
    np.testing.assert_allclose(np.asarray(last), np.asarray(full),
                               rtol=1e-5, atol=1e-6)
    assert int(cache.length) == 10 and cache.max_len == 16


def test_sampling_is_seeded_and_in_vocab(rng):
    model = tiny_model()
    params = model.init_params(2)
    prompt = jnp.asarray(rng.integers(0, 96, (2, 4)), jnp.int32)
    a = generate(model, params, prompt, 5, temperature=0.8, top_k=10, rng=7)
    b = generate(model, params, prompt, 5, temperature=0.8, top_k=10, rng=7)
    c = generate(model, params, prompt, 5, temperature=0.8, top_k=10, rng=8)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == (2, 5)
    assert np.asarray(a).min() >= 0 and np.asarray(a).max() < 96
    assert not np.array_equal(np.asarray(a), np.asarray(c))  # seed matters


def test_top_k_restricts_support():
    logits = jnp.asarray([[5.0, 4.0, -1.0, -2.0, -3.0]])
    picks = {int(sample_token(logits, jax.random.key(i), temperature=1.0,
                              top_k=2)[0]) for i in range(50)}
    assert picks <= {0, 1}
    assert int(sample_token(logits, jax.random.key(0))[0]) == 0  # greedy


def test_prompt_longer_than_cache_rejected(rng):
    model = tiny_model()
    params = model.init_params(0)
    prompt = jnp.asarray(rng.integers(0, 96, (1, 12)), jnp.int32)
    with pytest.raises(ValueError, match="exceeds cache"):
        prefill(model, params, prompt, max_len=8)


def test_init_cache_shapes():
    model = tiny_model()
    cache = init_cache(model, batch=3, max_len=32)
    assert cache.k.shape == (2, 3, 32, 4, 12)
    assert cache.v.shape == cache.k.shape
    assert int(cache.length) == 0


def test_repeated_generate_does_not_retrace(rng):
    from parameter_server_distributed_tpu.models import generation

    model = tiny_model()
    params = model.init_params(3)
    prompt = jnp.asarray(rng.integers(0, 96, (1, 4)), jnp.int32)
    generate(model, params, prompt, 3)
    run = generation._RUNNERS[
        (generation._model_key(model), 3, 0.0, 0, 0.0, "native")]
    traces_before = run._cache_size()
    out1 = generate(model, params, prompt, 3)
    out2 = generate(model, params, prompt, 3)
    assert run._cache_size() == traces_before  # same wrapper, no retrace
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_top_k_larger_than_vocab_is_no_truncation():
    logits = jnp.asarray([[1.0, 2.0, 3.0]])
    tok = sample_token(logits, jax.random.key(0), temperature=1.0, top_k=99)
    assert 0 <= int(tok[0]) < 3


def gqa_model(n_kv_heads):
    return Transformer(TransformerConfig(
        vocab=96, d_model=48, n_heads=4, n_kv_heads=n_kv_heads, n_layers=2,
        d_ff=96, max_seq=64, dtype=jnp.float32))


@pytest.mark.parametrize("n_kv", [1, 2])
def test_gqa_cached_greedy_matches_full_forward(rng, n_kv):
    """GQA decode (kv_heads-shaped cache, heads expanded at use) must
    reproduce the full-sequence forward token for token."""
    model = gqa_model(n_kv)
    params = model.init_params(0)
    prompt = jnp.asarray(rng.integers(0, 96, (2, 8)), jnp.int32)
    expected = greedy_by_full_forward(model, params, prompt, 6)
    got = generate(model, params, prompt, 6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_gqa_cache_is_smaller(rng):
    mha = init_cache(tiny_model(), batch=2, max_len=16)
    gqa = init_cache(gqa_model(1), batch=2, max_len=16)
    assert gqa.k.shape[3] == 1 and mha.k.shape[3] == 4
    assert gqa.k.size == mha.k.size // 4


def test_top_p_restricts_support():
    """probs ~ [.5, .3, .15, .05]: top_p=0.6 keeps exactly {0, 1} (tokens
    whose preceding cumulative mass < p); top_p>=1 truncates nothing."""
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    picks = {int(sample_token(logits, jax.random.key(i), temperature=1.0,
                              top_p=0.6)[0]) for i in range(60)}
    assert picks == {0, 1}
    picks_all = {int(sample_token(logits, jax.random.key(i),
                                  temperature=1.0, top_p=0.0)[0])
                 for i in range(120)}
    assert picks_all == {0, 1, 2, 3}
    # argmax token always survives even a tiny p
    assert int(sample_token(logits, jax.random.key(0), temperature=1.0,
                            top_p=1e-6)[0]) == 0


def test_top_p_generation_seeded(rng):
    model = tiny_model()
    params = model.init_params(4)
    prompt = jnp.asarray(rng.integers(0, 96, (2, 4)), jnp.int32)
    a = generate(model, params, prompt, 5, temperature=0.9, top_p=0.8, rng=3)
    b = generate(model, params, prompt, 5, temperature=0.9, top_p=0.8, rng=3)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.asarray(a).min() >= 0 and np.asarray(a).max() < 96


def test_generate_cli_end_to_end(tmp_path, rng, capsys):
    """pst-generate: train -> host checkpoint -> decode text, all through
    the CLI entry point."""
    from parameter_server_distributed_tpu.checkpoint import codec
    from parameter_server_distributed_tpu.cli.generate_main import main
    from parameter_server_distributed_tpu.models.registry import (
        get_model_and_batches)

    model, _ = get_model_and_batches("small_lm", 1)
    params = {k: np.asarray(v) for k, v in model.init_params(0).items()}
    ckpt = tmp_path / "m.ckpt"
    codec.save(str(ckpt), 1, 10, params)

    rc = main(["--model=small_lm", f"--ckpt={ckpt}", "--prompt=ab",
               "--max-new=4"])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.endswith("\n") and len(out) >= 1  # decoded text printed

    # raw token-id mode
    rc = main(["--model=small_lm", f"--ckpt={ckpt}", "--tokens=1,2,3",
               "--max-new=3", "--temperature=0.5", "--top-p=0.9"])
    assert rc == 0
    ids = [int(t) for t in capsys.readouterr().out.strip().split(",")]
    assert len(ids) == 3 and all(0 <= i < 1024 for i in ids)

    with pytest.raises(ValueError, match="out of range"):
        main(["--model=small_lm", f"--ckpt={ckpt}", "--tokens=99999"])


def test_generate_cli_from_sharded_checkpoint(tmp_path, capsys):
    """pst-train orbax checkpoint -> pst-generate --ckpt-dir round-trip."""
    from parameter_server_distributed_tpu.cli.generate_main import main
    from parameter_server_distributed_tpu.config import MeshConfig
    from parameter_server_distributed_tpu.parallel.train_loop import (
        TrainLoopConfig, run_training)

    run_training(TrainLoopConfig(
        model="small_lm", batch_size=8, steps=2, optimizer="sgd",
        learning_rate=0.1, mesh=MeshConfig(data=8),
        checkpoint_dir=str(tmp_path), checkpoint_every=2, log_every=1))
    rc = main(["--model=small_lm", f"--ckpt-dir={tmp_path}",
               "--prompt=hello", "--max-new=4"])
    assert rc == 0
    assert "sharded checkpoint step 2" in capsys.readouterr().err


def test_generate_cli_cross_layout(tmp_path, capsys):
    """A store trained with --scan-layers (stacked blocks/*) decodes on an
    unrolled model and vice versa — generate_main converts layouts, and
    greedy output is identical either way."""
    from parameter_server_distributed_tpu.checkpoint import codec
    from parameter_server_distributed_tpu.cli.generate_main import main
    from parameter_server_distributed_tpu.models.registry import (
        get_model_and_batches)
    from parameter_server_distributed_tpu.models.transformer import (
        stack_layers)

    model, _ = get_model_and_batches("small_lm", 1)
    params = {k: np.asarray(v) for k, v in model.init_params(0).items()}
    stacked = stack_layers(params, model.config.n_layers)

    flat_ckpt = tmp_path / "flat.ckpt"
    codec.save(str(flat_ckpt), 1, 10, params)
    stacked_ckpt = tmp_path / "stacked.ckpt"
    codec.save(str(stacked_ckpt), 1, 10,
               {k: np.asarray(v) for k, v in stacked.items()})

    outs = []
    for ckpt, flag in [(flat_ckpt, "--scan-layers"),
                       (stacked_ckpt, ""),          # unrolled model default
                       (stacked_ckpt, "--scan-layers"),
                       (flat_ckpt, "")]:
        argv = ["--model=small_lm", f"--ckpt={ckpt}", "--tokens=1,2,3",
                "--max-new=4"]
        if flag:
            argv.append(flag)
        assert main(argv) == 0
        outs.append(capsys.readouterr().out.strip())
    assert len(set(outs)) == 1, outs


def test_beam_width_one_is_greedy(rng):
    from parameter_server_distributed_tpu.models.generation import (
        beam_search, generate)
    from parameter_server_distributed_tpu.models.transformer import small_lm

    model = small_lm(vocab=64, seq=32)
    params = model.init_params(0)
    prompt = rng.integers(0, 64, (2, 5)).astype(np.int32)
    greedy = np.asarray(generate(model, params, prompt, max_new_tokens=6))
    beam, scores = beam_search(model, params, prompt, max_new_tokens=6,
                               beam_width=1)
    np.testing.assert_array_equal(np.asarray(beam), greedy)
    assert np.all(np.isfinite(np.asarray(scores)))


def test_beam_search_full_width_finds_joint_argmax(rng):
    """With beam_width = vocab, a 2-step beam search is exhaustive: its
    result must be the argmax of the joint log-prob over ALL two-token
    continuations, computed by brute force through the full forward."""
    from parameter_server_distributed_tpu.models.generation import beam_search
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)

    vocab = 16
    model = Transformer(TransformerConfig(
        vocab=vocab, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=16, dtype=jnp.float32))
    params = model.init_params(0)
    prompt = rng.integers(0, vocab, (1, 3)).astype(np.int32)

    out, score = beam_search(model, params, prompt, max_new_tokens=2,
                             beam_width=vocab)
    out = np.asarray(out)[0]

    # brute force: joint logprob of every (t1, t2)
    best = (None, -np.inf)
    logits = np.asarray(model.apply(params, prompt))  # [1, 3, V]
    lp1 = jax.nn.log_softmax(logits[0, -1])
    for t1 in range(vocab):
        seq = np.concatenate([prompt[0], [t1]])[None].astype(np.int32)
        lp2 = jax.nn.log_softmax(np.asarray(model.apply(params, seq))[0, -1])
        for t2 in range(vocab):
            joint = float(lp1[t1]) + float(lp2[t2])
            if joint > best[1]:
                best = ((t1, t2), joint)
    assert tuple(out) == best[0]
    assert float(np.asarray(score)[0]) == pytest.approx(best[1], rel=1e-4)


def test_beam_width_validation(rng):
    from parameter_server_distributed_tpu.models.generation import beam_search
    from parameter_server_distributed_tpu.models.transformer import small_lm

    model = small_lm(vocab=64, seq=32)
    params = model.init_params(0)
    prompt = rng.integers(0, 64, (1, 4)).astype(np.int32)
    for bad in (0, 65):
        with pytest.raises(ValueError, match="beam_width"):
            beam_search(model, params, prompt, 4, beam_width=bad)


def test_beam_search_eos_freezes_score(rng):
    """A beam that emits eos_id finishes: score frozen, EOS-padded, and it
    stays comparable against live beams.  Rigged so EOS is the argmax
    from the first step: the best beam must be all-EOS with joint score
    exactly logp(EOS at step 1)."""
    from parameter_server_distributed_tpu.models.generation import beam_search
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)

    vocab = 16
    model = Transformer(TransformerConfig(
        vocab=vocab, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=16, dtype=jnp.float32))
    params = model.init_params(0)
    prompt = rng.integers(0, vocab, (1, 3)).astype(np.int32)
    # the model's own first greedy token as EOS: the top beam finishes at
    # step 1 with score logp(eos), and no live beam can ever overtake it
    # (a live beam's joint is logp(weaker first token) + non-positive
    # continuations < logp(eos)), so the frozen beam must win
    logits = np.asarray(model.apply(params, prompt))[0, -1]
    eos = int(logits.argmax())

    out, score = beam_search(model, params, prompt, max_new_tokens=5,
                             beam_width=3, eos_id=eos)
    out = np.asarray(out)[0]
    assert np.all(out == eos)  # finished at step 1, EOS-padded after
    expect = float(jax.nn.log_softmax(logits)[eos])
    assert float(np.asarray(score)[0]) == pytest.approx(expect, rel=1e-5)


def test_beam_length_penalty_prefers_longer(rng):
    """alpha=0 picks the short frozen beam (highest raw joint log-prob);
    a large alpha divides long beams' negative scores by a big factor,
    flipping the selection to a full-length live beam."""
    from parameter_server_distributed_tpu.models.generation import beam_search
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)

    vocab = 16
    model = Transformer(TransformerConfig(
        vocab=vocab, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq=16, dtype=jnp.float32))
    params = model.init_params(0)
    prompt = rng.integers(0, vocab, (1, 3)).astype(np.int32)
    logits = np.asarray(model.apply(params, prompt))[0, -1]
    eos = int(logits.argmax())

    raw, _ = beam_search(model, params, prompt, max_new_tokens=5,
                         beam_width=3, eos_id=eos)
    assert np.all(np.asarray(raw)[0] == eos)  # short frozen beam wins

    # alpha=50: a full-length beam's negative score is divided by
    # (10/6)^50 ~ 1e11, so any live beam beats the frozen one unless
    # p(EOS) > 1 - 1e-10 — impossible for an untrained model
    norm, _ = beam_search(model, params, prompt, max_new_tokens=5,
                          beam_width=3, eos_id=eos, length_penalty=50.0)
    assert np.asarray(norm)[0][0] != eos


def test_speculative_matches_target_greedy(rng):
    """Speculative decoding is an exactness-preserving accelerator: for
    any draft (here a 1-layer LM with the target's vocab) the output must
    be token-identical to target-alone greedy decoding, while committing
    multiple tokens per target forward."""
    from parameter_server_distributed_tpu.models.generation import (
        generate, speculative_generate)
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig, small_lm)

    target = small_lm(vocab=256, seq=64)
    draft = Transformer(TransformerConfig(
        vocab=256, d_model=64, n_heads=4, n_layers=1, d_ff=128,
        max_seq=64, dtype=jnp.float32))
    tparams = target.init_params(0)
    dparams = draft.init_params(1)
    prompt = rng.integers(0, 256, (1, 7)).astype(np.int32)

    reference = np.asarray(generate(target, tparams, prompt,
                                    max_new_tokens=16))
    out, stats = speculative_generate(target, tparams, draft, dparams,
                                      prompt, 16, draft_len=3)
    np.testing.assert_array_equal(out, reference)
    assert stats["verify_calls"] >= 1
    assert stats["tokens_per_target_forward"] >= 1.0

    # a PERFECT draft (the target itself) must accept everything:
    # draft_len+1 tokens per verify call
    out2, stats2 = speculative_generate(target, tparams, target, tparams,
                                        prompt, 16, draft_len=3)
    np.testing.assert_array_equal(out2, reference)
    assert stats2["draft_accept_rate"] == pytest.approx(1.0)
    # 16 tokens from prefill + 4 fully-accepted verify calls = 5 forwards
    assert stats2["tokens_per_target_forward"] == pytest.approx(16 / 5)

    with pytest.raises(ValueError, match="vocab"):
        speculative_generate(target, tparams, small_lm(vocab=64, seq=32),
                             small_lm(vocab=64, seq=32).init_params(0),
                             prompt, 4)
    with pytest.raises(ValueError, match="batch-1"):
        speculative_generate(target, tparams, draft, dparams,
                             np.zeros((2, 4), np.int32), 4)


def test_accept_or_resample_preserves_target_distribution():
    """The rejection rule's defining property: over x ~ q followed by
    accept/resample, the output token is distributed exactly as p —
    checked empirically on a skewed (p, q) pair."""
    from parameter_server_distributed_tpu.models.generation import (
        accept_or_resample)

    rng = np.random.default_rng(0)
    p = np.asarray([0.5, 0.3, 0.15, 0.05])
    q = np.asarray([0.05, 0.15, 0.3, 0.5])  # draft skewed the wrong way
    n = 20000
    counts = np.zeros(4)
    for _ in range(n):
        x = int(rng.choice(4, p=q))
        token, _ = accept_or_resample(p, q, x, rng)
        counts[token] += 1
    freq = counts / n
    # 3-sigma bound per bin: sigma = sqrt(p(1-p)/n) < 0.0036
    np.testing.assert_allclose(freq, p, atol=0.012)


def test_speculative_sampling_perfect_draft_accepts_all(rng):
    """temperature > 0 with draft == target: p == q so acceptance is
    certain; output length and stats must reflect full acceptance."""
    from parameter_server_distributed_tpu.models.generation import (
        speculative_generate)
    from parameter_server_distributed_tpu.models.transformer import small_lm

    model = small_lm(vocab=128, seq=64)
    params = model.init_params(0)
    prompt = rng.integers(0, 128, (1, 5)).astype(np.int32)
    out, stats = speculative_generate(model, params, model, params,
                                      prompt, 12, draft_len=3,
                                      temperature=1.0, seed=7)
    assert out.shape == (1, 12)
    assert stats["draft_accept_rate"] == pytest.approx(1.0)
    # deterministic given the seed
    out2, _ = speculative_generate(model, params, model, params,
                                   prompt, 12, draft_len=3,
                                   temperature=1.0, seed=7)
    np.testing.assert_array_equal(out, out2)


def test_decode_block_matches_sequential_steps(rng):
    """A T-token decode_block equals T sequential decode_steps: same
    final logits and same cache contents (the verify-step contract)."""
    import dataclasses

    from parameter_server_distributed_tpu.models.generation import (
        decode_block, decode_step, init_cache, prefill)
    from parameter_server_distributed_tpu.models.transformer import small_lm

    model = small_lm(vocab=128, seq=64)
    params = model.init_params(0)
    prompt = rng.integers(0, 128, (2, 6)).astype(np.int32)
    toks = rng.integers(0, 128, (2, 4)).astype(np.int32)

    _, cache_a = prefill(model, params, prompt, 32)
    block_logits, cache_a = decode_block(model, params, toks, cache_a)

    _, cache_b = prefill(model, params, prompt, 32)
    step_logits = []
    for j in range(4):
        lg, cache_b = decode_step(model, params, toks[:, j], cache_b)
        step_logits.append(lg)

    np.testing.assert_allclose(np.asarray(block_logits[:, -1]),
                               np.asarray(step_logits[-1]),
                               rtol=2e-5, atol=2e-5)
    for j in range(4):
        np.testing.assert_allclose(np.asarray(block_logits[:, j]),
                                   np.asarray(step_logits[j]),
                                   rtol=2e-5, atol=2e-5)
    assert int(np.asarray(cache_a.length)) == int(np.asarray(cache_b.length))
    np.testing.assert_allclose(np.asarray(cache_a.k), np.asarray(cache_b.k),
                               rtol=2e-5, atol=2e-5)


def test_generate_cli_speculative_matches_greedy(tmp_path, capsys):
    """pst-generate --draft-model: greedy speculative output through the
    CLI is byte-identical to plain greedy decoding of the same model."""
    from parameter_server_distributed_tpu.checkpoint import codec
    from parameter_server_distributed_tpu.cli.generate_main import main
    from parameter_server_distributed_tpu.models.registry import (
        get_model_and_batches)

    model, _ = get_model_and_batches("small_lm", 1)
    params = {k: np.asarray(v) for k, v in model.init_params(0).items()}
    ckpt = tmp_path / "m.ckpt"
    codec.save(str(ckpt), 1, 10, params)

    base = ["--model=small_lm", f"--ckpt={ckpt}", "--tokens=5,6,7",
            "--max-new=8"]
    assert main(base) == 0
    greedy = capsys.readouterr().out.strip()
    assert main(base + ["--draft-model=moe_lm", "--draft-len=2"]) == 0
    spec = capsys.readouterr().out.strip()
    assert spec == greedy


# ---------------------------------------------------------------------------
# Batched on-device speculative decoding (whole loop under one jit)
# ---------------------------------------------------------------------------

def _spec_pair():
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig, small_lm)

    target = small_lm(vocab=256, seq=64)
    draft = Transformer(TransformerConfig(
        vocab=256, d_model=64, n_heads=4, n_layers=1, d_ff=128,
        max_seq=64, dtype=jnp.float32))
    return target, target.init_params(0), draft, draft.init_params(1)


def test_speculative_batched_greedy_matches_target(rng):
    """Every ROW of a batched device-speculative greedy run must equal
    target-alone greedy decoding — per-row acceptance lengths diverge, so
    this exercises the ragged caches end to end."""
    from parameter_server_distributed_tpu.models.generation import (
        generate, speculative_generate_batched)

    target, tparams, draft, dparams = _spec_pair()
    prompt = rng.integers(0, 256, (4, 7)).astype(np.int32)
    reference = np.asarray(generate(target, tparams, prompt,
                                    max_new_tokens=16))
    out, stats = speculative_generate_batched(target, tparams, draft,
                                              dparams, prompt, 16,
                                              draft_len=3)
    np.testing.assert_array_equal(out, reference)
    assert stats["verify_calls"] >= 1

    # perfect draft: every proposal accepted for every row
    out2, stats2 = speculative_generate_batched(target, tparams, target,
                                                tparams, prompt, 16,
                                                draft_len=3)
    np.testing.assert_array_equal(out2, reference)
    assert stats2["draft_accept_rate"] == pytest.approx(1.0)
    assert stats2["tokens_per_target_forward"] == pytest.approx(16 / 5)


def test_speculative_batched_agrees_with_host_reference(rng):
    """Batch-1 device greedy run == the host-loop reference
    implementation, token for token and stat for stat."""
    from parameter_server_distributed_tpu.models.generation import (
        speculative_generate, speculative_generate_batched)

    target, tparams, draft, dparams = _spec_pair()
    prompt = rng.integers(0, 256, (1, 7)).astype(np.int32)
    got, s_dev = speculative_generate_batched(target, tparams, draft,
                                              dparams, prompt, 16,
                                              draft_len=3)
    want, s_host = speculative_generate(target, tparams, draft, dparams,
                                        prompt, 16, draft_len=3)
    np.testing.assert_array_equal(got, np.asarray(want))
    assert s_dev["verify_calls"] == s_host["verify_calls"]


def test_speculative_batched_sampling_preserves_distribution():
    """The vectorized on-device rejection rule preserves the target
    distribution: empirical first-token frequencies of many seeded
    batched runs match direct target sampling (tiny vocab, 3-sigma)."""
    from parameter_server_distributed_tpu.models.generation import (
        speculative_generate_batched)
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)

    vocab = 8
    target = Transformer(TransformerConfig(
        vocab=vocab, d_model=16, n_heads=2, n_layers=1, d_ff=32,
        max_seq=32, dtype=jnp.float32))
    draft = Transformer(TransformerConfig(
        vocab=vocab, d_model=8, n_heads=1, n_layers=1, d_ff=16,
        max_seq=32, dtype=jnp.float32))
    tparams, dparams = target.init_params(0), draft.init_params(3)
    prompt = np.full((64, 4), 2, np.int32)  # identical rows
    temp = 1.0

    counts = np.zeros(vocab)
    reps = 8
    for seed in range(reps):
        out, _ = speculative_generate_batched(
            target, tparams, draft, dparams, prompt, 2, draft_len=2,
            temperature=temp, seed=seed)
        for tok in out[:, 0]:
            counts[int(tok)] += 1
    freq = counts / (64 * reps)

    # ground truth: the target's own first-token distribution
    from parameter_server_distributed_tpu.models.generation import prefill
    logits, _ = prefill(target, tparams, jnp.asarray(prompt[:1]), 8)
    p = np.asarray(jax.nn.softmax(logits[0] / temp))
    sigma = np.sqrt(p * (1 - p) / (64 * reps))
    np.testing.assert_array_less(np.abs(freq - p), 4 * sigma + 0.01)


def test_speculative_batched_rejects_vocab_mismatch(rng):
    from parameter_server_distributed_tpu.models.generation import (
        speculative_generate_batched)
    from parameter_server_distributed_tpu.models.transformer import small_lm

    target, tparams, _, _ = _spec_pair()
    other = small_lm(vocab=64, seq=32)
    with pytest.raises(ValueError, match="vocab"):
        speculative_generate_batched(target, tparams, other,
                                     other.init_params(0),
                                     np.zeros((2, 4), np.int32), 4)


def test_speculative_batched_gqa_target_matches_greedy(rng):
    """Batched device speculative decoding with a GQA target (unexpanded
    K/V caches through the ragged decode path) stays token-exact vs
    target-alone greedy decoding."""
    from parameter_server_distributed_tpu.models.generation import (
        generate, speculative_generate_batched)
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)

    target = Transformer(TransformerConfig(
        vocab=256, d_model=32, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=64, max_seq=64, dtype=jnp.float32))
    tparams = target.init_params(0)
    draft = Transformer(TransformerConfig(
        vocab=256, d_model=16, n_heads=2, n_layers=1, d_ff=32,
        max_seq=64, dtype=jnp.float32))
    dparams = draft.init_params(1)
    prompt = rng.integers(0, 256, (3, 6)).astype(np.int32)
    reference = np.asarray(generate(target, tparams, prompt,
                                    max_new_tokens=12))
    out, _ = speculative_generate_batched(target, tparams, draft, dparams,
                                          prompt, 12, draft_len=3)
    np.testing.assert_array_equal(out, reference)


def test_generation_with_xla_flash_prefill_matches_dense(rng):
    """A model built with the xla_flash attention kernel serves the same
    prefill as the dense model (decode then uses the cache einsums either
    way).  Logits compared with a tolerance, not token equality — the two
    kernels reorder float accumulation, and a near-tie argmax flip would
    make discrete comparison flaky across backends."""
    from parameter_server_distributed_tpu.models.generation import prefill
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig, select_attention)

    config = TransformerConfig(vocab=256, d_model=32, n_heads=4,
                               n_layers=2, d_ff=64, max_seq=64,
                               dtype=jnp.float32)
    dense = Transformer(config)
    flash = Transformer(config,
                        attention_fn=select_attention("xla_flash", None))
    params = dense.init_params(0)
    prompt = jnp.asarray(rng.integers(0, 256, (2, 8)), jnp.int32)
    logits_d, cache_d = prefill(dense, params, prompt, 32)
    logits_f, cache_f = prefill(flash, params, prompt, 32)
    np.testing.assert_allclose(np.asarray(logits_f), np.asarray(logits_d),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(cache_f.k), np.asarray(cache_d.k),
                               rtol=2e-4, atol=2e-4)


def test_sample_token_rowwise_exactness(rng):
    """The per-row sampler's contract against the scalar one: with a
    uniform temperature vector it draws EXACTLY sample_token's tokens
    (same rng, same truncation math), zero-temperature rows are exact
    argmax regardless of the other rows, and static top_k truncation
    applies to sampled rows."""
    logits = jnp.asarray(rng.standard_normal((6, 32)) * 3.0, jnp.float32)
    key = jax.random.key(7)

    # uniform hot vector == scalar sampler, token for token
    uniform = jnp.full((6,), 0.8, jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(sample_token_rowwise(logits, key, uniform)),
        np.asarray(sample_token(logits, key, 0.8)))
    # ... including under top_k/top_p truncation
    np.testing.assert_array_equal(
        np.asarray(sample_token_rowwise(logits, key, uniform,
                                        top_k=5, top_p=0.9)),
        np.asarray(sample_token(logits, key, 0.8, top_k=5, top_p=0.9)))

    # mixed batch: zero rows are exact argmax, whatever the others do
    mixed = jnp.asarray([0.0, 9.0, 0.0, 0.5, 0.0, 2.0], jnp.float32)
    out = np.asarray(sample_token_rowwise(logits, key, mixed))
    greedy = np.asarray(jnp.argmax(logits, axis=-1))
    for i in (0, 2, 4):
        assert out[i] == greedy[i]

    # top_k=1 forces argmax even at high temperature (truncation is
    # shared/static across rows)
    hot = jnp.full((6,), 9.0, jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(sample_token_rowwise(logits, key, hot, top_k=1)),
        greedy)


def test_optimal_draft_depth_controller():
    """The expected-throughput controller: depth follows per-proposal
    agreement p and the draft/target cost ratio.  Anchors: the round-4
    measurements (accept 0.57 at k=2 -> 1.20x, accept 0.36 at k=4 ->
    0.76x over-speculation) must map to k* <= 2 at rho~1/3, and a
    perfect draft must max out the cap."""
    from parameter_server_distributed_tpu.models.generation import (
        _invert_accept_fraction, optimal_draft_depth)

    # inversion: fraction at depth k back to per-proposal p
    for p in (0.1, 0.5, 0.9):
        for k in (1, 2, 4):
            frac = sum(p ** i for i in range(1, k + 1)) / k
            assert _invert_accept_fraction(frac, k) == pytest.approx(
                p, abs=1e-6)
    assert _invert_accept_fraction(0.0, 4) == 0.0
    assert _invert_accept_fraction(1.0, 4) == 1.0

    # perfect draft -> cap; hopeless draft -> minimum depth
    assert optimal_draft_depth(1.0, 2, 8, cost_ratio=0.1) == 8
    assert optimal_draft_depth(0.0, 4, 8, cost_ratio=0.3) == 1
    # the round-4 regression shape: mid accept, moderate cost ratio
    assert optimal_draft_depth(0.36, 4, 4, cost_ratio=1 / 3) <= 2
    assert optimal_draft_depth(0.57, 2, 4, cost_ratio=1 / 3) <= 2
    # near-free draft deepens even at mid accept
    assert optimal_draft_depth(0.6, 2, 8, cost_ratio=0.02) >= 4


def test_speculative_batched_adaptive_token_exact_and_settles(rng):
    """adaptive=True: token-exact vs target-alone greedy for any depth
    trajectory, and the controller settles where acceptance points —
    depth 0 (speculation disabled, plain greedy segments) for a
    random-init draft whose economics can never pay, the cap for a
    perfect self-draft (accept 1.0)."""
    from parameter_server_distributed_tpu.models.generation import (
        generate, speculative_generate_batched)

    target, tparams, draft, dparams = _spec_pair()
    prompt = rng.integers(0, 256, (4, 7)).astype(np.int32)
    reference = np.asarray(generate(target, tparams, prompt,
                                    max_new_tokens=32))
    out, stats = speculative_generate_batched(
        target, tparams, draft, dparams, prompt, 32, draft_len=4,
        adaptive=True, draft_cost_ratio=0.3, calibration="model")
    np.testing.assert_array_equal(out, reference)
    assert stats["draft_depths"][0] == 2          # starts at min(2, cap)
    assert stats["draft_depth"] == 0              # junk draft -> disabled
    assert 0 in stats["draft_depths"]             # greedy segments ran

    out2, stats2 = speculative_generate_batched(
        target, tparams, target, tparams, prompt, 32, draft_len=4,
        adaptive=True, draft_cost_ratio=0.3, calibration="model")
    np.testing.assert_array_equal(out2, reference)
    assert stats2["draft_depth"] == 4             # perfect draft -> cap
    assert stats2["draft_accept_rate"] == pytest.approx(1.0)

    # measured mode: depth choices are host-timing-dependent, but the
    # outputs must stay token-exact whatever the probes decide
    out3, stats3 = speculative_generate_batched(
        target, tparams, draft, dparams, prompt, 32, draft_len=4,
        adaptive=True, draft_cost_ratio=0.3)
    np.testing.assert_array_equal(out3, reference)
    assert stats3["draft_depth"] in (0, 1, 2, 3, 4)


def test_adaptive_memoizes_steady_state_depth(rng):
    """The first adaptive call calibrates (segmented run); subsequent
    calls for the same (target, draft, sampling) jump straight to the
    winning FUSED program — depths report "memo" and outputs stay
    token-exact.  A junk draft memoizes k=0 (plain generate); a perfect
    draft memoizes the cap (whole-loop spec)."""
    from parameter_server_distributed_tpu.models.generation import (
        generate, speculative_generate_batched)

    target, tparams, draft, dparams = _spec_pair()
    prompt = rng.integers(0, 256, (4, 7)).astype(np.int32)
    reference = np.asarray(generate(target, tparams, prompt,
                                    max_new_tokens=32))
    kw = dict(draft_len=4, adaptive=True, draft_cost_ratio=0.3,
              calibration="model")
    _, first = speculative_generate_batched(
        target, tparams, draft, dparams, prompt, 32, **kw)
    assert first["draft_depth"] == 0
    out, steady = speculative_generate_batched(
        target, tparams, draft, dparams, prompt, 32, **kw)
    np.testing.assert_array_equal(out, reference)
    assert steady["draft_depths"] == ["memo"]
    assert steady["draft_depth"] == 0
    assert steady["verify_calls"] == 32       # one target fwd per token

    _, first2 = speculative_generate_batched(
        target, tparams, target, tparams, prompt, 32, **kw)
    assert first2["draft_depth"] == 4
    out2, steady2 = speculative_generate_batched(
        target, tparams, target, tparams, prompt, 32, **kw)
    np.testing.assert_array_equal(out2, reference)
    assert steady2["draft_depths"] == ["memo"]
    assert steady2["draft_accept_rate"] == pytest.approx(1.0)
