"""MoE layer: routing semantics, capacity drops, expert-parallel sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_distributed_tpu.config import MeshConfig
from parameter_server_distributed_tpu.models.moe import (MoEConfig, MoELayer,
                                                         moe_sharding_rule)
from parameter_server_distributed_tpu.parallel.mesh import build_mesh
from parameter_server_distributed_tpu.parallel.sharding import shard_store


def test_moe_output_shape_and_aux(rng):
    layer = MoELayer(MoEConfig(d_model=16, d_ff=32, num_experts=4))
    params = layer.init_params(0)
    x = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)
    out, aux = layer.apply(params, x)
    assert out.shape == (2, 8, 16)
    assert np.isfinite(float(aux))
    # perfectly balanced routing gives aux == 1; anything routed gives >= 1
    assert float(aux) >= 1.0 - 1e-5


def test_moe_matches_manual_single_expert(rng):
    """With one expert and ample capacity, MoE == a plain gated FFN."""
    layer = MoELayer(MoEConfig(d_model=8, d_ff=16, num_experts=1,
                               capacity_factor=2.0))
    params = layer.init_params(0)
    x = jnp.asarray(rng.standard_normal((1, 4, 8)), jnp.float32)
    out, _ = layer.apply(params, x)
    tokens = x.reshape(4, 8)
    # router prob is 1.0 for the single expert
    h = jax.nn.gelu(tokens @ params["moe/w1"][0])
    expect = (h @ params["moe/w2"][0])
    np.testing.assert_allclose(np.asarray(out).reshape(4, 8),
                               np.asarray(expect), rtol=1e-5, atol=1e-6)


def test_moe_capacity_drops_tokens():
    """Tiny capacity: over-capacity tokens produce zero output."""
    config = MoEConfig(d_model=4, d_ff=8, num_experts=2, capacity_factor=0.25)
    layer = MoELayer(config)
    params = layer.init_params(0)
    # force all 8 tokens to expert 0 via a biased router
    params["moe/router/w"] = jnp.zeros((4, 2)).at[:, 0].set(10.0)
    x = jnp.ones((1, 8, 4), jnp.float32)
    cap = layer.capacity(8)
    assert cap == 1
    out, _ = layer.apply(params, x)
    nonzero_tokens = np.count_nonzero(
        np.abs(np.asarray(out).reshape(8, 4)).sum(-1) > 1e-9)
    assert nonzero_tokens == cap


def test_moe_expert_parallel_matches_unsharded(rng):
    mesh = build_mesh(MeshConfig(expert=4, data=2))
    layer = MoELayer(MoEConfig(d_model=16, d_ff=32, num_experts=8))
    params = layer.init_params(0)
    x = jnp.asarray(rng.standard_normal((2, 16, 16)), jnp.float32)
    base_out, base_aux = layer.apply(params, x)

    sharded_params = shard_store(params, mesh, moe_sharding_rule(mesh))
    w1 = sharded_params["moe/w1"]
    assert {s.data.shape for s in w1.addressable_shards} == {(2, 16, 32)}

    @jax.jit
    def run(p, x):
        return layer.apply(p, x)

    out, aux = run(sharded_params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base_out),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(float(aux), float(base_aux), rtol=1e-5)


def test_moe_gradients_flow(rng):
    layer = MoELayer(MoEConfig(d_model=8, d_ff=16, num_experts=4))
    params = layer.init_params(0)
    x = jnp.asarray(rng.standard_normal((1, 8, 8)), jnp.float32)

    def loss(p):
        out, aux = layer.apply(p, x)
        return jnp.sum(out ** 2) + 0.01 * aux

    grads = jax.grad(loss)(params)
    for name, g in grads.items():
        assert np.isfinite(np.asarray(g)).all(), name
    # router must receive gradient signal (through the gate)
    assert np.abs(np.asarray(grads["moe/router/w"])).max() > 0
