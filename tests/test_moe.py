"""MoE layer: routing semantics, capacity drops, expert-parallel sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_distributed_tpu.config import MeshConfig
from parameter_server_distributed_tpu.models.moe import (MoEConfig, MoELayer,
                                                         moe_sharding_rule)
from parameter_server_distributed_tpu.parallel.mesh import build_mesh
from parameter_server_distributed_tpu.parallel.sharding import shard_store


def test_moe_output_shape_and_aux(rng):
    layer = MoELayer(MoEConfig(d_model=16, d_ff=32, num_experts=4))
    params = layer.init_params(0)
    x = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)
    out, aux = layer.apply(params, x)
    assert out.shape == (2, 8, 16)
    assert np.isfinite(float(aux))
    # perfectly balanced routing gives aux == 1; anything routed gives >= 1
    assert float(aux) >= 1.0 - 1e-5


def test_moe_matches_manual_single_expert(rng):
    """With one expert and ample capacity, MoE == a plain gated FFN."""
    layer = MoELayer(MoEConfig(d_model=8, d_ff=16, num_experts=1,
                               capacity_factor=2.0))
    params = layer.init_params(0)
    x = jnp.asarray(rng.standard_normal((1, 4, 8)), jnp.float32)
    out, _ = layer.apply(params, x)
    tokens = x.reshape(4, 8)
    # router prob is 1.0 for the single expert
    h = jax.nn.gelu(tokens @ params["moe/w1"][0])
    expect = (h @ params["moe/w2"][0])
    np.testing.assert_allclose(np.asarray(out).reshape(4, 8),
                               np.asarray(expect), rtol=1e-5, atol=1e-6)


def test_moe_capacity_drops_tokens():
    """Tiny capacity: over-capacity tokens produce zero output."""
    config = MoEConfig(d_model=4, d_ff=8, num_experts=2, capacity_factor=0.25)
    layer = MoELayer(config)
    params = layer.init_params(0)
    # force all 8 tokens to expert 0 via a biased router
    params["moe/router/w"] = jnp.zeros((4, 2)).at[:, 0].set(10.0)
    x = jnp.ones((1, 8, 4), jnp.float32)
    cap = layer.capacity(8)
    assert cap == 1
    out, _ = layer.apply(params, x)
    nonzero_tokens = np.count_nonzero(
        np.abs(np.asarray(out).reshape(8, 4)).sum(-1) > 1e-9)
    assert nonzero_tokens == cap


def test_moe_expert_parallel_matches_unsharded(rng):
    mesh = build_mesh(MeshConfig(expert=4, data=2))
    layer = MoELayer(MoEConfig(d_model=16, d_ff=32, num_experts=8))
    params = layer.init_params(0)
    x = jnp.asarray(rng.standard_normal((2, 16, 16)), jnp.float32)
    base_out, base_aux = layer.apply(params, x)

    sharded_params = shard_store(params, mesh, moe_sharding_rule(mesh))
    w1 = sharded_params["moe/w1"]
    assert {s.data.shape for s in w1.addressable_shards} == {(2, 16, 32)}

    @jax.jit
    def run(p, x):
        return layer.apply(p, x)

    out, aux = run(sharded_params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base_out),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(float(aux), float(base_aux), rtol=1e-5)


def test_moe_gradients_flow(rng):
    layer = MoELayer(MoEConfig(d_model=8, d_ff=16, num_experts=4))
    params = layer.init_params(0)
    x = jnp.asarray(rng.standard_normal((1, 8, 8)), jnp.float32)

    def loss(p):
        out, aux = layer.apply(p, x)
        return jnp.sum(out ** 2) + 0.01 * aux

    grads = jax.grad(loss)(params)
    for name, g in grads.items():
        assert np.isfinite(np.asarray(g)).all(), name
    # router must receive gradient signal (through the gate)
    assert np.abs(np.asarray(grads["moe/router/w"])).max() > 0


# ---------------------------------------------------------------------------
# MoE inside the Transformer (moe_every)
# ---------------------------------------------------------------------------

def test_moe_transformer_param_shapes_and_training(rng):
    from parameter_server_distributed_tpu.models.transformer import moe_lm

    model = moe_lm()
    shapes = model.param_shapes()
    assert "layer1/moe/router/w" in shapes and "layer3/moe/w2" in shapes
    assert "layer0/mlp/w1" in shapes  # odd layers stay dense
    assert "layer1/mlp/w1" not in shapes

    params = model.init_params(0)
    tokens = jnp.asarray(rng.integers(0, 1024, (4, 32)), jnp.int32)
    loss_grad = jax.jit(jax.value_and_grad(model.loss))
    losses = []
    import optax
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    for _ in range(8):
        loss, grads = loss_grad(params, tokens)
        losses.append(float(loss))
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    # router actually received gradient signal
    assert float(jnp.abs(grads["layer1/moe/router/w"]).sum()) > 0


def test_moe_transformer_expert_parallel_matches_single_device(rng):
    """The EP-sharded MoE LM step must equal the unsharded one."""
    from parameter_server_distributed_tpu.config import MeshConfig
    from parameter_server_distributed_tpu.models.transformer import (
        moe_lm, transformer_rule)
    from parameter_server_distributed_tpu.parallel.mesh import build_mesh
    from parameter_server_distributed_tpu.parallel.train_step import (
        ShardedTrainer, TrainState, make_optimizer, make_train_step)

    model = moe_lm()
    params = model.init_params(0)
    tokens = np.asarray(rng.integers(0, 1024, (4, 32)), np.int32)

    opt = make_optimizer("sgd", 0.1)
    single_step = jax.jit(make_train_step(model.loss, opt))
    s0 = TrainState.create(params, opt)
    s_single, m_single = single_step(s0, jnp.asarray(tokens))

    mesh = build_mesh(MeshConfig(expert=2, data=2, fsdp=2))
    trainer = ShardedTrainer(model.loss, mesh, transformer_rule(mesh),
                             make_optimizer("sgd", 0.1))
    state = trainer.init_state(model.init_params(0))
    s_shard, m_shard = trainer.step(state, tokens)

    np.testing.assert_allclose(float(m_shard["loss"]), float(m_single["loss"]),
                               rtol=1e-5)
    for name in ("layer1/moe/w1", "layer0/mlp/w1", "layer1/moe/router/w"):
        np.testing.assert_allclose(
            np.asarray(s_shard.params[name]), np.asarray(s_single.params[name]),
            rtol=1e-4, atol=1e-6, err_msg=name)


def test_moe_transformer_cached_generation_matches_full_forward(rng):
    """Token-exact parity holds when no token is capacity-dropped in either
    path: decode is drop-free by design, and moe_capacity=8 makes the
    full forward's capacity exceed the token count.  (Under training
    capacity, dropping is batch-global — dependent on other sequence
    positions — so decode parity for dropped tokens is impossible by
    construction; see Transformer.ffn_residual.)"""
    from parameter_server_distributed_tpu.models.generation import generate
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from tests.test_generation import greedy_by_full_forward

    model = Transformer(TransformerConfig(
        vocab=1024, d_model=128, n_heads=4, n_layers=4, d_ff=512,
        max_seq=64, dtype=jnp.float32, moe_every=2, moe_experts=4,
        moe_capacity=8.0))
    params = model.init_params(1)
    prompt = jnp.asarray(rng.integers(0, 1024, (2, 8)), jnp.int32)
    expected = greedy_by_full_forward(model, params, prompt, 4)
    got = generate(model, params, prompt, 4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_moe_decode_is_drop_free_under_collisions(rng):
    """Every decode-step token gets its expert output even when all batch
    rows route to the same expert (training capacity would drop some)."""
    from parameter_server_distributed_tpu.models.moe import MoEConfig, MoELayer

    layer = MoELayer(MoEConfig(d_model=16, d_ff=32, num_experts=4,
                               capacity_factor=1.0))
    params = layer.init_params(0)
    # identical rows -> identical routing -> guaranteed collision
    x = jnp.tile(jnp.asarray(rng.standard_normal((1, 1, 16)), jnp.float32),
                 (4, 1, 1))
    dropped, _ = layer.apply(params, x)            # cap=1: rows 2..4 dropped
    kept, _ = layer.apply(params, x, capacity_override=4)
    assert float(jnp.abs(dropped[1:]).sum()) == 0.0  # training-style drop
    assert float(jnp.abs(kept[1:]).sum()) > 0.0      # drop-free inference
    np.testing.assert_allclose(np.asarray(kept[0]), np.asarray(kept[3]),
                               rtol=1e-6)


def test_moe_lm_expert_plus_tensor_parallel_matches_unsharded(rng):
    """MoE transformer step on an expert:2 x tensor:2 x data:2 mesh (expert
    dispatch + within-expert Megatron TP on d_ff) must match the
    single-device run exactly."""
    from parameter_server_distributed_tpu.config import MeshConfig
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig, transformer_rule)
    from parameter_server_distributed_tpu.parallel.mesh import build_mesh
    from parameter_server_distributed_tpu.parallel.train_step import (
        ShardedTrainer, make_optimizer)

    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                               d_ff=64, max_seq=16, dtype=jnp.float32,
                               moe_every=2, moe_experts=4)
    tokens = rng.integers(0, 64, (8, 16)).astype(np.int32)
    results = {}
    for label, mesh_config in (("sharded", MeshConfig(expert=2, tensor=2,
                                                      data=2)),
                               ("single", MeshConfig(data=8))):
        mesh = build_mesh(mesh_config)
        model = Transformer(config, mesh=mesh)
        trainer = ShardedTrainer(model.loss, mesh, transformer_rule(mesh),
                                 make_optimizer("sgd", 0.1))
        state = trainer.init_state(model.init_params(0))
        if label == "sharded":
            spec = state.params["layer1/moe/w1"].sharding.spec
            assert spec[0] == "expert" and spec[2] == "tensor", spec
        state, metrics = trainer.step(state, tokens)
        results[label] = (float(metrics["loss"]),
                          np.asarray(state.params["layer1/moe/w1"]))
    np.testing.assert_allclose(results["sharded"][0], results["single"][0],
                               rtol=1e-5)
    np.testing.assert_allclose(results["sharded"][1], results["single"][1],
                               rtol=1e-4, atol=1e-6)


def test_moe_top2_matches_manual_mixture(rng):
    """top_k=2 with ample capacity == the renormalized two-expert
    mixture computed densely per token."""
    layer = MoELayer(MoEConfig(d_model=8, d_ff=16, num_experts=4, top_k=2,
                               capacity_factor=8.0))
    params = layer.init_params(0)
    x = jnp.asarray(rng.standard_normal((2, 4, 8)), jnp.float32)
    out, aux = layer.apply(params, x)

    tokens = np.asarray(x).reshape(8, 8)
    probs = np.asarray(jax.nn.softmax(
        tokens @ np.asarray(params["moe/router/w"]), axis=-1))
    expect = np.zeros_like(tokens)
    for t in range(8):
        top2 = np.argsort(probs[t])[::-1][:2]
        gates = probs[t][top2] / probs[t][top2].sum()
        for g, e in zip(gates, top2):
            h = np.asarray(jax.nn.gelu(
                tokens[t] @ np.asarray(params["moe/w1"][e])))
            expect[t] += g * (h @ np.asarray(params["moe/w2"][e]))
    np.testing.assert_allclose(np.asarray(out).reshape(8, 8), expect,
                               rtol=1e-4, atol=1e-5)
    assert np.isfinite(float(aux))


def test_moe_top2_gradients_and_expert_parallel(rng):
    """top-2 routing trains under expert sharding and matches the
    unsharded layer."""
    config = MoEConfig(d_model=8, d_ff=16, num_experts=4, top_k=2,
                       capacity_factor=4.0)
    layer = MoELayer(config)
    params = layer.init_params(0)
    x = jnp.asarray(rng.standard_normal((2, 8, 8)), jnp.float32)

    def loss(p, x):
        out, aux = layer.apply(p, x)
        return jnp.sum(out ** 2) + 0.01 * aux

    grads = jax.jit(jax.grad(loss))(params, x)
    for name in ("moe/router/w", "moe/w1", "moe/w2"):
        assert float(jnp.max(jnp.abs(grads[name]))) > 0, name

    unsharded, _ = jax.jit(layer.apply)(params, x)
    mesh = build_mesh(MeshConfig(expert=4, data=2))
    sharded_params = shard_store(params, mesh, moe_sharding_rule(mesh))
    sharded, _ = jax.jit(layer.apply)(sharded_params, x)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(unsharded),
                               rtol=1e-5, atol=1e-6)


def test_moe_top_k_validation():
    with pytest.raises(ValueError, match="top_k"):
        MoELayer(MoEConfig(num_experts=4, top_k=5))
    with pytest.raises(ValueError, match="top_k"):
        MoELayer(MoEConfig(num_experts=4, top_k=0))


def test_moe_lm_top2_trains_and_decodes(rng):
    """The top-2 MoE transformer trains and its KV-cached decode stays
    token-exact vs the full forward.  Ample moe_capacity makes the
    training-capacity full forward drop-free too, so the equality is
    seed-robust (decode is always drop-free; the reference forward would
    otherwise drop under an unlucky routing draw)."""
    from parameter_server_distributed_tpu.models.generation import generate
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)

    model = Transformer(TransformerConfig(
        vocab=64, d_model=128, n_heads=4, n_layers=4, d_ff=512, max_seq=32,
        dtype=jnp.float32, moe_every=2, moe_experts=4, moe_top_k=2,
        moe_capacity=8.0))
    params = model.init_params(0)
    tokens = rng.integers(0, 64, (2, 16)).astype(np.int32)
    loss0 = float(jax.jit(model.loss)(params, tokens))
    assert np.isfinite(loss0)

    prompt = rng.integers(0, 64, (1, 4)).astype(np.int32)
    out = np.asarray(generate(model, params, prompt, max_new_tokens=6))
    # greedy decode must equal re-running the full forward each step
    ids = list(prompt[0])
    for _ in range(6):
        logits = model.apply(params, np.asarray([ids], np.int32))
        ids.append(int(np.asarray(logits)[0, -1].argmax()))
    np.testing.assert_array_equal(out[0], np.asarray(ids[4:]))


def test_moe_350m_preset_shape(rng):
    """The flagship-scale sparse preset: lm_350m trunk, 12 routed layers
    over 8 experts, ~1.07B total params; MFU uses ACTIVE-expert FLOPs
    (top_k of 8 experts per token — the per-token compute is ~the dense
    350M trunk's, which is the point of sparse MoE).  Full-size training
    is a TPU job (the sweep's moe350_b16 row); expert-sharded TRAINING
    coverage for this layout lives in test_moe/test_parallel's small
    twins."""
    from parameter_server_distributed_tpu.models.registry import (
        get_model_and_batches)

    model, batches = get_model_and_batches("moe_350m", 2)
    c = model.config
    assert sum(c.is_moe_layer(i) for i in range(c.n_layers)) == 12
    assert 1.0e9 < model.num_params() < 1.2e9
    fps = model.flops_per_sample()
    inactive = 12 * (c.moe_experts - c.moe_top_k) * 2 * c.d_model * c.d_ff
    assert fps == (6.0 * (model.num_params() - inactive) * c.max_seq
                   + 12.0 * c.n_layers * c.d_model * c.max_seq ** 2)
    tokens, = (next(batches),)
    assert tokens.shape == (2, 1024)
