"""LoRA fine-tuning (models/lora.py): adapters train, base stays frozen,
merge collapses exactly, and the CLI/train-loop integration works on a
sharded mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_distributed_tpu.models.lora import (
    DEFAULT_TARGETS, freeze_base, init_lora, lora_loss, lora_names,
    merge_lora, split_rank_alpha, trainable_mask)
from parameter_server_distributed_tpu.models.transformer import (
    Transformer, TransformerConfig)


def tiny(scan=False):
    return Transformer(TransformerConfig(
        vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=16,
        dtype=jnp.float32, scan_layers=scan))


def test_init_starts_at_base_model(rng):
    """B = 0 at init, so the adapted forward equals the base forward
    exactly; A/B appear for every q/v projection in both layouts."""
    tokens = rng.integers(0, 64, (2, 16)).astype(np.int32)
    for scan in (False, True):
        model = tiny(scan)
        params = model.init_params(0)
        adapted = init_lora(params, rank=4, rng=1)
        n_targets = 2 if scan else 2 * model.config.n_layers
        assert len(lora_names(adapted)) == 2 * n_targets
        base_loss = float(model.loss(params, tokens))
        wrapped = lora_loss(model.loss)
        assert float(wrapped(adapted, tokens)) == pytest.approx(base_loss)


def test_training_updates_only_adapters(rng):
    """Gradient steps through the masked optimizer move ONLY /lora_
    entries; the base store is bit-identical after training, and the
    loss decreases."""
    import optax

    model = tiny()
    tokens = rng.integers(0, 64, (4, 16)).astype(np.int32)
    params = init_lora(model.init_params(0), rank=4, rng=1)
    loss_fn = lora_loss(model.loss)
    opt = freeze_base(optax.adam(1e-2))
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    base_before = {n: np.asarray(v) for n, v in params.items()
                   if not n.endswith(("/lora_a", "/lora_b"))}
    losses = []
    for _ in range(12):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    for name, before in base_before.items():
        np.testing.assert_array_equal(np.asarray(params[name]), before,
                                      err_msg=f"{name} moved but is frozen")
    moved = [n for n in lora_names(params)
             if np.abs(np.asarray(params[n])).sum() > 0]
    assert any(n.endswith("/lora_b") for n in moved)  # B left zero-init


def test_merge_equals_adapted_forward(rng):
    """merge_lora folds adapters into plain dense weights whose forward
    matches the adapted model's exactly — the serving/export path."""
    model = tiny()
    tokens = rng.integers(0, 64, (2, 16)).astype(np.int32)
    params = init_lora(model.init_params(0), rank=4, rng=1)
    # give B real values so the adapters actually contribute
    for name in lora_names(params):
        if name.endswith("/lora_b"):
            key = jax.random.key(hash(name) % (2**31))
            params[name] = 0.1 * jax.random.normal(
                key, params[name].shape, params[name].dtype)
    adapted = float(lora_loss(model.loss, alpha=8.0)(params, tokens))
    merged = merge_lora(params, alpha=8.0)
    assert not lora_names(merged)
    assert float(model.loss(merged, tokens)) == pytest.approx(adapted,
                                                              rel=1e-6)
    # merged store has exactly the base names (serves/saves like dense)
    assert set(merged) == set(model.init_params(0))
    # rank is read from the factors — a different rank cannot mis-scale
    r2 = init_lora(model.init_params(0), rank=2, rng=3)
    assert merge_lora(r2)["layer0/attn/wq"].shape == (32, 32)


def test_hf_converted_checkpoint_lora_finetunes(rng):
    """The intended workflow: convert a transformers GPT-2 checkpoint,
    attach adapters, fine-tune — base (converted) weights frozen."""
    transformers = pytest.importorskip("transformers")
    import optax

    from parameter_server_distributed_tpu.models.hf import from_hf_gpt2

    cfg = transformers.GPT2Config(vocab_size=96, n_positions=32, n_embd=32,
                                  n_layer=2, n_head=2)
    hf = transformers.GPT2LMHeadModel(cfg)
    model, params = from_hf_gpt2(hf)
    params = init_lora(params, rank=2, rng=0)
    loss_fn = lora_loss(model.loss)
    opt = freeze_base(optax.adam(5e-2))
    opt_state = opt.init(params)
    tokens = rng.integers(0, 96, (2, 16)).astype(np.int32)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    wte_before = np.asarray(params["embed/tok"])
    losses = [float(step(params, opt_state)[2])]
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    np.testing.assert_array_equal(np.asarray(params["embed/tok"]),
                                  wte_before)


def test_train_loop_lora_on_mesh(tmp_path):
    """pst-train's code path: a dense run checkpoints, then --lora with
    --init-ckpt-dir fine-tunes FROM that pretrained base on an 8-device
    mesh (the dense-checkpoint -> LoRA flow the CLI documents)."""
    from parameter_server_distributed_tpu.config import MeshConfig
    from parameter_server_distributed_tpu.parallel.train_loop import (
        TrainLoopConfig, run_training)

    base_dir = str(tmp_path / "base")
    pre = run_training(TrainLoopConfig(
        model="small_lm", batch_size=8, steps=4, optimizer="adam",
        learning_rate=1e-2, log_every=2, checkpoint_dir=base_dir,
        checkpoint_every=4))
    summary = run_training(TrainLoopConfig(
        model="small_lm", batch_size=8, steps=6, optimizer="adam",
        learning_rate=1e-2, lora="4:8", log_every=3,
        init_ckpt_dir=base_dir,
        mesh=MeshConfig(data=2, fsdp=2, tensor=2)))
    assert pre["steps"] == 4
    assert summary["steps"] == 6
    assert np.isfinite(summary["final_loss"])


def test_generate_cli_merges_lora_checkpoint(tmp_path, rng):
    """pst-generate on a --lora checkpoint: refuses without --lora-alpha
    (the scale must match training), merges and decodes with it."""
    import os
    import subprocess
    import sys

    from parameter_server_distributed_tpu.parallel.train_loop import (
        TrainLoopConfig, run_training)

    ckpt = str(tmp_path / "ft")
    run_training(TrainLoopConfig(
        model="tiny_lm", batch_size=4, steps=2, optimizer="adam",
        learning_rate=1e-2, lora="2:4", checkpoint_dir=ckpt,
        checkpoint_every=2, log_every=2))
    env = dict(os.environ, PSDT_PLATFORM="cpu")
    base = [sys.executable, "-m",
            "parameter_server_distributed_tpu.cli.generate_main",
            "--model=tiny_lm", f"--ckpt-dir={ckpt}", "--tokens=1,2,3",
            "--max-new=3"]
    refused = subprocess.run(base, capture_output=True, text=True, env=env,
                             timeout=300)
    assert refused.returncode != 0
    assert "lora-alpha" in refused.stderr + refused.stdout
    merged = subprocess.run(base + ["--lora-alpha=4"], capture_output=True,
                            text=True, env=env, timeout=300)
    assert merged.returncode == 0, merged.stderr[-1500:]
    assert "LoRA merged" in merged.stderr
    # bare --lora-alpha would silently mean alpha=1 — rejected
    bare = subprocess.run(base + ["--lora-alpha"], capture_output=True,
                          text=True, env=env, timeout=300)
    assert bare.returncode != 0
    assert "explicit value" in bare.stderr + bare.stdout
    # --avg-last over LoRA checkpoints is nonlinear in the factors
    avg = subprocess.run(base + ["--lora-alpha=4", "--avg-last=2"],
                         capture_output=True, text=True, env=env,
                         timeout=300)
    assert avg.returncode != 0
    assert "nonlinear" in avg.stderr + avg.stdout


def test_init_ckpt_dir_rejects_adapter_store(tmp_path):
    """--init-ckpt-dir pointing at a LoRA run errors explicitly: with
    --lora it would overwrite trained factors, without it the adapters
    would ride along inert."""
    from parameter_server_distributed_tpu.parallel.train_loop import (
        TrainLoopConfig, run_training)

    ckpt = str(tmp_path / "ft")
    run_training(TrainLoopConfig(
        model="tiny_lm", batch_size=4, steps=2, optimizer="adam",
        learning_rate=1e-2, lora="2:4", checkpoint_dir=ckpt,
        checkpoint_every=2, log_every=2))
    for lora in ("2:4", ""):
        with pytest.raises(ValueError, match="already contains LoRA"):
            run_training(TrainLoopConfig(
                model="tiny_lm", batch_size=4, steps=2, lora=lora,
                init_ckpt_dir=ckpt, log_every=2))


def test_spec_parsing_and_errors():
    assert split_rank_alpha("8") == (8, 16.0)
    assert split_rank_alpha("4:32") == (4, 32.0)
    with pytest.raises(ValueError, match="--lora"):
        split_rank_alpha("abc")
    with pytest.raises(ValueError, match="rank"):
        split_rank_alpha("0")
    with pytest.raises(ValueError, match="no parameters match"):
        init_lora({"w": jnp.zeros((4, 4))}, targets=DEFAULT_TARGETS)
    # mask shape matches the store
    p = init_lora({"x/attn/wq": jnp.zeros((4, 4))}, rank=2)
    mask = trainable_mask(p)
    assert mask["x/attn/wq/lora_a"] and not mask["x/attn/wq"]


def test_lora_composes_with_pipeline(rng):
    """LoRA x pipeline: adapters follow the blocks/* restack ([P, Lc, d, r]
    factors), and lora_value_and_grad differentiates through the adapter
    collapse around the 1F1B schedule.  At init (B = 0) the loss equals
    the base pipelined model's; dL/dA = dW @ B^T = 0 while dL/dB != 0 —
    exactly the vjp chain through W_eff = W + scale * A @ B."""
    from parameter_server_distributed_tpu.config import MeshConfig
    from parameter_server_distributed_tpu.models.lora import (
        init_lora, lora_value_and_grad)
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.parallel.mesh import build_mesh
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    mesh = build_mesh(MeshConfig(pipeline=2, data=4))
    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=4,
                               d_ff=64, max_seq=16, dtype=jnp.float32)
    piped = PipelinedTransformerLM(Transformer(config), mesh,
                                   num_microbatches=2, schedule="1f1b")
    tokens = rng.integers(0, 64, (8, 16)).astype(np.int32)
    base_params = piped.init_params(0)
    params = init_lora(base_params, rank=2, rng=1)
    assert params["blocks/attn/wq/lora_a"].shape == (2, 2, 32, 2)
    assert params["blocks/attn/wq/lora_b"].shape == (2, 2, 2, 32)

    vg = jax.jit(lora_value_and_grad(piped.value_and_grad, alpha=4.0))
    loss0, grads = vg(params, tokens)
    loss_base, _ = jax.jit(piped.value_and_grad)(base_params, tokens)
    np.testing.assert_allclose(float(loss0), float(loss_base), rtol=1e-5)
    assert float(np.abs(np.asarray(
        grads["blocks/attn/wq/lora_b"])).max()) > 0
    np.testing.assert_allclose(
        np.asarray(grads["blocks/attn/wq/lora_a"]), 0.0, atol=1e-7)
    # base cotangents pass through the collapse unchanged
    assert float(np.abs(np.asarray(grads["blocks/attn/wq"])).max()) > 0


def test_train_loop_lora_pipeline_and_ema(tmp_path):
    """The full round-5 composition: --lora x pipeline (1F1B) x --ema in
    one run_training — adapters train under the pipe schedule, the EMA
    shadow tracks only the adapters (freeze_base masks params_ema), and
    the end-of-run eval grafts the shadowed adapters onto the frozen base
    to report ema_eval_loss."""
    from parameter_server_distributed_tpu.config import MeshConfig
    from parameter_server_distributed_tpu.parallel.train_loop import (
        TrainLoopConfig, run_training)

    summary = run_training(TrainLoopConfig(
        model="small_lm4", batch_size=8, steps=4, optimizer="adam",
        learning_rate=1e-2, lora="2:4", ema=0.5, eval_every=2,
        log_every=2, pipeline_schedule="1f1b",
        mesh=MeshConfig(pipeline=2, data=4)))
    assert summary["steps"] == 4
    assert np.isfinite(summary["final_loss"])
    assert np.isfinite(summary["eval_loss"])
    assert summary["ema_eval_loss"] is not None
    assert np.isfinite(summary["ema_eval_loss"])


def test_lora_ema_shadow_tracks_adapters_only(rng):
    """--ema x --lora at the optimizer level: freeze_base(make_optimizer
    (ema_decay>0)) masks params_ema to the adapters, extract_ema returns
    MaskedNode for frozen entries, and the grafted store (shadowed
    adapters on the frozen base) is the EMA of the full store."""
    import optax

    from parameter_server_distributed_tpu.models.lora import (
        freeze_base, init_lora, lora_loss, trainable_mask)
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.parallel.train_step import (
        extract_ema, make_optimizer)

    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                               d_ff=64, max_seq=16, dtype=jnp.float32)
    model = Transformer(config)
    params = init_lora(model.init_params(0), rank=2, rng=1)
    loss_fn = lora_loss(model.loss, alpha=4.0)
    opt = freeze_base(make_optimizer("adam", 1e-2, ema_decay=0.5))
    state = opt.init(params)
    tokens = rng.integers(0, 64, (4, 16)).astype(np.int32)

    shadows = []
    for _ in range(3):
        grads = jax.grad(loss_fn)(params, tokens)
        updates, state = opt.update(grads, state, params)
        params = optax.apply_updates(params, updates)
        ema = extract_ema(state)
        assert ema is not None
        shadows.append(ema)
    mask = trainable_mask(params)
    for name, trains in mask.items():
        if trains:
            assert isinstance(ema[name], jax.Array), name
        else:
            assert isinstance(ema[name], optax.MaskedNode), name
    # decay 0.5: shadow lags the live adapter, converging toward it
    live = np.asarray(params["layer0/attn/wq/lora_b"])
    shadow = np.asarray(shadows[-1]["layer0/attn/wq/lora_b"])
    assert np.abs(shadow).max() > 0
    assert not np.allclose(shadow, live)


def test_lora_ema_survives_resume(tmp_path):
    """--lora x --ema x --resume: the masked EmaState (MaskedNode
    placeholders for frozen base entries) must round-trip the sharded
    checkpoint template restore, and the resumed run still reports
    ema_eval_loss (the advisor flagged template-free restores degrading
    NamedTuples — the template path must not)."""
    from parameter_server_distributed_tpu.parallel.train_loop import (
        TrainLoopConfig, run_training)

    config = dict(
        model="tiny_lm", batch_size=4, steps=4, optimizer="adam",
        learning_rate=1e-2, lora="2:4", ema=0.7, eval_every=4,
        eval_steps=1, checkpoint_dir=str(tmp_path / "ft"),
        checkpoint_every=4, log_every=2)
    first = run_training(TrainLoopConfig(**config))
    assert np.isfinite(first["ema_eval_loss"])
    resumed = run_training(TrainLoopConfig(**config, resume=True))
    assert resumed["steps"] == 4            # nothing further to train
    assert np.isfinite(resumed["ema_eval_loss"])


def test_lora_composes_with_moe_and_converted_arch_1f1b(rng):
    """Two more cells of the composition matrix: (a) LoRA on an all-MoE
    LM — adapters target the attention projections, router/experts stay
    frozen base weights; (b) LoRA through the 1F1B schedule on a
    GPT-2-ARCH config (learned positions + layernorm + biases), the
    round-5 converted-checkpoint path."""
    import optax

    from parameter_server_distributed_tpu.config import MeshConfig
    from parameter_server_distributed_tpu.models.lora import (
        freeze_base, init_lora, lora_loss, lora_value_and_grad)
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig, switch_lm)
    from parameter_server_distributed_tpu.parallel.mesh import build_mesh
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)

    # (a) MoE: one masked adam step moves adapters only
    moe = switch_lm(vocab=128, seq=16)
    params = init_lora(moe.init_params(0), rank=2, rng=1)
    opt = freeze_base(optax.adam(1e-2))
    state = opt.init(params)
    tokens = rng.integers(0, 128, (4, 16)).astype(np.int32)
    loss_fn = lora_loss(moe.loss, alpha=4.0)
    grads = jax.grad(loss_fn)(params, tokens)
    updates, state = opt.update(grads, state, params)
    new = optax.apply_updates(params, updates)
    assert float(np.abs(np.asarray(
        new["layer0/attn/wq/lora_b"]
        - params["layer0/attn/wq/lora_b"])).max()) > 0
    np.testing.assert_array_equal(np.asarray(new["layer0/moe/w1"]),
                                  np.asarray(params["layer0/moe/w1"]))
    np.testing.assert_array_equal(np.asarray(new["layer0/moe/router/w"]),
                                  np.asarray(params["layer0/moe/router/w"]))

    # (b) GPT-2 arch x LoRA x 1F1B: collapse-wrapped schedule grads —
    # at init (B=0) loss equals base, dL/dB flows, base cotangents exist
    mesh = build_mesh(MeshConfig(pipeline=2, data=4))
    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=4,
                               d_ff=64, max_seq=16, dtype=jnp.float32,
                               pos_emb="learned", norm="layernorm",
                               bias=True, mlp_act="gelu")
    piped = PipelinedTransformerLM(Transformer(config), mesh,
                                   num_microbatches=2, schedule="1f1b")
    base_params = piped.init_params(0)
    lparams = init_lora(base_params, rank=2, rng=1)
    tokens = rng.integers(0, 64, (8, 16)).astype(np.int32)
    vg = jax.jit(lora_value_and_grad(piped.value_and_grad, alpha=4.0))
    loss0, grads = vg(lparams, tokens)
    base_loss, _ = jax.jit(piped.value_and_grad)(base_params, tokens)
    # B=0 at init: the adapted model IS the base model
    np.testing.assert_allclose(float(loss0), float(base_loss), rtol=1e-5)
    assert float(np.abs(np.asarray(
        grads["blocks/attn/wq/lora_b"])).max()) > 0
    assert float(np.abs(np.asarray(grads["embed/pos"])).max()) > 0
