"""Wire-format codec tests.

Golden byte vectors were produced with protoc-generated Python gencode for
the reference IDL (proto/parameter_server.proto, proto/coordinator.proto) and
verified byte-identical in both directions; they are embedded here so the
test suite needs no protoc/grpc_tools at runtime.
"""

import numpy as np
import pytest

from parameter_server_distributed_tpu.rpc import messages as m
from parameter_server_distributed_tpu.rpc import wire

GOLDENS = {
    "tensor": "0a086c61796572302f77120202031a180000c03f000010c0000000000000704095bfd633000000bf",
    "gradient_update": "080310111a280a086c61796572302f77120202031a180000c03f000010c0000000000000704095bfd633000000bf1a140a01621201031a0ccdcccc3dcdcc4c3e9a99993e",
    "push_response": "080112026f6b1811200128043004",
    "pull_negative": "08ffffffffffffffffff01",
    "worker_info": "0807120831302e302e302e35189687032208776f726b65722d37",
    "heartbeat": "08071002",
    "heartbeat_resp": "080110bb948ba98533",
    "list_workers": "0a1a0807120831302e302e302e35189687032208776f726b65722d371001",
    "load_ckpt": "080112066c6f61646564180322280a086c61796572302f77120202031a180000c03f000010c0000000000000704095bfd633000000bf",
}


def _tensor():
    return m.Tensor(name="layer0/w", shape=[2, 3],
                    data=np.array([1.5, -2.25, 0.0, 3.75, 1e-7, -0.5], np.float32),
                    dtype=0)


def _golden_msgs():
    t = _tensor()
    return {
        "tensor": t,
        "gradient_update": m.GradientUpdate(
            worker_id=3, iteration=17,
            gradients=[t, m.Tensor.from_array("b", np.array([0.1, 0.2, 0.3], np.float32))]),
        "push_response": m.PushResponse(success=True, message="ok", iteration=17,
                                        aggregation_complete=True, workers_received=4,
                                        total_workers=4),
        "pull_negative": m.PullRequest(worker_id=-1, iteration=0),
        "worker_info": m.WorkerInfo(worker_id=7, address="10.0.0.5", port=50070,
                                    hostname="worker-7"),
        "heartbeat": m.HeartbeatRequest(worker_id=7, status=m.WorkerStatus.CHECKPOINTING),
        "heartbeat_resp": m.HeartbeatResponse(success=True, timestamp=1753775000123),
        "list_workers": m.ListWorkersResponse(
            workers=[m.WorkerInfo(worker_id=7, address="10.0.0.5", port=50070,
                                  hostname="worker-7")],
            total_workers=1),
        "load_ckpt": m.LoadCheckpointResponse(success=True, message="loaded", epoch=3,
                                              parameters=[t]),
    }


@pytest.mark.parametrize("key", sorted(GOLDENS))
def test_encode_matches_protoc_golden(key):
    msg = _golden_msgs()[key]
    assert msg.encode().hex() == GOLDENS[key]


@pytest.mark.parametrize("key", sorted(GOLDENS))
def test_decode_golden_roundtrip(key):
    msg = _golden_msgs()[key]
    decoded = type(msg).decode(bytes.fromhex(GOLDENS[key]))
    assert decoded == msg
    assert decoded.encode().hex() == GOLDENS[key]


def test_varint_roundtrip():
    for v in [0, 1, 127, 128, 300, 2**31 - 1, 2**63 - 1, 2**64 - 1]:
        buf = wire.encode_varint(v)
        out, pos = wire.decode_varint(buf, 0)
        assert out == v and pos == len(buf)


def test_negative_int32_ten_byte_varint():
    req = m.PullRequest(worker_id=-1)
    assert req.encode() == bytes.fromhex("08ffffffffffffffffff01")
    assert m.PullRequest.decode(req.encode()).worker_id == -1


def test_default_elision():
    # proto3: default-valued scalar fields are omitted
    assert m.PushResponse().encode() == b""
    assert m.PullRequest(worker_id=0, iteration=0).encode() == b""


def test_unknown_field_skipped():
    # field 99 varint prepended — decoder must skip it
    extra = wire.encode_varint((99 << 3) | 0) + wire.encode_varint(42)
    body = extra + m.PullRequest(worker_id=5, iteration=2).encode()
    msg = m.PullRequest.decode(body)
    assert msg.worker_id == 5 and msg.iteration == 2


def test_unpacked_repeated_scalars_accepted():
    # proto3 decoders must accept unpacked encodings of packed fields:
    # shape as two separate varint fields, data as two separate fixed32 fields
    import struct
    body = b"".join([
        wire.encode_varint((2 << 3) | 0), wire.encode_varint(2),
        wire.encode_varint((2 << 3) | 0), wire.encode_varint(3),
        wire.encode_varint((3 << 3) | 5), struct.pack("<f", 1.5),
        wire.encode_varint((3 << 3) | 5), struct.pack("<f", 2.5),
    ])
    t = m.Tensor.decode(body)
    assert t.shape == [2, 3]
    np.testing.assert_array_equal(np.asarray(t.data), np.array([1.5, 2.5], np.float32))


def test_tensor_array_roundtrip(rng):
    arr = rng.standard_normal((4, 8, 3)).astype(np.float32)
    t = m.Tensor.from_array("x", arr)
    rt = m.Tensor.decode(t.encode())
    np.testing.assert_array_equal(rt.to_array(), arr)
    assert rt.name == "x" and rt.shape == [4, 8, 3]


def test_large_tensor_fast_path(rng):
    arr = rng.standard_normal((512, 512)).astype(np.float32)
    t = m.Tensor.from_array("big", arr)
    encoded = t.encode()
    rt = m.Tensor.decode(encoded)
    np.testing.assert_array_equal(rt.to_array(), arr)
    # wire size ≈ 4 bytes/element + small header
    assert len(encoded) < arr.size * 4 + 64


def test_empty_messages():
    assert m.ListWorkersRequest().encode() == b""
    assert isinstance(m.ListWorkersRequest.decode(b""), m.ListWorkersRequest)


# ---------------------------------------------------------------------------
# Packed-payload transport extension (Tensor fields 5/6, PullRequest field 3).
# The roundtrip tests take `each_codec` (tests/conftest.py): every run covers
# BOTH the numpy oracle (PSDT_NATIVE=0) and the native C++ codec, so the
# fallback path can never rot.
# ---------------------------------------------------------------------------

def test_raw_f32_packed_roundtrip_exact(rng, each_codec):
    arr = rng.standard_normal((64, 32)).astype(np.float32)
    t = m.Tensor.from_array("x", arr, wire_dtype=m.WIRE_RAW_F32)
    rt = m.Tensor.decode(t.encode())
    np.testing.assert_array_equal(rt.to_array(), arr)
    assert rt.packed_dtype == m.WIRE_RAW_F32
    assert np.asarray(rt.data).size == 0  # payload rides in field 5 only


def test_bf16_packed_halves_bytes_and_rounds_rne(rng, each_codec):
    import ml_dtypes

    arr = rng.standard_normal((256, 64)).astype(np.float32)
    f32 = m.Tensor.from_array("x", arr).encode()
    bf16 = m.Tensor.from_array("x", arr, wire_dtype=m.WIRE_BF16).encode()
    assert len(bf16) < len(f32) * 0.55  # ~half the payload
    rt = m.Tensor.decode(bf16).to_array()
    expected = arr.astype(ml_dtypes.bfloat16).astype(np.float32)
    np.testing.assert_array_equal(rt, expected)
    # bf16 keeps 8 exponent bits: values survive with ~3 decimal digits
    np.testing.assert_allclose(rt, arr, rtol=8e-3)


def test_reference_schema_skips_packed_fields(rng):
    """A reference peer (fields 1-4 only) must skip fields 5/6 cleanly per
    proto3 unknown-field rules."""

    class ReferenceTensor(wire.Message):
        FIELDS = m.Tensor.FIELDS[:4]

    arr = rng.standard_normal((8,)).astype(np.float32)
    encoded = m.Tensor.from_array("x", arr, wire_dtype=m.WIRE_BF16).encode()
    ref = ReferenceTensor.decode(encoded)
    assert ref.name == "x" and ref.shape == [8]
    assert np.asarray(ref.data).size == 0  # payload invisible, no crash


def test_pull_request_wire_dtype_default_elided():
    # a default-encoding PullRequest stays byte-identical to the reference's
    assert m.PullRequest(worker_id=1, iteration=2).encode() == \
        m.PullRequest(worker_id=1, iteration=2, wire_dtype=m.WIRE_F32).encode()
    rt = m.PullRequest.decode(
        m.PullRequest(worker_id=1, iteration=2, wire_dtype=m.WIRE_BF16).encode())
    assert rt.wire_dtype == m.WIRE_BF16


def test_int8_packed_quarter_bytes_and_error_bound(rng, each_codec):
    arr = rng.standard_normal((128, 64)).astype(np.float32) * 3.0
    f32 = m.Tensor.from_array("g", arr).encode()
    int8 = m.Tensor.from_array("g", arr, wire_dtype=m.WIRE_INT8).encode()
    assert len(int8) < len(f32) * 0.3  # ~quarter the payload
    rt = m.Tensor.decode(int8).to_array()
    scale = np.abs(arr).max() / 127.0
    assert np.abs(rt - arr).max() <= scale * 0.5 + 1e-7  # round-to-nearest
    # zeros encode/decode cleanly (scale guard)
    z = m.Tensor.from_array("z", np.zeros(16, np.float32),
                            wire_dtype=m.WIRE_INT8)
    np.testing.assert_array_equal(m.Tensor.decode(z.encode()).to_array(),
                                  np.zeros(16, np.float32))


def test_topk_packed_sparse_roundtrip(rng, each_codec):
    """WIRE_TOPK keeps exactly the k largest-|value| entries (bf16-
    precision values at their original indices, zeros elsewhere) and the
    payload shrinks with the density."""
    arr = rng.standard_normal((64, 32)).astype(np.float32)
    t = m.Tensor.from_array("g", arr, wire_dtype=m.WIRE_TOPK,
                            topk_density=0.1)
    rt = m.Tensor.decode(t.encode()).to_array()
    k = max(1, round(arr.size * 0.1))
    flat = arr.reshape(-1)
    keep = np.argsort(np.abs(flat))[-k:]
    assert np.count_nonzero(rt) == k
    mask = np.zeros(arr.size, bool)
    mask[keep] = True
    # kept entries match to bf16 precision; everything else is zero
    np.testing.assert_allclose(rt.reshape(-1)[mask], flat[mask],
                               rtol=8e-3, atol=1e-6)
    np.testing.assert_array_equal(rt.reshape(-1)[~mask], 0.0)
    # ~density * bf16 payload: 6 bytes/entry vs 4 dense f32 bytes
    f32 = m.Tensor.from_array("g", arr).encode()
    assert len(t.encode()) < len(f32) * 0.2
    # degenerate cases: empty tensor and k rounding to >= 1
    empty = m.Tensor.from_array("e", np.zeros((0,), np.float32),
                                wire_dtype=m.WIRE_TOPK)
    assert m.Tensor.decode(empty.encode()).to_array().size == 0
    tiny = m.Tensor.from_array("t", np.ones(3, np.float32),
                               wire_dtype=m.WIRE_TOPK, topk_density=0.01)
    assert np.count_nonzero(
        m.Tensor.decode(tiny.encode()).to_array()) == 1
    # 0-d scalar: np.prod([]) == 1, so it round-trips as one element
    # (shape (1,) through packed encodings; .item() — float() on a
    # 1-element array is deprecated in NumPy 1.25+)
    s = m.Tensor.from_array("s", np.float32(3.5), wire_dtype=m.WIRE_TOPK)
    assert m.Tensor.decode(s.encode()).to_array().item() == 3.5
    # u32 index space: a >= 2**32-element tensor would wrap indices on
    # decode, so encode refuses loudly (zero-stride broadcast view: 4B
    # elements without the 16 GB allocation)
    big = np.broadcast_to(np.float32(1.0), (2**32,))
    with pytest.raises(ValueError, match="u32"):
        m.Tensor.from_array("g", big, wire_dtype=m.WIRE_TOPK)
    # density > 1 clamps k to the tensor size instead of corrupting
    over = m.Tensor.from_array("o", np.ones(10, np.float32),
                               wire_dtype=m.WIRE_TOPK, topk_density=2.0)
    np.testing.assert_array_equal(
        m.Tensor.decode(over.encode()).to_array(), np.ones(10, np.float32))
    # the density default has ONE owner shared by wire, config, and CLI
    from parameter_server_distributed_tpu.config import WorkerConfig
    assert WorkerConfig().topk_density == m.TOPK_DEFAULT_DENSITY


def test_float64_dtype_tag_roundtrip(rng):
    """The reference IDL declares dtype=1 float64 (proto:23) while carrying
    data as `repeated float`; from_array marks float64 inputs and to_array
    honors the tag by upcasting, so a dtype=1 tensor round-trips at the
    declared dtype instead of being silently retyped float32."""
    arr = rng.standard_normal((4, 3))  # float64
    t = m.Tensor.from_array("w", arr)
    assert t.dtype == m.DTYPE_FLOAT64
    rt = m.Tensor.decode(t.encode())
    out = rt.to_array()
    assert out.dtype == np.float64
    np.testing.assert_allclose(out, arr, rtol=1e-6)  # f32 wire precision
    # float32 input keeps dtype=0 and decodes float32
    t32 = m.Tensor.from_array("w", arr.astype(np.float32))
    assert t32.dtype == m.DTYPE_FLOAT32
    assert m.Tensor.decode(t32.encode()).to_array().dtype == np.float32


def test_raw_f32_decode_is_writable(rng):
    """Every decode path returns a writable array (frombuffer views are
    read-only; in-place aggregation must work on any encoding)."""
    arr = rng.standard_normal(32).astype(np.float32)
    for wd in (m.WIRE_F32, m.WIRE_RAW_F32, m.WIRE_BF16, m.WIRE_INT8,
               m.WIRE_TOPK):
        out = m.Tensor.decode(
            m.Tensor.from_array("w", arr, wire_dtype=wd).encode()).to_array()
        out += 1.0  # raises on read-only arrays


def test_lazy_array_payload_encodes_identically_to_eager_bytes(rng):
    """ArrayPayload (fused convert-into-buffer encode) must produce byte-
    identical messages to an eager astype+tobytes payload, and to_array on
    a locally built tensor must return the same quantized values a wire
    round-trip would."""
    from parameter_server_distributed_tpu.rpc.wire import ArrayPayload

    arr = rng.standard_normal((33, 17)).astype(np.float32)
    for wd, np_dtype in ((m.WIRE_BF16, None), (m.WIRE_RAW_F32, "<f4")):
        t = m.Tensor.from_array("w", arr, wire_dtype=wd)
        assert isinstance(t.packed, ArrayPayload)
        eager = m.Tensor(name="w", shape=list(arr.shape),
                         packed=t.packed.tobytes(), packed_dtype=wd)
        assert t.encode() == eager.encode()
        # local read-back equals the decoded wire value
        decoded = m.Tensor.decode(t.encode())
        np.testing.assert_array_equal(t.to_array(), decoded.to_array())


def test_writer_output_is_plain_bytes(rng):
    """encode() must hand gRPC a real `bytes` object (its cython layer
    rejects bytearray/memoryview), produced without a final whole-message
    copy (wire._Writer's uninitialized-bytes backing)."""
    t = m.Tensor.from_array("w", rng.standard_normal(257).astype(np.float32),
                            wire_dtype=m.WIRE_BF16)
    buf = m.GradientUpdate(worker_id=1, iteration=2, gradients=[t]).encode()
    assert type(buf) is bytes
    back = m.GradientUpdate.decode(buf)
    assert back.worker_id == 1 and back.gradients[0].name == "w"
