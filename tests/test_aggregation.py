"""Streaming incremental aggregation + encode-once broadcast serve.

Covers ISSUE 3: equivalence of the streaming fold-on-arrival data path
with the classic buffered mean (bit-for-bit in f32 on the numpy path),
the documented duplicate-push policies, the O(model)/1x-model close
properties, the apply-outside-the-lock aggregating phase, the
encoded-chunk broadcast cache (single-flight, invalidation on
apply/restore/initialize, mixed wire dtypes), and the barrier_width TTL
cache lock."""

import threading
import time

import numpy as np
import pytest

from parameter_server_distributed_tpu import native
from parameter_server_distributed_tpu.core.optimizer import SGD, Adam, Momentum
from parameter_server_distributed_tpu.core.ps_core import ParameterServerCore
from parameter_server_distributed_tpu.core.tensor import store_nbytes, to_wire
from parameter_server_distributed_tpu.obs import stats as obs_stats
from parameter_server_distributed_tpu.rpc import messages as m


def store(**kw):
    return {k: np.asarray(v, np.float32) for k, v in kw.items()}


@pytest.fixture
def numpy_only():
    """Pin the numpy aggregation path: the native kernels sum in a
    different association order, and the bit-for-bit equivalence contract
    is defined on the numpy semantics."""
    native.set_enabled(False)
    yield
    native.set_enabled(True)


def _random_grads(rng, shapes):
    return {name: rng.standard_normal(shape).astype(np.float32)
            for name, shape in shapes.items()}


# ------------------------------------------------------------- equivalence

@pytest.mark.parametrize("n_workers", [1, 2, 3, 5])
@pytest.mark.parametrize("make_opt", [lambda: SGD(1.0),
                                      lambda: Momentum(0.1, momentum=0.9),
                                      lambda: Adam(0.01)])
def test_streaming_matches_buffered_bit_for_bit(numpy_only, n_workers,
                                                make_opt):
    """The streaming accumulator must land EXACTLY the buffered
    contributor mean — same f32 sum order, same scale, same optimizer
    apply — across worker counts, optimizers, and several iterations."""
    rng = np.random.default_rng(42)
    shapes = {"w": (33, 7), "b": (11,), "scalar": ()}
    init = _random_grads(rng, shapes)
    cores = {mode: ParameterServerCore(total_workers=n_workers,
                                       optimizer=make_opt(),
                                       aggregation=mode)
             for mode in ("streaming", "buffered")}
    for core in cores.values():
        core.initialize_parameters(init)
    for it in range(1, 4):
        pushes = [_random_grads(rng, shapes) for _ in range(n_workers)]
        for mode, core in cores.items():
            for wid, grads in enumerate(pushes):
                r = core.receive_gradients(wid, it, grads)
            assert r.aggregation_complete
        a = cores["streaming"].get_parameters()
        b = cores["buffered"].get_parameters()
        for name in shapes:
            np.testing.assert_array_equal(a[name], b[name])


def test_streaming_matches_buffered_empty_store_bootstrap(numpy_only):
    """Bootstrap (first aggregated mean BECOMES the params) is identical
    in both modes."""
    for mode in ("streaming", "buffered"):
        ps = ParameterServerCore(total_workers=2, aggregation=mode)
        ps.receive_gradients(0, 0, store(w=[2.0, 4.0]))
        r = ps.receive_gradients(1, 0, store(w=[4.0, 8.0]))
        assert r.aggregation_complete
        np.testing.assert_array_equal(ps.get_parameters()["w"],
                                      np.asarray([3.0, 6.0], np.float32))


def test_streaming_matches_buffered_elastic_shrink(numpy_only):
    """A mid-iteration barrier shrink (worker evicted) releases a
    buffered iteration via the sync poll identically in both modes."""
    results = {}
    for mode in ("streaming", "buffered"):
        live = {"n": 3}
        ps = ParameterServerCore(total_workers=3, aggregation=mode,
                                 live_workers_fn=lambda: live["n"])
        ps.initialize_parameters(store(w=[0.0]))
        ps.receive_gradients(0, 1, store(w=[2.0]))
        ps.receive_gradients(1, 1, store(w=[4.0]))
        _, ready, _, _ = ps.check_sync_status(1)
        assert not ready
        live["n"] = 2  # worker 2 evicted
        _, ready, recv, total = ps.check_sync_status(1)
        assert ready and recv == 2 and total == 2
        results[mode] = ps.get_parameters()["w"]
    np.testing.assert_array_equal(results["streaming"], results["buffered"])
    np.testing.assert_allclose(results["streaming"], [-3.0])


def test_streaming_late_and_gcd_pushes_are_noops():
    ps = ParameterServerCore(total_workers=1, gc_iterations=4,
                             aggregation="streaming")
    ps.initialize_parameters(store(w=[0.0]))
    for it in range(10):
        ps.receive_gradients(0, it, store(w=[0.0]))
    before = ps.get_parameters()["w"].copy()
    late = ps.receive_gradients(1, 9, store(w=[500.0]))  # state still live
    assert late.success and late.aggregation_complete
    gcd = ps.receive_gradients(1, 1, store(w=[999.0]))   # state GC'd
    assert gcd.success and gcd.aggregation_complete
    np.testing.assert_array_equal(ps.get_parameters()["w"], before)
    _, ready, _, _ = ps.check_sync_status(1)
    assert ready


# -------------------------------------------------- chunked fold / dedup

def test_chunked_fold_equals_whole_push(numpy_only):
    """A push delivered as several chunks through begin_push lands exactly
    the state one whole-store receive_gradients lands."""
    whole = ParameterServerCore(total_workers=2, aggregation="streaming")
    chunked = ParameterServerCore(total_workers=2, aggregation="streaming")
    init = store(a=[1.0, 1.0], b=[2.0], c=[3.0])
    whole.initialize_parameters(init)
    chunked.initialize_parameters(init)
    g0 = store(a=[0.5, 0.5], b=[1.0], c=[2.0])
    g1 = store(a=[1.5, 1.5], b=[3.0], c=[4.0])

    whole.receive_gradients(0, 1, g0)
    r_whole = whole.receive_gradients(1, 1, g1)

    sink0 = chunked.begin_push(0, 1)
    sink0.fold({"a": g0["a"]})
    sink0.fold({"b": g0["b"], "c": g0["c"]})
    r0 = sink0.commit()
    assert r0.success and not r0.aggregation_complete
    sink1 = chunked.begin_push(1, 1)
    sink1.fold({"a": g1["a"], "b": g1["b"]})
    sink1.fold({"c": g1["c"]})
    r1 = sink1.commit()
    assert r1.aggregation_complete == r_whole.aggregation_complete is True
    for name in init:
        np.testing.assert_array_equal(whole.get_parameters()[name],
                                      chunked.get_parameters()[name])


def test_retry_replay_folds_each_tensor_once(numpy_only):
    """An RPC retry replays the SAME payload (worker/worker.py invariant);
    the per-(worker, tensor) dedup must fold each tensor exactly once, so
    a partially-landed push + full replay converges to one contribution."""
    ps = ParameterServerCore(total_workers=2, aggregation="streaming")
    ps.initialize_parameters(store(a=[0.0], b=[0.0]))
    # first attempt dies after chunk 1 (no commit)
    sink = ps.begin_push(0, 1)
    sink.fold({"a": np.asarray([2.0], np.float32)})
    # retry replays the full payload
    retry = ps.begin_push(0, 1)
    retry.fold({"a": np.asarray([2.0], np.float32)})
    retry.fold({"b": np.asarray([4.0], np.float32)})
    r = retry.commit()
    assert r.success and r.workers_received == 1
    ps.receive_gradients(1, 1, store(a=[4.0], b=[6.0]))
    p = ps.get_parameters()
    np.testing.assert_allclose(p["a"], [-3.0])  # mean(2,4), not mean(2,2,4)
    np.testing.assert_allclose(p["b"], [-5.0])


def test_streaming_duplicate_push_policy_and_message():
    ps = ParameterServerCore(total_workers=3, aggregation="streaming")
    ps.initialize_parameters(store(w=[0.0]))
    ps.receive_gradients(0, 1, store(w=[3.0]))
    dup = ps.receive_gradients(0, 1, store(w=[99.0]))
    assert dup.success and not dup.aggregation_complete
    assert dup.workers_received == 1
    assert "first-push-wins" in dup.message


# ------------------------------------------------- memory / close behavior

def test_streaming_peak_gradient_buffer_is_one_model():
    """N buffered pushes must cost ~1x model in streaming mode and N x
    model in buffered mode — the headline memory claim."""
    n = 6
    shapes = {"w": (256, 16), "b": (64,)}
    rng = np.random.default_rng(0)
    init = _random_grads(rng, shapes)
    model_bytes = store_nbytes(init)
    peaks = {}
    for mode in ("streaming", "buffered"):
        ps = ParameterServerCore(total_workers=n, aggregation=mode)
        ps.initialize_parameters(init)
        for wid in range(n):
            ps.receive_gradients(wid, 1, _random_grads(rng, shapes))
        assert ps.grad_buffer_bytes == 0  # released at close
        peaks[mode] = ps.peak_grad_buffer_bytes
    assert peaks["streaming"] == model_bytes
    assert peaks["buffered"] == n * model_bytes


class _SlowSGD(SGD):
    apply_delay_s = 0.25

    def apply(self, params, grads):
        time.sleep(self.apply_delay_s)
        return super().apply(params, grads)


@pytest.mark.lockcheck
def test_streaming_apply_runs_outside_state_lock():
    """While iteration N's barrier apply is in flight (the "aggregating"
    phase), a push for iteration N+1 and a sync poll must NOT block
    behind it."""
    ps = ParameterServerCore(total_workers=2, optimizer=_SlowSGD(1.0),
                             aggregation="streaming")
    ps.initialize_parameters(store(w=[10.0]))
    ps.receive_gradients(0, 1, store(w=[1.0]))

    def close_barrier():
        ps.receive_gradients(1, 1, store(w=[1.0]))

    closer = threading.Thread(target=close_barrier)
    closer.start()
    time.sleep(0.05)  # let the closer enter the slow apply
    t0 = time.perf_counter()
    r = ps.receive_gradients(0, 2, store(w=[1.0]))
    push_latency = time.perf_counter() - t0
    _, ready, _, _ = ps.check_sync_status(1)
    poll_latency = time.perf_counter() - t0
    closer.join(timeout=5.0)
    assert not closer.is_alive()
    assert r.success and not r.aggregation_complete
    # both returned well inside the 0.25 s apply window
    assert push_latency < 0.15, f"push blocked {push_latency:.3f}s"
    assert poll_latency < 0.2, f"poll blocked {poll_latency:.3f}s"
    # iteration 1 only reads ready once its apply has landed
    _, ready1, _, _ = ps.check_sync_status(1)
    assert ready1
    np.testing.assert_allclose(ps.get_parameters()["w"], [9.0])


@pytest.mark.lockcheck
def test_push_during_aggregating_window_reports_incomplete():
    """A commit that lands while the barrier close is mid-apply must not
    claim completion: the params are not applied yet, and the worker must
    learn readiness from the poll/CV path when it is real."""
    ps = ParameterServerCore(total_workers=1, optimizer=_SlowSGD(1.0),
                             aggregation="streaming")
    ps.initialize_parameters(store(w=[5.0]))

    def close_barrier():
        ps.receive_gradients(0, 1, store(w=[1.0]))

    closer = threading.Thread(target=close_barrier)
    closer.start()
    time.sleep(0.05)
    late = ps.receive_gradients(1, 1, store(w=[100.0]))
    closer.join(timeout=5.0)
    assert late.success and not late.aggregation_complete
    assert "in progress" in late.message
    # the late worker's payload did not contaminate the closed mean
    _, ready, _, _ = ps.check_sync_status(1)
    assert ready
    np.testing.assert_allclose(ps.get_parameters()["w"], [4.0])


class _FlakySGD(SGD):
    """Raises on the first apply, works afterwards."""

    def __init__(self, lr):
        super().__init__(lr)
        self.failures_left = 1

    def apply(self, params, grads):
        if self.failures_left:
            self.failures_left -= 1
            raise RuntimeError("injected apply failure")
        return super().apply(params, grads)


@pytest.mark.lockcheck
@pytest.mark.parametrize("mode", ["streaming", "buffered"])
def test_failed_barrier_apply_is_retryable(numpy_only, mode):
    """An optimizer apply that raises at barrier close must not wedge the
    iteration: the aggregating flag comes back down, the gradients (or
    the restored accumulator) stay in place, and the next sync poll
    re-fires the close and lands the exact mean."""
    ps = ParameterServerCore(total_workers=2, optimizer=_FlakySGD(1.0),
                             aggregation=mode)
    ps.initialize_parameters(store(w=[10.0]))
    ps.receive_gradients(0, 1, store(w=[1.0]))
    with pytest.raises(RuntimeError, match="injected"):
        ps.receive_gradients(1, 1, store(w=[3.0]))
    # A straggler arriving between failure and retry: streaming SEALED
    # the contributor set at the close attempt (the restored accumulator
    # holds already-scaled means, so mixing in raw gradients would be
    # wrong); buffered keeps whole per-worker buffers, so including the
    # straggler in the retried mean is the original valid semantics.
    straggler = ps.receive_gradients(2, 1, store(w=[5.0]))
    assert straggler.success
    if mode == "streaming":
        # the straggler is deferred to the poll path, which re-fires
        assert not straggler.aggregation_complete
        _, ready, recv, _ = ps.check_sync_status(1)
        assert ready and recv == 2
        np.testing.assert_allclose(ps.get_parameters()["w"], [8.0])  # 10-mean(1,3)
    else:
        # the straggler's own push re-fires the close and joins the mean
        assert straggler.aggregation_complete
        _, ready, recv, _ = ps.check_sync_status(1)
        assert ready and recv == 3
        np.testing.assert_allclose(ps.get_parameters()["w"], [7.0])  # 10-mean(1,3,5)


def test_failed_fold_is_not_marked_folded():
    """A chunk whose accumulate raises (shape mismatch vs the running
    accumulator) must NOT be recorded as folded: the worker's retry with
    a good payload still contributes instead of being dedup-dropped."""
    ps = ParameterServerCore(total_workers=2, aggregation="streaming")
    ps.initialize_parameters(store(w=[0.0, 0.0]))
    ps.receive_gradients(0, 1, store(w=[2.0, 2.0]))
    with pytest.raises(ValueError):
        ps.receive_gradients(1, 1, store(w=[1.0, 1.0, 1.0]))  # bad shape
    r = ps.receive_gradients(1, 1, store(w=[4.0, 4.0]))
    assert r.aggregation_complete and r.workers_received == 2
    np.testing.assert_allclose(ps.get_parameters()["w"], [-3.0, -3.0])


@pytest.mark.lockcheck
def test_gc_never_evicts_mid_close_iteration():
    """GC pressure during the off-lock close window must not evict the
    closing iteration's state: a replayed (response-lost) push would
    recreate it and fire a SECOND aggregation for the same iteration."""
    ps = ParameterServerCore(total_workers=2, gc_iterations=1,
                             optimizer=_SlowSGD(1.0),
                             aggregation="streaming")
    ps.initialize_parameters(store(w=[10.0]))
    ps.receive_gradients(0, 1, store(w=[1.0]))
    closer = threading.Thread(
        target=lambda: ps.receive_gradients(1, 1, store(w=[1.0])))
    closer.start()
    time.sleep(0.05)  # closer is inside the slow apply
    for it in (2, 3, 4):  # GC pressure while iteration 1 is mid-close
        ps.receive_gradients(0, it, store(w=[1.0]))
    # replayed pushes for the closing iteration (lost responses)
    ps.receive_gradients(0, 1, store(w=[1.0]))
    ps.receive_gradients(1, 1, store(w=[1.0]))
    closer.join(timeout=5.0)
    assert not closer.is_alive()
    _, ready, _, _ = ps.check_sync_status(1)
    assert ready
    # exactly ONE apply of iteration 1's mean — 10 - mean(1,1), not 8.0
    np.testing.assert_allclose(ps.get_parameters()["w"], [9.0])


@pytest.mark.lockcheck
def test_restore_during_streaming_close_wins():
    """A checkpoint restore that lands while a barrier apply is in flight
    must end with EXACTLY the restored state: no stale mean applied on
    top, no resurrected watermark, and the next barrier works."""
    ps = ParameterServerCore(total_workers=1, optimizer=_SlowSGD(1.0),
                             aggregation="streaming")
    ps.initialize_parameters(store(w=[10.0]))

    def close_barrier():
        ps.receive_gradients(0, 1, store(w=[1.0]))

    closer = threading.Thread(target=close_barrier)
    closer.start()
    time.sleep(0.05)  # closer is inside the slow apply
    ps.restore(epoch=0, iteration=0, params=store(w=[42.0]))
    closer.join(timeout=5.0)
    assert not closer.is_alive()
    np.testing.assert_allclose(ps.get_parameters()["w"], [42.0])
    # the restored world starts fresh: a new iteration-1 barrier closes
    r = ps.receive_gradients(0, 1, store(w=[2.0]))
    assert r.aggregation_complete
    np.testing.assert_allclose(ps.get_parameters()["w"], [40.0])


# --------------------------------------------------- barrier_width TTL lock

@pytest.mark.lockcheck
def test_barrier_width_ttl_refresh_is_single_flight():
    """Concurrent expiry must issue ONE provider call (the old unlocked
    cache issued one per racing thread and could publish torn pairs)."""
    calls = []
    barrier = threading.Barrier(6)

    def provider():
        calls.append(threading.get_ident())
        time.sleep(0.05)  # widen the race window
        return 3

    ps = ParameterServerCore(total_workers=5, live_workers_fn=provider,
                             live_workers_ttl_s=60.0)
    widths = []

    def read():
        barrier.wait()
        widths.append(ps.barrier_width())

    threads = [threading.Thread(target=read) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=5.0)
    assert widths == [3] * 6
    assert len(calls) == 1, f"{len(calls)} provider calls for one expiry"


# --------------------------------------------------- encode-once serve cache

def _make_service(core):
    import tempfile

    from parameter_server_distributed_tpu.checkpoint.manager import (
        CheckpointManager)
    from parameter_server_distributed_tpu.server.ps_service import (
        ParameterServerService)

    return ParameterServerService(core, CheckpointManager(
        core, directory=tempfile.mkdtemp(prefix="psdt-aggtest-"),
        checkpoint_interval=10**9, check_period_s=3600.0))


def _cache_counters():
    snap = obs_stats.REGISTRY.snapshot()["counters"]
    return (snap.get("ps.serve.cache_hit", 0),
            snap.get("ps.serve.cache_miss", 0))


def _decode_serve(service, iteration=0, wire_dtype=0):
    chunks = list(service._parameter_chunks(iteration, wire_dtype))
    tensors = []
    for chunk in chunks:
        decoded = m.ParameterUpdate.decode(chunk.encode())
        assert decoded.ready
        tensors.extend(decoded.parameters)
    return {t.name: t.to_array() for t in tensors}


def test_serve_cache_hits_and_invalidation_on_apply():
    rng = np.random.default_rng(1)
    params = {"w": rng.standard_normal((64, 8)).astype(np.float32)}
    core = ParameterServerCore(total_workers=1, aggregation="streaming")
    core.initialize_parameters(params)
    service = _make_service(core)

    h0, m0 = _cache_counters()
    first = _decode_serve(service)
    np.testing.assert_array_equal(first["w"], params["w"])
    for _ in range(3):
        _decode_serve(service)
    h1, m1 = _cache_counters()
    assert m1 - m0 == 1 and h1 - h0 == 3  # one encode, three replays

    # an aggregation apply bumps the store version -> cache invalidated
    core.receive_gradients(0, 1, {"w": np.ones_like(params["w"])})
    after = _decode_serve(service)
    h2, m2 = _cache_counters()
    assert m2 - m1 == 1
    np.testing.assert_allclose(after["w"], params["w"] - 1.0, rtol=1e-6)


def test_serve_cache_invalidation_on_initialize_and_restore():
    core = ParameterServerCore(total_workers=1)
    core.initialize_parameters(store(w=[1.0, 2.0]))
    service = _make_service(core)
    np.testing.assert_allclose(_decode_serve(service)["w"], [1.0, 2.0])

    core.initialize_parameters(store(w=[7.0, 8.0]))
    np.testing.assert_allclose(_decode_serve(service)["w"], [7.0, 8.0])

    core.restore(epoch=3, iteration=5, params=store(w=[-1.0, -2.0]))
    h0, m0 = _cache_counters()
    np.testing.assert_allclose(_decode_serve(service)["w"], [-1.0, -2.0])
    np.testing.assert_allclose(_decode_serve(service)["w"], [-1.0, -2.0])
    h1, m1 = _cache_counters()
    assert m1 - m0 == 1 and h1 - h0 == 1


def test_serve_cache_keys_on_wire_dtype():
    rng = np.random.default_rng(2)
    w = rng.standard_normal(512).astype(np.float32)
    core = ParameterServerCore(total_workers=1)
    core.initialize_parameters({"w": w})
    service = _make_service(core)
    h0, m0 = _cache_counters()
    f32 = _decode_serve(service, wire_dtype=m.WIRE_F32)
    bf16 = _decode_serve(service, wire_dtype=m.WIRE_BF16)
    _decode_serve(service, wire_dtype=m.WIRE_F32)
    _decode_serve(service, wire_dtype=m.WIRE_BF16)
    # lossy pull requests serve bf16 (the serve guard) and share its entry
    topk = _decode_serve(service, wire_dtype=m.WIRE_TOPK)
    h1, m1 = _cache_counters()
    assert m1 - m0 == 2 and h1 - h0 == 3
    np.testing.assert_array_equal(f32["w"], w)
    np.testing.assert_allclose(bf16["w"], w, rtol=8e-3)
    np.testing.assert_array_equal(topk["w"], bf16["w"])


def test_serve_cache_fill_never_resurrects_superseded_version():
    """A builder whose encode landed on a version the cache has already
    moved past must not re-register its (dead) bytes; and a stale probe
    must not evict a newer version's entry (versions are monotone)."""
    from parameter_server_distributed_tpu.server.ps_service import (
        EncodedServeCache)

    cache = EncodedServeCache()
    e1, b1 = cache.lookup((1, 0, 32))
    assert b1
    e3, b3 = cache.lookup((3, 0, 32))  # newer version: v1 entry evicted
    assert b3
    cache.fill((3, 0, 32), e3, [b"v3"], 3)
    # a probe that read version 2 BEFORE the v3 serve registered arrives
    # late: it must not evict the newer entry
    cache.lookup((2, 0, 32))
    assert (3, 0, 32) in cache._entries
    # the v1 builder's encode actually captured v2 — superseded by v3, so
    # fill must NOT re-register its dead bytes
    cache.fill((1, 0, 32), e1, [b"v2"], 2)
    assert (2, 0, 32) not in [k for k in cache._entries
                              if cache._entries[k] is e1]
    assert e1.event.is_set()  # its own waiters still get served
    entry, builder = cache.lookup((3, 0, 32))
    assert not builder and entry.bodies == [b"v3"]


def test_serve_cache_empty_store_single_empty_chunk():
    core = ParameterServerCore(total_workers=1)
    service = _make_service(core)
    chunks = list(service._parameter_chunks(0, 0))
    assert len(chunks) == 1
    decoded = m.ParameterUpdate.decode(chunks[0].encode())
    assert decoded.ready and not decoded.parameters


def test_preencoded_parameter_update_is_byte_identical():
    """The cache's replayed message must encode byte-identically to the
    plain ParameterUpdate a reference-shaped peer expects."""
    from parameter_server_distributed_tpu.rpc.data_plane import (
        PreEncodedParameterUpdate, encode_parameter_records)

    rng = np.random.default_rng(3)
    tensors = to_wire({"a": rng.standard_normal((5, 3)).astype(np.float32),
                       "b": rng.standard_normal(7).astype(np.float32)})
    plain = m.ParameterUpdate(iteration=9, parameters=tensors,
                              ready=True).encode()
    pre = PreEncodedParameterUpdate(
        9, True, [encode_parameter_records(tensors)]).encode()
    assert plain == pre
    # default elision: iteration 0 / ready False elide exactly alike
    assert (m.ParameterUpdate(iteration=0, parameters=tensors,
                              ready=False).encode()
            == PreEncodedParameterUpdate(
                0, False, [encode_parameter_records(tensors)]).encode())


def test_fanout_runs_one_encode_per_version_and_dtype(tmp_path):
    """Acceptance: N in-process workers' post-barrier fan-out performs
    exactly ONE to_wire encode per (params version, wire dtype), verified
    by the cache counters — the other N-1 serves replay cached bytes."""
    from parameter_server_distributed_tpu.config import ParameterServerConfig
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient
    from parameter_server_distributed_tpu.server.ps_service import (
        ParameterServer)

    n = 4
    server = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=n,
        checkpoint_interval=100, checkpoint_dir=str(tmp_path),
        learning_rate=1.0, autosave_period_s=600.0))
    port = server.start()
    w0 = np.linspace(-1, 1, 2048).astype(np.float32)
    server.core.initialize_parameters({"w": w0})
    results = {}

    def worker(wid):
        with PSClient(f"127.0.0.1:{port}") as client:
            grads = [m.Tensor.from_array("w", np.full_like(w0, 0.5))]
            results[wid] = client.push_pull(wid, 1, grads)

    try:
        h0, m0 = _cache_counters()
        threads = [threading.Thread(target=worker, args=(wid,))
                   for wid in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert all(not t.is_alive() for t in threads)
        h1, m1 = _cache_counters()
        assert m1 - m0 == 1, f"{m1 - m0} encodes for the fan-out"
        assert h1 - h0 == n - 1
        for wid in range(n):
            push, params = results[wid]
            assert push.success and params is not None and params.ready
            np.testing.assert_allclose(params.parameters[0].to_array(),
                                       w0 - 0.5, rtol=1e-6)
    finally:
        server.stop()


# ------------------------------------- reference-shaped client equivalence

@pytest.mark.parametrize("mode", ["streaming", "buffered"])
def test_reference_shaped_unary_client_trains_identically(tmp_path, mode,
                                                          numpy_only):
    """A reference-shaped client (the 5 unary RPCs, repeated-float
    payloads, poll loop) must train to the same parameters in both
    aggregation modes."""
    from parameter_server_distributed_tpu.config import ParameterServerConfig
    from parameter_server_distributed_tpu.rpc.service import RpcClient
    from parameter_server_distributed_tpu.server.ps_service import (
        ParameterServer)

    server = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=2,
        checkpoint_interval=100, checkpoint_dir=str(tmp_path),
        learning_rate=1.0, autosave_period_s=600.0, aggregation=mode))
    port = server.start()
    rng = np.random.default_rng(7)
    w0 = rng.standard_normal(128).astype(np.float32)
    server.core.initialize_parameters({"w": w0})
    expected = w0.copy()
    try:
        with RpcClient(f"127.0.0.1:{port}", m.PARAMETER_SERVER_SERVICE,
                       m.PARAMETER_SERVER_METHODS) as client:
            for it in (1, 2, 3):
                grads = [rng.standard_normal(128).astype(np.float32)
                         for _ in range(2)]
                for wid in (0, 1):
                    push = client.call("ReceiveGradients", m.GradientUpdate(
                        worker_id=wid, iteration=it,
                        gradients=[m.Tensor.from_array("w", grads[wid])]))
                    assert push.success
                assert push.aggregation_complete
                sync = client.call("CheckSyncStatus",
                                   m.SyncStatusRequest(iteration=it))
                assert sync.ready
                expected = expected - (grads[0] + grads[1]) * np.float32(0.5)
                pulled = client.call("ServeParameters",
                                     m.PullRequest(worker_id=0, iteration=it))
                np.testing.assert_array_equal(
                    pulled.parameters[0].to_array(), expected)
    finally:
        server.stop()
