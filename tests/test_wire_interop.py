"""Wire interop against protoc gencode of the REFERENCE IDL.

This is the proof that "wire-compatible with the reference" holds: the
reference's contract is its compiled proto gencode
(reference: proto/parameter_server.proto, proto/coordinator.proto, compiled
at CMakeLists.txt:87-113).  Here the same .proto files are compiled with
the system protoc into Python gencode (google.protobuf runtime) and every
message is round-tripped BOTH directions against rpc/messages.py:

- our encode() -> gencode ParseFromString: a reference C++ peer parses our
  bytes and sees the same field values;
- gencode SerializeToString() -> our decode(): we parse bytes produced by a
  reference peer;
- packed AND unpacked encodings of repeated scalars (proto3 decoders must
  accept both);
- unknown-field skipping: our Tensor extension fields 5/6 are skipped by
  the reference gencode (which predates them), and its re-serialized
  unknown fields survive a round-trip.

Skips cleanly when `protoc` or the protobuf runtime is unavailable.
"""

from __future__ import annotations

import importlib
import shutil
import subprocess
import sys

import numpy as np
import pytest

from parameter_server_distributed_tpu.rpc import messages as m

pytest.importorskip("google.protobuf")

REFERENCE_PROTO_DIR = "/root/reference/proto"


@pytest.fixture(scope="module")
def gencode(tmp_path_factory):
    """Compile the reference .proto files with protoc; returns the two
    generated modules (parameter_server_pb2, coordinator_pb2).

    Where the reference checkout is absent (public CI), the IDL emitted
    from our own declarative schemas (rpc/idl.py) is compiled instead —
    still a real cross-check of the hand-rolled codec against protoc's
    encoder/decoder for the same schema."""
    protoc = shutil.which("protoc")
    if protoc is None:
        pytest.skip("protoc not available")
    import os
    out = tmp_path_factory.mktemp("gencode")
    if os.path.isdir(REFERENCE_PROTO_DIR):
        for name in ("parameter_server.proto", "coordinator.proto"):
            shutil.copy(f"{REFERENCE_PROTO_DIR}/{name}", out / name)
    else:
        from parameter_server_distributed_tpu.rpc import idl
        idl.write_protos(str(out))
    subprocess.run(
        [protoc, f"--python_out={out}", "parameter_server.proto",
         "coordinator.proto"],
        cwd=out, check=True, capture_output=True)
    sys.path.insert(0, str(out))
    try:
        ps_pb2 = importlib.import_module("parameter_server_pb2")
        c_pb2 = importlib.import_module("coordinator_pb2")
    finally:
        sys.path.remove(str(out))
    return ps_pb2, c_pb2


def _ours_to_theirs(ours: m.Message, pb_cls):
    pb = pb_cls()
    pb.ParseFromString(ours.encode())
    return pb


def _theirs_to_ours(pb, our_cls):
    return our_cls.decode(pb.SerializeToString())


# --------------------------------------------------------------------- Tensor

def test_tensor_roundtrip_both_directions(gencode, rng):
    ps_pb2, _ = gencode
    arr = rng.standard_normal((3, 4)).astype(np.float32)
    ours = m.Tensor.from_array("layer0/w", arr)

    pb = _ours_to_theirs(ours, ps_pb2.Tensor)
    assert pb.name == "layer0/w"
    assert list(pb.shape) == [3, 4]
    assert pb.dtype == 0
    np.testing.assert_array_equal(np.asarray(pb.data, np.float32),
                                  arr.reshape(-1))

    back = _theirs_to_ours(pb, m.Tensor)
    np.testing.assert_array_equal(back.to_array(), arr)
    assert back.name == ours.name


def test_tensor_byte_identical_encoding(gencode, rng):
    """Field-ordered proto3 encoding should be byte-identical, not just
    semantically equal (gencode serializes fields in number order, as we
    do)."""
    ps_pb2, _ = gencode
    arr = rng.standard_normal(17).astype(np.float32)
    ours = m.Tensor.from_array("t", arr)
    pb = ps_pb2.Tensor(name="t", shape=[17], data=arr.tolist(), dtype=0)
    assert ours.encode() == pb.SerializeToString()


def test_tensor_unpacked_repeated_float_decodes(gencode, rng):
    """proto3 decoders must accept the UNPACKED encoding of a packed field
    (one FIXED32 record per element, as proto2 C++ peers emit)."""
    from parameter_server_distributed_tpu.rpc import wire

    values = rng.standard_normal(5).astype(np.float32)
    buf = bytearray()
    buf += wire.encode_varint((1 << 3) | wire.WT_LEN) + b"\x01t"  # name="t"
    for v in values:  # field 3, one fixed32 record each
        buf += wire.encode_varint((3 << 3) | wire.WT_FIXED32)
        buf += np.float32(v).tobytes()
    ours = m.Tensor.decode(bytes(buf))
    np.testing.assert_array_equal(ours.to_array(), values)
    # the gencode accepts the same unpacked bytes
    ps_pb2, _ = gencode
    pb = ps_pb2.Tensor()
    pb.ParseFromString(bytes(buf))
    np.testing.assert_array_equal(np.asarray(pb.data, np.float32), values)


def test_tensor_unpacked_repeated_int32_shape(gencode):
    """Same unpacked-acceptance rule for the int32 shape field."""
    from parameter_server_distributed_tpu.rpc import wire

    buf = bytearray()
    for dim in (6, 7):
        buf += wire.encode_varint((2 << 3) | wire.WT_VARINT)
        buf += wire.encode_varint(dim)
    ours = m.Tensor.decode(bytes(buf))
    assert list(ours.shape) == [6, 7]


def test_extension_fields_skipped_by_reference_gencode(gencode, rng):
    """Our packed bf16 extension (fields 5/6) must be invisible to a
    reference peer: gencode parses the bytes, sees fields 1-4 defaults, and
    raises no error — exactly proto3 unknown-field skipping."""
    ps_pb2, _ = gencode
    arr = rng.standard_normal(8).astype(np.float32)
    ours = m.Tensor.from_array("q", arr, wire_dtype=m.WIRE_BF16)
    assert ours.packed  # extension payload present, field 3 empty

    pb = ps_pb2.Tensor()
    pb.ParseFromString(ours.encode())  # must not raise
    assert pb.name == "q"
    assert list(pb.shape) == [8]
    assert len(pb.data) == 0  # payload rode the unknown fields

    # protobuf preserves unknown fields on re-serialize: decoding the
    # gencode's bytes with OUR codec recovers the packed payload.
    back = m.Tensor.decode(pb.SerializeToString())
    assert back.packed_dtype == m.WIRE_BF16
    np.testing.assert_allclose(back.to_array(), arr, rtol=1e-2, atol=1e-2)


# ----------------------------------------------------------- full message set

def _compare_fields(ours: m.Message, pb) -> None:
    for f in ours.FIELDS:
        our_val = getattr(ours, f.name)
        if f.name not in pb.DESCRIPTOR.fields_by_name:
            # framework extension field (e.g. PullRequest.wire_dtype) — the
            # reference peer doesn't know it; it must be at its default so
            # nothing rides the wire in this reference-facing exchange
            assert not our_val, f"extension field {f.name} set in interop case"
            continue
        pb_val = getattr(pb, f.name)
        if f.kind == "message" and f.repeated:
            assert len(our_val) == len(pb_val)
        elif f.kind == "float" and f.repeated:
            np.testing.assert_array_equal(
                np.asarray(our_val, np.float32),
                np.asarray(pb_val, np.float32))
        elif f.kind in ("bytes",):
            assert bytes(our_val) == bytes(pb_val)
        elif f.repeated:
            assert list(our_val) == list(pb_val)
        else:
            assert our_val == pb_val


def _cases(ps_pb2, c_pb2, rng):
    tensors = [m.Tensor.from_array(f"t{i}",
                                   rng.standard_normal((2, 3)).astype(np.float32))
               for i in range(2)]
    return [
        (m.GradientUpdate(worker_id=3, iteration=17, gradients=tensors),
         ps_pb2.GradientUpdate),
        (m.PushResponse(success=True, message="ok", iteration=17,
                        aggregation_complete=True, workers_received=2,
                        total_workers=4),
         ps_pb2.PushResponse),
        (m.PullRequest(worker_id=1, iteration=9), ps_pb2.PullRequest),
        (m.ParameterUpdate(iteration=9, parameters=tensors, ready=True),
         ps_pb2.ParameterUpdate),
        (m.SyncStatusRequest(iteration=5), ps_pb2.SyncStatusRequest),
        (m.SyncStatusResponse(iteration=5, ready=False, workers_received=1,
                              total_workers=2),
         ps_pb2.SyncStatusResponse),
        (m.SaveCheckpointRequest(epoch=2, path="/tmp/x.ckpt"),
         ps_pb2.SaveCheckpointRequest),
        (m.SaveCheckpointResponse(success=True, message="saved",
                                  checkpoint_path="/tmp/x.ckpt"),
         ps_pb2.SaveCheckpointResponse),
        (m.LoadCheckpointRequest(path="/tmp/x.ckpt"),
         ps_pb2.LoadCheckpointRequest),
        (m.LoadCheckpointResponse(success=True, message="loaded", epoch=2,
                                  parameters=tensors),
         ps_pb2.LoadCheckpointResponse),
        (m.WorkerInfo(worker_id=7, address="10.0.0.2", port=50070,
                      hostname="worker-7"),
         c_pb2.WorkerInfo),
        (m.RegisterResponse(success=True, message="registered",
                            parameter_server_address="10.0.0.1:50051",
                            total_workers=8),
         c_pb2.RegisterResponse),
        (m.HeartbeatRequest(worker_id=7, status=m.WorkerStatus.TRAINING),
         c_pb2.HeartbeatRequest),
        (m.HeartbeatResponse(success=True, timestamp=1722300000123),
         c_pb2.HeartbeatResponse),
        (m.ListWorkersRequest(), c_pb2.ListWorkersRequest),
        (m.ListWorkersResponse(
            workers=[m.WorkerInfo(worker_id=1, address="a", port=2,
                                  hostname="h")],
            total_workers=1),
         c_pb2.ListWorkersResponse),
        (m.GetPSAddressRequest(), c_pb2.GetPSAddressRequest),
        (m.GetPSAddressResponse(address="10.0.0.1", port=50051),
         c_pb2.GetPSAddressResponse),
    ]


def test_every_message_roundtrips_both_directions(gencode, rng):
    """All 18 messages of both services: ours->gencode and gencode->ours,
    field-by-field equality, plus byte-identical encodings."""
    ps_pb2, c_pb2 = gencode
    for ours, pb_cls in _cases(ps_pb2, c_pb2, rng):
        pb = _ours_to_theirs(ours, pb_cls)
        _compare_fields(ours, pb)
        back = _theirs_to_ours(pb, type(ours))
        assert ours.encode() == pb.SerializeToString() == back.encode(), (
            f"{type(ours).__name__} encoding differs from gencode")


def test_enum_values_match_reference(gencode):
    _, c_pb2 = gencode
    for name in ("IDLE", "TRAINING", "CHECKPOINTING", "ERROR"):
        assert getattr(m.WorkerStatus, name) == c_pb2.WorkerStatus.Value(name)


def test_service_and_method_names_match_reference(gencode):
    """gRPC paths are /<package>.<Service>/<Method>; both services' names
    and full method lists must equal the reference IDL's."""
    ps_pb2, c_pb2 = gencode
    ps_svc = ps_pb2.DESCRIPTOR.services_by_name["ParameterServer"]
    assert m.PARAMETER_SERVER_SERVICE == ps_svc.full_name
    assert set(m.PARAMETER_SERVER_METHODS) == {
        meth.name for meth in ps_svc.methods}
    c_svc = c_pb2.DESCRIPTOR.services_by_name["Coordinator"]
    assert m.COORDINATOR_SERVICE == c_svc.full_name
    assert set(m.COORDINATOR_METHODS) == {meth.name for meth in c_svc.methods}
    # request/response types per method match as well
    for meth in ps_svc.methods:
        req_cls, resp_cls = m.PARAMETER_SERVER_METHODS[meth.name]
        assert req_cls.__name__ == meth.input_type.name
        assert resp_cls.__name__ == meth.output_type.name
    for meth in c_svc.methods:
        req_cls, resp_cls = m.COORDINATOR_METHODS[meth.name]
        assert req_cls.__name__ == meth.input_type.name
        assert resp_cls.__name__ == meth.output_type.name


def test_emitted_idl_matches_reference_descriptors(tmp_path):
    """rpc/idl.py's emitted .proto files, protoc-compiled, must describe
    the same wire contract as the reference IDL: every reference message's
    fields (number, proto type, label) are present and identical in the
    emitted schema.  This is what licenses the CI fallback that interop-
    tests against the emitted IDL when the reference checkout is absent."""
    import os

    protoc = shutil.which("protoc")
    if protoc is None:
        pytest.skip("protoc not available")
    if not os.path.isdir(REFERENCE_PROTO_DIR):
        pytest.skip("reference proto files not available")
    from parameter_server_distributed_tpu.rpc import idl

    emitted_src = tmp_path / "emitted"
    idl.write_protos(str(emitted_src))
    # descriptor_pb2-level comparison avoids the duplicate-registration
    # problem entirely: parse the FileDescriptorProto text protoc makes
    out = subprocess.run(
        [protoc, "-o", "/dev/stdout", "--include_imports",
         "parameter_server.proto", "coordinator.proto"],
        cwd=REFERENCE_PROTO_DIR, check=True, capture_output=True)
    ref_fds = out.stdout
    out = subprocess.run(
        [protoc, "-o", "/dev/stdout", "--include_imports",
         "parameter_server.proto", "coordinator.proto"],
        cwd=emitted_src, check=True, capture_output=True)
    our_fds = out.stdout

    from google.protobuf import descriptor_pb2

    def field_map(fds_bytes):
        fds = descriptor_pb2.FileDescriptorSet()
        fds.MergeFromString(fds_bytes)
        fields = {}
        for f in fds.file:
            for msg in f.message_type:
                for fld in msg.field:
                    fields[(f.package, msg.name, fld.number)] = (
                        fld.name, fld.type, fld.label)
        return fields

    ref_fields = field_map(ref_fds)
    our_fields = field_map(our_fds)
    for key, val in ref_fields.items():
        assert key in our_fields, f"reference field missing: {key} {val}"
        assert our_fields[key] == val, (
            f"field mismatch at {key}: ref={val} ours={our_fields[key]}")
    extras = set(our_fields) - set(ref_fields)
    # only the documented framework extensions may exceed the reference
    assert extras == {("parameter_server", "Tensor", 5),
                      ("parameter_server", "Tensor", 6),
                      ("parameter_server", "PullRequest", 3),
                      # fused data-plane extension: the wire encoding the
                      # pushing worker wants parameters streamed back in
                      # (read only by PushPullStream — rpc/data_plane.py)
                      ("parameter_server", "GradientUpdate", 4),
                      ("coordinator", "GetPSAddressResponse", 3),
                      # observability extensions (obs/): trace context on
                      # the traced request path, metric snapshots on
                      # heartbeats — field 999, skipped by reference peers
                      ("parameter_server", "GradientUpdate", 999),
                      ("parameter_server", "PullRequest", 999),
                      ("parameter_server", "SyncStatusRequest", 999),
                      ("coordinator", "HeartbeatRequest", 999)}, extras


def test_psclient_interoperates_with_gencode_server(gencode):
    """END-TO-END against a reference-shaped SERVER: a live gRPC service
    whose (de)serializers are the protoc gencode of the reference IDL —
    only unary RPCs exist (the 3 data-plane ones are implemented here;
    checkpoint RPCs are omitted as irrelevant to this path) and fields
    beyond the reference's are invisible.  Our PSClient must (a) fall back from the chunk-stream
    extension on UNIMPLEMENTED, (b) push/pull real values through the
    reference wire format, (c) observe reference aggregation semantics."""
    import concurrent.futures

    import grpc

    ps_pb2, _ = gencode
    store = {"w": np.array([1.0, 2.0, 3.0], np.float32)}
    iteration = {"n": 0}

    class GencodeService:
        """Minimal reference-semantics PS speaking pure gencode types."""

        def ReceiveGradients(self, request, context):
            iteration["n"] = max(iteration["n"], request.iteration)
            for t in request.gradients:
                grad = np.asarray(t.data, np.float32).reshape(list(t.shape))
                store[t.name] = store[t.name] - grad  # lr=1.0, 1 worker
            return ps_pb2.PushResponse(
                success=True, message="ok", iteration=iteration["n"],
                aggregation_complete=True, workers_received=1,
                total_workers=1)

        def ServeParameters(self, request, context):
            resp = ps_pb2.ParameterUpdate(iteration=iteration["n"],
                                          ready=True)
            for name, value in store.items():
                t = resp.parameters.add()
                t.name = name
                t.shape.extend(value.shape)
                t.data.extend(value.reshape(-1).tolist())
            return resp

        def CheckSyncStatus(self, request, context):
            return ps_pb2.SyncStatusResponse(
                iteration=request.iteration, ready=True,
                workers_received=1, total_workers=1)

    svc = GencodeService()
    handlers = {
        "ReceiveGradients": grpc.unary_unary_rpc_method_handler(
            svc.ReceiveGradients,
            request_deserializer=ps_pb2.GradientUpdate.FromString,
            response_serializer=ps_pb2.PushResponse.SerializeToString),
        "ServeParameters": grpc.unary_unary_rpc_method_handler(
            svc.ServeParameters,
            request_deserializer=ps_pb2.PullRequest.FromString,
            response_serializer=ps_pb2.ParameterUpdate.SerializeToString),
        "CheckSyncStatus": grpc.unary_unary_rpc_method_handler(
            svc.CheckSyncStatus,
            request_deserializer=ps_pb2.SyncStatusRequest.FromString,
            response_serializer=ps_pb2.SyncStatusResponse.SerializeToString),
    }
    server = grpc.server(concurrent.futures.ThreadPoolExecutor(max_workers=2))
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(
            m.PARAMETER_SERVER_SERVICE, handlers),))
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        from parameter_server_distributed_tpu.rpc.data_plane import PSClient

        with PSClient(f"127.0.0.1:{port}") as client:
            pulled = client.pull_parameters(m.PullRequest(worker_id=0,
                                                          iteration=0))
            assert client._stream_ok is False  # fell back to unary
            np.testing.assert_allclose(pulled.parameters[0].to_array(),
                                       [1.0, 2.0, 3.0])
            push = client.push_gradients(m.GradientUpdate(
                worker_id=0, iteration=1,
                gradients=[m.Tensor.from_array(
                    "w", np.array([0.5, 0.5, 0.5], np.float32))]))
            assert push.success and push.aggregation_complete
            after = client.pull_parameters(m.PullRequest(worker_id=0,
                                                         iteration=1))
            np.testing.assert_allclose(after.parameters[0].to_array(),
                                       [0.5, 1.5, 2.5])
            assert after.iteration == 1
            # (d) the FUSED round also degrades: push_pull falls back to
            # the unary push (params None — caller polls + pulls), the
            # payload crosses the reference wire format intact, and the
            # fallback is remembered per connection
            push, params = client.push_pull(
                0, 2, [m.Tensor.from_array(
                    "w", np.array([0.25, 0.25, 0.25], np.float32))])
            assert push.success and params is None
            assert client._fused_ok is False
            sync = client.call("CheckSyncStatus",
                               m.SyncStatusRequest(iteration=2))
            assert sync.ready  # the poll leg of the degraded round
            after2 = client.pull_parameters(m.PullRequest(worker_id=0,
                                                          iteration=2))
            np.testing.assert_allclose(after2.parameters[0].to_array(),
                                       [0.25, 1.25, 2.25])
    finally:
        server.stop(0)
