"""End-to-end training: coordinator + PS + 2 workers over real gRPC,
with real jitted gradients (the reference's only test is the localhost
multi-process smoke script, scripts/test_local.sh — this is its in-process
analogue plus actual learning-signal assertions)."""

import threading

import numpy as np
import pytest

from parameter_server_distributed_tpu.config import (CoordinatorConfig,
                                                     ParameterServerConfig,
                                                     WorkerConfig)
from parameter_server_distributed_tpu.cli.worker_main import build_worker
from parameter_server_distributed_tpu.server.coordinator_service import Coordinator
from parameter_server_distributed_tpu.server.ps_service import ParameterServer


@pytest.fixture
def cluster(tmp_path):
    ps = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=2,
        checkpoint_interval=2, checkpoint_dir=str(tmp_path),
        learning_rate=0.05, autosave_period_s=600.0))
    ps_port = ps.start()
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0,
        ps_address="127.0.0.1", ps_port=ps_port, reap_period_s=600.0))
    coord_port = coordinator.start()
    yield ps, coordinator, coord_port, tmp_path
    coordinator.stop()
    ps.stop()


def make_worker(coord_port, worker_id, iterations=6):
    config = WorkerConfig(
        coordinator_address=f"127.0.0.1:{coord_port}",
        worker_id=worker_id, iterations=iterations,
        address="127.0.0.1", port=50060 + worker_id,
        batch_size=16, model="mnist_mlp",
        heartbeat_period_s=1.0)
    return build_worker(config)


def run_workers(workers, iterations):
    """Drive N workers in lockstep threads (the barrier synchronizes them)."""
    losses = {w.config.worker_id: [] for w in workers}
    errors = []

    def loop(worker):
        try:
            for it in range(iterations):
                losses[worker.config.worker_id].append(worker.run_iteration(it))
        except Exception as exc:  # noqa: BLE001
            errors.append((worker.config.worker_id, exc))

    threads = [threading.Thread(target=loop, args=(w,)) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, f"worker failures: {errors}"
    return losses


def test_two_worker_sync_training_loss_decreases(cluster):
    ps, coordinator, coord_port, tmp_path = cluster
    workers = [make_worker(coord_port, 0), make_worker(coord_port, 1)]
    for w in workers:
        w.initialize()
    assert coordinator.core.live_worker_count() == 2
    try:
        losses = run_workers(workers, 8)
    finally:
        for w in workers:
            w.shutdown()
    # iteration 0 is the bootstrap (nan); real losses from iteration 1 on
    for wid, history in losses.items():
        assert len(history) == 8
        real = history[1:]
        assert not np.isnan(real).any()
        # learning signal: mean of last 3 < first loss
        assert np.mean(real[-3:]) < real[0], f"worker {wid}: {real}"
    assert ps.core.current_iteration == 7


def test_autosave_and_rpc_restore_roundtrip(cluster):
    ps, coordinator, coord_port, tmp_path = cluster
    worker = make_worker(coord_port, 0)
    # shrink barrier to 1 for a single-worker run (elastic-style)
    ps.core.set_total_workers(1)
    worker.initialize()
    try:
        for it in range(5):
            worker.run_iteration(it)
        # epoch = 4 // 2 = 2 -> autosave writes checkpoint_epoch_2.ckpt
        path = ps.ckpt.maybe_autosave()
        assert path is not None and path.endswith("checkpoint_epoch_2.ckpt")
        before = ps.core.get_parameters()
        # keep training, then restore via the worker-facing RPC
        for it in range(5, 7):
            worker.run_iteration(it)
        after = ps.core.get_parameters()
        assert any(not np.array_equal(before[k], after[k]) for k in before)
        assert worker.load_checkpoint_from_server(path)
        restored = ps.core.get_parameters()
        for k in before:
            np.testing.assert_array_equal(restored[k], before[k])
    finally:
        worker.shutdown()


def test_worker_reconnect_after_coordinator_restart(cluster):
    ps, coordinator, coord_port, tmp_path = cluster
    worker = make_worker(coord_port, 0)
    worker.initialize()
    try:
        # coordinator forgets the worker (simulates eviction); re-register
        evicted = coordinator.core.remove_stale_workers(timeout_s=-1)
        assert evicted == [0]
        worker.reconnect()
        assert coordinator.core.live_worker_count() == 1
    finally:
        worker.shutdown()
