"""End-to-end training: coordinator + PS + 2 workers over real gRPC,
with real jitted gradients (the reference's only test is the localhost
multi-process smoke script, scripts/test_local.sh — this is its in-process
analogue plus actual learning-signal assertions)."""

import threading

import numpy as np
import pytest

from parameter_server_distributed_tpu.config import (CoordinatorConfig,
                                                     ParameterServerConfig,
                                                     WorkerConfig)
from parameter_server_distributed_tpu.cli.worker_main import build_worker
from parameter_server_distributed_tpu.server.coordinator_service import Coordinator
from parameter_server_distributed_tpu.server.ps_service import ParameterServer


@pytest.fixture
def cluster(tmp_path):
    ps = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=2,
        checkpoint_interval=2, checkpoint_dir=str(tmp_path),
        learning_rate=0.05, autosave_period_s=600.0))
    ps_port = ps.start()
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0,
        ps_address="127.0.0.1", ps_port=ps_port, reap_period_s=600.0))
    coord_port = coordinator.start()
    yield ps, coordinator, coord_port, tmp_path
    coordinator.stop()
    ps.stop()


def make_worker(coord_port, worker_id, iterations=6):
    config = WorkerConfig(
        coordinator_address=f"127.0.0.1:{coord_port}",
        worker_id=worker_id, iterations=iterations,
        address="127.0.0.1", port=50060 + worker_id,
        batch_size=16, model="mnist_mlp",
        heartbeat_period_s=1.0)
    return build_worker(config)


def run_workers(workers, iterations):
    """Drive N workers in lockstep threads (the barrier synchronizes them)."""
    losses = {w.config.worker_id: [] for w in workers}
    errors = []

    def loop(worker):
        try:
            for it in range(iterations):
                losses[worker.config.worker_id].append(worker.run_iteration(it))
        except Exception as exc:  # noqa: BLE001
            errors.append((worker.config.worker_id, exc))

    threads = [threading.Thread(target=loop, args=(w,)) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, f"worker failures: {errors}"
    return losses


def test_two_worker_sync_training_loss_decreases(cluster):
    ps, coordinator, coord_port, tmp_path = cluster
    workers = [make_worker(coord_port, 0), make_worker(coord_port, 1)]
    for w in workers:
        w.initialize()
    assert coordinator.core.live_worker_count() == 2
    try:
        losses = run_workers(workers, 8)
    finally:
        for w in workers:
            w.shutdown()
    # iteration 0 is the bootstrap (nan); real losses from iteration 1 on
    for wid, history in losses.items():
        assert len(history) == 8
        real = history[1:]
        assert not np.isnan(real).any()
        # learning signal: mean of last 3 < first loss
        assert np.mean(real[-3:]) < real[0], f"worker {wid}: {real}"
    assert ps.core.current_iteration == 7


def test_autosave_and_rpc_restore_roundtrip(cluster):
    ps, coordinator, coord_port, tmp_path = cluster
    worker = make_worker(coord_port, 0)
    # shrink barrier to 1 for a single-worker run (elastic-style)
    ps.core.set_total_workers(1)
    worker.initialize()
    try:
        for it in range(5):
            worker.run_iteration(it)
        # epoch = 4 // 2 = 2 -> autosave writes checkpoint_epoch_2.ckpt
        path = ps.ckpt.maybe_autosave()
        assert path is not None and path.endswith("checkpoint_epoch_2.ckpt")
        before = ps.core.get_parameters()
        # keep training, then restore via the worker-facing RPC
        for it in range(5, 7):
            worker.run_iteration(it)
        after = ps.core.get_parameters()
        assert any(not np.array_equal(before[k], after[k]) for k in before)
        assert worker.load_checkpoint_from_server(path)
        restored = ps.core.get_parameters()
        for k in before:
            np.testing.assert_array_equal(restored[k], before[k])
    finally:
        worker.shutdown()


def test_worker_reconnect_after_coordinator_restart(cluster):
    ps, coordinator, coord_port, tmp_path = cluster
    worker = make_worker(coord_port, 0)
    worker.initialize()
    try:
        # coordinator forgets the worker (simulates eviction); re-register
        evicted = coordinator.core.remove_stale_workers(timeout_s=-1)
        assert evicted == [0]
        worker.reconnect()
        assert coordinator.core.live_worker_count() == 1
    finally:
        worker.shutdown()


def test_bf16_wire_training_loss_decreases(cluster):
    """Workers configured with --wire=bf16 train end to end; the PS decodes
    the packed payloads transparently and learning still happens."""
    ps, coordinator, coord_port, _ = cluster
    workers = []
    for wid in range(2):
        config = WorkerConfig(
            coordinator_address=f"127.0.0.1:{coord_port}",
            worker_id=wid, iterations=5,
            address="127.0.0.1", port=50060 + wid,
            batch_size=16, model="mnist_mlp",
            heartbeat_period_s=600.0, wire_dtype="bf16")
        w = build_worker(config)
        w.initialize()
        workers.append(w)
    try:
        losses = run_workers(workers, 5)
        for wid, series in losses.items():
            real = [x for x in series if np.isfinite(x)]
            assert len(real) >= 3
            assert real[-1] < real[0], f"worker {wid} loss did not decrease"
    finally:
        for w in workers:
            w.shutdown()


def test_unknown_wire_dtype_rejected(cluster):
    _, _, coord_port, _ = cluster
    with pytest.raises(ValueError, match="wire_dtype"):
        build_worker(WorkerConfig(
            coordinator_address=f"127.0.0.1:{coord_port}", worker_id=0,
            wire_dtype="fp16"))


def test_device_apply_training_loss_decreases(tmp_path, monkeypatch):
    """ISSUE 11 acceptance: with PSDT_DEVICE_APPLY=1 and a device
    optimizer selected, the existing two-worker e2e training run has
    zero failed steps and the same learning signal — the barrier closes
    are accelerator-resident end to end (device folds via
    core.device_fold, sharded device apply, async readback feeding the
    serve encodes)."""
    monkeypatch.setenv("PSDT_DEVICE_APPLY", "1")
    ps = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=2,
        checkpoint_interval=100, checkpoint_dir=str(tmp_path),
        learning_rate=0.05, optimizer="device_sgd",
        autosave_period_s=600.0))
    from parameter_server_distributed_tpu.async_sgd.device_optimizer import (
        ShardedDeviceOptimizer)

    assert isinstance(ps.core._optimizer, ShardedDeviceOptimizer)
    assert ps.core.device_fold
    ps_port = ps.start()
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0, ps_address="127.0.0.1",
        ps_port=ps_port, reap_period_s=600.0))
    coord_port = coordinator.start()
    workers = [make_worker(coord_port, 0), make_worker(coord_port, 1)]
    try:
        for w in workers:
            w.initialize()
        losses = run_workers(workers, 8)  # asserts zero failed steps
    finally:
        for w in workers:
            w.shutdown()
        coordinator.stop()
        ps.stop()
    for wid, history in losses.items():
        real = history[1:]  # iteration 0 is the bootstrap (nan)
        assert not np.isnan(real).any()
        assert np.mean(real[-3:]) < real[0], f"worker {wid}: {real}"
    from parameter_server_distributed_tpu.obs import stats as obs_stats

    # the closes really ran device-resident
    assert obs_stats.REGISTRY.snapshot()["counters"].get(
        "ps.apply.device", 0) >= 7


def test_arena_apply_training_loss_decreases(tmp_path, monkeypatch):
    """ISSUE 15 acceptance, end to end: PSDT_ARENA=1 on top of the
    device apply runs the same two-worker training over the real gRPC
    plane with zero failed steps and the same learning signal — folds
    scatter into the per-stripe sum arenas, the closes run flat, and
    the serve encodes read the contiguous readback's slab views."""
    monkeypatch.setenv("PSDT_DEVICE_APPLY", "1")
    monkeypatch.setenv("PSDT_ARENA", "1")
    ps = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=2,
        checkpoint_interval=100, checkpoint_dir=str(tmp_path),
        learning_rate=0.05, optimizer="device_sgd",
        autosave_period_s=600.0))
    assert ps.core._arena is not None
    ps_port = ps.start()
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0, ps_address="127.0.0.1",
        ps_port=ps_port, reap_period_s=600.0))
    coord_port = coordinator.start()
    workers = [make_worker(coord_port, 0), make_worker(coord_port, 1)]
    try:
        for w in workers:
            w.initialize()
        losses = run_workers(workers, 8)  # asserts zero failed steps
    finally:
        for w in workers:
            w.shutdown()
        coordinator.stop()
        ps.stop()
    for wid, history in losses.items():
        real = history[1:]
        assert not np.isnan(real).any()
        assert np.mean(real[-3:]) < real[0], f"worker {wid}: {real}"
    from parameter_server_distributed_tpu.obs import stats as obs_stats

    # the closes really ran FLAT (post-bootstrap; the seed close has no
    # table yet), with no silent per-tensor fallbacks
    counters = obs_stats.REGISTRY.snapshot()["counters"]
    assert counters.get("ps.apply.arena", 0) >= 6
    assert counters.get("ps.apply.arena_fallback", 0) == 0


def test_bf16_worker_falls_back_against_f32_only_ps(tmp_path):
    """A PS that ignores the packed extension (the reference's behavior: it
    skips unknown fields) must not receive packed pushes — the worker detects
    the f32-only response on its first pull and downgrades itself."""
    ps = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=2,
        checkpoint_interval=100, checkpoint_dir=str(tmp_path),
        learning_rate=0.05, autosave_period_s=600.0))
    seen_encodings = []
    orig_serve = type(ps.service).ServeParameters
    orig_recv = type(ps.service).ReceiveGradients

    def serve_f32_only(request, context):
        request.wire_dtype = 0  # a reference PS never sees field 3
        return orig_serve(ps.service, request, context)

    def recording_recv(request, context):
        seen_encodings.extend(t.packed_dtype for t in request.gradients)
        return orig_recv(ps.service, request, context)

    def unimplemented_stream(request, context):
        # a reference PS has no chunk-stream extension methods at all; an
        # unknown method surfaces to the client as UNIMPLEMENTED, which is
        # exactly what aborting here produces
        import grpc
        context.abort(grpc.StatusCode.UNIMPLEMENTED,
                      "reference PS: no streaming data plane")

    # patch BEFORE start(): bind_service captures bound methods at bind time
    ps.service.ServeParameters = serve_f32_only
    ps.service.ReceiveGradients = recording_recv
    ps.service.PushGradientsStream = unimplemented_stream
    ps.service.ServeParametersStream = unimplemented_stream
    ps.service.PushPullStream = unimplemented_stream  # no fused plane either
    # nor the versioned-delta extension (delta/, ISSUE 10): a bf16 delta
    # pull would mask the f32-only unary response the downgrade keys on
    ps.service.PullParametersDelta = unimplemented_stream
    ps.service.PushPullDeltaStream = unimplemented_stream
    ps.service.SubscribeWeights = unimplemented_stream
    ps_port = ps.start()
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0,
        ps_address="127.0.0.1", ps_port=ps_port, reap_period_s=600.0))
    coord_port = coordinator.start()

    workers = []
    try:
        for wid in range(2):
            w = build_worker(WorkerConfig(
                coordinator_address=f"127.0.0.1:{coord_port}",
                worker_id=wid, iterations=3, address="127.0.0.1",
                port=50060 + wid, batch_size=16, model="mnist_mlp",
                heartbeat_period_s=600.0, wire_dtype="bf16"))
            w.initialize()
            workers.append(w)
        losses = run_workers(workers, 3)
        # every push that reached the PS was plain f32 (no invisible payloads)
        assert seen_encodings and all(e == 0 for e in seen_encodings)
        for wid, series in losses.items():
            real = [x for x in series if np.isfinite(x)]
            assert real and real[-1] < real[0]
    finally:
        for w in workers:
            w.shutdown()
        coordinator.stop()
        ps.stop()


def test_int8_error_feedback_cancels_quantization_bias():
    """Pushing the same gradient repeatedly with error feedback: the mean
    of what the PS decodes converges to the true gradient, far below the
    single-shot quantization error."""
    from parameter_server_distributed_tpu.cli.worker_main import build_worker

    w = build_worker(WorkerConfig(worker_id=0, wire_dtype="int8",
                                  heartbeat_period_s=600.0))
    try:
        w._peer_packed_ok = True  # pretend negotiation succeeded
        rng = np.random.default_rng(0)
        g = {"w": rng.standard_normal(512).astype(np.float32)}
        decoded = []
        for _ in range(64):
            tensors, residual = w._compress_with_feedback(g, 3)  # WIRE_INT8
            w._ef_residual = residual  # as a successful push would
            decoded.append(tensors[0].to_array())
        single_err = np.abs(decoded[0] - g["w"]).max()
        mean_err = np.abs(np.mean(decoded, axis=0) - g["w"]).max()
        assert mean_err < single_err / 5  # bias cancelled over pushes
        assert any(np.abs(r).sum() > 0 for r in w._ef_residual.values())
    finally:
        w.shutdown()


def test_topk_error_feedback_delivers_full_mass():
    """Top-k sparsified pushes at 25% density: each push delivers only
    the largest entries, but the residual carries everything unsent —
    including the bf16 rounding of what WAS sent — so the telescoping
    identity sum(decoded pushes) + final_residual == N * true_gradient
    holds exactly (nothing is ever dropped, only deferred)."""
    from parameter_server_distributed_tpu.cli.worker_main import build_worker
    from parameter_server_distributed_tpu.rpc import messages as m

    w = build_worker(WorkerConfig(worker_id=0, wire_dtype="topk",
                                  topk_density=0.25,
                                  heartbeat_period_s=600.0))
    try:
        w._peer_packed_ok = True
        rng = np.random.default_rng(0)
        g = {"w": rng.standard_normal(256).astype(np.float32)}
        total = np.zeros(256, np.float32)
        n = 64
        for _ in range(n):
            tensors, residual = w._compress_with_feedback(g, m.WIRE_TOPK)
            w._ef_residual = residual
            arr = tensors[0].to_array()
            assert np.count_nonzero(arr) <= 64  # 25% of 256
            total += arr
        np.testing.assert_allclose(total + w._ef_residual["w"],
                                   n * g["w"], atol=1e-3)
        # and the deferred mass is bounded: the mean of what the PS saw
        # tracks the true gradient to O(residual / n)
        bound = np.abs(w._ef_residual["w"]).max() / n + 1e-3
        assert np.abs(total / n - g["w"]).max() <= bound
    finally:
        w.shutdown()


def test_int8_wire_training_loss_decreases(cluster):
    """End to end: int8 error-feedback pushes + bf16 pulls still learn."""
    ps, coordinator, coord_port, _ = cluster
    workers = []
    for wid in range(2):
        w = build_worker(WorkerConfig(
            coordinator_address=f"127.0.0.1:{coord_port}",
            worker_id=wid, iterations=5, address="127.0.0.1",
            port=50060 + wid, batch_size=16, model="mnist_mlp",
            heartbeat_period_s=600.0, wire_dtype="int8"))
        w.initialize()
        workers.append(w)
    try:
        losses = run_workers(workers, 5)
        for wid, series in losses.items():
            real = [x for x in series if np.isfinite(x)]
            assert len(real) >= 3
            assert real[-1] < real[0], f"worker {wid} loss did not decrease"
        # error feedback engaged on both workers
        for w in workers:
            assert w._wire_dtype == 3 and w._ef_residual
    finally:
        for w in workers:
            w.shutdown()


def test_topk_wire_training_loss_decreases(cluster):
    """End to end: top-k sparsified error-feedback pushes (10% density)
    + bf16 pulls still learn over real gRPC."""
    ps, coordinator, coord_port, _ = cluster
    workers = []
    for wid in range(2):
        w = build_worker(WorkerConfig(
            coordinator_address=f"127.0.0.1:{coord_port}",
            worker_id=wid, iterations=5, address="127.0.0.1",
            port=50070 + wid, batch_size=16, model="mnist_mlp",
            heartbeat_period_s=600.0, wire_dtype="topk",
            topk_density=0.1))
        w.initialize()
        workers.append(w)
    try:
        losses = run_workers(workers, 5)
        for wid, series in losses.items():
            real = [x for x in series if np.isfinite(x)]
            assert len(real) >= 3
            assert real[-1] < real[0], f"worker {wid} loss did not decrease"
        for w in workers:
            assert w._wire_dtype == 4 and w._ef_residual  # WIRE_TOPK
    finally:
        for w in workers:
            w.shutdown()
