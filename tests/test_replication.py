"""Replication subsystem (ISSUE 7): primary/backup state ships, hot
failover with zero failed steps, live 2->4 resharding under load,
promoted-replica checkpointing, and the lock discipline of it all."""

import threading
import time

import numpy as np
import pytest

from parameter_server_distributed_tpu.cli.worker_main import build_worker
from parameter_server_distributed_tpu.config import (CoordinatorConfig,
                                                     ParameterServerConfig,
                                                     WorkerConfig)
from parameter_server_distributed_tpu.core.coordinator_core import (
    CoordinatorCore, ShardMapEntry)
from parameter_server_distributed_tpu.core.tensor import to_wire
from parameter_server_distributed_tpu.replication import messages as rmsg
from parameter_server_distributed_tpu.replication.failover import (
    ShardMapClient)
from parameter_server_distributed_tpu.replication.replicator import (
    flatten_optimizer_state, split_replica_store)
from parameter_server_distributed_tpu.replication.resharding import (
    ReshardController)
from parameter_server_distributed_tpu.rpc import messages as m
from parameter_server_distributed_tpu.server.coordinator_service import (
    Coordinator)
from parameter_server_distributed_tpu.server.ps_service import ParameterServer
from parameter_server_distributed_tpu.utils.netsim import ThrottledRelay
from parameter_server_distributed_tpu.worker.ps_shards import (
    ShardedPSClient, shard_owner)


def make_ps(tmp_path, name, total_workers=1, **kw):
    ps = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=total_workers,
        checkpoint_dir=str(tmp_path / name), learning_rate=0.1,
        autosave_period_s=600.0, **kw))
    return ps, ps.start()


def rand_store(n=8, size=32, seed=0):
    rng = np.random.default_rng(seed)
    return {f"layer{i}/w": rng.standard_normal(size).astype(np.float32)
            for i in range(n)}


# ----------------------------------------------------------- shard map core

def test_shard_map_promote_idempotent():
    core = CoordinatorCore("10.0.0.1", 50051, ps_shards=("10.0.0.2:50051",),
                           ps_backups=("10.0.1.1:50051", "10.0.1.2:50051"))
    epoch0, entries = core.get_shard_map()
    assert [e.primary for e in entries] == ["10.0.0.1:50051",
                                           "10.0.0.2:50051"]
    assert [e.backup for e in entries] == ["10.0.1.1:50051",
                                           "10.0.1.2:50051"]
    epoch1, entries = core.promote_shard(0, "10.0.0.1:50051")
    assert epoch1 == epoch0 + 1
    assert entries[0].primary == "10.0.1.1:50051" and not entries[0].backup
    # discovery follows the promotion (reference peers see the replica)
    assert core.get_parameter_server_address() == ("10.0.1.1", 50051)
    # second report of the SAME dead primary: no-op, same map back
    epoch2, entries2 = core.promote_shard(0, "10.0.0.1:50051")
    assert epoch2 == epoch1
    assert entries2[0].primary == "10.0.1.1:50051"
    # a shard whose backup was already consumed cannot promote again
    epoch3, entries3 = core.promote_shard(0, "10.0.1.1:50051")
    assert epoch3 == epoch2 and entries3[0].primary == "10.0.1.1:50051"
    assert core.get_shard_map()[0] == epoch3


def test_set_shard_map_bumps_epoch_and_discovery():
    core = CoordinatorCore("10.0.0.1", 50051)
    epoch0, _ = core.get_shard_map()
    epoch = core.set_shard_map([ShardMapEntry(primary="10.0.9.1:1"),
                                ShardMapEntry(primary="10.0.9.2:2",
                                              backup="10.0.9.3:3")])
    assert epoch == epoch0 + 1
    assert core.get_parameter_server_shards() == ["10.0.9.1:1",
                                                  "10.0.9.2:2"]
    assert core.get_parameter_server_address() == ("10.0.9.1", 1)


def test_optimizer_state_flatten_roundtrip():
    state = {"velocity": {"a": np.arange(4, dtype=np.float32),
                          "b/c": np.ones(2, np.float32)},
             "t": 7}
    flat = flatten_optimizer_state(state)
    assert all(k.startswith("__opt__/") for k in flat)
    params, opt = split_replica_store({**flat, "w": np.zeros(3, np.float32)})
    assert set(params) == {"w"}
    assert opt["t"] == 7
    np.testing.assert_array_equal(opt["velocity"]["a"], state["velocity"]["a"])
    np.testing.assert_array_equal(opt["velocity"]["b/c"],
                                  state["velocity"]["b/c"])


# --------------------------------------------------------- replication ships

def test_replica_store_bit_identical_after_n_iterations(tmp_path):
    """The backup's store (and optimizer slots) must be byte-equal to the
    primary's after N barrier closes — lossless WIRE_RAW_F32 ships."""
    backup, bport = make_ps(tmp_path, "bk", optimizer="momentum")
    primary, _ = make_ps(tmp_path, "pr", optimizer="momentum",
                         backup_address=f"127.0.0.1:{bport}",
                         replication="sync")
    try:
        store = rand_store()
        primary.core.initialize_parameters(store)
        rng = np.random.default_rng(1)
        for it in range(1, 6):
            grads = {k: rng.standard_normal(32).astype(np.float32)
                     for k in store}
            r = primary.core.receive_gradients(0, it, grads)
            assert r.aggregation_complete, r.message
        assert primary.replicator.flush()
        pp, bp = primary.core.get_parameters(), backup.core.get_parameters()
        assert set(pp) == set(bp)
        for name in pp:
            assert np.array_equal(pp[name], bp[name]), name
        # momentum slots came along (a promoted replica optimizes
        # identically, not from cold slots)
        pv = primary.core._optimizer.state_dict()["velocity"]
        bv = backup.core._optimizer.state_dict()["velocity"]
        for name in pv:
            assert np.array_equal(np.asarray(pv[name], np.float32),
                                  np.asarray(bv[name], np.float32)), name
        assert backup.core.current_iteration == 5
        assert backup.service.replica_sink.primary_iteration == 5
        # retried push of an applied iteration: answered already-aggregated
        # (the promoted-replica dedup)
        r = backup.core.receive_gradients(0, 5, {k: np.zeros(32, np.float32)
                                                 for k in store})
        assert r.success and r.aggregation_complete
    finally:
        primary.stop(0)
        backup.stop(0)


def test_zombie_primary_delta_refused_after_promotion(tmp_path):
    """Once the replica aggregates on its own (promotion), a late ship
    from the dead-but-still-running ex-primary must not rewind it."""
    backup, bport = make_ps(tmp_path, "bk")
    primary, _ = make_ps(tmp_path, "pr",
                         backup_address=f"127.0.0.1:{bport}",
                         replication="sync")
    try:
        store = rand_store()
        primary.core.initialize_parameters(store)
        grads = {k: np.ones(32, np.float32) for k in store}
        assert primary.core.receive_gradients(0, 1, grads).aggregation_complete
        # promotion: the replica aggregates iteration 2 on its own
        assert backup.core.receive_gradients(0, 2, grads).aggregation_complete
        promoted = backup.core.get_parameters()
        # the zombie primary applies its own iteration 2 and ships it
        assert primary.core.receive_gradients(0, 2, grads).aggregation_complete
        primary.replicator.flush()
        assert primary.replicator.degraded  # refusal = permanent downgrade
        after = backup.core.get_parameters()
        for name in promoted:
            assert np.array_equal(promoted[name], after[name]), name
    finally:
        primary.stop(0)
        backup.stop(0)


# -------------------------------------------------------------- hot failover

def _losses_for_cluster(tmp_path, tag, iterations, kill_after=None,
                        base_port=15300):
    """Coordinator + primary(+backup, sync replication) cluster; two
    workers run ``iterations`` steps concurrently.  ``kill_after``: once
    every worker has completed that many iterations, the relay fronting
    the primary is hard-dropped (netsim chaos) — training must continue
    on the promoted replica with zero failed steps."""
    backup, bport = make_ps(tmp_path, f"{tag}-bk", total_workers=2)
    primary, pport = make_ps(tmp_path, f"{tag}-pr", total_workers=2,
                             backup_address=f"127.0.0.1:{bport}",
                             replication="sync")
    relay = ThrottledRelay(pport)
    relay_port = relay.start()
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0, ps_address="127.0.0.1",
        ps_port=relay_port, ps_backups=(f"127.0.0.1:{bport}",),
        reap_period_s=600.0))
    coord_port = coordinator.start()
    workers = [build_worker(WorkerConfig(
        coordinator_address=f"127.0.0.1:{coord_port}", worker_id=i,
        address="127.0.0.1", port=base_port + i, model="mnist_mlp",
        batch_size=32, heartbeat_period_s=600.0)) for i in range(2)]
    losses: dict[int, list[float]] = {0: [], 1: []}
    errors: list[BaseException] = []
    try:
        for w in workers:
            w.initialize()

        def run(w, wid):
            try:
                for it in range(iterations):
                    losses[wid].append(w.run_iteration(it))
            except BaseException as exc:  # noqa: BLE001 — asserted below
                errors.append(exc)

        threads = [threading.Thread(target=run, args=(w, i), daemon=True,
                                    name=f"repl-worker-{i}")
                   for i, w in enumerate(workers)]
        for t in threads:
            t.start()
        if kill_after is not None:
            deadline = time.monotonic() + 60
            while (min(len(ls) for ls in losses.values()) < kill_after
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            relay.drop_connections()  # mid-barrier, mid-stream — chaos
        for t in threads:
            t.join(timeout=180)
            assert not t.is_alive(), "worker wedged"
        assert not errors, errors
        assert all(len(ls) == iterations for ls in losses.values())
        promoted = (kill_after is not None
                    and backup.core.current_iteration > 0)
        return losses, promoted
    finally:
        for w in workers:
            w.shutdown()
        coordinator.stop()
        relay.stop()
        primary.stop(0)
        backup.stop(0)


def test_kill_primary_mid_run_promotes_replica_with_matching_losses(tmp_path):
    """THE failover acceptance: sever the primary under live 2-worker
    training (netsim chaos), training continues on the promoted replica
    with zero failed steps, and the loss curve tracks the no-failure
    run's (sync replication + lossless wire => same arithmetic)."""
    iterations = 6
    clean, _ = _losses_for_cluster(tmp_path, "clean", iterations,
                                   base_port=15300)
    chaos, promoted = _losses_for_cluster(tmp_path, "chaos", iterations,
                                          kill_after=2, base_port=15310)
    assert promoted, "the kill never forced a promotion"
    for wid in (0, 1):
        # iteration 0 is the bootstrap NaN on both runs
        np.testing.assert_allclose(chaos[wid][1:], clean[wid][1:],
                                   rtol=1e-4, atol=1e-6,
                                   err_msg=f"worker {wid} loss curve "
                                           f"diverged across the failover")


def test_failover_via_client_retries_same_iteration(tmp_path):
    """Direct (no-netsim) failover unit: the sharded client reports the
    dead shard, the coordinator promotes, and the SAME iteration lands on
    the replica — idempotently even when the primary had already applied
    and shipped it."""
    backup, bport = make_ps(tmp_path, "bk")
    primary, pport = make_ps(tmp_path, "pr",
                             backup_address=f"127.0.0.1:{bport}",
                             replication="sync")
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0, ps_address="127.0.0.1",
        ps_port=pport, ps_backups=(f"127.0.0.1:{bport}",),
        reap_period_s=600.0))
    coord_port = coordinator.start()
    shard_map = ShardMapClient(f"127.0.0.1:{coord_port}")
    assert shard_map.refresh() and shard_map.has_backups()
    client = ShardedPSClient(shard_map.primaries(), shard_map=shard_map)
    try:
        store = rand_store()
        primary.core.initialize_parameters(store)
        grads = to_wire({k: np.ones(32, np.float32) for k in store})
        r = client.push_gradients(m.GradientUpdate(worker_id=0, iteration=1,
                                                   gradients=grads))
        assert r.success and r.aggregation_complete
        applied = primary.core.get_parameters()
        primary._server.stop(None)  # hard kill
        # retry of the ALREADY-APPLIED iteration 1 (the worker never saw
        # the ack): the replica's watermark answers already-aggregated
        r = client.push_gradients(m.GradientUpdate(worker_id=0, iteration=1,
                                                   gradients=grads))
        assert r.success and r.aggregation_complete
        assert client.addresses == [f"127.0.0.1:{bport}"]
        bp = backup.core.get_parameters()
        for name in applied:  # replica state == what the primary applied
            assert np.array_equal(applied[name], bp[name]), name
        # and a FRESH iteration aggregates on the replica
        r = client.push_gradients(m.GradientUpdate(worker_id=0, iteration=2,
                                                   gradients=grads))
        assert r.success and r.aggregation_complete
        assert backup.core.current_iteration == 2
    finally:
        client.close()
        coordinator.stop()
        primary.stop(0)
        backup.stop(0)


def test_netsim_drop_connections_severs_and_refuses(tmp_path):
    """The chaos helper itself: live relayed connections die abruptly and
    new connects are refused until restore_connections()."""
    import socket

    server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    server.bind(("127.0.0.1", 0))
    server.listen(8)
    backend_port = server.getsockname()[1]
    accepted = []

    def echo_loop():
        while True:
            try:
                conn, _ = server.accept()
            except OSError:
                return
            accepted.append(conn)

    thread = threading.Thread(target=echo_loop, daemon=True,
                              name="netsim-test-echo")
    thread.start()
    relay = ThrottledRelay(backend_port)
    port = relay.start()
    try:
        client = socket.create_connection(("127.0.0.1", port))
        client.sendall(b"ping")
        time.sleep(0.2)
        assert accepted and accepted[0].recv(16) == b"ping"
        assert relay.drop_connections() >= 1
        # the severed socket surfaces EOF/RST promptly
        client.settimeout(5.0)
        try:
            data = client.recv(16)
        except OSError:
            data = b""
        assert data == b""
        # new connections die while refusing: either the connect itself is
        # reset, or it lands and the first read sees an immediate close
        try:
            probe = socket.create_connection(("127.0.0.1", port),
                                             timeout=5.0)
        except OSError:
            pass  # refused at connect — the "dead host" signature
        else:
            probe.settimeout(5.0)
            try:
                assert probe.recv(16) == b""
            except OSError:
                pass
            finally:
                probe.close()
        # ...and relay again after restore
        relay.restore_connections()
        again = socket.create_connection(("127.0.0.1", port), timeout=5.0)
        again.sendall(b"pong")
        time.sleep(0.2)
        assert len(accepted) >= 2
        again.close()
        client.close()
    finally:
        relay.stop()
        server.close()
        for conn in accepted:
            conn.close()


# ------------------------------------------------------------ live reshard

def test_live_2_to_4_reshard_under_load_zero_failed_steps(tmp_path):
    """THE reshard acceptance: 2->4 split while two workers push
    concurrently — zero failed steps, exact crc32%4 partition after, and
    the workers' clients repartition via the stale-shard-map replay."""
    shards = [make_ps(tmp_path, f"s{i}", total_workers=2) for i in range(4)]
    ports = [port for _, port in shards]
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0, ps_address="127.0.0.1",
        ps_port=ports[0], ps_shards=(f"127.0.0.1:{ports[1]}",),
        reap_period_s=600.0))
    coord_port = coordinator.start()
    iterations = 6
    workers = [build_worker(WorkerConfig(
        coordinator_address=f"127.0.0.1:{coord_port}", worker_id=i,
        address="127.0.0.1", port=15330 + i, model="mnist_mlp",
        batch_size=32, heartbeat_period_s=600.0)) for i in range(2)]
    losses: dict[int, list[float]] = {0: [], 1: []}
    errors: list[BaseException] = []
    try:
        for w in workers:
            w.initialize()
            assert w._ps.num_shards == 2

        def run(w, wid):
            try:
                for it in range(iterations):
                    losses[wid].append(w.run_iteration(it))
            except BaseException as exc:  # noqa: BLE001 — asserted below
                errors.append(exc)

        threads = [threading.Thread(target=run, args=(w, i), daemon=True,
                                    name=f"reshard-worker-{i}")
                   for i, w in enumerate(workers)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 60
        while (min(len(ls) for ls in losses.values()) < 2
               and time.monotonic() < deadline):
            time.sleep(0.01)
        stats = ReshardController(coordinator.core).reshard(
            [f"127.0.0.1:{port}" for port in ports])
        assert stats["moved_bytes"] > 0 and stats["new_shards"] == 4
        for t in threads:
            t.join(timeout=180)
            assert not t.is_alive(), "worker wedged across the reshard"
        assert not errors, errors
        assert all(len(ls) == iterations for ls in losses.values())
        for ls in losses.values():  # loss stays sane across the handoff
            assert all(np.isfinite(v) for v in ls[1:])
        # every shard owns exactly its crc32%4 partition, union = model
        expected = set(workers[0].trainer.init_params(0))
        union: set = set()
        for i, (ps, _) in enumerate(shards):
            names = set(ps.core.get_parameters())
            assert names == {n for n in expected if shard_owner(n, 4) == i}
            union |= names
        assert union == expected
        # the clients repartitioned live
        assert all(w._ps.num_shards == 4 for w in workers)
    finally:
        for w in workers:
            w.shutdown()
        coordinator.stop()
        for ps, _ in shards:
            ps.stop(0)


def test_retired_push_rejected_then_replayed_exactly_once(tmp_path):
    """Unit of the reshard fence: a push touching retired tensors is
    rejected whole with the STALE_SHARD_MAP marker, folds of the moved
    names never pollute the accumulator, and the post-repartition replay
    double-counts nothing."""
    primary, _ = make_ps(tmp_path, "pr")
    try:
        store = rand_store(n=4)
        primary.core.initialize_parameters(store)
        names = sorted(store)
        moved = names[:2]
        epoch, iteration, _version, taken, _opt = primary.core.retire_tensors(
            moved, map_epoch=7)
        assert set(taken) == set(moved)
        assert set(primary.core.get_parameters()) == set(names[2:])
        grads = {k: np.ones(32, np.float32) for k in store}
        r = primary.core.receive_gradients(0, 1, grads)
        assert not r.success and rmsg.STALE_SHARD_MAP in r.message
        # the replayed (repartitioned) push carries only owned names
        r = primary.core.receive_gradients(
            0, 1, {k: grads[k] for k in names[2:]})
        assert r.success and r.aggregation_complete
        after = primary.core.get_parameters()
        for k in names[2:]:  # exactly ONE update landed
            np.testing.assert_allclose(after[k], store[k] - 0.1, rtol=1e-6)
    finally:
        primary.stop(0)


def test_install_releases_superseded_barrier_state(tmp_path):
    """The failover-retry-vs-final-ship race: a worker's retried push
    creates a live barrier state on the replica, THEN the dead primary's
    last in-flight ship installs the same iteration (it was applied
    cluster-wide before the death).  The parked retry must be released
    as already-aggregated — not stranded behind a 1/N state no one else
    will ever push to."""
    replica, _ = make_ps(tmp_path, "rep", total_workers=2)
    try:
        store = rand_store()
        replica.core.initialize_parameters(store)
        # worker 1's retry lands first: 1/2 contributors, state parked
        r = replica.core.receive_gradients(
            1, 5, {k: np.ones(32, np.float32) for k in store})
        assert r.success and not r.aggregation_complete
        released: list = []

        def waiter():
            released.append(replica.core.wait_for_aggregation(5, timeout=30))

        t = threading.Thread(target=waiter, daemon=True,
                             name="superseded-waiter")
        t.start()
        time.sleep(0.2)
        # the zombie primary's ship of the APPLIED iteration 5 arrives
        replica.core.install_tensors(store, epoch=0, iteration=5,
                                     replace=True)
        t.join(timeout=10)
        assert not t.is_alive(), "waiter stranded behind superseded state"
        ready, _received, _total = released[0]
        assert ready
        # and a later poll agrees
        _, ready, _, _ = replica.core.check_sync_status(5)
        assert ready
    finally:
        replica.stop(0)


def test_retire_moves_optimizer_slots_and_install_merges(tmp_path):
    """A reshard handoff carries the moved tensors' optimizer slot
    entries: the source's slots shrink, the destination's grow by exactly
    the moved names with the SAME values — the optimization trajectory
    survives the move."""
    source, _ = make_ps(tmp_path, "src", optimizer="momentum")
    target, _ = make_ps(tmp_path, "dst", optimizer="momentum")
    try:
        store = rand_store(n=4)
        source.core.initialize_parameters(store)
        grads = {k: np.ones(32, np.float32) for k in store}
        assert source.core.receive_gradients(0, 1, grads).aggregation_complete
        names = sorted(store)
        moved = names[:2]
        src_velocity = {
            k: np.array(v) for k, v in
            source.core._optimizer.state_dict()["velocity"].items()}
        _e, it, _v, taken, moved_opt = source.core.retire_tensors(
            moved, map_epoch=3)
        assert set(moved_opt["velocity"]) == set(moved)
        # the source's remaining slots no longer know the moved names
        left = source.core._optimizer.state_dict()["velocity"]
        assert set(left) == set(names[2:])
        # install with merge on a target that has its own state
        target.core.initialize_parameters(
            {"other": np.zeros(8, np.float32)})
        assert target.core.receive_gradients(
            0, 1, {"other": np.ones(8, np.float32)}).aggregation_complete
        target.core.install_tensors(taken, iteration=it,
                                    optimizer_state=moved_opt,
                                    optimizer_merge=True)
        dst = target.core._optimizer.state_dict()["velocity"]
        assert set(dst) == {"other", *moved}  # merged, not replaced
        for name in moved:
            np.testing.assert_array_equal(
                np.asarray(dst[name], np.float32),
                np.asarray(src_velocity[name], np.float32))
    finally:
        source.stop(0)
        target.stop(0)


# ------------------------------------------------- promoted-replica ckpt

def test_checkpoint_roundtrip_through_promoted_replica(tmp_path):
    """Save a checkpoint FROM the promoted replica, restore it into a
    fresh PS: params and optimizer slots match the replica's exactly."""
    backup, bport = make_ps(tmp_path, "bk", optimizer="momentum")
    primary, pport = make_ps(tmp_path, "pr", optimizer="momentum",
                             backup_address=f"127.0.0.1:{bport}",
                             replication="sync")
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0, ps_address="127.0.0.1",
        ps_port=pport, ps_backups=(f"127.0.0.1:{bport}",),
        reap_period_s=600.0))
    coord_port = coordinator.start()
    shard_map = ShardMapClient(f"127.0.0.1:{coord_port}")
    shard_map.refresh()
    client = ShardedPSClient(shard_map.primaries(), shard_map=shard_map)
    fresh = None
    try:
        store = rand_store()
        primary.core.initialize_parameters(store)
        rng = np.random.default_rng(3)
        for it in range(1, 4):
            grads = to_wire({k: rng.standard_normal(32).astype(np.float32)
                             for k in store})
            r = client.push_gradients(m.GradientUpdate(
                worker_id=0, iteration=it, gradients=grads))
            assert r.success and r.aggregation_complete
        primary._server.stop(None)  # kill; next call fails over
        r = client.push_gradients(m.GradientUpdate(
            worker_id=0, iteration=4,
            gradients=to_wire({k: np.ones(32, np.float32) for k in store})))
        assert r.success and r.aggregation_complete
        # checkpoint THROUGH the promoted replica
        path = str(tmp_path / "promoted.ckpt")
        save = client.call("SaveCheckpoint",
                           m.SaveCheckpointRequest(epoch=1, path=path))
        assert save.success, save.message
        replica_params = backup.core.get_parameters()
        replica_slots = backup.core._optimizer.state_dict()["velocity"]
        # restore into a brand-new PS and compare
        fresh, _fport = make_ps(tmp_path, "fresh", optimizer="momentum")
        fresh.ckpt.load(path)
        restored = fresh.core.get_parameters()
        assert set(restored) == set(replica_params)
        for name in restored:
            assert np.array_equal(restored[name], replica_params[name]), name
        slots = fresh.core._optimizer.state_dict()["velocity"]
        for name in replica_slots:
            np.testing.assert_allclose(np.asarray(slots[name], np.float32),
                                       np.asarray(replica_slots[name],
                                                  np.float32), rtol=1e-6)
    finally:
        client.close()
        coordinator.stop()
        if fresh is not None:
            fresh.stop(0)
        backup.stop(0)


# ------------------------------------------------------------- lock checking

@pytest.mark.lockcheck
def test_lockcheck_replication_promotion_push_hammer(tmp_path):
    """Concurrent pushes + sync replication ships + reshard retires +
    zombie installs, all with PSDT_LOCK_CHECK=1: any ordering violation
    in the new Replicator/ReplicaSink/CoordinatorCore/core lock chains
    raises LockOrderError instead of deadlocking."""
    backup, bport = make_ps(tmp_path, "bk")
    primary, _ = make_ps(tmp_path, "pr", total_workers=4,
                         backup_address=f"127.0.0.1:{bport}",
                         replication="sync")
    coord = CoordinatorCore("127.0.0.1", 1,
                            ps_backups=(f"127.0.0.1:{bport}",))
    errors: list[BaseException] = []
    try:
        store = rand_store(n=8)
        primary.core.initialize_parameters(store)
        stop = threading.Event()

        def pusher(wid):
            try:
                rng = np.random.default_rng(wid)
                for it in range(1, 9):
                    grads = {k: rng.standard_normal(32).astype(np.float32)
                             for k in store}
                    primary.core.receive_gradients(wid, it, grads)
            except BaseException as exc:  # noqa: BLE001 — asserted below
                errors.append(exc)

        def churner():
            try:
                while not stop.is_set():
                    coord.promote_shard(0, "127.0.0.1:1")
                    coord.get_shard_map()
                    backup.service.replica_sink.push_delta(iter([
                        rmsg.ReplicaDeltaChunk(
                            epoch=0, iteration=0, params_version=1,
                            kind=rmsg.DELTA_INSTALL,
                            tensors=to_wire({"extra": np.ones(4,
                                                              np.float32)}))]))
                    time.sleep(0.001)
            except BaseException as exc:  # noqa: BLE001 — asserted below
                errors.append(exc)

        threads = [threading.Thread(target=pusher, args=(wid,), daemon=True,
                                    name=f"hammer-push-{wid}")
                   for wid in range(4)]
        churn = threading.Thread(target=churner, daemon=True,
                                 name="hammer-churn")
        for t in threads:
            t.start()
        churn.start()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive()
        stop.set()
        churn.join(timeout=10)
        assert not errors, errors
        assert primary.core.current_iteration == 8
    finally:
        primary.stop(0)
        backup.stop(0)


# ------------------------------------------------------------------- rollup

def test_replica_metrics_surface_in_rollup():
    from parameter_server_distributed_tpu.obs.export import (render_rollup,
                                                             worker_rollup)

    snap = {"counters": {"ps.replica.shipped_bytes": 4096,
                         "ps.replica.promotions": 2,
                         "ps.reshard.moved_bytes": 1024},
            "gauges": {"ps.replica.lag_bytes": 512},
            "histograms": {}, "t": 0.0}
    rolled = worker_rollup(snap)
    replica = rolled["ps"]["replica"]
    assert replica["shipped_bytes"] == 4096
    assert replica["promotions"] == 2
    assert replica["reshard_moved_bytes"] == 1024
    assert replica["lag_bytes"] == 512
    text = render_rollup({"per_worker": {0: rolled}, "cluster": {}})
    assert "replication:" in text
    assert "2 promotions" in text and "reshard moved" in text


# ------------------------------------------------ promoted-primary re-arm

def test_promoted_primary_unarmed_gauge(tmp_path):
    """ISSUE 9 satellite: a backup promoted to primary (it starts
    closing barriers) with no standby configured surfaces the
    unreplicated window as ps.replica.unarmed=1."""
    from parameter_server_distributed_tpu.obs import stats as obs_stats

    gauge = obs_stats.gauge("ps.replica.unarmed")
    gauge.set(0)
    backup, bport = make_ps(tmp_path, "ua-bk")
    primary, _ = make_ps(tmp_path, "ua-pr",
                         backup_address=f"127.0.0.1:{bport}",
                         replication="sync")
    try:
        store = rand_store()
        primary.core.initialize_parameters(store)
        grads = {k: np.ones(32, np.float32) for k in store}
        r = primary.core.receive_gradients(0, 1, grads)
        assert r.aggregation_complete
        assert backup.service.replica_sink.primary_version >= 0
        assert gauge.value == 0  # still just a backup: not unarmed
        # "promotion": training traffic starts landing on the ex-backup
        r = backup.core.receive_gradients(0, 2, grads)
        assert r.aggregation_complete
        assert gauge.value == 1, "promoted primary did not flag unarmed"
        rolled = __import__(
            "parameter_server_distributed_tpu.obs.export",
            fromlist=["worker_rollup"]).worker_rollup(
            {"counters": {}, "gauges": {"ps.replica.unarmed": 1},
             "histograms": {}, "t": 0.0})
        assert rolled["ps"]["replica"]["unarmed"] is True
    finally:
        gauge.set(0)
        primary.stop(0)
        backup.stop(0)


def test_promoted_primary_rearms_toward_standby(tmp_path):
    """With --standby configured, the promoted primary's Replicator arms
    itself on its FIRST barrier close as a primary — that close's state
    ships to the standby before anything can be lost — and the unarmed
    gauge stays down."""
    from parameter_server_distributed_tpu.obs import stats as obs_stats

    gauge = obs_stats.gauge("ps.replica.unarmed")
    gauge.set(0)
    standby, sport = make_ps(tmp_path, "sb-st", optimizer="momentum")
    backup, bport = make_ps(tmp_path, "sb-bk", optimizer="momentum",
                            standby_address=f"127.0.0.1:{sport}",
                            replication="sync")
    primary, _ = make_ps(tmp_path, "sb-pr", optimizer="momentum",
                         backup_address=f"127.0.0.1:{bport}",
                         replication="sync")
    try:
        assert backup.replicator is None  # dormant until promotion
        store = rand_store()
        primary.core.initialize_parameters(store)
        grads = {k: np.ones(32, np.float32) for k in store}
        assert primary.core.receive_gradients(0, 1, grads).aggregation_complete
        assert backup.service.replica_sink.primary_version >= 0
        # promotion: the ex-backup closes its first barrier as primary
        r = backup.core.receive_gradients(0, 2, grads)
        assert r.aggregation_complete
        assert backup.replicator is not None, "standby never armed"
        assert backup.replicator.backup_address == f"127.0.0.1:{sport}"
        assert gauge.value == 0
        # sync re-arm shipped THIS close: the standby is bit-identical
        bp, sp = backup.core.get_parameters(), standby.core.get_parameters()
        assert set(bp) == set(sp)
        for name in bp:
            assert np.array_equal(np.asarray(bp[name]),
                                  np.asarray(sp[name])), name
        # and it keeps shipping on later closes
        assert backup.core.receive_gradients(0, 3, grads).aggregation_complete
        sp = standby.core.get_parameters()
        for name in bp:
            assert np.array_equal(
                np.asarray(backup.core.get_parameters()[name]),
                np.asarray(sp[name])), name
    finally:
        gauge.set(0)
        primary.stop(0)
        backup.stop(0)
        standby.stop(0)
