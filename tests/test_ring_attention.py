"""Ring / Ulysses sequence-parallel attention vs dense causal attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_distributed_tpu.config import MeshConfig
from parameter_server_distributed_tpu.models.transformer import (
    Transformer, TransformerConfig, causal_attention)
from parameter_server_distributed_tpu.ops.ring_attention import (
    make_ring_attention, make_ulysses_attention)
from parameter_server_distributed_tpu.parallel.mesh import build_mesh
from parameter_server_distributed_tpu.parallel.train_step import (
    ShardedTrainer, make_optimizer)
from parameter_server_distributed_tpu.models.transformer import transformer_rule


def qkv(rng, b=4, s=32, h=4, d=16):
    shape = (b, s, h, d)
    return (rng.standard_normal(shape).astype(np.float32),
            rng.standard_normal(shape).astype(np.float32),
            rng.standard_normal(shape).astype(np.float32))


@pytest.mark.parametrize("seq_shards", [2, 4, 8])
def test_ring_matches_dense(seq_shards, rng):
    mesh = build_mesh(MeshConfig(sequence=seq_shards,
                                 data=8 // seq_shards))
    q, k, v = qkv(rng)
    dense = np.asarray(causal_attention(*map(jnp.asarray, (q, k, v))))
    ring = make_ring_attention(mesh)
    out = np.asarray(jax.jit(ring)(q, k, v))
    np.testing.assert_allclose(out, dense, rtol=2e-5, atol=2e-5)


def test_ring_with_tensor_parallel_heads(rng):
    mesh = build_mesh(MeshConfig(sequence=2, tensor=2, data=2))
    q, k, v = qkv(rng)
    dense = np.asarray(causal_attention(*map(jnp.asarray, (q, k, v))))
    out = np.asarray(jax.jit(make_ring_attention(mesh))(q, k, v))
    np.testing.assert_allclose(out, dense, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("seq_shards", [2, 4])
def test_ulysses_matches_dense(seq_shards, rng):
    mesh = build_mesh(MeshConfig(sequence=seq_shards,
                                 data=8 // seq_shards))
    q, k, v = qkv(rng)
    dense = np.asarray(causal_attention(*map(jnp.asarray, (q, k, v))))
    out = np.asarray(jax.jit(make_ulysses_attention(mesh))(q, k, v))
    np.testing.assert_allclose(out, dense, rtol=2e-5, atol=2e-5)


def test_ring_attention_long_sequence_gradients(rng):
    """Gradients must flow through the ring (backward ppermutes)."""
    mesh = build_mesh(MeshConfig(sequence=4, data=2))
    q, k, v = qkv(rng, b=2, s=64, h=2, d=8)
    ring = make_ring_attention(mesh)

    def f_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def f_dense(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    g_ring = jax.jit(jax.grad(f_ring))(q, k, v)
    g_dense = jax.jit(jax.grad(f_dense))(jnp.asarray(q), jnp.asarray(k),
                                         jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense),
                               rtol=5e-4, atol=5e-5)


def test_transformer_with_ring_attention_end_to_end(rng):
    """Full sharded LM step with ring attention == dense-attention loss."""
    mesh = build_mesh(MeshConfig(data=2, sequence=4))
    config = TransformerConfig(vocab=64, d_model=64, n_heads=4, n_layers=2,
                               d_ff=128, max_seq=64, dtype=jnp.float32)
    tokens = rng.integers(0, 64, (2, 64)).astype(np.int32)

    plain = Transformer(config)
    params = plain.init_params(0)
    base_loss = float(plain.loss(params, jnp.asarray(tokens)))

    ring_model = Transformer(config, attention_fn=make_ring_attention(mesh),
                             mesh=mesh)
    trainer = ShardedTrainer(ring_model.loss, mesh, transformer_rule(mesh),
                             make_optimizer("sgd", 0.1))
    state = trainer.init_state(params)
    state, metrics = trainer.step(state, tokens)
    np.testing.assert_allclose(float(metrics["loss"]), base_loss, rtol=2e-4)
