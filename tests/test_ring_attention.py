"""Ring / Ulysses sequence-parallel attention vs dense causal attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_distributed_tpu.config import MeshConfig
from parameter_server_distributed_tpu.models.transformer import (
    Transformer, TransformerConfig, causal_attention)
from parameter_server_distributed_tpu.ops.ring_attention import (
    make_ring_attention, make_ulysses_attention)
from parameter_server_distributed_tpu.parallel.mesh import build_mesh
from parameter_server_distributed_tpu.parallel.train_step import (
    ShardedTrainer, make_optimizer)
from parameter_server_distributed_tpu.models.transformer import transformer_rule


def qkv(rng, b=4, s=32, h=4, d=16):
    shape = (b, s, h, d)
    return (rng.standard_normal(shape).astype(np.float32),
            rng.standard_normal(shape).astype(np.float32),
            rng.standard_normal(shape).astype(np.float32))


@pytest.mark.parametrize("seq_shards", [2, 4, 8])
def test_ring_matches_dense(seq_shards, rng):
    mesh = build_mesh(MeshConfig(sequence=seq_shards,
                                 data=8 // seq_shards))
    q, k, v = qkv(rng)
    dense = np.asarray(causal_attention(*map(jnp.asarray, (q, k, v))))
    ring = make_ring_attention(mesh)
    out = np.asarray(jax.jit(ring)(q, k, v))
    np.testing.assert_allclose(out, dense, rtol=2e-5, atol=2e-5)


def test_ring_with_tensor_parallel_heads(rng):
    mesh = build_mesh(MeshConfig(sequence=2, tensor=2, data=2))
    q, k, v = qkv(rng)
    dense = np.asarray(causal_attention(*map(jnp.asarray, (q, k, v))))
    out = np.asarray(jax.jit(make_ring_attention(mesh))(q, k, v))
    np.testing.assert_allclose(out, dense, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("seq_shards", [2, 4])
def test_ulysses_matches_dense(seq_shards, rng):
    mesh = build_mesh(MeshConfig(sequence=seq_shards,
                                 data=8 // seq_shards))
    q, k, v = qkv(rng)
    dense = np.asarray(causal_attention(*map(jnp.asarray, (q, k, v))))
    out = np.asarray(jax.jit(make_ulysses_attention(mesh))(q, k, v))
    np.testing.assert_allclose(out, dense, rtol=2e-5, atol=2e-5)


def test_ring_attention_long_sequence_gradients(rng):
    """Gradients must flow through the ring (backward ppermutes)."""
    mesh = build_mesh(MeshConfig(sequence=4, data=2))
    q, k, v = qkv(rng, b=2, s=64, h=2, d=8)
    ring = make_ring_attention(mesh)

    def f_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def f_dense(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    g_ring = jax.jit(jax.grad(f_ring))(q, k, v)
    g_dense = jax.jit(jax.grad(f_dense))(jnp.asarray(q), jnp.asarray(k),
                                         jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense),
                               rtol=5e-4, atol=5e-5)


def test_transformer_with_ring_attention_end_to_end(rng):
    """Full sharded LM step with ring attention == dense-attention loss."""
    mesh = build_mesh(MeshConfig(data=2, sequence=4))
    config = TransformerConfig(vocab=64, d_model=64, n_heads=4, n_layers=2,
                               d_ff=128, max_seq=64, dtype=jnp.float32)
    tokens = rng.integers(0, 64, (2, 64)).astype(np.int32)

    plain = Transformer(config)
    params = plain.init_params(0)
    base_loss = float(plain.loss(params, jnp.asarray(tokens)))

    ring_model = Transformer(config, attention_fn=make_ring_attention(mesh),
                             mesh=mesh)
    trainer = ShardedTrainer(ring_model.loss, mesh, transformer_rule(mesh),
                             make_optimizer("sgd", 0.1))
    state = trainer.init_state(params)
    state, metrics = trainer.step(state, tokens)
    np.testing.assert_allclose(float(metrics["loss"]), base_loss, rtol=2e-4)


def test_sharded_flash_matches_dense(rng):
    """make_sharded_flash_attention on a 3-axis mesh (data x fsdp x tensor)
    must equal dense causal attention — the flash kernel runs per
    batch/head shard over the full sequence."""
    from parameter_server_distributed_tpu.models.transformer import (
        make_sharded_flash_attention)

    mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    q, k, v = qkv(rng, b=4, s=128, h=4, d=16)  # seq 128: real kernel path
    dense = np.asarray(causal_attention(*map(jnp.asarray, (q, k, v))))
    flash = make_sharded_flash_attention(mesh)
    out = np.asarray(jax.jit(flash)(q, k, v))
    np.testing.assert_allclose(out, dense, rtol=5e-4, atol=5e-4)


def test_sharded_flash_lm_step_matches_dense(rng):
    """Full sharded LM train step on a 2-axis mesh with the pallas flash
    kernel: loss and updated params must match the dense-attention run
    (VERDICT round 1 item 5 — mesh + flash at the same time)."""
    from parameter_server_distributed_tpu.models.transformer import (
        make_sharded_flash_attention)

    mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    config = TransformerConfig(vocab=128, d_model=32, n_heads=4, n_layers=2,
                               d_ff=64, max_seq=128, dtype=jnp.float32)
    tokens = rng.integers(0, 128, (4, 128)).astype(np.int32)

    results = {}
    for name, attn in (("dense", None),
                       ("flash", make_sharded_flash_attention(mesh))):
        model = Transformer(config, attention_fn=attn, mesh=mesh)
        trainer = ShardedTrainer(model.loss, mesh, transformer_rule(mesh),
                                 make_optimizer("sgd", 0.1))
        state = trainer.init_state(model.init_params(0))
        state, metrics = trainer.step(state, tokens)
        results[name] = (float(metrics["loss"]),
                         np.asarray(state.params["layer0/attn/wq"]))
    assert np.isfinite(results["dense"][0])
    np.testing.assert_allclose(results["flash"][0], results["dense"][0],
                               rtol=1e-4)
    np.testing.assert_allclose(results["flash"][1], results["dense"][1],
                               rtol=2e-3, atol=2e-5)


def test_select_attention_switch(rng):
    """select_attention: every CLI choice returns a working attention_fn
    (or None for dense) on the appropriate mesh."""
    from parameter_server_distributed_tpu.models.transformer import (
        flash_attention_auto, select_attention)

    assert select_attention("dense", None) is None
    assert select_attention("flash", None) is flash_attention_auto
    mesh = build_mesh(MeshConfig(sequence=2, data=4))
    q, k, v = qkv(rng)
    dense = np.asarray(causal_attention(*map(jnp.asarray, (q, k, v))))
    for name in ("ring", "ulysses", "ulysses_flash", "ulysses_xla_flash"):
        fn = select_attention(name, mesh)
        np.testing.assert_allclose(np.asarray(jax.jit(fn)(q, k, v)), dense,
                                   rtol=2e-5, atol=2e-5)
    with pytest.raises(ValueError, match="unknown attention"):
        select_attention("sliding", mesh)
    with pytest.raises(ValueError, match="needs a mesh"):
        select_attention("ring", None)


def test_ulysses_flash_inner_kernel_and_gradients(rng):
    """make_ulysses_attention(inner=flash): the pallas kernel runs on each
    device's gathered full sequence; output AND gradients match the dense
    composition."""
    from parameter_server_distributed_tpu.models.transformer import (
        flash_attention_auto)

    mesh = build_mesh(MeshConfig(sequence=2, data=4))
    q, k, v = qkv(rng)

    def loss(fn, q, k, v):
        return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    uf = make_ulysses_attention(mesh, inner=flash_attention_auto)
    val_f, grads_f = jax.jit(
        jax.value_and_grad(lambda *a: loss(uf, *a), argnums=(0, 1, 2)))(q, k, v)
    val_d, grads_d = jax.jit(
        jax.value_and_grad(lambda *a: loss(causal_attention, *a),
                           argnums=(0, 1, 2)))(q, k, v)
    np.testing.assert_allclose(float(val_f), float(val_d), rtol=1e-5)
    for gf, gd, name in zip(grads_f, grads_d, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   rtol=2e-4, atol=2e-4, err_msg=f"d{name}")


def test_ring_block_remat_gradients_match(rng):
    """The rematted ring block update is numerically invisible: gradients
    equal the dense reference (scores recomputed in backward)."""
    mesh = build_mesh(MeshConfig(sequence=4, data=2))
    q, k, v = qkv(rng, b=2, s=64, h=2, d=8)
    ring = make_ring_attention(mesh)

    def loss(fn, q, k, v):
        return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    val_r, grads_r = jax.jit(
        jax.value_and_grad(lambda *a: loss(ring, *a), argnums=(0, 1, 2)))(q, k, v)
    val_d, grads_d = jax.jit(
        jax.value_and_grad(lambda *a: loss(causal_attention, *a),
                           argnums=(0, 1, 2)))(q, k, v)
    np.testing.assert_allclose(float(val_r), float(val_d), rtol=1e-5)
    for gr, gd, name in zip(grads_r, grads_d, "qkv"):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=2e-4, atol=2e-4, err_msg=f"d{name}")


def test_ring_and_ulysses_gqa_unexpanded_kv(rng):
    """GQA contract: attention fns take kv_heads-sized K/V (the ring
    rotates / Ulysses all-to-alls the small tensors) and match the
    expanded dense reference."""
    from parameter_server_distributed_tpu.models.transformer import repeat_kv

    b, s, h, kv, d = 4, 32, 8, 2, 16
    q = rng.standard_normal((b, s, h, d)).astype(np.float32)
    k = rng.standard_normal((b, s, kv, d)).astype(np.float32)
    v = rng.standard_normal((b, s, kv, d)).astype(np.float32)
    dense = np.asarray(causal_attention(
        jnp.asarray(q), repeat_kv(jnp.asarray(k), h // kv),
        repeat_kv(jnp.asarray(v), h // kv)))

    mesh = build_mesh(MeshConfig(sequence=4, data=2))
    out_ring = np.asarray(jax.jit(make_ring_attention(mesh))(q, k, v))
    np.testing.assert_allclose(out_ring, dense, rtol=2e-5, atol=2e-5)

    # kv=2 divides seq axis 2: the small-transfer path
    mesh2 = build_mesh(MeshConfig(sequence=2, data=4))
    out_uly = np.asarray(jax.jit(make_ulysses_attention(mesh2))(q, k, v))
    np.testing.assert_allclose(out_uly, dense, rtol=2e-5, atol=2e-5)

    # kv=2 does NOT divide seq axis 4: the expand-first fallback
    mesh4 = build_mesh(MeshConfig(sequence=4, data=2))
    out_uly4 = np.asarray(jax.jit(make_ulysses_attention(mesh4))(q, k, v))
    np.testing.assert_allclose(out_uly4, dense, rtol=2e-5, atol=2e-5)


def test_mqa_with_tensor_parallel_heads(rng):
    """MQA (kv_heads=1) + tensor-sharded heads: kv_heads cannot be sharded
    by the tensor axis, so the wrappers pre-expand K/V — the pre-GQA-
    refactor behavior for this corner (regression test)."""
    from parameter_server_distributed_tpu.models.transformer import (
        make_sharded_flash_attention, repeat_kv)

    b, s, h, d = 4, 32, 4, 16
    q = rng.standard_normal((b, s, h, d)).astype(np.float32)
    k = rng.standard_normal((b, s, 1, d)).astype(np.float32)
    v = rng.standard_normal((b, s, 1, d)).astype(np.float32)
    dense = np.asarray(causal_attention(
        jnp.asarray(q), repeat_kv(jnp.asarray(k), h),
        repeat_kv(jnp.asarray(v), h)))

    mesh = build_mesh(MeshConfig(sequence=2, tensor=2, data=2))
    for maker in (make_ring_attention, make_ulysses_attention):
        out = np.asarray(jax.jit(maker(mesh))(q, k, v))
        np.testing.assert_allclose(out, dense, rtol=2e-5, atol=2e-5,
                                   err_msg=maker.__name__)

    fmesh = build_mesh(MeshConfig(tensor=2, data=4))
    out = np.asarray(jax.jit(make_sharded_flash_attention(fmesh))(q, k, v))
    np.testing.assert_allclose(out, dense, rtol=2e-5, atol=2e-5)
