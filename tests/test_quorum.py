"""K-of-N quorum barriers + bounded-staleness straggler folding
(elastic/, ISSUE 13).

Core-level units of the quorum close (grace window, elastic threshold,
contributor-mean math), the forward stale fold (staleness-1 landing,
per-(worker, tensor) dedup, learning-rate damping against hand-computed
sequences), the shared damping policy (async_sgd/damping.py), a
lockcheck-marked concurrent push/seal/drain hammer, and the gRPC
scenario acceptance: a 4-worker run with one netsim-delayed straggler
under PSDT_QUORUM=0.75 closes every barrier within grace (pst-trace
postmortem: zero stalled iterations) while its loss curve tracks the
fixed-membership f32 run.
"""

import os
import threading
import time

import numpy as np
import pytest

from parameter_server_distributed_tpu.async_sgd.damping import (
    DEFAULT_BETA, StalenessDamping, async_damping)
from parameter_server_distributed_tpu.core.optimizer import SGD
from parameter_server_distributed_tpu.core.ps_core import ParameterServerCore
from parameter_server_distributed_tpu.elastic import quorum as equorum


def _core(total=3, quorum=0.5, grace_ms=0.0, **kw):
    core = ParameterServerCore(total_workers=total, optimizer=SGD(1.0),
                               quorum=quorum, quorum_grace_ms=grace_ms,
                               **kw)
    core.initialize_parameters({"w": np.full(4, 4.0, np.float32)})
    return core


def _grad(value):
    return {"w": np.full(4, float(value), np.float32)}


# ------------------------------------------------------------------ policy

def test_quorum_threshold_math():
    assert equorum.threshold(0.75, 4) == 3
    assert equorum.threshold(0.5, 4) == 2
    assert equorum.threshold(0.5, 3) == 2  # ceil(1.5)
    assert equorum.threshold(0.75, 1) == 1
    assert equorum.threshold(0.1, 2) == 1
    assert equorum.threshold(0.99, 4) == 4
    assert equorum.threshold(0.5, 0) == 1  # degenerate width


def test_draining_preshrinks_threshold_hand_computed():
    """ISSUE 14 satellite (the PR 13 leftover): DRAINING caps K at
    width - draining, hand-computed before/after the drain."""
    # before any drain: ceil(q * width) as ever
    assert equorum.threshold(0.9, 4) == 4
    assert equorum.threshold(0.75, 8) == 6
    # one drain announced: K = min(ceil(0.9*4)=4, 4-1=3) = 3
    assert equorum.threshold(0.9, 4, draining=1) == 3
    # three drains: K = min(ceil(0.75*8)=6, 8-3=5) = 5
    assert equorum.threshold(0.75, 8, draining=3) == 5
    # the cap only ever SHRINKS K: ceil(0.5*4)=2 < 4-1=3 stays 2
    assert equorum.threshold(0.5, 4, draining=1) == 2
    # floor: a fully-draining barrier still needs one contributor
    assert equorum.threshold(0.9, 2, draining=2) == 1
    assert equorum.threshold(0.9, 4, draining=9) == 1


def test_graceful_drain_costs_zero_grace_windows():
    """With one member DRAINING, the close fires the moment every
    NON-draining member has committed — no grace window, even one set
    to 60 s (the pre-shrink satellite's end-to-end contract)."""
    class Reg:
        live = 4
        drain = ()

        def __call__(self):
            return self.live

        def draining(self):
            return self.drain

    reg = Reg()
    core = ParameterServerCore(total_workers=99, optimizer=SGD(1.0),
                               live_workers_fn=reg,
                               live_workers_ttl_s=0.0,
                               quorum=0.75, quorum_grace_ms=60_000.0)
    core.initialize_parameters({"w": np.full(4, 4.0, np.float32)})
    for worker in range(3):
        core.receive_gradients(worker, 1, _grad(1))
    # K = ceil(0.75*4) = 3 reached, but the 60 s grace gates the close
    _, ready, _, _ = core.check_sync_status(1)
    assert not ready
    # worker 3 announces its drain: the same three commits now close
    # IMMEDIATELY (every non-draining member is in), zero grace paid
    reg.drain = (3,)
    _, ready, received, total = core.check_sync_status(1)
    assert ready and received == 3 and total == 4


def test_drain_skip_never_cuts_off_a_healthy_worker():
    """The skip-the-grace close counts only NON-draining commits: a
    DRAINING worker finishing its last in-flight iteration must not
    let the close fire while a healthy worker is the absentee — the
    grace window exists for exactly that worker."""
    class Reg:
        live = 4
        drain = (3,)

        def __call__(self):
            return self.live

        def draining(self):
            return self.drain

    core = ParameterServerCore(total_workers=99, optimizer=SGD(1.0),
                               live_workers_fn=Reg(),
                               live_workers_ttl_s=0.0,
                               quorum=0.75, quorum_grace_ms=60_000.0)
    core.initialize_parameters({"w": np.full(4, 4.0, np.float32)})
    # the DRAINING worker (3) commits its last iteration + two healthy
    # peers: received = 3 = K, but only 2 of the 3 NON-draining members
    # are in — the grace must still gate the close
    for worker in (0, 1, 3):
        core.receive_gradients(worker, 1, _grad(1))
    _, ready, _, _ = core.check_sync_status(1)
    assert not ready
    # the last healthy worker lands: full barrier, immediate close
    core.receive_gradients(2, 1, _grad(1))
    _, ready, received, total = core.check_sync_status(1)
    assert ready and received == 4 and total == 4


def test_quorum_fraction_parsing(monkeypatch):
    monkeypatch.delenv(equorum.ENV_QUORUM, raising=False)
    assert equorum.quorum_fraction() == 0.0          # default off
    assert equorum.quorum_fraction(0.75) == 0.75     # config override
    assert equorum.quorum_fraction(1.0) == 0.0       # 1.0 == all-of-N
    monkeypatch.setenv(equorum.ENV_QUORUM, "0.6")
    assert equorum.quorum_fraction() == 0.6
    monkeypatch.setenv(equorum.ENV_QUORUM, "1.5")
    with pytest.raises(ValueError):
        equorum.quorum_fraction()


def test_damping_policy_units(monkeypatch):
    monkeypatch.delenv("PSDT_STALENESS_BETA", raising=False)
    d = StalenessDamping()
    assert d.beta == DEFAULT_BETA
    assert d.scale(0) == 1.0
    assert d.scale(1) == DEFAULT_BETA
    assert d.scale(3) == pytest.approx(DEFAULT_BETA ** 3)
    src = {"w": np.full(2, 8.0, np.float32)}
    out = d.damp(src, 1)
    np.testing.assert_allclose(out["w"], 4.0)
    np.testing.assert_allclose(src["w"], 8.0)  # never mutates the input
    # async-mode damping arms ONLY on an explicit env beta
    assert async_damping() is None
    monkeypatch.setenv("PSDT_STALENESS_BETA", "0.25")
    armed = async_damping()
    assert armed is not None and armed.scale(2) == pytest.approx(0.0625)
    monkeypatch.setenv("PSDT_STALENESS_BETA", "1.5")
    with pytest.raises(ValueError):
        StalenessDamping()


# ------------------------------------------------------------- quorum close

def test_quorum_off_by_default_is_all_of_n(monkeypatch):
    monkeypatch.delenv(equorum.ENV_QUORUM, raising=False)
    core = ParameterServerCore(total_workers=3, optimizer=SGD(1.0))
    core.initialize_parameters({"w": np.full(4, 4.0, np.float32)})
    assert core.quorum == 0.0
    core.receive_gradients(0, 1, _grad(1))
    core.receive_gradients(1, 1, _grad(1))
    time.sleep(0.02)
    _, ready, received, total = core.check_sync_status(1)
    assert not ready and received == 2 and total == 3  # parks forever


def test_quorum_close_waits_for_grace_then_fires():
    core = _core(total=3, quorum=0.5, grace_ms=60.0)
    core.receive_gradients(0, 1, _grad(2))
    r = core.receive_gradients(1, 1, _grad(2))
    # K=2 reached, but the grace window is still running
    assert not r.aggregation_complete
    _, ready, _, _ = core.check_sync_status(1)
    assert not ready
    time.sleep(0.08)
    _, ready, received, total = core.check_sync_status(1)
    assert ready and received == 2 and total == 3
    # contributor mean over the 2 contributors: 4 - mean(2, 2) = 2
    np.testing.assert_allclose(core.get_parameters()["w"], 2.0)


def test_quorum_full_width_still_closes_immediately():
    core = _core(total=2, quorum=0.5, grace_ms=10_000.0)
    core.receive_gradients(0, 1, _grad(1))
    r = core.receive_gradients(1, 1, _grad(3))
    # all of N present: the close never waits out the grace window
    assert r.aggregation_complete and r.workers_received == 2
    np.testing.assert_allclose(core.get_parameters()["w"], 2.0)


def test_quorum_threshold_follows_elastic_width():
    class Reg:
        live = 4

        def __call__(self):
            return self.live

    reg = Reg()
    core = ParameterServerCore(total_workers=99, optimizer=SGD(1.0),
                               live_workers_fn=reg, live_workers_ttl_s=0.0,
                               quorum=0.75, quorum_grace_ms=0.0)
    core.initialize_parameters({"w": np.full(4, 4.0, np.float32)})
    core.receive_gradients(0, 1, _grad(1))
    core.receive_gradients(1, 1, _grad(1))
    _, ready, _, _ = core.check_sync_status(1)
    assert not ready  # K = ceil(0.75 * 4) = 3 > 2
    reg.live = 2      # shrink: K = ceil(0.75 * 2) = 2 — already there
    _, ready, received, total = core.check_sync_status(1)
    assert ready and received == 2 and total == 2


def test_quorum_streaming_sync_only():
    # buffered mode keeps the classic all-of-N close even with a quorum
    core = ParameterServerCore(total_workers=3, optimizer=SGD(1.0),
                               aggregation="buffered", quorum=0.5,
                               quorum_grace_ms=0.0)
    core.initialize_parameters({"w": np.full(4, 4.0, np.float32)})
    core.receive_gradients(0, 1, _grad(1))
    core.receive_gradients(1, 1, _grad(1))
    time.sleep(0.01)
    _, ready, _, _ = core.check_sync_status(1)
    assert not ready


# -------------------------------------------------------- straggler folding

def test_straggler_folds_forward_at_staleness_one(monkeypatch):
    monkeypatch.delenv("PSDT_STALENESS_BETA", raising=False)
    core = _core(total=3, quorum=0.5, grace_ms=0.0)
    core.receive_gradients(0, 1, _grad(2))
    core.receive_gradients(1, 1, _grad(2))
    _, ready, _, _ = core.check_sync_status(1)
    assert ready  # quorum close without worker 2
    np.testing.assert_allclose(core.get_parameters()["w"], 2.0)

    # worker 2's push for the SEALED iteration 1: folded into iteration
    # 2 at staleness 1, lr-damped — not rejected
    r = core.receive_gradients(2, 1, _grad(8))
    assert r.success and r.aggregation_complete
    assert "staleness 1" in r.message and "folded into iteration 2" in r.message

    # workers 0+1 run iteration 2; the straggler's damped carry
    # (0.5 * 8 = 4) is already a contribution there
    core.receive_gradients(0, 2, _grad(1))
    _, ready, received, _ = core.check_sync_status(2)
    # contributors: {2 (stale), 0} = K; grace 0 => closes on this poll
    assert ready and received == 2
    # mean(damped 4, fresh 1) = 2.5; params 2 - 2.5 = -0.5
    np.testing.assert_allclose(core.get_parameters()["w"], -0.5)


def test_stale_fold_dedup_absorbs_the_real_push(monkeypatch):
    monkeypatch.delenv("PSDT_STALENESS_BETA", raising=False)
    core = _core(total=3, quorum=0.6, grace_ms=0.0)  # K = 2
    core.receive_gradients(0, 1, _grad(2))
    core.receive_gradients(1, 1, _grad(2))
    _, ready, _, _ = core.check_sync_status(1)
    assert ready  # quorum close without worker 2; params 4 - 2 = 2
    r = core.receive_gradients(2, 1, _grad(8))  # stale fold -> iteration 2
    assert "folded into iteration 2" in r.message
    # the straggler's REAL push for iteration 2 dedups per (worker,
    # tensor): first-push-wins, no double count — and iteration 2 is
    # still open (1 of K=2 contributors)
    r2 = core.receive_gradients(2, 2, _grad(100))
    assert r2.success and "duplicate" in r2.message
    core.receive_gradients(0, 2, _grad(2))
    time.sleep(0.01)
    _, ready, _, _ = core.check_sync_status(2)
    assert ready
    # iteration-2 mean = mean(damped 4, fresh 2) = 3; params 2 - 3 = -1
    # (the 100-valued duplicate must be invisible)
    np.testing.assert_allclose(core.get_parameters()["w"], -1.0)


def test_stale_fold_is_idempotent_on_retry(monkeypatch):
    monkeypatch.delenv("PSDT_STALENESS_BETA", raising=False)
    core = _core(total=3, quorum=0.6, grace_ms=0.0)  # K = 2
    core.receive_gradients(0, 1, _grad(2))
    core.receive_gradients(1, 1, _grad(2))
    core.check_sync_status(1)
    r1 = core.receive_gradients(2, 1, _grad(8))
    r2 = core.receive_gradients(2, 1, _grad(8))  # RPC retry, same payload
    assert "folded into iteration 2" in r1.message
    assert r2.success  # absorbed, not double-folded
    core.receive_gradients(0, 2, _grad(2))
    time.sleep(0.01)
    _, ready, _, _ = core.check_sync_status(2)
    assert ready
    np.testing.assert_allclose(core.get_parameters()["w"], -1.0)


def test_stale_fold_respects_staleness_bound():
    core = _core(total=2, quorum=0.5, grace_ms=0.0)
    from parameter_server_distributed_tpu.obs import stats as obs_stats
    before = obs_stats.REGISTRY.snapshot()["counters"].get(
        "ps.stale.folds", 0)
    # close iterations 1 AND 2 with worker 0 alone
    for it in (1, 2):
        core.receive_gradients(0, it, _grad(1))
        time.sleep(0.005)
        _, ready, _, _ = core.check_sync_status(it)
        assert ready
    # worker 1's push for iteration 1 is 2 behind the next open
    # iteration (3) — past max(1, staleness_bound): plain late push
    r = core.receive_gradients(1, 1, _grad(8))
    assert r.success and r.aggregation_complete
    assert "already aggregated" in r.message
    after = obs_stats.REGISTRY.snapshot()["counters"].get(
        "ps.stale.folds", 0)
    assert after == before


def test_stale_fold_via_chunk_streamed_sink(monkeypatch):
    """The fused data plane path: a PushSink whose chunks land after the
    quorum seal redirects per chunk and commits the stale contribution."""
    monkeypatch.delenv("PSDT_STALENESS_BETA", raising=False)
    core = _core(total=3, quorum=0.5, grace_ms=0.0)
    core.receive_gradients(0, 1, _grad(2))
    core.receive_gradients(1, 1, _grad(2))
    core.check_sync_status(1)  # quorum close
    sink = core.begin_push(2, 1)
    sink.fold(_grad(8))
    r = sink.commit()
    assert r.success and "staleness 1" in r.message
    core.receive_gradients(0, 2, _grad(1))
    time.sleep(0.01)
    _, ready, _, _ = core.check_sync_status(2)
    assert ready
    np.testing.assert_allclose(core.get_parameters()["w"], -0.5)


def test_async_mode_damping_armed_by_env(monkeypatch):
    monkeypatch.setenv("PSDT_STALENESS_BETA", "0.5")
    core = ParameterServerCore(total_workers=2, optimizer=SGD(1.0),
                               staleness_bound=2)
    # bootstrap, then advance the PS to iteration 3
    core.receive_gradients(0, 1, {"w": np.full(4, 4.0, np.float32)})
    core.receive_gradients(0, 3, _grad(1))      # fresh: 4 - 1 = 3
    r = core.receive_gradients(1, 2, _grad(2))  # staleness 1: - 0.5*2
    assert r.success
    np.testing.assert_allclose(core.get_parameters()["w"], 2.0)


def test_async_mode_undamped_without_env(monkeypatch):
    monkeypatch.delenv("PSDT_STALENESS_BETA", raising=False)
    core = ParameterServerCore(total_workers=2, optimizer=SGD(1.0),
                               staleness_bound=2)
    core.receive_gradients(0, 1, {"w": np.full(4, 4.0, np.float32)})
    core.receive_gradients(0, 3, _grad(1))
    core.receive_gradients(1, 2, _grad(2))  # staleness 1, full strength
    np.testing.assert_allclose(core.get_parameters()["w"], 1.0)


# ----------------------------------------------------------------- scenario

def _run_quorum_cluster(tmp_path, tag, iterations, workers_n=4,
                        quorum=0.0, grace_ms=120.0,
                        straggler_delay_ms=None, flight_dir=None):
    """4-worker gRPC cluster; optionally one worker rides a netsim
    relay (the straggler) and the PS closes at a quorum.  Returns the
    per-worker loss lists."""
    from parameter_server_distributed_tpu.cli.worker_main import build_worker
    from parameter_server_distributed_tpu.config import (
        CoordinatorConfig, ParameterServerConfig, WorkerConfig)
    from parameter_server_distributed_tpu.obs import flight
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient
    from parameter_server_distributed_tpu.server.coordinator_service import (
        Coordinator)
    from parameter_server_distributed_tpu.server.ps_service import (
        ParameterServer)
    from parameter_server_distributed_tpu.utils.netsim import ThrottledRelay

    if flight_dir:
        flight.enable(flight_dir, role=f"cluster-{tag}", records=65536)
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0,
        ps_address="127.0.0.1", ps_port=1, reap_period_s=600.0))
    coord_port = coordinator.start()
    ps = ParameterServer(
        ParameterServerConfig(
            bind_address="127.0.0.1", port=0, total_workers=workers_n,
            checkpoint_interval=10**6, checkpoint_dir=str(tmp_path / tag),
            learning_rate=0.05, elastic=True, live_workers_ttl_s=0.0,
            autosave_period_s=600.0, quorum=quorum,
            quorum_grace_ms=grace_ms),
        live_workers_fn=coordinator.core.width_provider())
    ps_port = ps.start()
    coordinator.core.set_parameter_server_address("127.0.0.1", ps_port)
    relay = None
    workers = []
    try:
        for wid in range(workers_n):
            w = build_worker(WorkerConfig(
                coordinator_address=f"127.0.0.1:{coord_port}",
                worker_id=wid, address="127.0.0.1", port=50400 + wid,
                batch_size=16, heartbeat_period_s=600.0))
            w.initialize()
            workers.append(w)
        if straggler_delay_ms:
            # the LAST worker's PS leg rides a netsim relay: its pushes
            # arrive ~delay late, landing after the quorum seal
            relay = ThrottledRelay(ps_port,
                                   delay_ms=straggler_delay_ms / 2.0)
            relay_port = relay.start()
            straggler = workers[-1]
            straggler._ps.close()
            straggler._ps = PSClient(f"127.0.0.1:{relay_port}")
            straggler._reset_wire_negotiation()
            straggler._next_params = None

        losses: dict[int, list[float]] = {w.config.worker_id: []
                                          for w in workers}
        errors: list = []

        def loop(w):
            try:
                for it in range(iterations):
                    losses[w.config.worker_id].append(w.run_iteration(it))
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append((w.config.worker_id, exc))

        threads = [threading.Thread(target=loop, args=(w,),
                                    name=f"{tag}-w{w.config.worker_id}")
                   for w in workers]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert errors == [], errors
        assert all(len(ls) == iterations for ls in losses.values())
        return losses
    finally:
        for w in workers:
            w.shutdown()
        if relay is not None:
            relay.stop()
        coordinator.stop()
        ps.stop()
        if flight_dir:
            flight.disable()


def test_quorum_netsim_straggler_zero_stalled_iterations(tmp_path,
                                                         monkeypatch):
    """ISSUE 13 acceptance: K=3-of-4 under one netsim-delayed straggler
    closes every barrier within grace — the pst-trace postmortem shows
    ZERO stalled iterations — and the loss curve tracks the
    fixed-membership f32 run (loose allclose: the straggler's damped
    forward folds perturb, they must not derail)."""
    from parameter_server_distributed_tpu.cli.trace_main import (
        main as trace_main)
    from parameter_server_distributed_tpu.obs import postmortem

    monkeypatch.delenv("PSDT_STALENESS_BETA", raising=False)
    monkeypatch.delenv("PSDT_QUORUM", raising=False)
    # the straggler's delay is injected at the TCP layer; the same-host
    # shm rings would negotiate past the relay and erase it
    monkeypatch.setenv("PSDT_SHM", "0")
    iterations = 5
    clean = _run_quorum_cluster(tmp_path, "clean", iterations)
    flight_dir = str(tmp_path / "flight")
    chaos = _run_quorum_cluster(
        tmp_path, "quorum", iterations, quorum=0.75, grace_ms=120.0,
        straggler_delay_ms=600.0, flight_dir=flight_dir)

    events = postmortem.merge_events(postmortem.load_rings(flight_dir))
    # the quorum actually fired (the straggler missed grace at least once)
    seals = [e for e in events if e["event"] == "quorum.seal"]
    assert seals, "no quorum close recorded — straggler never sealed out?"
    folds = [e for e in events if e["event"] == "stale.fold"]
    assert folds and all(e["worker"] == 3 for e in folds)
    # ZERO stalled iterations: no barrier waited on the straggler past
    # grace (generous scheduling slack; a stall would be the 60 s fused
    # barrier timeout)
    assert postmortem.stalled_iterations(events, stall_s=2.0) == []
    assert trace_main([flight_dir, "--stalled=2.0"]) == 0
    # the timeline of a quorum-closed iteration names the worker left
    # outside the close
    quorum_iterations = sorted({e["iteration"] for e in seals})
    tl = postmortem.iteration_timeline(events, quorum_iterations[0])
    assert tl.get("quorum", {}).get("outside") == [3]

    # loss curves: the three healthy workers track the fixed-membership
    # run within a loose band (damped stale folds perturb the
    # trajectory; they must not derail it), and every loss is finite
    for wid in range(3):
        # index 0 is the bootstrap seed (loss NaN by contract)
        c, q = np.asarray(clean[wid])[1:], np.asarray(chaos[wid])[1:]
        assert np.isfinite(c).all() and np.isfinite(q).all()
        np.testing.assert_allclose(q, c, rtol=0.5, atol=0.3,
                                   err_msg=f"worker {wid} loss diverged")


def test_preemption_chaos_drive_zero_stalled_iterations(tmp_path,
                                                        monkeypatch):
    """Preemption chaos under 4 workers with the quorum armed: one
    worker DIES mid-run (no leave announce — the reap evicts it), a
    second is drained via the pst-ctl path mid-run, the remaining two
    finish — and the pst-trace postmortem shows ZERO stalled
    iterations: no barrier ever waited past grace on the gone worker
    (quorum close), and the eviction/drain narrowed the width for the
    rest."""
    from parameter_server_distributed_tpu.cli.worker_main import build_worker
    from parameter_server_distributed_tpu.config import (
        CoordinatorConfig, ParameterServerConfig, WorkerConfig)
    from parameter_server_distributed_tpu.elastic.membership import (
        MembershipClient)
    from parameter_server_distributed_tpu.obs import flight, postmortem
    from parameter_server_distributed_tpu.server.coordinator_service import (
        Coordinator)
    from parameter_server_distributed_tpu.server.ps_service import (
        ParameterServer)

    monkeypatch.delenv("PSDT_STALENESS_BETA", raising=False)
    iterations = 8
    flight_dir = str(tmp_path / "flight")
    flight.enable(flight_dir, role="chaos", records=65536)
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0,
        ps_address="127.0.0.1", ps_port=1, reap_period_s=600.0))
    coord_port = coordinator.start()
    ps = ParameterServer(
        ParameterServerConfig(
            bind_address="127.0.0.1", port=0, total_workers=4,
            checkpoint_interval=10**6, checkpoint_dir=str(tmp_path / "ck"),
            learning_rate=0.05, elastic=True, live_workers_ttl_s=0.0,
            autosave_period_s=600.0, quorum=0.75, quorum_grace_ms=100.0),
        live_workers_fn=coordinator.core.width_provider())
    ps_port = ps.start()
    coordinator.core.set_parameter_server_address("127.0.0.1", ps_port)
    workers = []
    try:
        for wid in range(4):
            w = build_worker(WorkerConfig(
                coordinator_address=f"127.0.0.1:{coord_port}",
                worker_id=wid, address="127.0.0.1", port=50500 + wid,
                batch_size=16, heartbeat_period_s=600.0))
            w.initialize()
            workers.append(w)

        done: dict[int, int] = {wid: -1 for wid in range(4)}
        dead = threading.Event()
        errors: list = []

        def loop(w, last_it):
            try:
                for it in range(iterations):
                    if w.config.worker_id == 2 and w.drain_requested:
                        break  # the run()-loop drain contract
                    w.run_iteration(it)
                    done[w.config.worker_id] = it
                    if last_it is not None and it >= last_it:
                        dead.set()  # worker 3 "kill -9": just stops
                        return
            except Exception as exc:  # noqa: BLE001 — surfaced below
                errors.append((w.config.worker_id, exc))

        threads = [threading.Thread(
            target=loop, args=(w, 2 if w.config.worker_id == 3 else None),
            name=f"chaos-w{w.config.worker_id}") for w in workers]
        for t in threads:
            t.start()

        # the "killed" worker went silent after iteration 2: age its
        # heartbeat and reap — membership marks it GONE, the generation
        # bump narrows the barrier at the PS's next width read
        assert dead.wait(timeout=120)
        coordinator.core._workers[3].last_heartbeat = -1e9
        evicted = coordinator.core.remove_stale_workers(timeout_s=30.0)
        assert evicted == [3]

        # mid-run ctl drain of worker 2 (DRAINING at the coordinator;
        # the worker's heartbeat-cadence poll latches it — heartbeats
        # are parked in this test, so tick the poll directly)
        while done[2] < 4 and not errors:
            time.sleep(0.02)
        ctl = MembershipClient(f"127.0.0.1:{coord_port}")
        try:
            resp = ctl.drain(2)
            assert resp is not None and resp.success
        finally:
            ctl.close()
        workers[2]._poll_drain()
        assert workers[2].drain_requested
        # its loop stops between iterations; the leave announce at
        # shutdown narrows the width for the survivors
        threads[2].join(timeout=120)
        workers[2].shutdown()

        for t in threads:
            t.join(timeout=120)
        assert errors == [], errors
        assert done[0] == iterations - 1 and done[1] == iterations - 1
        assert done[3] == 2  # died on schedule
    finally:
        for w in workers:
            w.shutdown()
        coordinator.stop()
        ps.stop()
        flight.disable()

    events = postmortem.merge_events(postmortem.load_rings(flight_dir))
    # the acceptance: ZERO stalled iterations — no barrier waited past
    # grace on the gone worker (generous slack over the 100 ms grace;
    # a real stall would be the 60 s fused-barrier timeout)
    assert postmortem.stalled_iterations(events, stall_s=5.0) == []
    evicts = [e for e in events if e["event"] == "elastic.evict"]
    assert [e["worker"] for e in evicts] == [3]
    drains = [e for e in events if e["event"] == "elastic.drain"]
    assert any(e["worker"] == 2 for e in drains)
    # the narrative names the membership churn
    narrative = postmortem.failure_narrative(
        postmortem.load_rings(flight_dir), events)
    assert narrative["membership"]["evictions"] == [{"worker": 3}]


# ------------------------------------------------------------------- hammer

@pytest.mark.lockcheck
def test_quorum_concurrent_push_seal_drain_hammer(monkeypatch):
    """Concurrent pushes, quorum polls, and an elastic width flapping
    under a generation-aware provider — the push/seal/drain interleaving
    hammer, run under PSDT_LOCK_CHECK=1 (conftest lockcheck marker)."""
    monkeypatch.delenv("PSDT_STALENESS_BETA", raising=False)

    class Reg:
        def __init__(self):
            self.live = 4
            self.gen = 0

        def __call__(self):
            return self.live

        def generation(self):
            return self.gen

    reg = Reg()
    core = ParameterServerCore(total_workers=99, optimizer=SGD(0.001),
                               live_workers_fn=reg, live_workers_ttl_s=60.0,
                               quorum=0.75, quorum_grace_ms=0.0, stripes=2)
    core.initialize_parameters(
        {f"w{i}": np.ones(64, np.float32) for i in range(8)})
    iterations = 12
    errors: list = []
    stop = threading.Event()

    def worker_loop(wid: int):
        try:
            rng = np.random.default_rng(wid)
            for it in range(1, iterations + 1):
                grads = {f"w{i}": rng.standard_normal(64).astype(np.float32)
                         for i in range(8)}
                sink = core.begin_push(wid, it)
                for i in range(8):  # chunked
                    sink.fold({f"w{i}": grads[f"w{i}"]})
                sink.commit()
                core.wait_for_aggregation(it, timeout=10.0)
        except Exception as exc:  # noqa: BLE001 — surfaced below
            errors.append((wid, exc))

    def drain_loop():
        while not stop.is_set():
            reg.live = 3
            reg.gen += 1
            time.sleep(0.003)
            reg.live = 4
            reg.gen += 1
            time.sleep(0.003)

    threads = [threading.Thread(target=worker_loop, args=(wid,),
                                name=f"hammer-w{wid}", daemon=True)
               for wid in range(4)]
    drain = threading.Thread(target=drain_loop, name="hammer-drain",
                             daemon=True)
    for t in threads:
        t.start()
    drain.start()
    for t in threads:
        t.join(timeout=60)
    stop.set()
    drain.join(timeout=5)
    assert errors == []
    assert core.current_iteration == iterations
    # every iteration the workers pushed eventually published a barrier
    ready, _, _ = core.wait_for_aggregation(iterations, timeout=10.0)
    assert ready
