"""Continuous-batching decode server (models/serving.py).

The invariant everything hangs on: a request decoded through the slot
server — padded bucket prefill, cache splice, ragged shared-batch steps,
slot reuse — produces EXACTLY the tokens of a standalone greedy
``generate`` on the same prompt.  Staggered admission and slot recycling
must not perturb other rows.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_distributed_tpu.models.generation import generate
from parameter_server_distributed_tpu.models.serving import (DecodeServer,
                                                             _bucket)
from parameter_server_distributed_tpu.models.transformer import (
    Transformer, TransformerConfig)


def tiny(**kw):
    cfg = dict(vocab=96, d_model=48, n_heads=4, n_layers=2, d_ff=96,
               max_seq=128, dtype=jnp.float32)
    cfg.update(kw)
    return Transformer(TransformerConfig(**cfg))


def reference(model, params, prompt, n):
    out = generate(model, params, jnp.asarray([prompt], jnp.int32), n)
    return list(np.asarray(out)[0])


def test_bucket_rounding():
    assert _bucket(1) == 16 and _bucket(16) == 16 and _bucket(17) == 32


def test_single_request_matches_generate(rng):
    model = tiny()
    params = model.init_params(0)
    prompt = list(rng.integers(0, 96, 7))
    srv = DecodeServer(model, params, slots=4, max_len=64)
    rid = srv.submit(prompt, max_new_tokens=6)
    results = srv.run_to_completion()
    assert results[rid] == reference(model, params, prompt, 6)


def test_concurrent_requests_each_match_generate(rng):
    model = tiny()
    params = model.init_params(0)
    prompts = [list(rng.integers(0, 96, n)) for n in (5, 9, 17)]
    srv = DecodeServer(model, params, slots=4, max_len=64)
    rids = [srv.submit(p, max_new_tokens=6) for p in prompts]
    results = srv.run_to_completion()
    for rid, p in zip(rids, prompts):
        assert results[rid] == reference(model, params, p, 6)


def test_staggered_admission_does_not_perturb_inflight_rows(rng):
    """Admit B while A is mid-decode: both must still match standalone."""
    model = tiny()
    params = model.init_params(0)
    pa = list(rng.integers(0, 96, 6))
    pb = list(rng.integers(0, 96, 11))
    srv = DecodeServer(model, params, slots=2, max_len=64)
    ra = srv.submit(pa, max_new_tokens=8)
    for _ in range(3):
        srv.step()
    rb = srv.submit(pb, max_new_tokens=5)     # splice mid-flight
    results = srv.run_to_completion()
    assert results[ra] == reference(model, params, pa, 8)
    assert results[rb] == reference(model, params, pb, 5)


def test_slot_reuse_after_completion(rng):
    model = tiny()
    params = model.init_params(0)
    pa = list(rng.integers(0, 96, 20))        # long first tenant
    pb = list(rng.integers(0, 96, 4))         # short second tenant
    srv = DecodeServer(model, params, slots=1, max_len=64)
    ra = srv.submit(pa, max_new_tokens=5)
    assert srv._free_slot() is None
    with pytest.raises(RuntimeError):
        srv.submit(pb)
    first = srv.run_to_completion()
    rb = srv.submit(pb, max_new_tokens=5)     # reuses slot 0
    results = srv.run_to_completion()
    assert first[ra] == reference(model, params, pa, 5)
    assert results[rb] == reference(model, params, pb, 5)


def test_eos_frees_slot_early(rng):
    model = tiny()
    params = model.init_params(0)
    prompt = list(rng.integers(0, 96, 5))
    ref = reference(model, params, prompt, 8)
    eos = ref[2]                               # force a stop at token 3
    srv = DecodeServer(model, params, slots=2, max_len=64, eos_id=eos)
    rid = srv.submit(prompt, max_new_tokens=8)
    results = srv.run_to_completion()
    assert results[rid] == ref[:3]
    assert srv._free_slot() is not None


def test_prompt_cache_token_exact_and_lru(rng):
    """A repeated prompt served from the radix cache decodes EXACTLY the
    tokens of an uncached server; the byte budget evicts LRU-style; the
    hit counter surfaces in stats; a negative cap is rejected."""
    model = tiny()
    params = model.init_params(0)
    # distinct first tokens: three independent root edges, so each
    # admission pins exactly one 16-bucket K/V row and the byte-budget
    # arithmetic below is row-exact
    prompts = [[i * 7 + 1] + list(rng.integers(0, 96, n))
               for i, n in enumerate((5, 8, 12))]
    plain = DecodeServer(model, params, slots=2, max_len=64)
    expect = {}
    for i, p in enumerate(prompts):
        rid = plain.submit(p, max_new_tokens=5)
        expect[i] = plain.run_to_completion()[rid]

    srv = DecodeServer(model, params, slots=2, max_len=64, prompt_cache=2)
    srv.submit(prompts[0], max_new_tokens=5)
    srv.run_to_completion()
    row_bytes = srv._prefix_tree.bytes   # one 16-bucket row
    assert row_bytes > 0
    srv._prefix_tree.budget_bytes = 2 * row_bytes   # hold exactly 2 rows
    # each prompt twice: second submit of each must hit the cache
    rid = srv.submit(prompts[0], max_new_tokens=5)
    assert srv.run_to_completion()[rid] == expect[0]
    for _ in range(2):
        rid = srv.submit(prompts[1], max_new_tokens=5)
        assert srv.run_to_completion()[rid] == expect[1]
    assert srv.stats["prompt_cache_hits"] == 2
    # byte budget = 2 rows: admitting a 3rd distinct prompt evicts the
    # least-recently-touched node (prompts[0])
    rid = srv.submit(prompts[2], max_new_tokens=5)
    assert srv.run_to_completion()[rid] == expect[2]
    assert srv._prefix_tree.nodes == 2
    assert srv._prefix_tree.bytes <= srv._prefix_tree.budget_bytes
    assert srv.stats["prefix_evictions"] == 1
    # the evicted prompt misses again (and re-evicts to stay in budget)
    hits_before = srv._prompt_hits
    rid = srv.submit(prompts[0], max_new_tokens=5)
    assert srv.run_to_completion()[rid] == expect[0]
    assert srv._prompt_hits == hits_before
    with pytest.raises(ValueError, match="prompt_cache"):
        DecodeServer(model, params, slots=2, max_len=64, prompt_cache=-1)


@pytest.mark.parametrize("cache_dtype", ["native", "int8"])
def test_prefix_cache_extension_token_exact(rng, cache_dtype):
    """Shared-prefix reuse (fleet/, ISSUE 14): a miss whose prompt
    extends a cached prompt forwards ONLY the suffix, and the resulting
    generation matches standalone generate exactly — and matches what a
    fully-prefilled submission of the same prompt produces."""
    model = tiny()
    params = model.init_params(0)
    base = list(rng.integers(0, 96, 7))
    ext = base + list(rng.integers(0, 96, 4))
    longer = ext + list(rng.integers(0, 96, 3))
    srv = DecodeServer(model, params, slots=4, max_len=96,
                       prompt_cache=4, cache_dtype=cache_dtype)
    rid = srv.submit(base, max_new_tokens=5)
    assert srv.run_to_completion()[rid] == reference(model, params,
                                                     base, 5)
    rid = srv.submit(ext, max_new_tokens=5)
    assert srv.run_to_completion()[rid] == reference(model, params,
                                                     ext, 5)
    assert srv.stats["prefix_hits"] == 1
    # the extended prompt is itself cached: the LONGEST prefix wins
    # (ext, not base) when a further extension arrives
    rid = srv.submit(longer, max_new_tokens=5)
    assert srv.run_to_completion()[rid] == reference(model, params,
                                                     longer, 5)
    assert srv.stats["prefix_hits"] == 2
    # and an exact resubmission is a WHOLE-prompt hit, not an extension
    rid = srv.submit(ext, max_new_tokens=5)
    assert srv.run_to_completion()[rid] == reference(model, params,
                                                     ext, 5)
    assert srv.stats["prompt_cache_hits"] == 1
    assert srv.stats["prefix_hits"] == 2


def test_prefix_cache_overflow_falls_back_to_full_prefill(rng):
    """A combined prefix+suffix row that would overflow max_len must
    fall back to the ordinary full prefill (still token-exact)."""
    model = tiny()
    params = model.init_params(0)
    base = list(rng.integers(0, 96, 30))     # bucket 32
    ext = base + list(rng.integers(0, 96, 10))  # suffix bucket 16: 48>40
    srv = DecodeServer(model, params, slots=2, max_len=46,
                       prompt_cache=4)
    srv.submit(base, max_new_tokens=3)
    srv.run_to_completion()
    rid = srv.submit(ext, max_new_tokens=3)
    assert srv.run_to_completion()[rid] == reference(model, params,
                                                     ext, 3)
    assert srv.stats["prefix_hits"] == 0  # fell back, correctly


@pytest.mark.parametrize("cache_dtype", ["native", "int8"])
def test_radix_interior_prefix_reuse(rng, cache_dtype):
    """The radix point (ISSUE 20): a prompt sharing a prefix with the
    INTERIOR of a longer cached prompt — a prefix that was never
    admitted as a complete prompt — still rides the suffix-only path
    (the PR 14 whole-prompt scan missed exactly this), splitting the
    cached edge at the divergence token.  Token-exact vs generate."""
    model = tiny()
    params = model.init_params(0)
    long_prompt = list(rng.integers(0, 96, 20))
    fork = long_prompt[:13] + list(rng.integers(0, 96, 6))
    assert fork[13] != long_prompt[13] or fork.__setitem__(
        13, (long_prompt[13] + 1) % 96) or True
    srv = DecodeServer(model, params, slots=2, max_len=96,
                       prompt_cache=8, cache_dtype=cache_dtype)
    srv.submit(long_prompt, max_new_tokens=4)
    srv.run_to_completion()
    rid = srv.submit(fork, max_new_tokens=4)
    assert srv.run_to_completion()[rid] == reference(model, params,
                                                     fork, 4)
    assert srv.stats["prefix_hits"] == 1
    assert srv._prefix_tree.splits == 1  # edge split at token 13
    # the split shares the long prompt's row — no extra device bytes
    # beyond the two admitted rows
    assert srv._prefix_tree.nodes == 3


@pytest.mark.parametrize("cache_dtype", ["native", "int8"])
def test_radix_multi_hop_extension_token_exact(rng, cache_dtype):
    """Multi-hop chaining: each admission extends from the DEEPEST
    cached ancestor, whose row is itself extension-built — prefix
    buckets compound (16, 32, 48, 64) and every generation stays
    token-exact vs standalone generate."""
    model = tiny()
    params = model.init_params(0)
    prompt = list(rng.integers(0, 96, 7))
    srv = DecodeServer(model, params, slots=2, max_len=128,
                       prompt_cache=8, cache_dtype=cache_dtype)
    for hop, extra in enumerate((0, 4, 5, 3)):
        prompt = prompt + list(rng.integers(0, 96, extra))
        rid = srv.submit(prompt, max_new_tokens=4)
        assert srv.run_to_completion()[rid] == reference(model, params,
                                                         prompt, 4)
        assert srv.stats["prefix_hits"] == hop
    # each hop's combined row is one suffix bucket wider
    node, matched, partial = srv._prefix_tree.lookup(
        tuple(int(t) for t in prompt))
    assert matched == len(prompt) and not partial
    assert int(node.handle.row[0].shape[1]) == 64  # 16+16+16+16


def test_radix_deepest_common_ancestor_wins(rng):
    """With several cached prefixes of the same prompt, extension seeds
    from the DEEPEST one (most reuse, shortest suffix forward)."""
    model = tiny()
    params = model.init_params(0)
    base = list(rng.integers(0, 96, 6))
    mid = base + list(rng.integers(0, 96, 5))
    srv = DecodeServer(model, params, slots=2, max_len=128,
                       prompt_cache=8)
    for p in (base, mid):
        srv.submit(p, max_new_tokens=3)
        srv.run_to_completion()
    before = srv._prefill_tokens
    longer = mid + list(rng.integers(0, 96, 4))
    rid = srv.submit(longer, max_new_tokens=3)
    assert srv.run_to_completion()[rid] == reference(model, params,
                                                     longer, 3)
    # only the 4-token suffix past `mid` ran a forward — not the
    # 9-token suffix past `base`
    assert srv._prefill_tokens - before == len(longer) - len(mid)
    assert srv.stats["prefix_hits"] == 2  # mid extended base, longer mid


def test_radix_ancestor_path_touch_protects_shared_prefix(rng):
    """ISSUE 20 satellite: a hit through a descendant touches the WHOLE
    ancestor path, so a hot shared prefix is never the LRU victim while
    its descendants live — the PR 14 cache touched only the source
    entry."""
    model = tiny()
    params = model.init_params(0)
    shared = list(rng.integers(0, 96, 6))
    a = shared + list(rng.integers(0, 96, 4))
    b = shared + [(a[6] + 1) % 96] + list(rng.integers(0, 96, 3))
    other = [(shared[0] + 1) % 96] + list(rng.integers(0, 96, 8))
    srv = DecodeServer(model, params, slots=2, max_len=96,
                       prompt_cache=8)
    for p in (shared, other, a, b):
        srv.submit(p, max_new_tokens=3)
        srv.run_to_completion()
    tree = srv._prefix_tree
    # shared's node is tick-fresher than `other` despite being admitted
    # earlier: a and b both touched their ancestor path through it
    snode, sm, _ = tree.lookup(tuple(shared))
    onode, om, _ = tree.lookup(tuple(other))
    assert sm == len(shared) and om == len(other)
    assert snode.tick > onode.tick
    # evict down to just over two rows: `other` (stale) must go before
    # the shared prefix every descendant rides on
    tree.budget_bytes = tree.bytes - 1
    tree.evict_over_budget()
    onode2, om2, _ = tree.lookup(tuple(other))
    assert om2 < len(other)          # the cold entry was the victim
    snode2, sm2, _ = tree.lookup(tuple(shared))
    assert sm2 == len(shared) and snode2.last is not None


def test_prefix_reuse_in_speculative_mode(rng):
    """ISSUE 20 satellite (the PR 14 leftover closed): a speculative
    admission sharing a cached prefix extends BOTH the target and the
    draft K/V row from the tree node (draft rows are cached alongside),
    so it no longer falls back to full prefill — and greedy speculative
    decode stays token-exact vs the plain greedy server."""
    model = tiny()
    params = model.init_params(0)
    draft = tiny(n_layers=1)
    dparams = draft.init_params(1)
    base = list(rng.integers(0, 96, 6))
    ext = base + list(rng.integers(0, 96, 3))
    srv = DecodeServer(model, params, slots=2, max_len=96,
                       prompt_cache=4, draft=draft, draft_params=dparams,
                       draft_len=2)
    assert srv._k > 0  # speculation armed: the old code full-prefilled
    srv.submit(base, max_new_tokens=4)
    srv.run_to_completion()
    rid = srv.submit(ext, max_new_tokens=4)
    plain = DecodeServer(model, params, slots=2, max_len=96)
    prid = plain.submit(ext, max_new_tokens=4)
    assert (srv.run_to_completion()[rid]
            == plain.run_to_completion()[prid])
    assert srv.stats["prefix_hits"] == 1  # suffix-only, both models


def test_prefix_extension_when_speculation_disabled(rng):
    """ISSUE 15 satellite (the PR 14 leftover's smallest edge): a
    speculative server whose depth controller has speculation OFF
    (k == 0 — no draft row would be seeded anyway) falls back to
    plain-mode shared-prefix extension for the prompt phase, token-exact
    vs standalone generate; re-arming speculation later still works —
    the k==0-era tree nodes carry no draft row, and the radix path
    backfills the draft side with a full draft prefill."""
    model = tiny()
    params = model.init_params(0)
    draft = tiny(n_layers=1)
    dparams = draft.init_params(1)
    base = list(rng.integers(0, 96, 6))
    ext = base + list(rng.integers(0, 96, 3))
    srv = DecodeServer(model, params, slots=2, max_len=96,
                       prompt_cache=4, draft=draft, draft_params=dparams,
                       draft_len=2)
    srv._k = 0  # the adaptive controller concluded the draft cannot pay
    rid = srv.submit(base, max_new_tokens=4)
    assert srv.run_to_completion()[rid] == reference(model, params,
                                                     base, 4)
    rid = srv.submit(ext, max_new_tokens=4)
    assert srv.run_to_completion()[rid] == reference(model, params,
                                                     ext, 4)
    assert srv.stats["prefix_hits"] == 1
    # re-arm: the next extending admission still rides the radix path —
    # the k==0-era ancestor carries no draft row, so the draft side
    # (only) full-prefills while the target row suffix-extends
    # (ISSUE 20: the k>0 full-prefill fallback is gone)
    srv._k = 2
    longer = ext + list(rng.integers(0, 96, 3))
    rid = srv.submit(longer, max_new_tokens=4)
    assert srv.run_to_completion()[rid] == reference(model, params,
                                                     longer, 4)
    assert srv.stats["prefix_hits"] == 2


def test_prompt_cache_speculative_and_int8(rng):
    """The cache composes with speculative mode (draft row cached too)
    and the int8 KV cache — hits stay token-exact in both."""
    model = tiny()
    params = model.init_params(0)
    prompt = list(rng.integers(0, 96, 7))
    ref = reference(model, params, prompt, 6)
    srv = DecodeServer(model, params, slots=2, max_len=64,
                       draft=model, draft_params=params, draft_len=2,
                       prompt_cache=4)
    for expect_hits in (0, 1):
        rid = srv.submit(prompt, max_new_tokens=6)
        assert srv.run_to_completion()[rid] == ref
        assert srv._prompt_hits == expect_hits

    q = DecodeServer(model, params, slots=2, max_len=64,
                     cache_dtype="int8", prompt_cache=4)
    first = q.submit(prompt, max_new_tokens=6)
    a = q.run_to_completion()[first]
    second = q.submit(prompt, max_new_tokens=6)
    assert q.run_to_completion()[second] == a
    assert q._prompt_hits == 1


def test_per_request_stop_tokens(rng):
    """submit(stop=...) finishes THAT request at its stop token while a
    concurrent request sails past the same token id."""
    model = tiny()
    params = model.init_params(0)
    prompt = list(rng.integers(0, 96, 5))
    ref = reference(model, params, prompt, 8)
    stop = ref[2]                              # cut request A at token 3
    srv = DecodeServer(model, params, slots=2, max_len=64)
    ra = srv.submit(prompt, max_new_tokens=8, stop=[stop])
    rb = srv.submit(prompt, max_new_tokens=8)  # same prompt, no stop
    results = srv.run_to_completion()
    assert results[ra] == ref[:3]
    assert results[rb] == ref


def test_per_request_temperature_mixed_batch(rng):
    """A greedy request and a sampled request share one batch: the greedy
    row must stay token-exact vs standalone generate (sampling other rows
    may not perturb it), the sampled row must actually differ, and no
    recompile happens per distinct temperature (one step runner)."""
    model = tiny()
    params = model.init_params(0)
    prompt = list(rng.integers(0, 96, 6))
    ref = reference(model, params, prompt, 10)
    srv = DecodeServer(model, params, slots=2, max_len=64, seed=3)
    ra = srv.submit(prompt, max_new_tokens=10)                    # greedy
    rb = srv.submit(prompt, max_new_tokens=10, temperature=5.0)   # hot
    results = srv.run_to_completion()
    assert results[ra] == ref
    assert results[rb] != ref  # temperature 5 on a random-init model

    # default server temperature still applies when submit doesn't set one
    srv2 = DecodeServer(model, params, slots=1, max_len=64,
                        temperature=0.0)
    rc = srv2.submit(prompt, max_new_tokens=10)
    assert srv2.run_to_completion()[rc] == ref


def test_speculative_rejects_per_request_temperature(rng):
    """The speculative accept rule is compiled for the server temperature,
    so submit() must reject a differing per-request value (and accept a
    matching one)."""
    model = tiny()
    draft = tiny(n_layers=1)
    params = model.init_params(0)
    dparams = draft.init_params(1)
    srv = DecodeServer(model, params, slots=2, max_len=64,
                       draft=draft, draft_params=dparams, draft_len=2)
    with pytest.raises(ValueError, match="per-request temperature"):
        srv.submit([1, 2, 3], temperature=0.7)
    rid = srv.submit([1, 2, 3], max_new_tokens=4, temperature=0.0)
    assert rid in srv.run_to_completion()


def test_int8_cache_server_matches_int8_generate(rng):
    model = tiny()
    params = model.init_params(0)
    prompt = list(rng.integers(0, 96, 6))
    ref = list(np.asarray(generate(
        model, params, jnp.asarray([prompt], jnp.int32), 5,
        cache_dtype="int8"))[0])
    srv = DecodeServer(model, params, slots=2, max_len=64,
                       cache_dtype="int8")
    rid = srv.submit(prompt, max_new_tokens=5)
    results = srv.run_to_completion()
    assert results[rid] == ref


@pytest.mark.parametrize("cache_dtype", ["native", "int8"])
def test_mesh_tp_serving_token_exact(rng, cache_dtype):
    """Multi-chip serving: the same requests through a data×tensor-sharded
    DecodeServer (params under the Megatron rule, cache batch/heads
    sharded — int8 scale leaves included, GSPMD-partitioned step) produce
    exactly the single-device tokens — staggered admission included."""
    from parameter_server_distributed_tpu.config import MeshConfig
    from parameter_server_distributed_tpu.parallel.mesh import build_mesh

    model = tiny(d_model=64, n_heads=4)   # head_dim 16; tp=2 splits heads
    params = model.init_params(0)
    pa = list(rng.integers(0, 96, 6))
    pb = list(rng.integers(0, 96, 9))

    def drive(srv):
        ra = srv.submit(pa, max_new_tokens=6)
        for _ in range(2):
            srv.step()
        rb = srv.submit(pb, max_new_tokens=4)
        out = srv.run_to_completion()
        return out[ra], out[rb]

    base = drive(DecodeServer(model, params, slots=4, max_len=64,
                              cache_dtype=cache_dtype))
    mesh = build_mesh(MeshConfig(data=2, tensor=2, fsdp=2))
    sharded = drive(DecodeServer(model, params, slots=4, max_len=64,
                                 cache_dtype=cache_dtype, mesh=mesh))
    assert sharded == base


def test_mesh_serving_with_int8_weights_token_exact(rng):
    """The full int8 serving stack over a mesh: QTensor weights placed
    with their scales following the matrix's output sharding, int8 slot
    cache — tokens equal the single-device int8 server's."""
    from parameter_server_distributed_tpu.config import MeshConfig
    from parameter_server_distributed_tpu.models.quant import (
        QTensor, quantize_params)
    from parameter_server_distributed_tpu.parallel.mesh import build_mesh

    model = tiny(d_model=64, n_heads=4)
    qparams = quantize_params(model.init_params(0))
    prompt = list(rng.integers(0, 96, 7))

    def drive(srv):
        rid = srv.submit(prompt, max_new_tokens=5)
        return srv.run_to_completion()[rid]

    base = drive(DecodeServer(model, qparams, slots=2, max_len=64,
                              cache_dtype="int8"))
    mesh = build_mesh(MeshConfig(data=2, tensor=2, fsdp=2))
    srv = DecodeServer(model, qparams, slots=2, max_len=64,
                       cache_dtype="int8", mesh=mesh)
    # scale rides the matrix's output sharding (tensor axis)
    wq = srv.params["layer0/attn/wq"]
    assert isinstance(wq, QTensor)
    assert wq.scale.sharding.spec == wq.q.sharding.spec[-1:]
    assert drive(srv) == base


@pytest.mark.parametrize("draft_kind", ["self", "random"])
def test_speculative_serving_token_exact(rng, draft_kind):
    """Speculative continuous batching is token-exact vs the plain greedy
    server for ANY draft (greedy acceptance commits exactly the target's
    greedy tokens): a perfect self-draft accepts everything, a random-init
    draft accepts ~nothing — outputs must be identical either way,
    staggered admission and slot reuse included."""
    model = tiny()
    params = model.init_params(0)
    if draft_kind == "self":
        draft, dparams = model, params
    else:
        draft = tiny(n_layers=1)
        dparams = draft.init_params(7)
    pa = list(rng.integers(0, 96, 6))
    pb = list(rng.integers(0, 96, 11))
    pc = list(rng.integers(0, 96, 4))

    def drive(srv):
        ra = srv.submit(pa, max_new_tokens=7)
        srv.step()
        rb = srv.submit(pb, max_new_tokens=5)
        out = dict(srv.run_to_completion())
        rc = srv.submit(pc, max_new_tokens=6)     # slot reuse
        out.update(srv.run_to_completion())
        return out[ra], out[rb], out[rc]

    base = drive(DecodeServer(model, params, slots=2, max_len=64))
    spec = drive(DecodeServer(model, params, slots=2, max_len=64,
                              draft=draft, draft_params=dparams,
                              draft_len=3))
    assert spec == base


def test_speculative_serving_sampling_preserves_distribution():
    """T>0 speculative serving applies the rejection rule: empirical
    first-token frequencies over many seeded servers match the target's
    own softmax (tiny vocab, 4-sigma) — the serving analogue of the
    one-shot decoder's distribution test."""
    import jax
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)

    vocab = 8
    target = Transformer(TransformerConfig(
        vocab=vocab, d_model=16, n_heads=2, n_layers=1, d_ff=32,
        max_seq=64, dtype=jnp.float32))
    draft = Transformer(TransformerConfig(
        vocab=vocab, d_model=8, n_heads=1, n_layers=1, d_ff=16,
        max_seq=64, dtype=jnp.float32))
    tparams, dparams = target.init_params(0), draft.init_params(3)
    prompt = [2, 2, 2, 2]
    counts0 = np.zeros(vocab)
    counts1 = np.zeros(vocab)
    reps, slots = 48, 8
    for seed in range(reps):
        srv = DecodeServer(target, tparams, slots=slots, max_len=32,
                           temperature=1.0, seed=seed,
                           draft=draft, draft_params=dparams, draft_len=2)
        rids = [srv.submit(prompt, max_new_tokens=2)
                for _ in range(slots)]
        out = srv.run_to_completion()
        for rid in rids:
            counts0[out[rid][0]] += 1
            counts1[out[rid][1]] += 1
    n = reps * slots
    from parameter_server_distributed_tpu.models.generation import prefill
    logits, _ = prefill(target, tparams,
                        jnp.asarray([prompt], jnp.int32), 8)
    p0 = np.asarray(jax.nn.softmax(logits[0]))
    # position 0 is submit()'s direct target sample; position 1 is the
    # ROUND's accept/resample product — its ground truth marginalizes
    # over the first token: p1[j] = sum_i p0[i] * P(j | prompt+[i])
    p1 = np.zeros(vocab)
    for i in range(vocab):
        li, _ = prefill(target, tparams,
                        jnp.asarray([prompt + [i]], jnp.int32), 8)
        p1 += p0[i] * np.asarray(jax.nn.softmax(li[0]))
    for freq, p in ((counts0 / n, p0), (counts1 / n, p1)):
        sigma = np.sqrt(p * (1 - p) / n)
        np.testing.assert_array_less(np.abs(freq - p), 4 * sigma + 0.01)


def test_serving_stats(rng):
    """Observability counters: request/step/token accounting on the plain
    server; a perfect self-draft reports acceptance 1.0 and k+1
    tokens/round while requests are saturating the slots."""
    model = tiny()
    params = model.init_params(0)
    prompt = list(rng.integers(0, 96, 5))
    srv = DecodeServer(model, params, slots=2, max_len=64)
    rid = srv.submit(prompt, max_new_tokens=4)
    srv.run_to_completion()
    s = srv.stats
    assert s["requests_admitted"] == s["requests_completed"] == 1
    assert s["steps"] == 3          # first token came from prefill
    assert s["tokens_emitted"] == 3
    assert "draft_accept_rate" not in s

    spec = DecodeServer(model, params, slots=1, max_len=64,
                        draft=model, draft_params=params, draft_len=3,
                        adaptive_draft=False)  # pin k: exact round counts
    spec.submit(prompt, max_new_tokens=8)
    spec.run_to_completion()
    s = spec.stats
    assert s["draft_accept_rate"] == 1.0
    assert s["requests_completed"] == 1
    # 7 round-produced tokens (first came from prefill) over 2 rounds:
    # full k+1=4 then truncated at max_new
    assert s["tokens_per_round"] == 3.5


def test_speculative_serving_validation(rng):
    model = tiny()
    params = model.init_params(0)
    with pytest.raises(ValueError, match="top_k/top_p"):
        DecodeServer(model, params, slots=2, max_len=64, top_k=5,
                     draft=model, draft_params=params)
    with pytest.raises(ValueError, match="draft_params"):
        DecodeServer(model, params, slots=2, max_len=64, draft=model)
    other = tiny(vocab=64)
    with pytest.raises(ValueError, match="vocab"):
        DecodeServer(model, params, slots=2, max_len=64, draft=other,
                     draft_params=other.init_params(0))


def test_prompt_validation(rng):
    model = tiny()
    srv = DecodeServer(model, model.init_params(0), slots=1, max_len=32)
    with pytest.raises(ValueError):
        srv.submit([])
    with pytest.raises(ValueError):
        srv.submit(list(rng.integers(0, 96, 30)), max_new_tokens=10)


def test_speculative_serving_adaptive_depth(rng):
    """adaptive_draft: the server's depth controller follows acceptance —
    a perfect self-draft deepens to the cap, a random draft drops to 1 —
    while outputs stay token-exact vs the plain greedy server."""
    model = tiny()
    params = model.init_params(0)
    prompts = [list(rng.integers(0, model.config.vocab, 5))
               for _ in range(6)]

    def run(**kwargs):
        srv = DecodeServer(model, params, slots=2, max_len=64, **kwargs)
        pending = list(prompts)
        while pending or not srv.idle:
            while pending and srv.has_free_slot:
                srv.submit(pending.pop(0), max_new_tokens=24)
            srv.step()
        return srv

    plain = run()
    perfect = run(draft=model, draft_params=params, draft_len=4,
                  adaptive_draft=True, draft_cost_ratio=0.3)
    assert perfect.stats["draft_depth"] == 4
    junk = tiny(n_layers=1)
    junky = run(draft=junk, draft_params=junk.init_params(99),
                draft_len=4, adaptive_draft=True, draft_cost_ratio=0.3)
    # accept ~0: the controller disables speculation (k=0) and the
    # server switches to plain greedy rounds mid-flight
    assert junky.stats["draft_depth"] == 0
    for rid in range(6):
        want = plain.result(rid)      # result() pops — read once
        assert perfect.result(rid) == want
        assert junky.result(rid) == want
    # pinned mode keeps the configured depth
    pinned = run(draft=junk, draft_params=junk.init_params(99),
                 draft_len=3, adaptive_draft=False)
    assert pinned.stats["draft_depth"] == 3


def test_step_many_token_exact_vs_step_loop(rng):
    """Fused multi-round serving == the step() loop token for token:
    greedy and per-request-temperature sampling (identical rng split
    sequence), a stop token retiring a request MID-fused-block, and a
    mixed-length batch (the round count clamps to the minimum remaining
    budget)."""
    model = tiny()
    params = model.init_params(0)
    prompts = [list(rng.integers(0, 96, 5)) for _ in range(3)]

    def run(fused, stops=(), temps=()):
        srv = DecodeServer(model, params, slots=2, max_len=96, seed=3)
        results = {}
        pending = list(enumerate(prompts))
        while pending or not srv.idle:
            while pending and srv.has_free_slot:
                i, p = pending.pop(0)
                rid = srv.submit(
                    p, max_new_tokens=10 + 3 * i,       # mixed budgets
                    stop=list(stops),
                    temperature=(temps[i % len(temps)] if temps
                                 else None))
            (srv.step_many(4) if fused else srv.step())
        for rid in srv.finished():
            results[rid] = srv.result(rid)
        return srv, results

    base_srv, base = run(fused=False)
    fused_srv, got = run(fused=True)
    assert got == base
    assert fused_srv.stats["steps"] == base_srv.stats["steps"]

    # sampling path: same rng stream through the fused scan
    _, base_s = run(fused=False, temps=(0.8, 0.0))
    _, got_s = run(fused=True, temps=(0.8, 0.0))
    assert got_s == base_s

    # a stop token that fires mid-block: truncation must match exactly
    stop_tok = base[0][1]
    _, base_stop = run(fused=False, stops=(stop_tok,))
    _, got_stop = run(fused=True, stops=(stop_tok,))
    assert got_stop == base_stop


def test_step_many_speculative_falls_back(rng):
    """With an active draft the fused path defers to the adaptive spec
    round (host decisions between rounds); output stays exact."""
    model = tiny()
    params = model.init_params(0)
    prompt = list(rng.integers(0, 96, 5))

    def run(fused):
        srv = DecodeServer(model, params, slots=1, max_len=96,
                           draft=model, draft_params=params, draft_len=3,
                           adaptive_draft=False)
        rid = srv.submit(prompt, max_new_tokens=8)
        while not srv.idle:
            (srv.step_many(4) if fused else srv.step())
        return srv.result(rid)

    assert run(True) == run(False)
