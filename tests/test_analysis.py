"""Tests for the pst-analyze subsystem (analysis/).

Three layers:

1. **Fixture sources** — synthetic modules with seeded violations (a lock
   order inversion, a blocking call under a lock, raw acquires, swallowed
   exceptions, unnamed threads) fed through the same entry points the CLI
   uses, asserting each pass detects exactly its seeded finding.
2. **Gate** — the real package must analyze clean: zero non-baselined
   violations, and the committed wire manifest must match the live
   schemas bit for bit.
3. **Runtime mode** — PSDT_LOCK_CHECK=1 wraps the known locks in
   order-asserting proxies: a deliberate out-of-order acquire raises
   LockOrderError, normal operation (push → barrier → apply → serve,
   checkpoint save/load) does not.
"""

from __future__ import annotations

import json
import os
import re
import textwrap

import numpy as np
import pytest

from parameter_server_distributed_tpu.analysis import (eventcheck, extcheck,
                                                       findings as F,
                                                       knobcheck, lock_order,
                                                       lockcheck, runner,
                                                       wirecheck)
from parameter_server_distributed_tpu.cli import analyze_main


def analyze(src: str, rel: str = "fixture/mod.py"):
    file_findings, edges = runner.analyze_source(textwrap.dedent(src), rel)
    return file_findings + lockcheck.check_edges(edges)


def by_pass(found, pass_id):
    return [f for f in found if f.pass_id == pass_id]


# ----------------------------------------------------------- lock discipline

def test_detects_declared_rank_inversion():
    found = analyze("""
        import threading

        class ParameterServerCore:
            def __init__(self):
                self._state_lock = threading.Lock()
                self._params_lock = threading.Lock()

            def bad(self):
                with self._params_lock:
                    with self._state_lock:
                        pass
        """)
    inversions = by_pass(found, F.LOCK_ORDER)
    assert len(inversions) == 1
    assert "ParameterServerCore._state_lock" in inversions[0].message
    assert "rank" in inversions[0].message


def test_detects_lock_order_cycle_between_undeclared_locks():
    found = analyze("""
        import threading

        class Pool:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
        """)
    cycles = by_pass(found, F.LOCK_ORDER)
    assert len(cycles) == 1
    assert "cycle" in cycles[0].message


def test_consistent_undeclared_order_is_clean():
    found = analyze("""
        import threading

        class Pool:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
        """)
    assert by_pass(found, F.LOCK_ORDER) == []


def test_detects_blocking_call_under_lock():
    found = analyze("""
        import threading
        import time

        class ParameterServerCore:
            def __init__(self):
                self._state_lock = threading.Lock()

            def bad(self):
                with self._state_lock:
                    time.sleep(1.0)
        """)
    blocking = by_pass(found, F.LOCK_BLOCKING)
    assert len(blocking) == 1
    assert "time.sleep" in blocking[0].message


def test_blocking_under_apply_lock_is_allowed():
    # _apply_lock exists to serialize the blocking apply — the rule skips
    # locks in lock_order.BLOCKING_ALLOWED
    found = analyze("""
        import threading

        class ParameterServerCore:
            def __init__(self):
                self._apply_lock = threading.Lock()

            def close(self):
                with self._apply_lock:
                    self._optimizer.apply(1, 2)
        """)
    assert by_pass(found, F.LOCK_BLOCKING) == []


def test_raw_acquire_flagged_and_release_tracked():
    found = analyze("""
        import threading

        class Core:
            def __init__(self):
                self._lock = threading.Lock()

            def handoff(self):
                self._lock.acquire()
                self._lock.release()
        """)
    raw = by_pass(found, F.LOCK_RAW_ACQUIRE)
    assert len(raw) == 1
    assert "Core._lock" in raw[0].message


def test_cv_wait_on_own_lock_is_legal():
    found = analyze("""
        import threading

        class Core:
            def __init__(self):
                self._state_lock = threading.Lock()
                self._cv = threading.Condition(self._state_lock)

            def wait(self):
                with self._cv:
                    self._cv.wait(0.25)
        """)
    assert by_pass(found, F.LOCK_BLOCKING) == []


def test_cv_wait_while_holding_second_lock_flagged():
    found = analyze("""
        import threading

        class Core:
            def __init__(self):
                self._other = threading.Lock()
                self._state_lock = threading.Lock()
                self._cv = threading.Condition(self._state_lock)

            def wait(self):
                with self._other:
                    with self._cv:
                        self._cv.wait(0.25)
        """)
    assert len(by_pass(found, F.LOCK_BLOCKING)) == 1


def test_caller_holds_docstring_creates_edge():
    found = analyze("""
        import threading

        class ParameterServerCore:
            def __init__(self):
                self._state_lock = threading.Lock()
                self._params_lock = threading.Lock()

            def _helper_locked(self):
                \"\"\"Caller holds _params_lock.\"\"\"
                with self._state_lock:
                    pass
        """)
    # entry-held _params_lock (rank 40) then _state_lock (20): inversion
    assert len(by_pass(found, F.LOCK_ORDER)) == 1


def test_self_deadlock_on_nonreentrant_reacquire():
    found = analyze("""
        import threading

        class Core:
            def __init__(self):
                self._lock = threading.Lock()

            def oops(self):
                with self._lock:
                    with self._lock:
                        pass
        """)
    assert any("self-deadlock" in f.message
               for f in by_pass(found, F.LOCK_ORDER))


def test_checked_lock_factory_recognized_in_discovery():
    found = analyze("""
        from parameter_server_distributed_tpu.analysis.lock_order import checked_lock

        class ParameterServerCore:
            def __init__(self):
                self._state_lock = checked_lock("ParameterServerCore._state_lock")
                self._params_lock = checked_lock("ParameterServerCore._params_lock")

            def bad(self):
                with self._params_lock:
                    with self._state_lock:
                        pass
        """)
    assert len(by_pass(found, F.LOCK_ORDER)) == 1


# --------------------------------------------------------- exception hygiene

def test_bare_and_broad_swallowing_excepts_flagged():
    found = analyze("""
        def handler():
            try:
                work()
            except:
                pass

        def handler2():
            try:
                work()
            except Exception:
                return None
        """)
    exc = by_pass(found, F.EXCEPT_HYGIENE)
    assert len(exc) == 2
    assert any("bare except" in f.message for f in exc)


def test_surfacing_and_annotated_excepts_are_clean():
    found = analyze("""
        import logging
        log = logging.getLogger(__name__)

        def reraises():
            try:
                work()
            except Exception:
                raise

        def logs():
            try:
                work()
            except Exception:
                log.exception("failed")

        def reviewed():
            try:
                work()
            except Exception:  # noqa: BLE001 — boundary: reported via RPC
                return None

        def narrow():
            try:
                work()
            except OSError:
                pass
        """)
    assert by_pass(found, F.EXCEPT_HYGIENE) == []


# ------------------------------------------------------------ thread hygiene

def test_unnamed_or_nondaemon_threads_flagged():
    found = analyze("""
        import threading
        from concurrent.futures import ThreadPoolExecutor

        def spawn():
            threading.Thread(target=run).start()
            threading.Thread(target=run, daemon=True).start()
            threading.Thread(target=run, daemon=True, name="ok").start()
            ThreadPoolExecutor(max_workers=2)
            ThreadPoolExecutor(max_workers=2, thread_name_prefix="ok")
        """)
    threads = by_pass(found, F.THREAD_HYGIENE)
    assert len(threads) == 3  # two bad Thread ctors, one bad executor
    assert any("daemon=True and name=" in f.message for f in threads)


# ---------------------------------------------------------------- wire compat

def test_wire_manifest_matches_live_schemas():
    """The committed golden manifest must match rpc/messages.py +
    rpc/idl.py exactly — a failure here means a protocol edit shipped
    without `pst-analyze --write-wire-manifest`."""
    assert wirecheck.run() == []


def test_wire_drift_detected():
    golden = wirecheck.build_manifest()
    current = json.loads(json.dumps(golden))  # deep copy

    # renumber a Tensor field, drop a method, add a message
    tensor = current["messages"]["Tensor"]["fields"]
    tensor["7"] = tensor.pop("3")
    del current["services"]["parameter_server.ParameterServer"][
        "reference_methods"]["ServeParameters"]
    current["messages"]["Rogue"] = {"fields": {}}

    drifts = wirecheck.diff_manifests(golden, current)
    slugs = {f.slug for f in drifts}
    assert any("fields.3:removed" in s for s in slugs)
    assert any("fields.7:added" in s for s in slugs)
    assert any("ServeParameters:removed" in s for s in slugs)
    assert any("Rogue:added" in s for s in slugs)


def test_wire_constant_change_detected():
    golden = wirecheck.build_manifest()
    current = json.loads(json.dumps(golden))
    current["constants"]["TRACE_FIELD_NUMBER"] = 998
    drifts = wirecheck.diff_manifests(golden, current)
    assert any("TRACE_FIELD_NUMBER" in f.slug and "changed" in f.slug
               for f in drifts)


# ------------------------------------------------------------------ the gate

def test_package_analyzes_clean():
    """THE gate: zero non-baselined violations over the real package.  If
    this fails, either fix the new finding or — after review — add it to
    analysis/baseline.json with a one-line justification."""
    report = runner.run()
    assert report.errors == []
    rendered = "\n".join(f.render() for f in report.violations)
    assert report.violations == [], f"non-baselined findings:\n{rendered}"
    assert report.files > 50  # walked the real package, not a stub dir
    # baseline must stay tight: every entry still matches a real finding
    assert report.stale_baseline == [], [e.key for e in report.stale_baseline]
    assert all(f.baselined_by for f in report.baselined)


def test_tier_extension_stays_out_of_the_wire_manifest():
    """ISSUE 9 compat gate: the hierarchical-aggregation extension
    (tiers/messages.py) must leave the reference wire manifest
    byte-unchanged — its messages and the GetReductionTopology method
    must never appear in the pinned contract, and the committed golden
    must still match the live schemas bit for bit."""
    import json

    from parameter_server_distributed_tpu.analysis import wirecheck
    from parameter_server_distributed_tpu.tiers import messages as tmsg

    with open(wirecheck.default_manifest_path()) as fh:
        golden_bytes = fh.read()
    golden = json.loads(golden_bytes)
    assert wirecheck.diff_manifests(golden, wirecheck.build_manifest()) == []
    blob = json.dumps(golden)
    for name in ("TierGroupEntry", "TierTopologyRequest",
                 "TierTopologyResponse", "GetReductionTopology"):
        assert name not in blob, f"tier extension leaked: {name}"
    # and the extension method table really is disjoint from the pinned
    # coordinator contract
    from parameter_server_distributed_tpu.rpc import messages as m
    assert not set(tmsg.TIER_COORD_METHODS) & set(m.COORDINATOR_METHODS)


def test_fleet_extension_stays_out_of_the_wire_manifest():
    """ISSUE 14 compat gate: the decode-fleet extension
    (fleet/messages.py) must leave the reference wire manifest
    byte-unchanged — its messages, the UpdateFleet coordinator method,
    and the whole psdt_fleet.Decode service must never appear in the
    pinned contract, and the committed golden must still match the live
    schemas bit for bit."""
    import json

    from parameter_server_distributed_tpu.analysis import wirecheck
    from parameter_server_distributed_tpu.fleet import messages as fmsg

    with open(wirecheck.default_manifest_path()) as fh:
        golden = json.loads(fh.read())
    assert wirecheck.diff_manifests(golden, wirecheck.build_manifest()) == []
    blob = json.dumps(golden)
    for name in ("FleetEntry", "FleetRequest", "FleetResponse",
                 "UpdateFleet", "DecodeRequest", "DecodeChunk",
                 "DecodeControlRequest", "DecodeControlResponse",
                 "SubmitStream", "psdt_fleet"):
        assert name not in blob, f"fleet extension leaked: {name}"
    # and the extension method table really is disjoint from the pinned
    # coordinator contract
    from parameter_server_distributed_tpu.rpc import messages as m
    assert not set(fmsg.FLEET_COORD_METHODS) & set(m.COORDINATOR_METHODS)


def test_delta_extension_stays_out_of_the_wire_manifest():
    """ISSUE 10 compat gate: the versioned-delta / weight-publication
    extension (delta/messages.py) must leave the reference wire manifest
    byte-unchanged — its messages and the SubscribeWeights /
    PullParametersDelta / PushPullDeltaStream methods must never appear
    in the pinned contract, and the committed golden must still match
    the live schemas bit for bit."""
    import json

    from parameter_server_distributed_tpu.analysis import wirecheck
    from parameter_server_distributed_tpu.delta import messages as dmsg

    with open(wirecheck.default_manifest_path()) as fh:
        golden = json.loads(fh.read())
    assert wirecheck.diff_manifests(golden, wirecheck.build_manifest()) == []
    blob = json.dumps(golden)
    for name in ("DeltaFrame", "DeltaEntry", "DeltaPullRequest",
                 "DeltaPushChunk", "SubscribeRequest", "SubscribeWeights",
                 "PullParametersDelta", "PushPullDeltaStream"):
        assert name not in blob, f"delta extension leaked: {name}"
    # and the extension method table really is disjoint from the pinned
    # parameter-server contract (unary AND stream tables)
    from parameter_server_distributed_tpu.rpc import messages as m
    assert not set(dmsg.DELTA_PS_METHODS) & (
        set(m.PARAMETER_SERVER_METHODS)
        | set(m.PARAMETER_SERVER_STREAM_METHODS))


def test_arena_introduces_no_wire_drift_and_declares_its_lock():
    """ISSUE 15 compat gate: the flat arena (core/arena.py) is a RUNTIME
    layout, never a wire or disk format — the committed golden manifest
    must still match the live schemas bit for bit, nothing arena-named
    may appear in the pinned contract, and the ArenaManager lock must
    carry a declared rank (with its H2D packing blessed as the blocking
    section it serializes)."""
    import json

    from parameter_server_distributed_tpu.analysis import wirecheck
    from parameter_server_distributed_tpu.analysis.lock_order import (
        BLOCKING_ALLOWED, LOCK_RANKS)

    with open(wirecheck.default_manifest_path()) as fh:
        golden = json.loads(fh.read())
    assert wirecheck.diff_manifests(golden, wirecheck.build_manifest()) == []
    blob = json.dumps(golden)
    for name in ("Arena", "ArenaStore", "PackingTable", "PSDT_ARENA"):
        assert name not in blob, f"arena leaked into the manifest: {name}"
    assert "ArenaManager._lock" in LOCK_RANKS
    assert "ArenaManager._lock" in BLOCKING_ALLOWED


def test_freerun_introduces_no_wire_drift_and_no_new_locks():
    """ISSUE 16 compat gate: free-running mode (freerun/) is a SERVER
    apply policy riding the pinned PushGradients/ServeParameters
    contract — no new messages, no new methods, so the committed golden
    manifest must still match the live schemas bit for bit and nothing
    freerun-named may appear in the pinned contract.  The engine also
    deliberately adds ZERO locks (version vector + EWMA live under
    core._state_lock, publication state under core._apply_lock), so no
    FreeRun rank may ever show up in the declared order — a new lock
    here means the design changed and needs a declared rank + review."""
    import json

    from parameter_server_distributed_tpu.analysis import wirecheck
    from parameter_server_distributed_tpu.analysis.lock_order import (
        LOCK_RANKS)

    with open(wirecheck.default_manifest_path()) as fh:
        golden = json.loads(fh.read())
    assert wirecheck.diff_manifests(golden, wirecheck.build_manifest()) == []
    blob = json.dumps(golden)
    for name in ("FreeRun", "Freerun", "PSDT_FREERUN", "staleness_beta"):
        assert name not in blob, f"freerun leaked into the manifest: {name}"
    assert not [k for k in LOCK_RANKS if "FreeRun" in k or "freerun" in k]


def test_sharded_update_extension_stays_out_of_the_wire_manifest():
    """ISSUE 18 compat gate: the cross-replica sharded-update extension
    (replication/messages.py ShardedSliceChunk / ShardedSliceAck and
    the ShardedApplySlices / InstallSlabSlices methods) must leave the
    reference wire manifest byte-unchanged, its method table must stay
    disjoint from the pinned PS contract AND the replication extension
    table it rides alongside, and both new locks must carry declared
    ranks with their blocking sections blessed."""
    import json

    from parameter_server_distributed_tpu.analysis import wirecheck
    from parameter_server_distributed_tpu.analysis.lock_order import (
        BLOCKING_ALLOWED, LOCK_RANKS)
    from parameter_server_distributed_tpu.replication import (
        messages as repmsg)

    with open(wirecheck.default_manifest_path()) as fh:
        golden = json.loads(fh.read())
    assert wirecheck.diff_manifests(golden, wirecheck.build_manifest()) == []
    blob = json.dumps(golden)
    for name in ("ShardedSliceChunk", "ShardedSliceAck",
                 "ShardedApplySlices", "InstallSlabSlices",
                 "PSDT_SHARDED_UPDATE"):
        assert name not in blob, f"sharded update leaked: {name}"
    from parameter_server_distributed_tpu.rpc import messages as m
    assert not set(repmsg.SHARDED_UPDATE_PS_METHODS) & (
        set(m.PARAMETER_SERVER_METHODS)
        | set(m.PARAMETER_SERVER_STREAM_METHODS))
    assert not set(repmsg.SHARDED_UPDATE_PS_METHODS) & set(
        repmsg.REPLICATION_PS_METHODS)
    for lock in ("ShardedUpdateSink._lock", "ShardedUpdater._lock"):
        assert lock in LOCK_RANKS, lock
        assert lock in BLOCKING_ALLOWED, lock
    # the sink's rank precedes the replica sink's: a sharded install
    # advances the flat-ship bookkeeping INSIDE its critical section
    assert (LOCK_RANKS["ShardedUpdateSink._lock"]
            < LOCK_RANKS["ReplicaSink._lock"])


def test_elastic_extension_stays_out_of_the_wire_manifest():
    """ISSUE 13 compat gate: the elastic-membership extension
    (elastic/messages.py) must leave the reference wire manifest
    byte-unchanged — its messages and the UpdateMembership method must
    never appear in the pinned contract, and the committed golden must
    still match the live schemas bit for bit."""
    import json

    from parameter_server_distributed_tpu.analysis import wirecheck
    from parameter_server_distributed_tpu.elastic import messages as emsg

    with open(wirecheck.default_manifest_path()) as fh:
        golden = json.loads(fh.read())
    assert wirecheck.diff_manifests(golden, wirecheck.build_manifest()) == []
    blob = json.dumps(golden)
    for name in ("MembershipEntry", "MembershipRequest",
                 "MembershipResponse", "UpdateMembership"):
        assert name not in blob, f"elastic extension leaked: {name}"
    # and the extension method table really is disjoint from the pinned
    # coordinator contract
    from parameter_server_distributed_tpu.rpc import messages as m
    assert not set(emsg.ELASTIC_COORD_METHODS) & set(m.COORDINATOR_METHODS)


def test_cli_json_output_and_exit_codes(tmp_path, capsys):
    assert analyze_main.main(["--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is True and doc["violations"] == []

    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import threading

        def spawn():
            threading.Thread(target=spawn).start()
        """))
    assert analyze_main.main([str(tmp_path), "--json", "--no-wire"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is False
    assert doc["violations"][0]["pass_id"] == "thread-hygiene"


def test_baseline_requires_justification(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps(
        {"entries": [{"key": "lock-order:x:y", "reason": " "}]}))
    with pytest.raises(ValueError, match="justification"):
        F.load_baseline(str(path))


# ------------------------------------------------------------- runtime mode

def _store(**kw):
    return {k: np.asarray(v, np.float32) for k, v in kw.items()}


@pytest.mark.lockcheck
def test_runtime_out_of_order_acquire_raises():
    from parameter_server_distributed_tpu.core.ps_core import \
        ParameterServerCore

    ps = ParameterServerCore(total_workers=1)
    assert isinstance(ps._state_lock, lock_order.CheckedLock)
    with pytest.raises(lock_order.LockOrderError, match="lock-order"):
        with ps._params_lock:
            with ps._state_lock:
                pass
    # the failed acquire must not corrupt the per-thread held stack
    assert lock_order.held_locks() == ()


@pytest.mark.lockcheck
def test_runtime_self_deadlock_raises_instead_of_hanging():
    from parameter_server_distributed_tpu.core.ps_core import \
        ParameterServerCore

    ps = ParameterServerCore(total_workers=1)
    with pytest.raises(lock_order.LockOrderError, match="self-deadlock"):
        with ps._state_lock:
            with ps._state_lock:
                pass


@pytest.mark.lockcheck
def test_runtime_clean_on_full_server_cycle(tmp_path):
    """Push → barrier close (apply outside _state_lock) → serve → snapshot
    → checkpoint save/load → restore: the whole documented order, live,
    with assertions armed."""
    from parameter_server_distributed_tpu.checkpoint.manager import \
        CheckpointManager
    from parameter_server_distributed_tpu.core.ps_core import \
        ParameterServerCore

    ps = ParameterServerCore(total_workers=2, aggregation="streaming")
    ps.initialize_parameters(_store(w=[10.0, 10.0]))
    for worker in range(2):
        result = ps.receive_gradients(worker, 1, _store(w=[2.0, 4.0]))
    assert result.aggregation_complete
    np.testing.assert_allclose(ps.get_parameters()["w"], [8.0, 6.0])
    assert ps.wait_for_aggregation(1, timeout=0.5)[0]

    mgr = CheckpointManager(ps, directory=str(tmp_path))
    path = mgr.save(epoch=1)   # ckpt lock -> state -> apply -> params
    mgr.load(path)             # ckpt lock -> restore chain
    assert mgr.maybe_autosave() is None  # reentrant ckpt RLock re-acquire
    assert lock_order.held_locks() == ()


def test_checked_lock_disabled_returns_plain_lock(monkeypatch):
    monkeypatch.delenv(lock_order.ENV_FLAG, raising=False)
    lock = lock_order.checked_lock("ParameterServerCore._state_lock")
    assert not isinstance(lock, lock_order.CheckedLock)
    with lock:
        pass


def test_checked_lock_unknown_name_rejected():
    with pytest.raises(KeyError, match="declared rank"):
        lock_order.checked_lock("Mystery._lock")


@pytest.mark.lockcheck
def test_runtime_condition_variable_wait_through_proxy():
    """The barrier CV wraps the proxied _state_lock: park + notify must
    work (wait releases/reacquires through the proxy's held tracking)."""
    import threading

    from parameter_server_distributed_tpu.core.ps_core import \
        ParameterServerCore

    ps = ParameterServerCore(total_workers=1, aggregation="streaming")
    ps.initialize_parameters(_store(w=[1.0]))
    woke = []

    def waiter():
        woke.append(ps.wait_for_aggregation(1, timeout=5.0))

    t = threading.Thread(target=waiter, daemon=True, name="test-waiter")
    t.start()
    ps.receive_gradients(0, 1, _store(w=[1.0]))
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert woke and woke[0][0] is True


# ------------------------------------------------- extension protocol pass

def _write(tmp_path, rel, src):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(src))
    return path


_EXT_CORE = """
    TRACE_FIELD_NUMBER = 999

    class PushGradients:
        FIELDS = (
            Field(1, "worker_id", "varint"),
            Field(999, "trace_context", "bytes"),
        )

    PARAMETER_SERVER_METHODS = {
        "PushGradients": (PushGradients, PushGradients),
    }
    """


def test_ext_tag_and_method_collisions_detected(tmp_path):
    """A synthetic extension that (a) redefines a core message with a
    renamed field on a core tag, (b) claims the reserved trace tag,
    (c) duplicates a tag within one message, and (d) re-registers a core
    RPC method must produce one finding per sin."""
    _write(tmp_path, "rpc/messages.py", _EXT_CORE)
    _write(tmp_path, "foo/messages.py", """
        class PushGradients:
            FIELDS = (
                Field(1, "shard_id", "varint"),
            )

        class ShardHello:
            FIELDS = (
                Field(999, "shard_id", "varint"),
                Field(3, "epoch", "varint"),
                Field(3, "round", "varint"),
            )

        FOO_PS_METHODS = {
            "PushGradients": (PushGradients, PushGradients),
        }
        """)
    found = extcheck.check_collisions(str(tmp_path))
    assert all(f.pass_id == F.EXT_PROTOCOL for f in found)
    slugs = {f.slug for f in found}
    assert {"dup-message", "core-tag:1", "trace-tag:shard_id",
            "dup-tag:3", "dup-method:PushGradients"} <= slugs


def test_ext_manifest_drift_detected(tmp_path):
    """The golden gate: a pinned extension contract diffs clean against
    itself, then any tag renumbering shows up as ext-protocol drift."""
    _write(tmp_path, "rpc/messages.py", _EXT_CORE)
    ext = _write(tmp_path, "foo/messages.py", """
        class ShardHello:
            FIELDS = (
                Field(1, "shard_id", "varint"),
            )

        FOO_PS_METHODS = {
            "ShardHello": (ShardHello, ShardHello),
        }
        """)
    golden = tmp_path / "ext_manifests.json"
    extcheck.write_manifests(str(golden), root=str(tmp_path))
    assert extcheck.run(manifest_path=str(golden),
                        root=str(tmp_path)) == []
    ext.write_text(ext.read_text().replace('Field(1,', 'Field(2,'))
    found = extcheck.run(manifest_path=str(golden), root=str(tmp_path))
    assert found, "tag renumbering must not pass the golden gate"
    assert all(f.pass_id == F.EXT_PROTOCOL for f in found)
    assert any("write-ext-manifests" in f.message for f in found)


def test_committed_ext_manifests_current():
    """Currency gate: analysis/ext_manifests.json must match a fresh
    extraction bit for bit (pst-analyze --write-ext-manifests)."""
    golden = extcheck.load_manifests()
    assert golden is not None
    assert golden == extcheck.build_manifests()


# ----------------------------------------------------- knob registry pass

def test_knob_conflicting_default_detected(tmp_path):
    """Two subsystems reading one knob with different literal defaults is
    exactly the silent-divergence bug the pass exists for."""
    pkg = tmp_path / "pkg"
    _write(pkg, "a.py", """
        import os
        CHUNK = int(os.environ.get("PSDT_FIXTURE_CHUNK", "4"))
        """)
    _write(pkg, "b.py", """
        import os
        CHUNK = int(os.environ.get("PSDT_FIXTURE_CHUNK", "8"))
        """)
    found = knobcheck.run(root=str(pkg), check_registry=False)
    assert [f.slug for f in found] == ["conflicting-default"]
    assert found[0].symbol == "PSDT_FIXTURE_CHUNK"
    assert found[0].pass_id == F.KNOB_REGISTRY


def test_knob_doc_drift_detected(tmp_path):
    """An undocumented read and a documented-but-never-read knob each
    produce a doc-drift finding against the knob tables."""
    pkg = tmp_path / "pkg"
    _write(pkg, "a.py", """
        import os
        A = os.environ.get("PSDT_FIXTURE_A", "1")
        B = os.environ.get("PSDT_FIXTURE_B", "1")
        """)
    _write(tmp_path, "docs/knobs.md", """
        | knob | default | meaning |
        | --- | --- | --- |
        | `PSDT_FIXTURE_A` | 1 | documented and read |
        | `PSDT_FIXTURE_C` | 1 | stale row, nothing reads it |
        """)
    found = knobcheck.run(root=str(pkg),
                          docs_dir=str(tmp_path / "docs"),
                          check_registry=False)
    slugs = {(f.slug, f.symbol) for f in found}
    assert ("undocumented", "PSDT_FIXTURE_B") in slugs
    assert ("dead-doc", "PSDT_FIXTURE_C") in slugs
    assert ("undocumented", "PSDT_FIXTURE_A") not in slugs


def test_knob_cross_module_constant_default_resolves(tmp_path):
    """A knob read through a constant imported from a sibling module must
    resolve to that module's literal (the ENV_DTYPE pattern) — no
    conflicting-default false positive, and the registry records it."""
    pkg = tmp_path / "pkg"
    _write(pkg, "messages.py", """
        import os
        ENV_DTYPE = "PSDT_FIXTURE_DTYPE"
        KIND = os.environ.get(ENV_DTYPE, "bf16")
        """)
    _write(pkg, "chain.py", """
        import os

        from .messages import ENV_DTYPE

        KIND = os.environ.get(ENV_DTYPE, "bf16")
        """)
    found = knobcheck.run(root=str(pkg), check_registry=False)
    assert found == []
    reg = knobcheck.build_registry(str(pkg))
    assert reg["knobs"]["PSDT_FIXTURE_DTYPE"]["defaults"] == ["bf16"]


def test_committed_knob_registry_current():
    """Currency gate: analysis/knob_registry.json must match a fresh scan
    bit for bit (pst-analyze --write-knob-registry)."""
    golden = knobcheck.load_registry()
    assert golden is not None
    assert golden == knobcheck.build_registry()


# ------------------------------------------------------ flight event pass

def test_event_unpaired_and_duplicate_code_detected(tmp_path):
    """An .start with no .end, two names on one code, and events that no
    code path ever records each produce a flight-event finding."""
    _write(tmp_path, "obs/flight.py", """
        EVENTS = {
            "fixture.go.start": 1,
            "fixture.tick": 1,
        }
        """)
    found = eventcheck.run(root=str(tmp_path))
    assert all(f.pass_id == F.FLIGHT_EVENT for f in found)
    slugs = {f.slug for f in found}
    assert "unpaired" in slugs
    assert "dup-code:1" in slugs
    assert "never-recorded" in slugs


def test_event_conditional_record_site_counts(tmp_path):
    """Both arms of a ``record("a" if cond else "b")`` selection count as
    record sites — neither event is dead, and an unregistered name in
    either arm is still caught."""
    _write(tmp_path, "obs/flight.py", """
        EVENTS = {
            "fixture.warm": 10,
            "fixture.cold": 11,
        }
        """)
    _write(tmp_path, "svc.py", """
        def touch(flight, warm):
            flight.record("fixture.warm" if warm else "fixture.cold")
            flight.record("fixture.ghost")
        """)
    found = eventcheck.run(root=str(tmp_path))
    slugs = {(f.slug, f.symbol) for f in found}
    assert ("unregistered-record", "fixture.ghost") in slugs
    assert not any(slug == "never-recorded" for slug, _ in slugs)


# ------------------------------------------- interprocedural lock passes

def test_interproc_cross_function_inversion():
    """Each function is clean in isolation; only the call edge from the
    params-lock holder into the state-lock acquirer inverts the declared
    order — the whole point of the interprocedural pass."""
    summaries: list[lockcheck.FnSummary] = []
    found, edges = runner.analyze_source(textwrap.dedent("""
        import threading

        class ParameterServerCore:
            def __init__(self):
                self._state_lock = threading.Lock()
                self._params_lock = threading.Lock()

            def outer(self):
                with self._params_lock:
                    self._refresh()

            def _refresh(self):
                with self._state_lock:
                    pass
        """), "fixture/mod.py", summaries=summaries)
    assert by_pass(found + lockcheck.check_edges(edges), F.LOCK_ORDER) == []
    ip_edges, _ = lockcheck.interprocedural(summaries)
    inversions = by_pass(lockcheck.check_edges(edges + ip_edges),
                         F.LOCK_ORDER)
    assert len(inversions) == 1
    assert "_refresh" in inversions[0].message  # names the call chain
    assert "ParameterServerCore._state_lock" in inversions[0].message


def test_interproc_blocking_through_helper():
    """Blocking two calls deep while holding a lock that does not allow
    it: the finding names the callee AND the blocking primitive it
    reaches."""
    summaries: list[lockcheck.FnSummary] = []
    _, _ = runner.analyze_source(textwrap.dedent("""
        import threading
        import time

        class ParameterServerCore:
            def __init__(self):
                self._state_lock = threading.Lock()

            def outer(self):
                with self._state_lock:
                    self._drain()

            def _drain(self):
                time.sleep(0.1)
        """), "fixture/mod.py", summaries=summaries)
    _, ip_findings = lockcheck.interprocedural(summaries)
    blocking = by_pass(ip_findings, F.LOCK_BLOCKING)
    assert len(blocking) == 1
    assert blocking[0].symbol == "ParameterServerCore.outer"
    assert blocking[0].slug == "call:_drain:ParameterServerCore._state_lock"
    assert "time.sleep" in blocking[0].message


def test_interproc_cv_wait_handoff_is_legal():
    """Calling a helper whose only blocking act is waiting on the CV of
    the one lock the caller holds is the legal barrier hand-off, not a
    blocking-while-holding violation."""
    summaries: list[lockcheck.FnSummary] = []
    runner.analyze_source(textwrap.dedent("""
        import threading

        class ParameterServerCore:
            def __init__(self):
                self._state_lock = threading.Lock()
                self._cv = threading.Condition(self._state_lock)

            def outer(self):
                with self._state_lock:
                    self._park()

            def _park(self):
                self._cv.wait(timeout=1.0)
        """), "fixture/mod.py", summaries=summaries)
    _, ip_findings = lockcheck.interprocedural(summaries)
    assert by_pass(ip_findings, F.LOCK_BLOCKING) == []


# --------------------------------------------------- ranked-lock coverage

def test_every_ranked_lock_constructed_through_checked_lock():
    """Satellite gate: every LOCK_RANKS slot must be built through
    checked_lock("<name>") somewhere in the package, so PSDT_LOCK_CHECK=1
    arms ALL declared ranks — a rank with no checked construction site is
    discipline the runtime checker never enforces.  The reverse inclusion
    is free (checked_lock raises on undeclared names), but scanning both
    ways keeps the table and the call sites in one-to-one correspondence.
    analysis/ is excluded: the analyzer's own sources mention the pattern
    in docstrings, they construct no product locks."""
    root = runner.package_root()
    constructed: set[str] = set()
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("build", "__pycache__", "analysis")]
        for fname in filenames:
            if not fname.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fname),
                      encoding="utf-8") as fh:
                src = fh.read()
            constructed |= {m.group(1) for m in re.finditer(
                r'checked_lock\(\s*"([^"]+)"', src)}
    ranked = set(lock_order.LOCK_RANKS)
    assert ranked - constructed == set(), (
        f"ranked locks never built through checked_lock: "
        f"{sorted(ranked - constructed)}")
    assert constructed - ranked == set(), (
        f"checked_lock sites with no declared rank: "
        f"{sorted(constructed - ranked)}")


@pytest.mark.lockcheck
def test_runtime_every_ranked_lock_order_checked():
    """With the runtime checker armed, constructing ANY declared slot
    yields an order-asserting proxy, and the proxies enforce the table:
    a deliberate inversion across two arbitrary ranks raises."""
    for name in lock_order.LOCK_RANKS:
        assert isinstance(lock_order.checked_lock(name),
                          lock_order.CheckedLock), name
    low = lock_order.checked_lock("ParameterServerCore._params_lock")
    high = lock_order.checked_lock("FleetRouter._lock")
    with pytest.raises(lock_order.LockOrderError, match="lock-order"):
        with high:
            with low:
                pass
    assert lock_order.held_locks() == ()
