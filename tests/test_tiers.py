"""Hierarchical quantized aggregation units (ISSUE 9, tiers/):
topology grouping/election, weighted barrier folds with member covers,
the barrier relay, the per-tier error-feedback stage, the leaf
aggregator end to end, and the lock discipline of it all."""

import threading
import time

import numpy as np
import pytest

from parameter_server_distributed_tpu.core.coordinator_core import (
    CoordinatorCore)
from parameter_server_distributed_tpu.core.optimizer import SGD
from parameter_server_distributed_tpu.core.ps_core import ParameterServerCore
from parameter_server_distributed_tpu.core.tensor import to_wire
from parameter_server_distributed_tpu.rpc import messages as m
from parameter_server_distributed_tpu.tiers import messages as tmsg
from parameter_server_distributed_tpu.tiers import topology
from parameter_server_distributed_tpu.tiers.ef import ErrorFeedback
from parameter_server_distributed_tpu.tiers.topology import (
    contribution_map, form_groups)


def _entry(host, leader, members, leaf="addr:1"):
    return tmsg.TierGroupEntry(host_id=host, leader_worker_id=leader,
                               aggregate_id=tmsg.aggregate_id_for(leader),
                               leaf_address=leaf, member_ids=members)


# ----------------------------------------------------------------- grouping

def test_form_groups_by_host_with_threshold():
    workers = {0: ("hostA", "a:1"), 1: ("hostA", "a:2"),
               2: ("hostB", "b:1"),  # alone on hostB: stays flat
               3: ("hostA", "a:3")}
    groups, changed = form_groups(workers, [], set(), min_group=2)
    assert changed
    assert len(groups) == 1
    g = groups[0]
    assert g.host_id == "hostA"
    assert list(g.member_ids) == [0, 1, 3]
    assert g.leader_worker_id == 0  # lowest id with a leaf address leads
    assert g.leaf_address == "a:1"
    assert g.aggregate_id == tmsg.TIER_AGGREGATE_ID_BASE + 0
    # deterministic: same registry, same groups, no spurious change
    again, changed2 = form_groups(workers, groups, set(), min_group=2)
    assert not changed2 and [list(x.member_ids) for x in again] == [[0, 1, 3]]


def test_form_groups_freezes_membership():
    """A later same-host joiner does NOT resize a formed group (the live
    leaf barrier is armed at the original size)."""
    workers = {0: ("hostA", "a:1"), 1: ("hostA", "a:2")}
    groups, _ = form_groups(workers, [], set(), min_group=2)
    workers[5] = ("hostA", "a:5")
    after, changed = form_groups(workers, groups, set(), min_group=2)
    assert not changed
    assert [list(g.member_ids) for g in after] == [[0, 1]]


def test_form_groups_dissolved_leaf_never_reforms():
    workers = {0: ("hostA", "a:1"), 1: ("hostA", "a:2")}
    groups, _ = form_groups(workers, [], set(), min_group=2)
    dissolved = {groups[0].leaf_address}
    after, changed = form_groups(workers, groups, dissolved, min_group=2)
    assert changed
    # worker 1 still has a live leaf address, so a NEW group may form
    # under it at this layer; the coordinator's flat-latch (tested below)
    # is what prevents that for real members of a dissolved group
    assert all(g.leaf_address not in dissolved for g in after)


def test_contribution_map_weights_and_covers():
    groups = [_entry("hostA", 0, [0, 1, 3]), _entry("hostB", 4, [4, 5])]
    cmap = contribution_map(groups)
    assert cmap[tmsg.aggregate_id_for(0)] == (3, (0, 1, 3))
    assert cmap[tmsg.aggregate_id_for(4)] == (2, (4, 5))


def test_aggregate_id_base_matches_postmortem_mirror():
    """obs/postmortem.py mirrors the constant (it must not import
    tiers/); pst-analyze's flight-event pass is the primary drift gate
    (slug ``tier-base-mirror``) — this keeps the one-line runtime check
    close to the tier tests that depend on the labeling."""
    from parameter_server_distributed_tpu.obs import postmortem
    assert postmortem._TIER_ID_BASE == tmsg.TIER_AGGREGATE_ID_BASE


# --------------------------------------------------------- coordinator core

def test_coordinator_tier_register_and_confirmation(monkeypatch):
    monkeypatch.setenv("PSDT_TIERS", "1")
    core = CoordinatorCore("10.0.0.1", 50051)
    # worker 1 registers first; no group yet (alone)
    epoch0, groups, enabled, min_group, _ = core.tier_register(
        1, "hostA", "l1:1")
    assert enabled and min_group == 2 and not groups
    # worker 0 registers: group forms, led by 0 — and 0 (the leader) sees
    # it immediately...
    _, groups, _, _, _ = core.tier_register(0, "hostA", "l0:1")
    assert [list(g.member_ids) for g in groups] == [[0, 1]]
    assert groups[0].leader_worker_id == 0
    # ...which also CONFIRMS it, so members and the PS weight provider
    # (pure read) now see it too
    _, groups, _, _, _ = core.tier_register(-1, "")
    assert [g.leaf_address for g in groups] == ["l0:1"]


def test_coordinator_member_blind_until_leader_confirms(monkeypatch):
    monkeypatch.setenv("PSDT_TIERS", "1")
    core = CoordinatorCore("10.0.0.1", 50051)
    core.tier_register(0, "hostA", "l0:1")
    _, groups, _, _, _ = core.tier_register(1, "hostA", "l1:1")
    # the group formed on this call, but worker 1 (a member) must not see
    # it until the LEADER has been served it (the leader arms its leaf
    # synchronously before using the response)
    assert not groups
    _, groups, _, _, _ = core.tier_register(0, "hostA", "l0:1")
    assert groups  # leader sees (and confirms) it
    _, groups, _, _, _ = core.tier_register(1, "hostA", "l1:1")
    assert [list(g.member_ids) for g in groups] == [[0, 1]]


def test_coordinator_dead_leaf_dissolves_and_latches_flat(monkeypatch):
    monkeypatch.setenv("PSDT_TIERS", "1")
    core = CoordinatorCore("10.0.0.1", 50051)
    core.tier_register(0, "hostA", "l0:1")
    core.tier_register(1, "hostA", "l1:1")
    epoch1, groups, _, _, _ = core.tier_register(0, "hostA", "l0:1")
    assert groups
    epoch2, groups, _, _, _ = core.tier_register(1, "hostA",
                                              dead_leaf="l0:1")
    assert epoch2 > epoch1
    assert not groups
    # the ex-members are latched flat: re-registering never re-groups
    # them (their worker side downgraded permanently too)
    _, groups, _, _, _ = core.tier_register(0, "hostA", "l0:9")
    assert not groups
    _, groups, _, _, _ = core.tier_register(1, "hostA", "l1:9")
    assert not groups


def test_coordinator_eviction_drops_group(monkeypatch):
    monkeypatch.setenv("PSDT_TIERS", "1")
    now = [0.0]
    core = CoordinatorCore("10.0.0.1", 50051, time_fn=lambda: now[0])
    core.register_worker(0, "10.0.0.2", 1, "hostA")
    core.register_worker(1, "10.0.0.3", 1, "hostA")
    core.tier_register(0, "hostA", "l0:1")
    core.tier_register(1, "hostA", "l1:1")
    _, groups, _, _, _ = core.tier_register(0, "hostA", "l0:1")
    assert groups
    now[0] = 100.0
    assert set(core.remove_stale_workers(30.0)) == {0, 1}
    _, groups, _, _, _ = core.tier_register(-1, "")
    assert not groups


def test_coordinator_tiers_disabled_returns_nothing(monkeypatch):
    monkeypatch.delenv("PSDT_TIERS", raising=False)
    core = CoordinatorCore("10.0.0.1", 50051)
    _, groups, enabled, _, _ = core.tier_register(0, "hostA", "l0:1")
    assert not enabled and not groups


def test_coordinator_tells_latched_flat_workers(monkeypatch):
    """A worker whose group dissolved is TOLD it is latched flat, so a
    rebuilt TierClient stops polling (and releases its idle leaf)
    instead of re-registering at 2 Hz forever."""
    monkeypatch.setenv("PSDT_TIERS", "1")
    core = CoordinatorCore("10.0.0.1", 50051)
    core.tier_register(0, "hostA", "l0:1")
    core.tier_register(1, "hostA", "l1:1")
    *_, latched = core.tier_register(0, "hostA", "l0:1")
    assert not latched
    core.tier_register(1, "hostA", dead_leaf="l0:1")
    *_, latched = core.tier_register(0, "hostA", "l0:1")
    assert latched
    *_, latched = core.tier_register(1, "hostA", "l1:1")
    assert latched


# ------------------------------------------------- weighted folds + covers

def _agg(leader=0):
    return tmsg.aggregate_id_for(leader)


def _weighted_core(total=3, members=(0, 1), lr=1.0, **kw):
    cmap = {_agg(members[0]): (len(members), tuple(members))}
    core = ParameterServerCore(total_workers=total, optimizer=SGD(lr),
                               contributions_fn=lambda: cmap, **kw)
    core.initialize_parameters({"w": np.zeros(8, np.float32)})
    return core


@pytest.mark.parametrize("stripes", [1, 2])
def test_group_push_weights_the_mean_over_workers(stripes):
    core = _weighted_core(stripes=stripes)
    g01 = np.full(8, 6.0, np.float32)  # sum of workers 0 and 1
    g2 = np.full(8, 3.0, np.float32)
    r = core.receive_gradients(_agg(), 1, {"w": g01})
    assert not r.aggregation_complete and r.workers_received == 2
    r = core.receive_gradients(2, 1, {"w": g2})
    assert r.aggregation_complete and r.workers_received == 3
    np.testing.assert_allclose(core.get_parameters()["w"],
                               -(g01 + g2) / 3.0, rtol=1e-6)


def test_member_flat_repush_dedups_against_cover():
    """The downgrade recovery invariant: after a group contribution
    landed, a member's flat re-push of the SAME iteration is a duplicate
    — never a double count."""
    core = _weighted_core(total=3)
    core.receive_gradients(_agg(), 1, {"w": np.full(8, 6.0, np.float32)})
    r = core.receive_gradients(0, 1, {"w": np.full(8, 100.0, np.float32)})
    assert r.success and "duplicate" in r.message
    r = core.receive_gradients(1, 1, {"w": np.full(8, 100.0, np.float32)})
    assert r.success and "duplicate" in r.message
    # the real third worker still closes the barrier with the true mean
    r = core.receive_gradients(2, 1, {"w": np.full(8, 3.0, np.float32)})
    assert r.aggregation_complete
    np.testing.assert_allclose(core.get_parameters()["w"],
                               np.full(8, -3.0, np.float32), rtol=1e-6)


def test_group_overlapping_individual_contribution_rejected():
    """THE downgrade-race exactness guard: a member soft-fails at its
    leaf and re-pushes flat; the leaf later seals anyway and relays the
    group sum (which contains that member's gradient).  The PS must
    reject the overlapping group contribution whole — the other member
    replays flat and the mean stays exact — never fold it into a double
    count."""
    core = _weighted_core(total=2, members=(0, 1))
    g0 = np.full(8, 2.0, np.float32)
    g1 = np.full(8, 4.0, np.float32)
    r = core.receive_gradients(0, 1, {"w": g0})  # member 0 went flat
    assert r.success and not r.aggregation_complete
    # the leaf's group sum (g0 + g1) overlaps member 0's contribution
    r = core.receive_gradients(_agg(), 1, {"w": g0 + g1})
    assert not r.success and "overlaps" in r.message
    # member 1 replays flat: the barrier closes with the exact mean
    r = core.receive_gradients(1, 1, {"w": g1})
    assert r.aggregation_complete
    np.testing.assert_allclose(core.get_parameters()["w"],
                               -(g0 + g1) / 2.0, rtol=1e-6)


def test_group_after_commit_member_repush_is_exact():
    """The opposite interleaving: the group lands first, the member's
    flat replay dedups, and the mean is the same exact value."""
    core = _weighted_core(total=2, members=(0, 1))
    g0 = np.full(8, 2.0, np.float32)
    g1 = np.full(8, 4.0, np.float32)
    r = core.receive_gradients(_agg(), 1, {"w": g0 + g1})
    assert r.aggregation_complete  # the group IS the whole barrier here
    r = core.receive_gradients(0, 1, {"w": g0})
    assert r.success  # late: already aggregated
    np.testing.assert_allclose(core.get_parameters()["w"],
                               -(g0 + g1) / 2.0, rtol=1e-6)


def test_group_relay_retry_is_idempotent():
    """A leaf's re-push of an already-landed group contribution (e.g.
    after its params leg failed) folds nothing twice and commits as a
    duplicate."""
    core = _weighted_core(total=3)
    grads = {"w": np.full(8, 6.0, np.float32)}
    core.receive_gradients(_agg(), 1, grads)
    r = core.receive_gradients(_agg(), 1, grads)  # identical replay
    assert r.success and "duplicate" in r.message
    core.receive_gradients(2, 1, {"w": np.full(8, 3.0, np.float32)})
    np.testing.assert_allclose(core.get_parameters()["w"],
                               np.full(8, -3.0, np.float32), rtol=1e-6)


@pytest.mark.parametrize("mode", ["buffered", "async"])
def test_aggregate_push_rejected_outside_streaming_sync(mode):
    """Config-skew guard: group contributions exist only on the
    streaming sync path — the buffered escape hatch would count them as
    one phantom worker (members double-count on their flat replay) and
    async mode would apply the raw group SUM at group-size magnitude.
    Both must bounce retryably."""
    kw = (dict(aggregation="buffered") if mode == "buffered"
          else dict(staleness_bound=2))
    core = ParameterServerCore(total_workers=2, optimizer=SGD(1.0),
                               contributions_fn=lambda: {
                                   _agg(): (2, (0, 1))}, **kw)
    core.initialize_parameters({"w": np.zeros(4, np.float32)})
    before = core.get_parameters()["w"].copy()
    r = core.receive_gradients(_agg(), 1, {"w": np.ones(4, np.float32)})
    assert not r.success and "streaming" in r.message
    np.testing.assert_array_equal(core.get_parameters()["w"], before)
    # real workers are untouched by the guard
    r = core.receive_gradients(0, 1, {"w": np.ones(4, np.float32)})
    assert r.success


def test_unknown_aggregate_id_bounces_instead_of_phantom_fold():
    """The TTL-race guard: a group push whose aggregate id the PS cannot
    attribute (map predates the group, or no provider at all) is
    rejected RETRYABLY — folding it as a phantom weight-1 worker would
    double-count its members the moment they replay flat.  The lookup
    force-refreshes the cache once, so a just-confirmed group is
    accepted on the very push that races the TTL."""
    # no provider at all: aggregate ids always bounce, workers unaffected
    core = ParameterServerCore(total_workers=2, optimizer=SGD(1.0))
    core.initialize_parameters({"w": np.zeros(4, np.float32)})
    r = core.receive_gradients(_agg(), 1, {"w": np.ones(4, np.float32)})
    assert not r.success and "unknown tier aggregate" in r.message

    # provider whose FIRST map predates the group: the unknown-aggregate
    # lookup forces a refresh inside the TTL and the push lands
    maps = [{}, {_agg(): (2, (0, 1))}]
    calls = []

    def provider():
        calls.append(1)
        return maps[0] if len(calls) == 1 else maps[1]

    core = ParameterServerCore(total_workers=2, optimizer=SGD(1.0),
                               contributions_fn=provider,
                               contributions_ttl_s=60.0)
    core.initialize_parameters({"w": np.zeros(4, np.float32)})
    core.begin_push(0, 1)  # caches the empty pre-group map (call 1)
    r = core.receive_gradients(_agg(), 1, {"w": np.full(4, 6.0, np.float32)})
    assert r.success and r.aggregation_complete, r.message
    assert len(calls) == 2  # the forced refresh, not a TTL expiry
    np.testing.assert_allclose(core.get_parameters()["w"],
                               np.full(4, -3.0, np.float32), rtol=1e-6)


def test_contributions_ttl_cache_and_flap_protection():
    calls = []

    def provider():
        calls.append(1)
        return None if len(calls) > 1 else {_agg(): (2, (0, 1))}

    core = ParameterServerCore(total_workers=2,
                               contributions_fn=provider,
                               contributions_ttl_s=0.05)
    sink = core.begin_push(_agg(), 1)
    assert (sink.weight, sink.members) == (2, (0, 1))
    assert len(calls) == 1
    # within the TTL: cached, no second provider call
    core.begin_push(_agg(), 2)
    assert len(calls) == 1
    time.sleep(0.06)
    # expired AND the provider hiccups (None): the stale map keeps
    # serving instead of flapping weights mid-iteration
    sink = core.begin_push(_agg(), 3)
    assert (sink.weight, sink.members) == (2, (0, 1))
    assert len(calls) == 2


# ------------------------------------------------------------ barrier relay

def test_barrier_relay_installs_returned_store():
    core = ParameterServerCore(total_workers=2)
    core.initialize_parameters({"w": np.zeros(4, np.float32)})
    seen = {}

    def relay(iteration, sums, counts):
        seen["iteration"] = iteration
        seen["sums"] = {k: v.copy() for k, v in sums.items()}
        seen["counts"] = dict(counts)
        return {"w": np.full(4, 42.0, np.float32)}

    core.set_barrier_relay(relay)
    core.receive_gradients(0, 1, {"w": np.ones(4, np.float32)})
    r = core.receive_gradients(1, 1, {"w": np.ones(4, np.float32)})
    assert r.aggregation_complete
    assert seen["iteration"] == 1
    np.testing.assert_array_equal(seen["sums"]["w"],
                                  np.full(4, 2.0, np.float32))
    assert seen["counts"] == {"w": 2}  # RAW sums + counts, never scaled
    np.testing.assert_array_equal(core.get_parameters()["w"],
                                  np.full(4, 42.0, np.float32))


def test_barrier_relay_failure_leaves_barrier_retryable():
    core = ParameterServerCore(total_workers=2)
    core.initialize_parameters({"w": np.zeros(4, np.float32)})
    attempts = []

    def relay(iteration, sums, counts):
        attempts.append(dict(counts))
        if len(attempts) == 1:
            raise RuntimeError("upstream blip")
        return {"w": np.full(4, 7.0, np.float32)}

    core.set_barrier_relay(relay)
    core.receive_gradients(0, 1, {"w": np.ones(4, np.float32)})
    with pytest.raises(RuntimeError, match="upstream blip"):
        core.receive_gradients(1, 1, {"w": np.ones(4, np.float32)})
    # the accumulator was put back intact (counts NOT reset — no scale
    # ran); the next poll retries the close and the relay sees the same
    # sums again
    ready, received, total = core.wait_for_aggregation(1, timeout=5.0)
    assert ready and received == 2
    assert attempts == [{"w": 2}, {"w": 2}]
    np.testing.assert_array_equal(core.get_parameters()["w"],
                                  np.full(4, 7.0, np.float32))


# ------------------------------------------------------------ error feedback

def test_error_feedback_stage_two_phase_commit():
    ef = ErrorFeedback(enabled=True)
    g = np.linspace(-1, 1, 64, dtype=np.float32)
    tensors = ef.compress({"w": g}, m.WIRE_INT8)
    decoded = tensors[0].to_array()
    pending = ef.pending()
    np.testing.assert_allclose(pending["w"], g - decoded, atol=1e-7)
    assert ef.residual == {}  # not committed yet
    ef.commit()
    np.testing.assert_array_equal(ef.residual["w"], pending["w"])
    # next push compresses grad + residual
    t2 = ef.compress({"w": g}, m.WIRE_INT8)
    np.testing.assert_allclose(ef.pending()["w"],
                               (g + pending["w"]) - t2[0].to_array(),
                               atol=1e-7)


def test_error_feedback_stage_disabled_is_plain_to_wire():
    ef = ErrorFeedback(enabled=False)
    g = np.linspace(-1, 1, 32, dtype=np.float32)
    tensors = ef.compress({"w": g}, m.WIRE_INT8)
    ref = to_wire({"w": g}, m.WIRE_INT8)
    assert tensors[0].encode() == ref[0].encode()
    ef.commit()
    assert ef.residual == {}


def test_error_feedback_stages_are_independent():
    """Per-tier isolation: two compression points never share a carry."""
    a, b = ErrorFeedback(enabled=True), ErrorFeedback(enabled=True)
    g = np.linspace(-2, 2, 16, dtype=np.float32)
    a.compress({"w": g}, m.WIRE_INT8)
    a.commit()
    assert a.residual and not b.residual


def test_worker_ef_residual_property_back_compat():
    """`worker._ef_residual` stayed an assignable dict view over the new
    stage (PR-5 tests and callers poke it directly)."""
    from parameter_server_distributed_tpu.config import WorkerConfig
    from parameter_server_distributed_tpu.worker.worker import Worker

    worker = Worker(WorkerConfig(wire_dtype="int8"), trainer=None,
                    batches=iter(()), start_heartbeat=False)
    worker._ef_residual = {"w": np.ones(4, np.float32)}
    np.testing.assert_array_equal(worker._push_ef.residual["w"],
                                  np.ones(4, np.float32))
    tensors, residual = worker._compress_with_feedback(
        {"w": np.zeros(4, np.float32)}, m.WIRE_INT8)
    # the carry was applied: compress saw 0 + residual = 1
    np.testing.assert_allclose(tensors[0].to_array(),
                               np.ones(4, np.float32), atol=0.02)
    worker.shutdown()


# ---------------------------------------------------------- leaf aggregator

def _leaf_setup(tmp_path, group=2, wire=m.WIRE_RAW_F32, lr=0.5):
    from parameter_server_distributed_tpu.config import (
        ParameterServerConfig)
    from parameter_server_distributed_tpu.server.ps_service import (
        ParameterServer)
    from parameter_server_distributed_tpu.tiers.leaf import LeafAggregator

    ps = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=group,
        learning_rate=lr, checkpoint_dir=str(tmp_path / "ck"),
        autosave_period_s=600.0))
    port = ps.start()
    agg = _agg(0)
    ps.core.set_contributions_fn(
        lambda: {agg: (group, tuple(range(group)))})
    init = {"w": np.zeros(8, np.float32)}
    ps.core.initialize_parameters(init)
    leaf = LeafAggregator(0, f"127.0.0.1:{port}", wire_dtype=wire)
    return ps, leaf, init


def test_leaf_refuses_until_armed(tmp_path):
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient
    from parameter_server_distributed_tpu.tiers.leaf import LEAF_NOT_ARMED

    ps, leaf, init = _leaf_setup(tmp_path)
    client = PSClient(leaf.address)
    try:
        tensors = to_wire({"w": np.ones(8, np.float32)})
        push, params = client.push_pull(1, 1, lambda: iter(tensors),
                                        timeout=10.0)
        assert not push.success and LEAF_NOT_ARMED in push.message
        assert params is None
    finally:
        client.close()
        leaf.stop()
        ps.stop(0)


def test_leaf_group_round_end_to_end(tmp_path):
    """Two members push f32 to the leaf; ONE upstream contribution
    closes the PS barrier with the exact worker mean; fresh params fan
    back to both members."""
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient

    ps, leaf, init = _leaf_setup(tmp_path)
    leaf.arm(2, _agg(0), init)
    clients = [PSClient(leaf.address) for _ in range(2)]
    grads = [np.full(8, 1.0, np.float32), np.full(8, 3.0, np.float32)]
    stores: list = [None, None]

    def member(wid):
        local = {}
        tensors = to_wire({"w": grads[wid]})
        push, params = clients[wid].push_pull(
            wid, 1, lambda: iter(tensors),
            pull_wire_dtype=m.WIRE_RAW_F32, timeout=30.0,
            on_chunk=lambda ts: local.update(
                {t.name: t.to_array() for t in ts}))
        assert push.success, push.message
        assert params is not None
        stores[wid] = local

    try:
        threads = [threading.Thread(target=member, args=(i,),
                                    name=f"tier-member-{i}")
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive(), "member wedged"
        expected = -0.5 * (grads[0] + grads[1]) / 2.0
        np.testing.assert_allclose(ps.core.get_parameters()["w"], expected,
                                   rtol=1e-6)
        for store in stores:
            np.testing.assert_allclose(store["w"], expected, rtol=1e-6)
    finally:
        for c in clients:
            c.close()
        leaf.stop()
        ps.stop(0)


def test_leaf_quantized_upstream_carries_ef(tmp_path):
    """int8 upstream: the leaf's own EF stage carries the quantization
    error, so two rounds of identical gradients land closer to the exact
    trajectory than a single round's quantization error would suggest."""
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient

    ps, leaf, init = _leaf_setup(tmp_path, wire=m.WIRE_INT8, lr=1.0)
    leaf.arm(2, _agg(0), init)
    clients = [PSClient(leaf.address) for _ in range(2)]
    rng = np.random.default_rng(3)
    grads = rng.standard_normal(8).astype(np.float32)
    try:
        for it in range(1, 4):
            threads = [threading.Thread(
                target=lambda wid=wid: clients[wid].push_pull(
                    wid, it, lambda: iter(to_wire({"w": grads})),
                    pull_wire_dtype=m.WIRE_BF16, timeout=30.0),
                name=f"tm{it}-{wid}") for wid in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
        # exact: w = -3 * grads; int8+EF must be close (bias cancels)
        exact = -3.0 * grads
        got = ps.core.get_parameters()["w"]
        assert float(np.linalg.norm(got - exact)) < 0.15 * float(
            np.linalg.norm(exact))
        assert leaf._ef.residual  # the carry is live
    finally:
        for c in clients:
            c.close()
        leaf.stop()
        ps.stop(0)


# ------------------------------------------------------------ lock discipline

@pytest.mark.lockcheck
def test_leaf_fold_seal_downgrade_hammer():
    """Multi-worker leaf hammer under PSDT_LOCK_CHECK=1: concurrent
    member folds/commits across iterations, a relay that fails once per
    iteration (seal retry path), and a mid-run relay swap (the downgrade
    teardown shape) — no lock-order violation, exactly-once aggregation
    per iteration."""
    relay_calls: dict[int, int] = {}
    relay_lock = threading.Lock()

    def relay(iteration, sums, counts):
        with relay_lock:
            n = relay_calls[iteration] = relay_calls.get(iteration, 0) + 1
        if n == 1:
            raise RuntimeError("injected upstream failure")
        return {name: np.zeros_like(v) for name, v in sums.items()}

    core = ParameterServerCore(total_workers=4, stripes=2)
    core.initialize_parameters(
        {f"w{i}": np.zeros(64, np.float32) for i in range(8)})
    core.set_barrier_relay(relay)
    errors: list[BaseException] = []

    def worker(wid: int):
        rng = np.random.default_rng(wid)
        try:
            for it in range(1, 6):
                grads = {f"w{i}": rng.standard_normal(64).astype(np.float32)
                         for i in range(8)}
                try:
                    core.receive_gradients(wid, it, grads)
                except RuntimeError:
                    pass  # the injected relay failure: retried below
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    ready, _, _ = core.wait_for_aggregation(it, timeout=1.0)
                    if ready:
                        break
                else:
                    raise AssertionError(f"iteration {it} never closed")
        except BaseException as exc:  # noqa: BLE001 — surfaced below
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(wid,),
                                name=f"hammer-{wid}") for wid in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "hammer worker wedged"
    assert not errors, errors
    # every iteration aggregated exactly once, each after >= 1 retry
    assert set(relay_calls) == {1, 2, 3, 4, 5}
    assert all(n >= 2 for n in relay_calls.values())
    core.set_barrier_relay(None)  # the downgrade teardown shape


def test_topology_env_knobs(monkeypatch):
    monkeypatch.setenv("PSDT_TIERS", "1")
    assert topology.tiers_enabled()
    assert topology.tiers_enabled(None)
    assert not topology.tiers_enabled(False)  # config override wins
    monkeypatch.setenv("PSDT_TIERS", "0")
    assert not topology.tiers_enabled()
    assert topology.tiers_enabled(True)
    monkeypatch.setenv("PSDT_TIER_MIN_GROUP", "1")
    assert topology.min_group_size() == 2  # floor: a 1-group adds a hop
    monkeypatch.setenv("PSDT_TIER_DTYPE", "topk")
    assert topology.tier_wire_dtype() == m.WIRE_TOPK
    monkeypatch.setenv("PSDT_TIER_DTYPE", "nope")
    with pytest.raises(ValueError):
        topology.tier_wire_dtype()
