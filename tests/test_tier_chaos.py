"""Hierarchical aggregation acceptance (ISSUE 9): convergence of the
lossy tree with per-tier error feedback, the leaf-kill chaos drill
(group degrades to flat with zero failed steps and a matching loss
curve), PS ingress scaling with group count, and the pst-trace
reconstruction of the downgrade from on-disk flight rings."""

import threading
import time

import numpy as np
import pytest

from parameter_server_distributed_tpu.cli.worker_main import build_worker
from parameter_server_distributed_tpu.config import (CoordinatorConfig,
                                                     ParameterServerConfig,
                                                     WorkerConfig)
from parameter_server_distributed_tpu.core.optimizer import SGD
from parameter_server_distributed_tpu.core.ps_core import ParameterServerCore
from parameter_server_distributed_tpu.core.tensor import to_wire
from parameter_server_distributed_tpu.obs import flight, postmortem
from parameter_server_distributed_tpu.rpc import messages as m
from parameter_server_distributed_tpu.server.coordinator_service import (
    Coordinator)
from parameter_server_distributed_tpu.server.ps_service import ParameterServer
from parameter_server_distributed_tpu.tiers import messages as tmsg
from parameter_server_distributed_tpu.tiers.ef import ErrorFeedback
from parameter_server_distributed_tpu.tiers.topology import (
    TierContributionProvider)


def _grads(rng, shapes):
    return {name: rng.standard_normal(shape).astype(np.float32)
            for name, shape in shapes.items()}


# ---------------------------------------------------------------- convergence

@pytest.mark.parametrize("wire", ["int8", "topk"])
def test_lossy_tree_with_per_tier_ef_tracks_f32_closer(wire):
    """The ISSUE 9 convergence acceptance (the PR-5 EF test pattern,
    lifted to the tree): a two-worker group whose leaf quantizes its ONE
    upstream contribution tracks the flat-f32 trajectory strictly closer
    WITH the leaf's error-feedback stage than without it."""
    rng = np.random.default_rng(13)
    shapes = {"w": (64, 16), "b": (32,)}
    init = _grads(rng, shapes)
    steps = [[_grads(rng, shapes) for _ in range(2)] for _ in range(20)]
    wire_id = m.WIRE_DTYPE_NAMES[wire]
    agg = tmsg.aggregate_id_for(0)

    def run(mode: str) -> dict:
        core = ParameterServerCore(
            total_workers=2, optimizer=SGD(0.05),
            contributions_fn=(None if mode == "f32"
                              else (lambda: {agg: (2, (0, 1))})))
        core.initialize_parameters(init)
        leaf_ef = ErrorFeedback(enabled=(mode == "ef"))
        for it, pair in enumerate(steps, start=1):
            if mode == "f32":
                for wid, grads in enumerate(pair):
                    core.receive_gradients(wid, it, grads)
                continue
            # the leaf tier: fold the group locally (exact f32 adds),
            # quantize the ONE upstream contribution
            sums = {name: pair[0][name] + pair[1][name] for name in shapes}
            tensors = leaf_ef.compress(sums, wire_id, topk_density=0.25)
            seen = {t.name: t.to_array() for t in tensors}
            r = core.receive_gradients(agg, it, seen)
            assert r.aggregation_complete, r.message
            leaf_ef.commit()
        return core.get_parameters()

    exact = run("f32")
    with_ef = run("ef")
    without = run("lossy")

    def dist(params):
        return sum(float(np.linalg.norm(params[k] - exact[k]))
                   for k in shapes)

    assert dist(with_ef) < dist(without), (
        f"{wire}: tree+EF {dist(with_ef):.4f} !< tree-no-EF "
        f"{dist(without):.4f}")


# --------------------------------------------------------------- the cluster

def _tier_cluster(tmp_path, tag, iterations, kill_leaf_after=None,
                  base_port=16400, workers_n=2, flight_dir=None):
    """Coordinator + PS + ``workers_n`` tier-enabled workers sharing one
    simulated host: they form ONE group whose leaf folds locally and
    relays upstream.  ``kill_leaf_after``: once every worker completed
    that many iterations, the leaf's server is hard-aborted mid-run (all
    live member connections RST, the in-tree equivalent of the netsim
    connection drop) — the group must degrade to flat with ZERO failed
    steps."""
    if flight_dir is not None:
        flight.enable(str(flight_dir), role="cluster", records=65536)
    ps = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=workers_n,
        learning_rate=0.1, checkpoint_dir=str(tmp_path / f"{tag}-ck"),
        autosave_period_s=600.0))
    pport = ps.start()
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0, ps_address="127.0.0.1",
        ps_port=pport, reap_period_s=600.0))
    cport = coordinator.start()
    provider = TierContributionProvider(f"127.0.0.1:{cport}")
    ps.core.set_contributions_fn(provider)
    workers = [build_worker(WorkerConfig(
        coordinator_address=f"127.0.0.1:{cport}", worker_id=i,
        address="127.0.0.1", port=base_port + i, model="mnist_mlp",
        batch_size=32, heartbeat_period_s=600.0,
        tiers=True, tier_host_id=f"{tag}-host"))
        for i in range(workers_n)]
    losses: dict[int, list[float]] = {i: [] for i in range(workers_n)}
    errors: list[BaseException] = []
    try:
        for w in workers:
            w.initialize()
        # Deterministic activation: drive the rate-limited topology polls
        # until every worker holds its group assignment, so short test
        # runs measure the steady tiered state rather than the (benign,
        # soft-failure-covered) formation races of mid-run activation.
        # Poll EVERY worker each pass — registration is mutual, so a
        # short-circuiting check would starve the later workers' polls.
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            states = [w._tier.maybe_activate() for w in workers
                      if w._tier is not None]
            if all(states):
                break
            time.sleep(0.05)

        def run(w, wid):
            try:
                for it in range(iterations):
                    losses[wid].append(w.run_iteration(it))
            except BaseException as exc:  # noqa: BLE001 — asserted below
                errors.append(exc)

        threads = [threading.Thread(target=run, args=(w, i), daemon=True,
                                    name=f"tier-worker-{i}")
                   for i, w in enumerate(workers)]
        for t in threads:
            t.start()
        killed = False
        if kill_leaf_after is not None:
            deadline = time.monotonic() + 90
            while (min(len(ls) for ls in losses.values()) < kill_leaf_after
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            leaf = next((w._tier._leaf for w in workers
                         if w._tier is not None
                         and w._tier._leaf is not None), None)
            if leaf is not None:
                leaf._server.stop(None)  # hard abort: members see RST
                killed = True
        for t in threads:
            t.join(timeout=180)
            assert not t.is_alive(), "worker wedged"
        assert not errors, errors
        assert all(len(ls) == iterations for ls in losses.values())
        relayed = sum(1 for w in workers if w._tier is not None
                      and w._tier.active)
        return losses, killed, relayed
    finally:
        for w in workers:
            w.shutdown()
        provider.close()
        coordinator.stop()
        ps.stop(0)
        if flight_dir is not None:
            flight.disable()


@pytest.fixture
def tier_env(monkeypatch):
    """Cluster-test knobs: tiers on, LOSSLESS tree (so the two-tier
    arithmetic is the flat topology's exactly and loss curves compare
    with allclose), short leaf-barrier cap (formation races resolve in
    seconds, not the production 20 s), no shm (deterministic loopback)."""
    monkeypatch.setenv("PSDT_TIERS", "1")
    monkeypatch.setenv("PSDT_TIER_DTYPE", "raw")
    # long enough to ride out first-iteration jit-compile skew between
    # the members on a loaded host (a premature soft-fail is CORRECT but
    # makes the run partially flat), short enough that real races
    # resolve in seconds
    monkeypatch.setenv("PSDT_TIER_BARRIER_TIMEOUT_S", "8")
    monkeypatch.setenv("PSDT_SHM", "0")


def test_leaf_kill_mid_run_degrades_to_flat_zero_failed_steps(
        tmp_path, tier_env):
    """THE chaos acceptance: hard-kill the group's leaf aggregator under
    live 2-worker tiered training — the group downgrades to flat with
    zero failed steps and the loss curve matches the no-failure run
    (lossless tree => identical arithmetic on both topologies)."""
    iterations = 6
    clean, _, _ = _tier_cluster(tmp_path, "clean", iterations,
                                base_port=16400)
    flight_dir = tmp_path / "flight"
    chaos, killed, _ = _tier_cluster(tmp_path, "chaos", iterations,
                                     kill_leaf_after=3, base_port=16410,
                                     flight_dir=flight_dir)
    assert killed, "the leaf kill never fired"
    for wid in (0, 1):
        # iteration 0 is the bootstrap NaN on both runs
        np.testing.assert_allclose(chaos[wid][1:], clean[wid][1:],
                                   rtol=1e-4, atol=1e-6,
                                   err_msg=f"worker {wid} loss curve "
                                           f"diverged across the leaf kill")

    # pst-trace reconstructs the story from the on-disk rings: the
    # election, the group's upstream relays, and the permanent downgrade
    rings = postmortem.load_rings(str(flight_dir))
    events = postmortem.merge_events(rings)
    names = {e["event"] for e in events}
    assert "tier.elect" in names
    assert "tier.seal" in names and "tier.upstream" in names
    assert "tier.downgrade" in names
    rep = postmortem.report(str(flight_dir))
    degrades = rep["narrative"].get("degrades", [])
    assert any(d["what"] == "tier.downgrade" for d in degrades)
    rendered = postmortem.render_report(rep)
    assert "tier.downgrade" in rendered


def test_tiered_cluster_loss_matches_flat_cluster(tmp_path, tier_env,
                                                  monkeypatch):
    """The no-failure equivalence: a lossless two-tier run produces the
    flat topology's loss curve (the tree changes the route, not the
    math), and the group really did relay upstream."""
    from parameter_server_distributed_tpu.obs import stats as obs_stats

    relays_before = obs_stats.counter("tier.relays").value
    iterations = 5
    tiered, _, active = _tier_cluster(tmp_path, "tiered", iterations,
                                      base_port=16420)
    # the group really used the tree (even if a soft-failure on a loaded
    # host turned SOME iterations flat — the loss equivalence below holds
    # either way, that being the whole point of the downgrade design)
    assert obs_stats.counter("tier.relays").value > relays_before, \
        "the group never relayed upstream"
    monkeypatch.setenv("PSDT_TIERS", "0")
    flat, _, _ = _tier_cluster(tmp_path, "flat", iterations,
                               base_port=16430)
    for wid in (0, 1):
        np.testing.assert_allclose(tiered[wid][1:], flat[wid][1:],
                                   rtol=1e-4, atol=1e-6,
                                   err_msg=f"worker {wid}: tiered loss "
                                           f"curve diverged from flat")


# ------------------------------------------------------------------- ingress

class _IngressTally:
    """Counts encoded gradient bytes arriving at the PS service."""

    def __init__(self, service):
        self._service = service
        self.bytes = 0
        self._lock = threading.Lock()

    def PushPullStream(self, request_iterator, context):
        def tap():
            for chunk in request_iterator:
                n = sum(t.encoded_size() for t in chunk.gradients)
                with self._lock:
                    self.bytes += n
                yield chunk
        yield from self._service.PushPullStream(tap(), context)

    def __getattr__(self, name):
        return getattr(self._service, name)


def test_ingress_scales_with_group_count_not_worker_count(tmp_path,
                                                          monkeypatch):
    """The ISSUE 9 ingress acceptance, in-process: 4 workers in 2
    same-host groups push one iteration — per-iteration PS ingress bytes
    are <= 55% of the flat topology's (2 int8-quantized contributions vs
    4 f32 pushes; measured ~12.5%)."""
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient
    from parameter_server_distributed_tpu.rpc.service import (bind_service,
                                                              make_server)
    from parameter_server_distributed_tpu.server.ps_service import (
        ParameterServerService)
    from parameter_server_distributed_tpu.checkpoint.manager import (
        CheckpointManager)
    from parameter_server_distributed_tpu.tiers.leaf import LeafAggregator

    monkeypatch.setenv("PSDT_SHM", "0")  # every byte crosses the tally
    rng = np.random.default_rng(0)
    params = {f"w{i}": rng.standard_normal(4096).astype(np.float32)
              for i in range(4)}
    grads = [{k: rng.standard_normal(4096).astype(np.float32)
              for k in params} for _ in range(4)]

    def run(tiered: bool) -> int:
        core = ParameterServerCore(total_workers=4)
        core.initialize_parameters(params)
        service = ParameterServerService(core, CheckpointManager(
            core, directory=str(tmp_path / f"ck-{tiered}"),
            checkpoint_interval=10**9, check_period_s=3600.0))
        tally = _IngressTally(service)
        server = make_server(max_workers=16)
        bind_service(server, m.PARAMETER_SERVER_SERVICE,
                     {**m.PARAMETER_SERVER_METHODS,
                      **m.PARAMETER_SERVER_STREAM_METHODS}, tally)
        port = server.add_insecure_port("127.0.0.1:0")
        server.start()
        ps_addr = f"127.0.0.1:{port}"
        leaves, targets = [], [ps_addr] * 4
        if tiered:
            contrib = {}
            for leader, members in ((0, (0, 1)), (2, (2, 3))):
                agg = tmsg.aggregate_id_for(leader)
                leaf = LeafAggregator(leader, ps_addr,
                                      wire_dtype=m.WIRE_INT8)
                leaf.arm(2, agg, params)
                leaves.append(leaf)
                contrib[agg] = (2, members)
                for wid in members:
                    targets[wid] = leaf.address
            core.set_contributions_fn(lambda: contrib)
        clients = [PSClient(addr) for addr in targets]
        wire = [to_wire(g) for g in grads]
        try:
            threads = [threading.Thread(
                target=lambda wid=wid: clients[wid].push_pull(
                    wid, 1, lambda: iter(wire[wid]),
                    pull_wire_dtype=m.WIRE_BF16, timeout=60.0),
                name=f"ingress-{wid}") for wid in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
                assert not t.is_alive()
            return tally.bytes
        finally:
            for c in clients:
                c.close()
            for leaf in leaves:
                leaf.stop()
            server.stop(0.5)

    flat = run(tiered=False)
    tier = run(tiered=True)
    assert tier <= 0.55 * flat, (
        f"tier ingress {tier} B !<= 55% of flat {flat} B")
