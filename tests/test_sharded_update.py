"""Cross-replica sharded arena close (ISSUE 18): the primary and its
in-sync backups split every close's stripe slabs into owned slices,
each replica runs the fused arena stages only over its own slices, and
the fresh slabs all-gather back — raw exchange bit-identical to the
single-node arena close, quantized exchange bounded by error feedback,
any mid-exchange death degrading that close to the local full apply
with zero failed steps (replication/sharded_update.py)."""

import threading
import time

import numpy as np
import pytest

from parameter_server_distributed_tpu.config import ParameterServerConfig
from parameter_server_distributed_tpu.core import device_apply
from parameter_server_distributed_tpu.core.ps_core import ParameterServerCore
from parameter_server_distributed_tpu.async_sgd.device_optimizer import (
    ShardedDeviceOptimizer)
from parameter_server_distributed_tpu.obs import stats as obs_stats
from parameter_server_distributed_tpu.replication import sharded_update as su
from parameter_server_distributed_tpu.replication import messages as rmsg
from parameter_server_distributed_tpu.server.ps_service import ParameterServer

SIZE = 33  # deliberately prime-ish: slice boundaries land mid-tensor


def _counters():
    return dict(obs_stats.REGISTRY.snapshot().get("counters", {}))


def _gauge(name):
    return obs_stats.REGISTRY.snapshot().get("gauges", {}).get(name, 0)


def make_ps(tmp_path, name, total_workers=1, **kw):
    ps = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=total_workers,
        checkpoint_dir=str(tmp_path / name), learning_rate=0.1,
        autosave_period_s=600.0, **kw))
    return ps, ps.start()


def rand_store(n=6, size=SIZE, seed=0):
    rng = np.random.default_rng(seed)
    return {f"layer{i}/w": rng.standard_normal(size).astype(np.float32)
            for i in range(n)}


def run_closes(primary, store, iterations, seed=1, worker=0):
    rng = np.random.default_rng(seed)
    for it in range(1, iterations + 1):
        grads = {k: rng.standard_normal(len(v)).astype(np.float32)
                 for k, v in store.items()}
        r = primary.core.receive_gradients(worker, it, grads)
        assert r.aggregation_complete, r.message


def snapshot(ps):
    return {k: np.array(v, np.float32)
            for k, v in ps.core.get_parameters().items()}


@pytest.fixture
def arena_env(monkeypatch):
    """Every server-level sharded test runs the flat-arena close path
    (the sharded update only engages there)."""
    if not device_apply.available():
        pytest.skip("no jax backend/device for the arena close")
    monkeypatch.setenv("PSDT_ARENA", "1")


# ----------------------------------------------------------------- units

def test_slice_ranges_partition_exactly():
    for size in (0, 1, 2, 7, 33, 1024):
        for replicas in (1, 2, 3, 4, 7):
            ranges = su.slice_ranges(size, replicas)
            assert len(ranges) == replicas
            assert ranges[0][0] == 0 and ranges[-1][1] == size
            assert all(ranges[i][1] == ranges[i + 1][0]
                       for i in range(replicas - 1))
            assert su._full_cover(ranges, size)
    # R > size: some replicas own empty ranges, coverage still exact
    assert su.slice_ranges(2, 4) == [(0, 0), (0, 1), (1, 1), (1, 2)]


def test_exchange_dtype_options():
    from parameter_server_distributed_tpu.rpc import messages as m

    assert su.exchange_wire_dtype("raw") == m.WIRE_RAW_F32
    assert su.exchange_wire_dtype("bf16") == m.WIRE_BF16
    assert su.exchange_wire_dtype("int8") == m.WIRE_INT8
    with pytest.raises(ValueError):
        su.exchange_wire_dtype("fp4")


# -------------------------------------------------- raw bit identity

@pytest.mark.parametrize("backups", [1, 3])
def test_sharded_close_bit_identical_to_single_node(tmp_path, arena_env,
                                                    backups):
    """THE acceptance: the raw sharded close at R=2 and R=4 produces
    byte-identical params to the single-node arena close, every backup
    ends byte-identical to the primary, and the closes really ran
    sharded (counter-asserted, no silent full-apply)."""
    store = rand_store()
    base, _ = make_ps(tmp_path, "base", optimizer="sharded_momentum")
    bks = [make_ps(tmp_path, f"bk{i}", optimizer="sharded_momentum")
           for i in range(backups)]
    primary, _ = make_ps(
        tmp_path, "pr", optimizer="sharded_momentum",
        backup_address=",".join(f"127.0.0.1:{port}" for _, port in bks),
        replication="sync", sharded_update="1")
    try:
        assert primary.sharded_updater is not None
        before = _counters()
        base.core.initialize_parameters(rand_store())
        run_closes(base, store, 5)
        primary.core.initialize_parameters(rand_store())
        run_closes(primary, store, 5)
        after = _counters()
        # the FIRST close may run local (the backups learn the init
        # version through its flat ship); every later close shards
        sharded = (after.get("ps.apply.sharded", 0)
                   - before.get("ps.apply.sharded", 0))
        assert sharded >= 4, f"only {sharded} of 5 closes ran sharded"
        assert (after.get("ps.replica.sharded_bytes", 0)
                > before.get("ps.replica.sharded_bytes", 0))
        assert (after.get("ps.replica.sharded_applies", 0)
                - before.get("ps.replica.sharded_applies", 0)
                >= sharded * backups)
        expected = snapshot(base)
        got = snapshot(primary)
        assert set(expected) == set(got)
        for name in expected:
            assert np.array_equal(expected[name], got[name]), name
        # every backup holds the identical raw bits and the iteration
        for bk, _port in bks:
            bp = snapshot(bk)
            for name in expected:
                assert np.array_equal(expected[name], bp[name]), name
            assert bk.core.current_iteration == 5
        # the backups COMPUTED this close: not idle flat-ship replicas
        assert _gauge("ps.replica.idle_accelerator") == 0
    finally:
        primary.stop(0)
        for bk, _port in bks:
            bk.stop(0)
        base.stop(0)


def test_flat_ship_replica_flags_idle_accelerator(tmp_path, arena_env):
    """The satellite gauge: a backup replicating by flat SHIPPING only
    (sharded update off) surfaces its idle accelerator as
    ps.replica.idle_accelerator=1."""
    gauge = obs_stats.gauge("ps.replica.idle_accelerator")
    gauge.set(0)
    backup, bport = make_ps(tmp_path, "idle-bk",
                            optimizer="sharded_momentum")
    primary, _ = make_ps(tmp_path, "idle-pr", optimizer="sharded_momentum",
                         backup_address=f"127.0.0.1:{bport}",
                         replication="sync")
    try:
        assert primary.sharded_updater is None  # not requested
        store = rand_store()
        primary.core.initialize_parameters(store)
        run_closes(primary, store, 2)
        assert gauge.value == 1, "flat-ship replica did not flag idle"
        bp, pp = snapshot(backup), snapshot(primary)
        for name in pp:
            assert np.array_equal(pp[name], bp[name]), name
    finally:
        gauge.set(0)
        primary.stop(0)
        backup.stop(0)


def test_single_replica_declines_to_local_apply(tmp_path, arena_env):
    """sharded_update=1 with NO backup configured: the updater stays
    disarmed and every close runs the ordinary local arena apply."""
    before = _counters()
    solo, _ = make_ps(tmp_path, "solo", optimizer="sharded_momentum",
                      sharded_update="1")
    try:
        assert solo.sharded_updater is None
        store = rand_store()
        solo.core.initialize_parameters(store)
        run_closes(solo, store, 3)
        after = _counters()
        assert (after.get("ps.apply.sharded", 0)
                == before.get("ps.apply.sharded", 0))
        assert solo.core.current_iteration == 3
    finally:
        solo.stop(0)


# ----------------------------------------------- quantized exchange

@pytest.mark.parametrize("dtype,tol", [("bf16", 0.02), ("int8", 0.05)])
def test_quantized_exchange_bounded_error(tmp_path, arena_env, dtype, tol):
    """EQuARX-style lossy exchange + PR-9 error feedback: the sharded
    close under bf16/int8 sums tracks the exact run within a bounded
    envelope instead of compounding, and the closes really sharded."""
    store = rand_store()
    base, _ = make_ps(tmp_path, f"{dtype}-base", optimizer="sharded_adam")
    backup, bport = make_ps(tmp_path, f"{dtype}-bk",
                            optimizer="sharded_adam")
    primary, _ = make_ps(tmp_path, f"{dtype}-pr", optimizer="sharded_adam",
                         backup_address=f"127.0.0.1:{bport}",
                         replication="sync", sharded_update="1",
                         sharded_update_dtype=dtype)
    try:
        before = _counters()
        base.core.initialize_parameters(rand_store())
        run_closes(base, store, 6)
        primary.core.initialize_parameters(rand_store())
        run_closes(primary, store, 6)
        after = _counters()
        assert (after.get("ps.apply.sharded", 0)
                - before.get("ps.apply.sharded", 0)) >= 5
        expected, got = snapshot(base), snapshot(primary)
        scale = max(float(np.max(np.abs(v))) for v in expected.values())
        for name in expected:
            err = float(np.max(np.abs(expected[name] - got[name])))
            assert err <= tol * max(scale, 1.0), (name, err)
        # the backup's params: own slices exact, foreign slices arrive
        # through the quantized install leg — same bounded envelope
        bp = snapshot(backup)
        for name in expected:
            err = float(np.max(np.abs(got[name] - bp[name])))
            assert err <= tol * max(scale, 1.0), (name, err)
    finally:
        primary.stop(0)
        backup.stop(0)
        base.stop(0)


# ------------------------------------------------------------- chaos

def test_kill_backup_mid_run_zero_failed_steps(tmp_path, arena_env):
    """THE chaos acceptance: hard-kill the backup while closes stream
    through the sharded exchange — every step still succeeds (the
    degraded closes run the local full apply, which is bit-identical),
    the fallback counter surfaces the degrade, and the final params
    match the no-replication run exactly."""
    store = rand_store()
    base, _ = make_ps(tmp_path, "chaos-base", optimizer="sharded_momentum")
    backup, bport = make_ps(tmp_path, "chaos-bk",
                            optimizer="sharded_momentum")
    primary, _ = make_ps(tmp_path, "chaos-pr", optimizer="sharded_momentum",
                         backup_address=f"127.0.0.1:{bport}",
                         replication="sync", sharded_update="1")
    iterations = 8
    errors: list[BaseException] = []
    try:
        base.core.initialize_parameters(rand_store())
        run_closes(base, store, iterations)
        primary.core.initialize_parameters(rand_store())
        before = _counters()

        def pusher():
            try:
                run_closes(primary, store, iterations)
            except BaseException as exc:  # noqa: BLE001 — asserted below
                errors.append(exc)

        t = threading.Thread(target=pusher, daemon=True,
                             name="sharded-chaos-pusher")
        t.start()
        deadline = time.monotonic() + 60
        while (primary.core.current_iteration < 3
               and time.monotonic() < deadline):
            time.sleep(0.001)
        backup._server.stop(None)  # hard kill, streams die mid-flight
        t.join(timeout=120)
        assert not t.is_alive(), "pusher wedged after the backup died"
        assert not errors, errors
        assert primary.core.current_iteration == iterations
        after = _counters()
        assert (after.get("ps.apply.sharded_fallback", 0)
                > before.get("ps.apply.sharded_fallback", 0)), \
            "the kill never surfaced a sharded fallback"
        # zero drift: the degraded closes applied the same arithmetic
        expected, got = snapshot(base), snapshot(primary)
        for name in expected:
            assert np.array_equal(expected[name], got[name]), name
    finally:
        primary.stop(0)
        backup.stop(0)
        base.stop(0)


def test_sink_refuses_version_skew_and_empty_streams(tmp_path, arena_env):
    """Backup-side refusal paths answer in-band (error chunk / failed
    ack), never raise through the RPC plumbing."""
    backup, _bport = make_ps(tmp_path, "ref-bk",
                             optimizer="sharded_momentum")
    try:
        sink = backup.service.sharded_sink
        out = list(sink.apply_slices(iter([])))
        assert out and out[-1].error and out[-1].last
        ack = sink.install_slices(iter([]))
        assert not ack.success
        # a version the replica does not hold: refused before any apply
        chunk = rmsg.ShardedSliceChunk(plan_epoch=0, epoch=0, iteration=9,
                                       base_version=7, new_version=8,
                                       kind=rmsg.SLICE_SUMS, last=True,
                                       replicas=2, stripes=1)
        out = list(sink.apply_slices(iter([chunk])))
        assert out and out[-1].error
        assert "version" in out[-1].error or "empty" in out[-1].error
        # install with no pending apply: failed ack
        ack = sink.install_slices(iter([rmsg.ShardedSliceChunk(
            plan_epoch=0, epoch=0, iteration=9, base_version=7,
            new_version=8, kind=rmsg.SLICE_PARAMS, last=True,
            replicas=2, stripes=1)]))
        assert not ack.success and "pending" in ack.message
    finally:
        backup.stop(0)


# ---------------------------------------------------------- lockcheck

@pytest.mark.lockcheck
def test_lockcheck_sharded_close_hammer(tmp_path, arena_env):
    """Concurrent pushes through sharded closes + garbage sink streams
    + obs snapshots, all with PSDT_LOCK_CHECK=1: any ordering violation
    in the ShardedUpdater/ShardedUpdateSink/core chains raises
    LockOrderError instead of deadlocking."""
    backup, bport = make_ps(tmp_path, "hammer-bk",
                            optimizer="sharded_momentum")
    primary, _ = make_ps(tmp_path, "hammer-pr", total_workers=4,
                         optimizer="sharded_momentum",
                         backup_address=f"127.0.0.1:{bport}",
                         replication="sync", sharded_update="1")
    errors: list[BaseException] = []
    try:
        assert primary.sharded_updater is not None
        store = rand_store(n=8)
        primary.core.initialize_parameters(store)
        stop = threading.Event()

        def pusher(wid):
            try:
                rng = np.random.default_rng(wid)
                for it in range(1, 9):
                    grads = {k: rng.standard_normal(SIZE).astype(np.float32)
                             for k in store}
                    primary.core.receive_gradients(wid, it, grads)
            except BaseException as exc:  # noqa: BLE001 — asserted below
                errors.append(exc)

        def churner():
            try:
                sink = backup.service.sharded_sink
                while not stop.is_set():
                    list(sink.apply_slices(iter([])))
                    sink.install_slices(iter([]))
                    obs_stats.REGISTRY.snapshot()
                    time.sleep(0.001)
            except BaseException as exc:  # noqa: BLE001 — asserted below
                errors.append(exc)

        threads = [threading.Thread(target=pusher, args=(wid,), daemon=True,
                                    name=f"shard-hammer-{wid}")
                   for wid in range(4)]
        churn = threading.Thread(target=churner, daemon=True,
                                 name="shard-hammer-churn")
        for t in threads:
            t.start()
        churn.start()
        for t in threads:
            t.join(timeout=180)
            assert not t.is_alive()
        stop.set()
        churn.join(timeout=10)
        assert not errors, errors
        assert primary.core.current_iteration == 8
        # the sharded path genuinely ran under the hammer
        pp, bp = snapshot(primary), snapshot(backup)
        for name in pp:
            assert np.array_equal(pp[name], bp[name]), name
    finally:
        primary.stop(0)
        backup.stop(0)


# -------------------------------------- sub-chunked stage programs

@pytest.mark.parametrize("rule", ["momentum", "adam", "adamw", "lion"])
def test_stage_chunk_bit_identical(rule, monkeypatch, rng):
    """ISSUE 18 satellite (ISSUE 15 leftover): PSDT_DEVICE_STAGE_CHUNK
    splits every whole-stripe stage program into per-range programs over
    the SAME pure range kernels the sharded exchange uses — params and
    slot slabs stay bit-identical to the unchunked close, and the
    chunked run really took the range path (call-counted)."""
    if not device_apply.available():
        pytest.skip("no jax backend/device for the arena close")
    monkeypatch.setenv("PSDT_ARENA", "1")
    shapes = {f"t{i}": (4, 13) for i in range(6)}
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    grads_by_iter = [{k: rng.standard_normal(s).astype(np.float32)
                      for k, s in shapes.items()} for _ in range(3)]

    def closes(chunk):
        if chunk:
            monkeypatch.setenv(device_apply.ENV_STAGE_CHUNK, str(chunk))
        else:
            monkeypatch.delenv(device_apply.ENV_STAGE_CHUNK,
                               raising=False)
        core = ParameterServerCore(
            total_workers=1, stripes=2,
            optimizer=ShardedDeviceOptimizer(rule, 0.02))
        core.initialize_parameters(params)
        for it, grads in enumerate(grads_by_iter, start=1):
            r = core.receive_gradients(0, it, {k: g.copy()
                                               for k, g in grads.items()})
            assert r.aggregation_complete, r.message
        store = {k: np.array(v, np.float32)
                 for k, v in core.get_parameters().items()}
        slots = core._optimizer.state_dict()
        return store, slots

    calls = {"n": 0}
    real = ShardedDeviceOptimizer.apply_arena_range

    def counting(self, *a, **kw):
        calls["n"] += 1
        return real(self, *a, **kw)

    whole_store, whole_slots = closes(0)
    monkeypatch.setattr(ShardedDeviceOptimizer, "apply_arena_range",
                        counting)
    chunk_store, chunk_slots = closes(17)  # mid-tensor range boundaries
    assert calls["n"] >= 6, "chunked close never took the range path"
    assert set(whole_store) == set(chunk_store)
    for name in whole_store:
        assert np.array_equal(whole_store[name], chunk_store[name]), name

    def flat(d, prefix=""):
        out = {}
        for k, v in d.items():
            if isinstance(v, dict):
                out.update(flat(v, f"{prefix}{k}/"))
            elif not np.isscalar(v):
                out[f"{prefix}{k}"] = np.asarray(v, np.float32)
        return out

    ws, cs = flat(whole_slots), flat(chunk_slots)
    assert set(ws) == set(cs)
    for name in ws:
        assert np.array_equal(ws[name], cs[name]), name


# ------------------------------------------------------------- rollup

def test_sharded_metrics_surface_in_rollup():
    from parameter_server_distributed_tpu.obs.export import (render_rollup,
                                                             worker_rollup)

    snap = {"counters": {"ps.apply.sharded": 12,
                         "ps.apply.sharded_fallback": 2,
                         "ps.replica.sharded_bytes": 65536,
                         "ps.replica.sharded_applies": 24},
            "gauges": {"ps.replica.idle_accelerator": 1},
            "histograms": {}, "t": 0.0}
    rolled = worker_rollup(snap)
    replica = rolled["ps"]["replica"]
    assert replica["sharded_closes"] == 12
    assert replica["sharded_fallbacks"] == 2
    assert replica["sharded_bytes"] == 65536
    assert replica["sharded_applies"] == 24
    assert replica["idle_accelerator"] is True
    text = render_rollup({"per_worker": {0: rolled}, "cluster": {}})
    assert "12 sharded closes" in text
    assert "2 sharded fallbacks" in text
    assert "idle accelerator" in text
