"""Versioned delta serving + live weight publication (delta/, ISSUE 10).

The contract everything hangs on: chain-applied deltas are BIT-IDENTICAL
to a full pull at the same wire dtype, across dtypes, chunk budgets, and
stripe counts (the byte-identity oracle).  Around it: the depth-budget
and restore/reset fallback rows, serve_version monotonicity across
restore (a reused version id would silently serve a wrong delta base),
the client downgrade matrix (UNIMPLEMENTED / checksum mismatch =>
permanent per-connection full serve, zero failed steps), the
SubscribeWeights follower + DecodeServer hot swap acceptance, the
lockcheck-marked concurrent subscribe/apply/close hammer, and the obs
surfaces (rollup line, pst-trace events).
"""

import os
import tempfile
import threading
import time

import numpy as np
import pytest

from parameter_server_distributed_tpu.checkpoint.manager import (
    CheckpointManager)
from parameter_server_distributed_tpu.config import ParameterServerConfig
from parameter_server_distributed_tpu.core.ps_core import ParameterServerCore
from parameter_server_distributed_tpu.delta import messages as dmsg
from parameter_server_distributed_tpu.delta.chain import (DeltaChain,
                                                          store_crc)
from parameter_server_distributed_tpu.delta.client import (DeltaBaseMismatch,
                                                           DeltaPullState,
                                                           apply_frames)
from parameter_server_distributed_tpu.obs import stats as obs_stats
from parameter_server_distributed_tpu.rpc import messages as m
from parameter_server_distributed_tpu.server.ps_service import (
    ParameterServer, ParameterServerService)


def make_core(total_workers=1, lr=0.001, **kw):
    from parameter_server_distributed_tpu.core.optimizer import SGD

    return ParameterServerCore(total_workers=total_workers,
                               optimizer=SGD(lr), **kw)


def make_service(core, tmp=None):
    return ParameterServerService(core, CheckpointManager(
        core, directory=tmp or tempfile.mkdtemp(prefix="psdt-deltatest-"),
        checkpoint_interval=10**9, check_period_s=3600.0))


def rand_store(rng, shapes):
    return {name: rng.standard_normal(shape).astype(np.float32)
            for name, shape in shapes.items()}


def decode_full_pull(service, wire_dtype, iteration=0):
    """The full-serve oracle: what a plain pull at ``wire_dtype`` decodes
    to, through the ordinary encode-once path."""
    out = {}
    for chunk in service._parameter_chunks(iteration, wire_dtype):
        decoded = m.ParameterUpdate.decode(chunk.encode())
        assert decoded.ready
        out.update({t.name: t.to_array() for t in decoded.parameters})
    return out


def delta_round(service, state, wire_dtype, iteration=0):
    """One client-side PullParametersDelta round against the in-process
    service (frames re-decoded from their wire bytes, like gRPC would)."""
    req = dmsg.DeltaPullRequest(worker_id=0, iteration=iteration,
                                wire_dtype=wire_dtype,
                                held_version=max(state.version, 0))
    frames = [dmsg.DeltaFrame.decode(f.encode())
              for f in service.PullParametersDelta(req, None)]
    return apply_frames(iter(frames), state)


def delta_counters():
    snap = obs_stats.REGISTRY.snapshot()["counters"]
    return (snap.get("ps.serve.delta_hit", 0),
            snap.get("ps.serve.delta_miss", 0),
            snap.get("ps.serve.delta_bytes", 0))


# --------------------------------------------------------- byte identity


@pytest.mark.parametrize("dtype_name,wire_dtype", [
    ("bf16", m.WIRE_BF16),
    ("f32", m.WIRE_F32),
    ("raw", m.WIRE_RAW_F32),
])
@pytest.mark.parametrize("chunk_bytes", [1 << 20, 96])
@pytest.mark.parametrize("stripes", [1, 3])
def test_chain_applied_deltas_bit_identical_to_full_pull(
        monkeypatch, dtype_name, wire_dtype, chunk_bytes, stripes):
    """THE oracle: across wire dtypes x chunk budgets x stripe counts,
    a receiver advancing version by version through delta chains holds
    exactly the bytes a fresh full pull at the same dtype would."""
    monkeypatch.setenv("PSDT_DELTA_DTYPE", dtype_name)
    monkeypatch.setenv("PSDT_STREAM_CHUNK_BYTES", str(chunk_bytes))
    monkeypatch.setenv("PSDT_STRIPES", str(stripes))
    rng = np.random.default_rng(7)
    core = make_core()
    service = make_service(core)
    core.initialize_parameters(rand_store(
        rng, {"w": (512,), "b": (33,), "deep/k": (4, 64)}))
    state = DeltaPullState()
    first = delta_round(service, state, wire_dtype)
    assert not first.served_delta and first.store is not None
    served_any_delta = False
    for it in range(1, 5):
        grads = {k: rng.standard_normal(v.shape).astype(np.float32) * 1e-3
                 for k, v in core.get_parameters().items()}
        core.receive_gradients(0, it, grads)
        result = delta_round(service, state, wire_dtype, iteration=it)
        served_any_delta = served_any_delta or result.served_delta
        oracle = decode_full_pull(service, wire_dtype, iteration=it)
        assert set(result.store) == set(oracle)
        for name in oracle:
            np.testing.assert_array_equal(
                result.store[name].reshape(-1),
                oracle[name].reshape(-1),
                err_msg=f"{name} diverged from the full pull "
                        f"({dtype_name}, chunk={chunk_bytes}, "
                        f"stripes={stripes})")
    assert served_any_delta, "no round was ever delta-served"


def test_delta_bitwise_semantics_negzero_and_nan(monkeypatch):
    """The diff is BITWISE in wire space: 0.0 -> -0.0 ships (a float
    compare would miss it) and NaNs patch deterministically."""
    monkeypatch.setenv("PSDT_DELTA_DTYPE", "f32")
    core = make_core()
    service = make_service(core)
    core.initialize_parameters({"w": np.zeros(8, np.float32)})
    state = DeltaPullState()
    delta_round(service, state, m.WIRE_F32)  # arms the lazy chain
    # warm-up: the first post-arm version seeds the retained image (no
    # pair yet), and the round re-bases the receiver onto it
    core.initialize_parameters({"w": np.zeros(8, np.float32)})
    delta_round(service, state, m.WIRE_F32)
    tricky = np.zeros(8, np.float32)
    tricky[1] = np.float32(-0.0)
    tricky[2] = np.nan
    # the next initialize bumps the version by exactly one, so the sink
    # builds a (v, v+1) pair over the controlled value change
    core.initialize_parameters({"w": tricky})
    result = delta_round(service, state, m.WIRE_F32, iteration=1)
    assert result.served_delta
    oracle = decode_full_pull(service, m.WIRE_F32, iteration=1)
    got, want = result.store["w"], oracle["w"]
    assert got.tobytes() == want.tobytes()  # -0.0 and NaN, bit for bit
    assert np.signbit(got[1])  # the 0.0 -> -0.0 flip actually shipped
    assert np.isnan(got[2])


@pytest.mark.parametrize("indices,values", [
    # non-ascending indices whose max is out of range (idx[-1] in range)
    (np.array([12, 3], "<u4").tobytes(), np.zeros(2, "<f4").tobytes()),
    # truncated values buffer: not a multiple of the wire itemsize
    (np.array([1], "<u4").tobytes(), b"\x00\x01\x02"),
    # truncated index buffer: not a multiple of 4
    (b"\x00\x01\x02", np.zeros(1, "<f4").tobytes()),
])
def test_malformed_delta_entries_raise_base_mismatch(indices, values):
    """Wire-facing hardening: a buggy/version-skewed server's malformed
    entry must ride the SAME downgrade path as a drifted base (the
    never-failed-step / never-crashed-follower contract) — never a raw
    numpy IndexError/ValueError escaping into the caller's step."""
    state = DeltaPullState()
    state.note_full({"w": np.zeros(8, np.float32)}, 1)
    frame = dmsg.DeltaFrame(
        from_version=1, to_version=2, delta=True, last=True,
        wire_dtype=m.WIRE_F32, crc=0,
        entries=[dmsg.DeltaEntry(name="w", indices=indices,
                                 values=values, dense=False)])
    with pytest.raises(DeltaBaseMismatch):
        apply_frames(iter([frame]), state)


# ------------------------------------------------------- fallback matrix


def test_depth_budget_fallback_and_within_depth_hit(monkeypatch):
    monkeypatch.setenv("PSDT_DELTA_DEPTH", "2")
    rng = np.random.default_rng(3)
    core = make_core()
    service = make_service(core)
    core.initialize_parameters({"w": rng.standard_normal(256)
                                .astype(np.float32)})
    state = DeltaPullState()
    delta_round(service, state, m.WIRE_BF16)
    held_at_base = state.version
    for it in range(1, 4):  # 3 applies > depth 2
        core.receive_gradients(
            0, it, {"w": rng.standard_normal(256).astype(np.float32)})
    # 3 versions behind with depth 2: full serve
    h0, m0, _ = delta_counters()
    behind = DeltaPullState()
    behind.base = {k: v.copy() for k, v in state.base.items()}
    behind.version = held_at_base
    result = delta_round(service, behind, m.WIRE_BF16, iteration=3)
    h1, m1, _ = delta_counters()
    assert not result.served_delta and m1 - m0 == 1 and h1 - h0 == 0
    # the full serve re-based it; one more apply => within depth => delta
    core.receive_gradients(
        0, 4, {"w": rng.standard_normal(256).astype(np.float32) * 1e-3})
    result = delta_round(service, behind, m.WIRE_BF16, iteration=4)
    h2, m2, _ = delta_counters()
    assert result.served_delta and h2 - h1 == 1 and m2 - m1 == 0
    np.testing.assert_array_equal(
        result.store["w"], decode_full_pull(service, m.WIRE_BF16)["w"])


def test_restore_resets_chain_and_falls_back_full(tmp_path):
    """A checkpoint restore is a new world: the chain resets, the next
    serve is full (never a stale pair patching toward the old store),
    and the receiver re-bases correctly."""
    rng = np.random.default_rng(5)
    core = make_core()
    manager = CheckpointManager(core, directory=str(tmp_path),
                                checkpoint_interval=10**9,
                                check_period_s=3600.0)
    service = ParameterServerService(core, manager)
    core.initialize_parameters({"w": rng.standard_normal(128)
                                .astype(np.float32)})
    manager.save(epoch=1)
    state = DeltaPullState()
    delta_round(service, state, m.WIRE_BF16)  # arms the lazy chain
    # warm-up apply seeds the retained image; the round re-bases
    core.receive_gradients(0, 1, {"w": rng.standard_normal(128)
                                  .astype(np.float32) * 1e-3})
    delta_round(service, state, m.WIRE_BF16, iteration=1)
    core.receive_gradients(0, 2, {"w": rng.standard_normal(128)
                                  .astype(np.float32) * 1e-3})
    result = delta_round(service, state, m.WIRE_BF16, iteration=2)
    assert result.served_delta
    # restore the older checkpoint: chain must reset
    manager.load(manager.latest())
    assert service.delta_chain.pairs_between(state.version,
                                             core.serve_version()) is None
    result = delta_round(service, state, m.WIRE_BF16, iteration=3)
    assert not result.served_delta  # full re-base, not a stale delta
    np.testing.assert_array_equal(
        result.store["w"], decode_full_pull(service, m.WIRE_BF16)["w"])


def test_dtype_mismatch_serves_full(monkeypatch):
    """A chain built for bf16 must not patch an f32 receiver: the wire
    bytes differ even for identical values."""
    monkeypatch.setenv("PSDT_DELTA_DTYPE", "bf16")
    rng = np.random.default_rng(11)
    core = make_core()
    service = make_service(core)
    core.initialize_parameters({"w": rng.standard_normal(64)
                                .astype(np.float32)})
    state = DeltaPullState()
    delta_round(service, state, m.WIRE_F32)
    core.receive_gradients(0, 1, {"w": rng.standard_normal(64)
                                  .astype(np.float32) * 1e-3})
    result = delta_round(service, state, m.WIRE_F32, iteration=1)
    assert not result.served_delta
    np.testing.assert_array_equal(
        result.store["w"], decode_full_pull(service, m.WIRE_F32)["w"])


def test_depth_zero_disables_subsystem(monkeypatch):
    monkeypatch.setenv("PSDT_DELTA_DEPTH", "0")
    core = make_core()
    service = make_service(core)
    assert service.delta_chain is None
    core.initialize_parameters({"w": np.ones(8, np.float32)})
    state = DeltaPullState()
    result = delta_round(service, state, m.WIRE_BF16)
    assert not result.served_delta and result.store is not None
    # and the client side refuses to even try
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient
    client = PSClient.__new__(PSClient)
    client.chunk_bytes = 1 << 20
    client._delta_ok = None
    assert not client._delta()


# -------------------------------------------- serve_version monotonicity


def test_restore_never_reuses_a_served_version(tmp_path):
    """Satellite regression: restoring an OLDER checkpoint must not
    rewind the version counter — a delta receiver holding version v
    would silently patch a wrong base if v were re-served with
    different values."""
    rng = np.random.default_rng(1)
    core = make_core()
    manager = CheckpointManager(core, directory=str(tmp_path),
                                checkpoint_interval=10**9,
                                check_period_s=3600.0)
    core.initialize_parameters({"w": rng.standard_normal(32)
                                .astype(np.float32)})
    manager.save(epoch=1)
    for it in range(1, 6):
        core.receive_gradients(0, it, {"w": rng.standard_normal(32)
                                       .astype(np.float32)})
    served_max = core.serve_version()
    manager.load(manager.latest())  # back to the epoch-1 params
    assert core.serve_version() > served_max


def test_version_monotonic_across_processes_via_meta_sidecar(tmp_path):
    """The checkpoint meta sidecar carries the save-time counter, so a
    FRESH process restoring the file resumes numbering past everything
    the saving process served; a reference checkpoint (no sidecar)
    still restores."""
    rng = np.random.default_rng(2)
    core = make_core()
    manager = CheckpointManager(core, directory=str(tmp_path),
                                checkpoint_interval=10**9,
                                check_period_s=3600.0)
    core.initialize_parameters({"w": rng.standard_normal(16)
                                .astype(np.float32)})
    for it in range(1, 4):
        core.receive_gradients(0, it, {"w": rng.standard_normal(16)
                                       .astype(np.float32)})
    saved_at = core.serve_version()
    manager.save(epoch=1)
    # "new process": a fresh core restoring the same directory
    core2 = make_core()
    manager2 = CheckpointManager(core2, directory=str(tmp_path),
                                 checkpoint_interval=10**9,
                                 check_period_s=3600.0)
    manager2.load(manager2.latest())
    assert core2.serve_version() > saved_at
    # corrupt OPTIONAL sidecar (wrong-typed value): best-effort by
    # contract — the valid .ckpt must still restore
    for path in os.listdir(tmp_path):
        if path.endswith(".meta.json"):
            with open(os.path.join(tmp_path, path), "w",
                      encoding="utf-8") as f:
                f.write('{"params_version": null}')
    core25 = make_core()
    manager25 = CheckpointManager(core25, directory=str(tmp_path),
                                  checkpoint_interval=10**9,
                                  check_period_s=3600.0)
    manager25.load(manager25.latest())
    assert core25.get_parameters()
    # reference-written checkpoint: sidecar absent => still restores
    for path in os.listdir(tmp_path):
        if path.endswith(".meta.json"):
            os.remove(os.path.join(tmp_path, path))
    core3 = make_core()
    manager3 = CheckpointManager(core3, directory=str(tmp_path),
                                 checkpoint_interval=10**9,
                                 check_period_s=3600.0)
    manager3.load(manager3.latest())
    assert core3.get_parameters()


# ------------------------------------------------------ client downgrade


def test_client_downgrades_against_unary_only_server(tmp_path):
    """A reference PS (no delta methods bound) answers UNIMPLEMENTED:
    delta_pull returns None ONCE, latches, and the plain path serves —
    zero failed steps."""
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient
    from parameter_server_distributed_tpu.rpc.service import (bind_service,
                                                              make_server)

    core = make_core()
    core.initialize_parameters({"w": np.array([1.0, 2.0], np.float32)})
    service = make_service(core, tmp=str(tmp_path))
    server = make_server()
    bind_service(server, m.PARAMETER_SERVER_SERVICE,
                 m.PARAMETER_SERVER_METHODS, service)  # unary only
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        with PSClient(f"127.0.0.1:{port}") as client:
            assert client.delta_pull(m.PullRequest(
                worker_id=0, iteration=0,
                wire_dtype=m.WIRE_BF16), timeout=10) is None
            assert client._delta_ok is False
            assert client.delta_push_pull(0, 1, list, timeout=10) is None
            pulled = client.pull_parameters(
                m.PullRequest(worker_id=0, iteration=0))
            np.testing.assert_allclose(pulled.parameters[0].to_array(),
                                       [1.0, 2.0])
    finally:
        server.stop(0)


def test_checksum_mismatch_downgrades_and_recovers(tmp_path):
    """A poisoned base (receiver-side drift) fails the post-apply
    checksum: the connection downgrades PERMANENTLY and the next pull
    serves full — the training step never fails."""
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient

    rng = np.random.default_rng(9)
    server = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=1,
        checkpoint_interval=100, checkpoint_dir=str(tmp_path),
        learning_rate=0.001, autosave_period_s=600.0))
    port = server.start()
    server.core.initialize_parameters(
        {"w": rng.standard_normal(512).astype(np.float32)})
    try:
        with PSClient(f"127.0.0.1:{port}") as client:
            r = client.delta_pull(m.PullRequest(
                worker_id=0, iteration=0, wire_dtype=m.WIRE_BF16),
                timeout=10)
            assert r is not None and r.store is not None
            # warm-up: the first post-arm apply seeds the retained image
            # and this pull re-bases, so the NEXT pull is delta-served
            server.core.receive_gradients(
                0, 1, {"w": rng.standard_normal(512)
                       .astype(np.float32) * 1e-3})
            r = client.delta_pull(m.PullRequest(
                worker_id=0, iteration=1, wire_dtype=m.WIRE_BF16),
                timeout=10)
            assert r is not None
            # poison the cached base behind the client's back
            client._delta_state.base["w"][0] += 1.0
            server.core.receive_gradients(
                0, 2, {"w": rng.standard_normal(512)
                       .astype(np.float32) * 1e-3})
            assert client.delta_pull(m.PullRequest(
                worker_id=0, iteration=2, wire_dtype=m.WIRE_BF16),
                timeout=10) is None
            assert client._delta_ok is False
            # the plain protocol still serves, bit-correct
            pulled = client.pull_parameters(m.PullRequest(
                worker_id=0, iteration=2, wire_dtype=m.WIRE_BF16))
            assert pulled.parameters
    finally:
        server.stop()


def test_fused_delta_round_e2e_and_cache_one_repack(tmp_path, monkeypatch):
    """Loopback fused rounds: PushPullDeltaStream folds + barriers like
    PushPullStream, serves O(changed bytes), and the encoded delta-frame
    cache repacks each version pair ONCE for the whole fan-out."""
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient

    monkeypatch.setenv("PSDT_SHM", "0")  # shm would bypass the delta RPC
    rng = np.random.default_rng(17)
    n = 3
    server = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=n,
        checkpoint_interval=100, checkpoint_dir=str(tmp_path),
        learning_rate=0.001, autosave_period_s=600.0))
    port = server.start()
    w0 = rng.standard_normal(4096).astype(np.float32)
    server.core.initialize_parameters({"w": w0})
    clients = [PSClient(f"127.0.0.1:{port}") for _ in range(n)]
    try:
        def round_once(it):
            grads = rng.standard_normal(4096).astype(np.float32) * 1e-3
            results = [None] * n

            def run(wid):
                results[wid] = clients[wid].delta_push_pull(
                    wid, it, lambda: [m.Tensor.from_array("w", grads)],
                    pull_wire_dtype=m.WIRE_BF16, timeout=30)

            threads = [threading.Thread(target=run, args=(wid,))
                       for wid in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert all(not t.is_alive() for t in threads)
            return results

        round_once(1)  # arms the lazy chain, establishes every base
        round_once(2)  # first post-arm apply seeds the image; re-bases
        repacks_before = len(server.service._delta_cache._frames)
        results = round_once(3)
        for r in results:
            assert r is not None and r.push.success
            assert r.served_delta, "steady-state round was not delta-served"
        # the fan-out crossed ONE new version pair: one repack, n replays
        assert len(server.service._delta_cache._frames) \
            == repacks_before + 1
        # bit-identity against the live store's bf16 decode
        oracle = decode_full_pull(server.service, m.WIRE_BF16)
        for r in results:
            np.testing.assert_array_equal(r.store["w"], oracle["w"])
    finally:
        for c in clients:
            c.close()
        server.stop()


def test_delta_training_run_matches_full_serve_bit_for_bit(tmp_path,
                                                           monkeypatch):
    """Acceptance flavor: N iterations of fused training with delta
    serving land on EXACTLY the params of the same run with deltas
    disabled (both at bf16 pull) — the wire protocol is invisible to
    the training trajectory — and the delta run actually hit the chain."""
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient

    monkeypatch.setenv("PSDT_SHM", "0")

    def run(depth):
        monkeypatch.setenv("PSDT_DELTA_DEPTH", str(depth))
        rng = np.random.default_rng(23)
        server = ParameterServer(ParameterServerConfig(
            bind_address="127.0.0.1", port=0, total_workers=1,
            checkpoint_interval=100,
            checkpoint_dir=str(tmp_path / f"d{depth}"),
            learning_rate=0.05, autosave_period_s=600.0))
        port = server.start()
        server.core.initialize_parameters(
            {"w": np.linspace(-1, 1, 2048).astype(np.float32)})
        deltas = 0
        with PSClient(f"127.0.0.1:{port}") as client:
            for it in range(1, 7):
                grads = rng.standard_normal(2048).astype(np.float32)
                r = client.delta_push_pull(
                    0, it, lambda: [m.Tensor.from_array("w", grads)],
                    pull_wire_dtype=m.WIRE_BF16, timeout=30)
                if r is None:
                    push, store = client.push_pull(
                        0, it, [m.Tensor.from_array("w", grads)],
                        pull_wire_dtype=m.WIRE_BF16)
                    assert push.success
                else:
                    assert r.push.success
                    deltas += int(r.served_delta)
        final = np.asarray(server.core.get_parameters()["w"])
        server.stop()
        return final, deltas

    with_delta, hits = run(depth=4)
    without, zero_hits = run(depth=0)
    assert hits >= 4 and zero_hits == 0
    np.testing.assert_array_equal(with_delta, without)


# --------------------------------------------------------- subscription


class _StubContext:
    def __init__(self):
        self._active = True

    def is_active(self):
        return self._active

    def cancel(self):
        self._active = False


def test_subscribe_weights_streams_full_then_deltas(monkeypatch):
    monkeypatch.setenv("PSDT_SUBSCRIBE_POLL_S", "0.05")
    rng = np.random.default_rng(13)
    core = make_core()
    service = make_service(core)
    core.initialize_parameters({"w": rng.standard_normal(256)
                                .astype(np.float32)})
    ctx = _StubContext()
    stream = service.SubscribeWeights(
        dmsg.SubscribeRequest(subscriber_id=1, held_version=0,
                              wire_dtype=m.WIRE_BF16), ctx)
    state = DeltaPullState()
    versions = []

    def consume_one_version():
        batch = []
        for frame in stream:
            batch.append(dmsg.DeltaFrame.decode(frame.encode()))
            if batch[-1].last:
                break
        apply_frames(iter(batch), state)
        versions.append(state.version)

    consume_one_version()  # the establishing full serve
    assert versions[-1] == core.serve_version()
    for it in range(1, 4):
        core.receive_gradients(0, it, {"w": rng.standard_normal(256)
                                       .astype(np.float32) * 1e-3})
        consume_one_version()
        assert versions[-1] == core.serve_version()
        oracle = decode_full_pull(service, m.WIRE_BF16)
        np.testing.assert_array_equal(state.base["w"], oracle["w"])
    ctx.cancel()
    assert len(versions) == 4


def test_follower_backoff_decorrelated_jitter_bounds():
    """ISSUE 14 satellite: the reconnect backoff is decorrelated jitter
    with PINNED bounds — every sleep in [base, cap=8*base] and never
    above 3x the previous sleep — so a fleet of followers losing one
    restarted PS re-spreads instead of thundering-herding it."""
    from parameter_server_distributed_tpu.delta.subscriber import (
        WeightFollower)
    base = 0.5
    follower = WeightFollower("127.0.0.1:1", subscriber_id=3,
                              reconnect_backoff_s=base)  # never started
    cap = base * 8.0
    prev = base
    sleeps = [follower._next_backoff() for _ in range(64)]
    for sleep in sleeps:
        assert base <= sleep <= cap + 1e-9
        assert sleep <= max(base, prev * 3.0) + 1e-9
        prev = sleep
    # the walk actually moves (a constant schedule is the herd)
    assert len({round(s, 6) for s in sleeps}) > 10
    # different subscriber ids draw DIFFERENT schedules...
    other = WeightFollower("127.0.0.1:1", subscriber_id=4,
                           reconnect_backoff_s=base)
    assert [other._next_backoff() for _ in range(8)] != sleeps[:8]
    # ...while the same id reproduces (debuggability)
    replay = WeightFollower("127.0.0.1:1", subscriber_id=3,
                            reconnect_backoff_s=base)
    assert [replay._next_backoff() for _ in range(8)] == sleeps[:8]
    # a successful publish resets the walk to the base
    follower._prev_backoff = follower._backoff
    assert follower._next_backoff() <= 3.0 * base


def test_follower_wait_for_update_blocks_and_wakes():
    """wait_for_update parks on the mailbox CV (no busy-poll): a publish
    wakes the waiter with the pending version, and a degrade wakes it
    immediately with None instead of sleeping out the timeout."""
    from parameter_server_distributed_tpu.delta.subscriber import (
        WeightFollower)

    follower = WeightFollower("127.0.0.1:1")  # thread never started
    assert follower.wait_for_update(0.05) is None  # timeout path

    follower._state.base = {"w": np.arange(4, dtype=np.float32)}
    follower._state.version = 7
    t = threading.Timer(0.1, follower._publish)
    t.start()
    t0 = time.monotonic()
    got = follower.wait_for_update(10.0)
    assert got is not None
    store, version = got
    assert version == 7
    np.testing.assert_array_equal(store["w"], follower._state.base["w"])
    assert time.monotonic() - t0 < 5.0  # woke on publish, not timeout

    t = threading.Timer(0.1, follower._degrade, args=("test sever",))
    t.start()
    t0 = time.monotonic()
    assert follower.wait_for_update(10.0) is None
    assert time.monotonic() - t0 < 5.0  # degrade wakes the waiter
    assert follower.degraded


def test_weight_follower_tracks_live_run_and_severing_degrades(tmp_path):
    """Acceptance: a WeightFollower against a live PS receives >= 5
    versions; killing the PS mid-subscription degrades CLEANLY — the
    last-good weights stay available, no crash, bounded reconnects."""
    from parameter_server_distributed_tpu.delta.subscriber import (
        WeightFollower)

    rng = np.random.default_rng(29)
    server = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=1,
        checkpoint_interval=100, checkpoint_dir=str(tmp_path),
        learning_rate=0.001, autosave_period_s=600.0))
    port = server.start()
    server.core.initialize_parameters(
        {"w": rng.standard_normal(1024).astype(np.float32)})
    follower = WeightFollower(f"127.0.0.1:{port}", subscriber_id=3,
                              reconnect_attempts=1,
                              reconnect_backoff_s=0.05).start()
    try:
        last = None
        deadline = time.monotonic() + 30
        versions_seen = 0
        it = 0
        while versions_seen < 6 and time.monotonic() < deadline:
            it += 1
            server.core.receive_gradients(
                0, it, {"w": rng.standard_normal(1024)
                        .astype(np.float32) * 1e-3})
            for _ in range(100):
                fresh = follower.poll()
                if fresh is not None:
                    last = fresh
                    versions_seen += 1
                    break
                time.sleep(0.01)
        assert versions_seen >= 6  # boot full + 5 live versions
        assert not follower.degraded
        store, version = last
        np.testing.assert_array_equal(
            store["w"],
            decode_full_pull(server.service, m.WIRE_BF16)["w"])
        # sever: the PS dies mid-subscription
        server.stop()
        deadline = time.monotonic() + 20
        while not follower.degraded and time.monotonic() < deadline:
            time.sleep(0.05)
        assert follower.degraded
        # last-good weights still held by the consumer; poll never throws
        assert follower.poll() is None or True
        assert store["w"].size == 1024
    finally:
        follower.stop()


def test_follower_unimplemented_degrades_permanently(tmp_path):
    from parameter_server_distributed_tpu.delta.subscriber import (
        WeightFollower)
    from parameter_server_distributed_tpu.rpc.service import (bind_service,
                                                              make_server)

    core = make_core()
    core.initialize_parameters({"w": np.ones(8, np.float32)})
    service = make_service(core, tmp=str(tmp_path))
    server = make_server()
    bind_service(server, m.PARAMETER_SERVER_SERVICE,
                 m.PARAMETER_SERVER_METHODS, service)  # reference shape
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    follower = WeightFollower(f"127.0.0.1:{port}", subscriber_id=4).start()
    try:
        deadline = time.monotonic() + 15
        while not follower.degraded and time.monotonic() < deadline:
            time.sleep(0.05)
        assert follower.degraded
        assert "UNIMPLEMENTED" in follower.degrade_reason
    finally:
        follower.stop()
        server.stop(0)


# ------------------------------------------------- decode-server hot swap


def test_decode_server_hot_swaps_across_live_training(tmp_path):
    """THE publication acceptance: a DecodeServer following a live
    training PS hot-swaps params across >= 5 weight versions while
    token streams stay uninterrupted — tokens emitted before a swap
    stand, every request retires at full length, nothing crashes."""
    import jax.numpy as jnp

    from parameter_server_distributed_tpu.delta.subscriber import (
        WeightFollower)
    from parameter_server_distributed_tpu.models.serving import DecodeServer
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)

    model = Transformer(TransformerConfig(
        vocab=96, d_model=48, n_heads=4, n_layers=2, d_ff=96,
        max_seq=128, dtype=jnp.float32))
    params = {k: np.asarray(v, np.float32)
              for k, v in model.init_params(0).items()}

    server = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=1,
        checkpoint_interval=100, checkpoint_dir=str(tmp_path),
        learning_rate=0.001, autosave_period_s=600.0))
    port = server.start()
    server.core.initialize_parameters(params)
    follower = WeightFollower(f"127.0.0.1:{port}", subscriber_id=9).start()
    srv = DecodeServer(model, model.init_params(0), slots=2, max_len=64)
    rng = np.random.default_rng(31)
    try:
        rid = srv.submit(list(rng.integers(0, 96, 5)), max_new_tokens=24)
        swaps, it = 0, 0
        emitted_before_swap: list[int] = []
        while srv.active and swaps < 5:
            it += 1
            server.core.receive_gradients(
                0, it, {k: rng.standard_normal(v.shape)
                        .astype(np.float32) * 1e-3
                        for k, v in params.items()})
            deadline = time.monotonic() + 10
            fresh = None
            while fresh is None and time.monotonic() < deadline:
                fresh = follower.poll()
                time.sleep(0.005)
            assert fresh is not None, "follower stalled"
            srv.step()  # a decode round between publications
            prefix = list(srv.peek(rid))
            srv.swap_params(fresh[0])  # between rounds: the swap point
            swaps += 1
            srv.step()
            after = list(srv.peek(rid))
            # tokens emitted before the swap are NEVER rewritten
            assert after[:len(prefix)] == prefix
            emitted_before_swap = after
        assert swaps >= 5
        while srv.active:
            srv.step()
        out = srv.result(rid)
        assert len(out) == 24  # retired at full length: stream unbroken
        assert out[:len(emitted_before_swap)] == emitted_before_swap
        assert srv.stats["weight_swaps"] >= 5
    finally:
        follower.stop()
        server.stop()


def test_swap_params_drops_prompt_cache_and_counts(rng=None):
    import jax.numpy as jnp

    from parameter_server_distributed_tpu.models.serving import DecodeServer
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)

    model = Transformer(TransformerConfig(
        vocab=96, d_model=48, n_heads=4, n_layers=2, d_ff=96,
        max_seq=128, dtype=jnp.float32))
    params = model.init_params(0)
    srv = DecodeServer(model, params, slots=2, max_len=64, prompt_cache=2)
    rid = srv.submit([1, 2, 3, 4], max_new_tokens=4)
    srv.run_to_completion()
    assert srv._prefix_tree.nodes  # warmed
    srv.swap_params(model.init_params(1))
    assert not srv._prefix_tree.nodes  # stale prefill state dropped
    assert srv._prefix_tree.bytes == 0
    assert srv.prefix_fingerprint() == b""
    rid2 = srv.submit([1, 2, 3, 4], max_new_tokens=4)
    out = srv.run_to_completion()
    assert len(out[rid2]) == 4
    assert srv.stats["weight_swaps"] == 1
    # name/shape drift (upstream model change mid-publication) raises AT
    # THE SWAP POINT — where serve_main catches it and keeps last-good
    # weights — instead of crashing a later decode round
    good = srv.params
    with pytest.raises(ValueError):
        srv.swap_params({"nope": np.zeros(3, np.float32)})
    assert srv.params is good and srv.stats["weight_swaps"] == 1


# --------------------------------------------------- concurrency hammer


@pytest.mark.lockcheck
def test_lockcheck_concurrent_subscribe_apply_close_hammer(monkeypatch):
    """Applies (chain builds), delta pulls, subscribers opening/closing,
    and chain resets hammer the same service under PSDT_LOCK_CHECK=1:
    any rank inversion between DeltaChain._lock, the cache locks, and
    the core locks is a checked failure, and every served round must be
    bit-correct for SOME version (never a torn mix)."""
    monkeypatch.setenv("PSDT_SUBSCRIBE_POLL_S", "0.02")
    rng = np.random.default_rng(41)
    core = make_core(lr=0.01)
    service = make_service(core)
    core.initialize_parameters({"w": rng.standard_normal(256)
                                .astype(np.float32),
                                "b": rng.standard_normal(17)
                                .astype(np.float32)})
    stop = threading.Event()
    errors: list[BaseException] = []

    def applier():
        it = 0
        g = np.random.default_rng(1)
        while not stop.is_set():
            it += 1
            try:
                core.receive_gradients(
                    0, it, {"w": g.standard_normal(256)
                            .astype(np.float32) * 1e-2,
                            "b": g.standard_normal(17)
                            .astype(np.float32) * 1e-2})
            except BaseException as exc:  # noqa: BLE001 — hammer surface
                errors.append(exc)
                return

    def puller():
        state = DeltaPullState()
        while not stop.is_set():
            try:
                result = delta_round(service, state, m.WIRE_BF16)
                if result.store is not None:
                    crc = store_crc(result.store)
                    assert crc == store_crc(result.store)
            except DeltaBaseMismatch:
                state = DeltaPullState()  # re-base, like the client does
            except BaseException as exc:  # noqa: BLE001 — hammer surface
                errors.append(exc)
                return

    class _StopCtx:
        """Context that goes inactive when the hammer stops, so a parked
        SubscribeWeights generator unwinds instead of waiting forever."""

        def __init__(self):
            self._active = True

        def is_active(self):
            return self._active and not stop.is_set()

        def cancel(self):
            self._active = False

    def subscriber():
        while not stop.is_set():
            ctx = _StopCtx()
            state = DeltaPullState()
            stream = service.SubscribeWeights(
                dmsg.SubscribeRequest(subscriber_id=2, held_version=0,
                                      wire_dtype=m.WIRE_BF16), ctx)
            try:
                batch = []
                seen = 0
                for frame in stream:
                    decoded = dmsg.DeltaFrame.decode(frame.encode())
                    batch.append(decoded)
                    if decoded.last:
                        try:
                            apply_frames(iter(batch), state)
                        except DeltaBaseMismatch:
                            state = DeltaPullState()
                        batch = []
                        seen += 1
                        if seen >= 3:
                            break
            except BaseException as exc:  # noqa: BLE001 — hammer surface
                errors.append(exc)
                return
            finally:
                ctx.cancel()

    def resetter():
        while not stop.is_set():
            time.sleep(0.02)
            service.delta_chain.reset()

    threads = ([threading.Thread(target=applier, daemon=True,
                                 name="hammer-apply")]
               + [threading.Thread(target=puller, daemon=True,
                                   name=f"hammer-pull-{i}")
                  for i in range(2)]
               + [threading.Thread(target=subscriber, daemon=True,
                                   name="hammer-subscribe")]
               + [threading.Thread(target=resetter, daemon=True,
                                   name="hammer-reset")])
    for t in threads:
        t.start()
    time.sleep(2.0)
    stop.set()
    for t in threads:
        t.join(timeout=30)
        assert not t.is_alive(), f"{t.name} wedged"
    assert not errors, errors


# --------------------------------------------------------------- obs


def test_delta_counters_surface_in_rollup():
    from parameter_server_distributed_tpu.obs.export import (render_rollup,
                                                             worker_rollup)

    obs_stats.counter("ps.serve.delta_hit").add(7)
    obs_stats.counter("ps.serve.delta_miss").add(2)
    obs_stats.counter("ps.serve.delta_bytes").add(12345)
    snap = obs_stats.REGISTRY.snapshot()
    rolled = worker_rollup(snap)
    assert rolled["ps"]["delta"]["hits"] >= 7
    text = render_rollup({"cluster": {}, "per_worker": {0: rolled}})
    assert "delta serve" in text


def test_delta_events_render_in_postmortem(tmp_path):
    from parameter_server_distributed_tpu.obs import flight, postmortem

    ring_dir = str(tmp_path / "flight")
    flight.enable(ring_dir, role="ps:delta", records=256)
    try:
        flight.record("serve.delta.build", a=4096, b=7)
        flight.record("serve.delta.hit", iteration=3, a=512, b=1)
        flight.record("serve.delta.miss", iteration=3, a=2, b=7,
                      note="depth/reset")
        flight.record("publish.subscribe", a=0, b=9)
        flight.record("publish.swap", a=7, b=1500)
        flight.record("publish.lag", a=3, b=9)
        flight.record("serve.delta.downgrade", note="checksum")
        flight.record("push.commit", iteration=3, worker=0, a=1, b=1)
        flight.record("barrier.publish", iteration=3, a=1, b=1)
    finally:
        flight.disable()
    rep = postmortem.report(ring_dir, iteration=3)
    tl = rep["timeline"]
    assert tl["delta_serve"]["hits"] == 1
    assert tl["delta_serve"]["misses"] == 1
    assert tl["delta_serve"]["delta_bytes"] == 512
    assert "depth/reset" in tl["delta_serve"]["miss_reasons"]
    pub = rep["narrative"]["publication"]
    assert pub["subscriptions"] == 1 and pub["swaps"] == 1
    assert pub["last_version"] == 7 and pub["max_lag"] == 3
    assert any(d["what"] == "serve.delta.downgrade"
               for d in rep["narrative"]["degrades"])
    text = postmortem.render_report(rep)
    assert "delta serve" in text and "weight publication" in text
