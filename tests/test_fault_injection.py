"""Fault injection: crash, outage, and flaky-network scenarios.

The reference has no fault injection anywhere (SURVEY.md §5); its recovery
machinery (heartbeat eviction — reference src/coordinator_service.cpp:102-107,
retry backoff — src/worker.cpp:129-139, systemd Restart=always units —
terraform/user_data.sh:35-80) is only ever exercised by real outages.  These
tests inject the faults deliberately:

1. worker crash mid-barrier -> eviction shrinks the elastic barrier and the
   survivors' buffered iteration fires (no PS restart, unlike the reference's
   scale scripts which drop in-memory state);
2. transient RPC failures -> query_with_retry's exponential backoff recovers;
3. PS process crash -> restart from checkpoint, workers reconnect and resume
   (the systemd Restart=always story, in-process);
4. coordinator outage -> data plane (train loop against the PS) keeps going,
   heartbeats degrade gracefully.
"""

import threading
import time

import grpc
import numpy as np
import pytest

from parameter_server_distributed_tpu.cli.worker_main import build_worker
from parameter_server_distributed_tpu.config import (CoordinatorConfig,
                                                     ParameterServerConfig,
                                                     WorkerConfig)
from parameter_server_distributed_tpu.server.coordinator_service import Coordinator
from parameter_server_distributed_tpu.server.ps_service import ParameterServer


def make_ps(tmp_path, coordinator=None, port=0):
    kwargs = {}
    if coordinator is not None:
        kwargs["live_workers_fn"] = coordinator.core.live_worker_count
    return ParameterServer(
        ParameterServerConfig(
            bind_address="127.0.0.1", port=port, total_workers=2,
            checkpoint_interval=2, checkpoint_dir=str(tmp_path),
            learning_rate=0.05, autosave_period_s=600.0,
            elastic=coordinator is not None, live_workers_ttl_s=0.0),
        **kwargs)


def make_worker(coord_port, wid, **overrides):
    config = WorkerConfig(
        coordinator_address=f"127.0.0.1:{coord_port}", worker_id=wid,
        address="127.0.0.1", port=50090 + wid, batch_size=16,
        model="mnist_mlp", heartbeat_period_s=600.0, **overrides)
    w = build_worker(config)
    w.initialize()
    return w


def test_worker_crash_mid_barrier_releases_survivor(tmp_path):
    """Worker 1 dies after worker 0 already pushed: the coordinator evicts
    it, the barrier shrinks 2 -> 1, and worker 0's sync poll fires the
    buffered aggregation instead of stranding it for the full timeout."""
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0, ps_address="127.0.0.1", ps_port=1,
        reap_period_s=600.0))
    coord_port = coordinator.start()
    ps = make_ps(tmp_path, coordinator)
    ps_port = ps.start()
    coordinator.core.set_parameter_server_address("127.0.0.1", ps_port)
    w0 = w1 = None
    try:
        w0 = make_worker(coord_port, 0)
        w1 = make_worker(coord_port, 1)
        # both complete a lockstep iteration so the PS holds params
        t0 = threading.Thread(target=w0.run_iteration, args=(0,))
        t1 = threading.Thread(target=w1.run_iteration, args=(0,))
        t0.start(); t1.start(); t0.join(60); t1.join(60)
        assert ps.core.get_parameters()

        # worker 0 pushes for iteration 1; barrier (width 2) incomplete
        _, params = w0.pull_parameters(1)
        batch = next(w0.batches)
        grads, _ = w0.trainer.compute_gradients(params, batch)
        push = w0.push_gradients(1, grads)
        assert not push.aggregation_complete and push.workers_received == 1

        # CRASH: worker 1 dies without pushing; reaper evicts it.  A
        # crash never announces the graceful membership LEAVE that a
        # clean shutdown() sends since ISSUE 13 — silence it so this
        # stays the reap-release path (the leave path is covered in
        # tests/test_elastic.py)
        if w1._membership is not None:
            w1._membership.close()
            w1._membership = None
        w1.shutdown()
        w1 = None
        evicted = coordinator.core.remove_stale_workers(timeout_s=-1)
        assert 1 in evicted
        coordinator.core.register_worker(0, "127.0.0.1", 50090, "h0")

        # survivor's normal barrier poll must release iteration 1
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            resp = w0.check_sync_ready(1)
            if resp.ready:
                break
            time.sleep(0.05)
        assert resp.ready, "buffered iteration never fired after eviction"
        assert resp.total_workers == 1
        assert ps.core.current_iteration == 1

        # and the survivor keeps training alone
        loss = w0.run_iteration(2)
        assert np.isfinite(loss)
    finally:
        for w in (w0, w1):
            if w is not None:
                w.shutdown()
        coordinator.stop()
        ps.stop()


def test_transient_rpc_failures_recovered_by_retry(tmp_path):
    """First two attempts of every data-plane call fail; the reference-shape
    retry loop (5 attempts, exponential backoff — src/worker.cpp:129-139)
    must absorb them with no training-visible effect."""
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0, ps_address="127.0.0.1", ps_port=1,
        reap_period_s=600.0))
    coord_port = coordinator.start()
    ps = make_ps(tmp_path, coordinator)
    ps_port = ps.start()
    coordinator.core.set_parameter_server_address("127.0.0.1", ps_port)
    w = None
    try:
        w = make_worker(coord_port, 0, retry_base_delay_s=0.01)
        w.run_iteration(0)  # bootstrap cleanly

        real_call = w._ps.call
        fail_counts: dict[str, int] = {}

        def flaky_call(method, request, timeout=None):
            n = fail_counts.get(method, 0)
            if n < 2:
                fail_counts[method] = n + 1
                raise grpc.RpcError(f"injected fault #{n + 1} on {method}")
            return real_call(method, request, timeout=timeout)

        w._ps.call = flaky_call
        loss = w.run_iteration(1)
        assert np.isfinite(loss)
        assert ps.core.current_iteration == 1
        # the injection actually hit the pull and fused push→barrier→pull
        # paths (the worker's data plane rides the streaming RPCs —
        # rpc/data_plane.py; the post-bootstrap pull is one pull round —
        # the version-aware delta pull when PSDT_DELTA_DEPTH > 0, the
        # plain stream pull otherwise — and the step's communication is
        # one fused round)
        pull_faults = (fail_counts.get("ServeParametersStream", 0)
                       + fail_counts.get("PullParametersDelta", 0))
        assert pull_faults == 2, fail_counts
        assert fail_counts["PushPullStream"] == 2, fail_counts
    finally:
        if w is not None:
            w.shutdown()
        coordinator.stop()
        ps.stop()


def test_rpc_outage_exhausts_retries_with_clear_error(tmp_path):
    """A hard outage (every attempt fails) surfaces as WorkerError after the
    configured attempts, not a hang or a silent skip."""
    from parameter_server_distributed_tpu.worker.worker import WorkerError

    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0, ps_address="127.0.0.1", ps_port=1,
        reap_period_s=600.0))
    coord_port = coordinator.start()
    ps = make_ps(tmp_path, coordinator)
    ps_port = ps.start()
    coordinator.core.set_parameter_server_address("127.0.0.1", ps_port)
    w = None
    try:
        w = make_worker(coord_port, 0, retry_base_delay_s=0.01,
                        retry_max_attempts=3)
        attempts = []

        def dead_call(method, request, timeout=None):
            attempts.append(method)
            raise grpc.RpcError("injected outage")

        w._ps.call = dead_call
        with pytest.raises(WorkerError, match="after 3 attempts"):
            w.run_iteration(0)
        assert len(attempts) == 3
    finally:
        if w is not None:
            w.shutdown()
        coordinator.stop()
        ps.stop()


def test_ps_crash_restart_restores_from_checkpoint(tmp_path):
    """PS process dies and is replaced (the reference's systemd
    Restart=always story): the new process restores the checkpoint, the
    coordinator hands out the new address, workers reconnect and training
    resumes from the saved state instead of from scratch."""
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0, ps_address="127.0.0.1", ps_port=1,
        reap_period_s=600.0))
    coord_port = coordinator.start()
    ps = make_ps(tmp_path, coordinator)
    ps_port = ps.start()
    coordinator.core.set_parameter_server_address("127.0.0.1", ps_port)
    w = ps2 = None
    try:
        w = make_worker(coord_port, 0)
        for it in range(3):
            w.run_iteration(it)
        saved_path = ps.ckpt.save()
        saved_iteration = ps.core.current_iteration
        saved_params = ps.core.get_parameters()
        assert saved_iteration == 2

        # CRASH the PS
        ps.stop()

        # replacement process: restore checkpoint, re-publish address
        ps2 = make_ps(tmp_path, coordinator)
        ps2_port = ps2.start()
        epoch, iteration = ps2.ckpt.load(saved_path)
        assert iteration == saved_iteration
        coordinator.core.set_parameter_server_address("127.0.0.1", ps2_port)

        restored = ps2.core.get_parameters()
        for name, arr in saved_params.items():
            np.testing.assert_array_equal(restored[name], arr)

        # worker notices the outage, reconnects via the coordinator, resumes
        w.reconnect()
        loss = w.run_iteration(saved_iteration + 1)
        assert np.isfinite(loss)
        assert ps2.core.current_iteration == saved_iteration + 1
    finally:
        if w is not None:
            w.shutdown()
        coordinator.stop()
        if ps2 is not None:
            ps2.stop()


def test_coordinator_outage_does_not_block_training(tmp_path):
    """The coordinator is discovery/membership only: once a worker holds the
    PS address, a coordinator outage degrades heartbeats (None = unreachable)
    but the pull/push/barrier data plane keeps working."""
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0, ps_address="127.0.0.1", ps_port=1,
        reap_period_s=600.0))
    coord_port = coordinator.start()
    # static (non-elastic) barrier of 1: no live-registry dependency
    ps = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=1,
        checkpoint_interval=100, checkpoint_dir=str(tmp_path),
        learning_rate=0.05, autosave_period_s=600.0))
    ps_port = ps.start()
    coordinator.core.set_parameter_server_address("127.0.0.1", ps_port)
    w = None
    try:
        w = make_worker(coord_port, 0)
        w.run_iteration(0)

        coordinator.stop()  # OUTAGE

        assert w.send_heartbeat() is None  # degraded, not crashed
        for it in (1, 2):
            loss = w.run_iteration(it)
        assert np.isfinite(loss)
        assert ps.core.current_iteration == 2
    finally:
        if w is not None:
            w.shutdown()
        ps.stop()


def test_packed_wire_renegotiated_after_ps_replacement(tmp_path):
    """A bf16 worker that negotiated packed pushes against a framework PS
    must re-negotiate when the PS is replaced: if the replacement ignores
    the packed extension (reference behavior), pushes drop back to f32
    instead of silently shipping payloads the new PS cannot see."""
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0, ps_address="127.0.0.1", ps_port=1,
        reap_period_s=600.0))
    coord_port = coordinator.start()
    ps = make_ps(tmp_path, coordinator)
    ps_port = ps.start()
    coordinator.core.set_parameter_server_address("127.0.0.1", ps_port)
    w = ps2 = None
    try:
        w = make_worker(coord_port, 0, wire_dtype="bf16")
        for it in range(2):
            w.run_iteration(it)
        assert w._peer_packed_ok and w._wire_dtype != 0  # negotiated packed
        saved_path = ps.ckpt.save()
        ps.stop()

        # replacement PS that ignores the packed extension (reference-like)
        ps2 = make_ps(tmp_path, coordinator)
        seen_encodings = []
        orig_serve = type(ps2.service).ServeParameters
        orig_recv = type(ps2.service).ReceiveGradients

        def serve_f32_only(request, context):
            request.wire_dtype = 0
            return orig_serve(ps2.service, request, context)

        def recording_recv(request, context):
            seen_encodings.extend(t.packed_dtype for t in request.gradients)
            return orig_recv(ps2.service, request, context)

        def unimplemented_stream(request, context):
            # reference-like PS: no chunk-stream extension either — the
            # worker's PSClient must fall back to the recorded unary RPCs
            context.abort(grpc.StatusCode.UNIMPLEMENTED,
                          "reference PS: no streaming data plane")

        ps2.service.ServeParameters = serve_f32_only
        ps2.service.ReceiveGradients = recording_recv
        ps2.service.PushGradientsStream = unimplemented_stream
        ps2.service.ServeParametersStream = unimplemented_stream
        ps2.service.PushPullStream = unimplemented_stream
        # nor the versioned-delta extension (delta/, ISSUE 10) — without
        # these stubs the delta data plane would serve right past the
        # recording/unimplemented reference stubs above
        ps2.service.PullParametersDelta = unimplemented_stream
        ps2.service.PushPullDeltaStream = unimplemented_stream
        ps2.service.SubscribeWeights = unimplemented_stream
        # a reference PS has no shm negotiation either: without this stub
        # the same-host rings would carry the fused rounds right past the
        # recording/unimplemented gRPC stubs above
        ps2.service.NegotiateShm = unimplemented_stream
        ps2_port = ps2.start()
        ps2.ckpt.load(saved_path)
        coordinator.core.set_parameter_server_address("127.0.0.1", ps2_port)

        w.reconnect()
        loss = w.run_iteration(ps2.core.current_iteration + 1)
        assert np.isfinite(loss)
        # every push at the replacement PS was plain f32
        assert seen_encodings and all(e == 0 for e in seen_encodings)
        assert w._wire_dtype == 0  # downgraded for this connection
    finally:
        if w is not None:
            w.shutdown()
        coordinator.stop()
        if ps2 is not None:
            ps2.stop()


def test_packed_wire_renegotiated_after_same_address_restart(tmp_path):
    """A PS restarted at the SAME address is reached via transparent gRPC
    channel reconnection — the worker never re-runs discovery — so proven
    packed negotiation must be dropped as soon as a pull stops looking
    packed.  Here the restarted PS comes back EMPTY: the worker's next push
    seeds the store and must be full-precision f32, not bf16-quantized."""
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0, ps_address="127.0.0.1", ps_port=1,
        reap_period_s=600.0))
    coord_port = coordinator.start()
    ps = make_ps(tmp_path, coordinator)
    ps_port = ps.start()
    coordinator.core.set_parameter_server_address("127.0.0.1", ps_port)
    w = ps2 = None
    try:
        w = make_worker(coord_port, 0, wire_dtype="bf16")
        for it in range(2):
            w.run_iteration(it)
        assert w._peer_packed_ok and w._wire_dtype != 0  # negotiated packed
        ps.stop()

        # restart EMPTY at the same port; worker keeps its channel
        ps2 = make_ps(tmp_path, coordinator, port=ps_port)
        seen_encodings = []
        orig_recv = type(ps2.service).ReceiveGradients
        orig_stream = type(ps2.service).PushGradientsStream

        def recording_recv(request, context):
            seen_encodings.extend(t.packed_dtype for t in request.gradients)
            return orig_recv(ps2.service, request, context)

        def recording_stream(request_iterator, context):
            def record(chunks):
                for chunk in chunks:
                    seen_encodings.extend(t.packed_dtype
                                          for t in chunk.gradients)
                    yield chunk
            return orig_stream(ps2.service, record(request_iterator), context)

        ps2.service.ReceiveGradients = recording_recv
        ps2.service.PushGradientsStream = recording_stream
        ps2.start()

        # NO w.reconnect(): the stale negotiation must self-heal on pull
        w.run_iteration(3)  # bootstrap iterations return NaN by design
        assert w.last_bootstrap  # the restarted PS was empty and got seeded
        assert seen_encodings and all(e == 0 for e in seen_encodings), (
            f"bootstrap push after PS restart was packed: {seen_encodings}")
        # params seeded at full precision on the new PS
        assert ps2.core.get_parameters()
    finally:
        if w is not None:
            w.shutdown()
        coordinator.stop()
        if ps2 is not None:
            ps2.stop()
