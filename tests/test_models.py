"""Model zoo tests: ResNet and Transformer forward/loss/training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_distributed_tpu.config import MeshConfig
from parameter_server_distributed_tpu.models.mlp import MLP, billion_param_mlp, mnist_mlp
from parameter_server_distributed_tpu.models.resnet import ResNet, resnet18, resnet50
from parameter_server_distributed_tpu.models.transformer import (
    Transformer, TransformerConfig, small_lm, transformer_rule)
from parameter_server_distributed_tpu.parallel.mesh import build_mesh
from parameter_server_distributed_tpu.parallel.train_step import (
    ShardedTrainer, make_optimizer)


def test_mlp_num_params():
    assert mnist_mlp().num_params() == 784 * 256 + 256 + 256 * 10 + 10
    assert billion_param_mlp().num_params() > 1_000_000_000


def test_resnet18_structure():
    model = resnet18()
    # 18 = 1 stem + 2*2*4 convs + 1 head
    conv_names = [n for n in model.param_shapes() if "/conv" in n or n == "stem/conv/w"]
    assert len(conv_names) == 17
    assert model.num_params() > 10_000_000  # ~11M


def test_resnet50_structure():
    model = resnet50()
    assert model.num_params() > 23_000_000  # ~25.5M
    assert model.param_shapes()["head/w"] == (2048, 1000)


def test_vit_forward_and_training(rng):
    """Tiny ViT end to end: patchify shapes, bidirectional attention,
    CLS-pooled classification, and loss decreasing under SGD."""
    from parameter_server_distributed_tpu.models.vit import ViT, ViTConfig

    model = ViT(ViTConfig(image_size=8, patch_size=4, num_classes=4,
                          d_model=32, n_heads=2, n_layers=2, d_ff=64))
    assert model.config.n_patches == 4 and model.config.seq_len == 5
    params = model.init_params(0)
    x = rng.standard_normal((8, 8, 8, 3)).astype(np.float32)
    y = rng.integers(0, 4, 8).astype(np.int32)
    assert model.apply(params, x).shape == (8, 4)
    loss_fn = jax.jit(jax.value_and_grad(model.loss))
    losses = []
    for _ in range(15):
        loss, grads = loss_fn(params, (x, y))
        params = jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses

    # mean pooling is a config switch, not a new model
    import dataclasses as dc
    mean = ViT(dc.replace(model.config, pool="mean"))
    assert mean.apply(params, x).shape == (8, 4)
    with pytest.raises(ValueError, match="pool"):
        ViTConfig(pool="max")
    with pytest.raises(ValueError, match="divide"):
        ViTConfig(image_size=30, patch_size=4)


def test_vit_registry_and_sharded_training(rng):
    """The registry entries build with their data streams, and a ViT
    store shards under the TRANSFORMER rule (the suffix-compatible
    naming contract in models/vit.py's docstring) for mesh training."""
    from parameter_server_distributed_tpu.models.registry import (
        get_model_and_batches)
    from parameter_server_distributed_tpu.models.transformer import (
        transformer_rule)
    from parameter_server_distributed_tpu.models.vit import ViT, ViTConfig

    model, batches = get_model_and_batches("vit_tiny_cifar", 8)
    x, y = next(batches)
    assert x.shape == (8, 32, 32, 3) and model.num_params() > 2e6

    small = ViT(ViTConfig(image_size=8, patch_size=4, num_classes=4,
                          d_model=32, n_heads=2, n_layers=2, d_ff=64))
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    trainer = ShardedTrainer(small.loss, mesh, transformer_rule(mesh),
                             optimizer=make_optimizer("adam", 1e-3))
    state = trainer.init_state(small.init_params(0))
    xb = rng.standard_normal((8, 8, 8, 3)).astype(np.float32)
    yb = rng.integers(0, 4, 8).astype(np.int32)
    losses = []
    for _ in range(6):
        state, metrics = trainer.step(state, (xb, yb))
        loss = metrics["loss"] if isinstance(metrics, dict) else metrics
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0], losses
    # the Megatron rule actually sharded the 2-D weights
    wq = state.params["layer0/attn/wq"]
    assert len(wq.sharding.device_set) > 1


def test_tiny_resnet_forward_and_training():
    model = ResNet(stages=(1, 1), bottleneck=False, num_classes=4, width=8)
    params = model.init_params(0)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 8, 8, 3)).astype(np.float32)
    y = rng.integers(0, 4, 8).astype(np.int32)
    logits = model.apply(params, x)
    assert logits.shape == (8, 4)
    loss_fn = jax.jit(jax.value_and_grad(model.loss))
    losses = []
    for _ in range(12):
        loss, grads = loss_fn(params, (x, y))
        params = jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses


def test_tiny_bottleneck_resnet_forward():
    model = ResNet(stages=(1, 1), bottleneck=True, num_classes=4, width=8)
    params = model.init_params(0)
    x = np.zeros((2, 8, 8, 3), np.float32)
    assert model.apply(params, x).shape == (2, 4)


def test_bf16_resnet_trains_with_f32_inputs():
    """ResNet-50's mixed-precision path: bf16 weights, f32 images —
    regression for a dtype mismatch at the second conv (f32 conv output
    fed to a bf16-weight conv)."""
    model = ResNet(stages=(1, 1), bottleneck=True, num_classes=4, width=8,
                   small_inputs=False, dtype=jnp.bfloat16)
    params = model.init_params(0)
    x = np.random.default_rng(0).standard_normal((2, 16, 16, 3)).astype(np.float32)
    y = np.array([1, 2], np.int32)
    loss, grads = jax.value_and_grad(model.loss)(params, (x, y))
    assert np.isfinite(float(loss))
    assert grads["stem/conv/w"].dtype == jnp.bfloat16
    assert np.isfinite(np.float32(np.asarray(grads["head/w"]))).all()


def test_transformer_shapes_and_loss_at_init():
    model = small_lm(vocab=64, seq=32)
    params = model.init_params(0)
    tokens = np.random.default_rng(0).integers(0, 64, (2, 32)).astype(np.int32)
    logits = model.apply(params, jnp.asarray(tokens))
    assert logits.shape == (2, 32, 64)
    loss = float(model.loss(params, tokens))
    # random init => loss ~= ln(vocab)
    assert abs(loss - np.log(64)) < 0.35, loss


def test_transformer_causality():
    """Changing a future token must not change earlier logits."""
    model = small_lm(vocab=64, seq=16)
    params = model.init_params(0)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 64, (1, 16)).astype(np.int32)
    logits1 = np.asarray(model.apply(params, jnp.asarray(tokens)))
    tokens2 = tokens.copy()
    tokens2[0, -1] = (tokens2[0, -1] + 1) % 64
    logits2 = np.asarray(model.apply(params, jnp.asarray(tokens2)))
    np.testing.assert_allclose(logits1[0, :-1], logits2[0, :-1],
                               rtol=1e-4, atol=1e-5)
    assert not np.allclose(logits1[0, -1], logits2[0, -1])


def test_transformer_learns_repetition():
    model = small_lm(vocab=16, seq=16)
    params = model.init_params(0)
    # highly predictable data: token[t+1] = token[t] + 1 mod 16
    base = np.arange(16, dtype=np.int32) % 16
    tokens = np.stack([np.roll(base, -s) for s in range(8)]).astype(np.int32)
    loss_fn = jax.jit(jax.value_and_grad(model.loss))
    losses = []
    for _ in range(30):
        loss, grads = loss_fn(params, tokens)
        params = jax.tree.map(lambda p, g: p - 0.5 * g, params, grads)
        losses.append(float(loss))
    assert losses[-1] < 0.5, losses[-5:]


def test_transformer_sharded_tp_sp_training():
    """Full sharded training: dp=2 x tensor=2 x seq=2 mesh, Megatron TP rule,
    activation seq sharding; numerics must match the unsharded step."""
    mesh = build_mesh(MeshConfig(data=2, tensor=2, sequence=2))
    config = TransformerConfig(vocab=64, d_model=64, n_heads=4, n_layers=2,
                               d_ff=128, max_seq=32, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 64, (4, 32)).astype(np.int32)

    plain = Transformer(config)
    params = plain.init_params(0)
    base_loss = float(plain.loss(params, jnp.asarray(tokens)))

    sharded_model = Transformer(config, mesh=mesh)
    trainer = ShardedTrainer(sharded_model.loss, mesh, transformer_rule(mesh),
                             make_optimizer("adam", 1e-3))
    state = trainer.init_state(params)
    # TP sharding placed: wq column-sharded over tensor
    wq = state.params["layer0/attn/wq"]
    assert {s.data.shape for s in wq.addressable_shards} == {(64, 32)}
    state, metrics = trainer.step(state, tokens)
    np.testing.assert_allclose(float(metrics["loss"]), base_loss, rtol=2e-4)
    state, metrics2 = trainer.step(state, tokens)
    assert float(metrics2["loss"]) < base_loss  # one adam step helped


def test_transformer_flash_attention_drop_in(rng):
    """The pallas flash kernels are a numerical drop-in for the dense
    attention inside the full LM (rope + reshapes + mixed precision):
    same loss, same gradients."""
    from functools import partial

    import jax

    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)
    from parameter_server_distributed_tpu.ops.pallas.flash_attention import (
        flash_attention)

    config = TransformerConfig(vocab=128, d_model=64, n_heads=4, n_layers=2,
                               d_ff=128, max_seq=64, dtype=jnp.float32)
    dense_model = Transformer(config)
    flash_model = Transformer(
        config, attention_fn=partial(flash_attention, block_q=32, block_k=32))
    params = dense_model.init_params(0)
    tokens = jnp.asarray(rng.integers(0, 128, (2, 64)), jnp.int32)

    ld, gd = jax.value_and_grad(dense_model.loss)(params, tokens)
    lf, gf = jax.value_and_grad(flash_model.loss)(params, tokens)
    np.testing.assert_allclose(float(lf), float(ld), rtol=1e-5)
    for name in gd:
        np.testing.assert_allclose(np.asarray(gf[name]), np.asarray(gd[name]),
                                   rtol=1e-3, atol=1e-5, err_msg=name)


def test_flash_attention_env_default(rng, monkeypatch):
    """PSDT_FLASH_ATTENTION=1 switches the single-device model default to
    the flash-auto path on TPU only (interpret-mode pallas on other
    backends is a per-call opt-in, never a launch-env default)."""
    import jax

    from parameter_server_distributed_tpu.models import transformer as tr

    config = tr.TransformerConfig(vocab=64, d_model=32, n_heads=2,
                                  n_layers=1, d_ff=64, max_seq=32,
                                  dtype=jnp.float32)
    monkeypatch.setenv("PSDT_FLASH_ATTENTION", "1")
    # CPU backend (this test session): env flag alone must NOT select flash
    assert tr.Transformer(config).attention_fn is tr.causal_attention
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert tr.Transformer(config).attention_fn is tr.flash_attention_auto
    monkeypatch.delenv("PSDT_FLASH_ATTENTION")
    assert tr.Transformer(config).attention_fn is tr.causal_attention
    # indivisible seq falls back to dense inside flash_attention_auto
    q = jnp.asarray(rng.standard_normal((1, 48, 2, 16)), jnp.float32)
    ref = tr.causal_attention(q, q, q)
    np.testing.assert_allclose(np.asarray(tr.flash_attention_auto(q, q, q)),
                               np.asarray(ref), rtol=1e-5, atol=1e-6)


def test_remat_loss_and_gradients_match_non_remat(rng):
    """jax.checkpoint rematerialization must be numerically invisible:
    same loss, same gradients, only the backward memory profile changes."""
    import dataclasses

    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)

    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                               d_ff=64, max_seq=16, dtype=jnp.float32)
    tokens = rng.integers(0, 64, (4, 16)).astype(np.int32)
    plain = Transformer(config)
    remat = Transformer(dataclasses.replace(config, remat=True))
    params = plain.init_params(0)

    loss_a = float(jax.jit(plain.loss)(params, tokens))
    loss_b = float(jax.jit(remat.loss)(params, tokens))
    np.testing.assert_allclose(loss_b, loss_a, rtol=1e-6)

    g_a = jax.jit(jax.grad(plain.loss))(params, tokens)
    g_b = jax.jit(jax.grad(remat.loss))(params, tokens)
    for name in g_a:
        np.testing.assert_allclose(np.asarray(g_b[name]),
                                   np.asarray(g_a[name]), rtol=1e-5,
                                   atol=1e-7, err_msg=name)


def test_remat_dots_policy_matches_full(rng):
    """remat_policy='dots' (save projection/MLP dot outputs, recompute
    only the attention einsums) must be numerically identical to the
    full-recompute policy — the policy changes WHAT the backward pass
    recomputes, never the math.  Covers unrolled and scan layouts, and
    checks the credited-FLOPs accounting only credits the attention
    recompute under 'dots'."""
    import dataclasses

    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)

    tokens = rng.integers(0, 64, (4, 16)).astype(np.int32)
    for scan in (False, True):
        config = TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                   n_layers=2, d_ff=64, max_seq=16,
                                   dtype=jnp.float32, remat=True,
                                   scan_layers=scan)
        full = Transformer(config)
        dots = Transformer(dataclasses.replace(config, remat_policy="dots"))
        params = full.init_params(0)
        g_a = jax.jit(jax.grad(full.loss))(params, tokens)
        g_b = jax.jit(jax.grad(dots.loss))(params, tokens)
        for name in g_a:
            np.testing.assert_allclose(np.asarray(g_b[name]),
                                       np.asarray(g_a[name]), rtol=1e-5,
                                       atol=1e-7, err_msg=f"scan={scan} {name}")
        # credited accounting: full credits the whole recompute forward
        # (8P + 16 attn), dots only the attention einsums (6P + 16 attn)
        base = full.flops_per_sample()
        assert dots.flops_per_sample() == base
        assert (full.flops_per_sample(remat_credited=True)
                > dots.flops_per_sample(remat_credited=True) > base)

    with pytest.raises(ValueError, match="remat_policy"):
        TransformerConfig(remat_policy="bogus")


def test_remat_generation_still_exact(rng):
    """collect_kv (generation prefill) bypasses remat; decoding from a
    remat-configured model matches the plain model token for token."""
    import dataclasses

    from parameter_server_distributed_tpu.models.generation import generate
    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)

    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                               d_ff=64, max_seq=64, dtype=jnp.float32)
    plain = Transformer(config)
    remat = Transformer(dataclasses.replace(config, remat=True))
    params = plain.init_params(0)
    prompt = rng.integers(0, 64, (2, 8)).astype(np.int32)
    out_a = np.asarray(generate(plain, params, prompt, 8))
    out_b = np.asarray(generate(remat, params, prompt, 8))
    np.testing.assert_array_equal(out_a, out_b)


def test_registry_dtype_and_remat_plumbing():
    from parameter_server_distributed_tpu.models.registry import (
        get_model_and_batches)

    model, _ = get_model_and_batches("small_lm", 4, dtype="bf16", remat=True)
    assert model.config.dtype == jnp.bfloat16
    assert model.config.remat
    model, _ = get_model_and_batches("resnet18_cifar", 4, dtype="bf16")
    assert model.dtype == jnp.bfloat16
    with pytest.raises(ValueError, match="dtype"):
        get_model_and_batches("mnist_mlp", 4, dtype="bf16")
    with pytest.raises(ValueError, match="remat"):
        get_model_and_batches("mlp_1b", 4, remat=True)
    with pytest.raises(ValueError, match="unknown dtype"):
        get_model_and_batches("small_lm", 4, dtype="fp8")


def test_gqa_transformer_trains_and_matches_mha_when_equal(rng):
    """n_kv_heads=n_heads is exactly MHA (same shapes, same loss); a real
    GQA config has smaller wk/wv, finite loss, and gradients through them."""
    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                               d_ff=64, max_seq=16, dtype=jnp.float32)
    import dataclasses
    tokens = rng.integers(0, 64, (4, 16)).astype(np.int32)

    mha = Transformer(config)
    same = Transformer(dataclasses.replace(config, n_kv_heads=4))
    params = mha.init_params(0)
    np.testing.assert_allclose(
        float(jax.jit(same.loss)(params, tokens)),
        float(jax.jit(mha.loss)(params, tokens)), rtol=1e-6)

    gqa = Transformer(dataclasses.replace(config, n_kv_heads=2))
    assert gqa.param_shapes()["layer0/attn/wk"] == (32, 16)
    gparams = gqa.init_params(0)
    loss, grads = jax.jit(jax.value_and_grad(gqa.loss))(gparams, tokens)
    assert np.isfinite(float(loss))
    assert float(jnp.abs(grads["layer0/attn/wk"]).max()) > 0

    with pytest.raises(ValueError, match="n_kv_heads"):
        Transformer(dataclasses.replace(config, n_kv_heads=3))


def test_chunked_cross_entropy_matches_unchunked(rng):
    """loss_chunk must be numerically invisible: same loss, same gradients
    — only peak logits memory changes."""
    import dataclasses

    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                               d_ff=64, max_seq=16, dtype=jnp.float32)
    tokens = rng.integers(0, 64, (4, 16)).astype(np.int32)
    plain = Transformer(config)
    chunked = Transformer(dataclasses.replace(config, loss_chunk=4))
    params = plain.init_params(0)

    la = float(jax.jit(plain.loss)(params, tokens))
    lb = float(jax.jit(chunked.loss)(params, tokens))
    np.testing.assert_allclose(lb, la, rtol=1e-6)

    g_a = jax.jit(jax.grad(plain.loss))(params, tokens)
    g_b = jax.jit(jax.grad(chunked.loss))(params, tokens)
    for name in g_a:
        np.testing.assert_allclose(np.asarray(g_b[name]),
                                   np.asarray(g_a[name]), rtol=2e-5,
                                   atol=1e-7, err_msg=name)

    bad = Transformer(dataclasses.replace(config, loss_chunk=5))
    with pytest.raises(ValueError, match="divide"):
        jax.jit(bad.loss)(params, tokens)


def test_scan_layers_matches_unrolled(rng):
    """scan_layers is a layout/compile-time change only: with the same
    weights (converted via stack_layers) the loss and gradients match the
    unrolled model; unstack_layers round-trips the store."""
    import dataclasses

    from parameter_server_distributed_tpu.models.transformer import (
        stack_layers, unstack_layers)

    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=3,
                               d_ff=64, max_seq=16, dtype=jnp.float32)
    tokens = rng.integers(0, 64, (4, 16)).astype(np.int32)
    plain = Transformer(config)
    scanned = Transformer(dataclasses.replace(config, scan_layers=True))
    params = plain.init_params(0)
    stacked = stack_layers(params, config.n_layers)

    assert set(stacked) == set(scanned.param_shapes())
    assert scanned.num_params() == plain.num_params()
    back = unstack_layers(stacked)
    assert set(back) == set(params)
    for name in params:
        np.testing.assert_array_equal(np.asarray(back[name]),
                                      np.asarray(params[name]))

    loss_a = float(jax.jit(plain.loss)(params, tokens))
    loss_b = float(jax.jit(scanned.loss)(stacked, tokens))
    np.testing.assert_allclose(loss_b, loss_a, rtol=1e-6)

    # atol covers f32 reassociation noise: scan accumulates the embed
    # grad layer-by-layer in a different order than the unrolled sum
    g_a = stack_layers(jax.jit(jax.grad(plain.loss))(params, tokens),
                       config.n_layers)
    g_b = jax.jit(jax.grad(scanned.loss))(stacked, tokens)
    for name in g_a:
        np.testing.assert_allclose(np.asarray(g_b[name]),
                                   np.asarray(g_a[name]), rtol=2e-5,
                                   atol=2e-6, err_msg=name)

    # remat composes with scan (checkpointed scan body), still exact
    remat_scan = Transformer(dataclasses.replace(
        config, scan_layers=True, remat=True))
    loss_c = float(jax.jit(remat_scan.loss)(stacked, tokens))
    np.testing.assert_allclose(loss_c, loss_a, rtol=1e-6)
    g_c = jax.jit(jax.grad(remat_scan.loss))(stacked, tokens)
    for name in g_a:
        np.testing.assert_allclose(np.asarray(g_c[name]),
                                   np.asarray(g_a[name]), rtol=2e-5,
                                   atol=2e-6, err_msg=name)


def test_scan_layers_generation_matches_unrolled(rng):
    """KV-cached decode (prefill collect_kv + per-layer layer_view) works
    on the stacked layout and matches the unrolled model token-exactly."""
    import dataclasses

    from parameter_server_distributed_tpu.models.generation import generate
    from parameter_server_distributed_tpu.models.transformer import (
        stack_layers)

    config = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                               d_ff=64, max_seq=32, dtype=jnp.float32)
    plain = Transformer(config)
    scanned = Transformer(dataclasses.replace(config, scan_layers=True))
    params = plain.init_params(0)
    stacked = stack_layers(params, config.n_layers)
    prompt = rng.integers(0, 64, (2, 5)).astype(np.int32)

    out_a = np.asarray(generate(plain, params, prompt, max_new_tokens=8))
    out_b = np.asarray(generate(scanned, stacked, prompt, max_new_tokens=8))
    np.testing.assert_array_equal(out_a, out_b)


def test_scan_layers_sharded_training():
    """The stacked store trains under a dp x tp mesh: transformer_rule
    shards the trailing weight dims and leaves the scanned layer dim
    whole."""
    from jax.sharding import PartitionSpec

    model = small_lm(scan_layers=True)
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    rule = transformer_rule(mesh)
    spec = rule("blocks/attn/wq", (2, 128, 128))
    assert spec == PartitionSpec(None, "fsdp", "tensor")
    spec = rule("blocks/mlp/w2", (2, 512, 128))
    assert spec == PartitionSpec(None, "tensor", "fsdp")

    trainer = ShardedTrainer(model.loss, mesh, rule,
                             make_optimizer("adam", 1e-3))
    state = trainer.init_state(model.init_params(0))
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 1024, (8, 256)).astype(np.int32)
    losses = []
    for _ in range(3):
        state, metrics = trainer.step(state, tokens)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_scan_layers_rejects_moe():
    with pytest.raises(ValueError, match="homogeneous"):
        Transformer(TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                      n_layers=2, d_ff=64, moe_every=2,
                                      scan_layers=True))


def test_registry_seq_override():
    """seq_len builds the LM at the requested context length, the
    synthetic token stream follows, and non-LM models reject it."""
    from parameter_server_distributed_tpu.models.registry import (
        get_model_and_batches)

    model, batches = get_model_and_batches("small_lm", 2, seq_len=512)
    assert model.config.max_seq == 512
    batch = next(batches)
    assert batch.shape == (2, 512)
    with pytest.raises(ValueError, match="sequence length"):
        get_model_and_batches("mnist_mlp", 2, seq_len=512)


def test_flops_per_sample_accounting():
    """PaLM-convention FLOPs: 6P + 12*L*d*S per token; remat-credited adds
    the recompute forward (8P + 16*L*d*S).  MoE counts ACTIVE-expert
    FLOPs: P_active excludes the (E - top_k) experts a token never
    runs."""
    import dataclasses

    from parameter_server_distributed_tpu.models.transformer import (
        Transformer, TransformerConfig)

    config = TransformerConfig(vocab=128, d_model=64, n_heads=4, n_layers=2,
                               d_ff=128, max_seq=32, dtype=jnp.float32)
    model = Transformer(config)
    base = model.flops_per_sample()
    seq = config.max_seq
    assert base == (6.0 * model.num_params() * seq
                    + 12.0 * config.n_layers * config.d_model * seq * seq)
    credited = model.flops_per_sample(remat_credited=True)
    assert credited == (8.0 * model.num_params() * seq
                        + 16.0 * config.n_layers * config.d_model * seq * seq)
    moe = Transformer(dataclasses.replace(config, moe_every=2,
                                          moe_experts=4, moe_top_k=1))
    # layer 1 (1-based layer 2) is MoE: 3 of 4 experts inactive per token
    active = moe.num_params() - 1 * 3 * 2 * config.d_model * config.d_ff
    assert moe.flops_per_sample() == (
        6.0 * active * seq
        + 12.0 * config.n_layers * config.d_model * seq * seq)
    # top_k=2 activates one more expert's worth of FLOPs
    moe2 = Transformer(dataclasses.replace(config, moe_every=2,
                                           moe_experts=4, moe_top_k=2))
    assert moe2.flops_per_sample() > moe.flops_per_sample()


def test_vit_flops_accounting_excludes_non_matmul_params():
    """ViT MFU numerator: embed/pos is an add (no FLOPs credit), patch/w
    sees only the n_patches patch tokens (never CLS), and the classifier
    head sees exactly one pooled token."""
    import math

    from parameter_server_distributed_tpu.models.vit import ViT, ViTConfig

    c = ViTConfig(image_size=32, patch_size=8, d_model=64, n_heads=4,
                  n_layers=2, d_ff=128, num_classes=10)
    model = ViT(c)
    shapes = model.param_shapes()
    s, n = c.seq_len, c.n_patches
    block = sum(math.prod(shape) for name, shape in shapes.items()
                if len(shape) == 2
                and name not in ("lm_head/w", "embed/pos", "patch/w"))
    expected = (6.0 * (block * s + math.prod(shapes["patch/w"]) * n
                       + c.d_model * c.num_classes)
                + 12.0 * c.n_layers * c.d_model * s * s)
    assert model.flops_per_sample() == expected
    # the two excluded tables would have inflated the numerator
    assert math.prod(shapes["embed/pos"]) > 0
    assert model.flops_per_sample() < expected + 6.0 * s * math.prod(
        shapes["embed/pos"])
