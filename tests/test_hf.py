"""HF GPT-2 interop (models/hf.py) + the compatibility knobs it exercises
(learned positions, LayerNorm, projection biases).

Ground truth is the torch forward of a random-init GPT2LMHeadModel —
no network or checkpoint files involved; the conversion must be a pure
re-layout, so logits match to float32 tolerance and every downstream
capability (KV-cached decode, continuous batching, quantization) works
on the converted store unchanged.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402

from parameter_server_distributed_tpu.models.generation import (  # noqa: E402
    generate)
from parameter_server_distributed_tpu.models.hf import (  # noqa: E402
    from_hf_gpt2)
from parameter_server_distributed_tpu.models.serving import (  # noqa: E402
    DecodeServer)


@pytest.fixture(scope="module")
def hf_pair():
    torch.manual_seed(0)
    cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=32, n_layer=2, n_head=2)
    hf_model = transformers.GPT2LMHeadModel(cfg).eval()
    model, params = from_hf_gpt2(hf_model)
    return hf_model, model, params


def _torch_logits(hf_model, x):
    with torch.no_grad():
        return hf_model(torch.from_numpy(
            np.asarray(x, np.int64))).logits.numpy()


def test_logits_parity(hf_pair, rng):
    hf_model, model, params = hf_pair
    x = rng.integers(0, 128, (2, 12)).astype(np.int32)
    want = _torch_logits(hf_model, x)
    got = np.asarray(model.apply(params, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_scan_layers_conversion_parity(hf_pair, rng):
    hf_model, _, _ = hf_pair
    model, params = from_hf_gpt2(hf_model, scan_layers=True)
    x = rng.integers(0, 128, (1, 9)).astype(np.int32)
    want = _torch_logits(hf_model, x)
    got = np.asarray(model.apply(params, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_greedy_generation_matches_hf(hf_pair, rng):
    """End-to-end: our KV-cached greedy decode reproduces HF's greedy
    continuation token for token."""
    hf_model, model, params = hf_pair
    prompt = rng.integers(0, 128, (1, 6)).astype(np.int32)
    n = 8
    with torch.no_grad():
        hf_out = hf_model.generate(
            torch.from_numpy(prompt.astype(np.int64)),
            max_new_tokens=n, do_sample=False,
            pad_token_id=0)[0, prompt.shape[1]:].numpy()
    ours = np.asarray(generate(model, params, jnp.asarray(prompt), n))[0]
    np.testing.assert_array_equal(ours, hf_out.astype(ours.dtype))


def test_cached_decode_matches_full_forward_learned_pos(hf_pair, rng):
    """The cache-correctness invariant under learned positions: cached
    decode must equal re-running the whole sequence (position info enters
    via embed, not rope — a decode path that dropped the positional add
    would diverge here)."""
    hf_model, model, params = hf_pair
    prompt = jnp.asarray(rng.integers(0, 128, (2, 5)), jnp.int32)
    toks = prompt
    expected = []
    for _ in range(5):
        nxt = jnp.argmax(model.apply(params, toks)[:, -1], -1)
        expected.append(nxt.astype(jnp.int32))
        toks = jnp.concatenate([toks, nxt[:, None].astype(jnp.int32)], 1)
    got = generate(model, params, prompt, 5)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(jnp.stack(expected, 1)))


def test_converted_model_serves_and_quantizes(hf_pair, rng):
    """The whole serving stack composes on a converted checkpoint:
    continuous batching + int8 weights + int8 KV cache."""
    from parameter_server_distributed_tpu.models.quant import (
        quantize_params)
    hf_model, model, params = hf_pair
    prompt = list(rng.integers(0, 128, 6))
    ref = list(np.asarray(generate(
        model, params, jnp.asarray([prompt], jnp.int32), 5))[0])
    srv = DecodeServer(model, quantize_params(params), slots=2, max_len=64,
                       cache_dtype="int8")
    rid = srv.submit(prompt, max_new_tokens=5)
    results = srv.run_to_completion()
    assert len(results[rid]) == 5
    # int8 noise may flip late tokens on a random-init model; the first
    # token comes from prefill logits and must agree
    assert results[rid][0] == ref[0]


def test_conversion_shape_contract(hf_pair):
    hf_model, model, params = hf_pair
    assert {k: tuple(v.shape) for k, v in params.items()} \
        == model.param_shapes()


def test_position_budget_guard(hf_pair, rng):
    """Learned-position models reject decoding past max_seq (n_positions)
    instead of silently reusing the last position embedding."""
    hf_model, model, params = hf_pair
    max_seq = model.config.max_seq
    prompt = jnp.asarray(rng.integers(0, 128, (1, max_seq - 2)), jnp.int32)
    with pytest.raises(ValueError, match="learned-position"):
        generate(model, params, prompt, 5)
    srv = DecodeServer(model, params, slots=1, max_len=2 * max_seq)
    with pytest.raises(ValueError, match="learned-position"):
        srv.submit(list(np.asarray(prompt)[0]), max_new_tokens=5)


def test_unsupported_activation_rejected():
    cfg = transformers.GPT2Config(
        vocab_size=64, n_positions=32, n_embd=16, n_layer=1, n_head=2,
        activation_function="gelu")  # exact erf GELU — not our math
    hf_model = transformers.GPT2LMHeadModel(cfg)
    with pytest.raises(ValueError, match="activation_function"):
        from_hf_gpt2(hf_model)


def test_n_inner_honored():
    cfg = transformers.GPT2Config(
        vocab_size=64, n_positions=32, n_embd=16, n_layer=1, n_head=2,
        n_inner=40)
    model, params = from_hf_gpt2(transformers.GPT2LMHeadModel(cfg))
    assert model.config.d_ff == 40
    assert params["layer0/mlp/w1"].shape == (16, 40)


def test_config_knob_validation():
    from parameter_server_distributed_tpu.models.transformer import (
        TransformerConfig)
    with pytest.raises(ValueError, match="pos_emb"):
        TransformerConfig(pos_emb="learnt")
    with pytest.raises(ValueError, match="norm"):
        TransformerConfig(norm="layer_norm")


def test_position_budget_guard_beam_and_host_spec(hf_pair, rng):
    """Every decode entry point rejects past-max_seq generation on
    learned-position models — beam search and the host-loop speculative
    decoder included."""
    from parameter_server_distributed_tpu.models.generation import (
        beam_search, speculative_generate)
    hf_model, model, params = hf_pair
    max_seq = model.config.max_seq
    prompt = jnp.asarray(rng.integers(0, 128, (1, max_seq - 2)), jnp.int32)
    with pytest.raises(ValueError, match="learned-position"):
        beam_search(model, params, prompt, 5, beam_width=2)
    with pytest.raises(ValueError, match="learned-position"):
        speculative_generate(model, params, model, params, prompt, 5)


def test_attention_variant_configs_rejected():
    for field in ("scale_attn_by_inverse_layer_idx",
                  "reorder_and_upcast_attn"):
        cfg = transformers.GPT2Config(
            vocab_size=64, n_positions=32, n_embd=16, n_layer=1, n_head=2,
            **{field: True})
        with pytest.raises(ValueError, match=field):
            from_hf_gpt2(transformers.GPT2LMHeadModel(cfg))


@pytest.fixture(scope="module")
def llama_pair():
    torch.manual_seed(0)
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=56,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64)
    hf_model = transformers.LlamaForCausalLM(cfg).eval()
    from parameter_server_distributed_tpu.models.hf import from_hf_llama
    model, params = from_hf_llama(hf_model, dtype=jnp.float32)
    return hf_model, model, params


def test_llama_logits_parity(llama_pair, rng):
    """GQA + SwiGLU + RoPE (rotate-half) all line up with the torch
    forward — the LLaMA family is the native architecture."""
    hf_model, model, params = llama_pair
    assert model.config.mlp_act == "swiglu"
    assert model.config.kv_heads == 2
    x = rng.integers(0, 128, (2, 12)).astype(np.int32)
    want = _torch_logits(hf_model, x)
    got = np.asarray(model.apply(params, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_llama_greedy_generation_matches_hf(llama_pair, rng):
    hf_model, model, params = llama_pair
    prompt = rng.integers(0, 128, (1, 6)).astype(np.int32)
    n = 8
    with torch.no_grad():
        hf_out = hf_model.generate(
            torch.from_numpy(prompt.astype(np.int64)),
            max_new_tokens=n, do_sample=False,
            pad_token_id=0)[0, prompt.shape[1]:].numpy()
    ours = np.asarray(generate(model, params, jnp.asarray(prompt), n))[0]
    np.testing.assert_array_equal(ours, hf_out.astype(ours.dtype))


def test_llama_scan_layers_and_quant_compose(llama_pair, rng):
    from parameter_server_distributed_tpu.models.hf import from_hf_llama
    from parameter_server_distributed_tpu.models.quant import (
        QTensor, quantize_params)
    hf_model, _, _ = llama_pair
    model, params = from_hf_llama(hf_model, dtype=jnp.float32,
                                  scan_layers=True)
    qparams = quantize_params(params)
    assert isinstance(qparams["blocks/mlp/w3"], QTensor)
    prompt = jnp.asarray(rng.integers(0, 128, (1, 6)), jnp.int32)
    out = generate(model, qparams, prompt, 4, cache_dtype="int8")
    assert out.shape == (1, 4)


def test_llama_unsupported_variants_rejected():
    from parameter_server_distributed_tpu.models.hf import (
        config_from_hf_llama)
    base = dict(vocab_size=64, hidden_size=16, intermediate_size=32,
                num_hidden_layers=1, num_attention_heads=2,
                num_key_value_heads=2, max_position_embeddings=32)
    with pytest.raises(ValueError, match="rope_scaling"):
        config_from_hf_llama(transformers.LlamaConfig(
            **base, rope_scaling={"rope_type": "linear", "factor": 2.0}))
    with pytest.raises(ValueError, match="attention_bias"):
        config_from_hf_llama(transformers.LlamaConfig(
            **base, attention_bias=True))
    with pytest.raises(ValueError, match="hidden_act"):
        config_from_hf_llama(transformers.LlamaConfig(
            **base, hidden_act="gelu"))


def test_bf16_torch_checkpoint_converts():
    """Real checkpoints ship bf16 and torch bf16 tensors lack .numpy();
    the converter must upcast through float32."""
    from parameter_server_distributed_tpu.models.hf import from_hf_llama
    cfg = transformers.LlamaConfig(
        vocab_size=64, hidden_size=16, intermediate_size=32,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32)
    hf_model = transformers.LlamaForCausalLM(cfg).to(torch.bfloat16)
    model, params = from_hf_llama(hf_model)
    assert params["embed/tok"].dtype == jnp.bfloat16


def test_training_forward_rejects_past_position_table(hf_pair, rng):
    """apply()/loss() on a learned-position model must reject sequences
    longer than the table instead of silently clipping (wrong gradients)."""
    hf_model, model, params = hf_pair
    seq = model.config.max_seq + 8
    toks = jnp.asarray(rng.integers(0, 128, (1, seq)), jnp.int32)
    with pytest.raises(ValueError, match="learned-position"):
        model.apply(params, toks)


def test_swiglu_knob_validation():
    from parameter_server_distributed_tpu.models.transformer import (
        TransformerConfig)
    with pytest.raises(ValueError, match="mlp_act"):
        TransformerConfig(mlp_act="geglu")


@pytest.mark.parametrize("pair", ["gpt2", "llama"])
def test_converted_checkpoints_finetune(pair, hf_pair, llama_pair, rng):
    """The fine-tuning loop closes on converted checkpoints: gradients
    flow through every compatibility knob (learned pos + LayerNorm +
    biases for GPT-2; SwiGLU + GQA for LLaMA) and a few SGD steps reduce
    the loss on a fixed batch."""
    import jax

    _, model, params = hf_pair if pair == "gpt2" else llama_pair
    toks = jnp.asarray(rng.integers(0, 128, (2, 16)), jnp.int32)
    loss_fn = jax.jit(jax.value_and_grad(model.loss))
    l0, grads = loss_fn(params, toks)
    # every parameter receives real gradient signal (biases/pos table
    # included — an accidentally-detached leaf would be all-zero)
    zero_grads = [k for k, g in grads.items()
                  if float(jnp.abs(g).max()) == 0.0]
    assert not zero_grads, zero_grads
    for _ in range(5):
        params = jax.tree.map(lambda p, g: p - 0.05 * g, params, grads)
        _, grads = loss_fn(params, toks)
    final, _ = loss_fn(params, toks)
    assert float(final) < float(l0), (float(final), float(l0))


def test_llama_350m_registry_entry():
    from parameter_server_distributed_tpu.models.registry import (
        get_model_and_batches)
    model, _ = get_model_and_batches("llama_350m", 2)
    assert model.config.mlp_act == "swiglu"
    assert model.config.kv_heads == 4
    assert 300e6 < model.num_params() < 420e6


def test_export_round_trip_llama_finetuned(llama_pair, rng):
    """to_hf_llama: a store fine-tuned HERE loads back into the torch
    model with exact logits parity — the interop round-trips both ways
    (LLaMA's head is untied, so tuned weights export faithfully)."""
    import copy

    import jax

    from parameter_server_distributed_tpu.models.hf import to_hf_llama
    hf_model, model, params = llama_pair
    # the fixture is module-scoped: load tuned weights into a COPY so
    # the other parity tests keep their pristine torch model
    hf_model = copy.deepcopy(hf_model)
    toks = jnp.asarray(rng.integers(0, 128, (2, 12)), jnp.int32)
    _, grads = jax.value_and_grad(model.loss)(params, toks)
    tuned = jax.tree.map(lambda p, g: p - 0.01 * g, params, grads)
    sd = to_hf_llama(model, tuned)
    hf_model.load_state_dict(sd)
    x = rng.integers(0, 128, (2, 9)).astype(np.int32)
    want = np.asarray(model.apply(tuned, jnp.asarray(x)))
    got = _torch_logits(hf_model, x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_export_round_trip_gpt2(hf_pair, rng):
    """to_hf_gpt2 round-trips an (untuned or head-retied) store exactly;
    a fine-tuned store whose head diverged from wte.T is rejected loudly
    — HF GPT-2's tying cannot represent it."""
    import jax

    from parameter_server_distributed_tpu.models.hf import to_hf_gpt2
    hf_model, model, params = hf_pair
    import copy
    hf_model = copy.deepcopy(hf_model)   # module-scoped fixture
    x = rng.integers(0, 128, (2, 9)).astype(np.int32)
    want = np.asarray(model.apply(params, jnp.asarray(x)))
    hf_model.load_state_dict(to_hf_gpt2(model, params))
    got = _torch_logits(hf_model, x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    # fine-tune -> head unties -> export must refuse
    toks = jnp.asarray(rng.integers(0, 128, (2, 12)), jnp.int32)
    _, grads = jax.value_and_grad(model.loss)(params, toks)
    tuned = jax.tree.map(lambda p, g: p - 0.01 * g, params, grads)
    with pytest.raises(ValueError, match="ties lm_head"):
        to_hf_gpt2(model, tuned)
    # re-tying restores exportability
    tuned = dict(tuned)
    tuned["lm_head/w"] = tuned["embed/tok"].T
    hf_model.load_state_dict(to_hf_gpt2(model, tuned))


def test_export_scan_layout_and_quant_guard(llama_pair):
    import copy

    from parameter_server_distributed_tpu.models.hf import (from_hf_llama,
                                                            to_hf_llama)
    from parameter_server_distributed_tpu.models.quant import quantize_params
    hf_model, _, _ = llama_pair
    hf_model = copy.deepcopy(hf_model)       # module-scoped fixture
    model, params = from_hf_llama(hf_model, dtype=jnp.float32,
                                  scan_layers=True)
    sd = to_hf_llama(model, params)           # stacked layout exports too
    hf_model.load_state_dict(sd)
    with pytest.raises(ValueError, match="int8-quantized"):
        to_hf_llama(model, quantize_params(params))


def test_export_tied_destination_guard(llama_pair):
    """tie_word_embeddings=True destinations: the export omits lm_head
    (emitting it would stomp the shared embedding) and refuses a store
    whose head diverged from the tie."""
    from parameter_server_distributed_tpu.models.hf import to_hf_llama
    _, model, params = llama_pair
    tied = dict(params)
    tied["lm_head/w"] = tied["embed/tok"].T
    sd = to_hf_llama(model, tied, tie_word_embeddings=True)
    assert "lm_head.weight" not in sd
    with pytest.raises(ValueError, match="diverged"):
        to_hf_llama(model, params, tie_word_embeddings=True)


def test_pipeline_composes_with_converted_gpt2(hf_pair, rng):
    """A CONVERTED GPT-2 checkpoint trains under pipeline parallelism
    (GPipe) since round 5: the pipelined loss equals the plain converted
    model's (positional table and biases included).  The hand-written
    1F1B schedule keeps its native-arch guard and points at gpipe."""
    import jax

    from parameter_server_distributed_tpu.parallel.mesh import build_mesh
    from parameter_server_distributed_tpu.parallel.pipeline import (
        PipelinedTransformerLM)
    from parameter_server_distributed_tpu.config import MeshConfig
    _, model, params = hf_pair
    mesh = build_mesh(MeshConfig(pipeline=2, data=4))
    piped = PipelinedTransformerLM(model, mesh, num_microbatches=2)
    tokens = rng.integers(0, 128, (8, 16)).astype(np.int32)
    # the converted store is the unrolled layer<i>/* layout; restack it
    # into the pipeline's blocks/* layout so both run IDENTICAL weights
    loss_plain = float(jax.jit(model.loss)(params, jnp.asarray(tokens)))
    stacked = piped.restack_params(
        {k: jnp.asarray(v) for k, v in params.items()})
    loss_piped = float(jax.jit(piped.loss)(stacked, jnp.asarray(tokens)))
    np.testing.assert_allclose(loss_piped, loss_plain, rtol=1e-5)
    # the hand-written 1F1B schedule handles the converted arch too
    fb = PipelinedTransformerLM(model, mesh, num_microbatches=2,
                                schedule="1f1b")
    loss_fb, grads_fb = jax.jit(fb.value_and_grad)(
        stacked, jnp.asarray(tokens))
    np.testing.assert_allclose(float(loss_fb), loss_plain, rtol=1e-5)
    assert float(np.abs(np.asarray(grads_fb["embed/pos"])).max()) > 0


def test_run_training_finetunes_hf_checkpoint(tmp_path, hf_pair, rng):
    """pst-train --hf-gpt2=<checkout>: the FULL converted-checkpoint
    fine-tune flow through the training loop — plain, then --lora on a
    pipe mesh under 1F1B (the round-5 composition for converted
    models)."""
    from parameter_server_distributed_tpu.config import MeshConfig
    from parameter_server_distributed_tpu.parallel.train_loop import (
        TrainLoopConfig, run_training)

    hf_model, _, _ = hf_pair
    checkout = tmp_path / "hf_ckpt"
    hf_model.save_pretrained(checkout)

    summary = run_training(TrainLoopConfig(
        hf_gpt2=str(checkout), batch_size=8, steps=3, optimizer="adam",
        learning_rate=1e-3, log_every=1))
    assert summary["steps"] == 3
    assert np.isfinite(summary["final_loss"])

    summary2 = run_training(TrainLoopConfig(
        hf_gpt2=str(checkout), batch_size=8, steps=2, lora="2:4",
        pipeline_schedule="1f1b", log_every=1,
        mesh=MeshConfig(pipeline=2, data=4)))
    assert summary2["steps"] == 2
    assert np.isfinite(summary2["final_loss"])

    # initializer exclusivity is rejected loudly
    with pytest.raises(ValueError, match="initializers"):
        run_training(TrainLoopConfig(
            hf_gpt2=str(checkout), init_ckpt_dir=str(tmp_path), steps=1))


def test_run_training_finetunes_hf_llama(tmp_path, rng):
    """--hf-llama: the converted LlamaForCausalLM trains through
    run_training — native arch, so the 1F1B pipe mesh applies directly;
    --hf-gpt2 x --hf-llama conflict rejected."""
    from parameter_server_distributed_tpu.config import MeshConfig
    from parameter_server_distributed_tpu.parallel.train_loop import (
        TrainLoopConfig, run_training)

    torch.manual_seed(0)
    cfg = transformers.LlamaConfig(
        vocab_size=96, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32)
    checkout = tmp_path / "llama"
    transformers.LlamaForCausalLM(cfg).save_pretrained(checkout)

    summary = run_training(TrainLoopConfig(
        hf_llama=str(checkout), batch_size=8, steps=2, lora="2:4",
        pipeline_schedule="1f1b", log_every=1, model_dtype="f32",
        mesh=MeshConfig(pipeline=2, data=4)))
    assert summary["steps"] == 2
    assert np.isfinite(summary["final_loss"])

    with pytest.raises(ValueError, match="both pick"):
        run_training(TrainLoopConfig(
            hf_gpt2=str(checkout), hf_llama=str(checkout), steps=1))
