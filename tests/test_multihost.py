"""Multi-host (multi-controller) training e2e: two OS processes, CPU
backend, jax.distributed over localhost — the DCN story of
parallel/distributed.py actually exercised (VERDICT round 1 weak item 7).

Each process hosts 4 virtual CPU devices; the global mesh spans all 8.
The test drives the REAL CLI (cli.train_main with --coordinator/
--num-processes/--process-id), so it covers initialize_multihost, the
multi-controller batch/state placement in ShardedTrainer, and the training
loop end to end.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _child_env() -> dict:
    env = dict(os.environ)
    env["PSDT_PLATFORM"] = "cpu"  # sitecustomize overrides JAX_PLATFORMS
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


@pytest.mark.parametrize("model,mesh", [
    ("mnist_mlp", "data:4,fsdp:2"),
    ("small_lm", "data:8"),
])
def test_two_process_training_e2e(model, mesh, tmp_path):
    """train_main --num-processes=2 on two real processes: both must
    finish, report identical losses (same global batch, same collectives),
    and actually form one 8-device cluster."""
    port = _free_port()
    args = [sys.executable, "-m",
            "parameter_server_distributed_tpu.cli.train_main",
            f"--coordinator=127.0.0.1:{port}", "--num-processes=2",
            f"--model={model}", f"--mesh={mesh}", "--steps=4",
            "--batch=16", "--optimizer=sgd", "--lr=0.1", "--log-every=2"]
    procs = [
        subprocess.Popen(args + [f"--process-id={i}"], env=_child_env(),
                         cwd=str(tmp_path), stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE)
        for i in range(2)
    ]
    outs = []
    for i, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail(f"process {i} timed out")
        assert proc.returncode == 0, (
            f"process {i} rc={proc.returncode}\n"
            f"stderr tail:\n{err.decode(errors='replace')[-2000:]}")
        outs.append(out.decode(errors="replace"))

    summaries = []
    for i, out in enumerate(outs):
        line = [l for l in out.splitlines() if l.startswith("{")][-1]
        summaries.append(json.loads(line))
    losses = [s["final_loss"] for s in summaries]
    assert all(np.isfinite(l) for l in losses), losses
    # one logical computation on one global mesh -> identical results
    assert losses[0] == pytest.approx(losses[1], rel=1e-6)
    assert summaries[0]["steps"] == 4


def test_two_process_per_process_data(tmp_path):
    """--per-process-data: each host loads only batch/2 rows at its own
    seed and the global batch is stitched from local shards
    (make_array_from_process_local_data).  Both processes must agree on
    the loss (one SPMD program) and show a learning signal."""
    port = _free_port()
    args = [sys.executable, "-m",
            "parameter_server_distributed_tpu.cli.train_main",
            f"--coordinator=127.0.0.1:{port}", "--num-processes=2",
            "--model=mnist_mlp", "--mesh=data:8", "--steps=6",
            "--batch=32", "--optimizer=sgd", "--lr=0.1", "--log-every=2",
            "--per-process-data",
            "--metrics=metrics_{}.jsonl"]
    procs = [
        subprocess.Popen(
            [a.replace("{}", str(i)) for a in args] + [f"--process-id={i}"],
            env=_child_env(), cwd=str(tmp_path), stdout=subprocess.PIPE,
            stderr=subprocess.PIPE)
        for i in range(2)
    ]
    outs = []
    for i, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail(f"process {i} timed out")
        assert proc.returncode == 0, (
            f"process {i} rc={proc.returncode}\n"
            f"stderr tail:\n{err.decode(errors='replace')[-2000:]}")
        outs.append(out.decode(errors="replace"))

    summaries = [json.loads([l for l in out.splitlines()
                             if l.startswith("{")][-1]) for out in outs]
    losses = [s["final_loss"] for s in summaries]
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[0] == pytest.approx(losses[1], rel=1e-6)
    # learning signal across the stitched global batches
    lines = [json.loads(l)
             for l in open(tmp_path / "metrics_0.jsonl")]
    assert lines[-1]["loss"] < lines[0]["loss"]


def test_hybrid_mesh_config_single_process():
    """hybrid_mesh_config factorizes the (virtual) global device count with
    model axes innermost."""
    from parameter_server_distributed_tpu.parallel.distributed import (
        hybrid_mesh_config)

    config = hybrid_mesh_config(tensor=2)
    assert config.tensor == 2
    assert config.num_devices == 8  # conftest forces 8 virtual devices

    with pytest.raises(ValueError, match="divisible"):
        hybrid_mesh_config(tensor=3)


def test_initialize_multihost_single_process_noop():
    from parameter_server_distributed_tpu.parallel.distributed import (
        initialize_multihost)

    assert initialize_multihost(num_processes=1) is False


def _run_procs(args, n_procs, tmp_path, devices_per_proc=2, timeout=480):
    env = _child_env()
    env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count="
                        f"{devices_per_proc}")
    procs = [
        subprocess.Popen(args + [f"--process-id={i}"], env=env,
                         cwd=str(tmp_path), stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE)
        for i in range(n_procs)
    ]
    outs = []
    for i, proc in enumerate(procs):
        try:
            out, err = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail(f"process {i} timed out")
        assert proc.returncode == 0, (
            f"process {i} rc={proc.returncode}\n"
            f"stderr tail:\n{err.decode(errors='replace')[-2000:]}")
        outs.append(out.decode(errors="replace"))
    return [json.loads([l for l in out.splitlines()
                        if l.startswith("{")][-1]) for out in outs]


def test_four_process_fsdp_across_hosts(tmp_path):
    """4 OS processes x 2 virtual devices: one fsdp:8 mesh whose shard
    groups span every process boundary (DCN in production, localhost
    here).  All four controllers must agree bit-for-bit on the loss."""
    port = _free_port()
    args = [sys.executable, "-m",
            "parameter_server_distributed_tpu.cli.train_main",
            f"--coordinator=127.0.0.1:{port}", "--num-processes=4",
            "--model=mnist_mlp", "--mesh=fsdp:8", "--steps=3",
            "--batch=16", "--optimizer=sgd", "--lr=0.1", "--log-every=1"]
    summaries = _run_procs(args, 4, tmp_path)
    losses = [s["final_loss"] for s in summaries]
    assert all(np.isfinite(l) for l in losses), losses
    for l in losses[1:]:
        assert losses[0] == pytest.approx(l, rel=1e-6)
    assert summaries[0]["steps"] == 3


def test_four_process_pipeline_across_hosts(tmp_path):
    """4 processes x 2 devices, mesh pipe:4,data:2: each pipe group is 4
    consecutive devices = TWO processes, so the schedule's ppermute hops
    cross process boundaries — the DCN pipeline story end to end."""
    port = _free_port()
    args = [sys.executable, "-m",
            "parameter_server_distributed_tpu.cli.train_main",
            f"--coordinator=127.0.0.1:{port}", "--num-processes=4",
            "--model=small_lm4", "--mesh=pipe:4,data:2", "--steps=2",
            "--batch=16", "--optimizer=sgd", "--lr=0.1", "--log-every=1",
            "--pipeline-schedule=gpipe"]
    summaries = _run_procs(args, 4, tmp_path, timeout=540)
    losses = [s["final_loss"] for s in summaries]
    assert all(np.isfinite(l) for l in losses), losses
    for l in losses[1:]:
        assert losses[0] == pytest.approx(l, rel=1e-6)
