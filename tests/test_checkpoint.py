"""Checkpoint codec + manager tests.

The binary layout must match the reference's custom format
(reference: src/parameter_server.cpp:112-188) byte-for-byte:
epoch(i32) iter(i32) n(u64) then per tensor
name_len(u64)+name shape_len(u64)+shape(i32[]) dtype(i32) data_len(u64)+f32[].
"""

import os
import struct

import numpy as np
import pytest

from parameter_server_distributed_tpu.checkpoint import codec
from parameter_server_distributed_tpu.checkpoint.manager import (
    CheckpointManager, checkpoint_filename)
from parameter_server_distributed_tpu.core.optimizer import Adam
from parameter_server_distributed_tpu.core.ps_core import ParameterServerCore


def test_layout_matches_reference_format():
    params = {"w": np.array([[1.5, 2.5]], np.float32)}
    blob = codec.dumps(epoch=3, iteration=42, params=params)
    expected = b"".join([
        struct.pack("<i", 3),
        struct.pack("<i", 42),
        struct.pack("<Q", 1),
        struct.pack("<Q", 1), b"w",
        struct.pack("<Q", 2), struct.pack("<i", 1), struct.pack("<i", 2),
        struct.pack("<i", 0),
        struct.pack("<Q", 2), np.array([1.5, 2.5], "<f4").tobytes(),
    ])
    assert blob == expected


def test_roundtrip_multi_tensor(rng):
    params = {
        "layer0/w": rng.standard_normal((8, 4)).astype(np.float32),
        "layer0/b": rng.standard_normal(4).astype(np.float32),
        "scalarish": np.array([7.0], np.float32),
    }
    epoch, it, out = codec.loads(codec.dumps(11, 230, params))
    assert (epoch, it) == (11, 230)
    assert set(out) == set(params)
    for k in params:
        np.testing.assert_array_equal(out[k], params[k])
        assert out[k].shape == params[k].shape


def test_truncated_checkpoint_rejected():
    blob = codec.dumps(1, 2, {"w": np.ones(5, np.float32)})
    with pytest.raises(ValueError, match="truncated"):
        codec.loads(blob[:-4])


def test_bad_dtype_rejected():
    blob = bytearray(codec.dumps(1, 2, {"w": np.ones(1, np.float32)}))
    # dtype field sits after: 4+4+8 + 8+1 + 8+4 = 37
    blob[37:41] = struct.pack("<i", 9)
    with pytest.raises(ValueError, match="dtype"):
        codec.loads(bytes(blob))


def test_atomic_save_leaves_no_tmp(tmp_path):
    path = str(tmp_path / "c.ckpt")
    codec.save(path, 1, 2, {"w": np.ones(3, np.float32)})
    assert os.path.exists(path)
    assert not os.path.exists(path + ".tmp")
    epoch, it, params = codec.load(path)
    assert (epoch, it) == (1, 2)


def make_core_with_params(iteration=0):
    core = ParameterServerCore(total_workers=1)
    core.initialize_parameters({"w": np.array([1.0, 2.0], np.float32)})
    if iteration:
        core.receive_gradients(0, iteration, {"w": np.zeros(2, np.float32)})
    return core


def test_manager_autosave_epoch_math(tmp_path):
    core = make_core_with_params(iteration=25)
    mgr = CheckpointManager(core, directory=str(tmp_path), checkpoint_interval=10)
    path = mgr.maybe_autosave()  # epoch = 25 // 10 = 2
    assert path and path.endswith(checkpoint_filename(2))
    assert os.path.exists(path)
    # no epoch advance -> no new save
    assert mgr.maybe_autosave() is None
    # advance past epoch 3
    core.receive_gradients(0, 31, {"w": np.zeros(2, np.float32)})
    path2 = mgr.maybe_autosave()
    assert path2 and path2.endswith(checkpoint_filename(3))


def test_manager_retention_keeps_newest(tmp_path):
    core = make_core_with_params()
    mgr = CheckpointManager(core, directory=str(tmp_path),
                            checkpoint_interval=1, keep=2)
    for epoch in range(5):
        mgr.save(epoch=epoch)
    remaining = sorted(os.listdir(tmp_path))
    # each kept checkpoint rides with its version meta sidecar (delta
    # serving monotonicity, ISSUE 10); retired epochs lose both files
    assert remaining == [checkpoint_filename(3),
                         checkpoint_filename(3) + ".meta.json",
                         checkpoint_filename(4),
                         checkpoint_filename(4) + ".meta.json"]
    assert mgr.latest().endswith(checkpoint_filename(4))


def test_manager_load_restores_core_and_optimizer(tmp_path):
    opt = Adam(0.1)
    core = ParameterServerCore(total_workers=1, optimizer=opt)
    core.initialize_parameters({"w": np.array([5.0], np.float32)})
    core.receive_gradients(0, 9, {"w": np.array([1.0], np.float32)})
    mgr = CheckpointManager(core, directory=str(tmp_path), checkpoint_interval=3)
    path = mgr.save()
    assert os.path.exists(path + ".opt.npz")

    core2 = ParameterServerCore(total_workers=1, optimizer=Adam(0.1))
    mgr2 = CheckpointManager(core2, directory=str(tmp_path), checkpoint_interval=3)
    epoch, it = mgr2.load(path)
    assert it == 9
    np.testing.assert_allclose(core2.get_parameters()["w"],
                               core.get_parameters()["w"])
    # identical post-restore updates => identical Adam trajectories
    core.receive_gradients(0, 10, {"w": np.array([1.0], np.float32)})
    core2.receive_gradients(0, 10, {"w": np.array([1.0], np.float32)})
    np.testing.assert_allclose(core2.get_parameters()["w"],
                               core.get_parameters()["w"])


def test_async_sharded_save_roundtrip(tmp_path, rng):
    """Async orbax save commits after wait_for_saves and restores exactly;
    latest_step never sees an in-flight tmp dir as a checkpoint."""
    import jax
    import jax.numpy as jnp

    from parameter_server_distributed_tpu.checkpoint import sharded as sc

    state = {"w": jnp.asarray(rng.standard_normal((16, 8)), jnp.float32),
             "step": jnp.asarray(7, jnp.int32)}
    path = sc.save_sharded(str(tmp_path), 7, state, asynchronous=True)
    sc.wait_for_saves()
    assert sc.latest_step(str(tmp_path)) == 7
    restored = sc.restore_sharded(path, template=state)
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(state["w"]))
    assert int(restored["step"]) == 7
