"""pst-eval (cli/eval_main.py): standalone checkpoint evaluation.

Driven as real subprocesses.  Contract: one JSON line with loss +
perplexity (LMs) or loss + accuracy (classifiers); a trained checkpoint
evaluates better than fresh init on its own training data."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest


def run_eval(*flags: str, timeout: float = 400.0) -> dict:
    env = dict(os.environ, PSDT_PLATFORM="cpu")
    proc = subprocess.run(
        [sys.executable, "-m",
         "parameter_server_distributed_tpu.cli.eval_main", *flags],
        capture_output=True, text=True, timeout=timeout, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_lm_perplexity_and_classifier_accuracy():
    out = run_eval("--model=tiny_lm", "--batch=4", "--steps=2")
    assert out["perplexity"] == pytest.approx(np.exp(out["loss"]), rel=1e-4)
    out2 = run_eval("--model=mnist_mlp", "--batch=16", "--steps=2")
    assert 0.0 <= out2["accuracy"] <= 1.0 and "perplexity" not in out2


def test_trained_checkpoint_beats_fresh_init(tmp_path):
    """Train briefly on a corpus, then pst-eval the checkpoint vs fresh
    init on the SAME corpus — the checkpoint must score lower loss."""
    import pathlib

    corpus = tmp_path / "c.txt"
    corpus.write_text((pathlib.Path(__file__).resolve().parents[1]
                       / "parameter_server_distributed_tpu/models/lora.py"
                       ).read_text())
    env = dict(os.environ, PSDT_PLATFORM="cpu")
    subprocess.run(
        [sys.executable, "-m",
         "parameter_server_distributed_tpu.cli.train_main",
         "--model=tiny_lm", "--batch=8", "--steps=30", f"--data={corpus}",
         "--optimizer=adamw", "--lr=3e-3",
         f"--ckpt-dir={tmp_path}/ckpt", "--ckpt-every=30"],
        check=True, capture_output=True, text=True, timeout=400, env=env)
    trained = run_eval("--model=tiny_lm", f"--data={corpus}",
                       f"--ckpt-dir={tmp_path}/ckpt", "--batch=8",
                       "--steps=4")
    fresh = run_eval("--model=tiny_lm", f"--data={corpus}", "--batch=8",
                     "--steps=4")
    assert trained["loss"] < fresh["loss"]
    assert trained["perplexity"] < fresh["perplexity"]


def test_eval_hf_checkpoint(tmp_path, capsys):
    """pst-eval --hf-gpt2: loss/perplexity of a converted transformers
    checkpoint — the eval leg of the converted-model CLI suite."""
    import torch
    import transformers

    from parameter_server_distributed_tpu.cli.eval_main import main

    torch.manual_seed(0)
    checkout = tmp_path / "hf"
    transformers.GPT2LMHeadModel(transformers.GPT2Config(
        vocab_size=96, n_positions=32, n_embd=32, n_layer=2,
        n_head=2)).save_pretrained(checkout)
    rc = main([f"--hf-gpt2={checkout}", "--batch=4", "--steps=2"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["model"].startswith("hf-gpt2:")
    assert np.isfinite(out["loss"]) and out["perplexity"] > 1.0

    with pytest.raises(SystemExit, match="defines model"):
        main([f"--hf-gpt2={checkout}", "--model=small_lm"])
    # checkpoint-loading flags are meaningless here — rejected, not
    # silently ignored
    with pytest.raises(SystemExit, match="lora-alpha"):
        main([f"--hf-gpt2={checkout}", "--lora-alpha=16"])
