"""Elastic scaling end-to-end: barrier width follows the live registry,
with no PS restart (the reference restarts the PS and loses its in-memory
parameters on every scale event — scripts/scale_workers.sh:137-144)."""

import threading

import numpy as np
import pytest

from parameter_server_distributed_tpu.cli.worker_main import build_worker
from parameter_server_distributed_tpu.config import (CoordinatorConfig,
                                                     ParameterServerConfig,
                                                     WorkerConfig)
from parameter_server_distributed_tpu.server.coordinator_service import Coordinator
from parameter_server_distributed_tpu.server.ps_service import ParameterServer


@pytest.fixture
def elastic_cluster(tmp_path):
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0,
        ps_address="127.0.0.1", ps_port=1, reap_period_s=600.0))
    coord_port = coordinator.start()
    ps = ParameterServer(
        ParameterServerConfig(
            bind_address="127.0.0.1", port=0, total_workers=99,
            checkpoint_interval=100, checkpoint_dir=str(tmp_path),
            learning_rate=0.05, elastic=True, live_workers_ttl_s=0.0,
            autosave_period_s=600.0),
        live_workers_fn=coordinator.core.live_worker_count)
    ps_port = ps.start()
    # late-bind the PS address the coordinator hands out
    coordinator.core.set_parameter_server_address("127.0.0.1", ps_port)
    yield ps, coordinator, coord_port
    coordinator.stop()
    ps.stop()


def _worker(coord_port, wid):
    w = build_worker(WorkerConfig(
        coordinator_address=f"127.0.0.1:{coord_port}", worker_id=wid,
        address="127.0.0.1", port=50080 + wid, batch_size=16,
        heartbeat_period_s=600.0))
    w.initialize()
    return w


def test_scale_down_without_ps_restart(elastic_cluster):
    ps, coordinator, coord_port = elastic_cluster
    w0, w1 = _worker(coord_port, 0), _worker(coord_port, 1)
    try:
        # both run 3 lockstep iterations at barrier width 2
        done = []

        def loop(w):
            for it in range(3):
                w.run_iteration(it)
            done.append(w.config.worker_id)

        threads = [threading.Thread(target=loop, args=(w,)) for w in (w0, w1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert sorted(done) == [0, 1]
        params_before = ps.core.get_parameters()
        assert params_before  # PS holds state

        # worker 1 leaves; since ISSUE 13 its shutdown announces a
        # graceful membership LEAVE, so it is deregistered immediately —
        # the reap finds nothing of it to evict; w0 continues ALONE at
        # the same PS (barrier shrank 2 -> 1, params preserved)
        w1.shutdown()
        assert coordinator.core.live_worker_count() == 1
        evicted = coordinator.core.remove_stale_workers(timeout_s=-1)
        assert 1 not in evicted  # already gone via the leave announce
        coordinator.core.register_worker(0, "127.0.0.1", 50080, "h0")
        for it in range(3, 5):
            w0.run_iteration(it)
        assert ps.core.current_iteration == 4
    finally:
        w0.shutdown()


def test_scale_up_widens_barrier(elastic_cluster):
    ps, coordinator, coord_port = elastic_cluster
    w0 = _worker(coord_port, 0)
    try:
        w0.run_iteration(0)  # bootstrap alone (barrier 1)
        w0.run_iteration(1)
        # scale up: worker 2 joins -> barrier width 2
        w2 = _worker(coord_port, 2)
        try:
            results = {}

            def loop(w, start):
                for it in range(start, start + 2):
                    results.setdefault(w.config.worker_id, []).append(
                        w.run_iteration(it))

            t0 = threading.Thread(target=loop, args=(w0, 2))
            t2 = threading.Thread(target=loop, args=(w2, 2))
            t0.start(); t2.start()
            t0.join(timeout=60); t2.join(timeout=60)
            assert len(results[0]) == 2 and len(results[2]) == 2
            # barrier now requires both: a lone push at iteration 99 parks
            r = ps.core.receive_gradients(0, 99, {
                k: np.zeros_like(v) for k, v in
                ps.core.get_parameters().items()})
            assert not r.aggregation_complete and r.total_workers == 2
        finally:
            w2.shutdown()
    finally:
        w0.shutdown()


# --------------------------------------------------- core-level churn tests
# (no gRPC: ParameterServerCore + a fake registry, exercising the elastic
# barrier machinery of core/ps_core.py:122-137 directly)

class _Registry:
    """Fake live-worker provider counting how often the PS queries it."""

    def __init__(self, live=2):
        self.live = live
        self.calls = 0

    def __call__(self):
        self.calls += 1
        return self.live


def _core(registry, ttl=0.0, total=99):
    from parameter_server_distributed_tpu.core.ps_core import (
        ParameterServerCore)
    from parameter_server_distributed_tpu.core.optimizer import SGD

    core = ParameterServerCore(total_workers=total, optimizer=SGD(1.0),
                               live_workers_fn=registry,
                               live_workers_ttl_s=ttl)
    core.initialize_parameters({"w": np.array([4.0], np.float32)})
    return core


def test_live_workers_ttl_caches_provider_calls():
    """barrier_width() is read on every push and 20 Hz sync poll; with a
    TTL the provider (a remote registry RPC in production) is hit once per
    window, and a width change only becomes visible after expiry."""
    reg = _Registry(live=2)
    core = _core(reg, ttl=60.0)
    assert core.barrier_width() == 2
    for _ in range(50):
        core.barrier_width()
    assert reg.calls == 1  # cached for the whole window
    reg.live = 5
    assert core.barrier_width() == 2  # stale until expiry
    core._live_cache = (core._live_cache[0], 0.0)  # force expiry
    assert core.barrier_width() == 5
    assert reg.calls == 2


def test_registry_flap_to_zero_falls_back_to_static_width():
    """A coordinator outage (live count 0) must not collapse the barrier
    to zero width — the static total_workers is the fallback."""
    reg = _Registry(live=2)
    core = _core(reg, total=7)
    assert core.barrier_width() == 2
    reg.live = 0
    assert core.barrier_width() == 7  # static fallback, not 0
    reg.live = 2
    assert core.barrier_width() == 2  # recovers with the registry


def test_shrink_mid_barrier_releases_parked_iteration():
    """Worker 0 pushes at width 2, then worker 1 is evicted: the next sync
    poll re-reads the width and fires the barrier with the one real
    contributor (elastic release — nothing strands)."""
    reg = _Registry(live=2)
    core = _core(reg)
    r = core.receive_gradients(0, 1, {"w": np.array([1.0], np.float32)})
    assert not r.aggregation_complete
    reg.live = 1  # eviction
    _, ready, received, total = core.check_sync_status(1)
    assert ready and received == 1 and total == 1
    np.testing.assert_allclose(core.get_parameters()["w"], [3.0])


def test_grow_mid_barrier_parks_until_all_new_workers_push():
    """Width grows 1 -> 3 while an iteration is buffered: the barrier now
    waits for the larger contributor set, then aggregates the mean over
    ALL three pushes."""
    reg = _Registry(live=1)
    core = _core(reg)
    reg.live = 3  # scale-up lands before the push is aggregated... but
    # worker 0 already computed against width-1 expectations
    r0 = core.receive_gradients(0, 1, {"w": np.array([3.0], np.float32)})
    assert not r0.aggregation_complete and r0.total_workers == 3
    _, ready, _, _ = core.check_sync_status(1)
    assert not ready
    core.receive_gradients(1, 1, {"w": np.array([3.0], np.float32)})
    r2 = core.receive_gradients(2, 1, {"w": np.array([3.0], np.float32)})
    assert r2.aggregation_complete and r2.workers_received == 3
    np.testing.assert_allclose(core.get_parameters()["w"], [1.0])


def test_reap_generation_invalidates_width_cache_immediately():
    """ISSUE 13 satellite: a reaped worker used to shrink the barrier
    only when live_workers_ttl_s lapsed.  A generation-aware provider
    (``.generation`` attribute) invalidates the single-flight TTL cache
    the instant the registry generation moves."""

    class GenRegistry:
        def __init__(self):
            self.live = 2
            self.gen = 0
            self.calls = 0

        def __call__(self):
            self.calls += 1
            return self.live

        def generation(self):
            return self.gen

    from parameter_server_distributed_tpu.core.optimizer import SGD
    from parameter_server_distributed_tpu.core.ps_core import (
        ParameterServerCore)

    reg = GenRegistry()
    core = ParameterServerCore(total_workers=99, optimizer=SGD(1.0),
                               live_workers_fn=reg,
                               live_workers_ttl_s=3600.0)
    assert core.barrier_width() == 2
    for _ in range(20):
        core.barrier_width()
    assert reg.calls == 1  # TTL cache, same generation
    # eviction: generation bump makes the NEXT width read refresh —
    # no TTL lapse, no manual cache poke
    reg.live = 1
    reg.gen += 1
    assert core.barrier_width() == 1
    assert reg.calls == 2


def test_coordinator_width_provider_reflects_eviction_without_ttl():
    """CoordinatorCore.width_provider(): the in-process generation-aware
    provider — a reap narrows a long-TTL barrier immediately."""
    from parameter_server_distributed_tpu.core.coordinator_core import (
        CoordinatorCore)

    coord = CoordinatorCore("127.0.0.1", 1)
    coord.register_worker(0, "127.0.0.1", 50080, "h0")
    coord.register_worker(1, "127.0.0.1", 50081, "h1")
    core = _core(coord.width_provider(), ttl=3600.0)
    assert core.barrier_width() == 2
    coord.register_worker(0, "127.0.0.1", 50080, "h0")  # heartbeat upsert
    assert core.barrier_width() == 2  # re-registration: no live change
    evicted = coord.remove_stale_workers(timeout_s=-1)
    assert sorted(evicted) == [0, 1]
    assert core.barrier_width() == 99  # live 0 -> static fallback, NOW
    coord.register_worker(2, "127.0.0.1", 50082, "h2")
    assert core.barrier_width() == 1


def test_membership_epoch_transitions():
    """Membership is epoch-numbered: every JOINING/ACTIVE/DRAINING/GONE
    transition bumps the epoch; no-op announces do not."""
    from parameter_server_distributed_tpu.core.coordinator_core import (
        CoordinatorCore)
    from parameter_server_distributed_tpu.elastic import messages as emsg

    coord = CoordinatorCore("127.0.0.1", 1)
    epoch0, entries = coord.membership()
    assert entries == []
    coord.register_worker(0, "127.0.0.1", 50080, "h0")
    assert coord.member_state(0) == emsg.MEMBER_JOINING
    e1, _ = coord.membership()
    assert e1 == epoch0 + 1
    coord.member_join(0)
    assert coord.member_state(0) == emsg.MEMBER_ACTIVE
    e2, _ = coord.membership()
    assert e2 == e1 + 1
    coord.member_join(0)  # duplicate announce: no transition
    assert coord.membership()[0] == e2
    assert coord.drain_worker(0)
    assert coord.member_state(0) == emsg.MEMBER_DRAINING
    # DRAINING keeps the registry entry — the in-flight iteration's
    # barrier slot survives until the worker leaves
    assert coord.live_worker_count() == 1
    assert coord.deregister_worker(0)
    assert coord.member_state(0) == emsg.MEMBER_GONE
    assert coord.live_worker_count() == 0
    # draining an unknown/gone worker is refused
    assert not coord.drain_worker(0)
    assert not coord.drain_worker(42)
    # rejoin after GONE: back through JOINING
    coord.register_worker(0, "127.0.0.1", 50080, "h0")
    assert coord.member_state(0) == emsg.MEMBER_JOINING


def test_reap_marks_member_gone():
    from parameter_server_distributed_tpu.core.coordinator_core import (
        CoordinatorCore)
    from parameter_server_distributed_tpu.elastic import messages as emsg

    coord = CoordinatorCore("127.0.0.1", 1)
    coord.register_worker(0, "127.0.0.1", 50080, "h0")
    coord.member_join(0)
    gen = coord.registry_generation()
    assert coord.remove_stale_workers(timeout_s=-1) == [0]
    assert coord.member_state(0) == emsg.MEMBER_GONE
    assert coord.registry_generation() == gen + 1


def test_membership_rpc_roundtrip_and_ctl_drain(elastic_cluster):
    """UpdateMembership over real gRPC: join announce, pst-ctl drain
    visible to the worker's poll, graceful leave narrowing the live
    count immediately (no reap, no TTL)."""
    from parameter_server_distributed_tpu.elastic import messages as emsg
    from parameter_server_distributed_tpu.elastic.membership import (
        MembershipClient)

    ps, coordinator, coord_port = elastic_cluster
    addr = f"127.0.0.1:{coord_port}"
    coordinator.core.register_worker(7, "127.0.0.1", 50087, "h7")
    client = MembershipClient(addr, worker_id=7)
    try:
        resp = client.join()
        assert resp is not None and client.supported
        assert resp.self_state == emsg.MEMBER_ACTIVE
        assert [(e.worker_id, e.state) for e in resp.entries] == [
            (7, emsg.MEMBER_ACTIVE)]

        # pst-ctl path: a second client drains worker 7
        ctl = MembershipClient(addr)
        try:
            dresp = ctl.drain(7)
            assert dresp is not None and dresp.success
        finally:
            ctl.close()
        assert client.poll_state() == emsg.MEMBER_DRAINING
        assert coordinator.core.live_worker_count() == 1

        # graceful leave: registry narrows NOW
        lresp = client.leave()
        assert lresp is not None
        assert coordinator.core.live_worker_count() == 0
        assert coordinator.core.member_state(7) == emsg.MEMBER_GONE
    finally:
        client.close()


def test_ctl_main_drain_and_members(elastic_cluster, capsys):
    from parameter_server_distributed_tpu.cli.ctl_main import main as ctl_main

    _ps, coordinator, coord_port = elastic_cluster
    addr = f"127.0.0.1:{coord_port}"
    coordinator.core.register_worker(3, "127.0.0.1", 50083, "h3")
    coordinator.core.member_join(3)
    assert ctl_main(["members", addr]) == 0
    out = capsys.readouterr().out
    assert "worker 3: active" in out
    assert ctl_main(["drain", "3", addr]) == 0
    out = capsys.readouterr().out
    assert "draining" in out
    assert ctl_main(["drain", "99", addr]) == 1  # unknown worker
    assert ctl_main([]) == 2


def test_worker_drain_and_leave_shrinks_barrier(elastic_cluster):
    """Graceful preemption end to end: request_drain() stops the run
    loop between iterations, shutdown() announces leave, and the next
    barrier closes at the narrowed width with no reap involved."""
    ps, coordinator, coord_port = elastic_cluster
    w0, w1 = _worker(coord_port, 0), _worker(coord_port, 1)
    try:
        done = []

        def loop(w):
            for it in range(2):
                w.run_iteration(it)
            done.append(w.config.worker_id)

        threads = [threading.Thread(target=loop, args=(w,)) for w in (w0, w1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert sorted(done) == [0, 1]

        # drain worker 1: the run loop would stop before the next
        # iteration; shutdown() announces leave (deregistration)
        w1.request_drain()
        assert w1.drain_requested
        w1.run(iterations=5)  # drain latched: runs ZERO iterations
        assert w1.iteration == 1
        w1.shutdown()
        assert coordinator.core.live_worker_count() == 1
        from parameter_server_distributed_tpu.elastic import messages as emsg
        assert coordinator.core.member_state(1) == emsg.MEMBER_GONE
        # w0 continues alone at the same PS: barrier narrowed 2 -> 1
        for it in range(2, 4):
            w0.run_iteration(it)
        assert ps.core.current_iteration == 3
    finally:
        w0.shutdown()


def test_churn_register_evict_reregister_with_ttl():
    """Registry churn under a TTL: evict + rejoin inside one window is
    invisible (cached width), and the width settles once the window
    rolls — barrier semantics stay consistent throughout."""
    reg = _Registry(live=2)
    core = _core(reg, ttl=60.0)
    assert core.barrier_width() == 2
    reg.live = 1   # flap down...
    reg.live = 2   # ...and straight back up within the TTL window
    assert core.barrier_width() == 2 and reg.calls == 1
    # worker 1 leaves for real; window rolls; a parked push releases
    reg.live = 1
    core.receive_gradients(0, 1, {"w": np.array([1.0], np.float32)})
    core._live_cache = (core._live_cache[0], 0.0)  # window expiry
    _, ready, received, total = core.check_sync_status(1)
    assert ready and received == 1 and total == 1
