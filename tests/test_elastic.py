"""Elastic scaling end-to-end: barrier width follows the live registry,
with no PS restart (the reference restarts the PS and loses its in-memory
parameters on every scale event — scripts/scale_workers.sh:137-144)."""

import threading

import numpy as np
import pytest

from parameter_server_distributed_tpu.cli.worker_main import build_worker
from parameter_server_distributed_tpu.config import (CoordinatorConfig,
                                                     ParameterServerConfig,
                                                     WorkerConfig)
from parameter_server_distributed_tpu.server.coordinator_service import Coordinator
from parameter_server_distributed_tpu.server.ps_service import ParameterServer


@pytest.fixture
def elastic_cluster(tmp_path):
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0,
        ps_address="127.0.0.1", ps_port=1, reap_period_s=600.0))
    coord_port = coordinator.start()
    ps = ParameterServer(
        ParameterServerConfig(
            bind_address="127.0.0.1", port=0, total_workers=99,
            checkpoint_interval=100, checkpoint_dir=str(tmp_path),
            learning_rate=0.05, elastic=True, live_workers_ttl_s=0.0,
            autosave_period_s=600.0),
        live_workers_fn=coordinator.core.live_worker_count)
    ps_port = ps.start()
    # late-bind the PS address the coordinator hands out
    coordinator.core.set_parameter_server_address("127.0.0.1", ps_port)
    yield ps, coordinator, coord_port
    coordinator.stop()
    ps.stop()


def _worker(coord_port, wid):
    w = build_worker(WorkerConfig(
        coordinator_address=f"127.0.0.1:{coord_port}", worker_id=wid,
        address="127.0.0.1", port=50080 + wid, batch_size=16,
        heartbeat_period_s=600.0))
    w.initialize()
    return w


def test_scale_down_without_ps_restart(elastic_cluster):
    ps, coordinator, coord_port = elastic_cluster
    w0, w1 = _worker(coord_port, 0), _worker(coord_port, 1)
    try:
        # both run 3 lockstep iterations at barrier width 2
        done = []

        def loop(w):
            for it in range(3):
                w.run_iteration(it)
            done.append(w.config.worker_id)

        threads = [threading.Thread(target=loop, args=(w,)) for w in (w0, w1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert sorted(done) == [0, 1]
        params_before = ps.core.get_parameters()
        assert params_before  # PS holds state

        # worker 1 leaves; coordinator evicts it; w0 continues ALONE at the
        # same PS (barrier shrank 2 -> 1, params preserved)
        w1.shutdown()
        evicted = coordinator.core.remove_stale_workers(timeout_s=-1)
        assert 1 in evicted
        coordinator.core.register_worker(0, "127.0.0.1", 50080, "h0")
        for it in range(3, 5):
            w0.run_iteration(it)
        assert ps.core.current_iteration == 4
    finally:
        w0.shutdown()


def test_scale_up_widens_barrier(elastic_cluster):
    ps, coordinator, coord_port = elastic_cluster
    w0 = _worker(coord_port, 0)
    try:
        w0.run_iteration(0)  # bootstrap alone (barrier 1)
        w0.run_iteration(1)
        # scale up: worker 2 joins -> barrier width 2
        w2 = _worker(coord_port, 2)
        try:
            results = {}

            def loop(w, start):
                for it in range(start, start + 2):
                    results.setdefault(w.config.worker_id, []).append(
                        w.run_iteration(it))

            t0 = threading.Thread(target=loop, args=(w0, 2))
            t2 = threading.Thread(target=loop, args=(w2, 2))
            t0.start(); t2.start()
            t0.join(timeout=60); t2.join(timeout=60)
            assert len(results[0]) == 2 and len(results[2]) == 2
            # barrier now requires both: a lone push at iteration 99 parks
            r = ps.core.receive_gradients(0, 99, {
                k: np.zeros_like(v) for k, v in
                ps.core.get_parameters().items()})
            assert not r.aggregation_complete and r.total_workers == 2
        finally:
            w2.shutdown()
    finally:
        w0.shutdown()
