"""Weight-only int8 serving quantization (models/quant.py).

The contract: a quantized store flows through the existing model code —
forward, both layer layouts, KV-cached decode, sampling — with bounded
numerical error, and the QTensor pytree composes with jit/scan/slicing.
The reference has no quantized path at all (f32 `repeated float` end to
end — reference proto/parameter_server.proto:19-24); these tests pin the
added capability's correctness.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_distributed_tpu.models.generation import (
    generate, prefill, decode_step)
from parameter_server_distributed_tpu.models.quant import (
    QTensor, quantize, quantize_params, store_bytes, wdot)
from parameter_server_distributed_tpu.models.transformer import (
    Transformer, TransformerConfig)


def tiny(scan_layers=False, kv_heads=None):
    return Transformer(TransformerConfig(
        vocab=96, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq=64, dtype=jnp.float32, scan_layers=scan_layers,
        **({"n_kv_heads": kv_heads} if kv_heads else {})))


def test_quantize_roundtrip_error_bound(rng):
    w = jnp.asarray(rng.normal(size=(128, 256)), jnp.float32)
    qt = quantize(w)
    assert qt.q.dtype == jnp.int8 and qt.scale.shape == (256,)
    # symmetric absmax/127: per-channel error is at most half a step
    step = np.asarray(qt.scale)
    err = np.abs(np.asarray(qt.dequant()) - np.asarray(w))
    assert (err <= step[None, :] * 0.5 + 1e-7).all()


def test_quantize_zero_channel_is_safe():
    w = jnp.zeros((16, 4), jnp.float32)
    qt = quantize(w)
    assert np.asarray(qt.scale).all() > 0  # no div-by-zero sentinel left
    np.testing.assert_array_equal(np.asarray(qt.dequant()), 0.0)


def test_wdot_matches_dequant_dot(rng):
    x = jnp.asarray(rng.normal(size=(8, 128)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(128, 64)), jnp.float32)
    qt = quantize(w)
    got = wdot(x, qt)
    want = jnp.dot(x, qt.dequant())
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_wdot_passthrough_dense(rng):
    x = jnp.asarray(rng.normal(size=(4, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, 8)), jnp.float32)
    np.testing.assert_array_equal(np.asarray(wdot(x, w)),
                                  np.asarray(jnp.dot(
                                      x, w,
                                      preferred_element_type=jnp.float32)))


def test_qtensor_is_a_pytree_and_slices():
    qt = quantize(jnp.ones((3, 16, 8), jnp.float32))
    leaves = jax.tree_util.tree_leaves(qt)
    assert len(leaves) == 2
    sliced = qt[1]
    assert sliced.q.shape == (16, 8) and sliced.scale.shape == (8,)
    rebuilt = jax.tree_util.tree_map(lambda x: x, qt)
    assert isinstance(rebuilt, QTensor)


@pytest.mark.parametrize("scan_layers", [False, True],
                         ids=["unrolled", "scan"])
def test_quantized_logits_track_full_precision(rng, scan_layers):
    model = tiny(scan_layers=scan_layers)
    params = model.init_params(0)
    qparams = quantize_params(params)
    # weight matrices quantized in the right layout, rest untouched
    key = "blocks/attn/wq" if scan_layers else "layer0/attn/wq"
    assert isinstance(qparams[key], QTensor)
    assert not isinstance(qparams["embed/tok"], QTensor)
    assert not isinstance(qparams["final_ln/scale"], QTensor)
    toks = jnp.asarray(rng.integers(0, 96, (2, 16)), jnp.int32)
    lf = model.apply(params, toks)
    lq = model.apply(qparams, toks)
    cos = float(jnp.sum(lf * lq)
                / (jnp.linalg.norm(lf) * jnp.linalg.norm(lq)))
    assert cos > 0.999, cos


def test_quantized_cached_decode_matches_quantized_full_forward(rng):
    """The cache-correctness invariant holds for a quantized store too:
    cached decode must equal the quantized model's full re-forward."""
    model = tiny(scan_layers=True)
    qparams = quantize_params(model.init_params(0))
    prompt = jnp.asarray(rng.integers(0, 96, (2, 8)), jnp.int32)
    toks = prompt
    expected = []
    for _ in range(5):
        nxt = jnp.argmax(model.apply(qparams, toks)[:, -1], -1)
        expected.append(nxt.astype(jnp.int32))
        toks = jnp.concatenate([toks, nxt[:, None].astype(jnp.int32)], 1)
    got = generate(model, qparams, prompt, 5)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(jnp.stack(expected, 1)))


def test_quantized_gqa_decode_runs(rng):
    model = tiny(kv_heads=2)
    qparams = quantize_params(model.init_params(0))
    prompt = jnp.asarray(rng.integers(0, 96, (2, 8)), jnp.int32)
    logits, cache = prefill(model, qparams, prompt, 32)
    logits2, cache2 = decode_step(
        model, qparams, jnp.argmax(logits, -1).astype(jnp.int32), cache)
    assert bool(jnp.all(jnp.isfinite(logits2)))
    assert int(cache2.length) == 9


def test_int8_kv_cache_decode_tracks_fp_cache(rng):
    """QuantKVCache (generation.py): per-step logits error is bounded and
    prefill logits are bit-identical (the cache isn't read during
    prefill)."""
    from parameter_server_distributed_tpu.models.generation import (
        QuantKVCache)
    model = tiny(scan_layers=True)
    params = model.init_params(0)
    prompt = jnp.asarray(rng.integers(0, 96, (2, 8)), jnp.int32)
    lf, cf = prefill(model, params, prompt, 32)
    lq, cq = prefill(model, params, prompt, 32, cache_dtype="int8")
    assert isinstance(cq, QuantKVCache) and cq.k.dtype == jnp.int8
    np.testing.assert_array_equal(np.asarray(lf), np.asarray(lq))
    tok = jnp.argmax(lf, -1).astype(jnp.int32)
    sf, _ = decode_step(model, params, tok, cf)
    sq, cq2 = decode_step(model, params, tok, cq)
    rel = (np.max(np.abs(np.asarray(sf) - np.asarray(sq)))
           / np.max(np.abs(np.asarray(sf))))
    assert rel < 0.05, rel
    assert int(cq2.length) == 9


def test_int8_kv_cache_generate_runs_and_composes_with_weight_quant(rng):
    model = tiny()
    qparams = quantize_params(model.init_params(0))
    prompt = jnp.asarray(rng.integers(0, 96, (2, 8)), jnp.int32)
    out = generate(model, qparams, prompt, 5, cache_dtype="int8")
    assert out.shape == (2, 5)
    assert bool((np.asarray(out) >= 0).all())
    # deterministic: same runner, same inputs
    out2 = generate(model, qparams, prompt, 5, cache_dtype="int8")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


def test_int8_kv_cache_gqa(rng):
    """Value-checks the GQA scale folding: with kv_heads < n_heads the
    k/v scales broadcast over query-head groups — a transposed axis there
    yields finite-but-wrong logits, so bound the per-step error."""
    model = tiny(kv_heads=2)
    params = model.init_params(0)
    prompt = jnp.asarray(rng.integers(0, 96, (2, 6)), jnp.int32)
    lf, cf = prefill(model, params, prompt, 16)
    lq, cq = prefill(model, params, prompt, 16, cache_dtype="int8")
    tok = jnp.argmax(lf, -1).astype(jnp.int32)
    sf, _ = decode_step(model, params, tok, cf)
    sq, _ = decode_step(model, params, tok, cq)
    rel = (np.max(np.abs(np.asarray(sf) - np.asarray(sq)))
           / np.max(np.abs(np.asarray(sf))))
    assert rel < 0.05, rel
    out_fp = generate(model, params, prompt, 4)
    out_q8 = generate(model, params, prompt, 4, cache_dtype="int8")
    assert out_q8.shape == out_fp.shape


def test_int8_kv_cache_speculative_matches_int8_greedy(rng):
    """Perfect self-draft speculative decoding with int8 caches stays
    token-exact vs int8-cache greedy decoding: K/V depend only on (token,
    position, params), so ragged block writes and single-step writes
    quantize identically."""
    from parameter_server_distributed_tpu.models.generation import (
        speculative_generate_batched)
    model = tiny()
    params = model.init_params(0)
    prompt = jnp.asarray(rng.integers(0, 96, (2, 6)), jnp.int32)
    greedy = generate(model, params, prompt, 6, cache_dtype="int8")
    spec, stats = speculative_generate_batched(
        model, params, model, params, prompt, 6, draft_len=2,
        cache_dtype="int8")
    np.testing.assert_array_equal(np.asarray(spec), np.asarray(greedy))
    assert stats["draft_accept_rate"] == 1.0


def test_store_bytes_reports_shrink():
    model = tiny()
    params = {k: (v.astype(jnp.bfloat16) if v.ndim >= 2 else v)
              for k, v in model.init_params(0).items()}
    as_is, dense = store_bytes(quantize_params(params))
    assert as_is < dense  # int8 + f32 scales < bf16 matrices
