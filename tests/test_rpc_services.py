"""RPC-level tests: drive the two gRPC services over real sockets."""

import numpy as np
import pytest

from parameter_server_distributed_tpu.config import (CoordinatorConfig,
                                                     ParameterServerConfig)
from parameter_server_distributed_tpu.rpc import messages as m
from parameter_server_distributed_tpu.rpc.service import RpcClient
from parameter_server_distributed_tpu.server.coordinator_service import Coordinator
from parameter_server_distributed_tpu.server.ps_service import ParameterServer


@pytest.fixture
def ps(tmp_path):
    server = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=2,
        checkpoint_interval=2, checkpoint_dir=str(tmp_path),
        learning_rate=1.0, autosave_period_s=60.0))
    port = server.start()
    yield server, port
    server.stop()


@pytest.fixture
def coordinator():
    server = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0,
        ps_address="10.1.2.3", ps_port=50051, reap_period_s=60.0))
    port = server.start()
    yield server, port
    server.stop()


def ps_client(port):
    return RpcClient(f"127.0.0.1:{port}", m.PARAMETER_SERVER_SERVICE,
                     m.PARAMETER_SERVER_METHODS)


def coord_client(port):
    return RpcClient(f"127.0.0.1:{port}", m.COORDINATOR_SERVICE,
                     m.COORDINATOR_METHODS)


def test_push_pull_sync_over_wire(ps):
    server, port = ps
    server.core.initialize_parameters({"w": np.array([1.0, 2.0], np.float32)})
    with ps_client(port) as client:
        # pull
        resp = client.call("ServeParameters", m.PullRequest(worker_id=0, iteration=1))
        assert resp.ready
        np.testing.assert_allclose(resp.parameters[0].to_array(), [1.0, 2.0])
        # push worker 0: barrier incomplete
        grads = [m.Tensor.from_array("w", np.array([0.5, 0.5], np.float32))]
        push = client.call("ReceiveGradients",
                           m.GradientUpdate(worker_id=0, iteration=1, gradients=grads))
        assert push.success and not push.aggregation_complete
        assert push.workers_received == 1 and push.total_workers == 2
        # sync poll: not ready
        sync = client.call("CheckSyncStatus", m.SyncStatusRequest(iteration=1))
        assert not sync.ready
        # push worker 1: aggregation fires
        push2 = client.call("ReceiveGradients",
                            m.GradientUpdate(worker_id=1, iteration=1, gradients=grads))
        assert push2.aggregation_complete
        sync2 = client.call("CheckSyncStatus", m.SyncStatusRequest(iteration=1))
        assert sync2.ready and sync2.workers_received == 2
        # params moved by lr=1.0 * mean([0.5,0.5])
        resp2 = client.call("ServeParameters", m.PullRequest(worker_id=0, iteration=2))
        np.testing.assert_allclose(resp2.parameters[0].to_array(), [0.5, 1.5])


def test_checkpoint_save_load_over_wire(ps, tmp_path):
    server, port = ps
    server.core.initialize_parameters({"w": np.array([3.0], np.float32)})
    with ps_client(port) as client:
        save = client.call("SaveCheckpoint",
                           m.SaveCheckpointRequest(epoch=7, path=""))
        assert save.success, save.message
        assert "checkpoint_epoch_7.ckpt" in save.checkpoint_path
        # mutate params, then restore
        server.core.initialize_parameters({"w": np.array([-99.0], np.float32)})
        load = client.call("LoadCheckpoint",
                           m.LoadCheckpointRequest(path=save.checkpoint_path))
        assert load.success and load.epoch == 7
        np.testing.assert_allclose(load.parameters[0].to_array(), [3.0])
        np.testing.assert_allclose(server.core.get_parameters()["w"], [3.0])


def test_load_checkpoint_missing_file_reports_failure(ps):
    server, port = ps
    with ps_client(port) as client:
        load = client.call("LoadCheckpoint",
                           m.LoadCheckpointRequest(path="/nonexistent/x.ckpt"))
        assert not load.success and load.message


def test_coordinator_register_discover_heartbeat_list(coordinator):
    server, port = coordinator
    with coord_client(port) as client:
        addr = client.call("GetParameterServerAddress", m.GetPSAddressRequest())
        assert (addr.address, addr.port) == ("10.1.2.3", 50051)
        reg = client.call("RegisterWorker",
                          m.WorkerInfo(worker_id=0, address="127.0.0.1",
                                       port=50060, hostname="h0"))
        assert reg.success and reg.total_workers == 1
        assert reg.parameter_server_address == "10.1.2.3:50051"
        hb = client.call("Heartbeat",
                         m.HeartbeatRequest(worker_id=0,
                                            status=m.WorkerStatus.TRAINING))
        assert hb.success and hb.timestamp > 0
        unknown = client.call("Heartbeat",
                              m.HeartbeatRequest(worker_id=42,
                                                 status=m.WorkerStatus.IDLE))
        assert not unknown.success
        lst = client.call("ListWorkers", m.ListWorkersRequest())
        assert lst.total_workers == 1
        assert lst.workers[0].worker_id == 0 and lst.workers[0].port == 50060


def test_serve_parameters_in_requested_wire_dtype(ps):
    """PullRequest.wire_dtype (framework extension) selects the payload
    encoding; default stays reference-compatible repeated-float."""
    server, port = ps
    w = np.linspace(-2, 2, 1024).astype(np.float32)
    server.core.initialize_parameters({"w": w})
    with ps_client(port) as client:
        plain = client.call("ServeParameters",
                            m.PullRequest(worker_id=0, iteration=0))
        packed = client.call("ServeParameters",
                             m.PullRequest(worker_id=0, iteration=0,
                                           wire_dtype=m.WIRE_BF16))
        t_plain, t_packed = plain.parameters[0], packed.parameters[0]
        assert t_plain.packed_dtype == m.WIRE_F32 and not t_plain.packed
        assert t_packed.packed_dtype == m.WIRE_BF16
        assert len(t_packed.encode()) < len(t_plain.encode()) * 0.55
        # linspace over [-2,2] at 1024 points is bf16-representable enough
        np.testing.assert_allclose(t_packed.to_array(), w, rtol=8e-3)
        # pushes in bf16 aggregate fine (PS decodes transparently)
        grads = [m.Tensor.from_array("w", np.full_like(w, 0.25),
                                     wire_dtype=m.WIRE_BF16)]
        for wid in (0, 1):
            push = client.call("ReceiveGradients",
                               m.GradientUpdate(worker_id=wid, iteration=1,
                                                gradients=grads))
        assert push.aggregation_complete
        after = client.call("ServeParameters",
                            m.PullRequest(worker_id=0, iteration=1))
        np.testing.assert_allclose(after.parameters[0].to_array(), w - 0.25,
                                   rtol=1e-2, atol=1e-3)


def test_lossy_pull_requests_served_bf16(ps):
    """The lossy gradient-push encodings must never apply to SERVED
    parameters: a client asking to pull int8/topk gets bf16 — enforced
    server-side so a misconfigured client cannot receive sparsified
    (99%-zeroed) weights."""
    server, port = ps
    w = np.linspace(-2, 2, 1024).astype(np.float32)
    server.core.initialize_parameters({"w": w})
    with ps_client(port) as client:
        for lossy in (m.WIRE_INT8, m.WIRE_TOPK):
            resp = client.call("ServeParameters",
                               m.PullRequest(worker_id=0, iteration=0,
                                             wire_dtype=lossy))
            t = resp.parameters[0]
            assert t.packed_dtype == m.WIRE_BF16
            np.testing.assert_allclose(t.to_array(), w, rtol=8e-3)


# ---------------------------------------------------------------- streaming
# Chunk-stream data plane (rpc/data_plane.py): same payloads as the unary
# RPCs, shipped as streams of smaller GradientUpdate/ParameterUpdate chunks.

def test_streaming_push_pull_matches_unary(ps):
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient

    server, port = ps
    rng = np.random.default_rng(0)
    params = {f"w{i}": rng.standard_normal((64, 8)).astype(np.float32)
              for i in range(7)}
    server.core.initialize_parameters(params)
    # chunk_bytes far below one tensor: every tensor rides its own chunk
    with PSClient(f"127.0.0.1:{port}", chunk_bytes=128) as client:
        pulled = client.pull_parameters(
            m.PullRequest(worker_id=0, iteration=0, wire_dtype=m.WIRE_BF16))
        assert client._stream_ok is True
        assert pulled.ready
        assert {t.name for t in pulled.parameters} == set(params)
        for t in pulled.parameters:
            np.testing.assert_allclose(t.to_array(), params[t.name],
                                       rtol=8e-3, atol=1e-2)
        grads = [m.Tensor.from_array(k, np.full_like(v, 0.5))
                 for k, v in params.items()]
        for wid in (0, 1):
            push = client.push_gradients(
                m.GradientUpdate(worker_id=wid, iteration=1, gradients=grads))
            assert push.success
        assert push.aggregation_complete
        after = client.pull_parameters(
            m.PullRequest(worker_id=0, iteration=1))
        for t in after.parameters:
            np.testing.assert_allclose(t.to_array(), params[t.name] - 0.5,
                                       rtol=1e-5, atol=1e-6)


def test_streaming_falls_back_against_unary_only_server(tmp_path):
    """A server binding only the reference's 5 unary RPCs (a reference PS)
    answers UNIMPLEMENTED for the stream methods; PSClient must fall back
    to unary and remember (per connection)."""
    from parameter_server_distributed_tpu.checkpoint.manager import CheckpointManager
    from parameter_server_distributed_tpu.core.ps_core import ParameterServerCore
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient
    from parameter_server_distributed_tpu.rpc.service import (bind_service,
                                                              make_server)
    from parameter_server_distributed_tpu.server.ps_service import (
        ParameterServerService)

    core = ParameterServerCore(total_workers=1)
    core.initialize_parameters({"w": np.array([1.0, 2.0], np.float32)})
    service = ParameterServerService(
        core, CheckpointManager(core, directory=str(tmp_path),
                                checkpoint_interval=100, check_period_s=600.0))
    server = make_server()
    bind_service(server, m.PARAMETER_SERVER_SERVICE,
                 m.PARAMETER_SERVER_METHODS, service)  # unary only
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        with PSClient(f"127.0.0.1:{port}") as client:
            pulled = client.pull_parameters(m.PullRequest(worker_id=0,
                                                          iteration=0))
            assert client._stream_ok is False
            np.testing.assert_allclose(pulled.parameters[0].to_array(),
                                       [1.0, 2.0])
            push = client.push_gradients(m.GradientUpdate(
                worker_id=0, iteration=1,
                gradients=[m.Tensor.from_array(
                    "w", np.array([0.5, 0.5], np.float32))]))
            assert push.success and push.aggregation_complete
    finally:
        server.stop(0)


def test_streaming_empty_push_still_contributes_to_barrier(ps):
    """Sharded topology invariant: a shard owning none of the pushed
    tensors still receives the (empty) push as a barrier contribution —
    the stream variant must send one empty chunk, not zero chunks."""
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient

    server, port = ps
    server.core.initialize_parameters({"w": np.array([1.0], np.float32)})
    with PSClient(f"127.0.0.1:{port}") as client:
        push = client.push_gradients(
            m.GradientUpdate(worker_id=0, iteration=1, gradients=[]))
        assert push.success
        assert push.workers_received == 1 and push.total_workers == 2


def test_load_checkpoint_omits_echo_for_large_store(ps, monkeypatch):
    """A restore of a store too large for the unary response cap must
    still SUCCEED — the reference-shaped parameter echo is omitted (a 1B
    store's repeated-float encoding would blow the gRPC cap after the
    load already happened server-side); small stores keep the echo."""
    server, port = ps
    server.core.initialize_parameters(
        {"w": np.arange(64, dtype=np.float32)})
    with ps_client(port) as client:
        saved = client.call("SaveCheckpoint", m.SaveCheckpointRequest())
        assert saved.success
        # normal store: echo present
        loaded = client.call("LoadCheckpoint",
                             m.LoadCheckpointRequest(path=saved.checkpoint_path))
        assert loaded.success and loaded.parameters
        # force the cap below the store size: echo omitted, still success
        monkeypatch.setenv("PSDT_CKPT_ECHO_MAX_BYTES", "16")
        loaded2 = client.call("LoadCheckpoint",
                              m.LoadCheckpointRequest(path=saved.checkpoint_path))
        assert loaded2.success and not loaded2.parameters
        assert "echo omitted" in loaded2.message
        # the restore really happened: params servable
        pull = client.call("ServeParameters", m.PullRequest(worker_id=0))
        np.testing.assert_allclose(pull.parameters[0].to_array(),
                                   np.arange(64, dtype=np.float32))
