"""RPC-level tests: drive the two gRPC services over real sockets."""

import numpy as np
import pytest

from parameter_server_distributed_tpu.config import (CoordinatorConfig,
                                                     ParameterServerConfig)
from parameter_server_distributed_tpu.rpc import messages as m
from parameter_server_distributed_tpu.rpc.service import RpcClient
from parameter_server_distributed_tpu.server.coordinator_service import Coordinator
from parameter_server_distributed_tpu.server.ps_service import ParameterServer


@pytest.fixture
def ps(tmp_path):
    server = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=2,
        checkpoint_interval=2, checkpoint_dir=str(tmp_path),
        learning_rate=1.0, autosave_period_s=60.0))
    port = server.start()
    yield server, port
    server.stop()


@pytest.fixture
def coordinator():
    server = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0,
        ps_address="10.1.2.3", ps_port=50051, reap_period_s=60.0))
    port = server.start()
    yield server, port
    server.stop()


def ps_client(port):
    return RpcClient(f"127.0.0.1:{port}", m.PARAMETER_SERVER_SERVICE,
                     m.PARAMETER_SERVER_METHODS)


def coord_client(port):
    return RpcClient(f"127.0.0.1:{port}", m.COORDINATOR_SERVICE,
                     m.COORDINATOR_METHODS)


@pytest.mark.lockcheck
def test_push_pull_sync_over_wire(ps):
    server, port = ps
    server.core.initialize_parameters({"w": np.array([1.0, 2.0], np.float32)})
    with ps_client(port) as client:
        # pull
        resp = client.call("ServeParameters", m.PullRequest(worker_id=0, iteration=1))
        assert resp.ready
        np.testing.assert_allclose(resp.parameters[0].to_array(), [1.0, 2.0])
        # push worker 0: barrier incomplete
        grads = [m.Tensor.from_array("w", np.array([0.5, 0.5], np.float32))]
        push = client.call("ReceiveGradients",
                           m.GradientUpdate(worker_id=0, iteration=1, gradients=grads))
        assert push.success and not push.aggregation_complete
        assert push.workers_received == 1 and push.total_workers == 2
        # sync poll: not ready
        sync = client.call("CheckSyncStatus", m.SyncStatusRequest(iteration=1))
        assert not sync.ready
        # push worker 1: aggregation fires
        push2 = client.call("ReceiveGradients",
                            m.GradientUpdate(worker_id=1, iteration=1, gradients=grads))
        assert push2.aggregation_complete
        sync2 = client.call("CheckSyncStatus", m.SyncStatusRequest(iteration=1))
        assert sync2.ready and sync2.workers_received == 2
        # params moved by lr=1.0 * mean([0.5,0.5])
        resp2 = client.call("ServeParameters", m.PullRequest(worker_id=0, iteration=2))
        np.testing.assert_allclose(resp2.parameters[0].to_array(), [0.5, 1.5])


def test_checkpoint_save_load_over_wire(ps, tmp_path):
    server, port = ps
    server.core.initialize_parameters({"w": np.array([3.0], np.float32)})
    with ps_client(port) as client:
        save = client.call("SaveCheckpoint",
                           m.SaveCheckpointRequest(epoch=7, path=""))
        assert save.success, save.message
        assert "checkpoint_epoch_7.ckpt" in save.checkpoint_path
        # mutate params, then restore
        server.core.initialize_parameters({"w": np.array([-99.0], np.float32)})
        load = client.call("LoadCheckpoint",
                           m.LoadCheckpointRequest(path=save.checkpoint_path))
        assert load.success and load.epoch == 7
        np.testing.assert_allclose(load.parameters[0].to_array(), [3.0])
        np.testing.assert_allclose(server.core.get_parameters()["w"], [3.0])


def test_load_checkpoint_missing_file_reports_failure(ps):
    server, port = ps
    with ps_client(port) as client:
        load = client.call("LoadCheckpoint",
                           m.LoadCheckpointRequest(path="/nonexistent/x.ckpt"))
        assert not load.success and load.message


def test_coordinator_register_discover_heartbeat_list(coordinator):
    server, port = coordinator
    with coord_client(port) as client:
        addr = client.call("GetParameterServerAddress", m.GetPSAddressRequest())
        assert (addr.address, addr.port) == ("10.1.2.3", 50051)
        reg = client.call("RegisterWorker",
                          m.WorkerInfo(worker_id=0, address="127.0.0.1",
                                       port=50060, hostname="h0"))
        assert reg.success and reg.total_workers == 1
        assert reg.parameter_server_address == "10.1.2.3:50051"
        hb = client.call("Heartbeat",
                         m.HeartbeatRequest(worker_id=0,
                                            status=m.WorkerStatus.TRAINING))
        assert hb.success and hb.timestamp > 0
        unknown = client.call("Heartbeat",
                              m.HeartbeatRequest(worker_id=42,
                                                 status=m.WorkerStatus.IDLE))
        assert not unknown.success
        lst = client.call("ListWorkers", m.ListWorkersRequest())
        assert lst.total_workers == 1
        assert lst.workers[0].worker_id == 0 and lst.workers[0].port == 50060


def test_serve_parameters_in_requested_wire_dtype(ps):
    """PullRequest.wire_dtype (framework extension) selects the payload
    encoding; default stays reference-compatible repeated-float."""
    server, port = ps
    w = np.linspace(-2, 2, 1024).astype(np.float32)
    server.core.initialize_parameters({"w": w})
    with ps_client(port) as client:
        plain = client.call("ServeParameters",
                            m.PullRequest(worker_id=0, iteration=0))
        packed = client.call("ServeParameters",
                             m.PullRequest(worker_id=0, iteration=0,
                                           wire_dtype=m.WIRE_BF16))
        t_plain, t_packed = plain.parameters[0], packed.parameters[0]
        assert t_plain.packed_dtype == m.WIRE_F32 and not t_plain.packed
        assert t_packed.packed_dtype == m.WIRE_BF16
        assert len(t_packed.encode()) < len(t_plain.encode()) * 0.55
        # linspace over [-2,2] at 1024 points is bf16-representable enough
        np.testing.assert_allclose(t_packed.to_array(), w, rtol=8e-3)
        # pushes in bf16 aggregate fine (PS decodes transparently)
        grads = [m.Tensor.from_array("w", np.full_like(w, 0.25),
                                     wire_dtype=m.WIRE_BF16)]
        for wid in (0, 1):
            push = client.call("ReceiveGradients",
                               m.GradientUpdate(worker_id=wid, iteration=1,
                                                gradients=grads))
        assert push.aggregation_complete
        after = client.call("ServeParameters",
                            m.PullRequest(worker_id=0, iteration=1))
        np.testing.assert_allclose(after.parameters[0].to_array(), w - 0.25,
                                   rtol=1e-2, atol=1e-3)


def test_lossy_pull_requests_served_bf16(ps):
    """The lossy gradient-push encodings must never apply to SERVED
    parameters: a client asking to pull int8/topk gets bf16 — enforced
    server-side so a misconfigured client cannot receive sparsified
    (99%-zeroed) weights."""
    server, port = ps
    w = np.linspace(-2, 2, 1024).astype(np.float32)
    server.core.initialize_parameters({"w": w})
    with ps_client(port) as client:
        for lossy in (m.WIRE_INT8, m.WIRE_TOPK):
            resp = client.call("ServeParameters",
                               m.PullRequest(worker_id=0, iteration=0,
                                             wire_dtype=lossy))
            t = resp.parameters[0]
            assert t.packed_dtype == m.WIRE_BF16
            np.testing.assert_allclose(t.to_array(), w, rtol=8e-3)


# ---------------------------------------------------------------- streaming
# Chunk-stream data plane (rpc/data_plane.py): same payloads as the unary
# RPCs, shipped as streams of smaller GradientUpdate/ParameterUpdate chunks.

@pytest.mark.lockcheck
def test_streaming_push_pull_matches_unary(ps):
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient

    server, port = ps
    rng = np.random.default_rng(0)
    params = {f"w{i}": rng.standard_normal((64, 8)).astype(np.float32)
              for i in range(7)}
    server.core.initialize_parameters(params)
    # chunk_bytes far below one tensor: every tensor rides its own chunk
    with PSClient(f"127.0.0.1:{port}", chunk_bytes=128) as client:
        pulled = client.pull_parameters(
            m.PullRequest(worker_id=0, iteration=0, wire_dtype=m.WIRE_BF16))
        assert client._stream_ok is True
        assert pulled.ready
        assert {t.name for t in pulled.parameters} == set(params)
        for t in pulled.parameters:
            np.testing.assert_allclose(t.to_array(), params[t.name],
                                       rtol=8e-3, atol=1e-2)
        grads = [m.Tensor.from_array(k, np.full_like(v, 0.5))
                 for k, v in params.items()]
        for wid in (0, 1):
            push = client.push_gradients(
                m.GradientUpdate(worker_id=wid, iteration=1, gradients=grads))
            assert push.success
        assert push.aggregation_complete
        after = client.pull_parameters(
            m.PullRequest(worker_id=0, iteration=1))
        for t in after.parameters:
            np.testing.assert_allclose(t.to_array(), params[t.name] - 0.5,
                                       rtol=1e-5, atol=1e-6)


def test_streaming_falls_back_against_unary_only_server(tmp_path):
    """A server binding only the reference's 5 unary RPCs (a reference PS)
    answers UNIMPLEMENTED for the stream methods; PSClient must fall back
    to unary and remember (per connection)."""
    from parameter_server_distributed_tpu.checkpoint.manager import CheckpointManager
    from parameter_server_distributed_tpu.core.ps_core import ParameterServerCore
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient
    from parameter_server_distributed_tpu.rpc.service import (bind_service,
                                                              make_server)
    from parameter_server_distributed_tpu.server.ps_service import (
        ParameterServerService)

    core = ParameterServerCore(total_workers=1)
    core.initialize_parameters({"w": np.array([1.0, 2.0], np.float32)})
    service = ParameterServerService(
        core, CheckpointManager(core, directory=str(tmp_path),
                                checkpoint_interval=100, check_period_s=600.0))
    server = make_server()
    bind_service(server, m.PARAMETER_SERVER_SERVICE,
                 m.PARAMETER_SERVER_METHODS, service)  # unary only
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        with PSClient(f"127.0.0.1:{port}") as client:
            pulled = client.pull_parameters(m.PullRequest(worker_id=0,
                                                          iteration=0))
            assert client._stream_ok is False
            np.testing.assert_allclose(pulled.parameters[0].to_array(),
                                       [1.0, 2.0])
            push = client.push_gradients(m.GradientUpdate(
                worker_id=0, iteration=1,
                gradients=[m.Tensor.from_array(
                    "w", np.array([0.5, 0.5], np.float32))]))
            assert push.success and push.aggregation_complete
    finally:
        server.stop(0)


def test_streaming_empty_push_still_contributes_to_barrier(ps):
    """Sharded topology invariant: a shard owning none of the pushed
    tensors still receives the (empty) push as a barrier contribution —
    the stream variant must send one empty chunk, not zero chunks."""
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient

    server, port = ps
    server.core.initialize_parameters({"w": np.array([1.0], np.float32)})
    with PSClient(f"127.0.0.1:{port}") as client:
        push = client.push_gradients(
            m.GradientUpdate(worker_id=0, iteration=1, gradients=[]))
        assert push.success
        assert push.workers_received == 1 and push.total_workers == 2


def test_load_checkpoint_omits_echo_for_large_store(ps, monkeypatch):
    """A restore of a store too large for the unary response cap must
    still SUCCEED — the reference-shaped parameter echo is omitted (a 1B
    store's repeated-float encoding would blow the gRPC cap after the
    load already happened server-side); small stores keep the echo."""
    server, port = ps
    server.core.initialize_parameters(
        {"w": np.arange(64, dtype=np.float32)})
    with ps_client(port) as client:
        saved = client.call("SaveCheckpoint", m.SaveCheckpointRequest())
        assert saved.success
        # normal store: echo present
        loaded = client.call("LoadCheckpoint",
                             m.LoadCheckpointRequest(path=saved.checkpoint_path))
        assert loaded.success and loaded.parameters
        # force the cap below the store size: echo omitted, still success
        monkeypatch.setenv("PSDT_CKPT_ECHO_MAX_BYTES", "16")
        loaded2 = client.call("LoadCheckpoint",
                              m.LoadCheckpointRequest(path=saved.checkpoint_path))
        assert loaded2.success and not loaded2.parameters
        assert "echo omitted" in loaded2.message
        # the restore really happened: params servable
        pull = client.call("ServeParameters", m.PullRequest(worker_id=0))
        np.testing.assert_allclose(pull.parameters[0].to_array(),
                                   np.arange(64, dtype=np.float32))


# ------------------------------------------------------------------- fused
# Pipelined data plane (rpc/data_plane.py PushPullStream): one RPC round
# per synchronous step instead of push + barrier polls + pull.

@pytest.mark.lockcheck
def test_fused_push_pull_matches_unary_protocol(ps):
    """The fused round must land exactly the state the serial protocol
    lands: same aggregation, same served parameters."""
    import threading

    from parameter_server_distributed_tpu.rpc.data_plane import PSClient

    server, port = ps
    w0 = np.linspace(-1, 1, 512).astype(np.float32)
    server.core.initialize_parameters({"w": w0})
    grads = [m.Tensor.from_array("w", np.full_like(w0, 0.25))]
    results = {}

    def worker(wid):
        with PSClient(f"127.0.0.1:{port}") as client:
            results[wid] = client.push_pull(wid, 1, grads)

    threads = [threading.Thread(target=worker, args=(wid,))
               for wid in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for wid in (0, 1):
        push, params = results[wid]
        assert push.success
        assert params is not None and params.ready
        np.testing.assert_allclose(params.parameters[0].to_array(),
                                   w0 - 0.25, rtol=1e-6)
    # exactly what a serial pull now sees as well
    with ps_client(port) as plain:
        after = plain.call("ServeParameters", m.PullRequest(worker_id=0))
        np.testing.assert_allclose(after.parameters[0].to_array(),
                                   w0 - 0.25, rtol=1e-6)


def test_fused_falls_back_against_unary_only_server(tmp_path):
    """A reference-shaped server (5 unary RPCs only) answers UNIMPLEMENTED
    for PushPullStream: push_pull must degrade to the unary push (params
    None — the caller barrier-polls and pulls) and remember per
    connection."""
    from parameter_server_distributed_tpu.checkpoint.manager import CheckpointManager
    from parameter_server_distributed_tpu.core.ps_core import ParameterServerCore
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient
    from parameter_server_distributed_tpu.rpc.service import (bind_service,
                                                              make_server)
    from parameter_server_distributed_tpu.server.ps_service import (
        ParameterServerService)

    core = ParameterServerCore(total_workers=1)
    core.initialize_parameters({"w": np.array([1.0, 2.0], np.float32)})
    service = ParameterServerService(
        core, CheckpointManager(core, directory=str(tmp_path),
                                checkpoint_interval=100, check_period_s=600.0))
    server = make_server()
    bind_service(server, m.PARAMETER_SERVER_SERVICE,
                 m.PARAMETER_SERVER_METHODS, service)  # unary only
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        with PSClient(f"127.0.0.1:{port}") as client:
            grads = [m.Tensor.from_array(
                "w", np.array([0.5, 0.5], np.float32))]
            push, params = client.push_pull(0, 1, grads)
            assert push.success and push.aggregation_complete
            assert params is None            # caller must poll + pull
            assert client._fused_ok is False  # remembered
            assert client._stream_ok is False
            np.testing.assert_allclose(core.get_parameters()["w"],
                                       [0.5, 1.5])
            # second call goes straight to the fallback (no re-probe)
            push, params = client.push_pull(0, 2, grads)
            assert push.success and params is None
            pulled = client.pull_parameters(m.PullRequest(worker_id=0))
            np.testing.assert_allclose(pulled.parameters[0].to_array(),
                                       [0.0, 1.0])
    finally:
        server.stop(0)


def test_fused_push_refused_on_empty_store(ps):
    """A fused push must never bootstrap an empty store (the gradient
    payload would silently BECOME the parameters); the server refuses and
    the worker's recovery re-seeds via the plain push path."""
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient

    _, port = ps
    with PSClient(f"127.0.0.1:{port}") as client:
        grads = [m.Tensor.from_array("w", np.array([0.5], np.float32))]
        push, params = client.push_pull(0, 1, grads)
        assert not push.success and params is None
        assert "store empty" in push.message
        assert client._fused_ok is True  # implemented, just refused


def test_fused_lazy_tensor_factory_replayed_on_fallback(tmp_path):
    """With a CALLABLE tensor producer, the unary fallback re-invokes it
    (a half-consumed generator cannot be replayed): the pushed payload is
    identical either way."""
    from parameter_server_distributed_tpu.checkpoint.manager import CheckpointManager
    from parameter_server_distributed_tpu.core.ps_core import ParameterServerCore
    from parameter_server_distributed_tpu.rpc.data_plane import PSClient
    from parameter_server_distributed_tpu.rpc.service import (bind_service,
                                                              make_server)
    from parameter_server_distributed_tpu.server.ps_service import (
        ParameterServerService)

    core = ParameterServerCore(total_workers=1)
    core.initialize_parameters({"w": np.array([4.0], np.float32)})
    service = ParameterServerService(
        core, CheckpointManager(core, directory=str(tmp_path),
                                checkpoint_interval=100, check_period_s=600.0))
    server = make_server()
    bind_service(server, m.PARAMETER_SERVER_SERVICE,
                 m.PARAMETER_SERVER_METHODS, service)  # unary only
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    calls = []

    def tensors():
        calls.append(1)
        yield m.Tensor.from_array("w", np.array([1.0], np.float32))

    try:
        with PSClient(f"127.0.0.1:{port}") as client:
            push, params = client.push_pull(0, 1, tensors)
            assert push.success and params is None
            # the factory ran at least twice: fused attempt + fallback
            assert len(calls) >= 2
            np.testing.assert_allclose(core.get_parameters()["w"], [3.0])
    finally:
        server.stop(0)


def _steady_worker_cluster(tmp_path, n_workers, relay_cfg=None, **worker_kw):
    """Coordinator + PS (+ optional ThrottledRelay in front) + N workers,
    driven past bootstrap so the next run_iteration is a steady-state
    step.  Returns (ps, coordinator, workers, relay, stop)."""
    import threading

    from parameter_server_distributed_tpu.cli.worker_main import build_worker
    from parameter_server_distributed_tpu.config import (CoordinatorConfig,
                                                         WorkerConfig)
    from parameter_server_distributed_tpu.server.coordinator_service import (
        Coordinator)
    from parameter_server_distributed_tpu.utils.netsim import ThrottledRelay

    ps = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=n_workers,
        checkpoint_interval=100, checkpoint_dir=str(tmp_path),
        learning_rate=0.05, autosave_period_s=600.0))
    ps_port = ps.start()
    relay = None
    if relay_cfg is not None:
        relay = ThrottledRelay(ps_port, **relay_cfg)
        ps_port = relay.start()
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0, ps_address="127.0.0.1",
        ps_port=ps_port, reap_period_s=600.0))
    coord_port = coordinator.start()
    workers = []
    for wid in range(n_workers):
        w = build_worker(WorkerConfig(
            coordinator_address=f"127.0.0.1:{coord_port}", worker_id=wid,
            address="127.0.0.1", port=51500 + wid, batch_size=16,
            model="mnist_mlp", heartbeat_period_s=600.0, **worker_kw))
        w.initialize()
        workers.append(w)

    def run_step(it):
        errors = []

        def loop(w):
            try:
                w.run_iteration(it)
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        threads = [threading.Thread(target=loop, args=(w,))
                   for w in workers]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=90)
        assert not errors, errors
        return errors

    def stop():
        for w in workers:
            w.shutdown()
        coordinator.stop()
        if relay is not None:
            relay.stop()
        ps.stop()

    return ps, coordinator, workers, relay, run_step, stop


def _data_plane_counters():
    from parameter_server_distributed_tpu.obs import stats as obs_stats

    snap = obs_stats.REGISTRY.snapshot()["counters"]
    return {method: snap.get(f"rpc.client.{method}.calls", 0)
            for method in ("PushPullStream", "PushGradientsStream",
                           "ReceiveGradients", "ServeParameters",
                           "ServeParametersStream", "CheckSyncStatus",
                           "PullParametersDelta", "PushPullDeltaStream")}


def test_fused_step_is_single_rpc_round(tmp_path):
    """Acceptance: a steady-state synchronous step issues EXACTLY one
    data-plane round (PushPullStream) per worker, where the serial path
    issues >= 3 (push + >= 1 sync poll + pull)."""
    _, _, _, _, run_step, stop = _steady_worker_cluster(
        tmp_path / "fused", n_workers=2)
    try:
        run_step(0)   # bootstrap seed
        run_step(1)   # warm-up: first real step (does the initial pull)
        before = _data_plane_counters()
        run_step(2)   # steady state
        after = _data_plane_counters()
        delta = {k: after[k] - before[k] for k in after}
        assert delta["PushPullStream"] == 2, delta  # one round per worker
        for method in ("PushGradientsStream", "ReceiveGradients",
                       "ServeParameters", "ServeParametersStream",
                       "CheckSyncStatus"):
            assert delta[method] == 0, delta
    finally:
        stop()

    # the serial protocol, same shape: push + pull per worker plus the
    # first pusher's >=1 barrier poll
    _, _, _, _, run_step, stop = _steady_worker_cluster(
        tmp_path / "serial", n_workers=2, fused_step=False)
    try:
        run_step(0)
        run_step(1)
        before = _data_plane_counters()
        run_step(2)
        after = _data_plane_counters()
        delta = {k: after[k] - before[k] for k in after}
        assert delta["PushPullStream"] == 0, delta
        pushes = delta["PushGradientsStream"] + delta["ReceiveGradients"]
        # the version-aware delta pull (delta/, ISSUE 10) is still one
        # pull round — count it with the plain pull methods
        pulls = (delta["ServeParameters"] + delta["ServeParametersStream"]
                 + delta["PullParametersDelta"])
        assert pushes == 2 and pulls == 2, delta
        assert delta["CheckSyncStatus"] >= 1, delta  # >=3 rounds somewhere
    finally:
        stop()


def test_fused_step_pipelines_d2h_with_transport(tmp_path):
    """Acceptance: at least one gradient chunk is ON THE WIRE (relay byte
    counter) before the LAST D2H bucket is fetched — i.e. D2H, encode and
    transport overlap instead of serializing whole-store."""
    import os

    os.environ["PSDT_STREAM_CHUNK_BYTES"] = "16384"
    os.environ["PSDT_BUCKET_BYTES"] = "16384"
    try:
        _, _, workers, relay, run_step, stop = _steady_worker_cluster(
            tmp_path, n_workers=1, relay_cfg={"delay_ms": 0.0, "mbps": 0.0})
        worker = workers[0]
        observed = {}
        trainer = worker.trainer
        orig = trainer.compute_gradient_buckets

        def instrumented(params, batch, bucket_bytes=None, on_fetch=None):
            def record(i, n):
                if i == 0:
                    relay.reset_byte_counts()
                    observed["buckets"] = n
                elif i == n - 1:
                    # wait (bounded) for wire evidence: under pipelining,
                    # earlier buckets' chunks are already in flight; a
                    # serial fetch-everything-first implementation reaches
                    # this fetch before the RPC even starts and times out
                    import time
                    deadline = time.monotonic() + 15.0
                    while time.monotonic() < deadline:
                        sent = relay.byte_counts()[0]
                        if sent > 0:
                            observed["wire_bytes_at_last_fetch"] = sent
                            return
                        time.sleep(0.005)
                    observed["wire_bytes_at_last_fetch"] = 0

            return orig(params, batch, bucket_bytes=bucket_bytes,
                        on_fetch=record)

        trainer.compute_gradient_buckets = instrumented
        try:
            run_step(0)   # bootstrap
            run_step(1)   # steady fused step, instrumented
            # mnist_mlp packs into a handful of tensors and a tensor never
            # splits across buckets, so "several" is the right bar here
            assert observed.get("buckets", 0) >= 2, observed
            assert observed.get("wire_bytes_at_last_fetch", 0) > 0, (
                "no gradient bytes on the wire before the last D2H bucket "
                f"fetch: {observed}")
        finally:
            stop()
    finally:
        os.environ.pop("PSDT_STREAM_CHUNK_BYTES", None)
        os.environ.pop("PSDT_BUCKET_BYTES", None)


@pytest.mark.lockcheck
def test_fused_barrier_wider_than_default_thread_pool(tmp_path):
    """Liveness: parked fused handlers hold server threads, so a barrier
    WIDER than the old fixed 8-thread pool must still close promptly (the
    server pool is sized from total_workers) — the closing worker's push
    must never queue behind the parked handlers."""
    import threading
    import time

    from parameter_server_distributed_tpu.rpc.data_plane import PSClient

    n = 10
    server = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=n,
        checkpoint_interval=100, checkpoint_dir=str(tmp_path),
        learning_rate=1.0, autosave_period_s=600.0))
    port = server.start()
    server.core.initialize_parameters(
        {"w": np.array([1.0, 2.0], np.float32)})
    results = {}

    def worker(wid):
        with PSClient(f"127.0.0.1:{port}") as client:
            grads = [m.Tensor.from_array(
                "w", np.array([float(wid), 1.0], np.float32))]
            results[wid] = client.push_pull(wid, 1, grads)

    try:
        t0 = time.perf_counter()
        threads = [threading.Thread(target=worker, args=(wid,))
                   for wid in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        elapsed = time.perf_counter() - t0
        assert all(not t.is_alive() for t in threads)
        # well under the 60 s barrier timeout the starved pool would hit
        assert elapsed < 20, f"barrier took {elapsed:.1f}s (pool starved?)"
        expected = np.array([1.0, 2.0], np.float32) - [np.mean(range(n)), 1.0]
        for wid in range(n):
            push, params = results[wid]
            assert push.success, push.message
            assert params is not None and params.ready
            np.testing.assert_allclose(params.parameters[0].to_array(),
                                       expected, rtol=1e-6)
    finally:
        server.stop()
