"""Free-running barrier-free training (freerun/, ISSUE 16).

Covers the apply-on-arrival engine (version-vector dedup idempotence
under RPC retry replay, staleness damping with hand-computed sequences,
bootstrap, the downgrade matrix), the adaptive EWMA-normalized schedule
(fixed-beta oracle equivalence when the EWMA is flat), the damp floor
(clamp + flight event), coalesced publication (serve-version stability
and the encode-once serve-cache regression), N-worker convergence
against the synchronous baseline, the 50%-churn chaos row with zero
failed steps, and the lockcheck-marked concurrent push/apply/serve
hammer."""

import tempfile
import threading

import numpy as np
import pytest

from parameter_server_distributed_tpu.async_sgd.adaptive import AdaptiveDamping
from parameter_server_distributed_tpu.async_sgd.damping import (
    MAX_STALENESS, StalenessDamping, clamp_staleness)
from parameter_server_distributed_tpu.core.optimizer import SGD
from parameter_server_distributed_tpu.core.ps_core import (
    TIER_AGGREGATE_ID_BASE, ParameterServerCore)
from parameter_server_distributed_tpu.delta.chain import (
    publish_max_lag_s, publish_min_versions)
from parameter_server_distributed_tpu.obs import flight, postmortem
from parameter_server_distributed_tpu.obs import stats as obs_stats


def store(**kw):
    return {k: np.asarray(v, np.float32) for k, v in kw.items()}


def make_core(total_workers=2, lr=1.0, **kw):
    return ParameterServerCore(total_workers=total_workers,
                               optimizer=SGD(lr), freerun=True, **kw)


def counters():
    return obs_stats.REGISTRY.snapshot()["counters"]


# ------------------------------------------------------------------ damping

def test_fixed_damping_hand_computed_sequence():
    """beta^staleness against a hand-computed table, with the defensive
    clamps: negative staleness damps like fresh (1.0), and an
    overflow-sized staleness clamps to MAX_STALENESS instead of raising
    (beta**2^20 underflows cleanly to 0.0)."""
    d = StalenessDamping(beta=0.5)
    assert d.scale(0) == 1.0
    assert d.scale(1) == 0.5
    assert d.scale(3) == pytest.approx(0.125)
    # clamps (satellite: negative/overflow staleness must be defensive)
    assert clamp_staleness(-7) == 0
    assert clamp_staleness(2**40) == MAX_STALENESS
    assert d.scale(-7) == 1.0
    assert d.scale(2**40) == 0.0  # underflow, not OverflowError


def test_adaptive_matches_fixed_oracle_when_ewma_flat():
    """The fixed-beta path is the ORACLE: with the EWMA at <= 1 (a fleet
    whose pushes are at most one step stale) the adaptive schedule is
    beta**s exactly."""
    fixed = StalenessDamping(beta=0.7)
    adaptive = AdaptiveDamping(beta=0.7)  # ewma starts 0.0 (flat)
    for s in (0, 1, 2, 5, 11):
        assert adaptive.scale(s) == pytest.approx(fixed.scale(s))
    # a fleet operating at staleness <= 1 keeps the EWMA <= 1, so the
    # equivalence survives live observations too
    for _ in range(50):
        adaptive.observe(1)
    assert adaptive.ewma <= 1.0
    for s in (0, 2, 7):
        assert adaptive.scale(s) == pytest.approx(fixed.scale(s))


def test_adaptive_ewma_and_normalized_scale_hand_computed():
    """EWMA arithmetic and the normalized exponent against hand-computed
    values: after observing staleness 8 with alpha 0.5 twice from 0,
    ewma = 0.5*8 + 0.5*(0.5*8) = 6; scale(6) = beta^(6/6) = beta and
    scale(12) = beta^2."""
    a = AdaptiveDamping(beta=0.5, alpha=0.5)
    a.observe(8)
    assert a.ewma == pytest.approx(4.0)
    a.observe(8)
    assert a.ewma == pytest.approx(6.0)
    assert a.scale(6) == pytest.approx(0.5)
    assert a.scale(12) == pytest.approx(0.25)
    assert a.effective_beta == pytest.approx(0.5 ** (1 / 6))
    # seeding (pst-trace commit-spread) starts at the fleet's known
    # operating point instead of re-learning it
    seeded = AdaptiveDamping(beta=0.5, seed=4.0)
    assert seeded.scale(4) == pytest.approx(0.5)


def test_adaptive_validation():
    with pytest.raises(ValueError):
        AdaptiveDamping(beta=0.0)
    with pytest.raises(ValueError):
        AdaptiveDamping(beta=0.5, alpha=0.0)
    with pytest.raises(ValueError):
        AdaptiveDamping(beta=0.5, seed=-1.0)


def test_damp_floor_validation_and_flight_event(tmp_path):
    """A scale below PSDT_DAMP_FLOOR is an effectively-dropped
    contribution: floored() says so and records the damp.floor flight
    event (satellite 2)."""
    with pytest.raises(ValueError):
        StalenessDamping(beta=0.5, floor=1.5)
    d = StalenessDamping(beta=0.5, floor=0.1)
    ring_dir = str(tmp_path / "flight")
    flight.enable(ring_dir, role="test:floor", records=64)
    try:
        assert not d.floored(0.5, worker=1, iteration=3, staleness=1)
        assert d.floored(0.01, worker=1, iteration=9, staleness=7)
    finally:
        flight.disable()
    events = [e for ring in postmortem.load_rings(ring_dir)
              for e in ring["events"] if e["event"] == "damp.floor"]
    assert len(events) == 1
    assert events[0]["worker"] == 1
    assert events[0]["iteration"] == 9
    assert events[0]["a"] == 7  # staleness
    assert events[0]["b"] == int(0.01 * 1e9)  # scale in ppb
    # scale() runs the floor check itself on the fixed path
    off = StalenessDamping(beta=0.5)  # floor off by default
    assert not off.floored(0.0)


# ------------------------------------------------------------ engine: dedup

def test_version_vector_dedup_is_idempotent_under_retry_replay():
    """An RPC retry replays an IDENTICAL payload for the same
    (worker, worker_step): exactly one apply must land, and the retry
    must answer success (the worker's contribution DID land)."""
    core = make_core(total_workers=2)
    core.initialize_parameters(store(w=[10.0, 10.0]))
    before_dups = counters().get("ps.freerun.duplicates", 0)

    r1 = core.receive_gradients(0, 1, store(w=[1.0, 1.0]))
    assert r1.success and r1.aggregation_complete
    np.testing.assert_allclose(core.get_parameters()["w"], [9.0, 9.0])

    # the retry replay: same worker, same step, same payload
    r2 = core.receive_gradients(0, 1, store(w=[1.0, 1.0]))
    assert r2.success  # success-without-apply: the worker moves on
    assert "duplicate" in r2.message
    np.testing.assert_allclose(core.get_parameters()["w"], [9.0, 9.0])
    # an OLDER step replayed late dedups too (vector keeps the highest)
    core.receive_gradients(0, 5, store(w=[1.0, 1.0]))
    r3 = core.receive_gradients(0, 3, store(w=[1.0, 1.0]))
    assert r3.success and "duplicate" in r3.message
    assert counters().get("ps.freerun.duplicates", 0) - before_dups == 2
    # a DIFFERENT worker at the same step is a fresh contribution
    r4 = core.receive_gradients(1, 1, store(w=[1.0, 1.0]))
    assert r4.success and "applied" in r4.message


def test_freerun_bootstrap_and_stale_damping():
    """First push bootstraps (payload becomes the parameters — the
    reference quirk every mode preserves); a late worker's push applies
    damped by beta^staleness instead of being rejected."""
    import os
    os.environ.pop("PSDT_STALENESS_BETA", None)
    core = make_core(total_workers=2)
    boot = core.receive_gradients(0, 0, store(w=[4.0]))
    assert boot.success and "bootstrap" in boot.message
    np.testing.assert_allclose(core.get_parameters()["w"], [4.0])
    # bootstrap-duplicate (another worker racing the same init): dropped
    dup = core.receive_gradients(1, 0, store(w=[4.0]))
    assert dup.success and "bootstrap duplicate" in dup.message

    for it in range(1, 4):
        core.receive_gradients(0, it, store(w=[1.0]))
    np.testing.assert_allclose(core.get_parameters()["w"], [1.0])
    # worker 1 pushes step 1 while the clock sits at 3: staleness 2
    beta = core._freerun._damping.beta
    r = core.receive_gradients(1, 1, store(w=[1.0]))
    assert r.success and "staleness 2" in r.message
    np.testing.assert_allclose(core.get_parameters()["w"],
                               [1.0 - beta ** 2], rtol=1e-6)


def test_freerun_rejects_tier_aggregates_retryably():
    core = make_core()
    core.initialize_parameters(store(w=[1.0]))
    r = core.receive_gradients(TIER_AGGREGATE_ID_BASE + 3, 1,
                               store(w=[1.0]))
    assert not r.success and "replay flat" in r.message


def test_freerun_no_barrier_state():
    """check_sync_status answers ready immediately and creates no
    per-iteration state; wait_for_aggregation never blocks."""
    core = make_core()
    core.initialize_parameters(store(w=[1.0]))
    for it in (0, 1, 99):
        _, ready, received, _ = core.check_sync_status(it)
        assert ready and received == 1
    assert core.wait_for_aggregation(7, 0.01)[0]
    assert not core._iteration_states  # nothing materialized


# -------------------------------------------------------- downgrade matrix

def test_downgrade_matrix():
    """Buffered aggregation and bounded-staleness async win over a
    freerun request (warn + disable); a quorum is force-disabled UNDER
    freerun (no barrier to close)."""
    buffered = ParameterServerCore(total_workers=2, optimizer=SGD(1.0),
                                   freerun=True, aggregation="buffered")
    assert buffered._freerun is None
    bounded = ParameterServerCore(total_workers=2, optimizer=SGD(1.0),
                                  freerun=True, staleness_bound=4)
    assert bounded._freerun is None
    quorumed = ParameterServerCore(total_workers=4, optimizer=SGD(1.0),
                                   freerun=True, quorum=0.75)
    assert quorumed._freerun is not None
    assert quorumed._quorum == 0.0
    # default-off: no env, no flag -> no engine, byte-identical paths
    plain = ParameterServerCore(total_workers=2, optimizer=SGD(1.0))
    assert plain._freerun is None


# --------------------------------------------------- coalesced publication

def test_publication_coalescing_serve_version_stable(monkeypatch):
    """With PSDT_PUBLISH_MIN_VERSIONS=4 the served version advances at
    most once per 4 applies even though the raw store version bumps per
    push (satellite 1)."""
    monkeypatch.setenv("PSDT_PUBLISH_MIN_VERSIONS", "4")
    monkeypatch.setenv("PSDT_PUBLISH_MAX_LAG_MS", "60000")
    core = make_core(total_workers=2)
    core.initialize_parameters(store(w=np.zeros(8)))
    core.receive_gradients(0, 1, store(w=np.ones(8)))
    v0 = core.serve_version()
    versions = {v0}
    for it in range(2, 5):  # applies 2..4 within the window
        core.receive_gradients(0, it, store(w=np.ones(8)))
        versions.add(core.serve_version())
    assert len(versions) <= 2  # at most one publication boundary crossed
    for it in range(5, 9):
        core.receive_gradients(0, it, store(w=np.ones(8)))
    v_late = core.serve_version()
    assert v_late > v0  # the window DID roll over eventually
    # served values are the published snapshot, not the live store
    _, served, ready, version = core.serve_view()
    assert ready and version == v_late


def test_publication_knob_validation(monkeypatch):
    assert publish_min_versions(3) == 3
    with pytest.raises(ValueError):
        publish_min_versions(-1)
    monkeypatch.setenv("PSDT_PUBLISH_MIN_VERSIONS", "7")
    assert publish_min_versions() == 7
    assert publish_max_lag_s(250.0) == pytest.approx(0.25)
    with pytest.raises(ValueError):
        publish_max_lag_s(-5.0)
    monkeypatch.setenv("PSDT_PUBLISH_MAX_LAG_MS", "40")
    assert publish_max_lag_s() == pytest.approx(0.04)


def _make_service(core):
    from parameter_server_distributed_tpu.checkpoint.manager import (
        CheckpointManager)
    from parameter_server_distributed_tpu.server.ps_service import (
        ParameterServerService)

    return ParameterServerService(core, CheckpointManager(
        core, directory=tempfile.mkdtemp(prefix="psdt-freerun-"),
        checkpoint_interval=10**9, check_period_s=3600.0))


def test_serve_cache_hit_rate_stays_high_under_freerun(monkeypatch):
    """The encode-once serve cache regression (satellite 1): per-push
    version advance must NOT thrash the cache — serves between
    publications replay the cached encode.  8 applies at coalescing 4 =
    at most a handful of encodes for 24 serves."""
    monkeypatch.setenv("PSDT_PUBLISH_MIN_VERSIONS", "4")
    monkeypatch.setenv("PSDT_PUBLISH_MAX_LAG_MS", "60000")
    core = make_core(total_workers=2)
    core.initialize_parameters(store(w=np.zeros(64)))
    service = _make_service(core)

    def serve_once():
        for chunk in service._parameter_chunks(0, 0):
            chunk.encode()

    snap0 = counters()
    for it in range(1, 9):
        core.receive_gradients(0, it, store(w=np.ones(64)))
        for _ in range(3):
            serve_once()
    snap1 = counters()
    hits = snap1.get("ps.serve.cache_hit", 0) - snap0.get(
        "ps.serve.cache_hit", 0)
    misses = snap1.get("ps.serve.cache_miss", 0) - snap0.get(
        "ps.serve.cache_miss", 0)
    assert hits + misses == 24
    # without coalescing every apply would invalidate: ~8 misses.  With
    # a 4-apply window at most 3 publications land inside the run.
    assert misses <= 4, (hits, misses)
    assert hits >= 20, (hits, misses)


def test_delta_chain_pairing_survives_coalesced_publication(monkeypatch):
    """Consecutive +1 published versions keep the delta chain building
    pairs, so SubscribeWeights keyed off continuous versions still
    serves O(changed bytes) hops under free-run."""
    monkeypatch.setenv("PSDT_PUBLISH_MIN_VERSIONS", "2")
    monkeypatch.setenv("PSDT_PUBLISH_MAX_LAG_MS", "60000")
    from parameter_server_distributed_tpu.delta.chain import DeltaChain
    core = make_core(total_workers=2)
    core.initialize_parameters(store(w=np.zeros(32)))
    chain = DeltaChain()
    core.set_delta_sink(chain, seed=False)
    for it in range(1, 9):
        core.receive_gradients(0, it, store(w=np.ones(32)))
    head = chain.version
    assert head == core.serve_version()
    # at least one consecutive publication pair chained
    assert chain.pairs_between(head - 1, head)


# ------------------------------------------------------------- convergence

def _run_fleet(core, n_workers, steps, lr_noise=0.0):
    """Each worker pulls the served view, pushes grad = view (the shared
    quadratic loss 0.5*||w||^2), at its own pace."""
    errors = []

    def loop(wid):
        try:
            for it in range(1, steps + 1):
                _, view, _, _ = core.serve_view()
                r = core.receive_gradients(wid, it,
                                           {"w": view["w"].copy()})
                assert r.success, r.message
        except Exception as exc:  # noqa: BLE001
            errors.append((wid, repr(exc)))

    threads = [threading.Thread(target=loop, args=(w,)) for w in
               range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert not [t for t in threads if t.is_alive()]


def test_n_worker_freerun_converges_within_tolerance_of_sync():
    """Acceptance: the async free-run fleet lands the quadratic optimum
    to within tolerance of the synchronous all-of-N baseline."""
    n, steps, lr = 4, 12, 0.2
    init = store(w=np.full(16, 8.0))

    sync = ParameterServerCore(total_workers=n, optimizer=SGD(lr))
    sync.initialize_parameters({k: v.copy() for k, v in init.items()})
    for it in range(1, steps + 1):
        w = sync.get_parameters()["w"].copy()
        for wid in range(n):
            sync.receive_gradients(wid, it, {"w": w.copy()})
    sync_final = sync.get_parameters()["w"]
    # geometric decay toward 0: the baseline itself converged
    assert float(np.abs(sync_final).max()) < 1.0

    free = make_core(total_workers=n, lr=lr)
    free.initialize_parameters({k: v.copy() for k, v in init.items()})
    _run_fleet(free, n, steps)
    free_final = free.get_parameters()["w"]
    # same optimum, comparable distance: within tolerance of baseline
    assert float(np.abs(free_final).max()) <= \
        max(0.5, 2.0 * float(np.abs(sync_final).max()))


def test_churn_chaos_zero_failed_steps():
    """Acceptance: 50% churn — half the fleet joins late and leaves
    early (its last push still in flight applies damped) — with ZERO
    failed steps and no barrier for anyone to wedge on."""
    n, steps = 8, 10
    core = make_core(total_workers=n, lr=0.1,
                     gc_iterations=4)  # aggressive GC: nothing to leak
    core.initialize_parameters(store(w=np.full(8, 4.0)))
    results = []
    errors = []
    start_late = threading.Event()

    def loop(wid):
        try:
            if wid % 2:  # the churn half joins late...
                start_late.wait(timeout=30)
            span = steps // 2 if wid % 2 else steps  # ...and leaves early
            for it in range(1, span + 1):
                _, view, _, _ = core.serve_view()
                r = core.receive_gradients(wid, it,
                                           {"w": view["w"].copy()})
                results.append((wid, it, r.success, r.message))
        except Exception as exc:  # noqa: BLE001
            errors.append((wid, repr(exc)))

    threads = [threading.Thread(target=loop, args=(w,)) for w in range(n)]
    for t in threads:
        t.start()
    start_late.set()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    assert not [t for t in threads if t.is_alive()]
    failed = [r for r in results if not r[2]]
    assert not failed, failed
    assert len(results) == (n // 2) * steps + (n // 2) * (steps // 2)
    # the run made progress toward the optimum despite the churn
    assert float(np.abs(core.get_parameters()["w"]).max()) < 4.0


# ------------------------------------------------------- concurrency/locks

@pytest.mark.lockcheck
def test_concurrent_push_apply_serve_hammer():
    """Pushers, servers, and sync pollers hammer one freerun core under
    PSDT_LOCK_CHECK=1 (conftest arms order-asserting lock proxies): no
    deadlock, no lock-order violation, every push lands or dedups."""
    core = make_core(total_workers=4, lr=0.01)
    core.initialize_parameters(store(w=np.ones(32)))
    stop = threading.Event()
    errors = []

    def pusher(wid):
        try:
            for it in range(1, 40):
                r = core.receive_gradients(wid, it,
                                           store(w=np.full(32, 0.1)))
                assert r.success, r.message
        except Exception as exc:  # noqa: BLE001
            errors.append(("push", wid, repr(exc)))

    def server():
        try:
            while not stop.is_set():
                _, view, ready, version = core.serve_view()
                assert ready and version >= 0
                assert view["w"].shape == (32,)
                core.serve_version()
                core.check_sync_status(1)
        except Exception as exc:  # noqa: BLE001
            errors.append(("serve", repr(exc)))

    pushers = [threading.Thread(target=pusher, args=(w,)) for w in range(4)]
    servers = [threading.Thread(target=server) for _ in range(2)]
    for t in servers + pushers:
        t.start()
    for t in pushers:
        t.join(timeout=60)
    stop.set()
    for t in servers:
        t.join(timeout=10)
    assert not errors, errors
    assert not [t for t in pushers + servers if t.is_alive()]
    applies = counters().get("ps.freerun.applies", 0)
    assert applies > 0


# ----------------------------------------------------------- reset/restore

def test_restore_clears_version_vector_but_not_version_counter():
    """A checkpoint restore rewinds the store: worker step counters
    restart against the restored world (the version vector clears), but
    the published version counter never reuses a served id."""
    core = make_core(total_workers=2)
    core.initialize_parameters(store(w=np.zeros(4)))
    for it in range(1, 6):
        core.receive_gradients(0, it, store(w=np.ones(4)))
    v_before = core.serve_version()
    core.initialize_parameters(store(w=np.zeros(4)))
    core._reset_delta()  # the restore/install/retire hook
    assert core._freerun._published is None
    assert not core._freerun._version_vector
    # step 1 applies again (not deduped against the pre-restore world)
    r = core.receive_gradients(0, 1, store(w=np.ones(4)))
    assert r.success and "applied" in r.message
    assert core.serve_version() >= v_before
