"""File-backed data pipeline (data/files.py): memmap token shards and npz
example sets, plus registry/CLI integration."""

import numpy as np
import pytest

from parameter_server_distributed_tpu.data.files import (load_tokens,
                                                         npz_stream,
                                                         token_stream)


@pytest.fixture
def token_file(tmp_path):
    path = str(tmp_path / "corpus.bin")
    tokens = np.arange(5000, dtype="<u2") % 997
    tokens.tofile(path)
    return path, tokens


def test_token_stream_crops(token_file):
    path, tokens = token_file
    stream = token_stream(path, batch_size=4, seq_len=64, seed=0)
    batch = next(stream)
    assert batch.shape == (4, 64) and batch.dtype == np.int32
    # every crop is a contiguous slice of the corpus
    for row in batch:
        start = np.where(tokens == row[0])[0]
        assert any(np.array_equal(tokens[s:s + 64], row) for s in start)
    # different seeds draw different crops
    other = next(token_stream(path, batch_size=4, seq_len=64, seed=1))
    assert not np.array_equal(batch, other)


def test_token_stream_u32_extension(tmp_path):
    path = str(tmp_path / "corpus.u32")
    np.arange(300, dtype="<u4").tofile(path)
    batch = next(token_stream(path, batch_size=2, seq_len=16))
    assert batch.dtype == np.int32 and batch.max() < 300


def test_token_file_errors(tmp_path):
    with pytest.raises(FileNotFoundError):
        load_tokens(str(tmp_path / "missing.bin"))
    empty = str(tmp_path / "empty.bin")
    open(empty, "wb").close()
    with pytest.raises(ValueError, match="empty"):
        load_tokens(empty)
    short = str(tmp_path / "short.bin")
    np.arange(8, dtype="<u2").tofile(short)
    with pytest.raises(ValueError, match="need at least"):
        next(token_stream(short, batch_size=1, seq_len=64))


def test_npz_stream_epochs(tmp_path):
    path = str(tmp_path / "set.npz")
    x = np.arange(40, dtype=np.float32).reshape(10, 4)
    y = np.arange(10, dtype=np.int32)
    np.savez(path, x=x, y=y)
    stream = npz_stream(path, batch_size=4, seed=0)
    seen = []
    for _ in range(4):  # crosses an epoch boundary (10//4 = 2 batches/epoch)
        bx, by = next(stream)
        assert bx.shape == (4, 4) and by.shape == (4,)
        np.testing.assert_array_equal(bx[:, 0], y[by] * 4.0)  # x/y aligned
        seen.extend(by.tolist())
    assert len(set(seen)) > 4  # shuffling covers the set across epochs


def test_npz_stream_errors(tmp_path):
    bad = str(tmp_path / "bad.npz")
    np.savez(bad, x=np.zeros((4, 2)), labels=np.zeros(4))
    with pytest.raises(ValueError, match="lacks arrays"):
        next(npz_stream(bad, batch_size=2))
    mismatched = str(tmp_path / "mismatch.npz")
    np.savez(mismatched, x=np.zeros((4, 2)), y=np.zeros(3))
    with pytest.raises(ValueError, match="!="):
        next(npz_stream(mismatched, batch_size=2))


def test_registry_file_data_dispatch(tmp_path):
    from parameter_server_distributed_tpu.models.registry import (
        get_model_and_batches)

    tokens = str(tmp_path / "lm.bin")
    np.random.default_rng(0).integers(0, 1024, 2000).astype("<u2").tofile(tokens)
    model, batches = get_model_and_batches("small_lm", 2, data_path=tokens)
    batch = next(batches)
    assert batch.shape == (2, model.config.max_seq)

    images = str(tmp_path / "mnist.npz")
    np.savez(images, x=np.zeros((8, 784), np.float32),
             y=np.zeros(8, np.int32))
    model, batches = get_model_and_batches("mnist_mlp", 4, data_path=images)
    bx, by = next(batches)
    assert bx.shape == (4, 784)


def test_train_cli_with_file_data(tmp_path):
    """End to end: the SPMD train loop consumes a real npz dataset."""
    from parameter_server_distributed_tpu.parallel.train_loop import (
        TrainLoopConfig, run_training)

    rng = np.random.default_rng(0)
    centers = rng.standard_normal((10, 784)).astype(np.float32)
    y = rng.integers(0, 10, 256).astype(np.int32)
    x = (2.0 * centers[y]
         + rng.standard_normal((256, 784)).astype(np.float32))
    path = str(tmp_path / "train.npz")
    np.savez(path, x=x.astype(np.float32), y=y)

    summary = run_training(TrainLoopConfig(
        model="mnist_mlp", batch_size=32, steps=6, data_path=path,
        learning_rate=1e-2, log_every=100))
    assert np.isfinite(summary["final_loss"])
    assert summary["final_loss"] < 2.5  # learning on the file data


def test_token_stream_final_crop_reachable(tmp_path):
    """A file of exactly seq_len tokens yields that single full crop —
    the last token is not dead data."""
    path = str(tmp_path / "exact.bin")
    tokens = np.arange(16, dtype="<u2")
    tokens.tofile(path)
    batch = next(token_stream(path, batch_size=3, seq_len=16))
    for row in batch:
        np.testing.assert_array_equal(row, tokens.astype(np.int32))


def test_token_stream_vocab_validation(tmp_path):
    path = str(tmp_path / "oov.bin")
    np.full(100, 5000, dtype="<u2").tofile(path)
    with pytest.raises(ValueError, match="wrong tokenizer"):
        next(token_stream(path, batch_size=2, seq_len=16, vocab=1024))


def test_prefetch_to_device():
    """prefetch_to_device: same batches in order, loader/placement errors
    surface at next(), close() stops the worker."""
    import numpy as np
    from parameter_server_distributed_tpu.data.prefetch import (
        prefetch_to_device)

    def loader(n):
        for i in range(n):
            yield np.full((2, 2), i)

    got = list(prefetch_to_device(loader(5), place=lambda b: b * 10))
    assert [int(b[0, 0]) for b in got] == [0, 10, 20, 30, 40]

    def bad_loader():
        yield np.ones((1,))
        raise RuntimeError("loader died")

    it = prefetch_to_device(bad_loader(), place=lambda b: b)
    next(it)
    import pytest
    with pytest.raises(RuntimeError, match="loader died"):
        next(it)

    def endless():
        i = 0
        while True:
            yield np.full((1,), i)
            i += 1

    it = prefetch_to_device(endless(), place=lambda b: b, depth=1)
    assert int(next(it)[0]) == 0
    it.close()  # worker must stop even though the stream is endless
