"""Flat arena apply (ISSUE 15, core/arena.py): the bit-identity oracle
flat == per-tensor == numpy across 5 optimizers x stripe counts x fold
residences, the close dispatch-count bound (one kernel per stage per
stripe regardless of tensor count), packing-table stability/rebuild on
retire (tombstoned names vacate their slab, epoch fence), checkpoint
round-trips across PSDT_ARENA on/off and restore stripe counts, the
downgrade matrix (coverage / non-uniform counts / mixed momentum seeding
/ packing failure), serve-encode + delta-build byte identity, and a
lockcheck-marked concurrent push/close/serve hammer under the flag."""

import os
import threading

import numpy as np
import pytest

from parameter_server_distributed_tpu import native
from parameter_server_distributed_tpu.async_sgd.device_optimizer import (
    ShardedDeviceOptimizer)
from parameter_server_distributed_tpu.checkpoint.manager import (
    CheckpointManager)
from parameter_server_distributed_tpu.core import arena, device_apply
from parameter_server_distributed_tpu.core.optimizer import make_optimizer
from parameter_server_distributed_tpu.core.ps_core import ParameterServerCore
from parameter_server_distributed_tpu.obs import stats as obs_stats


def _jnp():
    import jax.numpy as jnp

    return jnp


@pytest.fixture(autouse=True)
def _arena_on(monkeypatch):
    """Every test here runs under the flag (the off path is covered by
    the whole pre-existing suite plus the each_arena "0" legs)."""
    if not device_apply.available():
        pytest.skip("no jax backend/device")
    monkeypatch.setenv(arena.ENV_ARENA, "1")
    yield


@pytest.fixture
def numpy_oracle():
    native.set_enabled(False)
    try:
        yield
    finally:
        native.set_enabled(
            os.environ.get("PSDT_NATIVE", "1").lower()
            not in ("0", "false"))


def _shapes():
    # odd sizes + matrices (exercise the adamw/lion decay-mask lanes and
    # uneven stripe partitions)
    return {"emb/w": (129, 33), "l0/w": (64, 65), "l0/b": (65,),
            "head/w": (33, 17), "odd": (513,)}


def _stores_equal(a, b) -> bool:
    if set(a) != set(b):
        return False
    return all(np.asarray(a[k], np.float32).tobytes()
               == np.asarray(b[k], np.float32).tobytes() for k in a)


def _closes(core, grads_by_iter, workers=2, device=False):
    jnp = _jnp() if device else None
    for it, grads in enumerate(grads_by_iter, start=1):
        for wid in range(workers):
            payload = ({k: jnp.asarray(g) for k, g in grads.items()}
                       if device else
                       {k: g.copy() for k, g in grads.items()})
            r = core.receive_gradients(wid, it, payload)
        assert r.aggregation_complete, r.message
    return {k: np.asarray(v, np.float32)
            for k, v in core.get_parameters().items()}


def _arena_counters():
    c = obs_stats.REGISTRY.snapshot().get("counters", {})
    return c.get("ps.apply.arena", 0), c.get("ps.apply.arena_fallback", 0)


# --------------------------------------------------------------- oracle
@pytest.mark.parametrize("stripes", [1, 2, 4])
@pytest.mark.parametrize("rule", ShardedDeviceOptimizer.RULES)
@pytest.mark.parametrize("device_grads", [False, True])
def test_flat_close_bit_identical_to_numpy(rule, stripes, device_grads,
                                           numpy_oracle, rng):
    """The triangle: flat (PSDT_ARENA=1) == per-tensor numpy oracle,
    across all five rules x stripe counts x fold residences — and the
    closes really ran flat (counter-asserted, no silent fallback)."""
    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    grads_by_iter = [
        {k: rng.standard_normal(s).astype(np.float32)
         for k, s in shapes.items()} for _ in range(3)]

    host_core = ParameterServerCore(total_workers=2, stripes=stripes,
                                    optimizer=make_optimizer(rule, 0.02))
    host_core.initialize_parameters(params)
    host = _closes(host_core, grads_by_iter)

    before, fb_before = _arena_counters()
    core = ParameterServerCore(total_workers=2, stripes=stripes,
                               optimizer=ShardedDeviceOptimizer(rule,
                                                                0.02))
    assert core._arena is not None and core._arena.active
    core.initialize_parameters(params)
    flat = _closes(core, grads_by_iter, device=device_grads)
    after, fb_after = _arena_counters()
    assert _stores_equal(host, flat)
    assert after >= before + 3, "closes did not run flat"
    assert fb_after == fb_before, "unexpected arena fallback"
    # the published store is an ArenaStore of zero-copy slab views
    store = core.get_parameters()
    layout = core._params.layout
    some = next(iter(store))
    e = layout.entries[some]
    assert np.shares_memory(store[some], core._params.slabs[e.stripe])


def test_flat_equals_per_tensor_device(numpy_oracle, rng, monkeypatch):
    """flat == per-tensor DEVICE path bit for bit (the third corner of
    the triangle: PR 11's path is itself oracle-proven)."""
    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    grads_by_iter = [
        {k: rng.standard_normal(s).astype(np.float32)
         for k, s in shapes.items()} for _ in range(3)]

    def run():
        core = ParameterServerCore(
            total_workers=2, stripes=2,
            optimizer=ShardedDeviceOptimizer("adamw", 0.02))
        core.initialize_parameters(params)
        return _closes(core, grads_by_iter, device=True)

    flat = run()
    monkeypatch.setenv(arena.ENV_ARENA, "0")
    per_tensor = run()
    assert _stores_equal(flat, per_tensor)


# ------------------------------------------------------- dispatch bound
@pytest.mark.parametrize("rule", ShardedDeviceOptimizer.RULES)
def test_close_dispatch_bound(rule, numpy_oracle, rng):
    """The acceptance bound: a flat close dispatches <= stages x stripes
    kernels REGARDLESS of tensor count (64 tensors here; the per-tensor
    path's operand count scales O(tensors)).  Counted via the kernel-
    library probe — fold lanes (slab_update/assemble) never route
    through k(), so the count is exactly the close stages."""
    stripes = 2
    shapes = {f"t{i:03d}": (64, 16) for i in range(64)}
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    grads = {k: rng.standard_normal(s).astype(np.float32)
             for k, s in shapes.items()}
    core = ParameterServerCore(total_workers=2, stripes=stripes,
                               optimizer=ShardedDeviceOptimizer(rule,
                                                                0.02))
    core.initialize_parameters(params)
    for it in (1, 2):  # it=1 warms jit + seeds slots
        core.receive_gradients(0, it, {k: g.copy()
                                       for k, g in grads.items()})
        if it == 1:
            core.receive_gradients(1, it, {k: g.copy()
                                           for k, g in grads.items()})
    real_k = device_apply.k
    calls = {"n": 0}

    def counting_k(name, _rk=real_k):
        calls["n"] += 1
        return _rk(name)

    device_apply.k = counting_k
    try:
        r = core.receive_gradients(1, 2, {k: g.copy()
                                          for k, g in grads.items()})
    finally:
        device_apply.k = real_k
    assert r.aggregation_complete
    budget = arena.close_dispatch_budget(rule, stripes)
    assert 0 < calls["n"] <= budget, (calls["n"], budget)


# ------------------------------------------------ packing table / epoch
def test_packing_table_stable_and_decay_prefix(rng):
    """Same store => identical offsets (process-stable, sorted
    decayed-first order); the decay mask is a per-stripe prefix; only a
    SHAPE change rebuilds (epoch fence) — value changes never do."""
    shapes = _shapes()
    store = {k: rng.standard_normal(s).astype(np.float32)
             for k, s in shapes.items()}
    t1 = arena.PackingTable(store, 2, epoch=1)
    t2 = arena.PackingTable(dict(reversed(list(store.items()))), 2,
                            epoch=1)
    assert {n: (e.stripe, e.offset, e.length, e.shape)
            for n, e in t1.entries.items()} == \
           {n: (e.stripe, e.offset, e.length, e.shape)
            for n, e in t2.entries.items()}
    for stripe in range(2):
        decayed = [t1.entries[n].decayed for n in t1.stripe_names[stripe]]
        assert decayed == sorted(decayed, reverse=True)  # prefix
    mgr = arena.ArenaManager(2)
    ta = mgr.ensure_table(store)
    changed_values = {k: v * 2 for k, v in store.items()}
    tb = mgr.ensure_table(changed_values)
    assert tb.epoch == ta.epoch  # same signature: no rebuild
    reshaped = dict(store)
    reshaped["odd"] = rng.standard_normal((3, 171)).astype(np.float32)
    tc = mgr.ensure_table(reshaped)
    assert tc.epoch == ta.epoch + 1  # shape change: epoch fence bumped


def test_alignment_pads_and_stays_exact(numpy_oracle, rng, monkeypatch):
    """PSDT_ARENA_ALIGN pads slab offsets; padding is reported by the
    gauge, never scattered into, and the closes stay bit-exact."""
    monkeypatch.setenv(arena.ENV_ALIGN, "32")
    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    grads_by_iter = [
        {k: rng.standard_normal(s).astype(np.float32)
         for k, s in shapes.items()} for _ in range(2)]
    host_core = ParameterServerCore(total_workers=1, stripes=2,
                                    optimizer=make_optimizer("adam",
                                                             0.02))
    host_core.initialize_parameters(params)
    host = _closes(host_core, grads_by_iter, workers=1)
    core = ParameterServerCore(total_workers=1, stripes=2,
                               optimizer=ShardedDeviceOptimizer("adam",
                                                                0.02))
    core.initialize_parameters(params)
    flat = _closes(core, grads_by_iter, workers=1)
    assert _stores_equal(host, flat)
    table = core._params.layout
    assert table.padding_elems > 0
    pad = obs_stats.REGISTRY.snapshot()["gauges"]["ps.apply.arena_pad"]
    assert pad > 0


def test_retire_vacates_slab_and_rebuilds(numpy_oracle, rng):
    """A reshard retire tombstones names: the in-flight iteration falls
    back per-tensor (popped names vacate coverage), the NEXT table epoch
    drops them from the slab, and the store tracks the host oracle
    through the whole sequence bit for bit."""
    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    seq = [{k: rng.standard_normal(s).astype(np.float32)
            for k, s in shapes.items()} for _ in range(2)]
    rest = {k: s for k, s in shapes.items() if k != "odd"}
    seq_after = [{k: rng.standard_normal(s).astype(np.float32)
                  for k, s in rest.items()} for _ in range(2)]

    def run(opt):
        core = ParameterServerCore(total_workers=1, stripes=2,
                                   optimizer=opt)
        core.initialize_parameters(params)
        for it, grads in enumerate(seq, start=1):
            r = core.receive_gradients(0, it, {k: g.copy()
                                               for k, g in grads.items()})
            assert r.aggregation_complete
        core.retire_tensors(["odd"], map_epoch=9)
        for it, grads in enumerate(seq_after, start=3):
            r = core.receive_gradients(0, it, {k: g.copy()
                                               for k, g in grads.items()})
            assert r.aggregation_complete
        return core

    dev = run(ShardedDeviceOptimizer("momentum", 0.05))
    host = run(make_optimizer("momentum", 0.05))
    assert _stores_equal(dev.get_parameters(), host.get_parameters())
    table = dev._params.layout
    assert "odd" not in table.entries  # the tombstoned name vacated


# ----------------------------------------------------------- checkpoint
@pytest.mark.parametrize("save_stripes,restore_stripes", [(2, 1), (1, 4)])
def test_checkpoint_roundtrip_across_arena_flag(save_stripes,
                                                restore_stripes,
                                                tmp_path, numpy_oracle,
                                                rng, monkeypatch):
    """Slot state saved from arena slabs restores bit-identically into a
    PSDT_ARENA=0 core (and a host optimizer), across restore stripe
    counts — the .ckpt layout is the host optimizers', unchanged."""
    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    grads_by_iter = [
        {k: rng.standard_normal(s).astype(np.float32)
         for k, s in shapes.items()} for _ in range(4)]

    core_a = ParameterServerCore(total_workers=1, stripes=save_stripes,
                                 optimizer=ShardedDeviceOptimizer(
                                     "adam", 0.02))
    core_a.initialize_parameters(params)
    _closes(core_a, grads_by_iter[:2], workers=1)
    path = CheckpointManager(core_a, directory=str(tmp_path)).save(epoch=3)

    for flag, opt in (("0", ShardedDeviceOptimizer("adam", 0.02)),
                      ("1", ShardedDeviceOptimizer("adam", 0.02)),
                      ("1", make_optimizer("adam", 0.02))):
        monkeypatch.setenv(arena.ENV_ARENA, flag)
        core_b = ParameterServerCore(total_workers=1,
                                     stripes=restore_stripes,
                                     optimizer=opt)
        CheckpointManager(core_b, directory=str(tmp_path)).load(path)
        assert _stores_equal(core_b.get_parameters(),
                             core_a.get_parameters())
        _closes(core_b, grads_by_iter[2:], workers=1)
        ref = ParameterServerCore(total_workers=1, stripes=save_stripes,
                                  optimizer=make_optimizer("adam", 0.02))
        ref.restore(3, 2, core_a.get_parameters(),
                    optimizer_state=core_a.optimizer_state())
        _closes(ref, grads_by_iter[2:], workers=1)
        assert _stores_equal(core_b.get_parameters(),
                             ref.get_parameters()), (flag, type(opt))


# ------------------------------------------------------ downgrade rows
def test_partial_coverage_falls_back_per_tensor(numpy_oracle, rng):
    """A close whose gradients skip a name (pass-through) cannot run
    flat — it downgrades to the per-tensor path for THAT close (counter
    + flight), stays bit-exact, and the next full close runs flat
    again."""
    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    seq = [{k: rng.standard_normal(s).astype(np.float32)
            for k, s in shapes.items()} for _ in range(3)]
    seq[1].pop("odd")  # iteration 2: partial shard

    def run(opt):
        core = ParameterServerCore(total_workers=1, stripes=2,
                                   optimizer=opt)
        core.initialize_parameters(params)
        return _closes(core, seq, workers=1), core

    before, fb_before = _arena_counters()
    flat, core = run(ShardedDeviceOptimizer("adam", 0.02))
    after, fb_after = _arena_counters()
    host, _ = run(make_optimizer("adam", 0.02))
    assert _stores_equal(host, flat)
    assert fb_after == fb_before + 1     # exactly the partial close
    assert after >= before + 2           # the full closes ran flat


def test_nonuniform_counts_fall_back(numpy_oracle, rng):
    """Disjoint-subset pushes (the sharded topology) give per-name
    counts that are not uniform: the flat scalar scale cannot represent
    them, so the close downgrades — and matches the host oracle."""
    shapes = {"a": (31,), "b": (17,)}
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    ga = {"a": rng.standard_normal((31,)).astype(np.float32)}
    gb = {"b": rng.standard_normal((17,)).astype(np.float32),
          "a": rng.standard_normal((31,)).astype(np.float32)}

    def run(opt):
        core = ParameterServerCore(total_workers=2, stripes=1,
                                   optimizer=opt)
        core.initialize_parameters(params)
        core.receive_gradients(0, 1, {k: g.copy() for k, g in ga.items()})
        r = core.receive_gradients(1, 1, {k: g.copy()
                                          for k, g in gb.items()})
        assert r.aggregation_complete
        return {k: np.asarray(v, np.float32)
                for k, v in core.get_parameters().items()}

    _, fb_before = _arena_counters()
    flat = run(ShardedDeviceOptimizer("sgd", 0.1))
    _, fb_after = _arena_counters()
    host = run(make_optimizer("sgd", 0.1))
    assert _stores_equal(host, flat)
    assert fb_after == fb_before + 1


def test_momentum_mixed_seed_falls_back(numpy_oracle, rng):
    """A velocity table covering only SOME names (reshard merge) cannot
    flatten (the copy-seed is per name): arena_ready refuses, the close
    runs per-tensor, and the result matches the host oracle.  The
    fallback SELF-HEALS: that close seeds every name's velocity, so the
    next close runs flat again."""
    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    vel = {"velocity": {"odd": rng.standard_normal((513,)).astype(
        np.float32)}}
    grads = [{k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()} for _ in range(2)]

    def run(opt):
        opt.load_state_dict({"velocity": {
            k: v.copy() for k, v in vel["velocity"].items()}})
        core = ParameterServerCore(total_workers=1, stripes=2,
                                   optimizer=opt)
        core.initialize_parameters(params)
        return _closes(core, grads, workers=1)

    closes_before, fb_before = _arena_counters()
    flat = run(ShardedDeviceOptimizer("momentum", 0.05))
    closes_after, fb_after = _arena_counters()
    host = run(make_optimizer("momentum", 0.05))
    assert _stores_equal(host, flat)
    assert fb_after == fb_before + 1   # the mixed close refused flat
    assert closes_after >= closes_before + 1  # ... and then self-healed


def test_broadcast_fold_evicts_slab_sum_exactly(numpy_oracle, rng):
    """Review regression: the same name folding into the slab (exact
    shape, worker A) and then arriving broadcast-shaped (worker B — the
    host fold's legal broadcast-up) must converge in ONE accumulator:
    the slab-resident partial sum is EVICTED into overflow and the
    broadcast add lands on it, so the fallback close's mean covers both
    contributions — bit-identical to the host oracle."""
    shapes = {"w": (4, 31), "b": (17,)}
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    ga = {k: rng.standard_normal(s).astype(np.float32)
          for k, s in shapes.items()}
    gb = {"w": rng.standard_normal((31,)).astype(np.float32),  # (31,)
          "b": rng.standard_normal((17,)).astype(np.float32)}  # broadcasts

    def run(opt):
        core = ParameterServerCore(total_workers=2, stripes=1,
                                   optimizer=opt)
        core.initialize_parameters(params)
        core.receive_gradients(0, 1, {k: g.copy() for k, g in ga.items()})
        r = core.receive_gradients(1, 1, {k: g.copy()
                                          for k, g in gb.items()})
        assert r.aggregation_complete
        return {k: np.asarray(v, np.float32)
                for k, v in core.get_parameters().items()}

    flat = run(ShardedDeviceOptimizer("sgd", 0.1))
    host = run(make_optimizer("sgd", 0.1))
    assert _stores_equal(host, flat)


def test_momentum_store_growth_respects_copy_seed(numpy_oracle, rng):
    """Review regression: slot slabs packed for an OLD table epoch must
    not short-circuit arena_ready after the store grows — the new
    name's velocity is unseeded, so repacking it as zeros would replace
    the copy-seed with mu*0+g.  The grown close must fall back (then
    self-heal) and stay bit-identical to the host oracle."""
    shapes = {"a/w": (13, 7), "b": (29,)}
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    grown = dict(shapes, new=(11,))

    def run(opt, seed=21):
        gen = np.random.default_rng(seed)
        core = ParameterServerCore(total_workers=1, stripes=1,
                                   optimizer=opt)
        core.initialize_parameters(params)
        g1 = {k: gen.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
        r = core.receive_gradients(0, 1, {k: v.copy()
                                          for k, v in g1.items()})
        assert r.aggregation_complete
        # the store grows a tensor (an install): table epoch bumps
        core.install_tensors(
            {"new": np.zeros((11,), np.float32)}, mark_aggregated=False)
        for it in (2, 3):
            g = {k: gen.standard_normal(s).astype(np.float32)
                 for k, s in grown.items()}
            # the copy-seed witness: a zeros-repacked velocity would
            # turn this element's seed into mu*0 + (-0.0) = +0.0
            g["new"][0] = np.float32(-0.0)
            r = core.receive_gradients(0, it, {k: v.copy()
                                               for k, v in g.items()})
            assert r.aggregation_complete
        return ({k: np.asarray(v, np.float32)
                 for k, v in core.get_parameters().items()},
                core.optimizer_state())

    _, fb_before = _arena_counters()
    flat, flat_opt = run(ShardedDeviceOptimizer("momentum", 0.05))
    _, fb_after = _arena_counters()
    host, host_opt = run(make_optimizer("momentum", 0.05))
    assert _stores_equal(host, flat)
    # slot bytes too: the -0.0 seed lives in the velocity slot
    assert _stores_equal(host_opt["velocity"], flat_opt["velocity"])
    assert fb_after >= fb_before + 1  # the grown close refused flat


def test_packing_failure_latches_off_never_fails(numpy_oracle, rng,
                                                 monkeypatch):
    """A packing EXCEPTION mid-close completes the close on the
    per-tensor path and latches the arena off — training continues,
    bit-exact, no boot/close failure."""
    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    grads = [{k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()} for _ in range(2)]
    core = ParameterServerCore(total_workers=1, stripes=2,
                               optimizer=ShardedDeviceOptimizer("adam",
                                                                0.02))
    core.initialize_parameters(params)

    def boom(*a, **kw):
        raise RuntimeError("injected packing failure")

    monkeypatch.setattr(core._arena, "ensure_param_slabs", boom)
    flat = _closes(core, grads, workers=1)
    assert not core._arena.active  # latched off
    host_core = ParameterServerCore(total_workers=1, stripes=2,
                                    optimizer=make_optimizer("adam",
                                                             0.02))
    host_core.initialize_parameters(params)
    host = _closes(host_core, grads, workers=1)
    assert _stores_equal(host, flat)


def test_env_gate_off_means_no_manager(monkeypatch):
    monkeypatch.setenv(arena.ENV_ARENA, "0")
    core = ParameterServerCore(total_workers=1,
                               optimizer=ShardedDeviceOptimizer("sgd",
                                                                0.1))
    assert core._arena is None
    # buffered/async cores never arm the arena either
    monkeypatch.setenv(arena.ENV_ARENA, "1")
    buffered = ParameterServerCore(total_workers=1, aggregation="buffered",
                                   optimizer=ShardedDeviceOptimizer(
                                       "sgd", 0.1))
    assert buffered._arena is None
    host = ParameterServerCore(total_workers=1,
                               optimizer=make_optimizer("sgd", 0.1))
    assert host._arena is None  # host optimizers have no flat stages


# ------------------------------------------------- serve + delta bytes
def test_serve_and_delta_bytes_identical(numpy_oracle, rng):
    """Acceptance: serve-cache encode bodies and delta pairs under
    PSDT_ARENA=1 are byte-identical to the per-tensor path's (the slab
    views and the slab diff change WHERE bytes come from, never the
    bytes)."""
    from parameter_server_distributed_tpu.core.tensor import to_wire
    from parameter_server_distributed_tpu.delta.chain import DeltaChain
    from parameter_server_distributed_tpu.rpc.codec import WIRE_BF16
    from parameter_server_distributed_tpu.rpc.data_plane import (
        encode_parameter_record_groups, split_tensors)

    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    grads = [{k: (1e-4 * rng.standard_normal(s)).astype(np.float32)
              for k, s in shapes.items()} for _ in range(3)]

    def run(opt):
        core = ParameterServerCore(total_workers=1, stripes=2,
                                   optimizer=opt)
        chain = DeltaChain(depth=4, wire_dtype=WIRE_BF16, stripes=2)
        core.set_delta_sink(chain, seed=False)
        core.initialize_parameters(params)
        _closes(core, grads, workers=1)
        _, store, _, _ = core.serve_view()
        bodies = encode_parameter_record_groups(
            [g for g in split_tensors(to_wire(store), 1 << 20)], 2)
        pairs = [(fv, p.to_version, p.crc, p.changed, p.entries)
                 for fv, p in chain._pairs.items()]
        return bodies, pairs

    flat_bodies, flat_pairs = run(ShardedDeviceOptimizer("adam", 0.02))
    host_bodies, host_pairs = run(make_optimizer("adam", 0.02))
    assert flat_bodies == host_bodies
    assert flat_pairs == host_pairs
    assert len(flat_pairs) >= 2  # slab-diffed pairs actually built


# --------------------------------------------------------------- hammer
@pytest.mark.lockcheck
def test_concurrent_push_close_serve_hammer(numpy_oracle, rng):
    """Concurrent pushes (device buffers), flat closes, checkpoint
    snapshots, and serves under the runtime lock-order checker; the
    final store must equal the single-threaded oracle."""
    jnp = _jnp()
    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    grads_by_iter = [
        {k: rng.standard_normal(s).astype(np.float32)
         for k, s in shapes.items()} for _ in range(5)]
    n_workers = 3
    core = ParameterServerCore(total_workers=n_workers, stripes=2,
                               optimizer=ShardedDeviceOptimizer("adam",
                                                                0.02))
    assert core._arena is not None
    core.initialize_parameters(params)
    stop = threading.Event()
    errors: list = []

    def server_noise():
        while not stop.is_set():
            try:
                core.serve_parameters()
                core.get_parameters()
                core.optimizer_state()
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)
                return

    noise = threading.Thread(target=server_noise, name="arena-noise",
                             daemon=True)
    noise.start()
    gate = threading.Barrier(n_workers)

    def worker(wid: int):
        try:
            for it, grads in enumerate(grads_by_iter, start=1):
                gate.wait(timeout=30)
                core.receive_gradients(
                    wid, it, {k: jnp.asarray(g)
                              for k, g in grads.items()})
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(w,),
                                name=f"arena-w{w}", daemon=True)
               for w in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    stop.set()
    noise.join(timeout=10)
    assert not errors, errors

    ref = ParameterServerCore(total_workers=n_workers,
                              optimizer=ShardedDeviceOptimizer("adam",
                                                               0.02))
    ref.initialize_parameters(params)
    for it, grads in enumerate(grads_by_iter, start=1):
        for wid in range(n_workers):
            ref.receive_gradients(wid, it, {k: g.copy()
                                            for k, g in grads.items()})
    assert _stores_equal(core.get_parameters(), ref.get_parameters())


def test_failed_apply_leaves_barrier_retryable(numpy_oracle, rng):
    """A raise inside the flat apply puts the (scaled) accumulator back
    and the next poll retries the close — sums are never donated into
    the stages, so the retry reads live slabs; stripes=1 so the raise
    precedes any slot mutation and the retry is bit-exact vs clean."""
    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}

    class Flaky(ShardedDeviceOptimizer):
        fail = True

        def apply_arena(self, table, param_slabs, grad_slabs):
            if Flaky.fail:
                Flaky.fail = False
                raise RuntimeError("injected arena apply failure")
            return super().apply_arena(table, param_slabs, grad_slabs)

    core = ParameterServerCore(total_workers=1, stripes=1,
                               optimizer=Flaky("momentum", 0.02))
    core.initialize_parameters(params)
    grads = {k: rng.standard_normal(s).astype(np.float32)
             for k, s in shapes.items()}
    with pytest.raises(RuntimeError):
        core.receive_gradients(0, 1, {k: g.copy()
                                      for k, g in grads.items()})
    _, complete, _, _ = core.check_sync_status(1)
    assert complete
    ref = ParameterServerCore(total_workers=1, stripes=1,
                              optimizer=ShardedDeviceOptimizer(
                                  "momentum", 0.02))
    ref.initialize_parameters(params)
    ref.receive_gradients(0, 1, {k: g.copy() for k, g in grads.items()})
    assert _stores_equal(core.get_parameters(), ref.get_parameters())


def test_timeline_renders_arena_line():
    """pst-trace iteration timelines carry an 'arena:' line with the
    pack/dispatch/readback phases (and the fallback reason when a close
    downgraded)."""
    from parameter_server_distributed_tpu.obs import postmortem

    base = {"pid": 1, "tid": 1, "worker": -1, "a": 0, "b": 0,
            "note": "", "role": "ps"}
    events = [
        dict(base, ts=1.0, event="barrier.seal", iteration=7, a=2),
        dict(base, ts=1.001, event="apply.arena.pack", iteration=7,
             a=1200, b=2),
        dict(base, ts=1.01, event="apply.start", iteration=7),
        dict(base, ts=1.02, event="apply.end", iteration=7, a=9000),
        dict(base, ts=1.02, event="apply.arena", iteration=7, a=5000,
             b=2000),
        dict(base, ts=1.03, event="barrier.publish", iteration=7, a=2,
             b=2),
    ]
    tl = postmortem.iteration_timeline(events, 7)
    assert tl["arena"]["dispatch_s"] == pytest.approx(5e-3)
    assert tl["arena"]["readback_s"] == pytest.approx(2e-3)
    assert tl["arena"]["pack_s"] == pytest.approx(1.2e-3)
    report = postmortem.render_report({
        "directory": "/tmp/flight", "processes": [],
        "iterations": {"seen": [7], "published": [7]},
        "iteration": 7, "timeline": tl, "narrative": {}})
    assert "arena:" in report and "dispatch" in report

    fb = [dict(base, ts=1.0, event="apply.arena.fallback", iteration=3,
               note="coverage"),
          dict(base, ts=1.01, event="barrier.publish", iteration=3,
               a=1, b=1)]
    tl = postmortem.iteration_timeline(fb, 3)
    assert tl["arena_fallback"] == "coverage"


def test_rollup_renders_arena_line(numpy_oracle, rng):
    from parameter_server_distributed_tpu.obs.export import (
        render_rollup, worker_rollup)

    shapes = _shapes()
    params = {k: rng.standard_normal(s).astype(np.float32)
              for k, s in shapes.items()}
    core = ParameterServerCore(total_workers=1, stripes=2,
                               optimizer=ShardedDeviceOptimizer("sgd",
                                                                0.05))
    core.initialize_parameters(params)
    r = core.receive_gradients(0, 1, {
        k: rng.standard_normal(s).astype(np.float32)
        for k, s in shapes.items()})
    assert r.aggregation_complete
    snap = obs_stats.REGISTRY.snapshot()
    rolled = worker_rollup(snap)
    assert rolled["ps"]["arena"]["applies"] >= 1
    text = render_rollup({"cluster": {}, "per_worker": {0: rolled}})
    assert "flat closes" in text
