"""bench.py driver-artifact contract: exactly one parseable JSON line on
stdout with a non-zero value, whatever the backend situation.

A bench.py regression silently costs the round's BENCH_r{N}.json, so the
orchestrator is exercised end to end (parent process -> subprocess child ->
JSON line) in CPU mode with tiny shapes.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def run_bench(mode: str, extra_env: dict | None = None,
              timeout: float = 420.0) -> dict:
    env = dict(os.environ)
    env.update({
        "PSDT_BENCH_MODE": mode,
        # skip TPU attempts entirely: this test is about the orchestration
        # and JSON contract, not the accelerator
        "PSDT_BENCH_TPU_ATTEMPTS": "0",
        "PSDT_BENCH_CPU_TIMEOUT": str(int(timeout - 30)),
        "PSDT_BENCH_STEPS": "2",
        "PSDT_PLATFORM": "cpu",
    })
    env.pop("PSDT_BENCH_CHILD", None)
    env.update(extra_env or {})
    proc = subprocess.run([sys.executable, BENCH], env=env, cwd=REPO,
                          stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                          timeout=timeout)
    lines = [ln for ln in proc.stdout.decode().splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line, got {lines}"
    result = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in result, f"missing {key}: {result}"
    return result


@pytest.mark.slow
def test_bench_mfu_cpu_contract():
    result = run_bench("mfu")
    # CPU fallback with zero TPU attempts is not labeled a fallback (no
    # failed attempt preceded it) but must still be a real number
    assert result["metric"].startswith("mlp")
    assert result["value"] > 0
    assert result["metric"] != "bench_error"


@pytest.mark.slow
def test_bench_pushpull_contract():
    result = run_bench("pushpull")
    assert result["metric"].startswith("ps_pushpull_p50")
    assert result["value"] > 0


@pytest.mark.slow
def test_bench_preflight_spaced_retry_then_fallback():
    # With a TPU attempt requested but every preflight doomed (tiny probe
    # timeout: the probe subprocess cannot even finish importing jax), the
    # orchestrator must burn the whole retry window, then fall back to an
    # honestly-labeled CPU number that records the probe count.
    result = run_bench("mfu", extra_env={
        "PSDT_BENCH_TPU_ATTEMPTS": "1",
        # no python subprocess can import jax and run an op in 0.5 s, so
        # the probe fails deterministically even on a healthy backend
        "PSDT_BENCH_PREFLIGHT_TIMEOUT": "0.5",
        "PSDT_BENCH_PREFLIGHT_RETRIES": "2",
        "PSDT_BENCH_PREFLIGHT_SPACING_S": "0",
    })
    assert result["metric"].endswith("_cpu_fallback")
    assert "2 spaced probes" in result.get("note", "")
    assert result["value"] > 0


@pytest.mark.slow
def test_bench_codec_contract():
    """codec mode: native-vs-Python encode/decode GB/s per packed wire
    dtype plus the same-host shm-vs-TCP fused-step A/B, all visible in
    the JSON."""
    result = run_bench("codec", extra_env={
        "PSDT_BENCH_PARAMS": "4e5",
        "PSDT_BENCH_STEPS": "2",
    })
    assert result["metric"].startswith("codec_encode_gbps")
    assert result["value"] > 0
    for dtype in ("bf16", "int8", "topk"):
        assert result["encode"][dtype]["python"] > 0
        assert result["decode"][dtype]["python"] > 0
    same_host = result["same_host"]
    assert same_host["tcp"]["p50_ms"] > 0
    assert same_host["shm"]["p50_ms"] > 0
    assert same_host["shm"]["shm_active"] is True
    assert same_host["shm"]["shm_bytes"] > 0
    assert same_host["tcp"]["shm_active"] is False


@pytest.mark.slow
def test_bench_aggregate_contract():
    """aggregate mode: streaming-vs-buffered PS aggregation profile with
    the acceptance properties visible in the JSON — ~1x model peak
    gradient memory and one serve encode per version under streaming."""
    result = run_bench("aggregate", extra_env={
        "PSDT_BENCH_PARAMS": "2e5",
        "PSDT_BENCH_WORKER_COUNTS": "2,4",
        "PSDT_BENCH_STEPS": "2",
    })
    assert result["metric"].startswith("ps_aggregate_barrier_close_ms")
    assert result["value"] > 0
    streaming, buffered = result["streaming"], result["buffered"]
    assert streaming["4"]["peak_grad_buffer_x_model"] <= 1.5
    assert buffered["4"]["peak_grad_buffer_x_model"] >= 3.5
    # one encode per (version, dtype): 2 iterations -> 2 misses for 8 serves
    assert streaming["4"]["serve_encodes"] == 2
    assert streaming["4"]["serves"] == 8


def test_bench_delta_contract():
    """delta mode: per-pull serve bytes through the version-delta chain
    vs the full encode-once serve at varying version locality, for SGD
    and momentum runs, plus the live publication latency — with the
    ISSUE 10 acceptance bound visible in the JSON: delta bytes <= 30%
    of the full serve at locality 1 for BOTH optimizers."""
    result = run_bench("delta", extra_env={
        "PSDT_BENCH_PARAMS": "2e5",
        "PSDT_BENCH_STEPS": "4",
        "PSDT_BENCH_DELTA_LOCALITY": "1,2",
    })
    assert result["metric"] == "ps_delta_serve_ratio_l1"
    assert 0 < result["value"] <= 0.30
    for opt in ("sgd", "momentum"):
        rows = result[opt]
        assert rows["1"]["delta_vs_full_ratio"] <= 0.30, (opt, rows)
        assert rows["1"]["full_fallbacks"] == 0, (opt, rows)
        assert rows["1"]["delta_pulls"] == 4, (opt, rows)
        # a longer hop still beats (or matches) re-shipping the model
        assert rows["2"]["delta_vs_full_ratio"] < 1.0, (opt, rows)
    assert result["publish_samples"] >= 3
    assert result["publish_p50_ms"] > 0


def test_bench_elastic_contract():
    """elastic mode (ISSUE 13): healthy-worker iteration wall p50 under
    a K-of-N quorum vs all-of-N with one netsim-delayed straggler — the
    quorum arm must actually quorum-close (and fold the straggler
    forward), and its p50 must beat the all-of-N arm, which pays the
    straggler's injected delay on every barrier."""
    result = run_bench("elastic", extra_env={
        "PSDT_BENCH_PARAMS": "1e5",
        "PSDT_BENCH_STEPS": "5",
        "PSDT_BENCH_STRAGGLER_MS": "250",
        "PSDT_BENCH_GRACE_MS": "80",
    })
    assert result["metric"] == "ps_elastic_iter_wall_p50_ms_quorum"
    assert result["value"] > 0
    assert result["quorum"]["quorum_closes"] > 0
    assert result["quorum"]["stale_folds"] > 0
    assert result["all_of_n"]["quorum_closes"] == 0
    # the quorum exists to cut the straggler's delay out of the healthy
    # workers' iteration wall: K-of-N p50 strictly under all-of-N p50
    assert (result["quorum"]["iter_wall_p50_ms"]
            < result["all_of_n"]["iter_wall_p50_ms"]), result["note"]


def test_bench_freerun_contract():
    """freerun mode (ISSUE 16): steps/s and time-to-target-loss for the
    barrier-free apply-on-arrival arm vs K-of-N quorum vs all-of-N under
    a heterogeneous-speed netsim profile.  The free-run arm must
    actually run free (applies land, the barriered arms record none)
    and out-rate the all-of-N arm, which pays the slowest worker's
    injected delay on every barrier."""
    result = run_bench("freerun", extra_env={
        "PSDT_BENCH_PARAMS": "1e5",
        "PSDT_BENCH_STEPS": "5",
        "PSDT_BENCH_STRAGGLER_MS": "150",
        "PSDT_BENCH_GRACE_MS": "80",
    })
    assert result["metric"] == "ps_freerun_steps_per_s"
    assert result["value"] > 0
    assert result["freerun"]["freerun_applies"] > 0
    assert result["freerun"]["freerun_publishes"] > 0
    assert result["all_of_n"]["freerun_applies"] == 0
    assert result["quorum"]["freerun_applies"] == 0
    # barrier-free pushes never wait for the straggler: the free-run
    # steps/s rate must beat the all-of-N barrier's
    assert (result["freerun"]["steps_per_s"]
            > result["all_of_n"]["steps_per_s"]), result
    assert result["freerun"]["time_to_target_ms"] is not None


@pytest.mark.slow
def test_bench_fleet_contract():
    """fleet mode (ISSUE 14): streams/s + p99 TTFT vs fleet size under
    an open-loop load generator, each decode server a real pst-serve
    subprocess over loopback gRPC.  Capacity is pinned sleep-bound
    (PSDT_BENCH_ROUND_DELAY_MS) so the control plane's scaling shows
    even on a small CI host: 2 servers must sustain materially more
    streams/s than 1 against the same arrival schedule, with zero
    failed streams either way.  The high-prefix-share arm (ISSUE 20)
    must show the radix cache absorbing the shared system prompt: its
    fleet-wide prefill-token ratio well under the uniform arm's."""
    result = run_bench("fleet", extra_env={
        "PSDT_BENCH_STEPS": "6",
        "PSDT_BENCH_REQUESTS": "16",
        "PSDT_BENCH_FLEET_SIZES": "1,2",
        "PSDT_BENCH_ROUND_DELAY_MS": "25",
    }, timeout=540.0)
    assert result["metric"].startswith("fleet_streams_per_s")
    assert result["value"] > 0
    one, two = result["sizes"]["1"], result["sizes"]["2"]
    assert one["failed"] == 0 and two["failed"] == 0
    assert one["streams"] > 0 and two["streams"] > 0
    assert two["streams_per_s"] > 1.25 * one["streams_per_s"], \
        result["note"]
    prefix = result["sizes"]["prefix_share_x2"]
    assert prefix["failed"] == 0 and prefix["streams"] > 0
    # shared prefixes must not be re-prefilled: most prompt tokens are
    # the 48-token system prompt, forwarded once then served from the
    # radix cache — the ratio collapses vs the unique-prompt arm
    assert prefix["prefill_token_ratio"] < 0.5, result["note"]
    assert (prefix["prefill_token_ratio"]
            < two["prefill_token_ratio"]), result["note"]


@pytest.mark.slow
def test_bench_replicate_contract():
    """replicate mode: barrier-close overhead off/async/sync replication,
    failover wall-clock, and the 2->4 reshard's moved bytes — all
    visible in the JSON."""
    result = run_bench("replicate", extra_env={
        "PSDT_BENCH_PARAMS": "1e5",
        "PSDT_BENCH_STEPS": "2",
    })
    assert result["metric"] == "ps_replicate_close_ms_sync"
    assert result["value"] > 0
    assert set(result["close_ms"]) == {"off", "async", "sync"}
    assert all(v > 0 for v in result["close_ms"].values())
    assert result["failover_s"] > 0
    assert result["reshard_s"] > 0
    assert result["reshard_moved_bytes"] > 0


def test_bench_replicate_sharded_contract():
    """replicate mode, sharded-update sweep (ISSUE 18): close p50 and
    TRUE replication wire bytes/iteration (client-side request+response
    byte counters over the PushReplicaDelta / ShardedApplySlices /
    InstallSlabSlices legs), flat ship vs sharded raw vs sharded
    quantized — with the acceptance visible in the JSON: the measured
    closes really sharded, and both sharded arms move fewer bytes per
    iteration than the flat ship at 2 replicas without a slower close."""
    result = run_bench("replicate", extra_env={
        "PSDT_BENCH_PARAMS": "1e5",
        "PSDT_BENCH_STEPS": "3",
        "PSDT_BENCH_SHARDED_ONLY": "1",
        "PSDT_BENCH_SHARDED_TENSORS": "32",
        "PSDT_BENCH_REPLICA_COUNTS": "1,2",
    })
    assert result["metric"] == "ps_replicate_sharded_bytes_ratio_2r"
    assert 0 < result["value"] < 1.0
    sweep = result["sharded"]
    rows = {(r["replicas"], r["arm"]): r for r in sweep["rows"]}
    # single-replica baseline: no replication traffic at all
    assert rows[(1, "flat")]["bytes_per_iter"] == 0
    flat = rows[(2, "flat")]
    assert flat["bytes_per_iter"] > 0 and flat["sharded_closes"] == 0
    for arm in ("sharded_raw", "sharded_quant"):
        row = rows[(2, arm)]
        # every measured close sharded (the warmup close absorbed the
        # backup's catch-up flat ship)
        assert row["sharded_closes"] == sweep["steps"], row
        assert row["sharded_fallbacks"] == 0, row
        assert 0 < row["bytes_per_iter"] < flat["bytes_per_iter"], row
        # close p50 no worse than the flat ship (generous envelope: tiny
        # shapes on a loaded CI host are noise-dominated)
        assert row["close_p50_ms"] < 2.0 * flat["close_p50_ms"], row
    ratios = sweep["bytes_per_iter_vs_flat"]["2"]
    assert ratios["sharded_quant"] < ratios["sharded_raw"] < 1.0


@pytest.mark.slow
def test_bench_obs_contract():
    """obs mode: flight-recorder event throughput + fused-step overhead
    recorder-on vs -off, with both arms' p50s visible in the JSON (the
    ISSUE 8 '<2% of fused-step p50' acceptance surface)."""
    result = run_bench("obs", extra_env={
        "PSDT_BENCH_PARAMS": "5e4",
        "PSDT_BENCH_STEPS": "3",
    })
    assert result["metric"] == "obs_flight_overhead_pct"
    assert result["events_per_s"] > 10_000
    assert result["ns_per_event"] > 0
    assert result["fused_p50_ms"]["off"] > 0
    assert result["fused_p50_ms"]["on"] > 0
    assert result["events_per_fused_step"] > 0
    # the acceptance bound is generous here (tiny shapes on a loaded CI
    # host are noise-dominated); the real BENCH row runs default shapes
    assert abs(result["value"]) < 50.0


@pytest.mark.slow
def test_bench_apply_contract():
    """apply mode: striped barrier-close profile, serial vs striped side
    by side with the stripe counts visible in the JSON, plus the
    ISSUE 11 device-vs-numpy sweep rows (tiny store here — the real
    32/128/512 MB rows run at default shapes)."""
    result = run_bench("apply", extra_env={
        "PSDT_BENCH_PARAMS": "4e5",
        "PSDT_BENCH_STRIPE_COUNTS": "1,2",
        "PSDT_BENCH_WORKER_COUNTS": "2",
        "PSDT_BENCH_STEPS": "2",
        "PSDT_BENCH_DEVICE_MB": "2",
        "PSDT_BENCH_DEVICE_OPTS": "sgd",
        "PSDT_BENCH_DEVICE_STRIPES": "1,2",
        "PSDT_BENCH_FLAT_TENSORS": "0",  # flat sweep: its own contract
    })
    assert result["metric"] == "ps_apply_close_ms_2stripes_2w"
    assert result["value"] > 0
    assert set(result["by_stripes"]) == {"1", "2"}
    assert result["by_stripes"]["1"]["2"]["barrier_close_ms"] > 0
    # the striped cell reports its achieved apply parallelism
    assert result["by_stripes"]["2"]["2"].get("apply_parallelism", 0) > 0
    # device-vs-numpy rows: every (size, opt, stripes) cell carries both
    # arms' close p50 and the ratio; the best-of-stripes summary keys
    # follow the "<mb>mb_<opt>" convention
    sweep = result["device_vs_numpy"]
    rows = sweep["rows"]
    assert len(rows) == 2  # 1 size x 1 opt x 2 stripe counts
    for row in rows:
        assert row["store_mb"] == 2 and row["opt"] == "sgd"
        assert row["numpy_close_ms"] > 0
        assert row["device_close_ms"] > 0
        assert row["device_vs_numpy"] > 0
    assert "2mb_sgd" in sweep["best_ratio"]
    assert "cpu-jax" in sweep["backend"]


def test_bench_apply_flat_contract():
    """apply mode, flat-arena sweep (ISSUE 15): flat-vs-per-tensor rows
    over a many-small-tensor store, with the acceptance visible in the
    JSON — the flat arm's close dispatches at most stages x stripes
    kernel-library calls (counted by the jit-lowering probe, NOT wall
    clock) while the per-tensor arm's operand count scales O(tensors)."""
    from parameter_server_distributed_tpu.core import arena

    result = run_bench("apply", extra_env={
        "PSDT_BENCH_PARAMS": "1e5",
        "PSDT_BENCH_STRIPE_COUNTS": "1",
        "PSDT_BENCH_WORKER_COUNTS": "2",
        "PSDT_BENCH_STEPS": "2",
        "PSDT_BENCH_DEVICE_MB": "",          # device sweep off
        "PSDT_BENCH_FLAT_TENSORS": "48",
        "PSDT_BENCH_FLAT_KB": "4",
        "PSDT_BENCH_FLAT_BIG_MB": "8",
        "PSDT_BENCH_FLAT_OPTS": "adam",
        "PSDT_BENCH_FLAT_STRIPES": "1,2",
        # shrink the regime bound so the tiny big-store control (8 MB)
        # still exercises the gate row the real sweep sees at 128 MB
        "PSDT_ARENA_MAX_TENSOR_BYTES": "65536",
    })
    sweep = result["flat_arena"]
    rows = sweep["rows"]
    assert len(rows) == 4  # (small, big) x 2 stripe counts
    small = [r for r in rows if r["store"] == "small"]
    assert len(small) == 2
    for row in small:
        assert row["tensors"] == 48 and row["opt"] == "adam"
        assert row["per_tensor_close_ms"] > 0
        assert row["flat_close_ms"] > 0
        assert not row["flat_regime_gated"]
        # THE bound: one kernel per stage per stripe, tensor count
        # notwithstanding (48 tensors here)
        budget = arena.close_dispatch_budget("adam", row["stripes"])
        assert 0 < row["flat_profile"]["stage_calls"] <= budget
        # ... while the per-tensor path's stage operands scale O(tensors)
        assert row["per_tensor_profile"]["operands"] >= row["tensors"]
        assert row["flat_profile"]["operands"] < budget * 4
    big = [r for r in rows if r["store"] == "big"]
    # the big-tensor control rides the mean-tensor-size regime gate
    # (bandwidth-bound: the per-tensor path is the right regime there)
    assert all(r["flat_regime_gated"] for r in big)
    assert "small_adam" in sweep["best_ratio"]


@pytest.mark.slow
def test_bench_tier_contract():
    """tier mode: PS ingress bytes + fused-round wall, flat vs two-tier,
    with the ISSUE 9 acceptance visible in the JSON — at 4 workers in 2
    groups the tier ingress ratio must be <= 0.55 of flat."""
    result = run_bench("tier", extra_env={
        "PSDT_BENCH_PARAMS": "2e5",
        "PSDT_BENCH_WORKER_COUNTS": "4",
        "PSDT_BENCH_STEPS": "2",
    })
    assert result["metric"] == "ps_tier_ingress_ratio_4w"
    assert 0 < result["value"] <= 0.55, result
    row = result["by_workers"]["4"]
    assert row["flat"]["ingress_bytes_per_iter"] > 0
    assert row["tier"]["ingress_bytes_per_iter"] > 0
    assert row["flat"]["round_wall_ms"] > 0
    assert row["tier"]["round_wall_ms"] > 0
    assert result["group_size"] == 2


@pytest.mark.slow
def test_bench_serve_contract():
    """serve mode: continuous-batching sustained tokens/s with the int8
    stack applied; the metric must carry the kv8 suffix."""
    result = run_bench("serve", extra_env={
        "PSDT_BENCH_MODEL": "tiny_lm",
        "PSDT_BENCH_BATCH": "2",
        "PSDT_BENCH_STEPS": "4",
        "PSDT_BENCH_REQUESTS": "4",
        "PSDT_BENCH_QUANT": "int8",
        "PSDT_BENCH_KV_CACHE": "int8",
    })
    assert result["metric"] == "tiny_lm_serve_tokens_per_sec_kv8"
    assert result["value"] > 0


@pytest.mark.slow
def test_bench_generate_int8_ab_contract():
    """generate-mode int8 A/B: metric suffix reflects exactly which of
    weights/cache are quantized, vs_baseline is the measured ratio."""
    result = run_bench("generate", extra_env={
        "PSDT_BENCH_MODEL": "tiny_lm",
        "PSDT_BENCH_BATCH": "2",
        "PSDT_BENCH_STEPS": "8",
        "PSDT_BENCH_QUANT": "int8",
    })
    assert result["metric"] == "tiny_lm_decode_tokens_per_sec_int8"
    assert result["value"] > 0 and result["vs_baseline"] > 0


@pytest.mark.slow
def test_bench_generate_trained_draft_contract():
    """PSDT_BENCH_TRAIN_STEPS fits target+draft on the source-code byte
    corpus before the speculative A/B; the JSON contract must hold and the
    metric must carry the trained suffix."""
    result = run_bench("generate", extra_env={
        "PSDT_BENCH_MODEL": "small_lm",
        "PSDT_BENCH_DRAFT": "tiny_lm",
        "PSDT_BENCH_TRAIN_STEPS": "3",
        "PSDT_BENCH_BATCH": "2",
        "PSDT_BENCH_STEPS": "8",
        "PSDT_BENCH_DRAFT_LEN": "2",
    })
    assert "speculative" in result["metric"]
    assert "_trained3" in result["metric"]
    assert result["value"] > 0
