"""Sharded SPMD train-step tests on the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_distributed_tpu.config import MeshConfig
from parameter_server_distributed_tpu.models.mlp import MLP
from parameter_server_distributed_tpu.parallel.mesh import (
    batch_sharding, build_mesh, data_parallel_size, default_mesh_config,
    replicated)
from parameter_server_distributed_tpu.parallel.sharding import (
    choose_shard_axis, fsdp_rule, fsdp_tp_rule, shard_store)
from parameter_server_distributed_tpu.parallel.train_step import (
    ShardedTrainer, TrainState, make_optimizer, make_train_step)
from jax.sharding import PartitionSpec


def test_device_count_is_eight():
    assert jax.device_count() == 8


def test_build_mesh_shapes():
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    assert mesh.shape["data"] == 2 and mesh.shape["fsdp"] == 2
    assert mesh.shape["tensor"] == 2 and mesh.shape["pipe"] == 1
    assert data_parallel_size(mesh) == 4
    with pytest.raises(ValueError, match="needs"):
        build_mesh(MeshConfig(data=3))


def test_default_mesh_config_factorization():
    config = default_mesh_config(8, tensor=2)
    assert config.num_devices == 8 and config.tensor == 2
    assert config.fsdp * config.data == 4
    config2 = default_mesh_config(8, fsdp=2)
    assert config2.data == 4 and config2.fsdp == 2
    with pytest.raises(ValueError):
        default_mesh_config(8, tensor=3)


def test_choose_shard_axis():
    assert choose_shard_axis((6, 8), 4) == 1
    assert choose_shard_axis((8, 6), 4) == 0
    assert choose_shard_axis((7, 9), 4) is None
    assert choose_shard_axis((8, 16), 4, avoid={1}) == 0


def test_fsdp_rule_specs():
    mesh = build_mesh(MeshConfig(fsdp=8))
    rule = fsdp_rule(mesh)
    assert rule("w", (16, 32)) == PartitionSpec(None, "fsdp")
    assert rule("b", (32,)) == PartitionSpec("fsdp")
    assert rule("odd", (7, 9)) == PartitionSpec()


def test_fsdp_tp_rule_specs():
    mesh = build_mesh(MeshConfig(fsdp=2, tensor=2, data=2))
    rule = fsdp_tp_rule(mesh)
    assert rule("w", (16, 32)) == PartitionSpec("fsdp", "tensor")
    assert rule("b", (32,)) == PartitionSpec("fsdp")


def test_shard_store_places_arrays():
    mesh = build_mesh(MeshConfig(fsdp=8))
    store = {"w": np.ones((16, 8), np.float32)}
    sharded = shard_store(store, mesh, fsdp_rule(mesh))
    # 8-way sharding along dim 0 -> each shard holds 2 rows
    shard_shapes = {s.data.shape for s in sharded["w"].addressable_shards}
    assert shard_shapes == {(2, 8)}


def _loss_quadratic(params, batch):
    x, y = batch
    pred = jnp.dot(x, params["w"])
    return jnp.mean((pred - y) ** 2)


def test_sharded_trainer_matches_single_device():
    """The fully-sharded step must be numerically identical to an unsharded
    single-device step — sharding is an implementation detail."""
    rng = np.random.default_rng(0)
    w = rng.standard_normal((16, 8)).astype(np.float32)
    x = rng.standard_normal((32, 16)).astype(np.float32)
    y = rng.standard_normal((32, 8)).astype(np.float32)

    # single-device baseline
    opt = make_optimizer("sgd", 0.1)
    step = make_train_step(_loss_quadratic, opt)
    state0 = TrainState.create({"w": jnp.asarray(w)}, opt)
    baseline, metrics0 = jax.jit(step)(state0, (jnp.asarray(x), jnp.asarray(y)))

    # sharded: fsdp=2 x data=2 x tensor=2
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    trainer = ShardedTrainer(_loss_quadratic, mesh, fsdp_tp_rule(mesh),
                             make_optimizer("sgd", 0.1))
    state = trainer.init_state({"w": w})
    state1, metrics1 = trainer.step(state, (x, y))

    np.testing.assert_allclose(float(metrics1["loss"]), float(metrics0["loss"]),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(state1.params["w"]),
                               np.asarray(baseline.params["w"]), rtol=1e-5,
                               atol=1e-6)


def test_sharded_trainer_state_is_actually_sharded():
    mesh = build_mesh(MeshConfig(fsdp=4, data=2))
    trainer = ShardedTrainer(_loss_quadratic, mesh, fsdp_rule(mesh),
                             make_optimizer("momentum", 0.1))
    state = trainer.init_state({"w": np.ones((16, 8), np.float32)})
    # params sharded 4-way on dim 0
    assert {s.data.shape for s in state.params["w"].addressable_shards} == {(4, 8)}
    # momentum slot mirrors the param sharding
    trace = state.opt_state[0].trace["w"]
    assert {s.data.shape for s in trace.addressable_shards} == {(4, 8)}


@pytest.mark.parametrize("opt_name", ["adafactor", "lion"])
def test_memory_frugal_optimizers_train(opt_name):
    """adafactor (factored second moments, O(rows+cols) slots) and lion
    (single sign-momentum slot) — the memory-frugal TPU-era optimizers —
    reduce loss through the sharded trainer like adam does."""
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    model = MLP((32, 64, 10))
    trainer = ShardedTrainer(model.loss, mesh, fsdp_tp_rule(mesh),
                             make_optimizer(opt_name, 1e-2))
    state = trainer.init_state(model.init_params(0))
    rng = np.random.default_rng(0)
    centers = rng.standard_normal((10, 32)).astype(np.float32)
    losses = []
    for i in range(10):
        y = rng.integers(0, 10, 16)
        x = (2 * centers[y] + rng.standard_normal((16, 32))).astype(np.float32)
        state, metrics = trainer.step(state, (x, y.astype(np.int32)))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses


def test_sharded_mlp_training_loss_decreases():
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    model = MLP((32, 64, 10))
    trainer = ShardedTrainer(model.loss, mesh, fsdp_tp_rule(mesh),
                             make_optimizer("adam", 1e-2))
    state = trainer.init_state(model.init_params(0))
    rng = np.random.default_rng(0)
    centers = rng.standard_normal((10, 32)).astype(np.float32)
    losses = []
    for i in range(10):
        y = rng.integers(0, 10, 16)
        x = (2 * centers[y] + rng.standard_normal((16, 32))).astype(np.float32)
        state, metrics = trainer.step(state, (x, y.astype(np.int32)))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses
    assert int(state.step) == 10


# ---------------------------------------------------------------------------
# Trainer extensions: LR schedules, gradient clipping, accumulation
# ---------------------------------------------------------------------------

def test_lr_schedule_shapes():
    from parameter_server_distributed_tpu.parallel.train_step import (
        make_lr_schedule)

    assert make_lr_schedule(0.1) == 0.1
    warm = make_lr_schedule(0.1, warmup_steps=10)
    assert float(warm(0)) == 0.0
    assert float(warm(5)) == pytest.approx(0.05)
    assert float(warm(10)) == pytest.approx(0.1)
    assert float(warm(100)) == pytest.approx(0.1)

    cos = make_lr_schedule(0.1, "cosine", warmup_steps=10, total_steps=110)
    assert float(cos(0)) == 0.0
    assert float(cos(10)) == pytest.approx(0.1)
    assert float(cos(60)) < 0.1  # decaying
    assert float(cos(110)) == pytest.approx(0.0, abs=1e-6)

    lin = make_lr_schedule(0.2, "linear", warmup_steps=0, total_steps=10)
    assert float(lin(5)) == pytest.approx(0.1)
    with pytest.raises(ValueError, match="total_steps"):
        make_lr_schedule(0.1, "cosine", warmup_steps=5, total_steps=5)
    with pytest.raises(ValueError, match="unknown schedule"):
        make_lr_schedule(0.1, "exponential", total_steps=10)


def test_gradient_clipping_bounds_update():
    rng = np.random.default_rng(1)
    w = rng.standard_normal((8, 4)).astype(np.float32)
    x = rng.standard_normal((16, 8)).astype(np.float32) * 100.0  # huge grads
    y = rng.standard_normal((16, 4)).astype(np.float32)

    opt = make_optimizer("sgd", 1.0, clip_norm=0.5)
    step = make_train_step(_loss_quadratic, opt)
    state = TrainState.create({"w": jnp.asarray(w)}, opt)
    new_state, metrics = jax.jit(step)(state, (jnp.asarray(x), jnp.asarray(y)))
    assert float(metrics["grad_norm"]) > 0.5  # raw grads exceed the clip
    update_norm = float(jnp.linalg.norm(new_state.params["w"] - w))
    assert update_norm <= 0.5 * 1.01  # lr=1: update norm == clipped norm


def test_gradient_accumulation_matches_full_batch():
    """accum_steps=4 over a batch of 32 must equal one full-batch step
    (mean-based loss => mean of microbatch grads == full-batch grad)."""
    rng = np.random.default_rng(2)
    w = rng.standard_normal((16, 8)).astype(np.float32)
    x = rng.standard_normal((32, 16)).astype(np.float32)
    y = rng.standard_normal((32, 8)).astype(np.float32)
    batch = (jnp.asarray(x), jnp.asarray(y))

    opt = make_optimizer("sgd", 0.1)
    full = jax.jit(make_train_step(_loss_quadratic, opt))
    accum = jax.jit(make_train_step(_loss_quadratic, opt, accum_steps=4))
    s_full, m_full = full(TrainState.create({"w": jnp.asarray(w)}, opt), batch)
    s_acc, m_acc = accum(TrainState.create({"w": jnp.asarray(w)}, opt), batch)

    np.testing.assert_allclose(float(m_acc["loss"]), float(m_full["loss"]),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(s_acc.params["w"]),
                               np.asarray(s_full.params["w"]),
                               rtol=1e-5, atol=1e-6)


def test_sharded_trainer_with_accumulation_and_schedule():
    """Accumulation + warmup-cosine + clipping all compose inside the
    sharded SPMD step on the 8-device mesh."""
    rng = np.random.default_rng(3)
    w = rng.standard_normal((16, 8)).astype(np.float32)
    x = rng.standard_normal((32, 16)).astype(np.float32)
    y = rng.standard_normal((32, 8)).astype(np.float32)

    mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    trainer = ShardedTrainer(
        _loss_quadratic, mesh, fsdp_tp_rule(mesh),
        make_optimizer("adam", 1e-2, schedule="cosine", warmup_steps=2,
                       total_steps=10, clip_norm=1.0),
        accum_steps=2)
    state = trainer.init_state({"w": w})
    losses = []
    for _ in range(4):
        state, metrics = trainer.step(state, (x, y))
        losses.append(float(metrics["loss"]))
    assert int(state.step) == 4
    assert losses[-1] < losses[0]  # learning after warmup


def test_accum_steps_validation():
    opt = make_optimizer("sgd", 0.1)
    with pytest.raises(ValueError, match="accum_steps"):
        make_train_step(_loss_quadratic, opt, accum_steps=0)
    step = jax.jit(make_train_step(_loss_quadratic, opt, accum_steps=3))
    state = TrainState.create({"w": jnp.zeros((16, 8))}, opt)
    with pytest.raises(ValueError, match="does not divide"):
        step(state, (jnp.zeros((32, 16)), jnp.zeros((32, 8))))


def test_adamw_decay_skips_norm_scales():
    """AdamW's weight decay must not pull 1D params (norm scales, biases)
    toward zero: with zero gradients, matrices shrink and vectors hold."""
    import jax
    import jax.numpy as jnp

    from parameter_server_distributed_tpu.parallel.train_step import (
        make_optimizer)

    opt = make_optimizer("adamw", 0.1, weight_decay=0.1)
    params = {"w": jnp.ones((4, 4)), "ln/scale": jnp.ones((4,))}
    state = opt.init(params)
    grads = jax.tree.map(jnp.zeros_like, params)
    updates, _ = opt.update(grads, state, params)
    new = jax.tree.map(lambda p, u: p + u, params, updates)
    assert float(jnp.max(jnp.abs(new["ln/scale"] - 1.0))) == 0.0
    assert float(jnp.max(new["w"])) < 1.0  # decayed
