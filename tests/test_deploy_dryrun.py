"""deploy.sh --dry-run: the full cloud action plan without cloud access.

The deploy script has never been executable in this environment (no
terraform/gcloud, no GCP credentials), so --dry-run is the testable
surface: it must print every command the real run would execute, in
order, for every verb — including the reference-parity properties the
scale verb documents (provision only NEW slices on scale-up, no PS
restart in either direction — reference scripts/scale_workers.sh:51-186).
CI pairs this with `terraform init -backend=false && validate` against
the pinned provider (.github/workflows/ci.yml deploy-validate job).

These tests run deploy.sh with plain bash — no terraform, gcloud, or jq
on PATH required (that is the point of --dry-run).
"""
from __future__ import annotations

import subprocess
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
DEPLOY = REPO / "deploy" / "deploy.sh"


def _dry_run(*args, env_extra=None):
    import os

    env = dict(os.environ, **(env_extra or {}))
    proc = subprocess.run(["bash", str(DEPLOY), "--dry-run", *args],
                          capture_output=True, text=True, timeout=60,
                          env=env)
    assert proc.returncode == 0, proc.stderr
    return proc.stdout


def test_apply_plan_orders_terraform_then_control_plane_then_workers():
    out = _dry_run("apply")
    plan = [ln for ln in out.splitlines() if ln.startswith("DRY-RUN:")]
    assert "terraform -chdir=terraform init" in plan[0]
    assert "terraform -chdir=terraform apply -auto-approve" in plan[1]
    # start order mirrors the reference: coordinator/PS before workers
    coord = out.index("psdt-coordinator:/tmp/psdt-pkg")
    worker0 = out.index("psdt-worker-0:/tmp/psdt-pkg")
    assert coord < worker0
    assert "systemctl enable --now psdt-coordinator psdt-ps" in out
    assert "systemctl enable --now psdt-worker" in out


def test_scale_up_ships_only_new_slices_and_never_restarts_ps():
    out = _dry_run("scale", "4",
                   env_extra={"PSDT_DRY_RUN_PREV_WORKERS": "2"})
    assert "2 -> 4 slices" in out
    assert "worker_slice_count=4" in out
    # only the NEW slices (2, 3) are provisioned; 0/1 keep running
    assert "psdt-worker-2:/tmp/psdt-pkg" in out
    assert "psdt-worker-3:/tmp/psdt-pkg" in out
    assert "psdt-worker-0:/tmp/psdt-pkg" not in out
    assert "psdt-worker-1:/tmp/psdt-pkg" not in out
    # the reference-divergence contract: no PS/coordinator restart
    assert "psdt-ps" not in out
    assert "psdt-coordinator:" not in out


def test_scale_down_is_terraform_only_reaper_evicts():
    out = _dry_run("scale", "1",
                   env_extra={"PSDT_DRY_RUN_PREV_WORKERS": "3"})
    assert "worker_slice_count=1" in out
    assert "reaper evicts" in out
    assert "psdt-pkg" not in out          # nothing shipped on scale-down
    assert "psdt-ps" not in out           # and no PS restart


def test_destroy_and_ship_plans():
    assert "terraform -chdir=terraform destroy -auto-approve" in _dry_run(
        "destroy")
    ship = _dry_run("ship")
    assert "terraform -chdir=terraform apply" not in ship  # no re-apply
    assert "psdt-coordinator:/tmp/psdt-pkg" in ship
    assert "psdt-worker-0:/tmp/psdt-pkg" in ship
