"""Pallas kernel tests (interpret mode on CPU; compiled on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_distributed_tpu.models.transformer import causal_attention
from parameter_server_distributed_tpu.ops.pallas.flash_attention import flash_attention
from parameter_server_distributed_tpu.ops.pallas.fused_update import (
    fused_adam, fused_momentum, fused_sgd)


@pytest.mark.parametrize("s,block", [(64, 32), (128, 128), (96, 32)])
def test_flash_attention_matches_dense(rng, s, block):
    b, h, d = 2, 2, 16
    q, k, v = (rng.standard_normal((b, s, h, d)).astype(np.float32)
               for _ in range(3))
    dense = np.asarray(causal_attention(*map(jnp.asarray, (q, k, v))))
    flash = np.asarray(flash_attention(jnp.asarray(q), jnp.asarray(k),
                                       jnp.asarray(v), block_q=block,
                                       block_k=block))
    np.testing.assert_allclose(flash, dense, rtol=2e-5, atol=2e-5)


def test_flash_attention_gradients_match_dense(rng):
    b, s, h, d = 1, 32, 2, 8
    q, k, v = (jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
               for _ in range(3))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_q=16, block_k=16) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=1e-5)


def test_flash_rejects_indivisible_seq(rng):
    q = jnp.zeros((1, 100, 2, 8), jnp.float32)
    with pytest.raises(ValueError, match="divide"):
        flash_attention(q, q, q, block_q=64, block_k=64)


def test_fused_sgd_matches_reference(rng):
    params = {"w": rng.standard_normal((13, 7)).astype(np.float32),
              "b": rng.standard_normal(5).astype(np.float32)}
    grads = {"w": rng.standard_normal((13, 7)).astype(np.float32),
             "b": rng.standard_normal(5).astype(np.float32)}
    out = fused_sgd(params, grads, lr=0.3)
    for k in params:
        np.testing.assert_allclose(np.asarray(out[k]),
                                   params[k] - 0.3 * grads[k], rtol=1e-5, atol=1e-7)
        assert out[k].shape == params[k].shape


def test_fused_momentum_matches_reference(rng):
    p = {"w": rng.standard_normal((9, 11)).astype(np.float32)}
    g = {"w": rng.standard_normal((9, 11)).astype(np.float32)}
    vel = {"w": rng.standard_normal((9, 11)).astype(np.float32)}
    new_p, new_v = fused_momentum(p, g, vel, lr=0.1, mu=0.9)
    v_ref = 0.9 * vel["w"] + g["w"]
    np.testing.assert_allclose(np.asarray(new_v["w"]), v_ref, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(new_p["w"]), p["w"] - 0.1 * v_ref,
                               rtol=1e-5, atol=1e-7)


def test_fused_adam_matches_host_adam(rng):
    from parameter_server_distributed_tpu.core.optimizer import Adam
    shape = (17, 5)
    p = {"w": rng.standard_normal(shape).astype(np.float32)}
    g = {"w": rng.standard_normal(shape).astype(np.float32)}
    m = {"w": np.zeros(shape, np.float32)}
    v = {"w": np.zeros(shape, np.float32)}

    host = Adam(0.01)
    host_out = host.apply(dict(p), dict(g))

    new_p, new_m, new_v = fused_adam(p, g, m, v, step=1, lr=0.01)
    np.testing.assert_allclose(np.asarray(new_p["w"]), host_out["w"],
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(new_m["w"]), host.m["w"], rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(new_v["w"]), host.v["w"], rtol=1e-5, atol=1e-7)


def test_fused_adam_traced_step_no_recompile(rng):
    """step is data (SMEM), not a compile-time constant: the jitted apply
    must not retrace across steps and must match the host Adam trajectory."""
    import jax

    from parameter_server_distributed_tpu.core.optimizer import Adam

    shape = (12, 6)
    p = {"w": rng.standard_normal(shape).astype(np.float32)}
    host = Adam(0.01)
    host_p = dict(p)

    traces = 0

    @jax.jit
    def apply(params, grads, m, v, step):
        nonlocal traces
        traces += 1
        return fused_adam(params, grads, m, v, step, lr=0.01)

    m = {"w": jnp.zeros(shape, jnp.float32)}
    v = {"w": jnp.zeros(shape, jnp.float32)}
    cur = {k: jnp.asarray(x) for k, x in p.items()}
    for step in range(1, 4):
        g = {"w": rng.standard_normal(shape).astype(np.float32)}
        host_p = host.apply(host_p, g)
        cur, m, v = apply(cur, {"w": jnp.asarray(g["w"])}, m, v,
                          jnp.int32(step))
    assert traces == 1
    np.testing.assert_allclose(np.asarray(cur["w"]), host_p["w"],
                               rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("rule", ["sgd", "momentum", "adam"])
def test_pallas_optimizer_matches_host_in_ps_core(rng, rule):
    """PallasOptimizer (the fused kernels' production caller) must drive
    ParameterServerCore to the same parameters as the host optimizer."""
    from parameter_server_distributed_tpu.async_sgd.device_optimizer import (
        PallasOptimizer)
    from parameter_server_distributed_tpu.core.optimizer import make_optimizer
    from parameter_server_distributed_tpu.core.ps_core import (
        ParameterServerCore)

    init = {"w": rng.standard_normal((6, 10)).astype(np.float32),
            "b": rng.standard_normal(4).astype(np.float32)}
    grad_seq = [{"w": rng.standard_normal((6, 10)).astype(np.float32),
                 "b": rng.standard_normal(4).astype(np.float32)}
                for _ in range(3)]

    stores = {}
    for name, opt in (("pallas", PallasOptimizer(rule, 0.1)),
                      ("host", make_optimizer(rule, 0.1))):
        ps = ParameterServerCore(total_workers=1, optimizer=opt,
                                 staleness_bound=2)
        ps.initialize_parameters(init)
        for it, g in enumerate(grad_seq, start=1):
            assert ps.receive_gradients(0, it, g).success
        stores[name] = ps.get_parameters()
    for key in init:
        np.testing.assert_allclose(np.asarray(stores["pallas"][key]),
                                   np.asarray(stores["host"][key]),
                                   rtol=1e-4, atol=1e-6)


def test_pallas_optimizer_state_roundtrip(rng):
    """state_dict/load_state_dict round-trips slots + step (the checkpoint
    sidecar contract)."""
    from parameter_server_distributed_tpu.async_sgd.device_optimizer import (
        PallasOptimizer)

    p = {"w": rng.standard_normal((5, 5)).astype(np.float32)}
    g = {"w": rng.standard_normal((5, 5)).astype(np.float32)}
    opt = PallasOptimizer("adam", 0.01)
    p2 = opt.apply(p, g)

    clone = PallasOptimizer("adam", 0.01)
    clone.load_state_dict(opt.state_dict())
    assert clone.step == opt.step
    out_a = opt.apply(p2, g)
    out_b = clone.apply(p2, g)
    np.testing.assert_allclose(np.asarray(out_a["w"]), np.asarray(out_b["w"]),
                               rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("block_q,block_k", [(32, 16), (16, 32), (64, 64)])
def test_flash_backward_blockwise_matches_dense(rng, block_q, block_k):
    """The blockwise dQ/dK/dV kernels must agree with dense autodiff for
    every block-shape combination (exercises the causal frontier math on
    both grids)."""
    b, s, h, d = 2, 64, 2, 16
    q, k, v = (jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
               for _ in range(3))
    cot = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)

    def f_flash(q, k, v):
        return jnp.vdot(flash_attention(q, k, v, block_q=block_q,
                                        block_k=block_k), cot)

    def f_dense(q, k, v):
        return jnp.vdot(causal_attention(q, k, v), cot)

    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
    for name, a, b_ in zip("qkv", gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=1e-5,
                                   err_msg=f"d{name} mismatch")


def test_flash_backward_bf16(rng):
    """bf16 inputs: blockwise grads track the f32 dense reference within
    bf16 resolution (accumulation is f32 inside the kernels)."""
    b, s, h, d = 1, 64, 2, 16
    qf, kf, vf = (jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
                  for _ in range(3))
    q, k, v = (x.astype(jnp.bfloat16) for x in (qf, kf, vf))

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_q=32, block_k=32)
                       .astype(jnp.float32) ** 2)

    def f_dense(q, k, v):
        return jnp.sum(causal_attention(q, k, v).astype(jnp.float32) ** 2)

    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(f_dense, argnums=(0, 1, 2))(qf, kf, vf)
    for a, b_ in zip(gf, gd):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(a, np.float32), np.asarray(b_),
                                   rtol=0.1, atol=0.05)


# ---------------------------------------------------------------------------
# GQA-folded flash: unexpanded K/V, group segments in the q-rows axis
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kv,groups,block", [(2, 4, 64), (1, 4, 32),
                                             (4, 2, 64)])
def test_flash_gqa_matches_dense(rng, kv, groups, block):
    from parameter_server_distributed_tpu.ops.pallas.flash_attention import (
        flash_attention_gqa)

    b, s, d = 2, 128, 16
    q = jnp.asarray(rng.standard_normal((b, s, kv * groups, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, kv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, kv, d)), jnp.float32)
    dense = np.asarray(causal_attention(q, k, v))  # expands GQA itself
    got = np.asarray(flash_attention_gqa(q, k, v, block_q=block,
                                         block_k=block))
    np.testing.assert_allclose(got, dense, rtol=2e-4, atol=2e-4)


def test_flash_gqa_gradients_match_dense_and_stay_kv_sized(rng):
    """dK/dV must come back [B, S, KV, D] (the group reduction happens in
    the kernel's k-block stream, never materializing H-sized grads) and
    equal the dense GQA gradients."""
    from parameter_server_distributed_tpu.ops.pallas.flash_attention import (
        flash_attention_gqa)

    b, s, kv, groups, d = 1, 128, 2, 3, 8
    q = jnp.asarray(rng.standard_normal((b, s, kv * groups, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, kv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, kv, d)), jnp.float32)

    def loss_gqa(q, k, v):
        return jnp.sum(
            flash_attention_gqa(q, k, v, block_q=32, block_k=32) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(causal_attention(q, k, v) ** 2)

    gf = jax.grad(loss_gqa, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    assert gf[1].shape == (b, s, kv, d)
    assert gf[2].shape == (b, s, kv, d)
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=1e-5)


def test_flash_gqa_rejects_bad_heads(rng):
    from parameter_server_distributed_tpu.ops.pallas.flash_attention import (
        flash_attention_gqa)

    q = jnp.zeros((1, 128, 6, 8), jnp.float32)
    k = jnp.zeros((1, 128, 4, 8), jnp.float32)
    with pytest.raises(ValueError, match="divide"):
        flash_attention_gqa(q, k, k)
