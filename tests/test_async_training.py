"""Async / bounded-staleness end-to-end training tests."""

import threading

import numpy as np
import pytest

from parameter_server_distributed_tpu.async_sgd.device_optimizer import DeviceOptimizer
from parameter_server_distributed_tpu.cli.worker_main import build_worker
from parameter_server_distributed_tpu.config import (CoordinatorConfig,
                                                     ParameterServerConfig,
                                                     WorkerConfig)
from parameter_server_distributed_tpu.core.ps_core import ParameterServerCore
from parameter_server_distributed_tpu.server.coordinator_service import Coordinator
from parameter_server_distributed_tpu.server.ps_service import ParameterServer


@pytest.fixture
def async_cluster(tmp_path):
    ps = ParameterServer(ParameterServerConfig(
        bind_address="127.0.0.1", port=0, total_workers=2,
        checkpoint_interval=100, checkpoint_dir=str(tmp_path),
        learning_rate=0.02, staleness_bound=4, autosave_period_s=600.0))
    ps_port = ps.start()
    coordinator = Coordinator(CoordinatorConfig(
        bind_address="127.0.0.1", port=0,
        ps_address="127.0.0.1", ps_port=ps_port, reap_period_s=600.0))
    coord_port = coordinator.start()
    yield ps, coordinator, coord_port
    coordinator.stop()
    ps.stop()


def test_async_two_workers_no_barrier(async_cluster):
    """Async workers never block on each other: run them sequentially —
    under a sync barrier this would deadlock (worker 0 would wait forever
    for worker 1)."""
    ps, coordinator, coord_port = async_cluster
    w0 = build_worker(WorkerConfig(
        coordinator_address=f"127.0.0.1:{coord_port}", worker_id=0,
        address="127.0.0.1", port=50070, batch_size=16,
        heartbeat_period_s=600.0))
    w0.initialize()
    try:
        for it in range(4):
            w0.run_iteration(max(it, w0.iteration + 1))  # no other worker: must not block
    finally:
        w0.shutdown()
    assert ps.core.applied_updates >= 3  # bootstrap + real updates


def test_async_staleness_rejection_and_fast_forward(async_cluster):
    ps, coordinator, coord_port = async_cluster
    # advance the PS far ahead
    ps.core.initialize_parameters({"w": np.zeros(4, np.float32)})
    for it in range(10):
        ps.core.receive_gradients(9, it, {"w": np.zeros(4, np.float32)})
    assert ps.core.current_iteration == 9

    worker = build_worker(WorkerConfig(
        coordinator_address=f"127.0.0.1:{coord_port}", worker_id=0,
        address="127.0.0.1", port=50071, batch_size=16,
        heartbeat_period_s=600.0))
    worker.initialize()
    try:
        # worker starts at iteration 0: 9 - 0 > bound 4 -> stale ->
        # fast-forward and succeed.  (Params mismatch the MLP here, so give
        # the worker matching params first.)
        params = worker.trainer.init_params(0)
        ps.core.initialize_parameters(params)
        loss = worker.run_iteration(0)
        assert np.isfinite(loss)
        assert worker.iteration >= 9
    finally:
        worker.shutdown()


def test_device_optimizer_matches_host_sgd():
    from parameter_server_distributed_tpu.core.optimizer import SGD
    rng = np.random.default_rng(0)
    params = {"w": rng.standard_normal((8, 4)).astype(np.float32)}
    grads = {"w": rng.standard_normal((8, 4)).astype(np.float32)}
    host = SGD(0.1).apply(dict(params), grads)
    dev = DeviceOptimizer.sgd(0.1).apply(dict(params), grads)
    np.testing.assert_allclose(np.asarray(dev["w"]), host["w"], rtol=1e-6)


def test_device_optimizer_adam_in_ps_core_with_checkpoint(tmp_path):
    from parameter_server_distributed_tpu.checkpoint.manager import CheckpointManager
    opt = DeviceOptimizer.adam(0.01)
    core = ParameterServerCore(total_workers=1, staleness_bound=2,
                               optimizer=opt)
    core.initialize_parameters({"w": np.ones(4, np.float32)})
    core.receive_gradients(0, 0, {"w": np.full(4, 0.5, np.float32)})
    core.receive_gradients(0, 1, {"w": np.full(4, 0.5, np.float32)})
    mgr = CheckpointManager(core, directory=str(tmp_path), checkpoint_interval=1)
    path = mgr.save()

    opt2 = DeviceOptimizer.adam(0.01)
    core2 = ParameterServerCore(total_workers=1, staleness_bound=2,
                                optimizer=opt2)
    mgr2 = CheckpointManager(core2, directory=str(tmp_path), checkpoint_interval=1)
    mgr2.load(path)
    # identical next update => identical trajectories (moments restored)
    core.receive_gradients(0, 2, {"w": np.full(4, 0.5, np.float32)})
    core2.receive_gradients(0, 2, {"w": np.full(4, 0.5, np.float32)})
    np.testing.assert_allclose(np.asarray(core2.get_parameters()["w"]),
                               np.asarray(core.get_parameters()["w"]),
                               rtol=1e-6)


def test_async_concurrent_workers_loss_decreases(async_cluster):
    ps, coordinator, coord_port = async_cluster
    workers = []
    for wid in range(2):
        w = build_worker(WorkerConfig(
            coordinator_address=f"127.0.0.1:{coord_port}", worker_id=wid,
            address="127.0.0.1", port=50075 + wid, batch_size=16,
            heartbeat_period_s=600.0))
        w.initialize()
        workers.append(w)
    losses = {0: [], 1: []}
    errors = []

    def loop(worker):
        try:
            for i in range(6):
                it = max(i, worker.iteration + 1)
                losses[worker.config.worker_id].append(worker.run_iteration(it))
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=loop, args=(w,)) for w in workers]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for w in workers:
        w.shutdown()
    assert not errors, errors
    real = [x for xs in losses.values() for x in xs[1:] if np.isfinite(x)]
    assert len(real) >= 8
    # learning signal across the async run
    assert np.mean(real[-4:]) < real[0]


def test_model_parallel_worker_trains_through_ps(async_cluster):
    """A worker with an intra-worker MODEL-parallel mesh (--mesh=
    fsdp:2,data:2,tensor:2 over the virtual CPU devices) speaks plain PS:
    its packed pushes/pulls train end to end, and its gradients equal a
    single-device worker's on the same params/batch."""
    ps, coordinator, coord_port = async_cluster
    sharded = build_worker(WorkerConfig(
        coordinator_address=f"127.0.0.1:{coord_port}", worker_id=0,
        address="127.0.0.1", port=51230, model="small_lm", batch_size=8,
        heartbeat_period_s=600.0, mesh="fsdp:2,data:2,tensor:2"), seed=0)
    plain = build_worker(WorkerConfig(
        coordinator_address=f"127.0.0.1:{coord_port}", worker_id=1,
        address="127.0.0.1", port=51231, model="small_lm", batch_size=8,
        heartbeat_period_s=600.0), seed=0)
    try:
        sharded.initialize()
        plain.initialize()
        assert sharded.trainer.num_local_devices == 8

        from parameter_server_distributed_tpu.models.registry import (
            get_model_and_batches)

        params = sharded.trainer.init_params(0)
        batch = next(get_model_and_batches("small_lm", 8, seed=3)[1])
        g_sharded, l_sharded = sharded.trainer.compute_gradients(params,
                                                                 batch)
        g_plain, l_plain = plain.trainer.compute_gradients(params, batch)
        np.testing.assert_allclose(l_sharded, l_plain, rtol=1e-5)
        for name in g_plain:
            np.testing.assert_allclose(g_sharded[name], g_plain[name],
                                       rtol=2e-4, atol=1e-5, err_msg=name)

        # and the protocol round-trip works with the sharded trainer
        for it in (1, 2):
            loss = sharded.run_iteration(it)
        assert np.isfinite(loss)
    finally:
        sharded.shutdown()
        plain.shutdown()


def test_device_adamw_bf16_slots_track_f32(rng):
    """bf16-slot AdamW: half the optimizer-state bytes, trajectory within
    bf16 tolerance of the f32-slot device AdamW over multiple steps."""
    from parameter_server_distributed_tpu.core.optimizer import make_optimizer

    params = {"w": rng.standard_normal((32, 16)).astype(np.float32),
              "b": rng.standard_normal(16).astype(np.float32)}
    grad_seq = [{k: rng.standard_normal(v.shape).astype(np.float32) * 0.1
                 for k, v in params.items()} for _ in range(5)]
    f32_opt = make_optimizer("device_adamw", 1e-2, weight_decay=0.1)
    b16_opt = make_optimizer("device_adamw_bf16", 1e-2, weight_decay=0.1)
    p32, p16 = dict(params), dict(params)
    for grads in grad_seq:
        p32 = f32_opt.apply(p32, grads)
        p16 = b16_opt.apply(p16, grads)
    for k in params:
        a, b = np.asarray(p32[k]), np.asarray(p16[k])
        np.testing.assert_allclose(b, a, rtol=5e-3, atol=5e-4)
    # the carried slots really are bf16 (the HBM claim)
    import jax
    leaves = jax.tree.leaves(b16_opt._opt_state)
    slot_dtypes = {str(x.dtype) for x in leaves if x.ndim > 0}
    assert "bfloat16" in slot_dtypes, slot_dtypes


def test_device_adamw_bf16_state_roundtrip(rng):
    from parameter_server_distributed_tpu.core.optimizer import make_optimizer

    params = {"w": rng.standard_normal((8, 4)).astype(np.float32)}
    grads = {"w": rng.standard_normal((8, 4)).astype(np.float32)}
    opt = make_optimizer("device_adamw_bf16", 1e-2)
    p1 = opt.apply(dict(params), grads)
    state = opt.state_dict()
    opt2 = make_optimizer("device_adamw_bf16", 1e-2)
    opt2.load_state_dict(state)
    out_a = opt.apply(dict(p1), grads)
    out_b = opt2.apply(dict(p1), grads)
    for k in out_a:
        np.testing.assert_allclose(np.asarray(out_a[k]),
                                   np.asarray(out_b[k]), rtol=1e-5)


def test_bf16_nu_tracks_decay_via_stochastic_rounding(rng):
    """The freeze hazard bf16 slots must NOT have: when gradients shrink
    10x, the second moment should decay ~100x over a few thousand steps
    even though each step's relative change (~0.1%) is below bf16's
    half-ulp (~0.2%).  Deterministic round-to-nearest freezes nu at its
    stale value; stochastic rounding keeps the EMA unbiased."""
    import jax
    import jax.numpy as jnp
    from parameter_server_distributed_tpu.async_sgd.device_optimizer import (
        _adam_with_bf16_slots)

    tx = _adam_with_bf16_slots(0.9, 0.999, 1e-8)
    params = {"w": jnp.ones((64,), jnp.float32)}
    state = tx.init(params)
    # phase 1: grads of scale 1.0 -> nu converges near 1.0
    g_big = {"w": jnp.ones((64,), jnp.float32)}
    def step(state, g):
        _, state = tx.update(g, state)
        return state, None
    state, _ = jax.lax.scan(lambda s, _: step(s, g_big), state,
                            None, length=3000)
    nu_big = float(jnp.mean(state["nu"]["w"].astype(jnp.float32)))
    assert 0.8 < nu_big < 1.2, nu_big
    # phase 2: grads shrink 10x -> nu must decay toward 0.01
    g_small = {"w": jnp.full((64,), 0.1, jnp.float32)}
    state, _ = jax.lax.scan(lambda s, _: step(s, g_small), state,
                            None, length=6000)
    nu_small = float(jnp.mean(state["nu"]["w"].astype(jnp.float32)))
    assert nu_small < 0.03, (
        f"nu froze at {nu_small} (expected ~0.01): bf16 narrowing is "
        f"biased")
