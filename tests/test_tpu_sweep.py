"""Kill-switch test for the resumable TPU sweep scaffolding.

scripts/tpu_sweep_lib.sh is the only thing standing between a 4-minute
tunnel window and an empty results file (round 4 banked 4 of 12 configs;
the losses were an unretried HTTP 500, a single fixed per-config timeout,
and expensive configs starving cheap ones).  These tests drive the lib
with a fake bench + fake probe at ~1 s timescales and assert the contract
the real sweeps rely on:

  * a short window still banks every cheap config (>= 3 here) even when a
    hog config sits in the middle of the list
  * a transport-layer 5xx is retried once and banks on the retry
  * a live-device timeout is retried once with a doubled budget
  * a config that keeps failing is deferred after MAX_TAG_FAILS failures
    (and runs again only under SWEEP_RETRY_DEFERRED=1)
  * a tunnel-down signature aborts rc=2 so the watchdog can wait it out
  * banked tags are skipped on re-run; bench_error rows are retried

No TPU involved: BENCH and PROBE_CMD are the lib's test seams.
"""
from __future__ import annotations

import json
import os
import stat
import subprocess
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

FAKE_BENCH = r"""
import json, os, sys, time

cost = float(os.environ.get("FAKE_COST_S", "0"))
timeout = float(os.environ.get("PSDT_BENCH_TPU_TIMEOUT", "560"))

marker = os.environ.get("FAKE_500_FILE", "")
if marker and not os.path.exists(marker):
    open(marker, "w").close()
    print(json.dumps({
        "metric": "bench_error", "value": 0.0, "unit": "error",
        "vs_baseline": 0.0,
        "note": "JaxRuntimeError remote_compile: HTTP 500: helper exit 1"}))
    sys.exit(0)

if os.environ.get("FAKE_PREFLIGHT_HANG"):
    print(json.dumps({
        "metric": "bench_error", "value": 0.0, "unit": "error",
        "vs_baseline": 0.0,
        "note": "TPU preflight hung (> 1s) after 1 spaced probes"}))
    sys.exit(0)

if cost > timeout:
    time.sleep(timeout)
    print(json.dumps({
        "metric": "bench_error", "value": 0.0, "unit": "error",
        "vs_baseline": 0.0,
        "note": "tpu attempt timed out after %ds" % timeout}))
    sys.exit(0)

time.sleep(cost)
print(json.dumps({
    "metric": "fake_mfu", "value": 0.5, "unit": "fraction_of_peak",
    "vs_baseline": 1.1}))
"""


def _env(tmp: Path, results: Path, probe_ok: bool = True) -> dict:
    env = dict(os.environ)
    env.update({
        "RESULTS": str(results),
        "LOG": str(tmp / "sweep.log"),
        "BENCH": f"python {tmp / 'fake_bench.py'}",
        "PROBE_CMD": "true" if probe_ok else "false",
        "PSDT_BENCH_TPU_TIMEOUT": "1",
        "RETRY_5XX_PAUSE_S": "0",
    })
    return env


def _write_sweep(tmp: Path, body: str) -> Path:
    (tmp / "fake_bench.py").write_text(FAKE_BENCH)
    sweep = tmp / "sweep.sh"
    sweep.write_text("#!/usr/bin/env bash\nset -u\n"
                     ". scripts/tpu_sweep_lib.sh\n" + body)
    sweep.chmod(sweep.stat().st_mode | stat.S_IEXEC)
    return sweep


def _banked(results: Path) -> dict:
    rows = {}
    if results.exists():
        for line in results.read_text().splitlines():
            row = json.loads(line)
            rows[row["config"]] = row["result"]
    return rows


def _run_sweep(sweep: Path, env: dict, timeout: float = 60.0):
    return subprocess.run(["bash", str(sweep)], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=timeout)


def test_short_window_banks_cheap_configs_despite_hog(tmp_path):
    """The round-4 failure shape: a hog mid-list must not starve the
    cheap configs behind it, and the whole window stays bounded by the
    hog's (budget + doubled retry), not by the window deadline."""
    results = tmp_path / "r.jsonl"
    sweep = _write_sweep(tmp_path, "\n".join([
        "run cheap1 FAKE_COST_S=0",
        "run hog FAKE_COST_S=99",
        "run cheap2 FAKE_COST_S=0",
        "run cheap3 FAKE_COST_S=0",
        ""]))
    start = time.monotonic()
    proc = _run_sweep(sweep, _env(tmp_path, results))
    elapsed = time.monotonic() - start
    assert proc.returncode == 0, proc.stderr
    rows = _banked(results)
    real = [t for t, r in rows.items() if r["metric"] == "fake_mfu"]
    assert sorted(real) == ["cheap1", "cheap2", "cheap3"]
    # hog: 1 s attempt + 2 s doubled retry, banked as error, didn't block
    assert rows["hog"]["metric"] == "bench_error"
    assert elapsed < 20, f"hog starved the window: {elapsed:.1f}s"
    assert "adaptive retry with 2s" in (tmp_path / "sweep.log").read_text()


def test_adaptive_retry_banks_config_that_fits_doubled_budget(tmp_path):
    """The headline round-4 fix: a config whose cost sits between the
    base budget and 2x budget must bank a REAL number on the doubled
    retry (warm compile cache in production), not a bench_error row."""
    results = tmp_path / "r.jsonl"
    sweep = _write_sweep(tmp_path, "run midcost FAKE_COST_S=1.5\n")
    proc = _run_sweep(sweep, _env(tmp_path, results))
    assert proc.returncode == 0, proc.stderr
    assert _banked(results)["midcost"]["metric"] == "fake_mfu"
    assert "adaptive retry with 2s" in (tmp_path / "sweep.log").read_text()


def test_transport_5xx_retried_once_and_banks(tmp_path):
    results = tmp_path / "r.jsonl"
    marker = tmp_path / "flaky_marker"
    sweep = _write_sweep(
        tmp_path, f"run flaky FAKE_500_FILE={marker} FAKE_COST_S=0\n")
    proc = _run_sweep(sweep, _env(tmp_path, results))
    assert proc.returncode == 0, proc.stderr
    assert marker.exists()  # first attempt consumed the 500
    assert _banked(results)["flaky"]["metric"] == "fake_mfu"


def test_repeat_offender_deferred_then_retried_under_flag(tmp_path):
    results = tmp_path / "r.jsonl"
    sweep = _write_sweep(tmp_path, "run hog FAKE_COST_S=99\n")
    env = _env(tmp_path, results)
    log = tmp_path / "sweep.log"
    # two watchdog re-invocations -> MAX_TAG_FAILS=2 reached
    for _ in range(2):
        assert _run_sweep(sweep, env).returncode == 0
    # third invocation: deferred without running (fast)
    start = time.monotonic()
    assert _run_sweep(sweep, env).returncode == 0
    assert time.monotonic() - start < 2
    assert "deferred" in log.read_text()
    # the chain's final pass still gives it the leftover budget
    env_retry = dict(env, SWEEP_RETRY_DEFERRED="1")
    assert _run_sweep(sweep, env_retry).returncode == 0
    assert "deferred (" not in log.read_text().splitlines()[-1]


def test_tunnel_down_timeout_aborts_rc2(tmp_path):
    """A timeout with a dead probe is a tunnel death -> rc=2, no retry."""
    results = tmp_path / "r.jsonl"
    sweep = _write_sweep(tmp_path, "run hog FAKE_COST_S=99\n")
    proc = _run_sweep(sweep, _env(tmp_path, results, probe_ok=False))
    assert proc.returncode == 2


def test_preflight_hang_aborts_rc2(tmp_path):
    results = tmp_path / "r.jsonl"
    sweep = _write_sweep(tmp_path, "run dead FAKE_PREFLIGHT_HANG=1\n")
    proc = _run_sweep(sweep, _env(tmp_path, results))
    assert proc.returncode == 2
    # the error row is still banked so the round artifact shows the state
    assert _banked(results)["dead"]["metric"] == "bench_error"


def test_banked_tag_skipped_error_tag_retried(tmp_path):
    results = tmp_path / "r.jsonl"
    results.write_text("\n".join([
        json.dumps({"config": "done", "result": {
            "metric": "fake_mfu", "value": 0.4}}),
        json.dumps({"config": "errored", "result": {
            "metric": "bench_error", "value": 0.0}}),
        ""]))
    sweep = _write_sweep(tmp_path, "\n".join([
        "run done FAKE_COST_S=99",    # would time out if not skipped
        "run errored FAKE_COST_S=0",
        ""]))
    proc = _run_sweep(sweep, _env(tmp_path, results))
    assert proc.returncode == 0, proc.stderr
    rows = _banked(results)
    assert rows["done"]["value"] == 0.4          # untouched
    assert rows["errored"]["metric"] == "fake_mfu"  # retried, replaced


def test_watchdog_waits_out_outage_then_banks_and_exits(tmp_path):
    """The full watchdog loop at 1s timescales: a down probe sleeps and
    re-probes; once the device 'recovers' the sweep runs to completion
    and the watchdog exits 0 with everything banked."""
    results = tmp_path / "r.jsonl"
    sweep = _write_sweep(tmp_path, "\n".join([
        "run a FAKE_COST_S=0",
        "run b FAKE_COST_S=0",
        ""]))
    # probe script: fails the first 2 calls (outage), then healthy
    probe = tmp_path / "probe.sh"
    probe.write_text("#!/usr/bin/env bash\n"
                     f"n=$(cat {tmp_path}/probes 2>/dev/null || echo 0)\n"
                     f"echo $((n + 1)) > {tmp_path}/probes\n"
                     "[ \"$n\" -ge 2 ]\n")
    env = _env(tmp_path, results)
    env.update({"PROBE_CMD": f"bash {probe}", "PROBE_SPACING_S": "1",
                "DEADLINE_S": "60", "SWEEP": str(sweep)})
    proc = subprocess.run(["bash", "scripts/tpu_watchdog.sh"], cwd=REPO,
                          env=env, capture_output=True, text=True,
                          timeout=90)
    assert proc.returncode == 0, proc.stderr + proc.stdout
    rows = _banked(results)
    assert rows["a"]["metric"] == rows["b"]["metric"] == "fake_mfu"
    log = (tmp_path / "sweep.log").read_text()
    assert "TPU down" in log and "sweep complete" in log
    assert int((tmp_path / "probes").read_text()) >= 3


def test_watchdog_reprobes_after_mid_sweep_tunnel_death(tmp_path):
    """A sweep abort (rc=2, tunnel died mid-config) sends the watchdog
    back to probing; the next window resumes the sweep with the already-
    banked tag skipped."""
    results = tmp_path / "r.jsonl"
    # config 'a' banks; 'b' times out — with the probe then DOWN, the lib
    # aborts rc=2.  The flag file flips the probe back up for the retry,
    # where 'b' is cheap and banks.
    flag = tmp_path / "second_window"
    sweep = _write_sweep(tmp_path, "\n".join([
        "run a FAKE_COST_S=0",
        f"if [ ! -f {flag} ]; then",
        f"  touch {flag}",
        "  run b FAKE_COST_S=99",    # times out; probe says down -> rc=2
        "else",
        "  run b FAKE_COST_S=0",
        "fi",
        ""]))
    # probe: healthy unless mid-first-sweep (flag exists but retry file
    # doesn't yet) — models the tunnel dying during config b
    probe = tmp_path / "probe.sh"
    probe.write_text(
        "#!/usr/bin/env bash\n"
        f"if [ -f {flag} ] && [ ! -f {tmp_path}/retry ]; then\n"
        f"  touch {tmp_path}/retry\n"
        "  exit 1\n"                 # one down verdict -> rc=2 + one wait
        "fi\nexit 0\n")
    env = _env(tmp_path, results)
    env.update({"PROBE_CMD": f"bash {probe}", "PROBE_SPACING_S": "1",
                "DEADLINE_S": "60", "SWEEP": str(sweep)})
    proc = subprocess.run(["bash", "scripts/tpu_watchdog.sh"], cwd=REPO,
                          env=env, capture_output=True, text=True,
                          timeout=90)
    assert proc.returncode == 0, proc.stderr + proc.stdout
    rows = _banked(results)
    assert rows["a"]["metric"] == "fake_mfu"
    assert rows["b"]["metric"] == "fake_mfu"   # banked on the 2nd window
    log = (tmp_path / "sweep.log").read_text()
    assert "sweep aborted (rc=2)" in log
