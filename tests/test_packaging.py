"""Packaging contract: pyproject console scripts resolve and the package
is installable metadata-wise (VERDICT round 1 missing item 1)."""

import importlib
import os
import re

try:
    import tomllib
except ImportError:  # Python 3.10: stdlib tomllib landed in 3.11
    tomllib = None

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mini_toml(path):
    """Fallback parser for exactly the pyproject shapes these tests read
    (table headers, string values, string arrays — including arrays that
    span lines), so the packaging contract stays tested on Python 3.10
    where tomllib is absent."""
    doc: dict = {}
    table = doc
    pending_key = None
    pending: list[str] | None = None
    with open(path, "r", encoding="utf-8") as f:
        for raw in f:
            line = raw.strip()
            if pending is not None:
                pending += re.findall(r'"((?:[^"\\]|\\.)*)"', line)
                if line.split("#")[0].rstrip().endswith("]"):
                    table[pending_key] = pending
                    pending = None
                continue
            if not line or line.startswith("#"):
                continue
            m = re.match(r"\[([^\]]+)\]$", line)
            if m:
                table = doc
                for part in m.group(1).split("."):
                    table = table.setdefault(part, {})
                continue
            m = re.match(r'(?:"([^"]+)"|([\w-]+))\s*=\s*(.*)$', line)
            if not m:
                continue
            key = m.group(1) or m.group(2)
            value = m.group(3).split("#")[0].strip() if not \
                m.group(3).startswith('"') else m.group(3)
            if value.startswith("["):
                strings = re.findall(r'"((?:[^"\\]|\\.)*)"', value)
                if value.rstrip().endswith("]"):
                    table[key] = strings
                else:
                    pending_key, pending = key, strings
            elif value.startswith('"'):
                table[key] = re.match(r'"((?:[^"\\]|\\.)*)"', value).group(1)
            elif value.startswith("{"):
                table[key] = dict(re.findall(
                    r'(\w+)\s*=\s*"((?:[^"\\]|\\.)*)"', value))
    return doc


def _pyproject():
    path = os.path.join(REPO, "pyproject.toml")
    if tomllib is not None:
        with open(path, "rb") as f:
            return tomllib.load(f)
    return _mini_toml(path)


def test_console_scripts_resolve():
    scripts = _pyproject()["project"]["scripts"]
    assert len(scripts) == 12  # ps/coordinator/worker + train/status/
    #                            generate/serve/eval/analyze/trace/ctl/
    #                            route
    for name, target in scripts.items():
        module, _, attr = target.partition(":")
        fn = getattr(importlib.import_module(module), attr)
        assert callable(fn), f"{name} -> {target} not callable"


def test_pinned_runtime_deps_importable():
    deps = _pyproject()["project"]["dependencies"]
    names = {d.split("==")[0].split(">=")[0].strip() for d in deps}
    assert {"jax", "optax", "grpcio", "numpy", "ml_dtypes"} <= names
    for mod in ("jax", "optax", "grpc", "numpy", "ml_dtypes"):
        importlib.import_module(mod)


def test_native_source_shipped_as_package_data():
    data = _pyproject()["tool"]["setuptools"]["package-data"]
    assert "*.cpp" in data["parameter_server_distributed_tpu.native"]
    assert os.path.exists(os.path.join(
        REPO, "parameter_server_distributed_tpu", "native",
        "psdt_native.cpp"))


def test_analysis_goldens_shipped_as_package_data():
    # pst-analyze needs the golden wire manifest, the per-extension
    # protocol manifests, the knob registry, and the reviewed baseline
    # from an installed copy, not just a checkout
    data = _pyproject()["tool"]["setuptools"]["package-data"]
    assert "*.json" in data["parameter_server_distributed_tpu.analysis"]
    for fname in ("wire_manifest.json", "ext_manifests.json",
                  "knob_registry.json", "baseline.json"):
        assert os.path.exists(os.path.join(
            REPO, "parameter_server_distributed_tpu", "analysis", fname))
