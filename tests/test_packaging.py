"""Packaging contract: pyproject console scripts resolve and the package
is installable metadata-wise (VERDICT round 1 missing item 1)."""

import importlib
import os
import tomllib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pyproject():
    with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
        return tomllib.load(f)


def test_console_scripts_resolve():
    scripts = _pyproject()["project"]["scripts"]
    assert len(scripts) == 8  # ps/coordinator/worker + train/status/
    #                           generate/serve/eval
    for name, target in scripts.items():
        module, _, attr = target.partition(":")
        fn = getattr(importlib.import_module(module), attr)
        assert callable(fn), f"{name} -> {target} not callable"


def test_pinned_runtime_deps_importable():
    deps = _pyproject()["project"]["dependencies"]
    names = {d.split("==")[0].split(">=")[0].strip() for d in deps}
    assert {"jax", "optax", "grpcio", "numpy", "ml_dtypes"} <= names
    for mod in ("jax", "optax", "grpc", "numpy", "ml_dtypes"):
        importlib.import_module(mod)


def test_native_source_shipped_as_package_data():
    data = _pyproject()["tool"]["setuptools"]["package-data"]
    assert "*.cpp" in data["parameter_server_distributed_tpu.native"]
    assert os.path.exists(os.path.join(
        REPO, "parameter_server_distributed_tpu", "native",
        "psdt_native.cpp"))
