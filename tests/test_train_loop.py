"""SPMD training loop + sharded checkpoint/resume tests."""

import json
import os

import numpy as np
import pytest

from parameter_server_distributed_tpu.checkpoint import sharded as sc
from parameter_server_distributed_tpu.cli.train_main import parse_mesh
from parameter_server_distributed_tpu.config import MeshConfig
from parameter_server_distributed_tpu.parallel.train_loop import (
    TrainLoopConfig, run_training)


def test_parse_mesh():
    config = parse_mesh("data:2,fsdp:2,tensor:2")
    assert (config.data, config.fsdp, config.tensor) == (2, 2, 2)
    assert parse_mesh("seq:4,pipe:2").sequence == 4
    assert parse_mesh("").num_devices == 1
    with pytest.raises(ValueError):
        parse_mesh("bogus:2")


def test_run_training_sharded_mesh(tmp_path):
    config = TrainLoopConfig(
        model="mnist_mlp", batch_size=32, steps=24, optimizer="sgd",
        learning_rate=0.05, mesh=MeshConfig(data=4, fsdp=2),
        log_every=4, metrics_path=str(tmp_path / "metrics.jsonl"))
    summary = run_training(config)
    assert summary["steps"] == 24 and summary["dp_size"] == 8
    assert np.isfinite(summary["final_loss"])
    lines = [json.loads(l) for l in open(tmp_path / "metrics.jsonl")]
    assert lines[-1]["step"] == 24
    assert lines[-1]["loss"] < lines[0]["loss"]  # learning signal


def test_sharded_checkpoint_roundtrip_and_reshard(tmp_path):
    import jax
    import jax.numpy as jnp
    from parameter_server_distributed_tpu.models.mlp import MLP
    from parameter_server_distributed_tpu.parallel.mesh import build_mesh
    from parameter_server_distributed_tpu.parallel.sharding import fsdp_rule
    from parameter_server_distributed_tpu.parallel.train_step import (
        ShardedTrainer, make_optimizer)

    model = MLP((16, 32, 8))
    mesh1 = build_mesh(MeshConfig(fsdp=8))
    trainer1 = ShardedTrainer(model.loss, mesh1, fsdp_rule(mesh1),
                              make_optimizer("momentum", 0.1))
    state1 = trainer1.init_state(model.init_params(0))
    rng = np.random.default_rng(0)
    batch = (rng.standard_normal((16, 16)).astype(np.float32),
             rng.integers(0, 8, 16).astype(np.int32))
    state1, _ = trainer1.step(state1, batch)
    path = sc.save_sharded(str(tmp_path), 1, state1)
    assert sc.latest_step(str(tmp_path)) == 1

    # restore into a DIFFERENT mesh/sharding (8-way fsdp -> 2x4)
    mesh2 = build_mesh(MeshConfig(data=4, fsdp=2))
    trainer2 = ShardedTrainer(model.loss, mesh2, fsdp_rule(mesh2),
                              make_optimizer("momentum", 0.1))
    state2 = trainer2.init_state(model.init_params(1))  # different init
    restored = sc.restore_sharded(path, template=state2)
    for k in state1.params:
        np.testing.assert_array_equal(np.asarray(restored.params[k]),
                                      np.asarray(state1.params[k]))
    assert int(np.asarray(restored.step)) == 1
    # restored state trains under the NEW mesh
    state3, metrics = trainer2.step(restored, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_train_loop_resume(tmp_path):
    ckpt_dir = str(tmp_path / "ck")
    base = dict(model="mnist_mlp", batch_size=16, optimizer="sgd",
                learning_rate=0.05, mesh=MeshConfig(data=2),
                checkpoint_dir=ckpt_dir, checkpoint_every=5, log_every=5)
    run_training(TrainLoopConfig(steps=5, **base))
    assert sc.latest_step(ckpt_dir) == 5
    summary = run_training(TrainLoopConfig(steps=10, resume=True, **base))
    assert summary["steps"] == 10
    assert sc.latest_step(ckpt_dir) == 10


@pytest.mark.parametrize("attention,mesh", [
    ("ring", MeshConfig(sequence=2, data=4)),
    ("ulysses", MeshConfig(sequence=2, data=2, fsdp=2)),
    ("flash", MeshConfig(data=2, fsdp=2, tensor=2)),
])
def test_run_training_attention_selection(attention, mesh):
    """--attention reaches run_training for every implementation: the LM
    trains on the corresponding mesh and the loss decreases."""
    config = TrainLoopConfig(
        model="small_lm", batch_size=8, steps=6, optimizer="sgd",
        learning_rate=0.5, attention=attention, mesh=mesh, log_every=2)
    summary = run_training(config)
    assert summary["steps"] == 6
    assert np.isfinite(summary["final_loss"])


def test_seq_mesh_drops_loss_chunk(monkeypatch):
    """Under sequence parallelism the chunked-cross-entropy scan would
    slice per-device shards out of the seq-sharded activations and
    serialize the LM head, so run_training disables it (per-device logits
    are already O(S/N * vocab) there); a seq-less mesh keeps it."""
    from parameter_server_distributed_tpu.models import registry as reg
    from parameter_server_distributed_tpu.parallel import train_loop as tl

    seen = {}
    real = reg.get_model_and_batches

    def spy(*args, **kwargs):
        model, batches = real(*args, **kwargs)
        import dataclasses
        model.config = dataclasses.replace(model.config, loss_chunk=8)
        seen["model"] = model
        return model, batches

    monkeypatch.setattr(tl, "get_model_and_batches", spy)
    config = TrainLoopConfig(
        model="small_lm", batch_size=4, steps=1, optimizer="sgd",
        attention="ring", mesh=MeshConfig(sequence=2, data=4))
    summary = run_training(config)
    assert np.isfinite(summary["final_loss"])
    assert seen["model"].config.loss_chunk == 0

    monkeypatch.setattr(tl, "get_model_and_batches", spy)
    summary = run_training(TrainLoopConfig(
        model="small_lm", batch_size=8, steps=1, optimizer="sgd",
        mesh=MeshConfig(data=8)))
    assert np.isfinite(summary["final_loss"])
    assert seen["model"].config.loss_chunk == 8


def test_attention_flag_rejected_for_non_transformer():
    config = TrainLoopConfig(model="mnist_mlp", attention="flash", steps=1,
                             mesh=MeshConfig(data=8))
    with pytest.raises(ValueError, match="transformer"):
        run_training(config)


def test_checkpoint_retention(tmp_path):
    """--ckpt-keep prunes all but the newest N committed checkpoints."""
    config = TrainLoopConfig(
        model="mnist_mlp", batch_size=16, steps=12, optimizer="sgd",
        learning_rate=0.05, mesh=MeshConfig(data=8),
        checkpoint_dir=str(tmp_path), checkpoint_every=2,
        checkpoint_keep=2, log_every=6)
    run_training(config)
    steps = sorted(int(p.name.split("_")[1]) for p in tmp_path.iterdir()
                   if p.name.startswith("step_"))
    assert steps == [10, 12]
    # the survivors restore fine
    assert sc.latest_step(str(tmp_path)) == 12


def test_checkpoint_retention_with_final_fallback_save(tmp_path):
    """steps not a multiple of ckpt-every: the end-of-run fallback save
    must not leave keep+1 checkpoints behind."""
    config = TrainLoopConfig(
        model="mnist_mlp", batch_size=16, steps=13, optimizer="sgd",
        learning_rate=0.05, mesh=MeshConfig(data=8),
        checkpoint_dir=str(tmp_path), checkpoint_every=2,
        checkpoint_keep=2, log_every=6)
    run_training(config)
    steps = sorted(int(p.name.split("_")[1]) for p in tmp_path.iterdir()
                   if p.name.startswith("step_"))
    assert steps == [12, 13]


def test_eval_loop(tmp_path):
    """--eval-every runs held-out evaluation: eval_loss lands in the
    summary and the JSONL metrics, and evaluation never perturbs training
    (same final_loss with eval on and off)."""
    metrics = tmp_path / "m.jsonl"
    base = dict(model="small_lm", batch_size=8, steps=4, optimizer="sgd",
                learning_rate=0.1, mesh=MeshConfig(data=2), log_every=2)
    with_eval = run_training(TrainLoopConfig(
        **base, eval_every=2, eval_steps=2, metrics_path=str(metrics)))
    assert np.isfinite(with_eval["eval_loss"])
    lines = [json.loads(line) for line in metrics.read_text().splitlines()]
    assert any("eval_loss" in entry for entry in lines)

    without = run_training(TrainLoopConfig(**base))
    assert without["final_loss"] == pytest.approx(with_eval["final_loss"])
    assert "eval_loss" not in without

    # gradient accumulation: eval scans the same microbatch split, and
    # the mean of equal-size microbatch means equals the full-batch mean
    # (same eval cadence -> same eval-stream batches as the accum=1 run)
    accum = run_training(TrainLoopConfig(
        **base, accum_steps=2, eval_every=2, eval_steps=2))
    assert accum["eval_loss"] == pytest.approx(with_eval["eval_loss"],
                                               rel=1e-4)


def test_checkpoint_averaging(tmp_path):
    """average_checkpoints: uniform f32 mean of the last K params, newest
    step's metadata, stored dtype preserved."""
    run_training(TrainLoopConfig(
        model="mnist_mlp", batch_size=16, steps=6, optimizer="sgd",
        learning_rate=0.1, mesh=MeshConfig(data=2),
        checkpoint_dir=str(tmp_path), checkpoint_every=2, log_every=6))
    import jax.numpy as jnp

    s4 = sc.restore_sharded(str(tmp_path / "step_4"))
    s6 = sc.restore_sharded(str(tmp_path / "step_6"))
    step, avg = sc.average_checkpoints(str(tmp_path), 2)
    assert step == 6
    p4 = s4["params"] if isinstance(s4, dict) else s4.params
    p6 = s6["params"] if isinstance(s6, dict) else s6.params
    pa = avg["params"] if isinstance(avg, dict) else avg.params
    for name in pa:
        expect = (np.asarray(p4[name], np.float32)
                  + np.asarray(p6[name], np.float32)) / 2
        np.testing.assert_allclose(np.asarray(pa[name], np.float32), expect,
                                   rtol=1e-6, err_msg=name)
        assert jnp.asarray(pa[name]).dtype == jnp.asarray(p6[name]).dtype

    none_step, none_state = sc.average_checkpoints(str(tmp_path / "nope"), 3)
    assert none_step is None and none_state is None


def test_params_ema_tracks_and_extracts():
    """params_ema keeps a Polyak shadow of the parameters inside the
    optimizer state: the recursion matches a hand computation, the
    shadow survives chaining (clip + sgd + ema), extract_ema finds it
    through the nested chain state, and invalid decays are rejected."""
    import jax.numpy as jnp
    import optax

    from parameter_server_distributed_tpu.parallel.train_step import (
        extract_ema, make_optimizer, params_ema)

    decay = 0.9
    opt = make_optimizer("sgd", 0.5, clip_norm=10.0, ema_decay=decay)
    params = {"w": jnp.asarray([2.0, -1.0], jnp.float32)}
    state = opt.init(params)
    expect_ema = np.asarray(params["w"])
    for step in range(4):
        grads = {"w": jnp.asarray([0.5, 0.5], jnp.float32)}
        updates, state = opt.update(grads, state, params)
        params = optax.apply_updates(params, updates)
        expect_ema = decay * expect_ema + (1 - decay) * np.asarray(
            params["w"])
    ema = extract_ema(state)
    np.testing.assert_allclose(np.asarray(ema["w"]), expect_ema, rtol=1e-6)
    # and the raw updates were NOT perturbed by the ema stage
    np.testing.assert_allclose(np.asarray(params["w"]),
                               np.asarray([2.0, -1.0]) - 4 * 0.25,
                               rtol=1e-6)

    assert extract_ema(make_optimizer("sgd", 0.1).init(params)) is None
    with pytest.raises(ValueError, match="decay"):
        params_ema(1.0)
    with pytest.raises(ValueError, match="decay"):
        params_ema(0.0)

    # bf16 regression: the shadow is kept in FLOAT32 — at decay 0.999
    # a bf16 shadow's per-step correction is below its half-ulp and
    # would round back to the init value forever
    opt16 = make_optimizer("sgd", 0.5, ema_decay=0.999)
    p16 = {"w": jnp.asarray([2.0], jnp.bfloat16)}
    s16 = opt16.init(p16)
    for _ in range(50):
        upd, s16 = opt16.update({"w": jnp.asarray([0.5], jnp.bfloat16)},
                                s16, p16)
        p16 = optax.apply_updates(p16, upd)
    ema16 = extract_ema(s16)
    assert ema16["w"].dtype == jnp.float32
    assert float(ema16["w"][0]) != 2.0  # the shadow actually moved


def test_train_loop_ema_eval(tmp_path):
    """run_training with --ema reports ema_eval_loss next to eval_loss,
    and the EMA tree rides the checkpoint: a --resume run (template
    restore preserves the typed EmaState) still reports it."""
    config = dict(
        model="mnist_mlp", batch_size=16, steps=8, optimizer="adam",
        learning_rate=1e-3, ema=0.9, eval_every=8, eval_steps=2,
        checkpoint_dir=str(tmp_path), checkpoint_every=8, log_every=4)
    summary = run_training(TrainLoopConfig(**config))
    assert np.isfinite(summary["eval_loss"])
    assert np.isfinite(summary["ema_eval_loss"])
    # resume at the final step: 0 further updates, the EMA evaluated is
    # exactly the checkpointed shadow
    summary2 = run_training(TrainLoopConfig(**config, resume=True))
    assert summary2["steps"] == 8
    assert np.isfinite(summary2["ema_eval_loss"])

    # --ema composes with --lora since round 5: freeze_base masks the
    # shadow to exactly the adapters and the EMA eval grafts them onto
    # the frozen base (tests/test_lora.py covers the mechanics; here
    # assert the combination runs end to end and reports the metric)
    summary3 = run_training(TrainLoopConfig(
        model="tiny_lm", batch_size=4, steps=2, lora="2:4", ema=0.9,
        eval_every=2, log_every=2))
    assert np.isfinite(summary3["ema_eval_loss"])
