"""Decode fleet control plane (fleet/, ISSUE 14).

The invariants everything hangs on:

- a stream admitted through the router produces EXACTLY the tokens of a
  standalone DecodeServer on the same prompt (the fleet is transparent);
- a stream is pinned to its server for its lifetime: a mid-fleet
  rolling weight update swaps versions UNDER the stream (no drop, no
  re-route), and a rollback to a pinned version never serves a
  newer-version continuation (every chunk's weight_version stamp is the
  evidence);
- scale-in is drain-before-stop: the victim finishes its in-flight
  streams, leaves the table, and only then is stopped — the acceptance
  test rolls weights across a 4-server fleet under sustained open-loop
  load with zero dropped streams.
"""

import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from parameter_server_distributed_tpu.config import CoordinatorConfig
from parameter_server_distributed_tpu.core.coordinator_core import (
    CoordinatorCore)
from parameter_server_distributed_tpu.fleet import messages as fmsg
from parameter_server_distributed_tpu.fleet.controller import (
    FleetController, ScalePolicy, occupancy, scale_decision)
from parameter_server_distributed_tpu.fleet.decode import FleetDecodeServer
from parameter_server_distributed_tpu.fleet.router import (FleetRouter,
                                                           score_backends)
from parameter_server_distributed_tpu.models.generation import generate
from parameter_server_distributed_tpu.models.serving import DecodeServer
from parameter_server_distributed_tpu.models.transformer import (
    Transformer, TransformerConfig)
from parameter_server_distributed_tpu.rpc.service import RpcClient
from parameter_server_distributed_tpu.server.coordinator_service import (
    Coordinator)

VOCAB = 64


def tiny(**kw):
    cfg = dict(vocab=VOCAB, d_model=32, n_heads=2, n_layers=2, d_ff=64,
               max_seq=160, dtype=jnp.float32)
    cfg.update(kw)
    return Transformer(TransformerConfig(**cfg))


_MODEL = tiny()
_PARAMS = _MODEL.init_params(0)


def reference(prompt, n):
    out = generate(_MODEL, _PARAMS, jnp.asarray([prompt], jnp.int32), n)
    return list(np.asarray(out)[0])


def entry(sid, state=fmsg.MEMBER_ACTIVE, free=4, queue=0, slots=4,
          version=0, fp=b""):
    return fmsg.FleetEntry(server_id=sid, address=f"h:{5000 + sid}",
                           slots=slots, free_slots=free,
                           queue_depth=queue, weight_version=version,
                           state=state, prefix_fp=fp)


# --------------------------------------------------------------- registry
def test_fleet_registry_lifecycle_and_epochs():
    core = CoordinatorCore("127.0.0.1", 1234)
    e0 = core.fleet_register(7, "h:1", 4)
    epoch, table, target = core.fleet_table()
    assert epoch == e0 and target == 0
    assert [(m.server_id, m.state, m.slots) for m in table] == \
        [(7, fmsg.MEMBER_ACTIVE, 4)]
    # heartbeat refreshes load without bumping the epoch
    state = core.fleet_heartbeat(7, free_slots=1, queue_depth=3,
                                 weight_version=5, active_streams=3)
    assert state == fmsg.MEMBER_ACTIVE
    epoch2, table, _ = core.fleet_table()
    assert epoch2 == epoch
    assert (table[0].free_slots, table[0].queue_depth,
            table[0].weight_version) == (1, 3, 5)
    # drain -> leave: two transitions, two epoch bumps
    assert core.fleet_drain(7)
    assert core.fleet_state(7) == fmsg.MEMBER_DRAINING
    assert core.fleet_leave(7)
    epoch3, table, _ = core.fleet_table()
    assert table[0].state == fmsg.MEMBER_GONE and epoch3 == epoch2 + 2
    # heartbeat from a GONE server asks it to re-register
    assert core.fleet_heartbeat(7, 4, 0, 0, 0) is None
    assert core.fleet_drain(7) is False
    # re-register resurrects the row
    core.fleet_register(7, "h:2", 8)
    assert core.fleet_state(7) == fmsg.MEMBER_ACTIVE
    assert core.fleet_table()[1][0].slots == 8


def test_fleet_reap_marks_gone():
    now = [0.0]
    core = CoordinatorCore("127.0.0.1", 1234, time_fn=lambda: now[0])
    core.fleet_register(1, "h:1", 4)
    core.fleet_register(2, "h:2", 4)
    now[0] = 10.0
    core.fleet_heartbeat(2, 4, 0, 0, 0)
    assert core.remove_stale_fleet(5.0) == [1]
    assert core.fleet_state(1) == fmsg.MEMBER_GONE
    assert core.fleet_state(2) == fmsg.MEMBER_ACTIVE


def test_fleet_manual_scale_target():
    core = CoordinatorCore("127.0.0.1", 1234)
    core.set_fleet_target(3)
    assert core.fleet_table()[2] == 3
    core.set_fleet_target(0)
    assert core.fleet_table()[2] == 0


# ---------------------------------------------------------------- scoring
def test_router_scoring_prefers_free_slots_then_queue():
    entries = [entry(0, free=1), entry(1, free=3),
               entry(2, free=3, queue=2),
               entry(3, state=fmsg.MEMBER_DRAINING, free=4),
               entry(4, state=fmsg.MEMBER_GONE, free=4)]
    ranked = score_backends(entries)
    assert [e.server_id for e in ranked] == [1, 2, 0]
    # claims debit capacity the table has not yet heartbeaten
    ranked = score_backends(entries, claims={1: 3})
    assert [e.server_id for e in ranked] == [2, 0, 1]


def test_router_scoring_prefix_overlap_affinity():
    """ISSUE 20: cached-prefix overlap counts as weight free slots —
    a backend already holding the prompt's leading blocks outranks an
    equally-free one; with no fingerprints, no prompt hashes, or weight
    0 the order is EXACTLY the PR 14 free-slot score (the downgrade)."""
    from parameter_server_distributed_tpu.models.prefix_tree import (
        pack_fp)
    hashes = [111, 222]
    entries = [entry(0, free=2), entry(1, free=2),
               entry(2, free=2, fp=pack_fp([111, 222, 333]))]
    # overlap 2 on server 2 beats the sid tie-break
    ranked = score_backends(entries, prompt_hashes=hashes, weight=1.0)
    assert [e.server_id for e in ranked] == [2, 0, 1]
    # one-block overlap loses to one extra free slot at weight 1.0 ...
    entries = [entry(0, free=3), entry(1, free=2, fp=pack_fp([111]))]
    ranked = score_backends(entries, prompt_hashes=hashes, weight=1.0)
    assert [e.server_id for e in ranked] == [0, 1]
    # ... and wins at weight 2.0
    ranked = score_backends(entries, prompt_hashes=hashes, weight=2.0)
    assert [e.server_id for e in ranked] == [1, 0]
    # downgrades: weight 0 / no hashes / fingerprint-free entries all
    # reproduce the PR 14 ordering
    entries = [entry(0, free=1), entry(1, free=3),
               entry(2, free=3, queue=2, fp=pack_fp([111, 222]))]
    assert [e.server_id for e in
            score_backends(entries, prompt_hashes=hashes,
                           weight=0.0)] == [1, 2, 0]
    assert [e.server_id for e in
            score_backends(entries, prompt_hashes=None)] == [1, 2, 0]
    # a diverging prompt (no leading-block match) scores zero overlap
    assert [e.server_id for e in
            score_backends(entries, prompt_hashes=[999],
                           weight=5.0)] == [1, 2, 0]


def test_heartbeat_carries_prefix_fingerprint():
    """The fingerprint rides the heartbeat into the fleet table and
    back out of UpdateFleet QUERY — pre-radix heartbeats (no field)
    leave it empty rather than erroring."""
    core = CoordinatorCore("127.0.0.1", 1234)
    core.fleet_register(7, "h:1", 4)
    core.fleet_heartbeat(7, 4, 0, 0, 0, prefix_fp=b"\x01\x02\x03\x04")
    _epoch, table, _t = core.fleet_table()
    assert table[0].prefix_fp == b"\x01\x02\x03\x04"
    core.fleet_heartbeat(7, 4, 0, 0, 0)  # positional legacy caller
    assert core.fleet_table()[1][0].prefix_fp == b""


def test_heartbeat_fingerprint_rpc_roundtrip():
    coordinator = Coordinator(CoordinatorConfig(bind_address="127.0.0.1",
                                                port=0))
    cport = coordinator.start()
    coordinator.core.fleet_register(0, "h:1", 4)
    client = RpcClient(f"127.0.0.1:{cport}", "coordinator.Coordinator",
                       fmsg.FLEET_COORD_METHODS)
    try:
        client.call("UpdateFleet", fmsg.FleetRequest(
            server_id=0, action=fmsg.FLEET_HEARTBEAT, free_slots=4,
            prefix_fp=b"\xaa\xbb\xcc\xdd"), timeout=5.0)
        resp = client.call("UpdateFleet", fmsg.FleetRequest(
            server_id=-1, action=fmsg.FLEET_QUERY), timeout=5.0)
    finally:
        client.close()
        coordinator.stop()
    by_sid = {int(e.server_id): bytes(e.prefix_fp)
              for e in resp.entries}
    assert by_sid[0] == b"\xaa\xbb\xcc\xdd"


def test_scale_decision_watermarks_and_manual():
    policy = ScalePolicy(low=0.3, high=0.8, min_servers=1, max_servers=4)
    idle = [entry(0, free=4), entry(1, free=4)]
    busy = [entry(0, free=0, queue=2), entry(1, free=1)]
    assert occupancy(idle) == 0.0
    assert occupancy(busy) == pytest.approx((4 + 3 + 2) / 8)
    assert scale_decision(idle, policy) == 1          # below low: -1
    assert scale_decision(busy, policy) == 3          # above high: +1
    assert scale_decision(busy, policy, manual_target=2) == 2
    assert scale_decision(idle, policy, manual_target=9) == 4  # clamp
    one = [entry(0, free=4)]
    assert scale_decision(one, policy) == 1           # min floor


# --------------------------------------------------------- gRPC plumbing
class _Fleet:
    """One coordinator + N FleetDecodeServers + router, torn down in
    reverse order.  Servers share one process (the decode dispatch lock
    serializes their jax) — the production shape is one per process,
    but loopback pinning/drain/version semantics are identical."""

    def __init__(self, n, slots=4, prompt_cache=0, heartbeat_s=0.1,
                 round_delay_s=0.0):
        self.coordinator = Coordinator(CoordinatorConfig(
            bind_address="127.0.0.1", port=0))
        cport = self.coordinator.start()
        self.caddr = f"127.0.0.1:{cport}"
        self.servers = []
        for sid in range(n):
            server = FleetDecodeServer(
                DecodeServer(_MODEL, _PARAMS, slots=slots, max_len=160,
                             prompt_cache=prompt_cache),
                server_id=sid, coordinator=self.caddr,
                heartbeat_s=heartbeat_s)
            # synthetic service time (the PSDT_DECODE_ROUND_DELAY_MS
            # knob): keeps streams IN FLIGHT long enough for a rollout
            # or drain to land mid-stream on this fast tiny model
            server._round_delay_s = round_delay_s
            server.start()
            self.servers.append(server)
        deadline = time.time() + 10.0
        while time.time() < deadline:
            _e, table, _t = self.coordinator.core.fleet_table()
            if sum(1 for f in table
                   if f.state == fmsg.MEMBER_ACTIVE) == n:
                break
            time.sleep(0.02)
        self.router = FleetRouter(self.caddr, poll_s=0.05)
        rport = self.router.start()
        self.client = RpcClient(f"127.0.0.1:{rport}",
                                fmsg.DECODE_SERVICE, fmsg.DECODE_METHODS)
        self.controller = FleetController(self.coordinator.core)

    def stream(self, prompt, max_new=6):
        """Submit through the router; returns (tokens, versions, error)."""
        chunks = list(self.client.call(
            "SubmitStream",
            fmsg.DecodeRequest(tokens=[int(t) for t in prompt],
                               max_new=max_new, temperature=-1.0),
            timeout=None))
        assert chunks and chunks[-1].done
        tokens = [int(c.token) for c in chunks if not c.done]
        versions = {int(c.weight_version) for c in chunks}
        return tokens, versions, chunks[-1].error

    def close(self):
        self.controller.close()
        self.client.close()
        self.router.stop()
        for server in self.servers:
            server.stop()
        self.coordinator.stop()


@pytest.fixture
def fleet2():
    fleet = _Fleet(2)
    yield fleet
    fleet.close()


def test_routed_stream_matches_standalone_generate(fleet2, rng):
    prompt = [int(t) for t in rng.integers(1, VOCAB, 7)]
    tokens, versions, error = fleet2.stream(prompt, max_new=6)
    assert not error
    assert tokens == reference(prompt, 6)
    assert versions == {0}  # boot weights


def test_router_spreads_streams_and_pins(fleet2, rng):
    """Concurrent streams land on BOTH servers (free-slot score +
    claims), and each stream's chunks all come from one server."""
    results = []
    lock = threading.Lock()

    def drive():
        prompt = [int(t) for t in rng.integers(1, VOCAB, 5)]
        out = fleet2.stream(prompt, max_new=8)
        with lock:
            results.append(out)

    threads = [threading.Thread(target=drive, daemon=True,
                                name=f"fleet-test-{i}") for i in range(6)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join(timeout=60.0)
    assert len(results) == 6
    assert all(not err for _t, _v, err in results)
    served = [s.streams_served for s in fleet2.servers]
    assert sum(served) == 6
    assert all(n > 0 for n in served), f"one server idle: {served}"


def test_router_prefers_backend_with_cached_prefix(rng):
    """End-to-end prefix-aware placement (ISSUE 20): after one stream
    warms a backend's radix cache with a long shared prefix, a second
    stream sharing that prefix routes to the SAME backend (overlap
    outbids the sid tie-break) and rides its suffix-only path — and
    stays token-exact through the router."""
    fleet = _Fleet(2, prompt_cache=8)
    try:
        shared = [int(t) for t in rng.integers(1, VOCAB, 18)]
        first = shared + [int(t) for t in rng.integers(1, VOCAB, 3)]
        tokens, _v, error = fleet.stream(first, max_new=4)
        assert not error and tokens == reference(first, 4)
        warm = next(s for s in fleet.servers if s.streams_served == 1)
        # wait for the fingerprint heartbeat to land in the fleet table
        deadline = time.time() + 10.0
        while time.time() < deadline:
            _e, table, _t = fleet.coordinator.core.fleet_table()
            if any(m.server_id == warm.server_id and m.prefix_fp
                   for m in table):
                break
            time.sleep(0.02)
        else:
            pytest.fail("prefix fingerprint never heartbeaten")
        second = shared + [int(t) for t in rng.integers(1, VOCAB, 4)]
        tokens, _v, error = fleet.stream(second, max_new=4)
        assert not error and tokens == reference(second, 4)
        assert warm.streams_served == 2, "router ignored the warm cache"
        assert warm.server.stats["prefix_hits"] == 1  # suffix-only path
    finally:
        fleet.close()


@pytest.mark.lockcheck
def test_lockcheck_concurrent_prefix_admit_extend_evict_swap(rng):
    """Radix cache under the real thread mix, PSDT_LOCK_CHECK=1: gRPC
    streams sharing prefixes (admit / extend / byte-bound evict on the
    decode loop), mid-hammer weight swaps (tree clear), and the
    heartbeat thread reading the fingerprint snapshot — every stream
    token-exact, no lock-order assertion."""
    server = FleetDecodeServer(
        DecodeServer(_MODEL, _PARAMS, slots=4, max_len=160,
                     prompt_cache=8, prefix_cache_bytes=1 << 16),
        server_id=0, heartbeat_s=0.02)
    server.start()
    shared = [int(t) for t in rng.integers(1, VOCAB, 14)]
    prompts = [shared[:6 + 4 * (i % 3)]
               + [int(t) for t in rng.integers(1, VOCAB, 3)]
               for i in range(12)]
    results = []
    lock = threading.Lock()

    def drive(worker):
        client = RpcClient(server.address, fmsg.DECODE_SERVICE,
                           fmsg.DECODE_METHODS)
        try:
            for prompt in prompts[worker::4]:
                chunks = list(client.call(
                    "SubmitStream",
                    fmsg.DecodeRequest(tokens=prompt, max_new=4,
                                       temperature=-1.0), timeout=None))
                with lock:
                    results.append(
                        (prompt,
                         [int(c.token) for c in chunks if not c.done],
                         chunks[-1].error))
        finally:
            client.close()

    threads = [threading.Thread(target=drive, args=(i,), daemon=True,
                                name=f"prefix-hammer-{i}")
               for i in range(4)]
    for thread in threads:
        thread.start()
    store = {name: np.array(arr) for name, arr in _PARAMS.items()}
    for version in (1, 2, 3):  # same values: swaps stay token-exact
        server.publish_version(store, version)
        resp = server.Control(fmsg.DecodeControlRequest(
            action=fmsg.CTRL_SWAP, version=version), None)
        assert resp.success, resp.message
        time.sleep(0.05)
    for thread in threads:
        thread.join(timeout=120.0)
    try:
        assert len(results) == 12
        assert all(not err for _p, _t, err in results)
        for prompt, tokens, _err in results:
            assert tokens == reference(prompt, 4)
    finally:
        server.stop()


def test_empty_fleet_rejects_instead_of_hanging():
    coordinator = Coordinator(CoordinatorConfig(bind_address="127.0.0.1",
                                                port=0))
    cport = coordinator.start()
    router = FleetRouter(f"127.0.0.1:{cport}", poll_s=0.05)
    rport = router.start()
    client = RpcClient(f"127.0.0.1:{rport}", fmsg.DECODE_SERVICE,
                       fmsg.DECODE_METHODS)
    try:
        chunks = list(client.call(
            "SubmitStream", fmsg.DecodeRequest(tokens=[1, 2],
                                               max_new=4), timeout=10.0))
        assert chunks[-1].error and chunks[-1].done
    finally:
        client.close()
        router.stop()
        coordinator.stop()


def test_bad_request_is_a_stream_error_not_a_crash(fleet2):
    _tokens, _versions, error = fleet2.stream([], max_new=4)
    assert "empty prompt" in error
    # the fleet still serves
    tokens, _versions, error = fleet2.stream([1, 2, 3], max_new=4)
    assert not error and len(tokens) == 4


# ------------------------------------------------------------ version skew
def test_rolling_update_and_rollback_version_rows(rng):
    """The ISSUE's version-skew rows: (1) a stream pinned to a v_k
    server survives a mid-fleet rollout to v_{k+1} — its early chunks
    decoded under v_k, its late chunks under v_{k+1}, nothing dropped;
    (2) after rollback to a pinned version, NO chunk anywhere carries a
    newer version until unpin."""
    fleet = _Fleet(2, round_delay_s=0.01)
    try:
        store = {name: np.array(arr) for name, arr in _PARAMS.items()}
        for server in fleet.servers:
            server.publish_version(store, 1)
        # a long stream rides through the rollout
        result = {}

        def long_stream():
            prompt = [int(t) for t in rng.integers(1, VOCAB, 5)]
            result["out"] = fleet.stream(prompt, max_new=40)

        thread = threading.Thread(target=long_stream, daemon=True,
                                  name="fleet-test-long")
        thread.start()
        time.sleep(0.15)  # stream under way on its pinned server
        swapped = fleet.controller.rolling_update(1)
        assert all(swapped.values()), swapped
        thread.join(timeout=60.0)
        tokens, versions, error = result["out"]
        assert not error and len(tokens) == 40
        assert versions <= {0, 1} and 1 in versions, versions
        # row 2: publish v2 everywhere, roll back to pinned v1
        for server in fleet.servers:
            server.publish_version(store, 2)
        rolled = fleet.controller.rollback(1)
        assert all(rolled.values()), rolled
        for _ in range(4):
            _tokens, versions, error = fleet.stream(
                [int(t) for t in rng.integers(1, VOCAB, 4)], max_new=6)
            assert not error
            assert versions == {1}, \
                f"newer-version continuation: {versions}"
        # pinned servers refuse the newer version outright
        refused = fleet.controller.rolling_update(2)
        assert not any(refused.values()), refused
        fleet.controller.unpin()
        assert all(fleet.controller.rolling_update(2).values())
        _tokens, versions, _error = fleet.stream([1, 2, 3], max_new=4)
        assert versions == {2}
    finally:
        fleet.close()


def test_swap_of_unheld_version_refused(fleet2):
    res = fleet2.controller.rolling_update(99)
    assert not any(res.values())


# ------------------------------------------------------------- autoscaler
class _FakeSpawner:
    def __init__(self):
        self.spawned = 0
        self.stopped = []

    def spawn(self):
        self.spawned += 1

    def stop(self, server_id):
        self.stopped.append(server_id)


def test_autoscaler_scale_out_on_high_occupancy():
    core = CoordinatorCore("127.0.0.1", 1234)
    core.fleet_register(0, "h:1", 4)
    core.fleet_heartbeat(0, free_slots=0, queue_depth=4,
                         weight_version=0, active_streams=4)
    spawner = _FakeSpawner()
    controller = FleetController(core, spawner=spawner,
                                 policy=ScalePolicy(max_servers=4))
    assert controller.scale_step() == 2
    assert spawner.spawned == 1
    # the new server has not registered yet: a second step re-asks for 2
    # but must not spawn a third while one drain/spawn is outstanding...
    core.fleet_register(1, "h:2", 4)  # ...it arrives
    core.fleet_heartbeat(1, 3, 0, 0, 1)
    core.fleet_heartbeat(0, 1, 0, 0, 3)
    assert controller.scale_step() == 2  # 0.5 occupancy: steady state
    assert spawner.spawned == 1


def test_autoscaler_scale_in_drains_before_stop():
    """The drain-before-stop contract: the victim is DRAINED first,
    spawner.stop only fires after the server reached GONE."""
    core = CoordinatorCore("127.0.0.1", 1234)
    for sid in range(2):
        core.fleet_register(sid, f"h:{sid}", 4)
        core.fleet_heartbeat(sid, 4, 0, 0, 0)
    spawner = _FakeSpawner()
    controller = FleetController(core, spawner=spawner,
                                 policy=ScalePolicy(low=0.3, high=0.8,
                                                    min_servers=1))
    assert controller.scale_step() == 1      # idle fleet: scale in
    assert core.fleet_state(1) == fmsg.MEMBER_DRAINING  # youngest first
    assert spawner.stopped == []             # NOT stopped yet
    assert controller.scale_step() == 1      # still draining: no action
    assert spawner.stopped == []
    core.fleet_leave(1)                      # drain completes
    controller.scale_step()
    assert spawner.stopped == [1]            # only now reaped
    controller.close()


def test_manual_scale_target_via_rpc(fleet2):
    resp = RpcClient(fleet2.caddr, "coordinator.Coordinator",
                     fmsg.FLEET_COORD_METHODS)
    try:
        out = resp.call("UpdateFleet", fmsg.FleetRequest(
            server_id=-1, action=fmsg.FLEET_SCALE, scale_target=3),
            timeout=5.0)
        assert out.scale_target == 3
    finally:
        resp.close()
    assert fleet2.coordinator.core.fleet_table()[2] == 3


# ------------------------------------------------------------ drain paths
def test_coordinator_drain_finishes_streams_then_leaves(fleet2, rng):
    """pst-ctl fleet-drain semantics over the heartbeat: the drained
    server's in-flight stream completes, the server goes GONE, new
    streams route to the survivor."""
    target = fleet2.servers[1]
    result = {}

    def long_stream():
        prompt = [int(t) for t in rng.integers(1, VOCAB, 5)]
        chunks = list(RpcClient(target.address, fmsg.DECODE_SERVICE,
                                fmsg.DECODE_METHODS).call(
            "SubmitStream",
            fmsg.DecodeRequest(tokens=prompt, max_new=30,
                               temperature=-1.0), timeout=None))
        result["tokens"] = [c.token for c in chunks if not c.done]
        result["error"] = chunks[-1].error

    thread = threading.Thread(target=long_stream, daemon=True,
                              name="fleet-test-drain")
    thread.start()
    time.sleep(0.1)
    fleet2.coordinator.core.fleet_drain(1)
    assert target.wait_drained(30.0), "drain never completed"
    thread.join(timeout=30.0)
    assert not result["error"] and len(result["tokens"]) == 30
    assert fleet2.coordinator.core.fleet_state(1) == fmsg.MEMBER_GONE
    # draining server rejects direct new submissions
    direct = RpcClient(target.address, fmsg.DECODE_SERVICE,
                       fmsg.DECODE_METHODS)
    try:
        chunks = list(direct.call("SubmitStream", fmsg.DecodeRequest(
            tokens=[1, 2], max_new=2), timeout=10.0))
        assert chunks[-1].error
    finally:
        direct.close()
    # the router still serves through the survivor
    tokens, _versions, error = fleet2.stream([3, 4, 5], max_new=4)
    assert not error and len(tokens) == 4


# -------------------------------------------------------------- ctl / CLI
def test_ctl_fleet_and_scale_cli(fleet2, capsys):
    from parameter_server_distributed_tpu.cli.ctl_main import main
    assert main(["fleet", fleet2.caddr]) == 0
    out = capsys.readouterr().out
    assert "2 servers" in out and "server 0" in out and "active" in out
    assert main(["scale", "3", fleet2.caddr]) == 0
    assert "scale target 3" in capsys.readouterr().out
    assert fleet2.coordinator.core.fleet_table()[2] == 3
    assert main(["fleet-drain", "1", fleet2.caddr]) == 0
    assert fleet2.coordinator.core.fleet_state(1) == fmsg.MEMBER_DRAINING
    assert main(["fleet-drain", "42", fleet2.caddr]) == 1


def test_fleet_rollup_rendered(fleet2):
    """The coordinator's GetClusterMetrics carries a fleet dict and
    pst-status renders it as one line."""
    import json

    from parameter_server_distributed_tpu.obs.export import render_fleet
    from parameter_server_distributed_tpu.rpc import messages as m
    client = RpcClient(fleet2.caddr, m.COORDINATOR_SERVICE,
                       m.COORDINATOR_EXT_METHODS)
    try:
        rollup = json.loads(client.call(
            "GetClusterMetrics", m.ClusterMetricsRequest(),
            timeout=5.0).rollup_json)
    finally:
        client.close()
    fleet = rollup["fleet"]
    assert fleet["states"]["active"] == 2
    assert fleet["slots"] == 8
    line = render_fleet(fleet)
    assert "2 active" in line and "slots free" in line


# -------------------------------------------------------------- acceptance
def test_rolling_update_4_server_fleet_zero_dropped_streams(rng):
    """THE acceptance row: a rolling weight update across a 4-server
    fleet under sustained open-loop load over loopback gRPC completes
    with zero dropped streams — every submitted stream runs to its done
    chunk with no error, while every server confirms its swap."""
    fleet = _Fleet(4, slots=2)
    try:
        store = {name: np.array(arr) for name, arr in _PARAMS.items()}
        for server in fleet.servers:
            server.publish_version(store, 1)
        results = []
        lock = threading.Lock()
        stop = threading.Event()

        def load_generator(i):
            while not stop.is_set():
                prompt = [int(t) for t in rng.integers(1, VOCAB, 4)]
                out = fleet.stream(prompt, max_new=10)
                with lock:
                    results.append(out)

        threads = [threading.Thread(target=load_generator, args=(i,),
                                    daemon=True, name=f"fleet-load-{i}")
                   for i in range(6)]
        for thread in threads:
            thread.start()
        time.sleep(0.3)  # load established
        swapped = fleet.controller.rolling_update(1)
        assert all(swapped.values()), swapped
        assert len(swapped) == 4
        time.sleep(0.3)  # load continues over the rolled fleet
        stop.set()
        for thread in threads:
            thread.join(timeout=60.0)
        assert len(results) >= 6
        dropped = [err for _t, _v, err in results if err]
        assert dropped == [], f"dropped streams: {dropped}"
        assert all(len(tokens) == 10 for tokens, _v, _e in results)
        # post-rollout streams decode under the new version
        _tokens, versions, error = fleet.stream([1, 2, 3], max_new=4)
        assert not error and versions == {1}
        assert sum(s.streams_served for s in fleet.servers) >= len(results)
    finally:
        fleet.close()


def test_abandoned_stream_frees_its_slot(rng):
    """A client that disconnects mid-stream must not burn its slot for
    the rest of max_new: the handler marks the stream cancelled and the
    decode loop reaps it (review finding — the capacity-collapse
    feedback loop under overload)."""
    server = FleetDecodeServer(
        DecodeServer(_MODEL, _PARAMS, slots=1, max_len=160),
        server_id=0, heartbeat_s=0.05)
    server._round_delay_s = 0.02
    server.start()
    client = RpcClient(server.address, fmsg.DECODE_SERVICE,
                       fmsg.DECODE_METHODS)
    try:
        prompt = [int(t) for t in rng.integers(1, VOCAB, 4)]
        chunks = client.call("SubmitStream", fmsg.DecodeRequest(
            tokens=prompt, max_new=200, temperature=-1.0), timeout=None)
        next(chunks)  # stream established and decoding
        chunks.cancel()  # client walks away mid-stream
        deadline = time.time() + 10.0
        while time.time() < deadline and server.server.active:
            time.sleep(0.02)
        # 200 rounds at 20ms would be 4s; the reap frees it in a round
        assert server.server.active == 0, "abandoned slot never freed"
        # and the freed slot serves the next client
        out = list(client.call("SubmitStream", fmsg.DecodeRequest(
            tokens=prompt, max_new=3, temperature=-1.0), timeout=30.0))
        assert out[-1].done and not out[-1].error
    finally:
        client.close()
        server.stop()


def test_pinned_version_survives_continued_publication():
    """The rollback pin exempts its version from LRU eviction: the
    training side keeps publishing past the bounded store, and the
    pinned version must stay swappable (review finding — a version-
    split fleet could otherwise never be re-pinned)."""
    server = FleetDecodeServer(
        DecodeServer(_MODEL, _PARAMS, slots=1, max_len=160),
        server_id=0, versions_kept=2, heartbeat_s=0.05)
    server.start()
    try:
        store = {name: np.array(arr) for name, arr in _PARAMS.items()}
        server.publish_version(store, 1)
        resp = server.Control(fmsg.DecodeControlRequest(
            action=fmsg.CTRL_ROLLBACK, version=1), None)
        assert resp.success and resp.pinned_version == 1
        for version in (2, 3, 4, 5):
            server.publish_version(store, version)
        with server._lock:
            held = list(server._versions)
        assert 1 in held, f"pinned version evicted: {held}"
        assert len(held) == 2  # the cap still holds for the rest
        # a rollback retry (new server joining the pinned fleet, a
        # failed swap) still finds the pinned version
        resp = server.Control(fmsg.DecodeControlRequest(
            action=fmsg.CTRL_ROLLBACK, version=1), None)
        assert resp.success, resp.message
        assert server.weight_version() == 1
    finally:
        server.stop()


def test_control_swap_reports_real_outcome():
    """Control(SWAP) success means the swap APPLIED — a version evicted
    or a store the DecodeServer rejects must come back success=False
    (review finding — 'processed' is not 'succeeded')."""
    server = FleetDecodeServer(
        DecodeServer(_MODEL, _PARAMS, slots=1, max_len=160),
        server_id=0, heartbeat_s=0.05)
    server.start()
    try:
        # a shape-drifted publication: held, but swap_params raises
        bad = {name: np.zeros((3, 3), np.float32) for name in _PARAMS}
        server.publish_version(bad, 7)
        resp = server.Control(fmsg.DecodeControlRequest(
            action=fmsg.CTRL_SWAP, version=7), None)
        assert not resp.success and "failed" in resp.message
        assert server.weight_version() == 0  # last-good kept
    finally:
        server.stop()


def test_fleet_messages_wire_roundtrip():
    req = fmsg.FleetRequest(server_id=3, action=fmsg.FLEET_HEARTBEAT,
                            address="h:1", slots=8, free_slots=2,
                            queue_depth=5, weight_version=7,
                            active_streams=6,
                            prefix_fp=b"\x01\x00\x00\x00\x02\x00\x00\x00")
    assert fmsg.FleetRequest.decode(req.encode()) == req
    ent = fmsg.FleetEntry(server_id=1, address="h:2", slots=4,
                          prefix_fp=b"\xff\xee\xdd\xcc")
    assert fmsg.FleetEntry.decode(ent.encode()) == ent
    resp = fmsg.FleetResponse(epoch=4, success=True, message="ok",
                              self_state=1, scale_target=2,
                              entries=[fmsg.FleetEntry(server_id=1,
                                                       address="h:2",
                                                       slots=4)])
    assert fmsg.FleetResponse.decode(resp.encode()) == resp
    chunk = fmsg.DecodeChunk(request_id=9, token=42, done=False,
                             weight_version=3)
    assert fmsg.DecodeChunk.decode(chunk.encode()) == chunk
    req2 = fmsg.DecodeRequest(tokens=[1, 2, 3], max_new=16,
                              temperature=-1.0, stop=[7])
    back = fmsg.DecodeRequest.decode(req2.encode())
    assert [int(t) for t in back.tokens] == [1, 2, 3]
    assert back.temperature == pytest.approx(-1.0)
