"""pst-serve (cli/serve_main.py): the JSONL line-protocol serving process.

Driven as a real subprocess — the same way a user (or a transport shim)
would.  Contract: every request's streamed tokens equal its final result,
concurrent requests interleave, errors are per-request, and stdin EOF
drains in-flight work then exits 0.
"""

import json
import os
import subprocess
import sys

import pytest


def run_serve(requests: list[dict], *extra_flags: str,
              timeout: float = 400.0) -> tuple[list[dict], str]:
    env = dict(os.environ)
    env["PSDT_PLATFORM"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m",
         "parameter_server_distributed_tpu.cli.serve_main",
         "--model=tiny_lm", "--slots=2", "--max-len=48", *extra_flags],
        input="\n".join(json.dumps(r) for r in requests) + "\n",
        capture_output=True, text=True, timeout=timeout, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return ([json.loads(line) for line in proc.stdout.strip().splitlines()],
            proc.stderr)


def test_stream_equals_result_and_errors_are_per_request():
    lines, _ = run_serve([
        {"id": "a", "tokens": [1, 2, 3], "max_new": 4},
        {"id": "b", "tokens": [7, 8], "max_new": 3},
        {"id": "oneshot", "tokens": [4], "max_new": 1},
        {"id": "bad"},
    ])
    streamed: dict = {}
    for line in lines:
        if "token" in line:
            streamed.setdefault(line["id"], []).append(line["token"])
    done = {line["id"]: line for line in lines if line.get("done")}
    assert set(done) == {"a", "b", "oneshot"}
    for rid, expect_n in (("a", 4), ("b", 3), ("oneshot", 1)):
        assert streamed[rid] == done[rid]["tokens"]
        assert len(done[rid]["tokens"]) == expect_n
    errors = [line for line in lines if "error" in line]
    assert len(errors) == 1 and errors[0]["id"] == "bad"


def test_malformed_lines_never_kill_the_server():
    """Type-confused requests, JSON scalars/arrays, and a bare `null`
    (which must not alias the EOF sentinel) all become per-line errors
    while the well-formed request completes."""
    env = dict(os.environ)
    env["PSDT_PLATFORM"] = "cpu"
    raw = "\n".join([
        json.dumps({"id": "t", "tokens": 5}),        # non-iterable tokens
        "42", "[1,2]", "null", "{not json",
        json.dumps({"id": "ok", "tokens": [1], "max_new": 2}),
    ]) + "\n"
    proc = subprocess.run(
        [sys.executable, "-m",
         "parameter_server_distributed_tpu.cli.serve_main",
         "--model=tiny_lm", "--slots=2", "--max-len=48"],
        input=raw, capture_output=True, text=True, timeout=400, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(line) for line in proc.stdout.strip().splitlines()]
    errors = [line for line in lines if "error" in line]
    assert len(errors) == 5, lines                   # one per bad line
    done = {line["id"]: line for line in lines if line.get("done")}
    assert len(done["ok"]["tokens"]) == 2            # null != EOF sentinel


def test_per_request_temperature_and_stop_fields():
    """Protocol-level pass-through of the per-request sampling knobs: a
    greedy request and a hot-temperature request on the SAME tokens give
    different streams, and "stop" cuts a request short."""
    greedy = {"id": "g", "tokens": [1, 2, 3], "max_new": 8}
    hot = {"id": "h", "tokens": [1, 2, 3], "max_new": 8, "temperature": 9.0}
    lines, _ = run_serve([greedy, hot])
    done = {line["id"]: line for line in lines if line.get("done")}
    assert len(done["g"]["tokens"]) == 8
    assert done["g"]["tokens"] != done["h"]["tokens"]

    # stop at the greedy stream's 3rd token truncates the result there
    stop_tok = done["g"]["tokens"][2]
    lines2, _ = run_serve([dict(greedy, id="s", stop=[stop_tok])])
    done2 = {line["id"]: line for line in lines2 if line.get("done")}
    assert done2["s"]["tokens"] == done["g"]["tokens"][:3]


def test_speculative_serving_protocol_multi_token_rounds():
    """Speculative mode at the protocol level: a SELF-draft at the same
    seed accepts every proposal, so each round commits draft_len+1 tokens
    and requests finish mid-round — the stream must still deliver every
    token exactly once and a done line per request (regression: the drain
    loop once popped a request at its finishing token and crashed on the
    same round's remaining pairs)."""
    lines, _ = run_serve(
        [{"id": "a", "tokens": [1, 2, 3], "max_new": 9},
         {"id": "b", "tokens": [4, 5], "max_new": 7}],
        "--draft-model=tiny_lm", "--draft-seed=0", "--draft-len=4")
    streamed: dict = {}
    for line in lines:
        if "token" in line:
            streamed.setdefault(line["id"], []).append(line["token"])
    done = {line["id"]: line for line in lines if line.get("done")}
    assert set(done) == {"a", "b"}
    for rid, expect_n in (("a", 9), ("b", 7)):
        assert streamed[rid] == done[rid]["tokens"]
        assert len(done[rid]["tokens"]) == expect_n

    # greedy speculative output is token-exact vs the plain server
    plain, _ = run_serve([{"id": "a", "tokens": [1, 2, 3], "max_new": 9}])
    plain_done = next(l for l in plain if l.get("done"))
    assert plain_done["tokens"] == done["a"]["tokens"]


def test_text_mode_round_trip():
    lines, _ = run_serve([{"id": 1, "prompt": "hi", "max_new": 3}])
    done = [line for line in lines if line.get("done")]
    assert len(done) == 1 and isinstance(done[0]["text"], str)


def test_hf_checkpoint_serves(tmp_path):
    """pst-serve --hf-gpt2 drives a local transformers checkout end to
    end (tokens-mode request — save_pretrained writes no tokenizer
    files, so the text path is not exercised here)."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    torch.manual_seed(0)
    cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=32, n_layer=2, n_head=2)
    transformers.GPT2LMHeadModel(cfg).save_pretrained(tmp_path)
    env = dict(os.environ)
    env["PSDT_PLATFORM"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m",
         "parameter_server_distributed_tpu.cli.serve_main",
         f"--hf-gpt2={tmp_path}", "--slots=2", "--max-len=48"],
        input=json.dumps({"id": 1, "tokens": [5, 6, 7],
                          "max_new": 3}) + "\n",
        capture_output=True, text=True, timeout=400, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(line) for line in proc.stdout.strip().splitlines()]
    done = [line for line in lines if line.get("done")]
    assert len(done) == 1 and len(done[0]["tokens"]) == 3


def test_overflow_request_rejected_not_fatal():
    """A request that cannot fit the cache errors; the server keeps
    serving the others and still exits cleanly."""
    lines, _ = run_serve([
        {"id": "big", "tokens": list(range(40)), "max_new": 20},
        {"id": "ok", "tokens": [1], "max_new": 2},
    ])
    assert any("error" in line and line["id"] == "big" for line in lines)
    done = {line["id"]: line for line in lines if line.get("done")}
    assert len(done["ok"]["tokens"]) == 2


def test_serve_cli_fused_rounds_token_exact():
    """--fused-rounds=N: same token streams as the per-round server
    (step_many is token-exact), just fewer device dispatches."""
    reqs = [{"id": i, "tokens": [3 + i, 7, 11], "max_new": 9}
            for i in range(3)]

    def done_map(lines):
        return {obj["id"]: obj["tokens"] for obj in lines
                if obj.get("done")}

    plain, _ = run_serve(reqs)
    fused, _ = run_serve(reqs, "--fused-rounds=4")
    assert done_map(fused) == done_map(plain)
